"""db-analyser: stream a stored chain and validate / benchmark it.

Reference: `Cardano.Tools.DBAnalyser` (Analysis.hs:75-88, Run.hs:42-151).
Implemented analyses:

  * ``only_validation`` — open the ImmutableDB with full integrity
    checking (ValidateAllChunks analog: reparse + body-hash check per
    block, Run.hs:133-143) and run full header revalidation. With the
    ``device`` backend the Praos crypto executes as epoch-segmented
    fused TPU batches (protocol/batch.py); with the ``host`` backend it
    folds the sequential pure-Python reference path — the same work the
    reference's libsodium-backed fold does.
  * ``benchmark_ledger_ops`` — per-block timing of forecast / header
    tick / header apply / ledger tick / ledger apply, CSV rows matching
    the reference's SlotDataPoint columns (Analysis.hs:526-607). Host
    backend only (per-block timing is meaningless inside a fused batch).
  * ``count_blocks`` — CountBlocks analog.

The device path is the north-star benchmark: headers validated/sec over
a db-synthesizer chain.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from ..block.praos_block import Block, Header
from ..protocol import batch as pbatch
from ..protocol import praos
from ..protocol.praos import PraosParams, PraosState
from ..protocol.views import LedgerView
from ..storage.immutable import ImmutableDB
from ..storage.open import default_check_integrity


@dataclass
class ValidationResult:
    n_blocks: int = 0
    n_valid: int = 0
    wall_s: float = 0.0
    open_s: float = 0.0  # ImmutableDB open (index load + validation)
    stage_s: float = 0.0  # host SoA staging time (device backend)
    device_s: float = 0.0  # kernel execution time (device backend)
    error: Exception | None = None
    final_state: PraosState | None = None
    resumed_headers: int = 0  # headers skipped by a checkpoint resume
    # (counted INTO n_valid: the record vouches for them — the resumed
    # total equals the uninterrupted run's by the differential suite)
    opened_dirty: bool = False  # the clean-shutdown marker was absent:
    # the validation policy escalated to all-chunks + on-disk repair
    # (storage/guard.py — forced revalidation after a crash)
    repairs: dict | None = None  # {action: count} of the store repairs
    # this open/replay applied (detailed rows ride the warmup report)
    # filled by collect_phases=True (protocol/batch tracer events):
    phases: dict | None = None  # per-phase wall s (stage/dispatch/...)
    h2d_bytes: int = 0  # staged bytes shipped host->device
    d2h_bytes: int = 0  # verdict/nonce bytes shipped device->host
    n_windows: int = 0  # dispatched windows
    packed_windows: int = 0  # windows that staged packed


class _PhaseCollector:
    """Batch tracer aggregating per-phase wall time + boundary bytes
    (Enclose brackets and TransferEvents from protocol/batch.py).
    Materialize events arrive from the reader worker thread; the +=
    updates are GIL-atomic enough for accounting."""

    def __init__(self):
        from collections import defaultdict

        self.wall = defaultdict(float)
        self.h2d = 0
        self.d2h = 0
        self.windows = 0
        self.packed = 0

    def __call__(self, ev):
        from ..utils.trace import EncloseEvent, TransferEvent

        if isinstance(ev, EncloseEvent):
            if ev.edge == "end":
                self.wall[ev.label] += ev.duration
        elif isinstance(ev, TransferEvent):
            if ev.phase == "dispatch":
                self.h2d += ev.h2d_bytes
                self.windows += 1
                if ev.packed:
                    self.packed += 1
            else:
                self.d2h += ev.d2h_bytes

    def fill(self, res: "ValidationResult") -> None:
        res.phases = dict(self.wall)
        res.h2d_bytes = self.h2d
        res.d2h_bytes = self.d2h
        res.n_windows = self.windows
        res.packed_windows = self.packed


@dataclass
class SlotDataPoint:
    """One CSV row of benchmark_ledger_ops (SlotDataPoint.hs)."""

    slot: int
    block_no: int
    block_bytes: int
    mut_forecast_us: float
    mut_header_tick_us: float
    mut_header_apply_us: float
    mut_block_tick_us: float
    mut_block_apply_us: float

    CSV_HEADER = (
        "slot,block_no,block_bytes,mut_forecast,mut_headerTick,"
        "mut_headerApply,mut_blockTick,mut_blockApply"
    )

    def csv(self) -> str:
        return (
            f"{self.slot},{self.block_no},{self.block_bytes},"
            f"{self.mut_forecast_us:.1f},{self.mut_header_tick_us:.1f},"
            f"{self.mut_header_apply_us:.1f},{self.mut_block_tick_us:.1f},"
            f"{self.mut_block_apply_us:.1f}"
        )


def open_immutable(db_path: str, validate_all=False,
                   repair: bool = False) -> ImmutableDB:
    """validate_all: False = most-recent-chunk check only; True =
    ValidateAllChunks at open (two disk passes: validation walk, then
    the replay's stream — truncates corrupted tails ON DISK, snipped
    bytes quarantined); "stream" = the SAME all-chunks checks (CRC +
    body-hash integrity, per-blob order) folded into the replay's own
    chunk reads by _stream_views — one disk pass, identical verdicts
    and truncation points. Stream mode is read-only analysis by
    default: pass ``repair=True`` (revalidate's ``--repair`` /
    ``repair=`` lever, forced by a dirty open) to write back the
    truncation the deep read computes, via `ImmutableDB.repair_to`.
    Reference: --only-validation forces ValidateAllChunks
    (Tools/DBAnalyser.hs:133-136); the stream mode is how the replay
    pays for it without reading every chunk twice."""
    import os

    from ..storage.open import default_check_integrity_batch

    stream = validate_all == "stream"
    deep = bool(validate_all) and not stream
    return ImmutableDB(
        os.path.join(db_path, "immutable"),
        check_integrity=default_check_integrity if deep else None,
        validate_all=deep,
        check_integrity_batch=(
            default_check_integrity_batch if deep else None
        ),
        # reader opens (shallow / plain stream) may not mutate the disk
        # AT ALL: truncations and index rebuilds are computed in memory
        # (applied=False rows); only a deep open or an explicit repair
        # lever writes — matching the StoreGuard writer decision
        repair=deep or bool(repair),
        stream_deep=stream,
        stream_repair=stream and bool(repair),
    )


def _epoch_segments(params: PraosParams, headers):
    """Cut a header stream at epoch boundaries (SURVEY.md §5.7: nonce and
    pool distribution are epoch-constant, so a batch spans one epoch)."""
    seg: list = []
    epoch = None
    for h in headers:
        e = params.epoch_of(h.slot)
        if epoch is None or e == epoch:
            seg.append(h)
            epoch = e
        else:
            yield seg
            seg = [h]
            epoch = e
    if seg:
        yield seg


def _columnar_enabled() -> bool:
    """OCT_COLUMNAR (default 1): flow the native chunk scan as
    ViewColumns windows end-to-end (vectorized prechecks, columnar
    packed staging, columnar epilogue — the round-8 host pipeline). =0
    restores the per-HeaderView object stream; read per call so the
    differential tests can A/B both paths in one process."""
    import os

    return os.environ.get("OCT_COLUMNAR", "1") != "0"


def _views_from_columns(cols):
    """native_loader.HeaderColumns -> HeaderViews (no Python CBOR) — the
    per-object stream (`OCT_COLUMNAR=0` and ragged-chunk fallback)."""
    from ..protocol.views import ViewColumns

    vc = ViewColumns.from_header_columns(cols)
    if vc is not None:
        return vc.views()
    # ragged spans (no rectangular column): per-row bytes-list path
    from ..protocol.views import HeaderView, OCert

    n = cols.n
    prev_b = cols.prev_hash.tobytes()
    issuer_b = cols.issuer_vk.tobytes()
    vrf_vk_b = cols.vrf_vk.tobytes()
    vrf_out_b = cols.vrf_output.tobytes()
    vrf_prf_b = cols.vrf_proof.tobytes()  # 128-wide zero-padded rows
    ocert_vk_b = cols.ocert_vk.tobytes()
    has_prev = cols.has_prev.tolist()
    counters = cols.ocert_counter.tolist()
    kes_periods = cols.ocert_kes_period.tolist()
    slots = cols.slot.tolist()
    prf_lens = cols.vrf_proof_len.tolist()
    out = []
    for i in range(n):
        o32 = 32 * i
        out.append(
            HeaderView(
                prev_hash=prev_b[o32:o32 + 32] if has_prev[i] else None,
                vk_cold=issuer_b[o32:o32 + 32],
                vrf_vk=vrf_vk_b[o32:o32 + 32],
                vrf_output=vrf_out_b[64 * i:64 * i + 64],
                vrf_proof=vrf_prf_b[128 * i:128 * i + prf_lens[i]],
                ocert=OCert(
                    ocert_vk_b[o32:o32 + 32],
                    counters[i],
                    kes_periods[i],
                    cols.ocert_sigma[i],
                ),
                slot=slots[i],
                signed_bytes=cols.signed_bytes[i],
                kes_sig=cols.kes_sig[i],
            )
        )
    return out


def _read_chunk(path: str, chunk_idx: int) -> bytes:
    """One chunk read behind the chaos seam (`chunk-corrupt@epoch:N` —
    the chunk index stands in for the epoch on the synthesized chains,
    one chunk per epoch) with ONE recovery reread: transient I/O (and
    the chaos taxonomy, transient by contract) recovers in place as a
    first-class `chunk-reread` RecoveryEvent; a second failure
    propagates — persistent corruption must truncate loudly, not loop."""
    from ..obs import recovery as _recovery
    from ..testing import chaos

    try:
        chaos.fire("chunk", chunk=chunk_idx)
        with open(path, "rb") as f:
            return f.read()
    except (chaos.ChaosError, OSError) as e:
        if not (_recovery.enabled() and _recovery.recoverable(e)):
            raise
        _recovery.note_recovery_event("chunk-reread", chunk_idx, 0, 1, e)
        with open(path, "rb") as f:
            data = f.read()
        _recovery.note_recovery_event("recovered", chunk_idx, 0, 1, e,
                                      ok=True)
        return data


def _stream_windows(imm: ImmutableDB, res: "ValidationResult"):
    """Per-chunk window stream for revalidation. Three tiers:

    1. **Sidecar fast path** (storage/sidecar.py): a fresh-sealed
       ``NNNNN.cols`` builds `ViewColumns` straight from mmap'd column
       blobs — ZERO per-header parse; stream-deep integrity collapses
       to the one native ``crc32_first_bad`` sweep plus the sidecar's
       body-hash columns (``ops/blake2b.hash_spans``), with the exact
       host walk kept as the anomaly path on any truncation.
    2. **Native parse** (`native_loader.extract_headers` — the C++
       data-loader path, SURVEY.md §7.3 item 5): the miss/stale
       fallback, which also BACKFILLS the sidecar through the PR 13
       tmp+rename protocol — writer opens only; a read-only open never
       writes.
    3. **HeaderView lists** (no native library, OCT_COLUMNAR=0, or
       ragged chunks).

    The mmap-vs-parse wall split rides nested `_enclose` brackets
    ("stream-mmap" / "stream-parse") inside the per-chunk "stream"
    span, so the flight recorder's phase collector banks both."""
    import os

    import numpy as np

    from .. import native_loader
    from ..protocol.views import ViewColumns
    from ..storage import sidecar as sidecar_mod
    from ..storage.immutable import _chunk_name

    native_ok = native_loader.load() is not None
    columnar = _columnar_enabled()
    stream_deep = getattr(imm, "stream_deep", False)
    # the sidecar produces ViewColumns, so the kill-switch rides BOTH
    # levers: OCT_SIDECAR=0 and OCT_COLUMNAR=0 each restore the parse
    use_sidecar = sidecar_mod.enabled() and native_ok and columnar
    for chunk_idx, n in enumerate(imm._chunks):
        entries = imm._entries[n]
        if not entries:
            continue
        # the per-chunk disk read + integrity walk + native column
        # extraction is the "stream" span of the flight recorder (one
        # Enclose bracket per CHUNK — per-window granularity, no object
        # tax); pbatch._enclose is a no-op while no tracer is installed
        with pbatch._enclose("stream"):
            data = _read_chunk(
                os.path.join(imm.path, _chunk_name(n)), chunk_idx
            )
            truncated = False
            sc = None
            if use_sidecar:
                with pbatch._enclose("stream-mmap"):
                    sc, outcome = sidecar_mod.load_sidecar(
                        imm.fs, imm.path, n, data, len(entries)
                    )
                sidecar_mod.record(outcome, n)
            if stream_deep:
                # single-pass validate-all: the open deferred the deep
                # walk to this read (open_immutable "stream" mode) —
                # same checks, same truncation point, no second disk pass
                from ..storage.open import (
                    default_check_integrity,
                    default_check_integrity_batch,
                )

                if sc is not None:
                    # hot path — no parse. WALKED seals (forge/truncater/
                    # deep-replay builds) skip the per-blob CRC sweep:
                    # the probe's whole-chunk CRC proved these are the
                    # build-time bytes, and the build-time walk proved
                    # those bytes pass the sweep; only the body-hash
                    # compare (cryptographic, vs the sealed column)
                    # still runs. Unwalked seals pay the full sweep.
                    if sc.walked:
                        good = sidecar_mod.integrity_batch_hook(sc)(
                            data, entries
                        )
                    else:
                        good = imm.deep_check_loaded(
                            data, entries, default_check_integrity,
                            sidecar_mod.integrity_batch_hook(sc),
                        )
                    if good < len(entries):
                        # anomaly path: recompute with the EXACT host
                        # walk so the truncation point and arbitration
                        # are parse-identical, and drop the sidecar —
                        # its seal dies with the repair anyway
                        sc = None
                        good = imm.deep_check_loaded(
                            data, entries, default_check_integrity,
                            default_check_integrity_batch,
                        )
                else:
                    good = imm.deep_check_loaded(
                        data, entries, default_check_integrity,
                        default_check_integrity_batch,
                    )
                if good < len(entries):
                    entries = entries[:good]
                    truncated = True
                    if getattr(imm, "stream_repair", False):
                        # --repair / dirty-open write-back: apply the
                        # truncation this deep read just computed —
                        # quarantine + on-disk cut, the same repair a
                        # deep open would have taken here
                        imm.repair_to(n, good, data=data)
            pieces = None
            cols = None
            if sc is not None and not truncated:
                with pbatch._enclose("stream-mmap"):
                    pieces = sc.pieces(data)
                if pieces is not None:
                    res.n_blocks += sc.n
            if pieces is None and native_ok and entries:
                with pbatch._enclose("stream-parse"):
                    offsets = np.asarray(
                        [e.offset for e in entries], np.int64
                    )
                    cols = native_loader.extract_headers(data, offsets)
                res.n_blocks += cols.n
                if use_sidecar and sc is None and not truncated \
                        and getattr(imm, "_repair", False):
                    # back-fill: the first replay of an un-sidecared
                    # chunk writes the sidecar it just paid the parse
                    # for (tmp+rename durability; WRITER opens only —
                    # a read-only open leaves the disk untouched).
                    # walked only when THIS replay's deep walk covered
                    # the whole chunk; a shallow replay seals unwalked
                    if sidecar_mod.backfill(imm.fs, imm.path, n, cols,
                                            data, walked=stream_deep):
                        sidecar_mod.record("rebuilt", n)
        if pieces is not None:
            yield from pieces
        elif cols is not None:
            pcs = (
                ViewColumns.pieces_from_header_columns(cols)
                if columnar else None
            )
            if pcs is None:
                yield _views_from_columns(cols)
            else:
                yield from pcs
        else:
            win = []
            for e in entries:
                res.n_blocks += 1
                win.append(Block.from_bytes(
                    data[e.offset : e.offset + e.size]
                ).header.to_view())
            yield win
        if truncated:
            return  # corruption truncates the chain here


def _stream_views(imm: ImmutableDB, res: "ValidationResult"):
    """Per-header HeaderView stream (the sequential reference fold's
    input; the batched backends consume `_stream_windows`)."""
    from ..protocol.views import ViewColumns

    for win in _stream_windows(imm, res):
        if isinstance(win, ViewColumns):
            yield from win.views()
        else:
            yield from win


def _cap_windows(wins, cap: int):
    """Truncate a window stream to `cap` total headers."""
    left = cap
    for win in wins:
        if left <= 0:
            return
        if len(win) > left:
            yield win[:left]
            return
        left -= len(win)
        yield win


def _skip_headers(wins, n: int):
    """Drop the first `n` headers of a window stream (checkpoint
    resume: the retired prefix is already banked and the fold is
    re-seeded from the host progress record). ViewColumns windows slice
    in place, so the stream stays columnar across the resume point."""
    left = n
    for win in wins:
        if left <= 0:
            yield win
        elif len(win) <= left:
            left -= len(win)
        else:
            yield win[left:]
            left = 0


def _epoch_window_segments(params: PraosParams, wins):
    """Cut a stream of chunk windows at epoch boundaries (SURVEY.md
    §5.7), merging same-epoch pieces: the columnar analog of
    `_epoch_segments`. Consecutive same-width ViewColumns pieces merge
    into ONE columnar segment per epoch (one array concat); a row-width
    change inside an epoch (CBOR integer-width step) yields separate
    columnar segments rather than falling back to objects —
    validate_chain threads state across them identically (the
    within-epoch tick is a no-op rotation)."""
    from ..protocol.views import ViewColumns

    def pieces():
        import numpy as np

        for win in wins:
            if isinstance(win, ViewColumns):
                epochs = win.slot // params.epoch_length
                cuts = np.flatnonzero(np.diff(epochs)) + 1
                bounds = [0, *cuts.tolist(), len(win)]
                for k in range(len(bounds) - 1):
                    yield int(epochs[bounds[k]]), win[bounds[k]:bounds[k + 1]]
            else:
                seg: list = []
                e = None
                for hv in win:
                    he = params.epoch_of(hv.slot)
                    if e is None or he == e:
                        seg.append(hv)
                        e = he
                    else:
                        yield e, seg
                        seg, e = [hv], he
                if seg:
                    yield e, seg

    def flush(parts):
        group: list = []
        gw = None
        for p in parts:
            if isinstance(p, ViewColumns):
                wkey = (p.signed_bytes.shape[1], p.kes_sig.shape[1])
                if group and gw == wkey:
                    group.append(p)
                    continue
                if group:
                    yield ViewColumns.concat(group)
                group, gw = [p], wkey
            else:
                if group:
                    yield ViewColumns.concat(group)
                    group, gw = [], None
                yield p
        if group:
            yield ViewColumns.concat(group)

    acc: list = []
    epoch = None
    for e, piece in pieces():
        if epoch is None or e == epoch:
            acc.append(piece)
            epoch = e
        else:
            yield from flush(acc)
            acc, epoch = [piece], e
    if acc:
        yield from flush(acc)


def _prefetch_iter(gen, depth: int = 2):
    """Pull a generator on a background thread through a bounded queue:
    the view-stream (disk read + integrity walk + native column
    extraction) of segment k+1 runs while segment k validates on
    device — part of the round-10 threaded staging pipeline
    (OCT_STAGE_THREAD=0 restores the inline pull). Exceptions from the
    stream are forwarded to the consumer; an early consumer exit
    (first-failure truncation) stops the pump without blocking."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    end = object()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def pump():
        try:
            for item in gen:
                if not _put(item):
                    return
            _put(end)
        except BaseException as e:  # noqa: BLE001 — forwarded, re-raised
            _put(e)

    t = threading.Thread(target=pump, daemon=True, name="oct-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is end:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def revalidate(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    backend: str = "device",
    validate_all: bool = True,
    max_batch: int = 8192,
    max_headers: int | None = None,  # replay only the first N headers
    # (bench.py measures the native baseline RATE on a prefix of the 1M
    # chain so the wall budget converts into device measurement)
    trace=lambda s: None,
    ledger=None,  # LEDGER-DERIVED epoch views: replay blocks through
    genesis_state=None,  # this ledger and take the per-epoch pool
    # distribution from its stake snapshots (view_for_epoch) instead of
    # the constant `lview` — Ledger/SupportsProtocol.hs
    # ledgerViewForecastAt driven from Storage/LedgerDB/Update.hs:115
    collect_phases: bool = False,  # per-phase wall + H2D/D2H byte
    # attribution in the result (batch tracer; bench.py json fields)
    resume: bool | None = None,  # resume from the OCT_CHECKPOINT
    # progress record when one matches this chain (None = follow the
    # OCT_RESUME env lever) — obs/recovery.py; batched backends only
    repair: bool = False,  # opt-in ON-DISK write-back of the
    # truncation the deep/stream validation computes (--repair):
    # quarantine + truncate via ImmutableDB.repair_to. Defaults OFF —
    # analysis stays read-only — but a DIRTY open (missing clean-
    # shutdown marker) forces it on, the reference's forced-
    # revalidation-after-crash semantics
    network_magic: int | None = None,  # strict chain-magic check of
    # the DB marker (wrong-chain open refuses with DbMarkerMismatch);
    # None = accept the existing marker, create the default on a
    # virgin store
) -> ValidationResult:
    """only-validation analysis: full chain revalidation from genesis
    — or, with `OCT_CHECKPOINT` set and a resume requested, from the
    last retired window of a killed attempt (crash-consistent progress
    record, obs/recovery.py; proven verdict-identical to the
    uninterrupted replay by tests/test_selfheal.py).

    The open speaks the store crash protocol (storage/guard.py): DB
    lock (a concurrent open refuses loudly with DbLocked), chain-magic
    marker (a wrong-chain open refuses with DbMarkerMismatch), and the
    clean-shutdown marker — an open that cannot prove the last writer
    shut down cleanly escalates its validation policy to all-chunks
    WITH on-disk repair, and the result records `opened_dirty` +
    `repairs` ({action: count}; detailed rows in the warmup report).

    collect_phases=True threads a batch tracer through the replay and
    fills `res.phases` / `res.h2d_bytes` / `res.d2h_bytes` /
    `res.n_windows` / `res.packed_windows` — the per-phase wall and
    device-boundary byte attribution the bench json reports.

    With OCT_TRACE=1 the obs flight recorder additionally rides the
    replay (per-window spans, gate-decline attribution, Perfetto-
    exportable event stream — ouroboros_consensus_tpu/obs).

    With any live-plane lever set (OCT_HEARTBEAT / OCT_STALL_BUDGET_S /
    OCT_METRICS_PORT) the replay also arms obs/live.py: an atomically
    rewritten heartbeat file, the no-progress stall watchdog, and the
    in-run /metrics /healthz HTTP endpoint — the run stops being a
    black box WHILE it runs.
    """
    from .. import obs
    from ..obs import live as _live

    # arming is exception-safe END TO END: whatever escapes the replay
    # (a validation error, an exhausted recovery ladder, a failure in
    # maybe_arm itself) must release the live plane's ref-count and
    # stop the OCT_METRICS_PORT server thread — a failed replay may
    # never leave an orphan listener behind (tests/test_live.py)
    installed = obs.maybe_install()
    try:
        plane = _live.maybe_arm()
    except BaseException:
        if installed:
            obs.uninstall()
        raise
    try:
        return _revalidate_traced(
            db_path, params, lview, backend, validate_all, max_batch,
            max_headers, trace, ledger, genesis_state, collect_phases,
            resume, repair, network_magic,
        )
    finally:
        if plane is not None:
            plane.disarm()
        if installed:
            obs.uninstall()


def _revalidate_traced(
    db_path, params, lview, backend, validate_all, max_batch,
    max_headers, trace, ledger, genesis_state, collect_phases, resume,
    repair, network_magic,
) -> ValidationResult:
    if collect_phases:
        coll = _PhaseCollector()
        prev = pbatch.BATCH_TRACER

        def chained(ev, _prev=prev, _coll=coll):
            if _prev is not None:
                _prev(ev)
            _coll(ev)

        pbatch.set_batch_tracer(chained)
        try:
            res = _revalidate_impl(
                db_path, params, lview, backend, validate_all, max_batch,
                max_headers, trace, ledger, genesis_state, resume,
                repair, network_magic,
            )
        finally:
            pbatch.set_batch_tracer(prev)
        coll.fill(res)
        return res
    return _revalidate_impl(
        db_path, params, lview, backend, validate_all, max_batch,
        max_headers, trace, ledger, genesis_state, resume, repair,
        network_magic,
    )


def _revalidate_impl(
    db_path, params, lview, backend, validate_all, max_batch,
    max_headers, trace, ledger, genesis_state, resume=None,
    repair=False, network_magic=None,
) -> ValidationResult:
    """The store crash protocol around the replay (storage/guard.py):
    lock → marker → clean-shutdown check. A dirty open escalates the
    validation policy to all-chunks (`storage/open.escalate_policy` —
    Recovery.hs's forced revalidation) and forces repair write-back;
    a guard refusal (DbLocked / DbMarkerMismatch) raises BEFORE any
    bytes are read. An exception unwinding out of the replay leaves
    the store dirty (crash shape); a completed replay closes clean
    only when its walk PROVED the whole store (deep open-time
    validation, or an uncapped stream that reached the end of the
    chain — a stream aborted at a validation error checked nothing
    past the error and leaves a dirty store dirty)."""
    from ..storage import guard as _guard_mod
    from ..storage import open as _open_mod
    from ..storage import repair as _repair_mod

    res = ValidationResult()
    t0 = time.monotonic()
    policy = validate_all
    # writer mode iff this open may mutate the store: a deep open
    # repairs on disk (reference ValidateAllChunks), --repair writes
    # back stream truncations; plain stream/shallow analysis is a
    # reader and leaves the markers alone
    guard = _guard_mod.StoreGuard(
        db_path, network_magic=network_magic,
        writer=bool(repair) or policy is True,
    )
    if guard.writer and not os.path.exists(
        os.path.join(db_path, "immutable")
    ):
        # a writer-mode open of a path with no store would FABRICATE
        # one (lock + default-magic marker + clean marker) and report
        # a healthy 0/0 chain — a typo'd --db must refuse loudly
        # first. (A read-only scan of a virgin path stays legal and
        # side-effect-free.)
        raise FileNotFoundError(
            f"no store at {db_path} (refusing to create one — check --db)"
        )
    guard.open()
    try:
        if guard.opened_dirty:
            policy = _open_mod.escalate_policy(policy, True)
            guard.promote_writer()
            _repair_mod.note_repair(
                "dirty-open-escalated",
                detail=f"no clean-shutdown marker: policy {validate_all!r}"
                       f" -> {policy!r}, repair forced on",
            )
            repair = True
        res.opened_dirty = guard.opened_dirty
        imm = open_immutable(db_path, validate_all=policy, repair=repair)
        res.open_s = time.monotonic() - t0
        out = _revalidate_body(
            imm, res, t0, db_path, params, lview, backend, max_batch,
            max_headers, trace, ledger, genesis_state, resume,
        )
        counts: dict = {}
        if res.opened_dirty:
            counts["dirty-open-escalated"] = 1
        # APPLIED rows only: computed-only (read-only scan) rows ride
        # the warmup report, never the applied counts
        counts.update(_repair_mod.count_actions(getattr(imm, "repairs", ())))
        out.repairs = counts or None
    except BaseException:
        guard.close(clean=False)  # the crash shape: store stays dirty
        raise
    # Stamp clean only when this open PROVED store consistency: a deep
    # open walked every chunk at open time (wherever the replay then
    # stopped), but a stream ran its checks only over the chunks it
    # actually consumed: it covers the whole chain only when uncapped
    # AND the replay reached the end — a validation ERROR aborts the
    # stream mid-chain, leaving later chunks unchecked and unrepaired
    # (a checkpoint resume still reads every chunk — the skip is
    # window-level). A capped or error-aborted stream on a DIRTY store
    # must leave it dirty so the next open still force-revalidates the
    # rest (Recovery.hs:24-59 — the promise is ALL chunks, not "the
    # prefix the replay happened to read").
    full_walk = policy is True or (policy == "stream"
                                   and max_headers is None
                                   and out.error is None)
    guard.close(clean=full_walk or not res.opened_dirty)
    return out


def _revalidate_body(
    imm, res, t0, db_path, params, lview, backend, max_batch,
    max_headers, trace, ledger, genesis_state, resume=None,
) -> ValidationResult:
    """The revalidate body (wrapped by `revalidate` for attribution and
    by `_revalidate_impl` for the store crash protocol).

    backend="device": epoch-segmented batches through the fused kernel
    (further split at max_batch to bound device memory; the jit caches
    per padded shape).
    backend="native": same segmentation through the C++ verifier
    (native/hostcrypto.cpp) — the measured single-core CPU baseline.
    backend="sharded": multi-chip SPMD — the batch axis sharded over a
    jax.sharding.Mesh of ALL visible devices with psum/pmin verdict
    collectives (parallel/spmd.py); the production multi-chip path.
    backend="host": the sequential fold (reference semantics, pure Python).
    """

    def stream_views(imm, res):
        if max_headers is None:
            return _stream_views(imm, res)
        import itertools

        return itertools.islice(_stream_views(imm, res), max_headers)

    st = PraosState()
    if ledger is not None and getattr(ledger, "view_for_epoch", None):
        # ledger-derived epoch views: stream BLOCKS (the ledger replay
        # needs tx bodies), segment at epoch boundaries, and feed each
        # segment the pool distribution the ledger's stake snapshots
        # dictate for that epoch
        lst = genesis_state
        seg: list = []
        seg_epoch = None

        def flush(seg, seg_epoch, st, lst):
            first_slot = seg[0].slot
            tls = ledger.tick(lst, first_slot)  # seals due snapshots
            lview_e = ledger.view_for_epoch(tls.state, seg_epoch)
            hvs = [b.header.to_view() for b in seg]
            ts = time.monotonic()
            result = pbatch.validate_chain(
                params, lambda _e: lview_e, st, hvs,
                max_batch=max_batch,
                backend=backend if backend != "host" else "native",
            )
            res.device_s += time.monotonic() - ts
            for b in seg[: result.n_valid]:
                lst = ledger.tick_then_reapply(lst, b)
            return result, lst

        decode = Block.from_bytes
        block_stream = imm.stream_all()
        if max_headers is not None:
            import itertools

            block_stream = itertools.islice(block_stream, max_headers)
        for entry, raw in block_stream:
            res.n_blocks += 1
            b = decode(raw)
            e = params.epoch_of(b.slot)
            if seg_epoch is None or e == seg_epoch:
                seg.append(b)
                seg_epoch = e
                continue
            result, lst = flush(seg, seg_epoch, st, lst)
            st = result.state
            res.n_valid += result.n_valid
            if result.error is not None:
                res.error = result.error
                break
            seg, seg_epoch = [b], e
        if seg and res.error is None:
            result, lst = flush(seg, seg_epoch, st, lst)
            st = result.state
            res.n_valid += result.n_valid
            if result.error is not None:
                res.error = result.error
        res.final_state = st
        res.wall_s = time.monotonic() - t0
        return res
    if backend == "host":
        try:
            for hv in stream_views(imm, res):
                ticked = praos.tick(params, lview, hv.slot, st)
                st = praos.update(params, hv, hv.slot, ticked)
                res.n_valid += 1
        except praos.PraosValidationError as e:
            res.error = e
    elif backend in ("device", "native", "sharded"):
        # crash-consistent checkpoint/resume (obs/recovery.py): when
        # OCT_CHECKPOINT is set, validate_chain's retire path persists
        # a progress record per retired window under this chain's tag;
        # a requested resume re-seeds the fold from the record and
        # skips the already-banked prefix of the window stream.
        from ..obs import recovery as _recovery

        tag = _recovery.chain_tag(db_path, params)
        want_resume = (_recovery.resume_requested()
                       if resume is None else resume)
        rec_doc = _recovery.resume_record(tag) if want_resume else None
        _recovery.arm_writer(
            tag,
            resumed_headers=int(rec_doc["headers"]) if rec_doc else 0,
            resumed_windows=int(rec_doc["windows"]) if rec_doc else 0,
        )
        try:
            if rec_doc is not None:
                st = _recovery.decode_state(rec_doc["state"])
                res.n_valid = int(rec_doc["headers"])
                res.resumed_headers = int(rec_doc["headers"])
                _recovery.note_resume(rec_doc)
            # one epoch segment buffered at a time (bounded memory on
            # real chains); validate_chain pipelines staging against
            # device execution within each segment. Segments flow
            # COLUMNAR (ViewColumns) end-to-end from the native chunk
            # scan; HeaderView lists appear only without the native
            # library / OCT_COLUMNAR=0
            wins = _stream_windows(imm, res)
            if max_headers is not None:
                wins = _cap_windows(wins, max_headers)
            if res.resumed_headers:
                wins = _skip_headers(wins, res.resumed_headers)
            segs = _epoch_window_segments(params, wins)
            if backend == "device" and pbatch._stage_thread_enabled():
                # prefetch the NEXT epoch segment's disk/parse/column
                # work while this one validates — the device loop's
                # staging thread then overlaps prechecks+staging within
                # the segment
                segs = _prefetch_iter(segs, depth=2)
            for seg in segs:
                ts = time.monotonic()
                result = pbatch.validate_chain(
                    params, lambda _e: lview, st, seg,
                    max_batch=max_batch, backend=backend,
                )
                res.device_s += time.monotonic() - ts
                st = result.state
                res.n_valid += result.n_valid
                if result.error is not None:
                    res.error = result.error
                    break
                trace(f"validated {res.n_valid} headers")
            w = _recovery._WRITER
            if w is not None:
                # mark the record COMPLETE (cleanly or at a validation
                # error): a later resume never skips a fresh run's work
                # based on a finished one's position
                w.finalize(st, res.error)
        finally:
            _recovery.disarm_writer()
    else:
        raise ValueError(f"unknown backend {backend!r}")

    if max_headers is not None:
        # the native columnar stream counts whole chunks into n_blocks;
        # the cap consumes only the first max_headers of the last one
        res.n_blocks = min(res.n_blocks, max_headers)
    res.final_state = st
    res.wall_s = time.monotonic() - t0
    return res


def benchmark_ledger_ops(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    ledger=None,
    genesis_state=None,
    out_csv=None,
) -> list[SlotDataPoint]:
    """Per-block μs timings of the five ledger ops (Analysis.hs:526-607).

    The ledger tick/apply columns use the mock ledger when one is given
    (matching the reference, where ledger cost dwarfs header cost only
    on real eras); header columns always run the host Praos path.
    """
    imm = open_immutable(db_path, validate_all=False)
    rows: list[SlotDataPoint] = []
    st = PraosState()
    lst = genesis_state
    for entry, raw in imm.stream_all():
        block = Block.from_bytes(raw)
        h = block.header
        hv = h.to_view()

        t = time.monotonic()
        # forecast: ledger view at the header's slot (epoch-constant here)
        _ = lview
        forecast_us = (time.monotonic() - t) * 1e6

        t = time.monotonic()
        ticked = praos.tick(params, lview, h.slot, st)
        header_tick_us = (time.monotonic() - t) * 1e6

        t = time.monotonic()
        st = praos.update(params, hv, h.slot, ticked)
        header_apply_us = (time.monotonic() - t) * 1e6

        block_tick_us = block_apply_us = 0.0
        if ledger is not None and lst is not None:
            t = time.monotonic()
            tls = ledger.tick(lst, h.slot)
            block_tick_us = (time.monotonic() - t) * 1e6
            t = time.monotonic()
            lst = ledger.apply_block(tls, block)
            block_apply_us = (time.monotonic() - t) * 1e6

        rows.append(
            SlotDataPoint(
                slot=h.slot,
                block_no=h.block_no,
                block_bytes=len(raw),
                mut_forecast_us=forecast_us,
                mut_header_tick_us=header_tick_us,
                mut_header_apply_us=header_apply_us,
                mut_block_tick_us=block_tick_us,
                mut_block_apply_us=block_apply_us,
            )
        )
    if out_csv is not None:
        with open(out_csv, "w") as f:
            f.write(SlotDataPoint.CSV_HEADER + "\n")
            for r in rows:
                f.write(r.csv() + "\n")
    return rows


def count_blocks(db_path: str) -> int:
    imm = open_immutable(db_path)
    return imm.n_blocks()


def _stream_decoded(db_path: str, decode_block=None):
    """Shared streaming loop of the per-block analyses: yield decoded
    blocks in chain order (one decoder seam for all of them)."""
    decode = decode_block or Block.from_bytes
    for _entry, raw in open_immutable(db_path).stream_all():
        yield decode(raw)


def show_slot_block_no(db_path: str, out=None, decode_block=None) -> int:
    """ShowSlotBlockNo (Analysis.hs:76, showSlotBlockNo): print every
    block's slot and block number while streaming the ImmutableDB."""
    n = 0
    for b in _stream_decoded(db_path, decode_block):
        h = b.header
        if out is not None:
            out(f"slot: {h.slot}, blockNo: {h.block_no}")
        n += 1
    return n


def count_tx_outputs(db_path: str, decode_block=None) -> int:
    """CountTxOutputs (Analysis.hs:77): cumulative count of transaction
    outputs over the whole chain (the reference's per-block running
    total; we return the final total and emit per-block rows via
    `show_slot_block_no`-style streaming on demand)."""
    from ..ledger.mock import decode_tx

    total = 0
    for b in _stream_decoded(db_path, decode_block):
        for tx in getattr(b, "txs", ()):
            try:
                _ins, outs = decode_tx(tx)
                total += len(outs)
            except Exception:
                # opaque (non-mock-ledger) tx bytes count as zero outputs
                pass
    return total


def show_ebbs(db_path: str, decode_block=None, out=None) -> list[dict]:
    """ShowEBBs (Analysis.hs:81, Byron/EBBs.hs): list every epoch
    boundary block with its hash, previous hash, and the "known" flag
    the reference checks against its hard-coded EBB table (we have no
    such table — synthetic chains — so `known` reports whether the EBB
    chains onto the previous block we streamed)."""
    ebbs: list[dict] = []
    prev_hash = None
    for b in _stream_decoded(db_path, decode_block):
        h = b.header
        if getattr(h, "is_ebb", False) or getattr(
            getattr(h, "body", None), "is_ebb", False
        ):
            row = {
                "slot": h.slot,
                "hash": h.hash_.hex(),
                "prev": h.prev_hash.hex() if h.prev_hash else None,
                "known": prev_hash is None or h.prev_hash == prev_hash,
            }
            ebbs.append(row)
            if out is not None:
                out(f"EBB {row['hash']} at slot {row['slot']} "
                    f"(prev {row['prev']}, chains: {row['known']})")
        prev_hash = h.hash_
    return ebbs


def trace_ledger_processing(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    ledger,
    genesis_state,
    out=None,
) -> list:
    """TraceLedgerProcessing (Analysis.hs:80): replay the chain applying
    each block to the ledger and emit the InspectLedger events of every
    transition (the reference pipes `inspectLedger old new` to stdout —
    cardano-node's "entering era" family of messages)."""
    from ..ledger.inspect import inspect_ledger

    imm = open_immutable(db_path)
    events: list = []
    lst = genesis_state
    st = PraosState()
    for entry, raw in imm.stream_all():
        block = Block.from_bytes(raw)
        h = block.header
        ticked = praos.tick(params, lview, h.slot, st)
        st = praos.reupdate(params, h.to_view(), h.slot, ticked)
        new_lst = ledger.tick_then_reapply(lst, block)
        for ev in inspect_ledger(ledger, lst, new_lst):
            events.append((h.slot, ev))
            if out is not None:
                out(f"slot {h.slot}: {ev!r}")
        lst = new_lst
    return events


def check_state_growth_every(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    ledger,
    genesis_state,
    every: int = 100,
) -> list[dict]:
    """CheckNoThunksEvery analog (Analysis.hs:84,396-412): the reference
    walks the ledger state every N blocks looking for space leaks
    (unforced thunks). Python has no thunks; the equivalent failure mode
    is UNBOUNDED STATE GROWTH — structures that should be pruned (ocert
    counters per retired pool, protocol nonce history, UTxO bookkeeping)
    accreting per block. Samples state sizes every `every` blocks so a
    leak shows as a monotone slope instead of an OOM at block 10M."""
    import sys as _sys

    imm = open_immutable(db_path)
    st = PraosState()
    lst = genesis_state
    samples: list[dict] = []
    for i, (entry, raw) in enumerate(imm.stream_all()):
        block = Block.from_bytes(raw)
        h = block.header
        ticked = praos.tick(params, lview, h.slot, st)
        st = praos.reupdate(params, h.to_view(), h.slot, ticked)
        if ledger is not None:
            lst = ledger.tick_then_reapply(lst, block)
        if i % every == 0:
            samples.append(
                {
                    "block": i,
                    "slot": h.slot,
                    "ocert_counters": len(st.ocert_counters),
                    "utxo_entries": (
                        len(lst.utxo) if hasattr(lst, "utxo") else None
                    ),
                    "chain_dep_bytes": _sys.getsizeof(st.ocert_counters),
                }
            )
    return samples


def show_block_stats(db_path: str) -> dict:
    """GetBlockApplicationMetrics / block-size counts analog
    (Analysis.hs:75-88 counts/sizes family): min/max/total sizes + slot
    span without validating anything."""
    imm = open_immutable(db_path)
    n = 0
    total = 0
    smallest = None
    largest = None
    first_slot = last_slot = None
    # sizes/slots live in the CRC index — no body reads
    for entry in imm.iter_entries():
        n += 1
        total += entry.size
        smallest = entry.size if smallest is None else min(smallest, entry.size)
        largest = entry.size if largest is None else max(largest, entry.size)
        if first_slot is None:
            first_slot = entry.slot
        last_slot = entry.slot
    return {
        "n_blocks": n,
        "total_bytes": total,
        "min_block_bytes": smallest,
        "max_block_bytes": largest,
        "first_slot": first_slot,
        "last_slot": last_slot,
    }


def show_block_header_size(db_path: str, out=None, decode_block=None) -> int:
    """ShowBlockHeaderSize (Analysis.hs:78, showHeaderSize): per-block
    header byte size (HeaderSizeEvent) and the running maximum, which is
    returned (MaxHeaderSizeEvent)."""
    max_size = 0
    for b in _stream_decoded(db_path, decode_block):
        h = b.header
        size = len(h.bytes_)
        max_size = max(max_size, size)
        if out is not None:
            out(f"slot: {h.slot}, blockNo: {h.block_no}, headerSize: {size}")
    if out is not None:
        out(f"maxHeaderSize: {max_size}")
    return max_size


def show_block_txs_size(db_path: str, out=None, decode_block=None) -> tuple[int, int]:
    """ShowBlockTxsSize (Analysis.hs:79, showTxSize): per-block tx count
    and total tx byte size; returns the chain totals."""
    n_txs = 0
    total = 0
    for b in _stream_decoded(db_path, decode_block):
        txs = getattr(b, "txs", ())
        block_bytes = sum(len(tx) for tx in txs)
        n_txs += len(txs)
        total += block_bytes
        if out is not None:
            out(f"slot: {b.header.slot}, numBlockTxs: {len(txs)}, "
                f"blockTxsSize: {block_bytes}")
    if out is not None:
        out(f"total: {n_txs} txs, {total} bytes")
    return n_txs, total


def store_ledger_state_at(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    slot: int,
    ledger,
    genesis_state,
    snap_dir: str,
) -> str | None:
    """StoreLedgerStateAt (Analysis.hs:118): replay (reapply, no crypto)
    up to the last block with slot <= `slot` and write that
    ExtLedgerState as a LedgerDB-compatible snapshot — a later
    db-analyser/node run can start from it instead of genesis."""
    from ..ledger.extended import ExtLedgerState
    from ..ledger.header_validation import AnnTip, HeaderState
    from ..storage.ledgerdb import encode_snapshot
    from ..utils.fs import REAL_FS

    imm = open_immutable(db_path)
    st = PraosState()
    lst = genesis_state
    tip = None
    for entry, raw in imm.stream_all():
        if entry.slot > slot:
            break
        block = Block.from_bytes(raw)
        h = block.header
        ticked = praos.tick(params, lview, h.slot, st)
        st = praos.reupdate(params, h.to_view(), h.slot, ticked)
        lst = ledger.tick_then_reapply(lst, block)
        tip = AnnTip(h.slot, h.block_no, h.hash_)
    if tip is None:
        return None
    ext = ExtLedgerState(lst, HeaderState(tip, st))
    import os as _os

    REAL_FS.makedirs(snap_dir)
    name = f"snapshot-{tip.slot}"
    REAL_FS.write_atomic(_os.path.join(snap_dir, name), encode_snapshot(ext))
    return name


def repro_mempool_and_forge(
    db_path: str,
    ledger,
    genesis_state,
    n_blocks: int | None = None,
) -> list[dict]:
    """ReproMempoolAndForge (Analysis.hs:615): replay the chain and, at
    every block, push that block's txs through a mempool against the
    pre-block ledger state and time the two phases the reference
    reports — durTick (snapshot revalidation tick) and durSnap
    (snapshot acquisition) — plus the add time."""
    from ..mempool import Mempool

    imm = open_immutable(db_path)
    rows: list[dict] = []
    lst = genesis_state
    for i, (entry, raw) in enumerate(imm.stream_all()):
        if n_blocks is not None and i >= n_blocks:
            break
        block = Block.from_bytes(raw)
        pool_state = lst
        pool = Mempool(ledger, lambda: (pool_state, block.slot))
        t = time.monotonic()
        accepted, rejected = pool.try_add_txs(list(block.txs))
        add_us = (time.monotonic() - t) * 1e6
        t = time.monotonic()
        ticked = ledger.tick(lst, block.slot)
        tick_us = (time.monotonic() - t) * 1e6
        t = time.monotonic()
        snap = pool.get_snapshot_for(ticked.state, block.slot)
        snap_us = (time.monotonic() - t) * 1e6
        rows.append(
            {
                "slot": block.slot,
                "n_txs": len(block.txs),
                "accepted": len(accepted),
                "rejected": len(rejected),
                "mut_add_us": add_us,
                "dur_tick_us": tick_us,
                "dur_snap_us": snap_us,
            }
        )
        lst = ledger.tick_then_reapply(lst, block)
    return rows


def main(argv=None) -> None:
    """CLI (app/db-analyser.hs + DBAnalyser/Parsers.hs analog)."""
    import argparse

    from .db_synthesizer import default_params, make_credentials

    p = argparse.ArgumentParser(prog="db_analyser", description=__doc__)
    p.add_argument("--db", required=True)
    p.add_argument("--pools", type=int, default=2,
                   help="credential count the chain was synthesized with")
    p.add_argument("--kes-depth", type=int, default=7)
    p.add_argument(
        "--analysis",
        choices=["only-validation", "benchmark-ledger-ops", "count-blocks",
                 "show-block-stats", "show-slot-block-no",
                 "count-tx-outputs", "show-ebbs", "show-block-header-size",
                 "show-block-txs-size"],
        default="only-validation",
    )
    p.add_argument("--backend", choices=["device", "native", "sharded", "host"], default="device")
    p.add_argument("--resume", action="store_true",
                   help="resume only-validation from the OCT_CHECKPOINT "
                        "progress record when one matches this chain "
                        "(default: follow the OCT_RESUME env lever)")
    p.add_argument("--repair", action="store_true",
                   help="write back (quarantine + truncate on disk) the "
                        "corrupted-tail truncation the validation walk "
                        "computes; default off = read-only analysis. A "
                        "dirty open (missing clean-shutdown marker) "
                        "forces this on regardless")
    p.add_argument("--out-csv", default=None)
    p.add_argument("--config", default=None,
                   help="node config.json (defaults to <db>/config/config.json "
                        "when present) instead of --pools/--kes-depth")
    p.add_argument("--cardano", action="store_true",
                   help="the DB holds the multi-era composite "
                        "(DBAnalyser/Block/Cardano.hs dispatch): "
                        "era-tagged blocks, per-era protocols, optional "
                        "full ledger replay (--with-ledgers)")
    p.add_argument("--with-ledgers", action="store_true",
                   help="with --cardano: fold the real era ledgers too")
    a = p.parse_args(argv)
    if a.with_ledgers and not a.cardano:
        p.error("--with-ledgers requires --cardano")
    if a.cardano:
        # block-type dispatch to the composite (the reference's
        # db-analyser picks the block type from the node config;
        # the composite's defaults mirror CardanoMockConfig)
        import json as _json

        from ..hardfork import composite as cardano

        if a.analysis != "only-validation":
            raise SystemExit("--cardano supports only-validation")
        if a.repair or a.resume:
            # a silently ignored flag would fake a repair/resume that
            # never ran — refuse loudly (same rule as --config below)
            raise SystemExit(
                "--cardano does not support --repair/--resume (the "
                "composite replay opens its stores read-only)"
            )
        if a.config is not None:
            # an ignored config would revalidate under WRONG parameters
            # and report spurious errors — refuse loudly instead
            raise SystemExit(
                "--cardano reads the composite's built-in config "
                "(CardanoMockConfig defaults); --config is not supported"
            )
        cfg = cardano.CardanoMockConfig(with_ledgers=a.with_ledgers)
        res = cardano.revalidate(a.db, cfg, backend=a.backend)
        out = {
            "blocks": res.n_blocks, "valid": res.n_valid,
            "per_era": res.per_era,
            "error": None if res.error is None else repr(res.error),
        }
        if (res.error is not None and a.with_ledgers
                and res.n_valid == res.n_blocks):
            # CONSENSUS passed on every block, only the LEDGER replay
            # failed — most often a flag mismatch, not corruption
            out["hint"] = (
                "ledger replay failed on a consensus-valid chain — was "
                "the DB synthesized with --with-ledgers? (a consensus-"
                "only synthesis forges placeholder tx bytes)"
            )
        print(_json.dumps(out))
        return
    if a.analysis == "count-blocks":
        print(count_blocks(a.db))
        return
    if a.analysis == "show-block-stats":
        import json as _json

        print(_json.dumps(show_block_stats(a.db)))
        return
    if a.analysis == "show-slot-block-no":
        n = show_slot_block_no(a.db, out=print)
        print(f"{n} blocks")
        return
    if a.analysis == "count-tx-outputs":
        print(count_tx_outputs(a.db))
        return
    if a.analysis == "show-ebbs":
        rows = show_ebbs(a.db, out=print)
        print(f"{len(rows)} EBBs")
        return
    if a.analysis == "show-block-header-size":
        # the analysis prints its own summary line through `out`
        show_block_header_size(a.db, out=print)
        return
    if a.analysis == "show-block-txs-size":
        show_block_txs_size(a.db, out=print)
        return
    import os as _os

    config = a.config
    if config is None:
        implicit = _os.path.join(a.db, "config", "config.json")
        if _os.path.exists(implicit):
            config = implicit
    if config:
        from .config import load_config

        params, lview, _pools = load_config(config)
    else:
        params = default_params(kes_depth=a.kes_depth)
        _, lview = make_credentials(a.pools, kes_depth=a.kes_depth)
    if a.analysis == "benchmark-ledger-ops":
        rows = benchmark_ledger_ops(a.db, params, lview, out_csv=a.out_csv)
        print(f"{len(rows)} blocks benchmarked" + (
            f"; CSV at {a.out_csv}" if a.out_csv else ""))
        return
    res = revalidate(a.db, params, lview, backend=a.backend,
                     trace=lambda s: print(s),
                     resume=True if a.resume else None,
                     repair=a.repair)
    status = "OK" if res.error is None else f"INVALID at {res.n_valid}: {res.error!r}"
    if res.repairs:
        acts = ", ".join(f"{k}={v}" for k, v in sorted(res.repairs.items()))
        print(("dirty open — " if res.opened_dirty else "")
              + f"store repairs: {acts}")
    print(
        f"validated {res.n_valid}/{res.n_blocks} headers in {res.wall_s:.1f}s "
        f"(device {res.device_s:.1f}s) -> {status}"
    )


if __name__ == "__main__":
    main()
