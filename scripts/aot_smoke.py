"""Device-side AOT smoke + stage timing — the FIRST thing a live tunnel
window runs (VERDICT r4 item 1c: capture the never-measured vrf/finish
stage timings before anything that can wedge).

Loads the serialized v5e executables from scripts/aot_cache (compiled
devicelessly by aot_precompile.py), runs each on real staged inputs, and
prints per-stage hot rates — flushing after EVERY stage so a wedged
tunnel still leaves a partial table in the session log. Ends with the
composed 5-stage dispatch cross-checked against the native verifier.

Stage order: relayout (cheap, produces the limb-first inputs) -> vrf ->
finish (the never-measured pair) -> ed -> kes -> composed.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import jax

from bench import KES_DEPTH, MAX_BATCH, build_or_load_chain
from ouroboros_consensus_tpu.ops.pk import aot
from ouroboros_consensus_tpu.ops.pk import kernels as K
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.tools import db_analyser as ana

B = MAX_BATCH


def main():
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)
    path, params, lview = build_or_load_chain()

    # real staged batch: first B headers of the bench chain
    imm = ana.open_immutable(path, validate_all=False)
    res = ana.ValidationResult()
    hvs = []
    for hv in ana._stream_views(imm, res):
        hvs.append(hv)
        if len(hvs) >= B:
            break
    pre = pbatch.host_prechecks(params, lview, hvs)
    eta0 = None  # the bench chain's first epoch runs on the neutral nonce
    staged = pbatch.stage(params, lview, eta0, hvs, pre.kes_evolution)
    padded = pbatch.pad_batch_to(staged, pbatch.bucket_size(len(hvs)))
    cols = pbatch.flatten_batch(padded)
    print(f"staged {len(hvs)} headers -> bucket "
          f"{padded.beta.shape[0]}", flush=True)

    def timed(name, fn, *args, n=3):
        t0 = time.monotonic()
        out = fn(*args)
        jax.tree.map(np.asarray, out)
        first = time.monotonic() - t0
        t0 = time.monotonic()
        for _ in range(n):
            out = fn(*args)
        jax.tree.map(np.asarray, out)
        hot = (time.monotonic() - t0) / n
        print(f"AOT {name:8s} first {first:7.2f}s  hot {hot*1e3:8.1f}ms  "
              f"({B/hot:9.0f} lanes/s)", flush=True)
        return out

    def load(name, args):
        sig = aot.sig_of(args)
        ex = aot.load(name, B, KES_DEPTH, K.TILE, sig)
        if ex is None:
            print(f"AOT {name}: NO executable for sig={sig} — "
                  "falling back to jit", flush=True)
            return None
        return ex

    # relayout first: cheap, and the limb-first outputs feed the rest
    rel = load("relayout", cols)
    stages = dict(K.split_stage_fns(KES_DEPTH))
    t0 = time.monotonic()
    limb = (rel or stages["relayout"])(*cols)
    jax.tree.map(np.asarray, limb)
    print(f"relayout ({'AOT' if rel else 'jit'}): "
          f"{time.monotonic()-t0:.2f}s", flush=True)
    (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
     l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
     l_kes_hb, l_kes_hnb,
     l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al,
     l_beta, l_tlo, l_thi) = limb

    # vrf FIRST (never measured on hardware)
    vrf_args = (l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al)
    vrf = load("vrf", vrf_args)
    vrf_out = timed("vrf", vrf or stages["vrf"], *vrf_args)

    # finish next: ed/kes verdict inputs are dummies (zeros) — valid for
    # TIMING; correctness is the composed check below
    import jax.numpy as jnp

    z_ok = jnp.zeros((1, B), jnp.int32)
    z_pt = jnp.zeros((80, B), jnp.int32)
    fin_args = (z_ok, z_pt, l_ed_r, z_ok, z_pt, l_kes_r,
                vrf_out[0], vrf_out[1], l_vrf_c, l_beta, l_tlo, l_thi)
    fin = load("finish", fin_args)
    timed("finish", fin or stages["finish"], *fin_args)

    ed_args = (l_ed_pk, l_ed_s, l_ed_hb, l_ed_hnb)
    ed = load("ed", ed_args)
    timed("ed", ed or stages["ed"], *ed_args)

    kes_args = (l_kes_vk, l_kes_per, l_kes_s, l_kes_leaf, l_kes_sib,
                l_kes_hb, l_kes_hnb)
    kes = load("kes", kes_args)
    timed("kes", kes or stages["kes"], *kes_args)

    # composed production dispatch (AOT executables via _stage_call) +
    # correctness vs the native verifier on the real (unpadded) lanes
    t0 = time.monotonic()
    out = K.verify_praos_split(*cols, kes_depth=KES_DEPTH)
    v = pbatch._pk_materialize(out, len(hvs))
    wall = time.monotonic() - t0
    print(f"composed split dispatch: {wall:.2f}s "
          f"({len(hvs)/wall:.0f} headers/s incl. host)", flush=True)
    t0 = time.monotonic()
    out = K.verify_praos_split(*cols, kes_depth=KES_DEPTH)
    v = pbatch._pk_materialize(out, len(hvs))
    wall = time.monotonic() - t0
    print(f"composed hot: {wall*1e3:.1f}ms "
          f"({padded.beta.shape[0]/wall:.0f} lanes/s)", flush=True)

    vn = pbatch.run_batch_native(params, lview, eta0, hvs[:64], pre)
    mism = [
        (i, f)
        for i in range(64)
        for f in ("ok_ocert_sig", "ok_kes_sig", "ok_vrf")
        if bool(getattr(v, f)[i]) != bool(getattr(vn, f)[i])
    ]
    print(f"verdict cross-check vs native (64 lanes): "
          f"{'OK' if not mism else mism}", flush=True)
    assert not mism


if __name__ == "__main__":
    main()
