#!/usr/bin/env python
"""Cross-round trajectory report: fold the run ledger plus every
BENCH_r*.json / MULTICHIP_r*.json into one markdown + JSON document
with explicit regression verdicts.

The single biggest fact about five rounds of benchmarking — r01 banked
3,986 headers/s on device, r02–r05 banked nothing — lived only in the
heads of people who hand-diffed the round files. This tool makes the
trajectory a build artifact: which rounds banked a device number, what
each dead round died of (classified from its own recorded output — the
probe timeouts, axon-format AOT rejections and compile walls are all
IN the tails), what the host/native ceilings did, how much warmup wall
each round burned, how many packed-qualification gate declines and
octwall pre-flight refusals the telemetry counted, and what env/build
facts changed at each transition (from the obs/ledger records when a
ledger exists).

Regression verdicts are configurable and exit non-zero so a CI perf
gate can consume this directly:

    python scripts/perf_report.py                      # report, exit 0
    python scripts/perf_report.py --threshold 0.8      # newest round
        # must be >= 0.8x the best previous round's headers/s: exit 1
    python scripts/perf_report.py --require-device     # newest round
        # must have banked a DEVICE number: exit 1 otherwise
    python scripts/perf_report.py --json out.json --out report.md

Round-file schema is deliberately treated as hostile: the five
checked-in rounds span three generations of bench.py output (r01 has
no warmup forensics, r05 has no metrics snapshot), so every field is
optional and classification falls back to the recorded tail text.

Since round 11 bench also banks the LIVE plane's evidence: a
`live_timeline` (the parent-tailed heartbeat classifications) and any
`stall_dump` the child's watchdog wrote. A dead round whose last
heartbeat says `phase=dispatch, age=600s` classifies as
`stalled@dispatch` — distinct from probe-timeout and compile-wall.

Since round 12 the RECOVERY plane's evidence rides too: the warmup
report's `recovery` rows (obs/recovery.py — every degradation-ladder
transition of every episode). A round that banked its device number
only because the supervisor walked failing windows down the ladder is
its own class, `recovered@<fault>` — priority-wise between `stalled@`
(it did not die) and clean (it did not run clean either) — rendered
with its per-action transition counts.

Since round 13 the durable-store REPAIR plane rides the same way: the
warmup report's `repairs` rows (storage/repair.py — every on-disk
repair the open-with-repair scan applied: truncated tails, rebuilt
indices, dropped chunks, dirty-open escalations). A round whose store
opened dirty or was repaired under it classifies `repaired@<action>`
— priority between `recovered@` (the replay itself never failed) and
clean (the store was not healthy either) — with per-action counts."""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# failure-mode classifiers, matched (all of them) against a dead
# round's recorded output — order is presentation priority, the FIRST
# match is the primary attribution
_FAILURE_PATTERNS = (
    ("aot-cache-rejected",
     re.compile(r"axon format|serialized executable is incompatible",
                re.IGNORECASE),
     "stale AOT/persistent-cache executables rejected by the runtime"),
    ("warmup-exceeded-wall",
     re.compile(r"exceeded\s+\d+s?\s*budget|warmup exceed",
                re.IGNORECASE),
     "device attempt ran past its wall budget (compile/warmup wall)"),
    ("backend-probe-timeout",
     re.compile(r"probe (?:timed out|failed)", re.IGNORECASE),
     "TPU backend probe timed out (tunnel unreachable / init hung)"),
    ("compile-wall-refused",
     re.compile(r"compile-wall-refused", re.IGNORECASE),
     "octwall pre-flight refused a cold compile against the deadline"),
)


def _round_of(path: str, doc: dict) -> int:
    m = re.search(r"_r(\d+)\.json$", path)
    if m:
        return int(m.group(1))
    return int(doc.get("n", 0))


def _first_float(pattern: str, text: str) -> float | None:
    m = re.search(pattern, text)
    return float(m.group(1)) if m else None


def _classify_failures(text: str, rc, parsed: dict | None = None) -> list[dict]:
    out = []
    # LIVE-PLANE classification first (round 11): a banked stall dump
    # or a heartbeat timeline whose last word is stalled/dead names the
    # wedged phase — a round whose last heartbeat said phase=dispatch,
    # age=600s is "stalled@dispatch", structurally distinct from a
    # probe timeout or a compile wall
    stall = (parsed or {}).get("stall_dump")
    if isinstance(stall, dict):
        out.append({
            "mode": f"stalled@{stall.get('phase') or '?'}",
            "detail": (
                f"stall watchdog tripped after {stall.get('age_s', '?')}s "
                f"without progress (budget {stall.get('budget_s', '?')}s; "
                "all-thread stacks in the banked stall_dump)"
            ),
        })
    timeline = (parsed or {}).get("live_timeline") or []
    last_live = timeline[-1] if timeline else None
    if (isinstance(last_live, dict)
            and last_live.get("state") in ("stalled", "dead") and not out):
        phase = last_live.get("phase") or "?"
        out.append({
            "mode": f"stalled@{phase}",
            "detail": (
                f"last heartbeat: state={last_live['state']}, "
                f"phase={phase}, headers={last_live.get('headers')}, "
                f"age={last_live.get('age_s', '?')}s (banked "
                "live_timeline)"
            ),
        })
    # STRUCTURED classification next (round 10): bench.py banks the
    # backend-probe verdict and a no_device_reason, so probe-timeout vs
    # driver-timeout vs run-death no longer rides regex archaeology
    probe = (parsed or {}).get("probe")
    if isinstance(probe, dict) and not probe.get("ok"):
        mode = probe.get("outcome") or "backend-probe"
        attempts = probe.get("attempts") or []
        out.append({
            "mode": mode,
            "detail": (f"backend probe verdict ({len(attempts)} "
                       "attempt(s), banked by bench.py)"),
        })
    reason = (parsed or {}).get("no_device_reason")
    if reason and not any(f["mode"] == reason for f in out):
        out.append({"mode": reason,
                    "detail": "bench.py's banked no-device reason"})
    for key, rx, desc in _FAILURE_PATTERNS:
        if rx.search(text) and not any(f["mode"] == key for f in out):
            out.append({"mode": key, "detail": desc})
    if rc not in (0, None):
        out.append({
            "mode": f"driver-timeout (rc={rc})",
            "detail": "the driver killed the run before the JSON line",
        })
    if not out:
        out.append({"mode": "unknown",
                    "detail": "no recognizable failure pattern in the "
                              "recorded output"})
    return out


def _recovery_counts(wr: dict | None) -> tuple[dict, str | None]:
    """({action: count}, fault-of-the-first-recovered-episode) out of a
    banked warmup report's `recovery` rows (obs/recovery.py). The fault
    is the exception class the supervisor recovered FROM — what
    `recovered@<fault>` names."""
    rows = (wr or {}).get("recovery") or []
    counts: dict = {}
    fault = None
    for row in rows:
        if not isinstance(row, dict):
            continue
        a = row.get("action", "?")
        counts[a] = counts.get(a, 0) + 1
        if fault is None and a == "recovered":
            fault = row.get("fault") or "?"
    return counts, fault


_REPAIR_PRIORITY = ("truncate-chunk", "drop-chunk", "rebuild-index",
                    "sweep-orphan-index", "dirty-open-escalated")


def _repair_counts(wr: dict | None) -> tuple[dict, str | None]:
    """({action: count}, primary-action) out of a banked warmup
    report's `repairs` rows (storage/repair.py). Only APPLIED rows
    count (dry-run scans are not repairs); the primary action — what
    `repaired@<action>` names — is the most disk-invasive one."""
    rows = (wr or {}).get("repairs") or []
    counts: dict = {}
    for row in rows:
        if not isinstance(row, dict) or not row.get("applied", True):
            continue
        a = row.get("action", "?")
        counts[a] = counts.get(a, 0) + 1
    primary = None
    for a in _REPAIR_PRIORITY:
        if counts.get(a):
            primary = a
            break
    if primary is None and counts:
        primary = sorted(counts)[0]
    return counts, primary


def _gate_counts(metrics: dict | None) -> dict:
    """{gate: count} out of a banked metrics snapshot (or {})."""
    if not isinstance(metrics, dict):
        return {}
    fam = metrics.get("oct_gate_declines_total") or {}
    out = {}
    for s in fam.get("samples", []):
        gate = (s.get("labels") or {}).get("gate", "?")
        out[gate] = out.get(gate, 0) + int(s.get("value", 0))
    return out


def analyze_bench_round(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else None
    tail = str(doc.get("tail", "") or "")
    rc = doc.get("rc")
    metric_text = (parsed or {}).get("metric", "")
    headers = None
    m = re.search(r"(\d[\d_,]*)-header", metric_text)
    if m:
        headers = int(m.group(1).replace(",", "").replace("_", ""))
    device_banked = bool(
        parsed
        and not parsed.get("device_unavailable")
        and parsed.get("value")
    )
    wr = (parsed or {}).get("warmup_report")
    warmup = None
    ladder_events: list = []
    if isinstance(wr, dict):
        ladder_events = wr.get("ladder") or []
        warmup = {
            "compile_total_s": wr.get("compile_total_s"),
            "n_stages": wr.get("n_stages"),
            "aot": wr.get("aot"),
            "refusals": len(wr.get("refusals", [])),
            "ladder": len(ladder_events),
            "cache_probe": (wr.get("cache_probe") or {}).get("outcome"),
        }
    recovery_actions, recovered_fault = _recovery_counts(
        wr if isinstance(wr, dict) else None
    )
    repair_actions, repaired_action = _repair_counts(
        wr if isinstance(wr, dict) else None
    )
    row = {
        "round": _round_of(path, doc),
        "file": os.path.basename(path),
        "rc": rc,
        "headers": headers,
        "device_banked": device_banked,
        "value_per_s": (parsed or {}).get("value"),
        "vs_baseline": (parsed or {}).get("vs_baseline"),
        "native_baseline_per_s": _first_float(
            r"# native baseline (\d+(?:\.\d+)?) headers/s", tail)
            or ((parsed or {}).get("value")
                if parsed and parsed.get("device_unavailable") else None),
        "warmup_wall_s": _first_float(r"warmup=(\d+(?:\.\d+)?)s", tail),
        "warmup": warmup,
        # a LADDERED round banked its device number while the
        # production monolith compiled in the background — its own
        # class of round, not a warmup death (and for a dead round,
        # evidence the ladder engaged before the wall)
        "laddered": bool(ladder_events
                         or (parsed or {}).get("laddered")),
        "ladder_swapped": any(e.get("kind") == "swap"
                              for e in ladder_events),
        # the recovery plane's banked story (round 12): ladder-
        # transition counts per action, and — for a round that FINISHED
        # via recovery — the fault class it recovered from
        "recovery_actions": recovery_actions,
        "recovered_fault": recovered_fault,
        # the durable-store repair plane's banked story (round 13):
        # applied repair counts per action + whether the store opened
        # dirty (warmup `repairs` rows / the banked attribution)
        "repair_actions": repair_actions,
        "repaired_action": repaired_action,
        "opened_dirty": bool((parsed or {}).get("opened_dirty")
                             or repair_actions.get("dirty-open-escalated")),
        "resumed_headers": (parsed or {}).get("resumed_headers") or 0,
        # the live plane's banked story (round 11): timeline length +
        # last state, and whether a stall dump named a wedged phase
        "live_states": [e.get("state") for e in
                        ((parsed or {}).get("live_timeline") or [])
                        if isinstance(e, dict)],
        "stalled_phase": (
            ((parsed or {}).get("stall_dump") or {}).get("phase")
            if isinstance((parsed or {}).get("stall_dump"), dict)
            else None
        ),
        "gate_declines": _gate_counts((parsed or {}).get("metrics")),
        "failures": ([] if device_banked
                     else _classify_failures(tail, rc, parsed)),
    }
    return row


def analyze_multichip_round(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    tail = str(doc.get("tail", "") or "")
    rate = _first_float(r"\((\d+(?:\.\d+)?) headers/s", tail)
    return {
        "round": _round_of(path, doc),
        "file": os.path.basename(path),
        "ok": bool(doc.get("ok")),
        "skipped": bool(doc.get("skipped")),
        "n_devices": doc.get("n_devices"),
        "rate_per_s": rate,
        "failures": ([] if doc.get("ok")
                     else _classify_failures(tail, doc.get("rc"))),
    }


# ---------------------------------------------------------------------------
# Ledger fold: what actually changed between runs
# ---------------------------------------------------------------------------


def _env_diff(prev: dict, cur: dict) -> dict:
    """{key: [old, new]} over the banked OCT_*/BENCH_* env snapshots."""
    keys = set(prev) | set(cur)
    return {
        k: [prev.get(k), cur.get(k)]
        for k in sorted(keys) if prev.get(k) != cur.get(k)
    }


def ledger_section(ledger_dir: str | None) -> dict | None:
    from ouroboros_consensus_tpu.obs import ledger

    runs = ledger.read_runs(ledger_dir, kind=None)
    if not runs:
        return None
    bench_runs = [r for r in runs if r.get("kind") == "bench"]
    transitions = []
    for prev, cur in zip(bench_runs, bench_runs[1:]):
        delta: dict = {}
        if (prev.get("git") or {}).get("rev") != (cur.get("git") or {}).get("rev"):
            delta["git_rev"] = [(prev.get("git") or {}).get("rev"),
                                (cur.get("git") or {}).get("rev")]
        if prev.get("build_id") != cur.get("build_id"):
            delta["build_id"] = [prev.get("build_id"), cur.get("build_id")]
        env = _env_diff(prev.get("env") or {}, cur.get("env") or {})
        if env:
            delta["env"] = env
        transitions.append({
            "from_ts": prev.get("ts_iso"), "to_ts": cur.get("ts_iso"),
            "changed": delta,
        })
    kinds: dict = {}
    for r in runs:
        kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
    return {
        "runs": len(runs),
        "by_kind": kinds,
        "bench_transitions": transitions,
    }


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------


def regression_verdicts(rounds: list[dict], threshold: float | None,
                        require_device: bool) -> list[dict]:
    """Explicit, configurable verdicts; any verdict with ok=False makes
    the process exit non-zero (the future CI perf gate)."""
    verdicts: list[dict] = []
    if not rounds:
        return [{"rule": "rounds-present", "ok": False,
                 "detail": "no BENCH_r*.json found"}]
    latest = rounds[-1]
    prev = rounds[:-1]
    if threshold is not None:
        best_prev = max(
            (r["value_per_s"] for r in prev if r.get("value_per_s")),
            default=None,
        )
        val = latest.get("value_per_s")
        if best_prev is None:
            # nothing to compare against — say so EXPLICITLY instead of
            # silently appending no verdict (a CI gate that goes green
            # without evaluating anything is the failure shape this
            # tool exists to kill). Not a regression: there is no prior
            # bar to fall below; pair with --require-device to gate on
            # banking itself.
            verdicts.append({
                "rule": f"latest >= {threshold:g} x best-previous",
                "ok": True,
                "detail": (
                    "no previous round banked a measurable headers/s — "
                    "threshold rule has nothing to compare (pair with "
                    "--require-device to gate on banking)"
                ),
            })
        elif val:
            ratio = val / best_prev
            verdicts.append({
                "rule": f"latest >= {threshold:g} x best-previous",
                "ok": ratio >= threshold,
                "detail": (
                    f"r{latest['round']:02d} banked {val:g} headers/s vs "
                    f"best previous {best_prev:g} (ratio {ratio:.2f})"
                ),
            })
        else:
            # the worst regression of all: the newest round produced NO
            # measurable number (driver kill before the JSON line). A
            # threshold gate that silently passes here would wave the
            # r02 failure shape through CI.
            verdicts.append({
                "rule": f"latest >= {threshold:g} x best-previous",
                "ok": False,
                "detail": (
                    f"r{latest['round']:02d} banked no measurable "
                    f"headers/s at all (best previous {best_prev:g}): "
                    + ", ".join(f["mode"]
                                for f in latest.get("failures", []))
                ),
            })
    if require_device:
        verdicts.append({
            "rule": "latest-round-banks-device",
            "ok": bool(latest.get("device_banked")),
            "detail": (
                f"r{latest['round']:02d} "
                + ("banked a device result" if latest.get("device_banked")
                   else "banked NO device result: "
                   + ", ".join(f["mode"] for f in latest.get("failures", [])))
            ),
        })
    return verdicts


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _md_escape(v) -> str:
    return str(v).replace("|", "\\|")


def render_markdown(report: dict) -> str:
    out = ["# Benchmark trajectory", ""]
    rounds = report["bench_rounds"]
    device_rounds = [r for r in rounds if r["device_banked"]]
    out.append(
        f"{len(rounds)} bench round(s); "
        f"{len(device_rounds)} banked a device result"
        + (" (" + ", ".join(f"r{r['round']:02d}" for r in device_rounds)
           + ")" if device_rounds else "")
        + "."
    )
    out += ["", "## Rounds", ""]
    out.append("| round | headers | device | headers/s | vs native | "
               "native/s | warmup s | declines | failure modes |")
    out.append("|---|---|---|---|---|---|---|---|---|")
    for r in rounds:
        declines = sum(r["gate_declines"].values()) or ""
        warm = r.get("warmup_wall_s")
        if warm is None and r.get("warmup"):
            warm = r["warmup"].get("compile_total_s")
        out.append("| r{:02d} | {} | {} | {} | {} | {} | {} | {} | {} |".format(
            r["round"],
            r["headers"] or "?",
            "YES" if r["device_banked"] else "no",
            r["value_per_s"] if r["device_banked"] else "—",
            r["vs_baseline"] if r["device_banked"] else "—",
            r["native_baseline_per_s"] or "?",
            warm if warm is not None else "?",
            declines,
            _md_escape(
                ", ".join(f["mode"] for f in r["failures"])
                or ", ".join(filter(None, [
                    # a banked round that finished VIA recovery is its
                    # own class — priority between stalled@ (it did not
                    # die) and clean (it did not run clean either);
                    # repaired@ sits between recovered@ and clean (the
                    # replay never failed, the STORE was not healthy)
                    (f"recovered@{r['recovered_fault']}"
                     if r.get("recovered_fault") else None),
                    (f"repaired@{r['repaired_action']}"
                     if r.get("repaired_action") else None),
                    ("laddered" + (" (swapped)" if r.get("ladder_swapped")
                                   else "")
                     if r.get("laddered") else None),
                ]))
                or "—"
            ),
        ))
    dead = [r for r in rounds if not r["device_banked"]]
    if dead:
        out += ["", "## Failure attribution", ""]
        for r in dead:
            modes = "; ".join(
                f"**{f['mode']}** ({f['detail']})" for f in r["failures"]
            )
            if r.get("laddered"):
                modes += " — warm ladder HAD engaged before the death"
            if r.get("recovery_actions"):
                acts = ", ".join(f"{k}={v}" for k, v in
                                 sorted(r["recovery_actions"].items()))
                modes += (" — recovery ladder HAD engaged before the "
                          f"death ({acts})")
            if r.get("repair_actions"):
                acts = ", ".join(f"{k}={v}" for k, v in
                                 sorted(r["repair_actions"].items()))
                modes += (" — store repairs HAD been applied before "
                          f"the death ({acts})")
            out.append(f"* r{r['round']:02d}: {modes}")
    recovered = [r for r in rounds
                 if r["device_banked"] and r.get("recovery_actions")]
    if recovered:
        out += ["", "## Recovered rounds", ""]
        for r in recovered:
            acts = ", ".join(f"{k}={v}" for k, v in
                             sorted(r["recovery_actions"].items()))
            resumed = (f"; resumed past {r['resumed_headers']} banked "
                       "headers" if r.get("resumed_headers") else "")
            out.append(
                f"* r{r['round']:02d}: recovered@"
                f"{r.get('recovered_fault') or '?'} — the supervisor "
                f"walked failing windows down the ladder ({acts})"
                f"{resumed}; the banked number is a RECOVERED replay's"
            )
    repaired = [r for r in rounds
                if r["device_banked"] and r.get("repair_actions")]
    if repaired:
        out += ["", "## Repaired rounds", ""]
        for r in repaired:
            acts = ", ".join(f"{k}={v}" for k, v in
                             sorted(r["repair_actions"].items()))
            out.append(
                f"* r{r['round']:02d}: repaired@"
                f"{r.get('repaired_action') or '?'} — the store "
                + ("opened dirty and " if r.get("opened_dirty") else "")
                + f"was repaired under the replay ({acts}); the banked "
                "number is a replay of the repaired store"
            )
    laddered = [r for r in rounds if r["device_banked"] and r.get("laddered")]
    if laddered:
        out += ["", "## Laddered rounds", ""]
        for r in laddered:
            out.append(
                f"* r{r['round']:02d}: banked {r['value_per_s']} headers/s "
                "while the production monolith compiled in the background"
                + (" (swapped to production mid-replay)"
                   if r.get("ladder_swapped") else " (no swap before end)")
            )
    po = report.get("point_ops")
    if po:
        out += ["", "## Static point-op ratchet (budgets.json)", ""]
        out.append(
            "Per-lane point-op ceilings pinned by lint exit 3 / "
            "`scripts/count_point_ops.py --check` — the device-free "
            "half of the perf story. Round 15 folded the Ed25519 and "
            "KES ladders into the one-RLC shared-bucket MSM, so the "
            "whole per-window pipeline now rides one aggregated "
            "program."
        )
        out.append("")
        out.append("| graph | pinned lane-ops/lane | at lanes |")
        out.append("|---|---|---|")
        for name, cfg in po["pins"]:
            out.append(f"| {name} | {cfg['lane_ops_per_lane']:g} | "
                       f"{cfg['at_lanes']} |")
        total = po.get("all_stage_total")
        if total:
            out.append(
                f"| **all_stage_total** ({'+'.join(total['graphs'])}) | "
                f"**{total['lane_ops_per_lane']:g}** | "
                f"{total['at_lanes']} |"
            )
    hc = report.get("host_ceiling")
    if hc:
        out += ["", "## Host ceiling trajectory", ""]
        out.append(
            "The best rate any device can be fed at "
            "(`profile_replay.py --host`). Round 17's columnar sidecar "
            "streams device-ready windows straight off disk — a warm "
            "sidecar replaces the native parse with an mmap."
        )
        out.append("")
        out.append("| round/run | pipeline | ceiling headers/s | "
                   "sidecar | mmap s | parse s |")
        out.append("|---|---|---|---|---|---|")
        for m in hc["milestones"]:
            out.append(f"| {m['round']} | {m['what']} | "
                       f"{m['ceiling_per_s']:,} | — | — | — |")
        for r in hc["runs"]:
            sc = r.get("sidecar") or {}
            sc_txt = (f"hit {sc.get('hit', 0)} / miss {sc.get('miss', 0)}"
                      if sc else "—")
            out.append("| {} | {} | {} | {} | {} | {} |".format(
                (r.get("ts") or "?")[:19],
                "sidecar" if sc.get("hit") else "parse",
                r.get("ceiling_per_s") or "?",
                sc_txt,
                r.get("stream_mmap_s") if r.get("stream_mmap_s")
                is not None else "—",
                r.get("stream_parse_s") if r.get("stream_parse_s")
                is not None else "—",
            ))
    fg = report.get("forge")
    if fg:
        out += ["", "## Forge trajectory", ""]
        out.append(
            "Chain-synthesis rates (`profile_forge.py`): the per-slot "
            "reference loop vs the batched host engine vs the packed "
            "device sweep (PR 18). Stub runs isolate the pipeline "
            "(crypto-independent per-slot costs); native runs are what "
            "a TPU session banks."
        )
        out.append("")
        out.append("| run | crypto | pools | engine | slots | blocks | "
                   "slots/s | vs loop |")
        out.append("|---|---|---|---|---|---|---|---|")
        for r in fg["runs"]:
            for e in r["engines"]:
                speed = r["speedups"].get(f"{e['engine']}_vs_loop")
                out.append("| {} | {} | {} | {} | {} | {} | {:,} | {} |".format(
                    (r.get("ts") or "?")[:19], r.get("crypto") or "?",
                    r.get("pools") or "?", e.get("engine") or "?",
                    e.get("slots") or "?", e.get("blocks") or "?",
                    e.get("slots_per_s") or 0,
                    f"{speed}x" if speed else "—",
                ))
    sv = report.get("serve")
    if sv:
        out += ["", "## Serving plane", ""]
        out.append(
            "Follow-the-tip serving rates (`profile_serve.py`): the "
            "same seeded multi-peer suffix traffic validated as one "
            "window per peer (the naive port) vs continuous-batched "
            "shared windows (PR 20), verdict-identical by assertion. "
            "The SLO columns are the live `/slo` document scraped "
            "during the batched run."
        )
        out.append("")
        out.append("| run | tenants | mode | headers | windows | "
                   "headers/s | speedup | p50 s | p99 s |")
        out.append("|---|---|---|---|---|---|---|---|---|")
        for r in sv["runs"]:
            slo = r.get("slo") or {}
            for m in r["modes"]:
                batched = m.get("mode") == "batched"
                p50 = slo.get("verdict_latency_p50_s")
                p99 = slo.get("verdict_latency_p99_s")
                out.append("| {} | {} | {} | {} | {} | {:,} | {} | {} | {} |".format(
                    (r.get("ts") or "?")[:19], r.get("tenants") or "?",
                    m.get("mode") or "?", m.get("headers") or "?",
                    m.get("windows") or "?",
                    m.get("headers_per_s") or 0,
                    (f"{r['speedup']}x" if batched and r.get("speedup")
                     else "—"),
                    (round(p50, 4) if batched and p50 is not None else "—"),
                    (round(p99, 4) if batched and p99 is not None else "—"),
                ))
    mc = report.get("multichip_rounds") or []
    if mc:
        out += ["", "## Multichip", ""]
        out.append("| round | devices | ok | headers/s | failure |")
        out.append("|---|---|---|---|---|")
        for r in mc:
            out.append("| r{:02d} | {} | {} | {} | {} |".format(
                r["round"], r.get("n_devices", "?"),
                "ok" if r["ok"] else ("skipped" if r["skipped"] else "FAIL"),
                r.get("rate_per_s") or "—",
                _md_escape(", ".join(f["mode"] for f in r["failures"]) or "—"),
            ))
    led = report.get("ledger")
    if led:
        out += ["", "## Run ledger", ""]
        out.append(f"{led['runs']} ledger run(s): " + ", ".join(
            f"{k}={v}" for k, v in sorted(led["by_kind"].items())))
        for t in led["bench_transitions"]:
            if t["changed"]:
                out.append(
                    f"* {t['from_ts']} → {t['to_ts']}: "
                    + "; ".join(f"{k} {v}" for k, v in t["changed"].items())
                )
    out += ["", "## Verdicts", ""]
    if not report["verdicts"]:
        out.append("(no regression rules configured — report only)")
    for v in report["verdicts"]:
        out.append(f"* {'OK ' if v['ok'] else 'REGRESSION'} "
                   f"[{v['rule']}]: {v['detail']}")
    return "\n".join(out) + "\n"


# the banked host-ceiling milestones (PERF.md): the parse ceiling's
# round-by-round trajectory the round-17 sidecar row appends to —
# static anchors so the section renders even on a box whose ledger
# only has the newest runs
_HOST_CEILING_MILESTONES = (
    ("r08", "columnar host pipeline", 26_800),
    ("r09", "threaded staging + native extract", 118_700),
    ("r16", "pass-5 host pipeline", 177_000),
    ("r17", "columnar sidecar: walked seals + PCLMUL CRC + native "
            "span hash", 419_000),
)


def host_ceiling_section(ledger_dir: str | None) -> dict | None:
    """The host-ceiling trajectory: the static PERF.md milestone
    anchors plus every `profile_replay --host` ledger record, with the
    round-17 sidecar evidence (hit/miss counts, mmap-vs-parse wall
    split) when the record carries it. Fail-soft like the ledger
    section."""
    rows = []
    try:
        from ouroboros_consensus_tpu.obs import ledger

        for r in ledger.read_runs(ledger_dir, kind="profile_replay"):
            cfg = r.get("config") or {}
            if cfg.get("mode") != "host":
                continue
            res = r.get("result") or {}
            phases = r.get("phases_s") or {}
            rows.append({
                "ts": r.get("ts_iso"),
                "headers": res.get("headers"),
                "ceiling_per_s": res.get("ceiling_per_s"),
                "sidecar": res.get("sidecar"),
                "stream_mmap_s": phases.get("stream-mmap"),
                "stream_parse_s": phases.get("stream-parse"),
            })
    except Exception:  # noqa: BLE001 — report survives a broken ledger
        pass
    if not rows and ledger_dir == "0":
        return None
    return {"milestones": [
        {"round": rd, "what": what, "ceiling_per_s": v}
        for rd, what, v in _HOST_CEILING_MILESTONES
    ], "runs": rows}


def forge_section(ledger_dir: str | None) -> dict | None:
    """The forging-rate trajectory: every `profile_forge` ledger record
    (engine table + speedups). Fail-soft like the ledger section — a
    broken or absent ledger just drops the section."""
    rows = []
    try:
        from ouroboros_consensus_tpu.obs import ledger

        for r in ledger.read_runs(ledger_dir, kind="profile_forge"):
            cfg = r.get("config") or {}
            res = r.get("result") or {}
            rows.append({
                "ts": r.get("ts_iso"),
                "n": cfg.get("n"),
                "pools": cfg.get("pools"),
                "crypto": cfg.get("crypto"),
                "engines": res.get("engines") or [],
                "speedups": res.get("speedups") or {},
            })
    except Exception:  # noqa: BLE001 — report survives a broken ledger
        pass
    if not rows:
        return None
    return {"runs": rows}


def serve_section(ledger_dir: str | None) -> dict | None:
    """The serving-plane trajectory: every `profile_serve` ledger
    record (continuous batching vs one-window-per-peer, with the
    scraped /slo document). Fail-soft like the ledger section."""
    rows = []
    try:
        from ouroboros_consensus_tpu.obs import ledger

        for r in ledger.read_runs(ledger_dir, kind="profile_serve"):
            cfg = r.get("config") or {}
            res = r.get("result") or {}
            rows.append({
                "ts": r.get("ts_iso"),
                "tenants": cfg.get("tenants"),
                "rounds": cfg.get("rounds"),
                "suffix_len": cfg.get("suffix_len"),
                "max_window": cfg.get("max_window"),
                "modes": res.get("modes") or [],
                "speedup": res.get("speedup_batched_vs_per_peer"),
                "slo": res.get("slo") or {},
            })
    except Exception:  # noqa: BLE001 — report survives a broken ledger
        pass
    if not rows:
        return None
    return {"runs": rows}


def point_ops_section() -> dict | None:
    """The ratcheted per-lane point-op pins from budgets.json — no
    tracing, a dict read: the STATIC perf trajectory (what the
    MSM/aggregate refactors banked) surfaced next to the device
    rounds. Fail-soft: a missing/odd budgets file just drops the
    section."""
    try:
        from ouroboros_consensus_tpu.analysis import graphs as an_graphs

        sec = an_graphs.load_budgets().get("point_ops", {})
    except Exception:  # noqa: BLE001 — report survives a broken budgets file
        return None
    if not sec:
        return None
    pins = [(n, cfg) for n, cfg in sorted(sec.items())
            if n != "all_stage_total" and cfg.get("lane_ops_per_lane")]
    return {
        "pins": pins,
        "all_stage_total": sec.get("all_stage_total"),
    }


def build_report(dir_: str, threshold: float | None,
                 require_device: bool, ledger_dir: str | None) -> dict:
    bench_rounds = sorted(
        (analyze_bench_round(p)
         for p in glob.glob(os.path.join(dir_, "BENCH_r*.json"))),
        key=lambda r: r["round"],
    )
    multichip = sorted(
        (analyze_multichip_round(p)
         for p in glob.glob(os.path.join(dir_, "MULTICHIP_r*.json"))),
        key=lambda r: r["round"],
    )
    led = None
    if ledger_dir != "0":
        try:
            led = ledger_section(ledger_dir)
        except Exception:  # noqa: BLE001 — a broken ledger never kills the report
            led = None
    verdicts = regression_verdicts(bench_rounds, threshold, require_device)
    return {
        "bench_rounds": bench_rounds,
        "multichip_rounds": multichip,
        "ledger": led,
        "point_ops": point_ops_section(),
        "host_ceiling": host_ceiling_section(ledger_dir),
        "forge": forge_section(ledger_dir),
        "serve": serve_section(ledger_dir),
        "verdicts": verdicts,
        "ok": all(v["ok"] for v in verdicts),
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO,
                    help="where the BENCH_r*.json round files live")
    ap.add_argument("--ledger", default=None,
                    help="run-ledger dir (default: the repo ledger; "
                         "pass 0 to skip)")
    ap.add_argument("--out", default=None, help="write markdown here "
                    "(default: stdout)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the JSON report here")
    ap.add_argument("--threshold", type=float, default=None,
                    help="regression rule: newest round's headers/s "
                         "must be >= THRESHOLD x the best previous "
                         "round's (exit 1 otherwise)")
    ap.add_argument("--require-device", action="store_true",
                    help="regression rule: newest round must have "
                         "banked a device result")
    args = ap.parse_args(argv)

    report = build_report(args.dir, args.threshold, args.require_device,
                          args.ledger)
    md = render_markdown(report)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(md)
    else:
        sys.stdout.write(md)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
