"""A/B experiment: dedicated squaring + suffix accumulation for fe.mul.

Candidate formulations over the [20, T] 13-bit-limb representation:

  mul_suffix — pad-accumulate mul, but each term is added only into
    acc[i:] (rows < i are already final): total add rows drop from
    19x41=779 to sum(41-i)=589 (-24%).
  sqr_sym — symmetric squaring: row i contributes (a_i^2, 2a_{i+1}a_i,
    ..., 2a_19a_i) at offset 2i — 210 limb products instead of 400, and
    suffix accumulation from row 2i: add rows 399 (-49%). Column sums
    are IDENTICAL to mul(a,a)'s, so the bound analysis and carry
    structure are unchanged.

Correctness: differential vs fe.mul on random + edge inputs (CPU).
Timing: standalone pallas kernels looping K ops (run on TPU).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops.pk import limbs as fe
from ouroboros_consensus_tpu.ops import bigint as bi

NLIMBS, BITS, MASK, FOLD = fe.NLIMBS, fe.BITS, fe.MASK, fe.FOLD


def _finish_acc(acc, t):
    """Shared tail of the pad-accumulate mul: 2 carry passes over 41
    rows, fold, weak reduce (copied contract from fe.mul)."""
    for _ in range(2):
        c = acc >> BITS
        acc = (acc & MASK) + jnp.concatenate(
            [jnp.zeros((1, t), jnp.int32), c[:-1]], axis=0
        )
    lo, hi, top = acc[:NLIMBS], acc[NLIMBS: 2 * NLIMBS], acc[2 * NLIMBS:]
    lo = lo + hi * FOLD
    row0 = lo[:1] + top * (FOLD * FOLD)
    lo = jnp.concatenate([row0, lo[1:]], axis=0)
    return fe.weak_reduce(lo, passes=2)


def mul_suffix(a, b):
    t = max(a.shape[-1], b.shape[-1])
    acc = jnp.broadcast_to(a * b[0:1], (NLIMBS, t))
    acc = jnp.concatenate([acc, jnp.zeros((21, t), jnp.int32)], axis=0)
    for i in range(1, NLIMBS):
        term = a * b[i: i + 1]  # [20, T] at offset i
        pad = 41 - i - NLIMBS
        suff = acc[i:] + jnp.concatenate(
            [term, jnp.zeros((pad, t), jnp.int32)], axis=0
        )
        acc = jnp.concatenate([acc[:i], suff], axis=0)
    return _finish_acc(acc, t)


def sqr_sym(a):
    t = a.shape[-1]
    a2 = a + a  # < 2^15, products still < 2*B_MAX^2 per term
    acc = None
    for i in range(NLIMBS):
        rows = (a[i: i + 1] if i + 1 >= NLIMBS else
                jnp.concatenate([a[i: i + 1], a2[i + 1:]], axis=0))
        term = rows * a[i: i + 1]  # [20-i, T] at offset 2*i
        if acc is None:
            acc = jnp.concatenate(
                [term, jnp.zeros((21, t), jnp.int32)], axis=0
            )
            continue
        pad = 41 - 2 * i - (NLIMBS - i)
        parts = [term]
        if pad:
            parts.append(jnp.zeros((pad, t), jnp.int32))
        suff = acc[2 * i:] + (
            parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        )
        acc = jnp.concatenate([acc[: 2 * i], suff], axis=0)
    return _finish_acc(acc, t)


def _to_int(col):
    return bi.limbs_to_int_np(np.asarray(col))


def check():
    rng = np.random.default_rng(7)
    P = fe.P_INT
    vals = [0, 1, 2, P - 1, P - 19, (1 << 255) - 20]
    vals += [int.from_bytes(rng.bytes(32), "little") % P for _ in range(30)]
    # build [20, T] arrays via the field helpers
    from ouroboros_consensus_tpu.ops import field as f

    a = np.stack([f.int_to_limbs_np(v) for v in vals], axis=-1)
    b = np.stack(
        [f.int_to_limbs_np(int.from_bytes(rng.bytes(32), "little") % P)
         for _ in vals], axis=-1)
    a, b = jnp.asarray(a), jnp.asarray(b)

    ref_mul = fe.mul(a, b)
    got_mul = mul_suffix(a, b)
    ref_sqr = fe.mul(a, a)
    got_sqr = sqr_sym(a)
    for i, v in enumerate(vals):
        bm = _to_int(np.asarray(b)[:, i])
        assert _to_int(np.asarray(got_mul)[:, i]) % P == (v * bm) % P, i
        assert _to_int(np.asarray(got_sqr)[:, i]) % P == (v * v) % P, i
        assert (_to_int(np.asarray(ref_mul)[:, i]) - _to_int(np.asarray(got_mul)[:, i])) % P == 0
    print(f"correctness OK over {len(vals)} lanes")


def bench_device():
    import functools

    import jax
    from jax.experimental import pallas as pl

    from jax import lax

    T, K, CHAINS = 128, 400, 4  # 4 independent chains: the real ladders'
    # ILP shape (4 point coords in flight); fori_loop keeps module small

    def run(name, op, binary):
        def kern(x_ref, o_ref):
            vs = [x_ref[:] + i for i in range(CHAINS)]

            def body(_, ws):
                if binary:
                    return tuple(op(w, v) for w, v in zip(ws, vs))
                return tuple(op(w) for w in ws)

            ws = lax.fori_loop(0, K, body, tuple(vs))
            acc = ws[0]
            for w in ws[1:]:
                acc = acc + w
            o_ref[:] = acc

        f_ = pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((NLIMBS, T), jnp.int32),
        )
        x = jnp.asarray(
            np.random.default_rng(1).integers(0, MASK, (NLIMBS, T), np.int32)
        )
        jf = jax.jit(f_)
        t0 = time.time(); r = jax.block_until_ready(jf(x))
        print(f"{name}: compile+1 {time.time()-t0:.2f}s", flush=True)
        best = None
        for _ in range(5):
            t0 = time.time()
            jax.block_until_ready(jf(x))
            wall = time.time() - t0
            best = wall if best is None or wall < best else best
        nops = K * CHAINS
        print(f"{name}: best {best*1e3:9.2f}ms for {nops} ops "
              f"({best/nops*1e9:7.1f} ns/op)", flush=True)

    run("mul_cur", fe.mul, True)
    run("mul_suffix", mul_suffix, True)
    run("sqr_cur", lambda x: fe.mul(x, x), False)
    run("sqr_sym", sqr_sym, False)


if __name__ == "__main__":
    check()
    if "--bench" in sys.argv:
        bench_device()
