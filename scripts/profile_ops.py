"""Microbenchmark the crypto kernel building blocks on the real device.

Usage: python scripts/profile_ops.py [batch]
Prints per-op wall times so optimization targets the real hot spots.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import curve, field as fe, scalar, sha512

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
rng = np.random.default_rng(0)


def _sync(out):
    return jax.tree.map(np.asarray, out)


def timeit(name, fn, *args, n=10):
    fn_j = jax.jit(fn)
    _sync(fn_j(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    _sync(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:28s} {dt*1e3:9.3f} ms   ({dt*1e9/B:8.1f} ns/lane)", flush=True)
    return dt


def rand_fe(shape):
    return jnp.asarray(rng.integers(0, 8192, size=(*shape, fe.NLIMBS), dtype=np.int32))


a = rand_fe((B,))
b = rand_fe((B,))
pt = curve.Point(a, b, rand_fe((B,)), rand_fe((B,)))

print(f"batch = {B}, device = {jax.devices()[0]}")
timeit("field.mul", fe.mul, a, b)
timeit("field.sqr", fe.sqr, a)
timeit("field.add", fe.add, a, b)
timeit("field.canonical", fe.canonical, a)
timeit("curve.add", curve.add, pt, pt)
timeit("curve.double", curve.double, pt)
timeit("field.inv", fe.inv, a, n=3)
timeit("sqrt_ratio", lambda x, y: fe.sqrt_ratio(x, y)[1], a, b, n=3)

bits = jnp.asarray(rng.integers(0, 2, size=(B, 253), dtype=np.int32))
digits = scalar.windows4_from_bits(
    jnp.concatenate([bits, jnp.zeros((B, 3), jnp.int32)], axis=-1)
)
timeit("scalar_mul_w4 (253b)", curve.scalar_mul_w4, digits, pt, n=3)
timeit("base_mul", curve.base_mul, digits, n=3)

enc = jnp.asarray(rng.integers(0, 256, size=(B, 32), dtype=np.int32))
timeit("decompress", lambda e: curve.decompress(e)[1], enc, n=3)
timeit("compress", curve.compress, pt, n=3)

blocks = jnp.asarray(rng.integers(0, 2**32, size=(B, 4, 16, 2), dtype=np.uint32))
nb = jnp.full((B,), 4, jnp.int32)
timeit("sha512 (4 blocks)", sha512.sha512, blocks, nb, n=3)
