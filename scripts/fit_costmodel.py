#!/usr/bin/env python
"""Fit / validate the octwall compile-cost model (analysis/costmodel).

    python scripts/fit_costmodel.py --measure   # compile the calibration
                                                #   set on this box, fit,
                                                #   write costmodel.json
    python scripts/fit_costmodel.py --fit       # re-fit from the stored
                                                #   rows + banked bench
                                                #   warmup reports
    python scripts/fit_costmodel.py --check     # predicted-vs-measured:
                                                #   >= 80% of calibrated
                                                #   stages within 2x, else
                                                #   exit 1

Calibration rows come from two sources and are joined by the costmodel
feature hash, so every measured wall is matched EXACTLY to the static
features of the graph structure it was measured against:

  1. local calibration runs (--measure): a spread of synthetic jaxprs
     (multiply chains unfenced vs fori-fenced, elementwise ladders,
     scan bodies, dot stacks) plus the small/medium registry graphs,
     each compiled ONCE on this box (JAX_PLATFORMS=cpu) with its
     first-execute wall timed the same way obs/warmup.py times
     production stages;
  2. the per-stage first-execute walls the warmup recorder banks into
     BENCH round JSONs (`parsed.warmup_report.stages` — via=jit rows
     carry a feature_hash since PR 8; earlier rounds predate the hash
     and are reported as unjoinable, not silently dropped).

The model extrapolates to the composed monoliths (aggregate_core at
330k eqns) from the measured small/medium spread — that extrapolation
is exactly what the bench pre-flight gate needs: a structural estimate
good to ~2x, not a profiler.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ouroboros_consensus_tpu.analysis import costmodel, graphs  # noqa: E402

# registry graphs cheap enough to compile on the 1-core box; the
# composed cores (224k-330k eqns, many minutes each on XLA:CPU) are
# prediction targets, not calibration targets
MEASURE_REGISTRY = (
    "verdict_reduce", "packed_unpack", "msm", "finish_core", "ed_core",
)
MEASURE_REGISTRY_FULL = MEASURE_REGISTRY + ("kes_core", "vrf_core")


def _sds(shape, dtype="float32"):
    import jax
    from jax import numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _syn_chain(depth: int, fenced: bool):
    """An unrolled multiply chain of `depth` (the algebraic-simplifier
    pathology shape) or its fori_loop-fenced twin."""

    def unfenced(x):
        for _ in range(depth):
            x = x * x + x
        return x

    def fori(x):
        from jax import lax

        return lax.fori_loop(0, depth, lambda _, v: v * v + v, x)

    return (fori if fenced else unfenced), (_sds((32,)),)


def _syn_elementwise(n: int):
    def fn(x):
        for i in range(n):
            x = x + (x * 0.5 if i % 3 else x - 0.25)
        return x

    return fn, (_sds((64,)),)


def _syn_scan(body: int, length: int):
    def fn(x):
        from jax import lax

        def step(c, _):
            for i in range(body):
                c = c + c * 0.5 if i % 2 else c - 0.125
            return c, c

        out, _ = lax.scan(step, x, None, length=length)
        return out

    return fn, (_sds((32,)),)


def _syn_dots(n: int):
    def fn(x):
        from jax import numpy as jnp

        for _ in range(n):
            x = jnp.dot(x, x) / 17.0
        return x

    return fn, (_sds((16, 16)),)


def _syn_wide(fanout: int):
    def fn(x):
        parts = [x * (i + 1) for i in range(fanout)]
        return sum(parts)

    return fn, (_sds((64,)),)


def _syn_fences(n: int, body: int):
    """Many small fenced subcomputations (the split-stage shape)."""

    def fn(x):
        from jax import lax

        for _ in range(n):
            x = lax.fori_loop(0, 3, lambda _i, v: _chain_body(v, body), x)
        return x

    return fn, (_sds((32,)),)


def _chain_body(v, body):
    for i in range(body):
        v = v * 0.5 + v if i % 2 else v - 0.25
    return v


SYNTHETIC = {
    "syn_chain_64": _syn_chain(64, False),
    "syn_chain_256": _syn_chain(256, False),
    "syn_chain_640": _syn_chain(640, False),
    "syn_chain_640_fenced": _syn_chain(640, True),
    "syn_ew_512": _syn_elementwise(512),
    "syn_ew_2048": _syn_elementwise(2048),
    "syn_ew_8192": _syn_elementwise(8192),
    "syn_scan_200x8": _syn_scan(200, 8),
    "syn_scan_2000x4": _syn_scan(2000, 4),
    "syn_dots_64": _syn_dots(64),
    "syn_dots_256": _syn_dots(256),
    "syn_wide_256": _syn_wide(256),
    "syn_fences_48x16": _syn_fences(48, 16),
}


def _zeros_for(args):
    import numpy as np

    return [np.zeros(a.shape, dtype=a.dtype) for a in args]


def measure_one(name: str, fn, args) -> dict:
    """Trace (features) + compile-inclusive first-execute wall, timed
    exactly the way obs/warmup.py times a production stage."""
    import jax

    traced = jax.make_jaxpr(fn)(*args)
    feats = costmodel.extract_features(traced, name)
    concrete = _zeros_for(args)
    jitted = jax.jit(fn)
    t0 = time.monotonic()
    out = jitted(*concrete)
    jax.block_until_ready(out)
    wall = time.monotonic() - t0
    return {
        "stage": name,
        "graph": name if name in graphs.REGISTRY else None,
        "features": feats.to_dict(),
        "feature_hash": feats.hash(),
        "measured_s": round(wall, 3),
        "via": "local-calibration",
    }


def measure(full: bool = False) -> list[dict]:
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    rows = []
    targets = dict(SYNTHETIC)
    for g in (MEASURE_REGISTRY_FULL if full else MEASURE_REGISTRY):
        targets[g] = graphs.REGISTRY[g](None)
    for name, (fn, args) in targets.items():
        t0 = time.monotonic()
        row = measure_one(name, fn, args)
        rows.append(row)
        print(f"  {name:24s} eqns={row['features']['eqns']:>7d} "
              f"first-execute {row['measured_s']:7.2f}s "
              f"(total {time.monotonic()-t0:.1f}s)", flush=True)
    return rows


def bench_rows(pattern: str) -> tuple[list[dict], int]:
    """Joinable (feature-hash-matched) warmup-report stage walls from
    banked BENCH round JSONs; second result = rows seen but NOT
    joinable (no hash, aot via, or hash drifted from the current pin)."""
    rows, unjoined = [], 0
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = d.get("parsed") if isinstance(d.get("parsed"), dict) else d
        wr = (parsed or {}).get("warmup_report") or {}
        for stage, info in (wr.get("stages") or {}).items():
            if info.get("via") == "aot":
                continue  # an AOT load, not a compile
            h = info.get("feature_hash")
            g = costmodel.stage_graph(stage)
            pin = costmodel.pinned(g) if g else None
            if not h or not pin or pin.get("feature_hash") != h:
                unjoined += 1
                continue
            rows.append({
                "stage": f"{os.path.basename(path)}:{stage}",
                "graph": g,
                "features": pin["features"],
                "feature_hash": h,
                "measured_s": float(info["wall_s"]),
                "via": "bench-warmup",
            })
    return rows, unjoined


def check(rows: list[dict], model: dict | None) -> int:
    """Predicted-vs-measured: >= 80% of calibrated stages within 2x."""
    if not rows:
        print("no calibration rows to validate (run --measure first)")
        return 1
    if not model:
        print("no fitted model (run --measure or --fit first)")
        return 1
    n_ok = 0
    for r in rows:
        pred = costmodel.predict(r["features"], model)
        meas = max(1e-3, float(r["measured_s"]))
        ratio = pred / meas
        ok = 0.5 <= ratio <= 2.0
        n_ok += ok
        print(f"  {r['stage']:40s} measured {meas:8.2f}s "
              f"predicted {pred:8.2f}s x{ratio:5.2f} "
              f"{'ok' if ok else 'MISS'}")
    frac = n_ok / len(rows)
    print(f"check: {n_ok}/{len(rows)} within 2x ({frac:.0%}; need >= 80%)")
    return 0 if frac >= 0.8 else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure", action="store_true",
                    help="compile the calibration set, fit, write")
    ap.add_argument("--full", action="store_true",
                    help="include the slower registry graphs in --measure")
    ap.add_argument("--fit", action="store_true",
                    help="re-fit from stored rows + bench reports")
    ap.add_argument("--check", action="store_true",
                    help="validate predicted-vs-measured (>=80% within 2x)")
    ap.add_argument("--bench-glob",
                    default=os.path.join(REPO, "BENCH_r*.json"))
    args = ap.parse_args(argv)

    try:
        stored = costmodel.load_cost()
    except (OSError, ValueError):
        stored = {}
    calibration = list(stored.get("calibration", []))
    joined, unjoined = bench_rows(args.bench_glob)
    print(f"bench warmup reports: {len(joined)} joinable stage wall(s), "
          f"{unjoined} unjoinable (pre-hash rounds / drifted features / "
          "aot loads)")

    if args.measure:
        print("measuring calibration set (compile-inclusive first "
              "executes, JAX_PLATFORMS=cpu):", flush=True)
        calibration = measure(full=args.full)

    all_rows = calibration + joined
    if args.measure or args.fit:
        import jax

        backend = f"cpu/jax-{jax.__version__}"
        model = costmodel.fit_model(
            [(r["features"], r["measured_s"]) for r in all_rows],
            backend=backend,
        )
        costmodel.write_cost(model=model, calibration=calibration)
        print(f"costmodel.json: model re-fit on {len(all_rows)} row(s) "
              f"({backend}); coeffs: "
              f"{ {k: v for k, v in model['coeffs'].items() if v} }")
        print("(predicted_s pins recomputed from stored features; run "
              "scripts/lint.py --update-costs after structural changes)")

    if args.check:
        try:
            model = costmodel.load_cost().get("model")
        except (OSError, ValueError):
            model = None
        return check(all_rows, model)
    return 0


if __name__ == "__main__":
    sys.exit(main())
