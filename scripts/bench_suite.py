"""BASELINE.md config suite: one JSON line per benchmark config.

Covers the five configs BASELINE.json prescribes (bench.py at the repo
root is the driver-facing north-star — config 1 at full scale):

  1. db-analyser --only-validation on a db-synthesizer Praos chain
     (device vs measured single-core C++ baseline)
  2. standalone batched Ed25519 verify (Praos.hs:580 shape)
  3. batched Praos VRF leader checks (Praos.hs:528-556 + VRF.hs:55-112)
  4. batched CompactSum KES verifies (Praos.hs:582)
  5. mixed-era HFC revalidation (Cardano/CanHardFork.hs:273 shape) with
     the batched backend on the Praos-class segments

Sizes scale with --scale (1.0 = the BASELINE sizes; use 0.01 on CPU).

Usage: python scripts/bench_suite.py [--scale 0.05] [--configs 1,2,3,4,5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(config: int, metric: str, n: int, device_s: float, baseline_s: float | None,
          extra: dict | None = None):
    row = {
        "config": config,
        "metric": metric,
        "n": n,
        "device_per_s": round(n / device_s, 1) if device_s else None,
        "baseline_per_s": (
            round(n / baseline_s, 1) if baseline_s else None
        ),
        "vs_baseline": (
            round(baseline_s / device_s, 2) if device_s and baseline_s else None
        ),
    }
    if extra:
        row.update(extra)
    print(json.dumps(row))
    # one run-ledger record per config run (obs/ledger.py): the row plus
    # git/build/env provenance, with the heavyweight obs blocks split
    # into their dedicated record sections. record_replay folds in the
    # warmup + per-stage device-resource ledgers the row doesn't carry.
    try:
        from ouroboros_consensus_tpu.obs import ledger

        big = ("warmup_report", "metrics", "metrics_summary")
        ledger.record_replay(
            "bench_suite",
            config={"config": config, "n": n},
            result={k: v for k, v in row.items() if k not in big},
            **{k: row[k] for k in big if k in row},
        )
    except Exception:  # noqa: BLE001 — the ledger never breaks the suite
        pass
    return row


def _synth_once(path: str, forge) -> None:
    """Synthesize exactly once: a COMPLETE marker guards against reusing
    a chain left truncated by an interrupted earlier run."""
    import shutil

    marker = os.path.join(path, "COMPLETE")
    if os.path.exists(marker):
        return
    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    forge()
    with open(marker, "w") as f:
        f.write("ok")


def config1(scale: float, tmp: str):
    """End-to-end revalidation (10k headers at scale 1.0)."""
    from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

    n = max(200, int(10_000 * scale))
    params = db_synthesizer.default_params(kes_depth=7)
    pools, lview = db_synthesizer.make_credentials(1, kes_depth=7)
    path = os.path.join(tmp, f"cfg1-{n}")
    _synth_once(path, lambda: db_synthesizer.synthesize(
        path, params, pools, lview, db_synthesizer.ForgeLimit(blocks=n)
    ))
    t0 = time.monotonic()
    r = db_analyser.revalidate(path, params, lview, backend="device",
                               collect_phases=True)
    dev = time.monotonic() - t0
    assert r.error is None and r.n_valid == n
    t0 = time.monotonic()
    rb = db_analyser.revalidate(path, params, lview, backend="native")
    base = time.monotonic() - t0
    assert rb.error is None
    extra = {}
    if r.n_windows:
        # per-phase wall attribution + boundary bytes (set_batch_tracer
        # via collect_phases): the transfer tax is a bench-trajectory
        # column now, not an ad-hoc profiling artifact
        extra = {
            "phases_s": {k: round(v, 2) for k, v in sorted(r.phases.items())},
            "windows": r.n_windows,
            "packed_windows": r.packed_windows,
            "h2d_bytes_per_window": int(r.h2d_bytes / r.n_windows),
            "d2h_bytes_per_window": int(r.d2h_bytes / r.n_windows),
        }
    # compile/warmup forensics + (with OCT_TRACE=1) the flight
    # recorder's metrics snapshot ride into the suite row the same way
    # bench.py banks them into BENCH_r*.json
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    extra["warmup_report"] = WARMUP.report()
    if obs.enabled():
        extra["metrics_summary"] = obs.recorder().latency_summary()
        extra["metrics"] = obs.recorder().registry.snapshot()
    return _emit(1, "headers revalidated end-to-end", n, dev, base, extra)


def _ed25519_inputs(n):
    from ouroboros_consensus_tpu.ops.host import fast

    seeds = [bytes([i % 251 + 1]) * 32 for i in range(n)]
    msgs = [b"witness-%d" % i for i in range(n)]
    pks = [fast.ed25519_public(s) for s in seeds]
    sigs = [fast.ed25519_sign(s, m) for s, m in zip(seeds, msgs)]
    return pks, sigs, msgs


def config2(scale: float, tmp: str):
    """64k standalone Ed25519 verifies."""
    import numpy as np

    from ouroboros_consensus_tpu import native_loader as nl
    from ouroboros_consensus_tpu.ops import ed25519_batch

    n = max(256, int(65_536 * scale))
    pks, sigs, msgs = _ed25519_inputs(n)
    ok = ed25519_batch.verify_batch(pks[:8], sigs[:8], msgs[:8])  # warm
    t0 = time.monotonic()
    ok = ed25519_batch.verify_batch(pks, sigs, msgs)
    dev = time.monotonic() - t0
    assert np.asarray(ok).all()
    t0 = time.monotonic()
    for p, s, m in zip(pks, sigs, msgs):
        assert nl.native_ed25519_verify(p, s, m)
    base = time.monotonic() - t0
    return _emit(2, "standalone Ed25519 verifies", n, dev, base)


def config3(scale: float, tmp: str):
    """100k VRF leader checks (verify + leader threshold)."""
    import numpy as np

    from ouroboros_consensus_tpu import native_loader as nl
    from ouroboros_consensus_tpu.ops import ecvrf_batch
    from ouroboros_consensus_tpu.ops.host import fast
    from ouroboros_consensus_tpu.protocol import nonces

    n = max(256, int(100_000 * scale))
    eta = b"\x07" * 32
    seeds = [bytes([i % 251 + 1]) * 32 for i in range(n)]
    alphas = [nonces.mk_input_vrf(i, eta) for i in range(n)]
    pks = [fast.ed25519_public(s) for s in seeds]
    pis = [fast.ecvrf_prove(s, a) for s, a in zip(seeds, alphas)]
    ecvrf_batch.verify_batch(pks[:8], pis[:8], alphas[:8])  # warm
    t0 = time.monotonic()
    ok, betas = ecvrf_batch.verify_batch(pks, pis, alphas)
    dev = time.monotonic() - t0
    assert np.asarray(ok).all()
    t0 = time.monotonic()
    for p, pi, a in zip(pks, pis, alphas):
        assert nl.native_ecvrf_verify(p, pi, a) is not None
    base = time.monotonic() - t0
    return _emit(3, "VRF leader-check verifies", n, dev, base)


def config4(scale: float, tmp: str):
    """50k CompactSum7 KES verifies."""
    import numpy as np

    from ouroboros_consensus_tpu import native_loader as nl
    from ouroboros_consensus_tpu.ops import kes_batch
    from ouroboros_consensus_tpu.ops.host import kes as hk

    n = max(256, int(50_000 * scale))
    depth = 7
    # a handful of keys at varied evolutions, repeated across the batch
    base_keys = [(bytes([i + 1]) * 32, i % 5) for i in range(8)]
    vks, periods, msgs, sigs = [], [], [], []
    for i in range(n):
        seed, t = base_keys[i % len(base_keys)]
        msg = b"hdr-%d" % i
        vks.append(hk.derive_vk(seed, depth))
        periods.append(t)
        msgs.append(msg)
        sigs.append(hk.sign(seed, depth, t, msg))
    kes_batch.verify_batch(vks[:8], periods[:8], msgs[:8], sigs[:8], depth)
    t0 = time.monotonic()
    ok = kes_batch.verify_batch(vks, periods, msgs, sigs, depth)
    dev = time.monotonic() - t0
    assert np.asarray(ok).all()
    t0 = time.monotonic()
    for v, p, m, s in zip(vks, periods, msgs, sigs):
        assert nl.native_kes_verify(v, depth, p, m, s)
    base = time.monotonic() - t0
    return _emit(4, "CompactSum7 KES verifies", n, dev, base)


def config5(scale: float, tmp: str):
    """Mixed-era (Byron→TPraos→Praos) revalidation through the HFC."""
    from ouroboros_consensus_tpu.hardfork import composite

    n_slots = max(300, int(30_000 * scale))
    cfg = composite.CardanoMockConfig()
    path = os.path.join(tmp, f"cfg5-{n_slots}")
    _synth_once(path, lambda: composite.synthesize(path, cfg, n_slots))
    t0 = time.monotonic()
    r = composite.revalidate(path, cfg, backend="device")
    dev = time.monotonic() - t0
    assert r.error is None
    t0 = time.monotonic()
    rb = composite.revalidate(path, cfg, backend="native")
    base = time.monotonic() - t0
    assert rb.error is None
    return _emit(5, "mixed-era HFC blocks revalidated", r.n_valid, dev, base)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--tmp", default="/tmp/oc-bench-suite")
    args = ap.parse_args(argv)
    os.makedirs(args.tmp, exist_ok=True)
    import jax

    # honor an explicit platform request even under a sitecustomize that
    # force-prefers a TPU plugin after interpreter start (bench.py does
    # the same): the env var alone is not enough there
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    fns = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}
    for c in (int(x) for x in args.configs.split(",")):
        fns[c](args.scale, args.tmp)


if __name__ == "__main__":
    main()
