"""Deviceless AOT artifact BUILDER for the v5e stage programs.

Compiles every per-stage jit of the production pk dispatch
(ops/pk/kernels.verify_praos_split) against a v5e TopologyDescription
using libtpu's compile-only client — NO tunnel, no device — and saves
the PJRT executables into the build-pinned artifact store
(ops/pk/aot.py: scripts/aot_cache/<build-slug>/ + manifest).  A live
TPU session (OCT_PK_AOT=1) then loads instead of compiling, so a
flaky-tunnel window spends ~0 s in Mosaic and goes straight to
measurement (VERDICT r4 item 1b).

The store is keyed by RUNTIME BUILD: export
``OCT_AOT_BUILD_ID='<platform_version>'`` (take it from a previous
round's banked ``build_id``) so the artifacts are filed under the
runtime that will load them — without it they land under this box's
own build and the TPU child skips them as ``wrong_build`` (a zero-cost
skip, not a ~15 s rejected deserialize; the child's write-back then
populates the store itself).

Shape discovery replays the EXACT batching the bench replay performs
(epoch segments -> max_batch slices -> power-of-two padding) over the
cached bench chain, so every executable matches a real batch signature
— including the per-batch KES hash-block count, which tracks the
longest signed header bytes in each batch.

Usage: python scripts/aot_precompile.py [--check]
  --check: compile nothing — verify every manifest entry of the
           CURRENT build's store deserializes under this runtime
           (exit 1 on any problem).
Env: BENCH_HEADERS/BENCH_KES_DEPTH/BENCH_MAX_BATCH as bench.py;
     OCT_AOT_BUILD_ID pins artifact provenance (see above).
"""

import functools
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["OCT_PK_INTERPRET"] = "0"  # real Mosaic lowering from CPU
os.environ.setdefault("OCT_PK_HASH_IMPL", "unrolled")  # TPU hash path

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402

from bench import KES_DEPTH, MAX_BATCH, build_or_load_chain  # noqa: E402
from ouroboros_consensus_tpu.ops.pk import aot  # noqa: E402
from ouroboros_consensus_tpu.ops.pk import kernels as K  # noqa: E402
from ouroboros_consensus_tpu.protocol import batch as pbatch  # noqa: E402
from ouroboros_consensus_tpu.tools import db_analyser as ana  # noqa: E402

TOPOLOGY = os.environ.get("OCT_AOT_TOPOLOGY", "v5e:2x2")
# wall budget for THIS precompile run (seconds; 0 = unlimited). Stages
# whose octwall-predicted compile wall cannot fit the remaining budget
# are skipped (recorded in the manifest) instead of blowing it.
AOT_BUDGET = float(os.environ.get("OCT_AOT_BUDGET", "0") or 0)
_T0 = time.time()


def _predicted_wall(stage: str) -> float | None:
    """octwall pinned prediction for a stage's graph twin (dict lookup,
    no tracing). The model is calibrated on first-execute walls, which
    bound the lower+compile bracket here from above — conservative in
    the safe direction for the budget skip."""
    from ouroboros_consensus_tpu.analysis import costmodel

    g = costmodel.stage_graph(stage)
    return costmodel.predicted_wall(g) if g else None


def discover_batches(path, params):
    """Yield (bucket, representative HeaderView with the longest signed
    bytes) per distinct (bucket, max-signed-len) over the replay's exact
    batch slicing."""
    imm = ana.open_immutable(path, validate_all=False)
    res = ana.ValidationResult()
    seen = {}
    for seg in ana._epoch_segments(params, ana._stream_views(imm, res)):
        for i in range(0, len(seg), MAX_BATCH):
            sub = seg[i : i + MAX_BATCH]
            bucket = pbatch.bucket_size(len(sub))
            rep = max(sub, key=lambda hv: len(hv.signed_bytes))
            key = (bucket, len(rep.signed_bytes), len(rep.ocert.signable()))
            if key not in seen:
                seen[key] = (bucket, rep)
    return list(seen.values())


def staged_sds(params, lview, bucket, rep, sharding):
    """ShapeDtypeStructs for the relayout stage: stage a tiny batch
    around the representative header, pad to the bucket — per-column
    shapes depend only on (bucket, longest message), so these equal the
    real batch's."""
    hvs = [rep] * 8
    pre = pbatch.host_prechecks(params, lview, hvs)
    staged = pbatch.stage(params, lview, b"\x00" * 32, hvs, pre.kes_evolution)
    padded = pbatch.pad_batch_to(staged, bucket)
    cols = pbatch.flatten_batch(padded)
    return [
        jax.ShapeDtypeStruct(np.asarray(c).shape, np.asarray(c).dtype,
                             sharding=sharding)
        for c in cols
    ]


def packed_sds(params, lview, bucket, rep, sharding):
    """(layout, unpack-arg SDS list, reduce-arg SDS list) for the PACKED
    dispatch (the production default), or None when the representative
    header does not qualify for packed staging."""
    hvs = [rep] * 8
    res = pbatch.stage_packed(params, lview, b"\x00" * 32, hvs)
    if res is None:
        return None
    layout, parr = res
    parr = pbatch.pad_packed_to(parr, bucket)

    def sds(a):
        a = np.asarray(a)
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding)

    unpack_in = [sds(c) for c in parr[:10]]  # body .. nonce
    i32 = np.int32
    red_in = [
        jax.ShapeDtypeStruct((5, bucket), i32, sharding=sharding),  # flags
        jax.ShapeDtypeStruct((32, bucket), i32, sharding=sharding),  # eta
        sds(parr.within),
        jax.ShapeDtypeStruct((), i32, sharding=sharding),  # n_real
        jax.ShapeDtypeStruct((32,), i32, sharding=sharding),  # ev0
        jax.ShapeDtypeStruct((), np.bool_, sharding=sharding),  # ev0_set
        jax.ShapeDtypeStruct((32,), i32, sharding=sharding),  # cand0
        jax.ShapeDtypeStruct((), np.bool_, sharding=sharding),  # cand0_set
    ]
    return layout, unpack_in, red_in


def compile_stage(name, fn, in_sds, b, manifest, kes_depth=KES_DEPTH,
                  tile=K.TILE, wall_label=None):
    """Compile-and-save one stage; returns True iff a FRESH executable
    was written (False = an on-disk entry was reused). The unified
    aggregate programs pass kes_depth=0, tile=0 — the store key
    protocol/batch._warm_timed loads them back under (the layout's
    depth is baked into the program, not the key)."""
    sig = aot.sig_of(in_sds)
    path = aot.stage_path(name, b, kes_depth, tile, sig)
    key = aot.entry_key(name, b, kes_depth, tile, sig)
    # cached means artifact AND manifest row: a crash between the
    # artifact write and the manifest update (or a corrupt manifest)
    # orphans the file — load() gates on the manifest, so an orphan is
    # permanently "missing" unless the builder heals the row here
    if os.path.exists(path) and key in aot.read_manifest():
        print(f"  {name:8s} sig={sig} — cached", flush=True)
        return False
    predicted = _predicted_wall(wall_label or name)
    if AOT_BUDGET and predicted is not None:
        remaining = AOT_BUDGET - (time.time() - _T0)
        if predicted > remaining:
            print(f"  {name:8s} sig={sig} — SKIPPED: predicted "
                  f"{predicted:.0f}s compile > {remaining:.0f}s of "
                  "OCT_AOT_BUDGET left", flush=True)
            manifest.append({
                "stage": name, "b": b, "sig": sig, "skipped": True,
                "predicted_s": round(predicted, 1),
                "budget_left_s": round(remaining, 1),
            })
            return False
    t0 = time.time()
    lowered = jax.jit(fn).trace(*in_sds).lower(lowering_platforms=("tpu",))
    t_lower = time.time() - t0
    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0
    meta = {
        "stage": name, "b": b, "kes_depth": kes_depth, "tile": tile,
        "sig": sig, "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1), "topology": TOPOLOGY,
        "jax": jax.__version__,
        "hash_impl": os.environ.get("OCT_PK_HASH_IMPL", ""),
    }
    p = aot.save(name, b, kes_depth, tile, sig, compiled, meta)
    meta["bytes"] = os.path.getsize(p)
    if predicted is not None:
        meta["predicted_s"] = round(predicted, 1)
    manifest.append(meta)
    pred_note = (f" (octwall predicted {predicted:.0f}s)"
                 if predicted is not None else "")
    print(f"  {name:8s} sig={sig} lower {t_lower:6.1f}s compile "
          f"{t_compile:6.1f}s -> {meta['bytes']/1e6:.1f} MB{pred_note}",
          flush=True)
    return True


def check() -> int:
    """--check: every manifest entry of the current build's store must
    deserialize under THIS runtime (the store's loadability contract —
    run it on the target box before a bench session)."""
    ok, problems = aot.check_store()
    print(f"store {aot.store_dir()} (build {aot.build_id()!r}): "
          f"{ok} entr(y/ies) deserialize clean")
    for p in problems:
        print(f"  PROBLEM: {p}")
    return 1 if problems else 0


def main():
    t0 = time.time()
    path, params, lview = build_or_load_chain()
    topo = topologies.get_topology_desc(TOPOLOGY, "tpu")
    shard = jax.sharding.SingleDeviceSharding(topo.devices[0])
    combos = discover_batches(path, params)
    print(f"discovered {len(combos)} distinct batch signature(s) in "
          f"{time.time()-t0:.1f}s: "
          f"{[(b, len(r.signed_bytes)) for b, r in combos]}", flush=True)
    print(f"store: {aot.store_dir()} (build {aot.build_id()!r})", flush=True)
    if not os.environ.get("OCT_AOT_BUILD_ID"):
        print("# note: OCT_AOT_BUILD_ID unset — artifacts are pinned to "
              "THIS box's runtime; a TPU child on another build will "
              "skip them as wrong_build", flush=True)

    # compile-run log (predicted vs actual walls per stage) beside the
    # store's own provenance manifest
    manifest = []
    manifest_path = os.path.join(aot.aot_dir(), "COMPILE_LOG.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    fresh: list = []
    for bucket, rep in combos:
        print(f"batch bucket={bucket} kes_msg={len(rep.signed_bytes)}B",
              flush=True)
        rel_sds = staged_sds(params, lview, bucket, rep, shard)
        # batch-compatible chains stage 22 columns (announced u, v in
        # place of the 16-byte challenge) and dispatch the vrf_bc stage
        bc = len(rel_sds) == 22
        relayout_name = "relayout_bc" if bc else "relayout"
        relayout_fn = (K.staged_to_limb_first_bc if bc
                       else K.staged_to_limb_first)
        limb = jax.eval_shape(relayout_fn, *rel_sds)
        limb = [jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shard)
                for s in limb]
        ed_in = [limb[0], limb[2], limb[3], limb[4]]
        kes_in = [limb[5], limb[6], limb[8], limb[9], limb[10], limb[11],
                  limb[12]]
        nv = 6 if bc else 5  # vrf column count
        vrf_in = limb[13:13 + nv]
        kes_fn = functools.partial(K.kes_points, depth=KES_DEPTH)
        ed_out = jax.eval_shape(K.ed_points, *ed_in)
        kes_out = jax.eval_shape(kes_fn, *kes_in)
        vrf_name = "vrf_bc" if bc else "vrf"
        vrf_fn = K.vrf_points_bc if bc else K.vrf_points
        vrf_out = jax.eval_shape(vrf_fn, *vrf_in)
        _shard = lambda s: jax.ShapeDtypeStruct(  # noqa: E731
            s.shape, s.dtype, sharding=shard)
        # the finish stage's challenge column: derived on device for bc
        # (vrf stage output), staged for draft-03
        c_sds = _shard(vrf_out[1]) if bc else limb[15]
        vrf_pts = _shard(vrf_out[2] if bc else vrf_out[1])
        fin_in = [
            _shard(ed_out[0]), _shard(ed_out[1]), limb[1],
            _shard(kes_out[0]), _shard(kes_out[1]), limb[7],
            _shard(vrf_out[0]), vrf_pts, c_sds,
            limb[13 + nv], limb[14 + nv], limb[15 + nv],
        ]
        # vrf/finish first: the stages never yet timed on hardware
        # (VERDICT r4 item 1c) are the ones a short tunnel window must
        # not be left without
        fresh.append(compile_stage(vrf_name, vrf_fn, vrf_in, bucket, manifest))
        fresh.append(compile_stage("finish", K.finish, fin_in, bucket, manifest))
        fresh.append(compile_stage("ed", K.ed_points, ed_in, bucket, manifest))
        fresh.append(compile_stage("kes", kes_fn, kes_in, bucket, manifest))
        # packed dispatch stages (the production default): unpack
        # replaces relayout on the packed wire format; reduce packs the
        # verdict bits and runs the device nonce scan. The crypto stages
        # above are SHARED between the packed and staged paths.
        pk = packed_sds(params, lview, bucket, rep, shard)
        if pk is not None:
            layout, unpack_in, red_in = pk
            fresh.append(compile_stage(K.packed_unpack_name(layout),
                                       K._mk_packed_unpack(layout),
                                       unpack_in, bucket, manifest))
            fresh.append(compile_stage("reduce", K._mk_reduce(True),
                                       red_in, bucket, manifest))
            # UNIFIED aggregated window programs (round 15): the
            # one-RLC monolith ("all", the production default) and the
            # OCT_RLC_ALL=0 kill-switch ("vrf"), compiled under the
            # EXACT store rows protocol/batch._warm_timed loads —
            # name = _store_name(label), b = padded lanes,
            # kes_depth = tile = 0, sig over the runtime call args
            # (unpack columns + the verdict_reduce scan tail)
            if layout.vrf_proof_len == 128:
                agg_in = unpack_in + red_in[2:]
                for mode in ("all", "vrf"):
                    label = (f"{pbatch._AGG_STAGE_FAMILY[mode]}:"
                             f"{layout.body_len}b:scan")
                    fresh.append(compile_stage(
                        pbatch._store_name(label),
                        pbatch._packed_agg_fn(layout, True, mode),
                        agg_in, bucket, manifest,
                        kes_depth=0, tile=0, wall_label=label,
                    ))
        # generic-fallback relayout (mixed-layout windows)
        fresh.append(compile_stage(relayout_name, relayout_fn, rel_sds, bucket,
                      manifest))
        # tmp -> fsync -> rename: the compile log lives inside the AOT
        # store dir, so it rides the store's durability protocol
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, manifest_path)
    # forge pipeline programs (PR 18): the election sweep at its
    # production bucket and the OCert batch signer at its padding
    # quantum, compiled under the EXACT store rows protocol/forge's
    # _jit_of -> _warm_timed loads (kes_depth = tile = 0, b = the
    # dispatch lane count, sig over the runtime call columns). The
    # signable length is derived from a zero proto-OCert so the row's
    # KES hash-block count tracks the real message, not a guess.
    from ouroboros_consensus_tpu.ops import ed25519_batch  # noqa: E402
    from ouroboros_consensus_tpu.protocol import forge as pforge  # noqa: E402
    from ouroboros_consensus_tpu.protocol.views import OCert  # noqa: E402

    fb = pforge.FORGE_BUCKET
    u8 = lambda *s: jax.ShapeDtypeStruct(s, np.uint8, sharding=shard)  # noqa: E731
    sweep_in = [
        u8(fb, 32), u8(fb, 32), u8(fb, 32),
        jax.ShapeDtypeStruct((fb,), np.int32, sharding=shard),
        u8(32), u8(fb, 32), u8(fb, 32),
    ]
    fresh.append(compile_stage("forge_sweep", pforge._SWEEP_FN, sweep_in,
                               fb, manifest, kes_depth=0, tile=0))
    # neutral-nonce variant (epoch 0 of a fresh chain): same family,
    # statically nonce-free — its own store row, no [32] nonce arg
    sweep_n_in = sweep_in[:4] + sweep_in[5:]
    fresh.append(compile_stage("forge_sweep-neutral",
                               pforge._make_sweep_neutral(pforge._SWEEP_FN),
                               sweep_n_in, fb, manifest, kes_depth=0,
                               tile=0))
    sb = pforge._SIGN_BUCKET
    msg = OCert(b"\0" * 32, 0, 0, b"").signable()
    sign_cols = ed25519_batch.stage_sign_np([b"\0" * 32] * sb, [msg] * sb)
    sign_in = [jax.ShapeDtypeStruct(np.asarray(c).shape,
                                    np.asarray(c).dtype, sharding=shard)
               for c in sign_cols]
    fresh.append(compile_stage("forge_sign", pforge._SIGN_FN, sign_in,
                               sb, manifest, kes_depth=0, tile=0))
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest_path)
    # clear a persisted per-build rejection ONLY when this run wrote
    # EVERY entry itself: a cached early-return may be reusing exactly
    # the stale executables the REJECTED marker records (fresh saves
    # post-date the marker anyway — ops/pk/aot.load trusts those — but
    # an all-fresh store deserves a clean slate)
    if fresh and all(fresh):
        aot.clear_rejection()
    print(f"done in {time.time()-t0:.0f}s; store manifest: "
          f"{aot.manifest_path()}; compile log: {manifest_path}",
          flush=True)


if __name__ == "__main__":
    if "--check" in sys.argv[1:]:
        sys.exit(check())
    main()
