"""Profiled serving plane: aggregate headers/s, continuous batching
vs one-window-per-peer.

Drives the SAME seeded multi-peer traffic (testing/traffic.py) through
two serving disciplines:

  * `batched` — node/serve.ValidationService: continuous batching of
    candidate suffixes from all tenants into shared packed windows
    (the PR-20 serving plane);
  * `per-peer` — the naive port: every peer's every suffix dispatched
    as its OWN device window (`validate_batch` per suffix), padded to
    its own tiny bucket — the one-window-per-peer baseline the
    continuous batcher exists to beat.

Convention is the STUBBED-CRYPTO DEVICE TWIN (testing/stubs
`install_stub_crypto`, same as profile_replay/profile_forge): both
disciplines validate byte-identical traffic through the same stubbed
packed programs, so what the A/B isolates is the WINDOWING — per-peer
dispatch walls and minimum-bucket padding vs shared full windows. Both
modes pay an untimed warmup pass first (compiles + jit caches); rates
are steady-state.

The run also mounts the live SLO endpoint (obs/server.py `/slo`) on an
ephemeral port and banks the scraped document — p50/p99 verdict
latency, aggregate headers/s, queue depths, degraded flag — alongside
the rate table in one run-ledger record (`kind=profile_serve`); the
"Serving plane" section of scripts/perf_report.py renders the
trajectory across runs.

Usage: python scripts/profile_serve.py [tenants] [--rounds=N]
         [--suffix-len=N] [--max-window=N] [--seed=N] [--check=4.0]
       (default 64 tenants, 4 rounds, 8-header suffixes, 256-lane
        windows; --check=X exits 1 unless batched >= X x per-peer)
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
TENANTS = int(ARGS[0]) if ARGS else 64


def _opt(name: str, default, cast=int):
    return next((cast(a.split("=", 1)[1]) for a in sys.argv[1:]
                 if a.startswith(f"--{name}=")), default)


ROUNDS = _opt("rounds", 4)
SUFFIX_LEN = _opt("suffix-len", 8)
MAX_WINDOW = _opt("max-window", 256)
SEED = _opt("seed", 0)
CHECK = _opt("check", None, float)


class _Patch:
    """install_stub_crypto's monkeypatch surface (setattr only) without
    pytest — the patches live for the process, which is the point."""

    def setattr(self, obj, name, value):
        setattr(obj, name, value)


def _mk_traffic():
    from ouroboros_consensus_tpu.testing import traffic

    # the tier-1 mix at profile scale: mixed draft-03/bc tenants, fork
    # storms, equivocating pools, both injected failure classes
    return traffic.make_traffic(
        n_tenants=TENANTS, rounds=ROUNDS, suffix_len=SUFFIX_LEN,
        seed=SEED, bc_every=4, fork_storm=max(2, TENANTS // 8),
        equivocators=max(1, TENANTS // 16), bad_lane_every=7,
        unknown_pool_every=11,
    )


def run_batched(timed: bool) -> dict:
    from ouroboros_consensus_tpu.node import serve
    from ouroboros_consensus_tpu.obs import server as obs_server
    from ouroboros_consensus_tpu.obs.registry import MetricsRegistry

    tr = _mk_traffic()
    reg = MetricsRegistry()
    svc = serve.ValidationService(tr.params, tr.lview, tr.eta0,
                                  registry=reg, max_window=MAX_WINDOW)
    srv = obs_server.MetricsServer(registry=reg,
                                   slo_doc=svc.slo_snapshot) if timed else None
    t0 = time.monotonic()
    for sfx in tr.suffixes():
        svc.submit(sfx.tenant_id, sfx.hvs)
    svc.run_until_drained()
    wall = time.monotonic() - t0
    headers = sum(t.headers_done for t in svc.tenants.values())
    suffixes = sum(t.done for t in svc.tenants.values())
    slo = None
    if srv is not None:
        slo = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/slo"))
        srv.close()
    return {
        "mode": "batched", "headers": headers, "suffixes": suffixes,
        "windows": svc.windows, "wall_s": round(wall, 3),
        "headers_per_s": round(headers / wall, 1),
        "slo": slo,
        "verdicts": {s.tenant_id: [v.row() for v in
                                   svc.verdicts(s.tenant_id)]
                     for s in tr.tenants},
    }


def run_per_peer() -> dict:
    """The naive baseline: one device window per peer per suffix —
    same traffic, same packed path, no sharing. First-failure fold per
    suffix against the peer's own state, exactly like the service."""
    from ouroboros_consensus_tpu.node import serve
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos

    tr = _mk_traffic()
    states = {s.tenant_id: tr.genesis_state() for s in tr.tenants}
    rows: dict[str, list] = {s.tenant_id: [] for s in tr.tenants}
    headers = 0
    windows = 0
    t0 = time.monotonic()
    for sfx in tr.suffixes():
        st = states[sfx.tenant_id]
        ticked = praos.tick(tr.params, tr.lview, sfx.hvs[0].slot, st)
        res = pbatch.validate_batch(tr.params, ticked, list(sfx.hvs))
        states[sfx.tenant_id] = res.state
        headers += res.n_valid
        windows += 1
        rows[sfx.tenant_id].append(
            [sfx.seq, res.n_valid, serve._canon_error(res.error)]
        )
    wall = time.monotonic() - t0
    return {
        "mode": "per-peer", "headers": headers,
        "suffixes": sum(len(r) for r in rows.values()),
        "windows": windows, "wall_s": round(wall, 3),
        "headers_per_s": round(headers / wall, 1),
        "verdicts": rows,
    }


def main() -> int:
    from ouroboros_consensus_tpu.testing import stubs

    stubs.install_stub_crypto(_Patch())
    print(f"profile_serve: {TENANTS} tenants x {ROUNDS} rounds x "
          f"{SUFFIX_LEN}-header suffixes, {MAX_WINDOW}-lane windows, "
          "stub crypto", flush=True)

    # untimed warmup pass per discipline: compiles + jit caches for
    # every bucket shape the timed pass will dispatch
    run_batched(timed=False)
    run_per_peer()

    batched = run_batched(timed=True)
    per_peer = run_per_peer()

    # the A/B is only meaningful if both disciplines produced the SAME
    # verdicts on the same seeded traffic — assert it, loudly
    if batched["verdicts"] != per_peer["verdicts"]:
        print("FATAL: batched and per-peer verdicts diverge", flush=True)
        return 2
    speedup = (batched["headers_per_s"] / per_peer["headers_per_s"]
               if per_peer["headers_per_s"] else 0.0)
    for row in (per_peer, batched):
        print(f"  {row['mode']:9s} {row['headers']:>7d} headers "
              f"{row['windows']:>5d} windows in {row['wall_s']:8.2f}s "
              f"-> {row['headers_per_s']:>10.1f} headers/s", flush=True)
    print(f"  batched_vs_per_peer: {speedup:.1f}x", flush=True)
    slo = batched.get("slo") or {}
    print(f"  slo: p50={slo.get('verdict_latency_p50_s')} "
          f"p99={slo.get('verdict_latency_p99_s')} "
          f"degraded={slo.get('degraded')}", flush=True)

    from ouroboros_consensus_tpu.obs import ledger

    for row in (batched, per_peer):
        row.pop("verdicts")  # byte-identity asserted; too big to bank
    ledger.record_replay(
        "profile_serve",
        config={"tenants": TENANTS, "rounds": ROUNDS,
                "suffix_len": SUFFIX_LEN, "max_window": MAX_WINDOW,
                "seed": SEED, "crypto": "stub"},
        result={"modes": [per_peer, batched],
                "speedup_batched_vs_per_peer": round(speedup, 1),
                "slo": slo},
    )
    if CHECK is not None and speedup < CHECK:
        print(f"CHECK FAILED: {speedup:.1f}x < {CHECK:g}x", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
