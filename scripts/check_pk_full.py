"""End-to-end check + timing of the Pallas verify path on real headers.

Forges a valid Praos chain segment (host sign-side), corrupts a few
lanes in distinct ways, and compares the pk kernel verdicts against the
native C++ verifier lane by lane. Then times the full pipeline at a
production batch size.

Usage: python scripts/check_pk_full.py [B] [timing_B]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from fractions import Fraction

import numpy as np
import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
TB = int(sys.argv[2]) if len(sys.argv) > 2 else 4096

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=100_000,
    kes_depth=3,
)
ETA0 = b"\x07" * 32

pools = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(3)]
lview = fixtures.make_ledger_view(pools)

print(f"forging {B} headers...", flush=True)
hvs = []
slot = 1
prev = None
while len(hvs) < B:
    pool = fixtures.find_leader(PARAMS, pools, lview, slot, ETA0)
    if pool is not None:
        hv = fixtures.forge_header_view(
            PARAMS, pool, slot=slot, epoch_nonce=ETA0, prev_hash=prev,
            body_bytes=b"body-%d" % len(hvs),
        )
        hvs.append(hv)
        prev = (b"%032d" % len(hvs))[:32]
    slot += 1

# corrupt lanes: ocert sig, kes sig, vrf proof, vrf beta
import dataclasses


def corrupt(hv, **kw):
    return dataclasses.replace(hv, **kw)


bad = {}
hvs[10] = corrupt(hvs[10], ocert=dataclasses.replace(
    hvs[10].ocert, sigma=hvs[10].ocert.sigma[:-1] + bytes([hvs[10].ocert.sigma[-1] ^ 1])))
bad[10] = "ocert"
hvs[20] = corrupt(hvs[20], kes_sig=hvs[20].kes_sig[:-1] + bytes([hvs[20].kes_sig[-1] ^ 1]))
bad[20] = "kes"
hvs[30] = corrupt(hvs[30], vrf_proof=hvs[30].vrf_proof[:1] + bytes([hvs[30].vrf_proof[1] ^ 1]) + hvs[30].vrf_proof[2:])
bad[30] = "vrf"
hvs[40] = corrupt(hvs[40], vrf_output=hvs[40].vrf_output[:1] + bytes([hvs[40].vrf_output[1] ^ 1]) + hvs[40].vrf_output[2:])
bad[40] = "beta"

pre = pbatch.host_prechecks(PARAMS, lview, hvs)
staged = pbatch.stage(PARAMS, lview, ETA0, hvs, pre.kes_evolution)

t0 = time.time()
out = pbatch._pk_dispatch(staged)
v = pbatch._pk_materialize(out, B)
print(f"pk pipeline (compile+run) {time.time()-t0:.1f}s", flush=True)

vn = pbatch.run_batch_native(PARAMS, lview, ETA0, hvs, pre)

mism = []
for i in range(B):
    stop = min(bad.keys(), default=B)
    # native short-circuits at first failure; compare only up to there
    if i > min(bad, default=B):
        break
    for f_ in ("ok_ocert_sig", "ok_kes_sig", "ok_vrf"):
        a = bool(getattr(v, f_)[i])
        b_ = bool(getattr(vn, f_)[i])
        if a != b_:
            mism.append((i, f_, a, b_))
if mism:
    print("MISMATCH vs native:", mism[:10])
else:
    print("verdicts match native up to first failure")

# full-batch verdict sanity: exactly the corrupted lanes fail
fails = {
    i: [f_ for f_ in ("ok_ocert_sig", "ok_kes_sig", "ok_vrf")
        if not getattr(v, f_)[i]]
    for i in range(B)
    if not (v.ok_ocert_sig[i] and v.ok_kes_sig[i] and v.ok_vrf[i])
}
print("failing lanes:", {k: tuple(fv) for k, fv in sorted(fails.items())})
expect = {10: ("ok_ocert_sig",), 20: ("ok_kes_sig",), 30: ("ok_vrf",), 40: ("ok_vrf",)}
ok = set(fails) == set(expect) and all(tuple(fails[k]) == expect[k] for k in expect)
print("corruption pattern:", "OK" if ok else "WRONG")

# eta/leader_value spot check vs native
eta_ok = (v.eta[:9] == vn.eta[:9]).all()
lv_ok = (v.leader_value[:9] == vn.leader_value[:9]).all()
print("eta match:", bool(eta_ok), "leader_value match:", bool(lv_ok))

# ---- timing at TB ---------------------------------------------------------
if TB:
    reps = (TB + B - 1) // B
    big = pbatch.PraosBatch(
        ed=type(staged.ed)(*(np.concatenate([np.asarray(c)] * reps)[:TB] for c in staged.ed)),
        kes=type(staged.kes)(*(np.concatenate([np.asarray(c)] * reps)[:TB] for c in staged.kes)),
        vrf=type(staged.vrf)(*(np.concatenate([np.asarray(c)] * reps)[:TB] for c in staged.vrf)),
        beta=np.concatenate([staged.beta] * reps)[:TB],
        thr_lo=np.concatenate([staged.thr_lo] * reps)[:TB],
        thr_hi=np.concatenate([staged.thr_hi] * reps)[:TB],
    )
    t0 = time.time()
    out = pbatch._pk_dispatch(big)
    v = pbatch._pk_materialize(out, TB)
    print(f"B={TB} first (compile+run) {time.time()-t0:.1f}s", flush=True)
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        out = pbatch._pk_dispatch(big)
        v = pbatch._pk_materialize(out, TB)
        best = min(best, time.time() - t0)
    print(f"B={TB} hot: {best*1e3:.1f}ms -> {TB/best:.0f} headers/s (kernel only)")
