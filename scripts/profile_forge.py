"""Profiled chain synthesis: headers-forged/s, engine vs engine.

Runs the SAME `db_synthesizer.synthesize` three times — the per-slot
reference loop (`OCT_FORGE_DEVICE=0`, the pre-PR-18 path), the batched
host engine, and the packed device sweep (`OCT_FORGE_DEVICE=1`) — over
a fresh DB each, and prints the forging-rate table the PR-18
acceptance gate banks (PERF.md "Forge trajectory").

Default convention is the STUBBED-CRYPTO DEVICE TWIN (testing/stubs
`install_stub_forge`, the same convention as `profile_replay
--overlap-ab`): every engine forges byte-identical chains through the
counter-mode expansion family, the device sweep compiles in seconds on
XLA:CPU, and what the A/B isolates is the PIPELINE — per-slot Python +
Fraction leader checks vs whole-window packed dispatch. The per-slot
loop's dominant costs (the Python slot loop and the exact Fraction
compare per (slot, pool)) are crypto-independent, so the stub ratio
UNDERSTATES the native one: native proves add ~0.49 ms x pools to
every loop slot but only amortized bucket dispatches to the sweep.
`--native` runs the real crypto instead (host libsodium-family proves;
the device engine then pays the real XLA compile — minutes on CPU,
the convention a TPU session banks).

Each engine pays a small warmup window first (compiles + jit caches),
then the timed window; rates are steady-state slots/s and blocks/s.
The loop engine is timed over `--loop-slots` (default 4096) — at
~1 ms/slot a 100k-slot loop window would dominate the wall for no
extra information; rates are per-second and directly comparable.

One run-ledger record (`kind=profile_forge`) banks the table; the
"Forge trajectory" section of scripts/perf_report.py renders the
trajectory across runs.

Usage: python scripts/profile_forge.py [n_slots] [--native]
         [--pools=N] [--loop-slots=N] [--skip-device]
       (default n_slots 100000, 4 pools)
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
NATIVE = "--native" in sys.argv[1:]
SKIP_DEVICE = "--skip-device" in sys.argv[1:]
N = int(ARGS[0]) if ARGS else 100_000
POOLS = next((int(a.split("=", 1)[1]) for a in sys.argv[1:]
              if a.startswith("--pools=")), 4)
LOOP_SLOTS = next((int(a.split("=", 1)[1]) for a in sys.argv[1:]
                   if a.startswith("--loop-slots=")), 4096)
WARMUP_SLOTS = 512


class _Patch:
    """install_stub_forge's monkeypatch surface (setattr only) without
    pytest — the patches live for the process, which is the point."""

    def setattr(self, obj, name, value):
        setattr(obj, name, value)


def _engine_env(engine: str):
    if engine == "loop":
        os.environ["OCT_FORGE_DEVICE"] = "0"
    elif engine == "device":
        os.environ["OCT_FORGE_DEVICE"] = "1"
    else:
        os.environ.pop("OCT_FORGE_DEVICE", None)


def run_engine(engine: str, n_slots: int, params, pools, lview,
               tmp: str) -> dict:
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    _engine_env(engine)
    try:
        # warmup window: first-execute compiles / jit caches / staged
        # pool columns — steady state is what the table compares
        synth.synthesize(
            os.path.join(tmp, f"warm-{engine}"), params, pools, lview,
            synth.ForgeLimit(slots=WARMUP_SLOTS),
        )
        db = os.path.join(tmp, f"db-{engine}")
        t0 = time.monotonic()
        res = synth.synthesize(
            db, params, pools, lview, synth.ForgeLimit(slots=n_slots),
        )
        wall = time.monotonic() - t0
    finally:
        os.environ.pop("OCT_FORGE_DEVICE", None)
    return {
        "engine": engine, "slots": res.n_slots, "blocks": res.n_blocks,
        "wall_s": round(wall, 3),
        "slots_per_s": round(res.n_slots / wall, 1),
        "blocks_per_s": round(res.n_blocks / wall, 1),
    }


def main() -> int:
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    crypto = "native" if NATIVE else "stub"
    if not NATIVE:
        from ouroboros_consensus_tpu.testing import stubs

        stubs.install_stub_forge(_Patch(), bucket=256)
    params = synth.default_params()
    pools, lview = synth.make_credentials(POOLS)
    print(f"profile_forge: {N} slots, {POOLS} pools, {crypto} crypto "
          f"(loop window {LOOP_SLOTS} slots)", flush=True)

    rows = []
    engines = ["loop", "host"] + ([] if SKIP_DEVICE else ["device"])
    with tempfile.TemporaryDirectory() as tmp:
        for engine in engines:
            n = LOOP_SLOTS if engine == "loop" else N
            t0 = time.monotonic()
            row = run_engine(engine, n, params, pools, lview, tmp)
            print(f"  {engine:6s} {row['slots']:>7d} slots "
                  f"{row['blocks']:>6d} blocks in {row['wall_s']:8.2f}s "
                  f"-> {row['slots_per_s']:>9.1f} slots/s "
                  f"{row['blocks_per_s']:>8.1f} blocks/s "
                  f"(+{time.monotonic() - t0 - row['wall_s']:.1f}s warmup)",
                  flush=True)
            rows.append(row)

    by = {r["engine"]: r for r in rows}
    speedups = {}
    loop_rate = by["loop"]["slots_per_s"]
    for eng in ("host", "device"):
        if eng in by and loop_rate:
            speedups[f"{eng}_vs_loop"] = round(
                by[eng]["slots_per_s"] / loop_rate, 1
            )
    for k, v in sorted(speedups.items()):
        print(f"  {k}: {v}x")

    from ouroboros_consensus_tpu.obs import ledger

    ledger.record_replay(
        "profile_forge",
        config={"n": N, "pools": POOLS, "crypto": crypto,
                "loop_slots": LOOP_SLOTS},
        result={"engines": rows, "speedups": speedups},
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
