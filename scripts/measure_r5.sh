#!/bin/bash
# Round-5 measurement pipeline. Fresh workspace + live tunnel: rebuild
# the deviceless artifacts first (native .so, bench chain, AOT
# executables), then spend the tunnel in strict value-per-minute order:
# the never-measured vrf/finish stage timings, the 100k end-to-end
# number, the 1M north-star number, the config suite, and on-device
# compile attribution LAST (historically the tunnel-wedging step).
# Everything is serialized: the box has 1 core and host-side pipeline
# rates are part of the measurement.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=/tmp/ouroboros-jax-cache
LOGDIR=scripts/tpu_session_logs
mkdir -p "$LOGDIR"

stage() {  # stage <name> <timeout-s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "== $name (budget ${tmo}s) $(date -u +%H:%M:%S)"
  timeout "$tmo" env "${STAGE_ENV[@]:-IGNORE=1}" "$@" > "$LOGDIR/$name.log" 2>&1
  echo "   rc=$? $(tail -1 "$LOGDIR/$name.log" | cut -c1-140)"
}
STAGE_ENV=(IGNORE=1)

stage native_build 600 python -c "from ouroboros_consensus_tpu import native_loader as nl; print('scan', nl.load() is not None, 'crypto', nl.load_crypto() is not None)"

# Deviceless: synthesizes the 100k chain (~2.5 min) and compiles the
# five v5e stage executables (~2 min total per the r5 manifest).
stage aot_precompile 3600 python -u scripts/aot_precompile.py

stage probe 120 python -c "import jax, jax.numpy as jnp; assert jax.devices()[0].platform=='tpu'; print((jnp.ones((8,8))+1).sum())"

# 1. vrf/finish hot timings within minutes of the window opening.
stage aot_smoke 1800 python -u scripts/aot_smoke.py

# 2. end-to-end device number at 100k (first since round 1).
stage bench_100k 1500 python -u bench.py

# 3. the 1M north-star chain (~15 min native forging, no tunnel use).
STAGE_ENV=(BENCH_HEADERS=1000000)
stage synth_1m 2400 python -u -c "import bench; bench.build_or_load_chain()"

# 4. cover any batch signatures the 1M replay adds (cached ones skip).
stage aot_precompile_1m 3600 python -u scripts/aot_precompile.py

# 5. the north-star number: 1M-header replay, wide budget.
STAGE_ENV=(BENCH_TOTAL_BUDGET=2400 BENCH_DEVICE_BUDGET=2000)
stage bench_1m 2500 python -u bench.py
STAGE_ENV=(IGNORE=1)

# 6. BASELINE config suite device-side numbers.
stage bench_suite 3600 python -u scripts/bench_suite.py --scale 0.5

# 7. on-device per-kernel compile attribution — deliberately last.
stage time_kernels 3500 python -u scripts/time_pk_kernels.py 8192

echo "measure_r5 done $(date -u +%H:%M:%S); logs in $LOGDIR"
