#!/bin/bash
# Round-4 TPU watchdog: probe the axon tunnel on a loop; the moment it
# answers, run the one-shot measurement session (scripts/tpu_session.sh)
# and stop. Rationale (VERDICT r3 item 1): two rounds lost the device
# number because the tunnel was only probed when a human/agent happened
# to try — this keeps trying all day. Single-flight: only ONE process
# ever touches the tunnel at a time (round-3 postmortem: concurrent
# compiles + a SIGTERM mid-compile wedged the relay for hours).
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=/tmp/ouroboros-jax-cache
LOG=scripts/tpu_watchdog.log
DONE=scripts/tpu_session_logs/SESSION_DONE
DEADLINE=$(( $(date +%s) + ${WATCHDOG_HOURS:-11} * 3600 ))

echo "watchdog start $(date -u +%F.%H:%M:%S)" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -e "$DONE" ]; do
  t0=$(date +%s)
  if timeout 420 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform in ('tpu', 'axon'), d.platform
print('probe ok:', d, float((jnp.ones((8, 8)) + 1).sum()))
" >> "$LOG" 2>&1; then
    echo "tunnel UP $(date -u +%H:%M:%S) — running session" >> "$LOG"
    bash scripts/tpu_session.sh >> "$LOG" 2>&1
    touch "$DONE"
    echo "session done $(date -u +%H:%M:%S)" >> "$LOG"
    break
  else
    rc=$?
    echo "probe failed (rc=$rc, $(( $(date +%s) - t0 ))s) $(date -u +%H:%M:%S)" >> "$LOG"
  fi
  sleep 240
done
echo "watchdog exit $(date -u +%F.%H:%M:%S)" >> "$LOG"
