#!/bin/bash
# Round-4 TPU watchdog: probe the axon tunnel on a loop; the moment it
# answers, run the one-shot measurement session (scripts/tpu_session.sh)
# and stop. Rationale (VERDICT r3 item 1): two rounds lost the device
# number because the tunnel was only probed when a human/agent happened
# to try — this keeps trying all day. Single-flight: only ONE process
# ever touches the tunnel at a time (round-3 postmortem: concurrent
# compiles + a SIGTERM mid-compile wedged the relay for hours).
#
# Round 11: while the session runs, the watchdog TAILS the live
# heartbeat (obs/live.py, $OCT_HEARTBEAT) and logs the classification —
# compiling / staging / running / stalled / dead — every ~30 s, so the
# log tells a wedged session from a compiling one in real time instead
# of only after the wall.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=/tmp/ouroboros-jax-cache
LOG=scripts/tpu_watchdog.log
DONE=scripts/tpu_session_logs/SESSION_DONE
DEADLINE=$(( $(date +%s) + ${WATCHDOG_HOURS:-11} * 3600 ))

# the live plane levers for the session's bench children (inherit any
# operator override)
export OCT_HEARTBEAT="${OCT_HEARTBEAT:-$PWD/.bench_cache/heartbeat.json}"
export OCT_STALL_BUDGET_S="${OCT_STALL_BUDGET_S:-240}"

live_status() {
  # one line of live classification off the heartbeat file; silent when
  # the file does not exist yet (session still synthesizing/probing).
  # JAX_PLATFORMS=cpu: reading a JSON file must never touch the tunnel.
  [ -e "$OCT_HEARTBEAT" ] || return 0
  JAX_PLATFORMS=cpu python - "$OCT_HEARTBEAT" <<'PYEOF' 2>/dev/null
import sys
from ouroboros_consensus_tpu.obs import live
doc = live.read_heartbeat(sys.argv[1])
state = live.classify(doc)
if doc:
    print(f"live: {state} phase={doc.get('phase')} "
          f"headers={doc.get('headers')} "
          f"rate={doc.get('headers_per_s')} age={doc.get('age_s')}s "
          f"stalls={doc.get('stalls')}")
else:
    print(f"live: {state}")
PYEOF
}

echo "watchdog start $(date -u +%F.%H:%M:%S)" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -e "$DONE" ]; do
  t0=$(date +%s)
  if timeout 420 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform in ('tpu', 'axon'), d.platform
print('probe ok:', d, float((jnp.ones((8, 8)) + 1).sum()))
" >> "$LOG" 2>&1; then
    echo "tunnel UP $(date -u +%H:%M:%S) — running session" >> "$LOG"
    # session in the background so the watchdog can tail the heartbeat;
    # still single-flight — exactly one session, and the loop below
    # blocks until it exits
    bash scripts/tpu_session.sh >> "$LOG" 2>&1 &
    SESSION_PID=$!
    while kill -0 "$SESSION_PID" 2>/dev/null; do
      sleep 30
      status=$(live_status)
      [ -n "$status" ] && echo "$(date -u +%H:%M:%S) $status" >> "$LOG"
    done
    wait "$SESSION_PID"
    touch "$DONE"
    echo "session done $(date -u +%H:%M:%S)" >> "$LOG"
    break
  else
    rc=$?
    echo "probe failed (rc=$rc, $(( $(date +%s) - t0 ))s) $(date -u +%H:%M:%S)" >> "$LOG"
  fi
  sleep 240
done
echo "watchdog exit $(date -u +%F.%H:%M:%S)" >> "$LOG"
