"""Time the three sub-verifiers + the fused verify_praos on random inputs.

Validity doesn't affect timing (batch-uniform mask-lane control flow), so
random garbage with the right shapes measures the real kernel cost.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import ecvrf_batch, ed25519_batch, kes_batch
from ouroboros_consensus_tpu.protocol import batch as pbatch

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
NB = 4  # sha512 blocks per message
DEPTH = 7
rng = np.random.default_rng(0)


def b8(*shape):
    return jnp.asarray(rng.integers(0, 256, size=shape, dtype=np.uint8))


def _sync(out):
    # axon (tunneled TPU) can return before execution completes even
    # after block_until_ready; a host transfer is the only reliable sync
    return jax.tree.map(np.asarray, out)


def timeit(name, fn, *args, n=5):
    fn_j = jax.jit(fn)
    t0 = time.perf_counter()
    _sync(fn_j(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    _sync(out)
    dt = (time.perf_counter() - t0) / n
    print(
        f"{name:22s} {dt*1e3:9.2f} ms  ({dt*1e9/B:9.1f} ns/lane)  "
        f"compile {compile_s:.1f}s",
        flush=True,
    )
    return dt


ed_args = (
    b8(B, 32), b8(B, 32), b8(B, 32),
    jnp.asarray(rng.integers(0, 2**32, size=(B, NB, 16, 2), dtype=np.uint32)),
    jnp.full((B,), NB, jnp.int32),
)
kes_args = (
    b8(B, 32), jnp.asarray(rng.integers(0, 128, size=(B,), dtype=np.int32)),
    b8(B, 32), b8(B, 32), b8(B, 32), b8(B, DEPTH, 32),
    jnp.asarray(rng.integers(0, 2**32, size=(B, NB, 16, 2), dtype=np.uint32)),
    jnp.full((B,), NB, jnp.int32),
)
vrf_args = (b8(B, 32), b8(B, 32), b8(B, 16), b8(B, 32), b8(B, 32))

print(f"batch = {B}, device = {jax.devices()[0]}")
timeit("ed25519.verify", ed25519_batch.verify, *ed_args)
timeit("kes.verify", kes_batch.verify, *kes_args)
timeit("ecvrf.verify", ecvrf_batch.verify, *vrf_args)

full_args = (
    *ed_args, *kes_args, *vrf_args,
    b8(B, 64), b8(B, 32), b8(B, 32),
)
timeit("verify_praos (fused)", pbatch.verify_praos, *full_args)
