"""Point-op accounting: per-lane ladder path vs aggregated RLC/MSM path.

The ratchet version of this accounting now lives in the analysis
package: every `analysis/graphs.py` trace_graph() call captures the
trace-time op counter (ops/pk/curve.py) for free, and
`graphs.check_point_ops` fails any graph over its budgets.json
"point_ops" ceiling — scripts/lint.py and
`python -m ouroboros_consensus_tpu.analysis pointops` drive it in CI.

This script keeps the PERF.md evidence mode: it traces the per-lane
composed core against the aggregated window program at
production-grade constants (NB=3, KES depth 7 — the registry uses
reduced tiles) and prints the reduction factor measured against the
>=5x bar of round 7.

Usage:
    JAX_PLATFORMS=cpu python scripts/count_point_ops.py [T]
    JAX_PLATFORMS=cpu python scripts/count_point_ops.py --all-stages [T]
        # round-15 evidence mode: per-stage point-op table for the
        # WHOLE per-window pipeline (unified one-RLC program vs the
        # OCT_RLC_ALL=0 kill-switch program vs the per-lane ladders)
        # plus the all-stage totals the point_ops.all_stage_total
        # budget pins
    JAX_PLATFORMS=cpu python scripts/count_point_ops.py --check
        # run the budgets.json point_ops ratchet — including the
        # composite all_stage_total pin — and exit nonzero on any
        # violation (same check scripts/lint.py applies)
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import functools  # noqa: E402

import jax  # noqa: E402
from jax import numpy as jnp  # noqa: E402

from ouroboros_consensus_tpu.ops.pk import aggregate as agg  # noqa: E402
from ouroboros_consensus_tpu.ops.pk import curve as pc  # noqa: E402
from ouroboros_consensus_tpu.ops.pk import verify as pv  # noqa: E402

T = 1024
NB = 3
DEPTH = 7


def _s(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _args_bc():
    return (
        _s(32, T), _s(32, T), _s(32, T), _s(NB, 128, T), _s(1, T),
        _s(32, T), _s(1, T), _s(32, T), _s(32, T), _s(32, T),
        _s(DEPTH, 32, T), _s(NB, 128, T), _s(1, T),
        _s(32, T), _s(32, T), _s(32, T), _s(32, T), _s(32, T), _s(32, T),
        _s(64, T), _s(32, T), _s(32, T),
    )


def _args_core_bc():
    a = list(_args_bc())
    a[4] = _s(T)  # the core takes flat [T] block counts
    a[6] = _s(T)
    a[12] = _s(T)
    return tuple(a)


def count(fn, args, label):
    with pc.op_counter() as stats:
        jax.make_jaxpr(fn)(*args)
        ops, lane_ops = stats["ops"], stats["lane_ops"]
    print(f"{label:28s} point-op invocations {ops:10d}   "
          f"lane-ops {lane_ops:14d}   ({lane_ops / T:10.1f}/lane)")
    return lane_ops


def all_stages():
    """Per-stage accounting of the full per-window pipeline.

    The unified dispatch path runs: packed unpack (no point ops by
    construction — byte slicing + hashing only), ONE aggregated
    program, verdict reduce (also point-op-free). So the unified
    all-stage total IS the aggregate_window count, and the table
    makes that visible rather than assumed. The kill-switch column
    (OCT_RLC_ALL=0, aggregate_window_vrf) carries the exact per-lane
    ed/KES ladders inline, so its total shows what the one-RLC fold
    is buying at this lane count."""
    unified = count(
        functools.partial(agg.aggregate_window, kes_depth=DEPTH),
        _args_bc(), f"unified RLC (all stages, T={T})",
    )
    vrf_only = count(
        functools.partial(agg.aggregate_window_vrf, kes_depth=DEPTH),
        _args_bc(), f"kill-switch OCT_RLC_ALL=0 (T={T})",
    )
    per_lane = count(
        functools.partial(pv.verify_praos_core_bc, kes_depth=DEPTH),
        _args_core_bc(), f"per-lane ladders (T={T})",
    )
    print(f"all-stage total (unified):     {unified / T:10.2f} lane-ops/lane")
    print(f"all-stage total (kill-switch): {vrf_only / T:10.2f} lane-ops/lane")
    print(f"all-stage total (per-lane):    {per_lane / T:10.2f} lane-ops/lane")
    print(f"unified vs kill-switch: {vrf_only / unified:.2f}x; "
          f"unified vs per-lane ladders: {per_lane / unified:.2f}x")
    return 0


def main():
    if "--all-stages" in sys.argv:
        return all_stages()
    if "--check" in sys.argv:
        from ouroboros_consensus_tpu.analysis import graphs

        violations = graphs.check_point_ops()
        for v in violations:
            print(f"BUDGET: {v}")
        print(f"pointops ratchet: {len(violations)} violation(s)")
        return 1 if violations else 0

    per_lane = count(
        functools.partial(pv.verify_praos_core_bc, kes_depth=DEPTH),
        _args_core_bc(), f"per-lane ladders (T={T})",
    )
    aggregated = count(
        functools.partial(agg.aggregate_window, kes_depth=DEPTH),
        _args_bc(), f"aggregated RLC/MSM (T={T})",
    )
    print(f"point-op reduction: {per_lane / aggregated:.2f}x "
          f"({per_lane / T:.0f} -> {aggregated / T:.0f} lane-ops/lane)")
    return 0


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    if args:
        T = int(args[0])
    sys.exit(main())
