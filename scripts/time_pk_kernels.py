"""Per-kernel compile + hot timing of the pk pipeline at a fixed batch,
then the full differential check vs the native verifier. One process."""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from fractions import Fraction

import numpy as np
import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops.pk import kernels as K
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
NSRC = 128
DEPTH = 3

PARAMS = praos.PraosParams(
    slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
    active_slot_coeff=Fraction(1, 2), epoch_length=100_000, kes_depth=DEPTH,
)
ETA0 = b"\x07" * 32

pools = [fixtures.make_pool(i, kes_depth=DEPTH) for i in range(3)]
lview = fixtures.make_ledger_view(pools)

t0 = time.time()
hvs, slot, prev = [], 1, None
while len(hvs) < NSRC:
    pool = fixtures.find_leader(PARAMS, pools, lview, slot, ETA0)
    if pool is not None:
        hvs.append(fixtures.forge_header_view(
            PARAMS, pool, slot=slot, epoch_nonce=ETA0, prev_hash=prev,
            body_bytes=b"body-%d" % len(hvs)))
        prev = (b"%032d" % len(hvs))[:32]
    slot += 1
print(f"forged {NSRC} in {time.time()-t0:.1f}s", flush=True)

import dataclasses
hvs[10] = dataclasses.replace(hvs[10], ocert=dataclasses.replace(
    hvs[10].ocert, sigma=hvs[10].ocert.sigma[:-1] + bytes([hvs[10].ocert.sigma[-1] ^ 1])))
hvs[20] = dataclasses.replace(hvs[20], kes_sig=hvs[20].kes_sig[:-1] + bytes([hvs[20].kes_sig[-1] ^ 1]))
hvs[30] = dataclasses.replace(hvs[30], vrf_proof=hvs[30].vrf_proof[:1] + bytes([hvs[30].vrf_proof[1] ^ 1]) + hvs[30].vrf_proof[2:])
hvs[40] = dataclasses.replace(hvs[40], vrf_output=hvs[40].vrf_output[:1] + bytes([hvs[40].vrf_output[1] ^ 1]) + hvs[40].vrf_output[2:])

pre = pbatch.host_prechecks(PARAMS, lview, hvs)
staged = pbatch.stage(PARAMS, lview, ETA0, hvs, pre.kes_evolution)
reps = (B + NSRC - 1) // NSRC
big = pbatch.PraosBatch(
    ed=type(staged.ed)(*(np.concatenate([np.asarray(c)] * reps)[:B] for c in staged.ed)),
    kes=type(staged.kes)(*(np.concatenate([np.asarray(c)] * reps)[:B] for c in staged.kes)),
    vrf=type(staged.vrf)(*(np.concatenate([np.asarray(c)] * reps)[:B] for c in staged.vrf)),
    beta=np.concatenate([staged.beta] * reps)[:B],
    thr_lo=np.concatenate([staged.thr_lo] * reps)[:B],
    thr_hi=np.concatenate([staged.thr_hi] * reps)[:B],
)
arrays = [jnp.asarray(x) for x in pbatch.pk_arrays(big)]
(ed_pk, ed_r, ed_s, ed_hb, ed_hnb, kes_vk, kes_per, kes_r, kes_s, kes_leaf,
 kes_sib, kes_hb, kes_hnb, vrf_pk, vrf_g, vrf_c, vrf_s, vrf_al,
 beta, tlo, thi) = arrays


def timed(name, fn, *a):
    t0 = time.time()
    out = fn(*a)
    jax.tree.map(np.asarray, out)
    compile_s = time.time() - t0
    t0 = time.time()
    n = 3
    for _ in range(n):
        out = fn(*a)
    jax.tree.map(np.asarray, out)
    hot = (time.time() - t0) / n
    print(f"{name:8s} compile+run {compile_s:7.1f}s   hot {hot*1e3:8.1f}ms "
          f"({B/hot:8.0f} lanes/s)", flush=True)
    return out


ed_j = jax.jit(K.ed_points)
kes_j = jax.jit(lambda *a: K.kes_points(*a, DEPTH))
vrf_j = jax.jit(K.vrf_points)
fin_j = jax.jit(K.finish)

ed_ok, ed_pt = timed("ed", ed_j, ed_pk, ed_s, ed_hb, ed_hnb)
kes_ok, kes_pt = timed("kes", kes_j, kes_vk, kes_per, kes_s, kes_leaf, kes_sib, kes_hb, kes_hnb)
vrf_ok, vrf_pts = timed("vrf", vrf_j, vrf_pk, vrf_g, vrf_c, vrf_s, vrf_al)
fin = timed("finish", fin_j, ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r,
            vrf_ok, vrf_pts, vrf_c, beta, tlo, thi)

# whole pipeline hot (one dispatch)
full_j = jax.jit(lambda *a: K.verify_praos_tiles(*a, kes_depth=DEPTH))
t0 = time.time()
out = full_j(*arrays)
jax.tree.map(np.asarray, out)
print(f"full pipeline first: {time.time()-t0:.1f}s", flush=True)
best = 1e9
for _ in range(3):
    t0 = time.time()
    out = full_j(*arrays)
    jax.tree.map(np.asarray, out)
    best = min(best, time.time() - t0)
print(f"full pipeline hot: {best*1e3:.1f}ms -> {B/best:.0f} headers/s", flush=True)

# differential vs native on the first NSRC lanes
v = pbatch._pk_materialize(out, B)
vn = pbatch.run_batch_native(PARAMS, lview, ETA0, hvs, pre)
mism = []
for i in range(11):  # up to + including first corrupt lane
    for f_ in ("ok_ocert_sig", "ok_kes_sig", "ok_vrf"):
        if bool(getattr(v, f_)[i]) != bool(getattr(vn, f_)[i]):
            mism.append((i, f_))
fails = {i for i in range(NSRC)
         if not (v.ok_ocert_sig[i] and v.ok_kes_sig[i] and v.ok_vrf[i])}
print("mismatch vs native:", mism or "none")
print("failing lanes (want {10,20,30,40}):", sorted(fails))
print("eta match:", bool((v.eta[:9] == vn.eta[:9]).all()),
      "lv match:", bool((v.leader_value[:9] == vn.leader_value[:9]).all()))
ok10 = not v.ok_ocert_sig[10] and not v.ok_kes_sig[20] and not v.ok_vrf[30] and not v.ok_vrf[40]
print("corruption kinds:", "OK" if ok10 else "WRONG")
