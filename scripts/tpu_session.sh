#!/bin/bash
# One-shot TPU measurement session for round 3. Run when the axon tunnel
# is healthy. Stages are separate processes so one wedge loses one stage,
# not the session; everything lands in the persistent compilation cache
# (/tmp/ouroboros-jax-cache) so the driver's bench.py run compiles
# NOTHING. Logs to scripts/tpu_session_logs/.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=/tmp/ouroboros-jax-cache
LOGDIR=scripts/tpu_session_logs
mkdir -p "$LOGDIR"

stage() {  # stage <name> <timeout-s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "== $name (budget ${tmo}s) $(date -u +%H:%M:%S)"
  timeout "$tmo" "$@" > "$LOGDIR/$name.log" 2>&1
  echo "   rc=$? $(tail -1 "$LOGDIR/$name.log" | cut -c1-120)"
}

# 0. probe
stage probe 120 python -c "import jax, jax.numpy as jnp; assert jax.devices()[0].platform=='tpu'; print((jnp.ones((8,8))+1).sum())" || true

# 1. per-kernel compile attribution + hot timing at production batch
#    (tile=128). This ALSO populates the cache for every kernel.
stage time_kernels 3500 python -u scripts/time_pk_kernels.py 8192

# 2. end-to-end bench exactly as the driver runs it (cache now warm)
stage bench 1800 python -u bench.py

# 3. the BASELINE config suite (configs 2-5 device-side numbers)
stage bench_suite 3600 python -u scripts/bench_suite.py --scale 0.5

echo "session done $(date -u +%H:%M:%S); logs in $LOGDIR"
