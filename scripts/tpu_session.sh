#!/bin/bash
# One-shot TPU measurement session (round 5). Run when the axon tunnel
# is healthy. Stages are separate processes so one wedge loses one
# stage, not the session. Round-5 order (VERDICT r4 item 1): the
# deviceless-AOT executables (scripts/aot_cache, compiled by
# aot_precompile.py with NO device) are deserialized and RUN first —
# capturing the never-measured vrf/finish stage timings within minutes
# of the tunnel opening — then the end-to-end bench. On-device
# compilation (time_pk_kernels) runs LAST, as attribution, because it
# is the thing that historically wedged the tunnel.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=/tmp/ouroboros-jax-cache
LOGDIR=scripts/tpu_session_logs
mkdir -p "$LOGDIR"

stage() {  # stage <name> <timeout-s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "== $name (budget ${tmo}s) $(date -u +%H:%M:%S)"
  timeout "$tmo" "$@" > "$LOGDIR/$name.log" 2>&1
  echo "   rc=$? $(tail -1 "$LOGDIR/$name.log" | cut -c1-120)"
}

# 0. probe
stage probe 120 python -c "import jax, jax.numpy as jnp; assert jax.devices()[0].platform=='tpu'; print((jnp.ones((8,8))+1).sum())" || true

# 1. AOT smoke: deserialize the precompiled v5e stage executables and
#    time them (vrf/finish first), then the composed dispatch + a
#    verdict cross-check vs the native verifier. ~0 compile time.
stage aot_smoke 1200 python -u scripts/aot_smoke.py

# 2. end-to-end bench exactly as the driver runs it (AOT dispatch is
#    default-on; any stage whose executable fails to load falls back to
#    jit + the persistent cache)
stage bench 1800 python -u bench.py

# 3. the BASELINE config suite (configs 2-5 device-side numbers)
stage bench_suite 3600 python -u scripts/bench_suite.py --scale 0.5

# 4. per-kernel ON-DEVICE compile attribution (the wedge-prone step —
#    deliberately last; also fills the persistent cache for non-AOT
#    shapes)
stage time_kernels 3500 python -u scripts/time_pk_kernels.py 8192

echo "session done $(date -u +%H:%M:%S); logs in $LOGDIR"
