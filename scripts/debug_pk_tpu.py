"""Component-wise TPU bisection of the pk kernels: each suspect piece in
its own tiny pallas_call, checked against host references."""

import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
from jax import numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ouroboros_consensus_tpu.ops import field as fe_b
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.pk import curve as pc
from ouroboros_consensus_tpu.ops.pk import hashes as ph
from ouroboros_consensus_tpu.ops.pk import limbs as fe

B = 256
rng = np.random.default_rng(5)


def run_kernel(body, outs, *args, base8=False):
    """outs: list of (prefix_shape, dtype). All args [*, B]."""
    in_specs = []
    call_args = []
    if base8:
        call_args.append(jnp.asarray(pc.BASE8_NP))
        in_specs.append(
            pl.BlockSpec(pc.BASE8_NP.shape, lambda: (0, 0, 0), memory_space=pltpu.VMEM)
        )
    for a in args:
        call_args.append(jnp.asarray(a))
        in_specs.append(
            pl.BlockSpec(np.asarray(a).shape, lambda *_, _n=np.asarray(a).ndim: (0,) * _n,
                         memory_space=pltpu.VMEM)
        )
    return pl.pallas_call(
        body,
        in_specs=in_specs,
        out_specs=tuple(
            pl.BlockSpec((*p, B), lambda *_, _n=len(p) + 1: (0,) * _n,
                         memory_space=pltpu.VMEM)
            for p, _ in outs
        ),
        out_shape=tuple(jax.ShapeDtypeStruct((*p, B), d) for p, d in outs),
    )(*call_args)


which = set(sys.argv[1:]) or {"sha", "blake", "base", "ladder", "decomp", "scalar"}

# --- 1. unrolled sha512_fixed (66 bytes) ------------------------------------
if "sha" in which:
    data = rng.integers(0, 256, (66, B), dtype=np.int32)

    def k_sha(d_ref, o_ref):
        with fe.kernel_consts(B):
            o_ref[:] = ph._sha512_fixed_unrolled(d_ref[:])

    out = np.asarray(run_kernel(k_sha, [((64,), jnp.int32)], data)[0])
    want = np.stack(
        [np.frombuffer(hashlib.sha512(bytes(data[:, i].astype(np.uint8))).digest(), np.uint8)
         for i in range(B)], axis=1)
    print("sha512_fixed unrolled:", "OK" if (out == want).all() else "MISMATCH")

    # var variant, 2 blocks mixed
    msgs = [rng.bytes(int(rng.integers(1, 200))) for _ in range(B)]
    nb = 2
    byts = np.zeros((nb, 128, B), np.int32)
    nblocks = np.zeros((B,), np.int32)
    for i, m in enumerate(msgs):
        k = (len(m) + 17 + 127) // 128
        padded = bytearray(k * 128)
        padded[: len(m)] = m
        padded[len(m)] = 0x80
        padded[-16:] = (8 * len(m)).to_bytes(16, "big")
        for blk in range(k):
            byts[blk, :, i] = np.frombuffer(bytes(padded[blk*128:(blk+1)*128]), np.uint8)
        nblocks[i] = k

    def k_shav(d_ref, n_ref, o_ref):
        with fe.kernel_consts(B):
            o_ref[:] = ph._sha512_var_unrolled(d_ref[:], n_ref[:][0])

    out = np.asarray(run_kernel(k_shav, [((64,), jnp.int32)], byts, nblocks.reshape(1, B))[0])
    want = np.stack([np.frombuffer(hashlib.sha512(m).digest(), np.uint8) for m in msgs], axis=1)
    print("sha512_var unrolled:", "OK" if (out == want).all() else "MISMATCH")

# --- 2. unrolled blake2b (64 bytes, ds 32) ----------------------------------
if "blake" in which:
    data = rng.integers(0, 256, (64, B), dtype=np.int32)

    def k_b2b(d_ref, o_ref):
        with fe.kernel_consts(B):
            o_ref[:] = ph._blake2b_fixed_unrolled(d_ref[:], 64, 32)

    out = np.asarray(run_kernel(k_b2b, [((32,), jnp.int32)], data)[0])
    want = np.stack(
        [np.frombuffer(hashlib.blake2b(bytes(data[:, i].astype(np.uint8)), digest_size=32).digest(), np.uint8)
         for i in range(B)], axis=1)
    print("blake2b unrolled:", "OK" if (out == want).all() else "MISMATCH")

# --- 3. base_mul_w8 (MXU one-hot) -------------------------------------------
if "base" in which:
    ks = [int.from_bytes(rng.bytes(32), "little") for _ in range(B)]
    digits = np.zeros((32, B), np.int32)
    for i, k in enumerate(ks):
        for w in range(32):
            digits[w, i] = (k >> (8 * w)) & 0xFF

    def k_base(b8_ref, d_ref, o_ref):
        with fe.kernel_consts(B), pc.kernel_base8(b8_ref[:]):
            p = pc.base_mul_w8(d_ref[:])
            o_ref[:] = jnp.concatenate([p.x, p.y, p.z, p.t], axis=0)

    out = np.asarray(run_kernel(k_base, [((80,), jnp.int32)], digits, base8=True)[0])
    okall = True
    for i in range(0, B, 37):
        x = fe_b.limbs_to_int_np(out[0:20, i]) % fe.P_INT
        y = fe_b.limbs_to_int_np(out[20:40, i]) % fe.P_INT
        z = fe_b.limbs_to_int_np(out[40:60, i]) % fe.P_INT
        zi = pow(z, fe.P_INT - 2, fe.P_INT)
        want = he.point_mul(ks[i], he.B)
        wzi = pow(want[2], fe.P_INT - 2, fe.P_INT)
        if (x * zi % fe.P_INT, y * zi % fe.P_INT) != (want[0] * wzi % fe.P_INT, want[1] * wzi % fe.P_INT):
            okall = False
    print("base_mul_w8:", "OK" if okall else "MISMATCH")

# --- 4. scalar_mul_w4 rotate-ladder ----------------------------------------
if "ladder" in which:
    pts = []
    for i in range(B):
        k = int(rng.integers(1, 2**60))
        p = he.point_mul(k, he.B)
        zi = pow(p[2], fe.P_INT - 2, fe.P_INT)
        pts.append((p[0] * zi % fe.P_INT, p[1] * zi % fe.P_INT))
    px = np.stack([fe_b.int_to_limbs_np(p[0]) for p in pts], axis=1)
    py = np.stack([fe_b.int_to_limbs_np(p[1]) for p in pts], axis=1)
    pt_ = np.stack([fe_b.int_to_limbs_np(p[0] * p[1] % fe.P_INT) for p in pts], axis=1)
    pz = np.tile(fe_b.int_to_limbs_np(1)[:, None], (1, B))
    flat_in = np.concatenate([px, py, pz, pt_], axis=0).astype(np.int32)
    ks = [int.from_bytes(rng.bytes(32), "little") >> 3 for _ in range(B)]
    digits = np.zeros((64, B), np.int32)
    for i, k in enumerate(ks):
        for w in range(64):
            digits[w, i] = (k >> (4 * w)) & 0xF
    digits_msb = digits[::-1].copy()

    def k_lad(p_ref, d_ref, o_ref):
        with fe.kernel_consts(B):
            pt = pc.Point(p_ref[0:20], p_ref[20:40], p_ref[40:60], p_ref[60:80])
            q = pc.scalar_mul_w4(d_ref[:], pt)
            o_ref[:] = jnp.concatenate([q.x, q.y, q.z, q.t], axis=0)

    out = np.asarray(run_kernel(k_lad, [((80,), jnp.int32)], flat_in, digits_msb)[0])
    okall = True
    for i in range(0, B, 37):
        x = fe_b.limbs_to_int_np(out[0:20, i]) % fe.P_INT
        y = fe_b.limbs_to_int_np(out[20:40, i]) % fe.P_INT
        z = fe_b.limbs_to_int_np(out[40:60, i]) % fe.P_INT
        zi = pow(z, fe.P_INT - 2, fe.P_INT)
        xx, yy = pts[i]
        want = he.point_mul(ks[i], (xx, yy, 1, xx * yy % fe.P_INT))
        wzi = pow(want[2], fe.P_INT - 2, fe.P_INT)
        if (x * zi % fe.P_INT, y * zi % fe.P_INT) != (want[0] * wzi % fe.P_INT, want[1] * wzi % fe.P_INT):
            okall = False
    print("scalar_mul_w4:", "OK" if okall else "MISMATCH")

# --- 5. decompress + compress ----------------------------------------------
if "decomp" in which:
    encs = []
    for i in range(B):
        k = int(rng.integers(1, 2**60))
        encs.append(he.point_compress(he.point_mul(k, he.B)))
    enc_arr = np.stack([np.frombuffer(e, np.uint8) for e in encs], axis=1).astype(np.int32)

    def k_dec(e_ref, ok_ref, o_ref):
        with fe.kernel_consts(B):
            ok, p = pc.decompress(e_ref[:])
            ok_ref[:] = ok.astype(jnp.int32)[None, :]
            o_ref[:] = pc.compress(p)

    ok, out = run_kernel(k_dec, [((1,), jnp.int32), ((32,), jnp.int32)], enc_arr)
    ok = np.asarray(ok); out = np.asarray(out)
    print("decompress/compress:", "OK" if (ok[0] != 0).all() and (out == enc_arr).all() else "MISMATCH",
          f"(ok {(ok[0]!=0).sum()}/{B}, enc match {(out==enc_arr).all(axis=0).sum()}/{B})")

# --- 6. reduce512 + is_canonical_scalar -------------------------------------
if "scalar" in which:
    raw = rng.integers(0, 256, (64, B), dtype=np.int32)

    def k_red(d_ref, o_ref, c_ref):
        with fe.kernel_consts(B):
            o_ref[:] = fe.reduce512(d_ref[:])
            c_ref[:] = fe.is_canonical_scalar(d_ref[:][:32]).astype(jnp.int32)[None, :]

    out, canon = run_kernel(k_red, [((20,), jnp.int32), ((1,), jnp.int32)], raw)
    out = np.asarray(out); canon = np.asarray(canon)
    okall = True
    for i in range(0, B, 17):
        v = int.from_bytes(bytes(raw[:, i].astype(np.uint8)), "little")
        if fe_b.limbs_to_int_np(out[:, i]) != v % fe.L_INT:
            okall = False
        s = int.from_bytes(bytes(raw[:32, i].astype(np.uint8)), "little")
        if bool(canon[0, i]) != (s < fe.L_INT):
            okall = False
    print("reduce512/is_canonical:", "OK" if okall else "MISMATCH")
