"""Profiled end-to-end device replay: where does the wall time go?

Runs the SAME replay as bench.py's device child but with the
protocol/batch Enclose brackets (stage / dispatch / materialize /
epilogue) collected, plus disk-stream and segmentation timings, and
prints a budget table. This is the round-5 item-3 instrument: the gap
between the composed kernel rate (~11.6k lanes/s hot) and the
end-to-end rate (5.3k headers/s, BENCH r5 first run) has to be
attributed before it can be closed.

`--host` runs the HOST-PIPELINE-ONLY replay instead: stream the chain,
segment it, run host_prechecks + packed staging per window — no device
dispatch at all. This measures the host pipeline CEILING (µs/header of
view-stream + prechecks + stage; its reciprocal is the best rate any
device can be fed at) and is CPU-verifiable on a box with no
accelerator. A/B the columnar window pipeline against the per-object
one with OCT_COLUMNAR=0 (round-8 acceptance metric); OCT_TRACE=1
installs the obs flight recorder — per-window spans only, so the
ceiling must stay within 2% of OCT_TRACE=0 (round-9 acceptance).

`--trace-out=PATH` (device replay) writes the flight recorder's event
stream as a Chrome trace-event JSON after the hot replay — load it at
ui.perfetto.dev or chrome://tracing — and prints the
dispatch->materialize latency p50/p99.

Usage:  python scripts/profile_replay.py [--host] [--trace-out=f.json]
        [n_headers]   (default 100000)
"""

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
HOST_ONLY = "--host" in sys.argv[1:]
TRACE_OUT = next(
    (a.split("=", 1)[1] for a in sys.argv[1:]
     if a.startswith("--trace-out=")), None,
)
N = int(ARGS[0]) if ARGS else 100_000


def host_ceiling():
    """Host-pipeline-only replay: window stream -> epoch segmentation ->
    host_prechecks -> packed staging (+ bucket pad), timed per phase.
    No verdicts are produced (no device); the epoch nonce fed to staging
    comes from a genesis tick — staging cost does not depend on the
    nonce VALUE, only its presence, so the measured work is identical
    to the real replay's stage bracket."""
    os.environ.setdefault("BENCH_HEADERS", str(N))
    import numpy as np

    import bench
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos
    from ouroboros_consensus_tpu.protocol.views import ViewColumns
    from ouroboros_consensus_tpu.tools import db_analyser as ana

    path, params, lview = bench.build_or_load_chain()
    columnar = ana._columnar_enabled()
    mode = "columnar (ViewColumns)" if columnar else "per-object (HeaderView)"
    # the acceptance A/B: OCT_TRACE=1 must not tax the host ceiling —
    # the recorder hangs off BATCH_TRACER and sees per-window events
    # only, none of which this host-only loop emits per header
    traced = obs.maybe_install()
    print(f"host pipeline: {mode} (OCT_TRACE={'1' if traced else '0'})",
          flush=True)

    for attempt in ("warm", "hot"):
        res = ana.ValidationResult()
        imm = ana.open_immutable(path, validate_all="stream")
        t_stream = t_pre = t_stage = 0.0
        nh = nwin = npacked = 0
        t0 = time.monotonic()

        def timed_windows():
            nonlocal t_stream
            it = ana._stream_windows(imm, res)
            while True:
                ts = time.monotonic()
                try:
                    win = next(it)
                except StopIteration:
                    t_stream += time.monotonic() - ts
                    return
                t_stream += time.monotonic() - ts
                yield win

        wins = ana._cap_windows(timed_windows(), N)
        state = praos.PraosState()
        for seg in ana._epoch_window_segments(params, wins):
            ticked = praos.tick(
                params, lview, pbatch._slot_at(seg, 0), state
            )
            eta0 = ticked.state.epoch_nonce
            w, seg_n = 0, len(seg)
            while w < seg_n:
                j = pbatch._proof_break(seg, w, min(w + bench.MAX_BATCH, seg_n))
                win = seg[w:j]
                ts = time.monotonic()
                pre = pbatch.host_prechecks(params, lview, win)
                t_pre += time.monotonic() - ts
                ts = time.monotonic()
                packed = None
                if isinstance(win, ViewColumns) and isinstance(
                    pre, pbatch.ColumnChecks
                ):
                    packed = pbatch.stage_packed_columns(
                        params, lview, eta0, win, pre
                    )
                elif not isinstance(win, ViewColumns):
                    packed = pbatch.stage_packed(params, lview, eta0, win)
                if packed is None:
                    pbatch.stage_any(params, lview, eta0, win, pre)
                else:
                    pbatch.pad_packed_to(
                        packed[1], pbatch.bucket_size(len(win))
                    )
                    npacked += 1
                t_stage += time.monotonic() - ts
                nh += len(win)
                nwin += 1
                w = j
        wall = time.monotonic() - t0
        host_s = t_stream + t_pre + t_stage
        print(f"\n== {attempt}: {nh} headers, host pipeline {host_s:.2f}s "
              f"(ceiling {nh/host_s:.0f} headers/s; wall {wall:.2f}s)",
              flush=True)
        for label, secs in (("view-stream", t_stream),
                            ("prechecks", t_pre), ("stage", t_stage)):
            print(f"  {label:12s} {secs:8.2f}s  {secs/nh*1e6:7.2f} us/header")
        print(f"  windows: {nwin} ({npacked} packed)")
    # one run-ledger record per invocation (obs/ledger.py): the hot
    # attempt's ceiling + phase walls, with full env/git provenance
    from ouroboros_consensus_tpu.obs import ledger

    ledger.record_replay(
        "profile_replay",
        recorder=obs.recorder() if traced else None,
        config={"n": N, "mode": "host", "columnar": columnar,
                "traced": traced},
        result={
            "headers": nh, "host_s": round(host_s, 3),
            "ceiling_per_s": round(nh / host_s, 1),
            "windows": nwin, "packed_windows": npacked,
        },
        wall_s=wall,
        phases_s={"view-stream": round(t_stream, 3),
                  "prechecks": round(t_pre, 3),
                  "stage": round(t_stage, 3)},
    )


def main():
    os.environ.setdefault("BENCH_HEADERS", str(N))
    import bench
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.utils.trace import EncloseEvent, TransferEvent

    path, params, lview = bench.build_or_load_chain()
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)

    tot = defaultdict(float)
    cnt = defaultdict(int)
    xfer = defaultdict(int)  # h2d/d2h bytes + packed/generic window counts

    def tracer(ev):
        if isinstance(ev, EncloseEvent) and ev.edge == "end":
            tot[ev.label] += ev.duration
            cnt[ev.label] += 1
        elif isinstance(ev, TransferEvent):
            xfer["h2d"] += ev.h2d_bytes
            xfer["d2h"] += ev.d2h_bytes
            if ev.phase == "dispatch":
                xfer["packed" if ev.packed else "generic"] += 1

    pbatch.set_batch_tracer(tracer)
    # the flight recorder chains BEHIND the local tracer (obs.install
    # preserves it) — spans + histograms + the Perfetto event stream
    rec = obs.install() if (TRACE_OUT or obs.enabled()) else None

    # instrument the window stream (disk read + native parse + column
    # build) by timing the generator pulls
    stream_s = 0.0
    orig_stream = ana._stream_windows

    def timed_stream(imm, res):
        nonlocal stream_s
        it = orig_stream(imm, res)
        while True:
            t0 = time.monotonic()
            try:
                win = next(it)
            except StopIteration:
                stream_s += time.monotonic() - t0
                return
            stream_s += time.monotonic() - t0
            yield win

    for attempt in ("warm", "hot"):
        tot.clear(); cnt.clear(); xfer.clear(); stream_s = 0.0
        ana._stream_windows = lambda imm, res: timed_stream(imm, res)
        t0 = time.monotonic()
        r = ana.revalidate(
            path, params, lview, backend="device", validate_all=True,
            max_batch=bench.MAX_BATCH,
        )
        wall = time.monotonic() - t0
        ana._stream_windows = orig_stream
        assert r.error is None and r.n_valid == r.n_blocks
        print(f"\n== {attempt}: {r.n_valid} headers in {wall:.2f}s "
              f"({r.n_valid/wall:.0f} headers/s)", flush=True)
        accounted = 0.0
        for label in ("stage", "dispatch", "materialize", "epilogue"):
            if cnt[label]:
                print(f"  {label:12s} {tot[label]:8.2f}s  x{cnt[label]:4d} "
                      f"({tot[label]/wall*100:5.1f}%)")
                accounted += tot[label]
        print(f"  {'view-stream':12s} {stream_s:8.2f}s          "
              f"({stream_s/wall*100:5.1f}%)")
        other = wall - accounted - stream_s
        print(f"  {'other':12s} {other:8.2f}s          "
              f"({other/wall*100:5.1f}%)")
        nwin = xfer["packed"] + xfer["generic"]
        if nwin:
            print(
                f"  windows: {nwin} ({xfer['packed']} packed) | "
                f"H2D {xfer['h2d']/nwin/1e3:.1f} KB/window | "
                f"D2H {xfer['d2h']/nwin/1e3:.1f} KB/window"
            )
    if rec is not None:
        s = rec.latency_summary()
        if s["windows"]:
            p50 = s["device_latency_p50_s"]
            p99 = s["device_latency_p99_s"]
            print(
                f"\ndispatch->materialize latency over {s['windows']} "
                f"windows: p50 {p50*1e3:.1f} ms | p99 {p99*1e3:.1f} ms"
            )
        if TRACE_OUT:
            from ouroboros_consensus_tpu.obs import perfetto

            doc = rec.write_chrome_trace(TRACE_OUT)
            errs = perfetto.validate_chrome_trace(doc)
            print(f"chrome trace: {TRACE_OUT} "
                  f"({len(doc['traceEvents'])} events"
                  f"{'' if not errs else f', INVALID: {errs[:3]}'})")
        obs.uninstall()
    pbatch.set_batch_tracer(None)
    # one run-ledger record per invocation: the hot replay's rate, phase
    # walls and boundary bytes, plus the warmup/resource ledgers
    from ouroboros_consensus_tpu.obs import ledger

    nwin = xfer["packed"] + xfer["generic"]
    ledger.record_replay(
        "profile_replay",
        recorder=rec,
        config={"n": N, "mode": "device", "platform": dev.platform},
        result={
            "headers": r.n_valid, "wall_s": round(wall, 3),
            "rate_per_s": round(r.n_valid / wall, 1),
            "windows": nwin, "packed_windows": xfer["packed"],
            "h2d_bytes": int(xfer["h2d"]), "d2h_bytes": int(xfer["d2h"]),
        },
        wall_s=wall,
        phases_s={k: round(v, 3) for k, v in sorted(tot.items())},
    )


if __name__ == "__main__":
    if HOST_ONLY:
        host_ceiling()
    else:
        main()
