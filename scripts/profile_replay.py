"""Profiled end-to-end device replay: where does the wall time go?

Runs the SAME replay as bench.py's device child but with the
protocol/batch Enclose brackets (stage / dispatch / materialize /
epilogue) collected, plus disk-stream and segmentation timings, and
prints a budget table. This is the round-5 item-3 instrument: the gap
between the composed kernel rate (~11.6k lanes/s hot) and the
end-to-end rate (5.3k headers/s, BENCH r5 first run) has to be
attributed before it can be closed.

Usage:  python scripts/profile_replay.py [n_headers]  (default 100000)
"""

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000


def main():
    os.environ.setdefault("BENCH_HEADERS", str(N))
    import bench
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.utils.trace import EncloseEvent, TransferEvent

    path, params, lview = bench.build_or_load_chain()
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)

    tot = defaultdict(float)
    cnt = defaultdict(int)
    xfer = defaultdict(int)  # h2d/d2h bytes + packed/generic window counts

    def tracer(ev):
        if isinstance(ev, EncloseEvent) and ev.edge == "end":
            tot[ev.label] += ev.duration
            cnt[ev.label] += 1
        elif isinstance(ev, TransferEvent):
            xfer["h2d"] += ev.h2d_bytes
            xfer["d2h"] += ev.d2h_bytes
            if ev.phase == "dispatch":
                xfer["packed" if ev.packed else "generic"] += 1

    pbatch.set_batch_tracer(tracer)

    # instrument the view stream (disk read + native parse + HeaderView
    # build) by timing the generator pulls
    stream_s = 0.0
    orig_stream = ana._stream_views

    def timed_stream(imm, res):
        nonlocal stream_s
        it = orig_stream(imm, res)
        while True:
            t0 = time.monotonic()
            try:
                hv = next(it)
            except StopIteration:
                stream_s += time.monotonic() - t0
                return
            stream_s += time.monotonic() - t0
            yield hv

    for attempt in ("warm", "hot"):
        tot.clear(); cnt.clear(); xfer.clear(); stream_s = 0.0
        ana._stream_views = lambda imm, res: timed_stream(imm, res)
        t0 = time.monotonic()
        r = ana.revalidate(
            path, params, lview, backend="device", validate_all=True,
            max_batch=bench.MAX_BATCH,
        )
        wall = time.monotonic() - t0
        ana._stream_views = orig_stream
        assert r.error is None and r.n_valid == r.n_blocks
        print(f"\n== {attempt}: {r.n_valid} headers in {wall:.2f}s "
              f"({r.n_valid/wall:.0f} headers/s)", flush=True)
        accounted = 0.0
        for label in ("stage", "dispatch", "materialize", "epilogue"):
            if cnt[label]:
                print(f"  {label:12s} {tot[label]:8.2f}s  x{cnt[label]:4d} "
                      f"({tot[label]/wall*100:5.1f}%)")
                accounted += tot[label]
        print(f"  {'view-stream':12s} {stream_s:8.2f}s          "
              f"({stream_s/wall*100:5.1f}%)")
        other = wall - accounted - stream_s
        print(f"  {'other':12s} {other:8.2f}s          "
              f"({other/wall*100:5.1f}%)")
        nwin = xfer["packed"] + xfer["generic"]
        if nwin:
            print(
                f"  windows: {nwin} ({xfer['packed']} packed) | "
                f"H2D {xfer['h2d']/nwin/1e3:.1f} KB/window | "
                f"D2H {xfer['d2h']/nwin/1e3:.1f} KB/window"
            )
    pbatch.set_batch_tracer(None)


if __name__ == "__main__":
    main()
