"""Profiled end-to-end device replay: where does the wall time go?

Runs the SAME replay as bench.py's device child but with the
protocol/batch Enclose brackets (stage / dispatch / materialize /
epilogue) collected, plus disk-stream and segmentation timings, and
prints a budget table. This is the round-5 item-3 instrument: the gap
between the composed kernel rate (~11.6k lanes/s hot) and the
end-to-end rate (5.3k headers/s, BENCH r5 first run) has to be
attributed before it can be closed.

`--host` runs the HOST-PIPELINE-ONLY replay instead: stream the chain,
segment it, run host_prechecks + packed staging per window — no device
dispatch at all. This measures the host pipeline CEILING (µs/header of
view-stream + prechecks + stage; its reciprocal is the best rate any
device can be fed at) and is CPU-verifiable on a box with no
accelerator. A/B the columnar window pipeline against the per-object
one with OCT_COLUMNAR=0 (round-8 acceptance metric); OCT_TRACE=1
installs the obs flight recorder — per-window spans only, so the
ceiling must stay within 2% of OCT_TRACE=0 (round-9 acceptance).

`--trace-out=PATH` (device replay) writes the flight recorder's event
stream as a Chrome trace-event JSON after the hot replay — load it at
ui.perfetto.dev or chrome://tracing — and prints the
dispatch->materialize latency p50/p99.

`--overlap-ab` runs the STUBBED-CRYPTO DEVICE TWIN A/B for the
round-10 threaded staging pipeline: the same end-to-end replay with
crypto hash-stubbed (testing/stubs — compiles in seconds on XLA:CPU)
and a simulated per-window device latency (`OCT_TWIN_DEVICE_MS`,
default 40 — a sleep in materialize, GIL-released exactly like a real
device wait), once with `OCT_STAGE_THREAD=0` (inline staging) and once
with `=1` (producer thread + segment prefetch). On a staging-bound
profile with >= 2 host cores the threaded run must be >= 1.3x the
inline run (the acceptance gate; exit 1 below it); the
`oct_window_*_seconds` histogram p50s are printed as the overlap
evidence (staging wall unchanged per window while end-to-end shrinks).
On a SINGLE-core host the gate is advisory only: the producer/prefetch
threads and the main loop serialize on the one core and the GIL, the
round-9 materialize worker already hides the device sleeps, and the
measured A/B lands at parity (0.97-1.24x across profiles on this box)
— the harness reports the ratio and the per-phase evidence either way
so a TPU session can bank the real number.

Usage:  python scripts/profile_replay.py [--host] [--overlap-ab]
        [--trace-out=f.json] [n_headers]   (default 100000)
"""

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
HOST_ONLY = "--host" in sys.argv[1:]
OVERLAP_AB = "--overlap-ab" in sys.argv[1:]
TRACE_OUT = next(
    (a.split("=", 1)[1] for a in sys.argv[1:]
     if a.startswith("--trace-out=")), None,
)
N = int(ARGS[0]) if ARGS else 100_000


def host_ceiling():
    """Host-pipeline-only replay: window stream -> epoch segmentation ->
    host_prechecks -> packed staging (+ bucket pad), timed per phase.
    No verdicts are produced (no device); the epoch nonce fed to staging
    comes from a genesis tick — staging cost does not depend on the
    nonce VALUE, only its presence, so the measured work is identical
    to the real replay's stage bracket."""
    os.environ.setdefault("BENCH_HEADERS", str(N))
    import numpy as np

    import bench
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos
    from ouroboros_consensus_tpu.protocol.views import ViewColumns
    from ouroboros_consensus_tpu.storage import sidecar as sidecar_mod
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.utils.trace import EncloseEvent

    path, params, lview = bench.build_or_load_chain()
    columnar = ana._columnar_enabled()
    mode = "columnar (ViewColumns)" if columnar else "per-object (HeaderView)"
    # the round-17 mmap-vs-parse wall split rides the nested
    # "stream-mmap"/"stream-parse" Enclose brackets — per-CHUNK events
    # (a handful per run), collected by a local tracer the recorder
    # chains behind exactly as in main()
    split = defaultdict(float)

    def _split_tracer(ev):
        if isinstance(ev, EncloseEvent) and ev.edge == "end" \
                and ev.label in ("stream-mmap", "stream-parse"):
            split[ev.label] += ev.duration

    pbatch.set_batch_tracer(_split_tracer)
    # the acceptance A/B: OCT_TRACE=1 must not tax the host ceiling —
    # the recorder hangs off BATCH_TRACER and sees per-window events
    # only, none of which this host-only loop emits per header
    traced = obs.maybe_install()
    # the live plane rides the same bound: with OCT_HEARTBEAT + the
    # stall watchdog armed the hot ceiling must stay within 2% of
    # OCT_TRACE=0 (one atomic file rewrite per ~2 s — nothing per
    # header; round-11 acceptance)
    from ouroboros_consensus_tpu.obs import live as _live

    plane = _live.maybe_arm()
    print(f"host pipeline: {mode} (OCT_TRACE={'1' if traced else '0'}, "
          f"live={'armed' if plane else 'off'})", flush=True)

    try:
        for attempt in ("warm", "hot"):
            split.clear()
            sidecar_mod.reset_counters()
            res = ana.ValidationResult()
            imm = ana.open_immutable(path, validate_all="stream")
            t_stream = t_pre = t_stage = 0.0
            nh = nwin = npacked = 0
            t0 = time.monotonic()

            def timed_windows():
                nonlocal t_stream
                it = ana._stream_windows(imm, res)
                while True:
                    ts = time.monotonic()
                    try:
                        win = next(it)
                    except StopIteration:
                        t_stream += time.monotonic() - ts
                        return
                    t_stream += time.monotonic() - ts
                    yield win

            wins = ana._cap_windows(timed_windows(), N)
            state = praos.PraosState()
            for seg in ana._epoch_window_segments(params, wins):
                ticked = praos.tick(
                    params, lview, pbatch._slot_at(seg, 0), state
                )
                eta0 = ticked.state.epoch_nonce
                w, seg_n = 0, len(seg)
                while w < seg_n:
                    j = pbatch._proof_break(seg, w, min(w + bench.MAX_BATCH, seg_n))
                    win = seg[w:j]
                    ts = time.monotonic()
                    pre = pbatch.host_prechecks(params, lview, win)
                    t_pre += time.monotonic() - ts
                    ts = time.monotonic()
                    packed = None
                    if isinstance(win, ViewColumns) and isinstance(
                        pre, pbatch.ColumnChecks
                    ):
                        packed = pbatch.stage_packed_columns(
                            params, lview, eta0, win, pre
                        )
                    elif not isinstance(win, ViewColumns):
                        packed = pbatch.stage_packed(params, lview, eta0, win)
                    if packed is None:
                        pbatch.stage_any(params, lview, eta0, win, pre)
                    else:
                        pbatch.pad_packed_to(
                            packed[1], pbatch.bucket_size(len(win))
                        )
                        npacked += 1
                    t_stage += time.monotonic() - ts
                    nh += len(win)
                    nwin += 1
                    w = j
            wall = time.monotonic() - t0
            host_s = t_stream + t_pre + t_stage
            print(f"\n== {attempt}: {nh} headers, host pipeline {host_s:.2f}s "
                  f"(ceiling {nh/host_s:.0f} headers/s; wall {wall:.2f}s)",
                  flush=True)
            for label, secs in (("view-stream", t_stream),
                                ("prechecks", t_pre), ("stage", t_stage)):
                print(f"  {label:12s} {secs:8.2f}s  {secs/nh*1e6:7.2f} us/header")
            print(f"  windows: {nwin} ({npacked} packed)")
            sc_counts = sidecar_mod.counters()
            if any(sc_counts.values()) or split:
                print(f"  sidecar: {sc_counts} | "
                      f"mmap {split['stream-mmap']:.3f}s / "
                      f"parse {split['stream-parse']:.3f}s")
        # one run-ledger record per invocation (obs/ledger.py): the hot
        # attempt's ceiling + phase walls, with full env/git provenance
        from ouroboros_consensus_tpu.obs import ledger

        ledger.record_replay(
            "profile_replay",
            recorder=obs.recorder() if traced else None,
            config={"n": N, "mode": "host", "columnar": columnar,
                    "traced": traced,
                    "sidecar": sidecar_mod.enabled()},
            result={
                "headers": nh, "host_s": round(host_s, 3),
                "ceiling_per_s": round(nh / host_s, 1),
                "windows": nwin, "packed_windows": npacked,
                "sidecar": sc_counts,
            },
            wall_s=wall,
            phases_s={"view-stream": round(t_stream, 3),
                      "prechecks": round(t_pre, 3),
                      "stage": round(t_stage, 3),
                      "stream-mmap": round(split["stream-mmap"], 3),
                      "stream-parse": round(split["stream-parse"], 3)},
        )
    finally:
        # a raising replay must still disarm the live plane — the
        # unwind is what keeps maybe_arm re-entrant for the next run;
        # and the split tracer must not leak into the next run
        if plane is not None:
            plane.disarm()
        pbatch.set_batch_tracer(None)


def main():
    os.environ.setdefault("BENCH_HEADERS", str(N))
    import bench
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.utils.trace import EncloseEvent, TransferEvent

    path, params, lview = bench.build_or_load_chain()
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)

    tot = defaultdict(float)
    cnt = defaultdict(int)
    xfer = defaultdict(int)  # h2d/d2h bytes + packed/generic window counts

    def tracer(ev):
        if isinstance(ev, EncloseEvent) and ev.edge == "end":
            tot[ev.label] += ev.duration
            cnt[ev.label] += 1
        elif isinstance(ev, TransferEvent):
            xfer["h2d"] += ev.h2d_bytes
            xfer["d2h"] += ev.d2h_bytes
            if ev.phase == "dispatch":
                xfer["packed" if ev.packed else "generic"] += 1

    pbatch.set_batch_tracer(tracer)
    # the flight recorder chains BEHIND the local tracer (obs.install
    # preserves it) — spans + histograms + the Perfetto event stream
    rec = obs.install() if (TRACE_OUT or obs.enabled()) else None
    try:

        # instrument the window stream (disk read + native parse + column
        # build) by timing the generator pulls
        stream_s = 0.0
        orig_stream = ana._stream_windows

        def timed_stream(imm, res):
            nonlocal stream_s
            it = orig_stream(imm, res)
            while True:
                t0 = time.monotonic()
                try:
                    win = next(it)
                except StopIteration:
                    stream_s += time.monotonic() - t0
                    return
                stream_s += time.monotonic() - t0
                yield win

        for attempt in ("warm", "hot"):
            tot.clear(); cnt.clear(); xfer.clear(); stream_s = 0.0
            ana._stream_windows = lambda imm, res: timed_stream(imm, res)
            t0 = time.monotonic()
            r = ana.revalidate(
                path, params, lview, backend="device", validate_all=True,
                max_batch=bench.MAX_BATCH,
            )
            wall = time.monotonic() - t0
            ana._stream_windows = orig_stream
            assert r.error is None and r.n_valid == r.n_blocks
            print(f"\n== {attempt}: {r.n_valid} headers in {wall:.2f}s "
                  f"({r.n_valid/wall:.0f} headers/s)", flush=True)
            accounted = 0.0
            for label in ("stage", "dispatch", "materialize", "epilogue"):
                if cnt[label]:
                    print(f"  {label:12s} {tot[label]:8.2f}s  x{cnt[label]:4d} "
                          f"({tot[label]/wall*100:5.1f}%)")
                    accounted += tot[label]
            print(f"  {'view-stream':12s} {stream_s:8.2f}s          "
                  f"({stream_s/wall*100:5.1f}%)")
            other = wall - accounted - stream_s
            print(f"  {'other':12s} {other:8.2f}s          "
                  f"({other/wall*100:5.1f}%)")
            nwin = xfer["packed"] + xfer["generic"]
            if nwin:
                print(
                    f"  windows: {nwin} ({xfer['packed']} packed) | "
                    f"H2D {xfer['h2d']/nwin/1e3:.1f} KB/window | "
                    f"D2H {xfer['d2h']/nwin/1e3:.1f} KB/window"
                )
        if rec is not None:
            s = rec.latency_summary()
            if s["windows"]:
                p50 = s["device_latency_p50_s"]
                p99 = s["device_latency_p99_s"]
                print(
                    f"\ndispatch->materialize latency over {s['windows']} "
                    f"windows: p50 {p50*1e3:.1f} ms | p99 {p99*1e3:.1f} ms"
                )
            if TRACE_OUT:
                from ouroboros_consensus_tpu.obs import perfetto

                doc = rec.write_chrome_trace(TRACE_OUT)
                errs = perfetto.validate_chrome_trace(doc)
                print(f"chrome trace: {TRACE_OUT} "
                      f"({len(doc['traceEvents'])} events"
                      f"{'' if not errs else f', INVALID: {errs[:3]}'})")
    finally:
        # unwind even when revalidate raises: the recorder and the
        # module-level tracer hook must not leak into the next run
        if rec is not None:
            obs.uninstall()
        pbatch.set_batch_tracer(None)
    # one run-ledger record per invocation: the hot replay's rate, phase
    # walls and boundary bytes, plus the warmup/resource ledgers
    from ouroboros_consensus_tpu.obs import ledger

    nwin = xfer["packed"] + xfer["generic"]
    ledger.record_replay(
        "profile_replay",
        recorder=rec,
        config={"n": N, "mode": "device", "platform": dev.platform},
        result={
            "headers": r.n_valid, "wall_s": round(wall, 3),
            "rate_per_s": round(r.n_valid / wall, 1),
            "windows": nwin, "packed_windows": xfer["packed"],
            "h2d_bytes": int(xfer["h2d"]), "d2h_bytes": int(xfer["d2h"]),
        },
        wall_s=wall,
        phases_s={k: round(v, 3) for k, v in sorted(tot.items())},
    )


def overlap_ab():
    """The staging-overlap acceptance harness (round 10): stubbed
    crypto + simulated device latency, OCT_STAGE_THREAD off vs on."""
    os.environ.setdefault("BENCH_HEADERS", str(N))
    os.environ["OCT_TRACE"] = "1"

    import bench
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.obs import ledger
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.testing import stubs
    from ouroboros_consensus_tpu.tools import db_analyser as ana

    path, params, lview = bench.build_or_load_chain()
    stubs.install_stub_crypto()
    # the simulated device/tunnel wait per window: a sleep inside
    # materialize releases the GIL, so staging/prefetch threads overlap
    # it exactly as they would a real device round trip
    twin_ms = float(os.environ.get("OCT_TWIN_DEVICE_MS", "40"))
    max_batch = int(os.environ.get("OCT_AB_MAX_BATCH", "1024"))
    orig_mat = pbatch.materialize_verdicts

    def slow_materialize(tagged, b):
        time.sleep(twin_ms / 1e3)
        return orig_mat(tagged, b)

    pbatch.materialize_verdicts = slow_materialize
    # OCT_AB_DEPTH (default 1): pipeline depth for BOTH runs. Depth 1
    # isolates the staging thread's contribution — the thread-off
    # baseline is then fully serial (stage -> dispatch -> device wait
    # -> epilogue per window), which is the honest control on a 1-core
    # host where the depth-3 in-loop overlap already saturates the GIL
    # (measured there: thread-on is CPU-bound at ~1.2x). On a
    # multi-core host / real device run with OCT_AB_DEPTH=3.
    depth = int(os.environ.get("OCT_AB_DEPTH", "1"))
    orig_vc = pbatch.validate_chain

    def vc_depth(*a, **k):
        k.setdefault("pipeline_depth", depth)
        return orig_vc(*a, **k)

    pbatch.validate_chain = vc_depth
    print(f"overlap A/B: stubbed crypto, twin device latency "
          f"{twin_ms:.0f} ms/window, max_batch={max_batch}, "
          f"pipeline_depth={depth}", flush=True)

    walls: dict[str, float] = {}
    summaries: dict[str, dict] = {}
    for label, thread in (("warmup", "1"), ("thread-off", "0"),
                          ("thread-on", "1")):
        os.environ["OCT_STAGE_THREAD"] = thread
        rec = obs.install()
        rec.clear()
        t0 = time.monotonic()
        try:
            r = ana.revalidate(path, params, lview, backend="device",
                               validate_all="stream", max_batch=max_batch)
            wall = time.monotonic() - t0
        finally:
            obs.uninstall()
        assert r.error is None and r.n_valid == r.n_blocks > 0
        walls[label] = wall
        summaries[label] = rec.latency_summary()
        print(f"  {label:10s} {r.n_valid} headers in {wall:6.2f}s "
              f"({r.n_valid / wall:8.0f} headers/s)", flush=True)

    ratio = walls["thread-off"] / walls["thread-on"]
    print(f"\npipeline-thread-on / off speedup: {ratio:.2f}x "
          f"({walls['thread-off']:.2f}s -> {walls['thread-on']:.2f}s)")
    print("per-window p50s (oct_window_*_seconds) — the overlap "
          "evidence: staging wall per window is unchanged while the "
          "end-to-end wall shrinks:")
    for phase in ("stage", "dispatch", "materialize", "epilogue"):
        off = summaries["thread-off"].get(f"{phase}_p50_s")
        on = summaries["thread-on"].get(f"{phase}_p50_s")
        print(f"  {phase:12s} off {off if off is None else round(off, 4)}"
              f"  on {on if on is None else round(on, 4)}")
    ledger.record_replay(
        "profile_replay",
        recorder=None,
        config={"n": N, "mode": "overlap-ab", "twin_device_ms": twin_ms,
                "max_batch": max_batch},
        result={"wall_off_s": round(walls["thread-off"], 3),
                "wall_on_s": round(walls["thread-on"], 3),
                "speedup": round(ratio, 3)},
        wall_s=sum(walls.values()),
    )
    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    if ratio < 1.3:
        if cores < 2:
            # one core: the producer/prefetch threads and the main loop
            # serialize on the GIL and the round-9 worker already hides
            # the device sleeps — parity is the EXPECTED result here,
            # not a failure of the mechanism (module docstring)
            print(f"note: speedup {ratio:.2f}x on a single-core host — "
                  "the >=1.3x bound applies on >=2 cores / a real "
                  "device; reporting only")
            return 0
        print(f"WARNING: speedup {ratio:.2f}x below the 1.3x acceptance "
              "bound on this profile")
        return 1
    return 0


if __name__ == "__main__":
    if HOST_ONLY:
        host_ceiling()
    elif OVERLAP_AB:
        sys.exit(overlap_ab())
    else:
        main()
