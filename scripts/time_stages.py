"""Quick per-stage hot timing on the live device (ground-truth A/B for
kernel changes). Compiles the requested stages fresh (the persistent
cache keys on source, so edited kernels recompile once) and prints hot
rates in the same format as aot_smoke.py.

Usage: python scripts/time_stages.py [ed vrf kes finish] (default: ed vrf)
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

os.environ["OCT_PK_AOT"] = "0"  # jit path only — we are timing edits
# before the bench import: bench.py resolves BENCH_HEADERS at import
# time, and stage timing wants the 100k chain even when the 1M cache
# exists (its open alone is multi-second)
os.environ.setdefault("BENCH_HEADERS", "100000")

from bench import KES_DEPTH, MAX_BATCH, build_or_load_chain  # noqa: E402
from ouroboros_consensus_tpu.ops.pk import kernels as K  # noqa: E402
from ouroboros_consensus_tpu.protocol import batch as pbatch  # noqa: E402
from ouroboros_consensus_tpu.tools import db_analyser as ana  # noqa: E402

B = MAX_BATCH


def main():
    which = sys.argv[1:] or ["ed", "vrf"]
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)
    path, params, lview = build_or_load_chain()
    imm = ana.open_immutable(path, validate_all=False)
    res = ana.ValidationResult()
    hvs = []
    for hv in ana._stream_views(imm, res):
        hvs.append(hv)
        if len(hvs) >= B:
            break
    pre = pbatch.host_prechecks(params, lview, hvs)
    staged = pbatch.stage(params, lview, None, hvs, pre.kes_evolution)
    padded = pbatch.pad_batch_to(staged, pbatch.bucket_size(len(hvs)))
    cols = pbatch.flatten_batch(padded)
    stages = dict(K.split_stage_fns(KES_DEPTH))

    t0 = time.monotonic()
    limb = stages["relayout"](*cols)
    jax.tree.map(np.asarray, limb)
    print(f"relayout first {time.monotonic()-t0:.2f}s", flush=True)
    (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
     l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
     l_kes_hb, l_kes_hnb,
     l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al,
     l_beta, l_tlo, l_thi) = limb

    import jax.numpy as jnp

    args = {
        "ed": (l_ed_pk, l_ed_s, l_ed_hb, l_ed_hnb),
        "kes": (l_kes_vk, l_kes_per, l_kes_s, l_kes_leaf, l_kes_sib,
                l_kes_hb, l_kes_hnb),
        "vrf": (l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al),
    }

    outs = {}
    for name in ("vrf", "ed", "kes", "finish"):
        if name not in which:
            continue
        if name == "finish":
            vrf_out = outs.get("vrf") or stages["vrf"](*args["vrf"])
            z_ok = jnp.zeros((1, B), jnp.int32)
            z_pt = jnp.zeros((80, B), jnp.int32)
            a = (z_ok, z_pt, l_ed_r, z_ok, z_pt, l_kes_r,
                 vrf_out[0], vrf_out[1], l_vrf_c, l_beta, l_tlo, l_thi)
        else:
            a = args[name]
        fn = stages[name]
        t0 = time.monotonic()
        out = fn(*a)
        jax.tree.map(np.asarray, out)
        first = time.monotonic() - t0
        # aot_smoke methodology: n async dispatches, materialize ONCE —
        # the per-call D2H through the tunnel (vrf points are 13 MB)
        # otherwise swamps the kernel time
        n = 6
        t0 = time.monotonic()
        for _ in range(n):
            out = fn(*a)
        jax.tree.map(np.asarray, out)
        hot = (time.monotonic() - t0) / n
        outs[name] = out
        print(f"{name:8s} first {first:7.2f}s  hot {hot*1e3:8.1f}ms  "
              f"({B/hot:9.0f} lanes/s)", flush=True)


if __name__ == "__main__":
    main()
