#!/usr/bin/env python
"""Repo-wide octlint gate: both static-analysis passes, ratcheted.

    python scripts/lint.py              # AST pass + jaxpr budgets
    python scripts/lint.py --no-graphs  # AST pass only (no jax import)
    python scripts/lint.py --update-baseline   # re-grandfather

Exit 0 = no NEW findings (anything in analysis/baseline.json is
grandfathered) and every registered kernel graph within its
analysis/budgets.json ceiling. Exit 1 otherwise. The baseline only ever
shrinks in normal operation — fixing a grandfathered finding makes its
key stale, and the gate prints a reminder to re-run --update-baseline
so the ratchet tightens.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ouroboros_consensus_tpu.analysis import astlint, graphs  # noqa: E402

BASELINE = os.path.join(
    REPO, "ouroboros_consensus_tpu", "analysis", "baseline.json"
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-graphs", action="store_true")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    roots = [
        os.path.join(REPO, "ouroboros_consensus_tpu"),
        os.path.join(REPO, "bench.py"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "tutorials"),
    ]
    findings = astlint.lint_paths(
        [p for p in roots if os.path.exists(p)], rel_to=REPO
    )
    unsuppressed = [f for f in findings if not f.suppressed]

    with open(BASELINE, encoding="utf-8") as f:
        baseline = set(json.load(f).get("findings", []))

    if args.update_baseline:
        payload = {
            "comment": "Grandfathered octlint finding keys "
                       "(scripts/lint.py ratchet).",
            "findings": sorted({f.key() for f in unsuppressed}),
        }
        with open(BASELINE, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"baseline updated: {len(payload['findings'])} finding(s)")
        return 0

    new = [f for f in unsuppressed if f.key() not in baseline]
    current_keys = {f.key() for f in unsuppressed}
    stale = sorted(baseline - current_keys)

    violations: list[str] = []
    reports: list[graphs.GraphReport] = []
    if not args.no_graphs:
        # abstract tracing needs no accelerator; pin the platform so a
        # wedged TPU tunnel (this box's sitecustomize force-registers
        # the plugin) can never hang the lint gate
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized by the embedding process
        reports = graphs.analyze_registered()
        violations = graphs.check_budgets(reports)

    if args.json:
        print(json.dumps({
            "new_findings": [f.format() for f in new],
            "stale_baseline": stale,
            "budget_violations": violations,
            "graphs": [r.to_dict() for r in reports],
            "ok": not (new or violations),
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        for v in violations:
            print(f"BUDGET: {v}")
        for k in stale:
            print(f"note: baseline entry no longer fires "
                  f"(run --update-baseline to ratchet): {k}")
        print(
            f"lint: {len(new)} new finding(s), "
            f"{len(violations)} budget violation(s), "
            f"{len(stale)} stale baseline entr(y/ies)"
        )
    return 1 if (new or violations) else 0


if __name__ == "__main__":
    sys.exit(main())
