#!/usr/bin/env python
"""Repo-wide octlint + octrange gate: all static-analysis passes,
ratcheted.

    python scripts/lint.py                    # AST + budgets + point-ops
                                              #   + octrange certification
                                              #   + octwall compile costs
    python scripts/lint.py --no-graphs        # AST pass only (no jax)
    python scripts/lint.py --changed          # re-trace only graphs whose
                                              #   source modules differ from
                                              #   git HEAD (fast path)
    python scripts/lint.py --tier full        # full lane sweeps
    python scripts/lint.py --update-baseline  # re-grandfather AST keys
    python scripts/lint.py --update-certified # re-pin certification
    python scripts/lint.py --update-costs     # re-pin compile-cost features
                                              #   + compile_wall ceilings
    python scripts/lint.py --update-resources # re-measure + re-pin the
                                              #   device_resources section
                                              #   (lowers AND COMPILES every
                                              #   registry graph — slow)
    python scripts/lint.py --update-sync      # re-pin the octsync
                                              #   concurrency ratchet
                                              #   (analysis/concurrency.json)
    python scripts/lint.py --update-flow      # re-pin the octflow
                                              #   failure-taxonomy ratchet
                                              #   (analysis/flow.json)

Exit 0 = no NEW AST findings (anything in analysis/baseline.json is
grandfathered), every registered kernel graph within its
analysis/budgets.json ceilings (jaxpr metrics AND per-lane point-ops),
zero equation growth from telemetry on the instrumentation-purity
graphs (budgets.json "instrumentation_purity": the obs flight recorder
must stay host-side), every certification pin in
analysis/certified.json still holding (range proofs intact, no new
taint findings), and every graph's octwall predicted cold-compile wall
under its budgets.json "compile_wall" ceiling. Nonzero exits mirror
`python -m ouroboros_consensus_tpu.analysis`: 1 = new AST finding(s),
2 = registry drift (a REGISTRY/aux entry without a shapes.json spec or
source mapping — gate misconfiguration, checked before anything
traces), 3 = budget violation(s), 4 = certification ratchet
violation(s), 5 = compile-wall ratchet violation(s), 6 = device-resource
ratchet violation(s) (budgets.json "device_resources": a registry graph
without a pin, a pin whose octwall feature hash no longer matches the
traced structure, or a pinned FLOP/byte/peak-HBM value over its
ceiling — obs/resources.check_device_resources; the check is dict
compares only, the compiles run solely under --update-resources),
7 = octsync concurrency/durability ratchet violation(s) (Pass 5,
analysis/concurrency.py: a new unsuppressed SYNC2xx finding — lock-order
inversion, unguarded `# guarded-by:` attribute, silent thread death,
bare write to a protected store path — or drift in the pinned
lock/thread/guarded inventory vs analysis/concurrency.json; pure AST,
runs even under --no-graphs),
8 = octflow failure-taxonomy ratchet violation(s) (Pass 6,
analysis/flow.py: a new unsuppressed FLOW3xx finding — an unclassified
raise in the durable planes, a laundered REFUSE/REPAIR class inside the
recovery ladder, a silent broad handler on a verdict path, a device
dispatch unreachable from a host-reference protector, a dead or
re-entrant OCT_*=0 kill-switch lever, an unpinned anomaly re-dispatch —
drift in the pinned raise-site/handler/rung-edge/lever inventory vs
analysis/flow.json, or a README kill-switch row out of sync with the
pinned lever inventory (analysis/envlevers.check_kill_switches); pure
AST, runs even under --no-graphs). The
ratchet files only ever shrink in normal operation — fixing a
grandfathered finding makes its key stale, and the gate prints a
reminder to re-run the matching --update flag so the ratchet tightens.

One trace per graph feeds all four jaxpr passes: the gate traces each
graph at its fast-sweep lane count (production 8192 for the
lane-sensitive graphs, the registry tile otherwise) and the budget
metrics, point-op counts, certification AND compile-cost features all
read that cached trace.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ouroboros_consensus_tpu.analysis import astlint, graphs  # noqa: E402

BASELINE = os.path.join(
    REPO, "ouroboros_consensus_tpu", "analysis", "baseline.json"
)
# a diff in any of these invalidates every certificate, not just one
# graph's — force the full sweep. scripts/fit_costmodel.py is costmodel
# machinery living outside analysis/ (a re-fit changes every predicted
# wall), so it is mapped into the fast path explicitly.
_MACHINERY_PREFIX = "ouroboros_consensus_tpu/analysis/"
_MACHINERY_FILES = {"scripts/fit_costmodel.py"}
# observability sources: an obs/ (or trajectory-report) edit cannot
# change any crypto graph, but it CAN leak telemetry into the traced
# programs — map these into the instrumentation-purity re-trace so an
# obs diff re-runs the zero-eqn differential instead of skipping every
# graph pass. The live-plane modules (obs/live.py, obs/server.py) and
# the recovery plane (obs/recovery.py) ride the prefix;
# parallel/spmd.py is mapped explicitly since round 11 — it emits
# per-shard ShardSpan telemetry beside the shard_map program, exactly
# the host/device boundary the purity differential fences — and
# testing/chaos.py since round 12: its injection seams sit beside the
# packed_unpack/verdict_reduce dispatch paths, so a chaos edit re-runs
# the zero-eqn differential proving the seams add no equations to the
# production jaxprs when disarmed. storage/ joined in round 13: the
# durable-store repair plane (immutable.py's write-fault seams +
# RepairEvent emission, guard.py's marker seam) emits telemetry beside
# the replay's staging inputs, so a storage edit re-runs the same
# zero-eqn differential.
_OBS_PREFIXES = ("ouroboros_consensus_tpu/obs/",
                 "ouroboros_consensus_tpu/storage/")
_OBS_FILES = {"scripts/perf_report.py",
              "ouroboros_consensus_tpu/parallel/spmd.py",
              "ouroboros_consensus_tpu/testing/chaos.py",
              "ouroboros_consensus_tpu/protocol/forge.py"}
# octsync (Pass 5) --changed trigger: the thread/lock/rename fabric
# lives in obs/ + storage/ + the chaos seams + the analysis machinery
# itself; protocol/batch.py and ops/pk/aot.py carry guarded-by
# annotations and bench.py hosts thread entries, so an edit to any of
# them re-runs the concurrency sweep too (pure AST — seconds, no jax)
_SYNC_PREFIXES = ("ouroboros_consensus_tpu/obs/",
                  "ouroboros_consensus_tpu/storage/",
                  "ouroboros_consensus_tpu/analysis/")
_SYNC_FILES = {"ouroboros_consensus_tpu/testing/chaos.py",
               "ouroboros_consensus_tpu/protocol/batch.py",
               "ouroboros_consensus_tpu/ops/pk/aot.py",
               "bench.py",
               # the serving plane (round 20): the scheduler's service
               # lock + checkpoint rename discipline, the lock-free
               # admission single-writer contract, and the seeded
               # traffic source the chaos matrix drives through it
               "ouroboros_consensus_tpu/node/serve.py",
               "ouroboros_consensus_tpu/protocol/admission.py",
               "ouroboros_consensus_tpu/testing/traffic.py"}


def _sync_selected(changed: set[str]) -> bool:
    """--changed: does the diff touch the concurrency plane? Empty
    diff/no git -> True (conservative: the sweep is cheap)."""
    if not changed:
        return True
    return any(f.startswith(_SYNC_PREFIXES) or f in _SYNC_FILES
               for f in changed)


# octflow (Pass 6) --changed trigger: the failure-routing fabric — the
# triage table (node/exit.py), the degradation ladder (obs/ prefix
# covers obs/recovery.py), the dispatch seams (protocol/batch.py,
# forge.py, tpraos.py), the REFUSE-classed storage planes, the chaos
# injection seams, and the analysis machinery itself. Any other diff
# skips the sweep under --changed (pure AST — seconds, no jax).
_FLOW_PREFIXES = ("ouroboros_consensus_tpu/storage/",
                  "ouroboros_consensus_tpu/obs/",
                  "ouroboros_consensus_tpu/analysis/")
_FLOW_FILES = {"ouroboros_consensus_tpu/node/exit.py",
               "ouroboros_consensus_tpu/protocol/batch.py",
               "ouroboros_consensus_tpu/protocol/forge.py",
               "ouroboros_consensus_tpu/protocol/tpraos.py",
               "ouroboros_consensus_tpu/testing/chaos.py",
               # the serving plane (round 20): its dispatch seam must
               # stay ladder-protected (FLOW304), AdmissionRefused is a
               # classified raise (FLOW301), and OCT_SERVE_DEVICE is a
               # documented lever (FLOW305)
               "ouroboros_consensus_tpu/node/serve.py",
               "ouroboros_consensus_tpu/protocol/admission.py",
               "ouroboros_consensus_tpu/testing/traffic.py"}


def _flow_selected(changed: set[str]) -> bool:
    """--changed: does the diff touch the failure-routing plane? Empty
    diff/no git -> True (conservative: the sweep is cheap)."""
    if not changed:
        return True
    return any(f.startswith(_FLOW_PREFIXES) or f in _FLOW_FILES
               for f in changed)


def _changed_files() -> set[str]:
    """Repo-relative paths that differ from HEAD (staged, unstaged and
    untracked)."""
    files: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, cwd=REPO, check=True
            ).stdout
        except (subprocess.CalledProcessError, FileNotFoundError):
            return set()  # not a git checkout: caller falls back to full
        files |= {ln.strip() for ln in out.splitlines() if ln.strip()}
    return files


def _select_graphs(changed: set[str]) -> list[str] | None:
    """Graphs whose traced source modules intersect the diff; None =
    run everything (machinery changed, or git unavailable)."""
    from ouroboros_consensus_tpu.analysis import absint

    if not changed:
        return []
    if any(f.startswith(_MACHINERY_PREFIX) or f in _MACHINERY_FILES
           for f in changed):
        return None
    sources = dict(graphs.GRAPH_SOURCES)
    sources.update(absint.AUX_SOURCES)
    names = [
        n for n in absint.certifiable_graphs()
        if changed & set(sources.get(n, []))
    ]
    if any(f.startswith(_OBS_PREFIXES) or f in _OBS_FILES for f in changed):
        purity = graphs.load_budgets().get(
            "instrumentation_purity", {}
        ).get("graphs", [])
        names.extend(n for n in purity if n not in names)
    return names


def _update_compile_wall_budgets(cost_features) -> None:
    """--update-costs: re-pin the budgets.json compile_wall ceilings at
    ~1.3x each graph's current predicted wall (same headroom philosophy
    as the jaxpr-metric budgets — drift toward the compile-wall
    pathology fails statically long before a TPU session burns on it).
    The advisory thresholds are hand-set policy and are preserved."""
    from ouroboros_consensus_tpu.analysis import costmodel

    path = graphs._BUDGET_PATH
    with open(path, encoding="utf-8") as f:
        budgets = json.load(f)
    sec = budgets.setdefault("compile_wall", {})
    sec.setdefault("advisory", {})
    per_graph = {}
    for feat in cost_features:
        pred = costmodel.predict(feat)
        if pred is None:
            continue
        per_graph[feat.name] = {
            "predicted_s_max": round(max(1.0, pred * 1.3), 1)
        }
    sec["graphs"] = per_graph
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=2)
        f.write("\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-graphs", action="store_true")
    ap.add_argument("--changed", action="store_true",
                    help="re-trace only graphs whose sources changed")
    ap.add_argument("--tier", choices=("fast", "full"), default="fast")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--update-certified", action="store_true")
    ap.add_argument("--update-costs", action="store_true",
                    help="re-pin costmodel.json graph features and the "
                         "budgets.json compile_wall ceilings")
    ap.add_argument("--update-resources", action="store_true",
                    help="re-measure (lower + COMPILE every registry "
                         "graph — slow) and re-pin the budgets.json "
                         "device_resources section; missing ceilings "
                         "are created, existing ones preserved")
    ap.add_argument("--update-sync", action="store_true",
                    help="re-pin the octsync concurrency ratchet "
                         "(analysis/concurrency.json: grandfathered "
                         "finding keys + lock/thread/guarded inventory)")
    ap.add_argument("--update-flow", action="store_true",
                    help="re-pin the octflow failure-taxonomy ratchet "
                         "(analysis/flow.json: grandfathered finding "
                         "keys + raise-site/handler/rung-edge/lever "
                         "inventory)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    roots = [
        os.path.join(REPO, "ouroboros_consensus_tpu"),
        os.path.join(REPO, "bench.py"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "tutorials"),
    ]
    findings = astlint.lint_paths(
        [p for p in roots if os.path.exists(p)], rel_to=REPO
    )
    unsuppressed = [f for f in findings if not f.suppressed]

    with open(BASELINE, encoding="utf-8") as f:
        baseline = set(json.load(f).get("findings", []))

    if args.update_baseline:
        payload = {
            "comment": "Grandfathered octlint finding keys "
                       "(scripts/lint.py ratchet).",
            "findings": sorted({f.key() for f in unsuppressed}),
        }
        with open(BASELINE, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"baseline updated: {len(payload['findings'])} finding(s)")
        return 0

    new = [f for f in unsuppressed if f.key() not in baseline]
    current_keys = {f.key() for f in unsuppressed}
    stale = sorted(baseline - current_keys)

    # Pass 5 (octsync): the concurrency/durability sweep is pure AST —
    # it runs with or without the graph passes, and under --changed only
    # when the diff touches the thread/lock/rename fabric
    from ouroboros_consensus_tpu.analysis import concurrency

    sync_violations: list[str] = []
    sync_stale: list[str] = []
    run_sync = (args.update_sync or not args.changed
                or _sync_selected(_changed_files()))
    if run_sync:
        sync_report = concurrency.sweep_paths(
            concurrency.default_roots(REPO), REPO, concurrency.load_roots()
        )
        if args.update_sync:
            payload = concurrency.write_baseline(sync_report)
            print(f"concurrency.json updated: "
                  f"{len(payload['findings'])} grandfathered finding(s), "
                  f"{sum(len(v) for v in payload['inventory'].values())} "
                  "inventory row(s)")
            return 0
        sync_violations, sync_stale = concurrency.check_sync(
            sync_report, concurrency.load_baseline()
        )

    # Pass 6 (octflow): the exception-routing/degradation-lattice sweep
    # is pure AST too — same run policy as Pass 5, own --changed map
    from ouroboros_consensus_tpu.analysis import envlevers, flow

    flow_violations: list[str] = []
    flow_stale: list[str] = []
    run_flow = (args.update_flow or not args.changed
                or _flow_selected(_changed_files()))
    if run_flow:
        flow_report = flow.sweep_paths(
            flow.default_roots(REPO), REPO
        )
        if args.update_flow:
            payload = flow.write_baseline(flow_report)
            print(f"flow.json updated: "
                  f"{len(payload['findings'])} grandfathered finding(s), "
                  f"{sum(len(v) for v in payload['inventory'].values())} "
                  "inventory row(s)")
            return 0
        flow_violations, flow_stale = flow.check_flow(
            flow_report, flow.load_baseline()
        )
        # the README kill-switch table and the pinned FLOW305 lever
        # inventory must name the same levers — a documented lever the
        # analyzer never proved guarded (or a proven lever the README
        # forgot) is a Pass-6 violation, not a docs nit
        flow_violations += envlevers.check_kill_switches(
            os.path.join(REPO, "ouroboros_consensus_tpu", "obs",
                         "README.md")
        )

    budget_violations: list[str] = []
    cert_violations: list[str] = []
    cost_violations: list[str] = []
    resource_violations: list[str] = []
    reports: list[graphs.GraphReport] = []
    cert_reports = []
    cost_features = []
    names: list[str] | None = None
    if not args.no_graphs:
        # abstract tracing needs no accelerator; pin the platform so a
        # wedged TPU tunnel (this box's sitecustomize force-registers
        # the plugin) can never hang the lint gate
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized by the embedding process

        from ouroboros_consensus_tpu.analysis import absint, costmodel

        shapes = absint.load_shapes()
        # registry drift gate: a REGISTRY/aux entry without a
        # shapes.json spec or a source mapping is a gate
        # misconfiguration — fail loudly BEFORE anything traces
        drift = absint.check_registry_drift(shapes)
        if drift:
            if args.json:
                print(json.dumps(
                    {"drift_violations": drift, "ok": False},
                    indent=2, sort_keys=True,
                ))
            else:
                for v in drift:
                    print(f"DRIFT: {v}")
            return 2

        if args.changed:
            names = _select_graphs(_changed_files())
        todo = names if names is not None else absint.certifiable_graphs()
        budgets = graphs.load_budgets()
        # warm-ladder rung pins (costmodel.ladder_pins): every rung
        # program the ladder may compile gets its own cost features,
        # ratcheted by the SAME compile_wall + pin-freshness passes as
        # the registry graphs (they carry no device_resources pins —
        # structurally they are the base graphs at rung lane counts).
        # --changed selects them through their base graph, so an edit
        # to the aggregate/msm sources re-fences every rung; the ladder
        # ORCHESTRATION lives in protocol/batch.py, which already maps
        # onto packed_unpack/verdict_reduce (cost re-extract) and the
        # instrumentation-purity differential.
        ladder_features = []
        for name in todo:
            # one trace per graph serves certification, jaxpr budgets,
            # point-op budgets and compile-cost features (trace_graph
            # LRU cache)
            cert_reports.extend(absint.certify_graph(name, args.tier,
                                                     shapes))
            if name in graphs.REGISTRY:
                lanes0 = absint.sweep_lanes(name, args.tier, shapes)[0]
                reports.append(graphs.analyze_jaxpr(
                    graphs.trace_graph(name, lanes0), name
                ))
                # cost features ALWAYS at the fast-sweep lane count —
                # the tile the costmodel.json pins are defined at, so
                # the pin-freshness check compares like with like even
                # under --tier full
                cost_lanes = absint.sweep_lanes(name, "fast", shapes)[0]
                cost_features.append(costmodel.extract_features(
                    graphs.trace_graph(name, cost_lanes), name
                ))
                budget_violations += graphs.check_point_ops(
                    budgets, names=[name]
                )
        for pin_name, base, lanes in costmodel.ladder_pins():
            if base in todo:
                ladder_features.append(costmodel.extract_features(
                    graphs.trace_graph(base, lanes), pin_name
                ))
        budget_violations += graphs.check_budgets(reports, budgets)
        # instrumentation purity: the registry graphs built from the
        # telemetry-instrumented host modules must gain ZERO equations
        # with the obs flight recorder installed (observability is
        # host-side only — budgets.json "instrumentation_purity")
        budget_violations += graphs.check_instrumentation_purity(
            budgets, names=names
        )

        if args.update_certified:
            if names is not None:
                print("--update-certified requires the full sweep "
                      "(drop --changed)")
                return 2
            absint.write_certified(cert_reports)
            print(f"certified.json updated: "
                  f"{len(absint.load_certified()['graphs'])} graph(s)")
            return 0
        if args.update_costs:
            if names is not None:
                print("--update-costs requires the full sweep "
                      "(drop --changed)")
                return 2
            model = (costmodel._cached_cost() or {}).get("model")
            costmodel.write_cost(
                graphs_section=costmodel.pin_payload(
                    cost_features + ladder_features, model
                )
            )
            _update_compile_wall_budgets(cost_features + ladder_features)
            print(f"costmodel.json pins updated: "
                  f"{len(cost_features)} graph(s) + "
                  f"{len(ladder_features)} ladder rung pin(s)")
            return 0
        if args.update_resources:
            if names is not None:
                print("--update-resources requires the full sweep "
                      "(drop --changed)")
                return 2
            from ouroboros_consensus_tpu.obs import resources as obs_res

            measurements = {}
            hashes = {f.name: f.hash() for f in cost_features}
            for f in cost_features:
                lanes = absint.sweep_lanes(f.name, "fast", shapes)[0]
                print(f"# measuring {f.name}"
                      f"@{lanes if lanes is not None else 'tile'} "
                      "(lower + compile)...", flush=True)
                measurements[f.name] = obs_res.measure_graph(
                    f.name, lanes, compile=True
                )
            path = graphs._BUDGET_PATH
            with open(path, encoding="utf-8") as fh:
                budgets_doc = json.load(fh)
            obs_res.update_budgets_section(
                budgets_doc, measurements, hashes,
                measured_at=obs_res.measured_at_string(),
            )
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(budgets_doc, fh, indent=2)
                fh.write("\n")
            print(f"device_resources pins updated: "
                  f"{len(measurements)} graph(s)")
            return 0
        cert_violations = absint.check_certified(cert_reports)
        cost_violations = costmodel.check_compile_wall(
            cost_features + ladder_features, budgets
        )
        # pin freshness: stale pins would stamp warmup stage notes with
        # an old structure's hash and mis-join calibration walls (the
        # ladder rung pins are held to the same freshness)
        cost_violations += costmodel.check_pins(
            cost_features + ladder_features
        )
        # sixth ratchet: device-resource pins (hash-freshness + ceiling
        # compares only — no lowering, no compiling)
        from ouroboros_consensus_tpu.obs import resources as obs_res

        resource_violations = obs_res.check_device_resources(
            cost_features, budgets
        )

    if args.json:
        print(json.dumps({
            "new_findings": [f.format() for f in new],
            "stale_baseline": stale,
            "budget_violations": budget_violations,
            "certification_violations": cert_violations,
            "cost_violations": cost_violations,
            "resource_violations": resource_violations,
            "sync_violations": sync_violations,
            "stale_sync": sync_stale,
            "flow_violations": flow_violations,
            "stale_flow": flow_stale,
            "graphs": [r.to_dict() for r in reports],
            "certified": [r.to_dict() for r in cert_reports],
            "cost_features": [f.to_dict() | {"name": f.name}
                              for f in cost_features],
            "changed_selection": names,
            "ok": not (new or budget_violations or cert_violations
                       or cost_violations or resource_violations
                       or sync_violations or flow_violations),
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.format())
        for v in budget_violations:
            print(f"BUDGET: {v}")
        for v in cert_violations:
            print(f"CERTIFIED: {v}")
        for v in cost_violations:
            print(f"COST: {v}")
        for v in resource_violations:
            print(f"RESOURCES: {v}")
        for v in sync_violations:
            print(f"SYNC: {v}")
        for v in flow_violations:
            print(f"FLOW: {v}")
        for k in stale:
            print(f"note: baseline entry no longer fires "
                  f"(run --update-baseline to ratchet): {k}")
        for k in sync_stale:
            print(f"note: concurrency baseline entry no longer fires "
                  f"(run --update-sync to ratchet): {k}")
        for k in flow_stale:
            print(f"note: flow baseline entry no longer fires "
                  f"(run --update-flow to ratchet): {k}")
        if names is not None:
            print(f"--changed: {len(names)} graph(s) selected: "
                  f"{', '.join(names) or '(none)'}")
        print(
            f"lint: {len(new)} new finding(s), "
            f"{len(budget_violations)} budget violation(s), "
            f"{len(cert_violations)} certification violation(s), "
            f"{len(cost_violations)} compile-wall violation(s), "
            f"{len(resource_violations)} device-resource violation(s), "
            f"{len(sync_violations)} concurrency violation(s), "
            f"{len(flow_violations)} flow violation(s), "
            f"{len(stale)} stale baseline entr(y/ies)"
        )
    if new:
        return 1
    if budget_violations:
        return 3
    if cert_violations:
        return 4
    if cost_violations:
        return 5
    if resource_violations:
        return 6
    if sync_violations:
        return 7
    return 8 if flow_violations else 0


if __name__ == "__main__":
    sys.exit(main())
