"""Tutorial: build a consensus protocol from scratch.

Reference analog: `ouroboros-consensus/src/tutorials/.../Tutorial/
{Simple,WithEpoch}.lhs` — the literate walk-through that implements a toy
protocol against the `ConsensusProtocol` class, then refines it with an
epoch notion. This file is the runnable Python version for THIS
framework: it builds the same two protocols against
`ouroboros_consensus_tpu.protocol.abstract`, wires them to the real
storage engine, and ends with a 2-node property.

Run it:  python tutorials/simple_protocol.py

Part 1 — "SP", the simplest possible protocol
=============================================
A block may be forged in slot s by node (s mod n): pure round robin, no
crypto, no randomness. Everything a protocol needs:

  * ChainDepState — nothing (the protocol keeps no memory)
  * LedgerView    — the number of nodes n
  * ValidateView  — the slot + claimed issuer carried by the header
  * SelectView    — the block number (longest chain wins)
  * IsLeader      — evidence we may forge (here: our node id)

Part 2 — "WithEpoch": state that evolves with time
==================================================
The reference's second tutorial adds epoch-dependent behavior to show
WHY `tick` exists: protocol state may change merely because time passed.
Here the leader schedule rotates one position at every epoch boundary —
`tick` applies the rotation, `update` stays a pure check. This is the
miniature of what Praos does with its nonce rotation (praos.py tick,
Praos.hs:407-432).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace

sys.path.insert(0, ".")  # run from the repo root

from ouroboros_consensus_tpu.protocol.abstract import ConsensusError


# --------------------------------------------------------------------------
# Part 1: the SP protocol
# --------------------------------------------------------------------------


class SPWrongLeader(ConsensusError):
    """The slot's round-robin leader differs from the header's issuer."""


@dataclass(frozen=True)
class SPTicked:
    """Ticked state: SP has no state, but `tick` still marks the type
    transition — slot time has been applied (Ticked.hs)."""

    n_nodes: int


class SimpleProtocol:
    """ConsensusProtocol instance: five operations, no crypto."""

    def __init__(self, n_nodes: int, security_param: int = 10):
        self.n_nodes = n_nodes
        self.security_param = security_param

    # tickChainDepState: apply the passage of time to the state.
    # SP keeps no state, so the ticked state only records the view.
    def tick(self, ledger_view, slot, state) -> SPTicked:
        return SPTicked(n_nodes=ledger_view)

    # updateChainDepState: FULL validation of a header in context.
    # view = (slot, issuer) — what the header claims.
    def update(self, view, slot, ticked: SPTicked):
        vslot, issuer = view
        if issuer != vslot % ticked.n_nodes:
            raise SPWrongLeader(f"slot {vslot}: {issuer} forged, "
                                f"{vslot % ticked.n_nodes} scheduled")
        return None  # the (empty) new state

    # reupdateChainDepState: the checks are known to pass — state only.
    def reupdate(self, view, slot, ticked):
        return None

    # checkIsLeader: are WE scheduled for this slot?
    def check_is_leader(self, node_id, slot, ticked: SPTicked):
        return node_id if slot % ticked.n_nodes == node_id else None

    # chain order: longest chain (block number at the tip)
    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


def part1() -> None:
    proto = SimpleProtocol(n_nodes=3)
    ticked = proto.tick(3, slot=7, state=None)
    # slot 7 with 3 nodes: node 1 leads
    assert proto.check_is_leader(1, 7, ticked) == 1
    assert proto.check_is_leader(0, 7, ticked) is None
    proto.update((7, 1), 7, ticked)  # valid: scheduled leader
    try:
        proto.update((7, 2), 7, ticked)
    except SPWrongLeader as e:
        print(f"part 1: invalid header rejected as expected: {e}")
    else:
        raise AssertionError("wrong leader accepted!")
    print("part 1: SP protocol behaves")


# --------------------------------------------------------------------------
# Part 2: epochs — state that changes with time alone
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class EpochState:
    """ChainDepState: the rotation offset + the slot it was computed at
    (WithEpoch.lhs keeps the analogous 'last applied' marker)."""

    offset: int = 0
    last_slot: int | None = None


@dataclass(frozen=True)
class EpochTicked:
    state: EpochState
    n_nodes: int


class WithEpochProtocol:
    """Round robin whose schedule rotates by one at epoch boundaries:
    leader(slot) = (slot + offset(epoch)) mod n."""

    def __init__(self, n_nodes: int, epoch_length: int, security_param: int = 10):
        self.n_nodes = n_nodes
        self.epoch_length = epoch_length
        self.security_param = security_param

    def _epoch(self, slot: int) -> int:
        return slot // self.epoch_length

    # THE lesson: tick may change the state with no header at all.
    # Praos rotates nonces here (Praos.hs:407-432); we rotate the offset.
    def tick(self, ledger_view, slot, state: EpochState) -> EpochTicked:
        prev = 0 if state.last_slot is None else self._epoch(state.last_slot)
        cur = self._epoch(slot)
        if cur > prev:
            state = replace(state, offset=(state.offset + (cur - prev)) % self.n_nodes)
        return EpochTicked(state, n_nodes=ledger_view)

    def _leader(self, slot: int, ticked: EpochTicked) -> int:
        return (slot + ticked.state.offset) % ticked.n_nodes

    def update(self, view, slot, ticked: EpochTicked) -> EpochState:
        vslot, issuer = view
        if issuer != self._leader(vslot, ticked):
            raise SPWrongLeader(f"slot {vslot}: {issuer} forged, "
                                f"{self._leader(vslot, ticked)} scheduled")
        return replace(ticked.state, last_slot=vslot)

    def reupdate(self, view, slot, ticked: EpochTicked) -> EpochState:
        return replace(ticked.state, last_slot=view[0])

    def check_is_leader(self, node_id, slot, ticked: EpochTicked):
        return node_id if self._leader(slot, ticked) == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


def part2() -> None:
    proto = WithEpochProtocol(n_nodes=3, epoch_length=10)
    st = EpochState()
    # epoch 0: leader(7) = 7 mod 3 = 1
    t0 = proto.tick(3, 7, st)
    assert proto.check_is_leader(1, 7, t0) == 1
    st = proto.update((7, 1), 7, t0)
    # cross into epoch 1 (slot 12): offset rotates to 1 -> leader(12) =
    # (12+1) mod 3 = 1, NOT 12 mod 3 = 0
    t1 = proto.tick(3, 12, st)
    assert t1.state.offset == 1
    assert proto.check_is_leader(1, 12, t1) == 1
    assert proto.check_is_leader(0, 12, t1) is None
    st = proto.update((12, 1), 12, t1)
    print("part 2: epoch rotation via tick behaves")


# --------------------------------------------------------------------------
# Part 3: the protocol is ALL the framework needs — a 2-chain selection
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ToyHeader:
    slot: int
    block_no: int
    issuer: int

    def to_view(self):
        return (self.slot, self.issuer)


def part3() -> None:
    """Chain selection uses ONLY select_view/compare_candidates: the
    same ordering machinery ChainDB runs (chaindb.py _best_candidate_*).
    """
    proto = SimpleProtocol(n_nodes=2)
    chain_a = [ToyHeader(0, 0, 0), ToyHeader(1, 1, 1)]
    chain_b = [ToyHeader(0, 0, 0), ToyHeader(3, 1, 1), ToyHeader(4, 2, 0)]
    va = proto.select_view(chain_a[-1])
    vb = proto.select_view(chain_b[-1])
    assert proto.compare_candidates(va, vb) > 0  # b is longer: preferred
    # validate chain_b the way LedgerDB.push_many folds update
    st = None
    for h in chain_b:
        ticked = proto.tick(2, h.slot, st)
        st = proto.update(h.to_view(), h.slot, ticked)
    print("part 3: chain selection + validation fold behave")


if __name__ == "__main__":
    part1()
    part2()
    part3()
    print("tutorial complete")
