"""Tutorial: run a real-era (Shelley STS) node end to end.

The first tutorial (simple_protocol.py) builds a protocol from scratch;
this one shows the OTHER side of the framework — using the shipped
real-era stack the way an operator would:

  1. write a Shelley genesis file (sgInitialFunds + sgStaking shape);
  2. load it into a ledger + genesis state (protocolInfoShelley analog);
  3. open a ChainDB over ExtLedger(ShelleyLedger, PraosProtocol);
  4. run a forging NodeKernel whose elections come from the LEDGER'S
     stake snapshots, submit a real transaction through the mempool,
     and watch it land in a block;
  5. query the node over LocalStateQuery (the v3 Shelley vocabulary).

Run it:  python tutorials/shelley_node.py
"""

import os
import sys
import tempfile
from dataclasses import replace
from fractions import Fraction

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import shelley as sh
from ouroboros_consensus_tpu.miniprotocol import localstate
from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import config as cfg_tools

# --- 1. credentials + genesis file -----------------------------------------

PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=3,
    active_slot_coeff=Fraction(1),  # tutorial: every slot elects
    epoch_length=50,
    kes_depth=3,
)
pool = fixtures.make_pool(0, kes_depth=PARAMS.kes_depth)
cred = b"tutorial-cred" + b"\x00" * 15
workdir = tempfile.mkdtemp(prefix="shelley-tutorial-")

genesis_cfg = sh.ShelleyGenesis(
    pparams=sh.PParams(min_fee_a=0, min_fee_b=0, key_deposit=100,
                       pool_deposit=500),
    epoch_length=PARAMS.epoch_length,
    stability_window=PARAMS.stability_window,
    max_supply=1_000_000,
)
gen_path = cfg_tools.write_shelley_genesis(
    workdir,
    genesis_cfg,
    initial_funds=[(b"alice-pay" + b"\x00" * 19, cred, 10_000)],
    initial_pools=(sh.PoolParams(
        pool_id=hash_key(pool.vk_cold),
        vrf_hash=hash_vrf_vk(pool.vrf_vk),
        pledge=0, cost=0, margin=Fraction(0), reward_cred=cred, owners=(),
    ),),
    initial_delegations=((cred, hash_key(pool.vk_cold)),),
)
print(f"wrote {gen_path}")

# --- 2. protocolInfo: ledger + genesis state from the file ------------------

ledger, genesis_state = cfg_tools.load_shelley_genesis(gen_path)

# --- 3. the consensus stack over the real ledger ----------------------------

ext = ExtLedger(ledger, PraosProtocol(PARAMS, use_device_batch=False))
genesis = ext.genesis(genesis_state)
genesis = replace(
    genesis,
    header_state=replace(
        genesis.header_state,
        chain_dep_state=replace(
            genesis.header_state.chain_dep_state, epoch_nonce=b"\x42" * 32
        ),
    ),
)
db = open_chaindb(os.path.join(workdir, "db"), ext, genesis,
                  k=PARAMS.security_param)
node = NodeKernel("tutorial", db, ext.protocol, ext.ledger, pool=pool,
                  clock=SlotClock(1.0))

# --- 4. a real transaction through the mempool into a block -----------------

spend = sh.encode_tx(
    [(bytes(32), 0)],  # the genesis outpoint
    [(b"bob-pay" + b"\x00" * 21, None, 10_000)],
    fee=0,
)
node.mempool.add_tx(spend)
for slot in range(1, 4):
    blk = node.try_forge(slot)
    if blk is not None and spend in blk.txs:
        print(f"tx included in block {blk.block_no}@{blk.slot}")
        break
assert db.tip_point() is not None

# --- 5. query the node (LocalStateQuery v3 Shelley vocabulary) --------------

st = db.current_ledger()
distr = localstate.run_query(node, st, "get_stake_distribution", ())
bal = localstate.run_query(node, st, "get_balance", (b"bob-pay" + b"\x00" * 21,))
acct = localstate.run_query(node, st, "get_account_state", ())
print(f"stake distribution: { {k.hex()[:8]: str(v) for k, v in distr.items()} }")
print(f"bob's balance: {bal}")
print(f"treasury={acct['treasury']} reserves={acct['reserves']}")
assert bal == 10_000
db.close()
print("tutorial complete")
