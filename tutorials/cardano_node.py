"""Tutorial: the multi-era composite, end to end.

The reference's flagship block type is `CardanoBlock` — a hard-fork
combinator composition of real eras (Cardano/Block.hs:96). This
tutorial drives the TPU framework's analog the way an operator or
integrator would:

  1. configure the ledger-backed 3-real-era composite
     (Byron UTxO+delegation → Shelley STS → Mary multi-asset);
  2. synthesize a chain that crosses BOTH era boundaries, moving real
     value the whole way (Byron fee-paying txs, a Shelley carry-over
     spend, a Mary mint);
  3. revalidate it end to end — consensus checks per era plus the full
     ledger replay with translations at each boundary;
  4. inspect the final state: the era-0 coin is still spendable two
     translations later, carrying a Mary-native asset;
  5. ask era-aware queries (the HFC query dispatch + EraMismatch).

Run it:  python tutorials/cardano_node.py
"""

import os
import sys
import tempfile
from fractions import Fraction

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from ouroboros_consensus_tpu.hardfork import composite
from ouroboros_consensus_tpu.hardfork.combinator import (
    HardForkTx,
    hard_fork_query,
    inject_tx,
)
from ouroboros_consensus_tpu.ledger.mary import MaryValue
from ouroboros_consensus_tpu.ledger.shelley import ShelleyState
from ouroboros_consensus_tpu.ledger import shelley as sh


def main() -> None:
    # -- 1. configuration (protocolInfoCardano analog) ---------------------
    # the Byron era must end exactly on a Shelley epoch boundary (the
    # reference arranges mainnet's boundary the same way)
    cfg = composite.CardanoMockConfig(
        byron_epochs=1, byron_epoch_length=40,
        shelley_epochs=1, epoch_length=40,
        n_delegs=2, shelley_d=Fraction(1, 2),
        k=5, kes_depth=3,
        with_ledgers=True,
    )
    cm = composite.CardanoMock(cfg)
    print("eras:", [e.name for e in cm.eras])

    # -- 2. synthesize across both boundaries ------------------------------
    path = tempfile.mkdtemp(prefix="cardano-tutorial-")
    n_slots = 40 + 40 + 20  # byron + shelley + a chunk of the mary era
    n = composite.synthesize(path, cfg, n_slots)
    print(f"synthesized {n} blocks over {n_slots} slots at {path}")

    # -- 3. full revalidation (db-analyser shape) --------------------------
    res = composite.revalidate(path, cfg, backend="host")
    assert res.error is None, repr(res.error)
    assert res.n_valid == n
    print(f"revalidated {res.n_valid} blocks; per era: {res.per_era}")

    # -- 4. the value chain survived two era translations ------------------
    lst = res.final_ledger_state
    assert lst.era == 2 and isinstance(lst.inner, ShelleyState)
    [(addr, val)] = list(lst.inner.utxo.values())
    assert isinstance(val, MaryValue)
    print(f"final output: {int(val)} lovelace + assets {dict(val.assets)}")
    # conservation across ALL eras: byron fees folded into reserves at
    # the boundary, every lovelace in exactly one pot
    total = (int(val) + lst.inner.fees + lst.inner.prev_fees
             + lst.inner.reserves + lst.inner.treasury
             + lst.inner.deposits)
    assert total == cm.shelley_ledger.genesis.max_supply
    print("conservation holds across 3 eras")

    # -- 5. era-aware queries ----------------------------------------------
    era_ix, era_name = hard_fork_query(
        cm.hf_ledger, cm.summary, lst, "get_current_era"
    )
    print(f"current era: {era_ix} ({era_name})")
    start = hard_fork_query(cm.hf_ledger, cm.summary, lst, "get_era_start")
    print(f"era start slot: {start}")

    # a Shelley-format tx can still enter the Mary-era mempool through
    # the HFC's tx injection (translate_tx at each boundary)
    outpoint = next(iter(lst.inner.utxo))
    sh_tx = sh.encode_tx(
        [outpoint], [(addr[0], addr[1], int(val))], fee=0, ttl=2**62
    )
    injected = inject_tx(cm.eras, lst.era, HardForkTx(era=1, tx=sh_tx))
    view = cm.hf_ledger.mempool_view(lst, n_slots)
    try:
        cm.hf_ledger.apply_tx(view, injected)
        print("ERROR: ada-only respend of a multi-asset output passed?!")
        sys.exit(1)
    except sh.ShelleyTxError as e:
        # the output carries native assets: an ada-only respend is NOT
        # conserved under the Mary rules — the era really changed
        print(f"mary rules reject the ada-only respend: {e!r}")

    print("tutorial complete")


if __name__ == "__main__":
    main()
