"""The build-pinned AOT artifact store (ops/pk/aot.py) — round 10.

Round-8 pinned the latch-and-skip contract; round 10 REPLACES it with
the store: entries keyed (build_id, src_digest, stage, tile) under
per-build directories with a provenance manifest. The r02-r05 failure
family ("axon format vN" costing ~15 s per doomed deserialize) is now
structurally impossible: `load` checks the manifest's build_id BEFORE
touching the artifact, a format rejection condemns only PRE-rejection
entries (marker mtime), and the write-back re-serializes the fallback
compile so the next process loads warm. These tests pin that contract:
real save/load roundtrips on XLA:CPU executables, the zero-deserialize
wrong_build skip, rejection -> write-back -> warm reload, manifest
integrity under concurrent writers, and `aot_precompile --check`'s
store verification."""

import os
import threading
import time

import numpy as np
import pytest

import jax

from ouroboros_consensus_tpu.ops.pk import aot


@pytest.fixture
def fresh_store(tmp_path, monkeypatch):
    """Isolated store state: private dir, un-latched globals."""
    monkeypatch.setenv("OCT_PK_AOT_DIR", str(tmp_path))
    monkeypatch.delenv("OCT_PK_AOT", raising=False)
    monkeypatch.delenv("OCT_PK_AOT_WRITEBACK", raising=False)
    monkeypatch.delenv("OCT_AOT_BUILD_ID", raising=False)
    _fresh_process(monkeypatch)
    return tmp_path


def _fresh_process(monkeypatch):
    """Reset the in-memory state as a new process would start."""
    monkeypatch.setattr(aot, "_RUNTIME_REJECTED", False)
    monkeypatch.setattr(aot, "_MARKER_CHECKED", False)
    monkeypatch.setattr(aot, "_MARKER_TIME", None)
    monkeypatch.setattr(aot, "_LOADED", {})
    monkeypatch.setattr(aot, "_MANIFEST_CACHE", {})


ARGS = (np.ones((4,), np.float32),)


def _compiled(mult=2.0):
    return jax.jit(lambda x: x * mult + 1).trace(*ARGS).lower().compile()


# ---------------------------------------------------------------------------
# save/load roundtrip + provenance
# ---------------------------------------------------------------------------


def test_save_load_roundtrip_and_manifest(fresh_store):
    sig = aot.sig_of(ARGS)
    path = aot.save("ed", 4, 3, 128, sig, _compiled(), {"via": "test"})
    assert path.startswith(str(fresh_store))
    assert aot._build_slug() in path  # per-build subdirectory
    (meta,) = aot.read_manifest().values()
    assert meta["build_id"] == aot.build_id()
    assert meta["src_digest"] == aot._src_digest()
    assert meta["via"] == "test"
    ex = aot.load("ed", 4, 3, 128, sig)
    assert ex is not None
    np.testing.assert_allclose(np.asarray(ex(*ARGS)),
                               np.asarray(ARGS[0]) * 2 + 1)


def test_wrong_build_skips_without_deserialize(fresh_store, monkeypatch,
                                               capsys):
    """An entry pinned to ANOTHER build is a zero-cost skip: the
    manifest check happens BEFORE the artifact file is ever opened —
    the structural fix for the ~15 s doomed deserializes."""
    import builtins

    sig = aot.sig_of(ARGS)
    aot.save("kes", 4, 3, 128, sig, _compiled(), {})
    _fresh_process(monkeypatch)
    # the runtime moved on: same slug dir on disk, new platform_version
    monkeypatch.setattr(aot, "_BUILD_ID", "tpu v99 (future runtime)")
    real_open = builtins.open

    def guarded(path, *a, **k):
        assert not str(path).endswith(".jaxexec"), \
            "wrong_build entry was deserialized"
        return real_open(path, *a, **k)

    monkeypatch.setattr(builtins, "open", guarded)
    assert aot.load("kes", 4, 3, 128, sig) is None
    # memoized: the second probe does not even re-read the manifest row
    assert aot.load("kes", 4, 3, 128, sig) is None


def test_missing_entry_is_cheap(fresh_store, monkeypatch):
    import builtins

    real_open = builtins.open

    def guarded(path, *a, **k):
        assert not str(path).endswith(".jaxexec")
        return real_open(path, *a, **k)

    monkeypatch.setattr(builtins, "open", guarded)
    assert aot.load("vrf", 8, 3, 128, "deadbeef") is None


# ---------------------------------------------------------------------------
# format rejection -> write-back -> next process warm
# ---------------------------------------------------------------------------


def _poison(name: str, sig: str, saved_at: float):
    """A manifest entry that CLAIMS the current build but whose
    artifact the runtime rejects (the mislabeled-entry hazard the
    marker still defends against)."""
    import pickle

    path = aot.stage_path(name, 4, 3, 128, sig)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(pickle.dumps({"ser": b"junk", "in_tree": None,
                              "out_tree": None, "meta": {}}))
    aot._manifest_update(
        aot.entry_key(name, 4, 3, 128, sig),
        {"build_id": aot.build_id(), "src_digest": aot._src_digest(),
         "saved_at": saved_at},
    )


def test_rejection_writeback_heals_next_process(fresh_store, monkeypatch):
    """The round-10 contract: format rejection -> the fallback compile
    is re-serialized for the current build -> the NEXT process loads
    warm, and the other pre-rejection entries are marker-skipped with
    zero deserializes."""
    from jax.experimental import serialize_executable as se

    sig = aot.sig_of(ARGS)
    _poison("vrf", sig, saved_at=time.time())
    _poison("finish", "aaaaaaaa", saved_at=time.time())
    real_deser = se.deserialize_and_load
    monkeypatch.setattr(
        se, "deserialize_and_load",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError(
            "cached executable is axon format v79599086, this build is v9"
        )),
    )
    assert aot.load("vrf", 4, 3, 128, sig) is None  # ONE rejected deserialize
    assert aot._RUNTIME_REJECTED
    assert os.path.exists(aot._reject_marker())
    # the sibling pre-rejection entry is condemned WITHOUT a deserialize
    deser_calls = []
    monkeypatch.setattr(
        se, "deserialize_and_load",
        lambda *a, **k: deser_calls.append(1) or real_deser(*a, **k),
    )
    assert aot.load("finish", 4, 3, 128, "aaaaaaaa") is None
    assert deser_calls == []
    # the write-back: the stage compiles through the fallback anyway —
    # compile_and_store re-serializes it for the current build
    monkeypatch.setenv("OCT_PK_AOT_WRITEBACK", "1")
    time.sleep(0.05)  # saved_at must post-date the marker mtime
    ex = aot.compile_and_store("vrf", 4, 3, 128,
                               jax.jit(lambda x: x * 3.0), ARGS)
    assert ex is not None
    np.testing.assert_allclose(np.asarray(ex(*ARGS)),
                               np.asarray(ARGS[0]) * 3.0)
    # NEXT PROCESS on the same build: the fresh entry loads warm, the
    # stale sibling is still a zero-deserialize marker_skip
    _fresh_process(monkeypatch)
    deser_calls.clear()
    ex2 = aot.load("vrf", 4, 3, 128, sig)
    assert ex2 is not None
    assert len(deser_calls) == 1  # exactly the healed entry
    np.testing.assert_allclose(np.asarray(ex2(*ARGS)),
                               np.asarray(ARGS[0]) * 3.0)
    assert aot.load("finish", 4, 3, 128, "aaaaaaaa") is None
    assert len(deser_calls) == 1


def test_non_format_failures_do_not_latch(fresh_store):
    assert not aot.note_failure(TypeError(
        "deserialize_and_load() got an unexpected keyword argument"
    ))
    assert not aot._RUNTIME_REJECTED


def test_clear_rejection_unlatches(fresh_store, monkeypatch):
    aot.note_failure(RuntimeError("cached executable is axon format v1"))
    assert aot._RUNTIME_REJECTED and os.path.exists(aot._reject_marker())
    aot.clear_rejection()  # aot_precompile after an ALL-fresh run
    assert not aot._RUNTIME_REJECTED
    assert not os.path.exists(aot._reject_marker())


def test_env_disable_still_wins(fresh_store, monkeypatch):
    monkeypatch.setenv("OCT_PK_AOT", "0")
    assert not aot.enabled()
    assert not aot.writeback_enabled()
    sig = aot.sig_of(ARGS)
    aot.save("ed", 4, 3, 128, sig, _compiled(), {})
    monkeypatch.setattr(aot, "_LOADED", {})
    assert aot.load("ed", 4, 3, 128, sig) is None


# ---------------------------------------------------------------------------
# manifest integrity under concurrent writers
# ---------------------------------------------------------------------------


def test_manifest_concurrent_writers(fresh_store):
    """N threads saving distinct entries concurrently: every entry
    lands in the manifest (locked read-modify-write), the JSON never
    tears, and every artifact loads."""
    compiled = _compiled(5.0)
    n = 6
    errs: list = []

    def worker(i):
        try:
            aot.save(f"s{i}", 4, 3, 128, f"si{i:06x}", compiled, {})
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs == []
    man = aot.read_manifest()
    assert len(man) == n
    for i in range(n):
        assert aot.entry_key(f"s{i}", 4, 3, 128, f"si{i:06x}") in man
    ok, problems = aot.check_store()
    assert problems == [] and ok == n


# ---------------------------------------------------------------------------
# store queries: status + aot_precompile --check
# ---------------------------------------------------------------------------


def test_store_status_counts_matching(fresh_store, monkeypatch):
    sig = aot.sig_of(ARGS)
    aot.save("ed", 4, 3, 128, sig, _compiled(), {})
    monkeypatch.setenv("OCT_AOT_BUILD_ID", "other-runtime v7")
    aot.save("ed", 4, 3, 128, sig, _compiled(), {})
    monkeypatch.delenv("OCT_AOT_BUILD_ID")
    st = aot.store_status()
    assert st["entries"] == 2
    assert st["matching"] == 1
    assert st["build_id"] == aot.build_id()


def test_check_store_reports_problems(fresh_store, monkeypatch):
    """aot_precompile --check: every manifest entry must deserialize
    under the current build — corrupt artifacts, missing files and
    foreign-build pins are each named."""
    sig = aot.sig_of(ARGS)
    aot.save("good", 4, 3, 128, sig, _compiled(), {})
    _poison("bad", "bbbbbbbb", saved_at=time.time())
    aot._manifest_update(
        aot.entry_key("ghost", 4, 3, 128, "cccccccc"),
        {"build_id": aot.build_id(), "saved_at": time.time()},
    )
    aot._manifest_update(
        aot.entry_key("foreign", 4, 3, 128, "dddddddd"),
        {"build_id": "some other runtime", "saved_at": time.time()},
    )
    (fresh_store / aot._build_slug() /
     "foreign_b4_d3_t128_dddddddd.jaxexec").write_bytes(b"x")
    ok, problems = aot.check_store()
    assert ok == 1
    assert len(problems) == 3
    joined = "\n".join(problems)
    assert "bad_b4_d3_t128_bbbbbbbb" in joined
    assert "no artifact file" in joined
    assert "pinned to build" in joined


# ---------------------------------------------------------------------------
# the _stage_call write-back integration (ops/pk/kernels)
# ---------------------------------------------------------------------------


def test_stage_call_writeback_then_warm_reload(fresh_store, monkeypatch):
    """_stage_call with write-back on: the cold call compiles
    explicitly, stores the executable, and a fresh process's first
    _stage_call LOADS it (aot outcome `loaded`, no compile)."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP
    from ouroboros_consensus_tpu.ops.pk import kernels as K

    monkeypatch.setenv("OCT_PK_AOT_WRITEBACK", "1")
    monkeypatch.setattr(K, "_FIRST_EXEC", set())
    monkeypatch.setattr(K, "_AOT_WARM", set())
    WARMUP.reset()
    fn = jax.jit(lambda x: x + 7.0)
    out = K._stage_call("tst", fn, 4, 3, *ARGS)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ARGS[0]) + 7.0)
    rep = WARMUP.report()
    assert rep["aot"].get("saved", 0) == 1
    assert rep["stages"]["tst@b4"]["via"] == "jit"
    # fresh process: the stored executable serves the stage
    _fresh_process(monkeypatch)
    monkeypatch.setattr(K, "_FIRST_EXEC", set())
    monkeypatch.setattr(K, "_AOT_WARM", set())
    WARMUP.reset()
    out2 = K._stage_call("tst", fn, 4, 3, *ARGS)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ARGS[0]) + 7.0)
    rep2 = WARMUP.report()
    assert rep2["aot"].get("loaded", 0) == 1
    assert rep2["stages"]["tst@b4"]["via"] == "aot"
    WARMUP.reset()
