"""The pk-aot rejection latch (ops/pk/aot.py).

Round-8 satellite: the BENCH_r05 tail showed six per-stage "axon format
vN" deserialize failures in ONE attempt — the PR-2 latch was per-process
and `load()` never consulted it, so concurrent/later loads re-paid the
~15 s rejection. These tests pin the fixed contract: one format
rejection disables every later load in-process, persists a per-build
marker that disables the load path for FRESH processes on the same
build (bench attempt 2), does not outlive a build change, and is
cleared when new executables are written."""

import pytest

from ouroboros_consensus_tpu.ops.pk import aot


@pytest.fixture
def fresh_aot(tmp_path, monkeypatch):
    """Isolated aot module state: private cache dir, known build slug,
    un-latched globals (and restore after)."""
    monkeypatch.setenv("OCT_PK_AOT_DIR", str(tmp_path))
    monkeypatch.delenv("OCT_PK_AOT", raising=False)
    monkeypatch.setattr(aot, "_BUILD_SLUG", "aaaaaaaaaaaa")
    monkeypatch.setattr(aot, "_RUNTIME_REJECTED", False)
    monkeypatch.setattr(aot, "_MARKER_CHECKED", False)
    monkeypatch.setattr(aot, "_LOADED", {})
    return tmp_path


def _fresh_process(monkeypatch):
    """Reset the in-memory latch as a new process would start."""
    monkeypatch.setattr(aot, "_RUNTIME_REJECTED", False)
    monkeypatch.setattr(aot, "_MARKER_CHECKED", False)
    monkeypatch.setattr(aot, "_LOADED", {})


def test_format_rejection_latches_in_process(fresh_aot):
    assert aot.enabled()
    latched = aot.note_failure(RuntimeError(
        "INVALID_ARGUMENT: PJRT_Executable_DeserializeAndLoad: cached "
        "executable is axon format v79599086, this build is v9"
    ))
    assert latched and not aot.enabled()


def test_non_format_failures_do_not_latch(fresh_aot):
    assert not aot.note_failure(TypeError(
        "deserialize_and_load() got an unexpected keyword argument"
    ))
    assert aot.enabled()


def test_load_skips_disk_once_latched(fresh_aot, monkeypatch):
    """After the latch, load() must return None WITHOUT touching the
    cache (no stat, no open, no deserialize — the ~15 s tax)."""
    aot.note_failure(RuntimeError("serialized executable is incompatible"))

    def boom(*a, **k):
        raise AssertionError("latched load() touched the cache path")

    monkeypatch.setattr(aot, "stage_path", boom)
    assert aot.load("ed", 8192, 7, 128, "deadbeef") is None


def test_rejection_persists_to_next_process_same_build(fresh_aot,
                                                       monkeypatch):
    aot.note_failure(RuntimeError("cached executable is axon format v1"))
    assert (fresh_aot / "REJECTED.aaaaaaaaaaaa").exists()
    _fresh_process(monkeypatch)
    assert not aot.enabled()  # marker read: attempt 2 skips instantly
    # the memoized-marker read happens once
    assert aot._MARKER_CHECKED


def test_rejection_does_not_outlive_build_change(fresh_aot, monkeypatch):
    aot.note_failure(RuntimeError("cached executable is axon format v1"))
    _fresh_process(monkeypatch)
    monkeypatch.setattr(aot, "_BUILD_SLUG", "bbbbbbbbbbbb")
    assert aot.enabled()  # a new build retries its own executables


def test_env_disable_still_wins(fresh_aot, monkeypatch):
    monkeypatch.setenv("OCT_PK_AOT", "0")
    assert not aot.enabled()


def test_clear_rejection_reenables(fresh_aot, monkeypatch):
    aot.note_failure(RuntimeError("cached executable is axon format v1"))
    assert not aot.enabled()
    aot.clear_rejection()  # what aot_precompile does after a FULL run
    assert aot.enabled()
    assert not (fresh_aot / "REJECTED.aaaaaaaaaaaa").exists()
    _fresh_process(monkeypatch)
    assert aot.enabled()


def test_concurrent_loads_single_rejection(fresh_aot, monkeypatch):
    """Two threads racing into load() on a poisoned cache: exactly ONE
    deserialize attempt runs; the loser sees the latch inside the lock
    and returns None without paying for a second one."""
    import threading

    attempts = []

    # two distinct poisoned entries, as dispatch would probe ed then kes
    for name in ("ed", "kes"):
        p = fresh_aot / f"{name}_b8_d3_t128_cafebabe.jaxexec"
        p.write_bytes(b"not a pickle")

    real_open = open

    def counting_open(path, *a, **k):
        if str(path).endswith(".jaxexec"):
            attempts.append(path)
            raise RuntimeError("cached executable is axon format v1")
        return real_open(path, *a, **k)

    import builtins

    monkeypatch.setattr(builtins, "open", counting_open)

    barrier = threading.Barrier(2)
    results = {}

    def worker(name):
        barrier.wait()
        results[name] = aot.load(name, 8, 3, 128, "cafebabe")

    ts = [threading.Thread(target=worker, args=(n,)) for n in ("ed", "kes")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {"ed": None, "kes": None}
    assert len(attempts) == 1, attempts
    assert not aot.enabled()
