"""octflow FLOW305 fixture: kill-switch integrity.

tests/test_flow.py sweeps this with kill_switches ["OCT_FX_DEAD",
"OCT_FX_DEAD_SUPP", "OCT_FX_GOOD", "OCT_FX_REENTER"].
"""

import os

DEAD = os.environ.get("OCT_FX_DEAD", "1")
DEAD_SUPP = os.environ.get("OCT_FX_DEAD_SUPP", "1")  # octflow: disable=FLOW305 — fixture twin


def _impl(xs):
    return xs


def _fallback(xs):
    return list(xs)


def good(xs):
    if os.environ.get("OCT_FX_GOOD", "1") != "0":
        return _impl(xs)
    return _fallback(xs)


def reenter(xs):
    if os.environ.get("OCT_FX_REENTER", "1") != "0":
        return _impl(xs)
    else:
        return _impl(xs)
