"""octsync fixture: SYNC208 stale suppression.

NOT a test module and never imported — swept by tests/test_concurrency.py.
The disable below suppresses nothing on the current tree, so the
SYNC208 audit flags the comment itself.
"""


def tidy():
    return 0  # octsync: disable=SYNC202
