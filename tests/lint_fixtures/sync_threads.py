"""octsync fixture: SYNC204 unjoined thread + SYNC205 escaping/silent
thread exceptions.

NOT a test module and never imported — swept by tests/test_concurrency.py.
`_worker` has no broad handler (a raise kills the daemon thread with
nothing feeding a recorder seam); `_quiet` has a pass-only broad
handler (same silence, different spelling); `_ok` routes the exception
into a callable seam and is clean. The `u` thread is non-daemon and
never joined; `v` is joined; `w`/`_quiet2` are the suppressed twins.
"""

import threading


def _worker():
    raise RuntimeError("boom")


def _quiet():
    try:
        return 1
    except Exception:
        pass


def _ok():
    try:
        return 2
    except Exception as exc:
        _record(exc)


def _record(exc):
    del exc


def start_workers():
    t = threading.Thread(target=_worker, daemon=True)
    t.start()
    u = threading.Thread(target=_quiet)  # fires SYNC204 (never joined)
    u.start()
    v = threading.Thread(target=_ok)
    v.start()
    v.join()  # joined: NOT a finding


def start_suppressed():
    w = threading.Thread(target=_quiet2)  # octsync: disable=SYNC204
    w.start()


def _quiet2():
    try:
        return 3
    except Exception:  # octsync: disable=SYNC205
        pass
