"""octflow FLOW304 fixture: holes in the degradation lattice.

tests/test_flow.py sweeps this with ladder module "flow_lattice",
router "RecoverySupervisor._run_rung", terminal "host_reference_fold",
dispatch functions ["run_batch"] and protectors ["recover_window"].
"""

LADDERS = {
    "device": ("retry", "host-reference"),
    "ghost": ("missing-rung", "host-reference"),
    "floorless": ("retry",),
}


def run_batch(xs):
    return xs


def host_reference_fold(xs):
    return xs


class RecoverySupervisor:
    def _run_rung(self, rung, xs):
        if rung == "retry":
            return run_batch(xs)
        if rung == "host-reference":
            return host_reference_fold(xs)
        raise ValueError(rung)

    def recover_window(self, xs):
        return self._run_rung("retry", xs)


def uncovered_dispatch(xs):
    return run_batch(xs)


def covered_dispatch(xs):
    sup = RecoverySupervisor()
    if not xs:
        return sup.recover_window(xs)
    return run_batch(xs)


def suppressed_dispatch(xs):
    return run_batch(xs)  # octflow: disable=FLOW304 — fixture twin
