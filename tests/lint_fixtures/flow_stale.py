"""octflow FLOW308 fixture: a suppression that suppresses nothing.

Swept with the base fixture config by tests/test_flow.py.
"""


def clean(xs):
    return list(xs)  # octflow: disable=FLOW303 — nothing fires here
