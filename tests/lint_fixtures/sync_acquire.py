"""octsync fixture: SYNC202 acquire-without-release.

NOT a test module and never imported — swept by tests/test_concurrency.py.
`grab` takes the module lock and returns while still holding it;
`grab_pair` releases in a finally and is clean; `grab_quietly` is the
suppressed twin.
"""

import threading

_L = threading.Lock()


def grab():
    _L.acquire()  # fires SYNC202 (no release on any path)
    return True


def grab_pair():
    _L.acquire()
    try:
        return True
    finally:
        _L.release()  # released: NOT a finding


def grab_quietly():
    _L.acquire()  # octsync: disable=SYNC202
    return True
