"""octlint fixture: one positive + one suppressed case per AST rule.

NOT a test module (pytest never collects it) and never imported — it
exists to be linted by tests/test_analysis.py and by
`python -m ouroboros_consensus_tpu.analysis --paths tests/lint_fixtures`.
Every unsuppressed line below must fire exactly the rule named in the
trailing comment; every `# octlint: disable=...` line must not.
"""

import asyncio

import jax
import numpy as np
from jax import numpy as jnp

_CACHE: dict = {}
_CACHE["warm"] = True  # mutated: a real capture hazard


@jax.jit
def oct101_positive(x):
    y = jnp.sum(x)
    return float(y)  # fires OCT101 (float() on a traced value)


@jax.jit
def oct101_more(x):
    host = np.asarray(x)  # fires OCT101 (np.asarray on traced arg)
    scalar = x.item()  # fires OCT101 (.item() host sync)
    return host, scalar


@jax.jit
def oct101_suppressed(x):
    y = jnp.sum(x)
    return float(y)  # octlint: disable=OCT101 — debug-only path


@jax.jit
def oct102_positive(x):
    flag = jnp.any(x > 0)
    if flag:  # fires OCT102 (Python `if` on a traced value)
        return x + 1
    return x


@jax.jit
def oct102_suppressed(x):
    flag = jnp.any(x > 0)
    if flag:  # octlint: disable=OCT102 — unit-test-only eager helper
        return x + 1
    return x


@jax.jit
def oct103_positive(x):
    return x + len(_CACHE)  # fires OCT103 (mutated module global)


@jax.jit
def oct103_suppressed(x):
    return x + len(_CACHE)  # octlint: disable=OCT103 — read-only by convention


@jax.jit
def oct104_positive(x):
    return x & 0xFFFFFFFF  # fires OCT104 (literal wider than int32)


@jax.jit
def oct104_suppressed(x):
    return x & 0xFFFFFFFF  # octlint: disable=OCT104 — x is int64 here


@jax.jit
def oct104_dtype_wrapped_ok(x):
    # an explicit dtype constructor documents the width: NOT a finding
    return x & jnp.uint32(0xFFFFFFFF)


class _Lock:
    def acquire_write(self):
        return self

    def release_write(self):
        return None


async def oct105_positive(lock: _Lock):
    lock.acquire_write()
    await asyncio.sleep(1)  # fires OCT105 (await holding a lock)
    lock.release_write()


async def oct105_suppressed(lock: _Lock):
    lock.acquire_write()
    await asyncio.sleep(1)  # octlint: disable=OCT105 — bounded sleep
    lock.release_write()


async def oct105_clean(lock: _Lock):
    lock.acquire_write()
    lock.release_write()
    await asyncio.sleep(1)  # lock released: NOT a finding


# -- OCT106: stale suppressions ---------------------------------------------

def oct106_positive():
    # the disable below suppresses nothing (no OCT104 fires here): the
    # stale comment itself is the OCT106 finding
    return 1  # octlint: disable=OCT104


def oct106_suppressed():
    # listing OCT106 alongside the stale rule suppresses the audit —
    # the reviewed way to keep a deliberately pre-emptive suppression
    return 2  # octlint: disable=OCT104,OCT106
