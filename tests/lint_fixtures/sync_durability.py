"""octsync fixture: SYNC207 bare write to a protected store path.

NOT a test module and never imported — swept by tests/test_concurrency.py
with the REAL analysis/sync_roots.json table: `OCT_HEARTBEAT` is an
env_path_lever, so its value taints as a protected path. `write_bare`
opens it directly for writing (fires); `write_atomic` rides the
blessed write-tmp -> fsync -> rename idiom (clean); `write_quietly`
is the suppressed twin.
"""

import json
import os


def write_bare(doc):
    path = os.environ.get("OCT_HEARTBEAT")
    with open(path, "w", encoding="utf-8") as f:  # fires SYNC207
        json.dump(doc, f)


def write_atomic(doc):
    path = os.environ.get("OCT_HEARTBEAT")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:  # tmp+rename: NOT a finding
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_quietly(doc):
    path = os.environ.get("OCT_HEARTBEAT")
    with open(path, "w", encoding="utf-8") as f:  # octsync: disable=SYNC207
        json.dump(doc, f)
