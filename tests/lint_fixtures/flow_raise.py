"""octflow FLOW301 fixture: unclassified raise sites.

Swept by tests/test_flow.py with raise_scope [""] — every line here is
in the crash/verdict-bearing plane for the fixture sweep.
"""


class Disposition:
    REFUSE = "refuse"
    RECOVER = "recover"


class ClassifiedError(Exception):
    pass


class ChildError(ClassifiedError):
    pass


class OddError(Exception):
    pass


DISPOSITIONS = {
    "ClassifiedError": Disposition.REFUSE,
}


def fires():
    raise OddError("no DISPOSITIONS row")


def classified_ok():
    raise ClassifiedError("has a row")


def ancestor_ok():
    raise ChildError("classified through its ClassifiedError base")


def builtin_ok():
    raise ValueError("exempt builtin")


def variable_ok(err):
    raise err  # class unknowable statically: FLOW301 stays silent


def suppressed():
    raise OddError("x")  # octflow: disable=FLOW301 — fixture twin
