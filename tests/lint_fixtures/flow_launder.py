"""octflow FLOW302 fixture: the recovery ladder laundering REFUSE.

tests/test_flow.py sweeps this with the three recover_window* ladder
roots — the PR 13 bug shape: the ladder absorbing a quarantine refusal.
"""


class Disposition:
    REFUSE = "refuse"


class QuarantineError(Exception):
    pass


DISPOSITIONS = {
    "QuarantineError": Disposition.REFUSE,
}


def triage(exc):
    return DISPOSITIONS.get(type(exc).__name__)


def _rung(fn):
    return fn()


def recover_window(fn):
    try:
        return _rung(fn)
    except QuarantineError:
        return None


def recover_window_triaged(fn):
    try:
        return _rung(fn)
    except QuarantineError as e:
        if triage(e) == "refuse":
            raise
        return None


def recover_window_suppressed(fn):
    try:
        return _rung(fn)
    except QuarantineError:  # octflow: disable=FLOW302 — fixture twin
        return None
