"""octsync fixture: SYNC201 lock-order inversion.

NOT a test module and never imported — swept by tests/test_concurrency.py
and `python -m ouroboros_consensus_tpu.analysis sync --paths ...`.
`ab` takes _A then _B while `ba` takes _B then _A: the classic ABBA
cycle. One finding per cycle, reported at the lexically-first edge of
the first sorted pair — `ab`'s inner `with`. The _C/_D cycle is the
suppressed twin (disable on the reported edge only).
"""

import threading

_A = threading.Lock()
_B = threading.Lock()
_C = threading.Lock()
_D = threading.Lock()


def ab():
    with _A:
        with _B:  # fires SYNC201 (the {A,B} cycle's reported edge)
            pass


def ba():
    with _B:
        with _A:
            pass


def cd():
    with _C:
        with _D:  # octsync: disable=SYNC201
            pass


def dc():
    with _D:
        with _C:
            pass
