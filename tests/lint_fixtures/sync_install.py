"""octsync fixture: SYNC206 unbalanced recorder install/uninstall.

NOT a test module and never imported — swept by tests/test_concurrency.py.
`run_once` pairs install with a straight-line uninstall (an exception
in between leaks the armed recorder); `run_safe` uninstalls in a
finally and is clean; `run_quietly` is the suppressed twin.
"""


def run_once(rec):
    rec.install()
    do_work()
    rec.uninstall()  # fires SYNC206 (straight-line only)


def run_safe(rec):
    rec.install()
    try:
        do_work()
    finally:
        rec.uninstall()  # unwound: NOT a finding


def run_quietly(rec):
    rec.install()
    do_work()
    rec.uninstall()  # octsync: disable=SYNC206


def do_work():
    return None
