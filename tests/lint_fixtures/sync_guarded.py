"""octsync fixture: SYNC203 unguarded guarded-by attribute.

NOT a test module and never imported — swept by tests/test_concurrency.py.
`Counter.value` is annotated guarded-by `_lock`; `_spin` is a thread
target, so every method it reaches is thread-reachable. `bump` touches
the attribute inside `with self._lock` (clean), `peek` touches it bare
(fires), `peek_quietly` is the suppressed twin.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.value += 1  # held: NOT a finding

    def peek(self):
        return self.value  # fires SYNC203 (thread-reachable, no lock)

    def peek_quietly(self):
        return self.value  # octsync: disable=SYNC203


_COUNTER = Counter()


def _spin():
    try:
        _COUNTER.bump()
        _COUNTER.peek()
        _COUNTER.peek_quietly()
    except Exception as exc:
        print("spin failed:", exc)


_T = threading.Thread(target=_spin, daemon=True)
