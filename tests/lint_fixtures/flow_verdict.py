"""octflow FLOW303 fixture: silent verdict fabrication.

tests/test_flow.py sweeps this with the validate_chain* functions as
verdict roots and raise_scope [""].
"""


def _device_step(x):
    return x + 1


def validate_chain(xs):
    out = []
    for x in xs:
        try:
            out.append(_device_step(x))
        except Exception:
            pass
    return out


def validate_chain_forwarding(xs):
    try:
        return [_device_step(x) for x in xs], None
    except Exception as e:
        return [], e


def validate_chain_suppressed(xs):
    try:
        return [_device_step(x) for x in xs]
    except Exception:  # octflow: disable=FLOW303 — fixture twin
        return []
