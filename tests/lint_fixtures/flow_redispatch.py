"""octflow FLOW307 fixture: re-dispatch drifting off its pinned route.

tests/test_flow.py sweeps this with redispatch_pins on materialize /
routed / drifted_suppressed / gone_fn.
"""


def reference_fold(xs):
    return xs


def materialize(xs):
    return [x + 1 for x in xs]


def routed(xs):
    return reference_fold(xs)


def drifted_suppressed(xs):  # octflow: disable=FLOW307 — fixture twin
    return xs
