"""octflow FLOW306 fixture: unsanctioned bare/BaseException handlers.

tests/test_flow.py sweeps this with sanctioned_broad ["pump"].
"""


def fires(fn):
    try:
        return fn()
    except BaseException:
        return None


def bare_fires(fn):
    try:
        return fn()
    except:  # noqa: E722
        return None


def reraises(fn):
    try:
        return fn()
    except BaseException:
        raise


def pump(fn, out):
    try:
        out.append(fn())
    except BaseException as e:
        out.append(e)


def suppressed(fn):
    try:
        return fn()
    except BaseException:  # octflow: disable=FLOW306 — fixture twin
        return None
