"""CheckInFuture / clock skew + mempool-bench smoke.

Reference: `Fragment/InFuture.hs:45,99` (checkInFuture truncates
candidates at the first future header; defaultClockSkew tolerance) and
`bench/mempool-bench/Main.hs:50`.
"""

from dataclasses import replace
from fractions import Fraction

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.block.infuture import CheckInFuture, no_check
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=5,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOL = fixtures.make_pool(0, kes_depth=2)
LVIEW = fixtures.make_ledger_view([POOL])
ETA0 = b"\x22" * 32


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _forge_chain(n, start_slot=1):
    blocks, prev, bno = [], None, 0
    for i in range(n):
        b = forge_block(
            PARAMS, POOL, slot=start_slot + i, block_no=bno + i,
            prev_hash=prev, epoch_nonce=ETA0,
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


def test_truncate_unit():
    blocks = _forge_chain(5)  # slots 1..5
    cif = CheckInFuture(now=_FakeClock(2.2), slot_length=1.0, max_clock_skew=0.5)
    kept, dropped = cif.truncate(blocks)
    # slots 1, 2 have onset <= 2.7; slot 3 onset 3.0 > 2.7
    assert [b.slot for b in kept] == [1, 2]
    assert [b.slot for b in dropped] == [3, 4, 5]
    assert no_check().truncate(blocks) == (blocks, [])


def test_chaindb_rejects_future_blocks(tmp_path):
    clock = _FakeClock(3.0)
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    db = open_chaindb(
        str(tmp_path / "db"), ext, st, PARAMS.security_param,
        check_in_future=CheckInFuture(
            now=clock, slot_length=1.0, max_clock_skew=0.5
        ),
    )
    blocks = _forge_chain(5)  # slots 1..5
    for b in blocks:
        db.add_block(b)
    # wallclock 3.0 + skew 0.5: slots 4,5 are in the future
    assert db.tip_point().slot == 3
    # REOPEN at the same wallclock: initial chain selection must apply
    # the same in-future truncation (the stored future blocks sit in
    # the VolatileDB but may not be selected)
    db.close()
    db2 = open_chaindb(
        str(tmp_path / "db"), ext, st, PARAMS.security_param,
        check_in_future=CheckInFuture(
            now=clock, slot_length=1.0, max_clock_skew=0.5
        ),
    )
    assert db2.tip_point().slot == 3
    # time passes; the blocks are still in the VolatileDB, so the next
    # add (or a re-add) reruns selection and picks up the suffix
    clock.t = 10.0
    db2.add_block(blocks[-1])
    assert db2.tip_point().slot == 5


def test_mempool_bench_smoke():
    from ouroboros_consensus_tpu.tools.mempool_bench import bench_add_txs

    r = bench_add_txs(500)
    assert r["n_txs"] == 500 and r["txs_per_s"] > 0
