"""Serving-plane invariants (node/serve.py + protocol/admission.py).

The contract stack, from the ISSUE acceptance wording:

  * differential equality — the continuous-batching scheduler's
    per-tenant verdicts and final fold states are byte-identical to a
    sequential per-tenant `validate_batch` reference, on a mixed
    draft-03 / batch-compatible tenant population with fork storms,
    equivocating pools and injected failure lanes;
  * first-failure semantics per peer under interleaving, and no
    cross-tenant verdict bleed inside shared windows;
  * fairness — one tenant's backlog (same shape via quantum fill, or
    a cold shape via the shape-rotation + rung-capped admission path)
    cannot starve the other tenants;
  * OCT_SERVE_DEVICE=0 actually REROUTES dispatch (a trap on
    `prepare_window` proves the device path is never touched) and the
    host-fold verdicts equal the sequential reference on REAL crypto
    (the host reference fold uses the real host verifiers — stub
    traffic cannot exercise it);
  * a device fault mid-traffic (`device-error@serve-dispatch`) sheds
    to the recovery ladder: verdicts byte-identical to the undisturbed
    run, no tenant dropped, the degraded interval visible (and closed)
    on the SLO surface;
  * a REAL SIGKILL mid-traffic (`sigkill@serve`) relaunches with
    per-tenant carry resume: regenerated seeded traffic fast-forwards
    and the combined verdicts equal the uninterrupted run's;
  * the /slo route serves the live snapshot over HTTP.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from ouroboros_consensus_tpu.node import serve
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.obs.registry import MetricsRegistry
from ouroboros_consensus_tpu.protocol import admission, praos
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.testing import chaos, fixtures, stubs, traffic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def stub_crypto(monkeypatch):
    stubs.install_stub_crypto(monkeypatch)


@pytest.fixture(autouse=True)
def _chaos_disarmed(monkeypatch):
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    monkeypatch.delenv("OCT_SERVE_DEVICE", raising=False)
    chaos.reset()
    recovery.reset_for_tests()
    yield
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    chaos.reset()
    recovery.reset_for_tests()


def _service(tr, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("max_window", 32)
    return serve.ValidationService(tr.params, tr.lview, tr.eta0, **kw)


def _drive(svc, tr):
    """Submit the full seeded arrival order, then drain."""
    for sfx in tr.suffixes():
        svc.submit(sfx.tenant_id, sfx.hvs)
    svc.run_until_drained()


def _verdict_rows(svc, tr):
    return {spec.tenant_id: [v.row() for v in svc.verdicts(spec.tenant_id)]
            for spec in tr.tenants}


def _final_states(svc, tr):
    return {spec.tenant_id:
            recovery.encode_state(svc.tenants[spec.tenant_id].state)
            for spec in tr.tenants}


def _reference(tr):
    """Sequential per-tenant validate_batch fold: the differential
    oracle. One tenant at a time, one suffix per call — the exact
    semantics the shared-window scheduler must reproduce."""
    fresh = traffic.Traffic(tr.cfg)
    rows: dict[str, list] = {s.tenant_id: [] for s in fresh.tenants}
    states = {s.tenant_id: fresh.genesis_state() for s in fresh.tenants}
    for sfx in fresh.suffixes():
        st = states[sfx.tenant_id]
        ticked = praos.tick(fresh.params, fresh.lview, sfx.hvs[0].slot, st)
        res = pbatch.validate_batch(fresh.params, ticked, list(sfx.hvs))
        rows[sfx.tenant_id].append(
            [sfx.seq, res.n_valid, serve._canon_error(res.error)]
        )
        states[sfx.tenant_id] = res.state
    return rows, {t: recovery.encode_state(s) for t, s in states.items()}


# ---------------------------------------------------------------------------
# differential equality + first-failure + no cross-tenant bleed
# ---------------------------------------------------------------------------


def test_differential_batched_vs_sequential(stub_crypto):
    """The headline: shared continuous-batched windows over a mixed
    draft-03/bc population with fork storms, equivocators and both
    injected failure classes == the sequential per-tenant reference,
    verdict rows AND final fold states."""
    tr = traffic.make_traffic(
        n_tenants=6, rounds=2, suffix_len=8, bc_every=3,
        fork_storm=4, equivocators=2, bad_lane_every=5,
        unknown_pool_every=6, seed=11,
    )
    svc = _service(tr)
    _drive(svc, tr)
    ref_rows, ref_states = _reference(tr)
    assert _verdict_rows(svc, tr) == ref_rows
    assert _final_states(svc, tr) == ref_states
    # every suffix resolved: nothing dropped, nothing double-counted
    snap = svc.slo_snapshot()
    assert snap["suffixes_done"] == 12 and snap["queue_depth"] == 0


def test_first_failure_per_peer_and_no_bleed_in_shared_windows(stub_crypto):
    """Tenants share windows (fewer windows than suffixes), the bad
    tenant's counter jump surfaces at ITS exact lane, and every clean
    tenant sharing those windows stays fully valid."""
    tr = traffic.make_traffic(
        n_tenants=6, rounds=1, suffix_len=6, bad_lane_every=3, seed=4,
    )
    svc = _service(tr)
    _drive(svc, tr)
    assert svc.windows < 6  # windows were genuinely shared
    bad = {s.tenant_id for s in tr.tenants if s.bad_lane is not None}
    assert bad  # the mix really contains failure lanes
    for spec in tr.tenants:
        (row,) = _verdict_rows(svc, tr)[spec.tenant_id]
        if spec.tenant_id in bad:
            # first-failure: the valid prefix stops AT the bad lane
            assert row[1] == spec.bad_lane
            assert row[2].startswith("CounterOverIncrementedOCERT")
        else:
            assert row[1] == 6 and row[2] is None


# ---------------------------------------------------------------------------
# fairness
# ---------------------------------------------------------------------------


def test_quantum_fill_big_backlog_cannot_starve_same_shape(stub_crypto):
    """Same-shape fairness: the rotating quantum fill shares each
    window, so three 8-header tenants finish in two 16-lane windows
    even though a 64-header suffix is pending the whole time."""
    small = traffic.make_traffic(n_tenants=3, rounds=1, suffix_len=8,
                                 seed=5)
    big = traffic.make_traffic(n_tenants=4, rounds=1, suffix_len=64,
                               seed=5)
    svc = _service(small, max_window=16)
    big_sfx = big.next_suffix(big.tenants[3])  # peer-003: same shape
    svc.submit(big_sfx.tenant_id, big_sfx.hvs)
    for sfx in small.suffixes():
        svc.submit(sfx.tenant_id, sfx.hvs)
    assert svc.pump() and svc.pump()
    for spec in small.tenants:
        assert len(svc.verdicts(spec.tenant_id)) == 1  # smalls resolved
    assert not svc.verdicts("peer-003")  # the backlog is still pending
    svc.run_until_drained()
    (row,) = [v.row() for v in svc.verdicts("peer-003")]
    assert row[1] == 64 and row[2] is None


def test_cold_shape_cannot_starve_warm_tenants(stub_crypto):
    """Cross-shape fairness: a cold tenant with an alien window shape
    (different body length -> different compiled program) rides its
    own rung-capped windows under the shape rotation; the warm
    tenants' traffic completes within a bounded number of pumps."""
    warm = traffic.make_traffic(n_tenants=2, rounds=1, suffix_len=8,
                                seed=3)
    cold = traffic.make_traffic(n_tenants=3, rounds=1, suffix_len=64,
                                body_len=96, seed=3)
    svc = _service(warm, max_window=16)
    cold_sfx = cold.next_suffix(cold.tenants[2])
    svc.submit(cold_sfx.tenant_id, cold_sfx.hvs)  # cold arrives FIRST
    for sfx in warm.suffixes():
        svc.submit(sfx.tenant_id, sfx.hvs)
    for _ in range(4):
        svc.pump()
    for spec in warm.tenants:
        assert len(svc.verdicts(spec.tenant_id)) == 1, (
            "warm tenant starved behind the cold shape"
        )
    svc.run_until_drained()
    (row,) = [v.row() for v in svc.verdicts("peer-002")]
    assert row[1] == 64 and row[2] is None
    # both shapes retired windows of their own
    assert svc.windows >= 5


# ---------------------------------------------------------------------------
# admission
# ---------------------------------------------------------------------------


def _shape():
    return admission.WindowShape(proof_len=80, body_len=64)


def test_admission_rung_ladder_escalates_one_rung_per_warm_window():
    pol = admission.AdmissionPolicy(rungs=(8, 16))
    shape = _shape()
    pol.note_window(shape, 8)  # bucket 8 earned
    d = pol.admit(shape, 32)
    assert d.mode == "rung" and d.lane_cap == 16  # one rung up
    pol.note_window(shape, 16)
    d = pol.admit(shape, 32)
    assert d.mode == "rung" and d.lane_cap == 32  # ladder top reached
    pol.note_window(shape, 32)
    d = pol.admit(shape, 32)
    assert d.mode == "warm" and d.lane_cap == 32
    assert pol.decisions == {"warm": 1, "rung": 2, "host": 0}


def test_admission_kill_switch_prices_nothing(monkeypatch):
    monkeypatch.setenv("OCT_SERVE_DEVICE", "0")
    d = admission.AdmissionPolicy().admit(_shape(), 12)
    assert d.mode == "host" and d.lane_cap == 12
    assert d.predicted_wall_s is None


def test_admission_refuses_malformed_at_the_door(stub_crypto):
    tr = traffic.make_traffic(n_tenants=2, rounds=1, suffix_len=4, seed=1)
    hvs = list(tr.next_suffix(tr.tenants[0]).hvs)
    with pytest.raises(admission.AdmissionRefused, match="empty"):
        admission.shape_of("t", [])
    bc = traffic.make_traffic(n_tenants=2, rounds=1, suffix_len=4,
                              bc_every=2, seed=1)
    mixed = hvs[:2] + list(bc.next_suffix(bc.tenants[1]).hvs)[:2]
    with pytest.raises(admission.AdmissionRefused, match="proof formats"):
        admission.shape_of("t", mixed)
    with pytest.raises(admission.AdmissionRefused, match="non-increasing"):
        admission.shape_of("t", [hvs[1], hvs[0]])
    # the service: refusal surfaces to the caller, counts, touches nothing
    svc = _service(tr)
    with pytest.raises(admission.AdmissionRefused):
        svc.submit("peer-000", [hvs[1], hvs[0]])
    assert svc.slo_snapshot()["queue_depth"] == 0
    assert svc._m_suffixes.labels(result="refused").value == 1


# ---------------------------------------------------------------------------
# the OCT_SERVE_DEVICE=0 lever: must actually reroute, on REAL crypto
# ---------------------------------------------------------------------------

_REAL_PARAMS = praos.PraosParams(
    slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
    active_slot_coeff=__import__("fractions").Fraction(1, 2),
    epoch_length=500, kes_depth=3,
)


def test_lever_reroutes_to_host_fold_real_crypto(monkeypatch):
    """OCT_SERVE_DEVICE=0 regression pin: the device window path is
    NEVER entered (prepare_window is trapped), every window retires
    mode="host", and the host-fold verdicts equal the sequential
    praos.update reference — on REAL crypto, because the host
    reference fold uses the real host verifiers (stub traffic cannot
    reach this floor)."""
    pools = [fixtures.make_pool(i, kes_depth=3) for i in range(3)]
    lview = fixtures.make_ledger_view(pools)
    eta0 = b"\x07" * 32
    chains: dict[str, list] = {"peer-a": [], "peer-b": []}
    slot = 1
    while any(len(c) < 3 for c in chains.values()):
        pool = fixtures.find_leader(_REAL_PARAMS, pools, lview, slot, eta0)
        if pool is not None:
            tid = min(chains, key=lambda t: len(chains[t]))
            if len(chains[tid]) < 3:
                chains[tid].append(fixtures.forge_header_view(
                    _REAL_PARAMS, pool, slot=slot, epoch_nonce=eta0,
                    prev_hash=None, body_bytes=b"b%07d" % slot,
                ))
        slot += 1

    def _trap(*a, **kw):
        raise AssertionError("device path entered with the lever down")

    monkeypatch.setenv("OCT_SERVE_DEVICE", "0")
    monkeypatch.setattr(pbatch, "prepare_window", _trap)
    reg = MetricsRegistry()
    svc = serve.ValidationService(_REAL_PARAMS, lview, eta0,
                                  registry=reg, max_window=8)
    for tid, hvs in chains.items():
        svc.submit(tid, hvs)
    svc.run_until_drained()
    for tid, hvs in chains.items():
        ticked = praos.tick(_REAL_PARAMS, lview, hvs[0].slot,
                            praos.PraosState(epoch_nonce=eta0))
        st, n, err = hvs[0], 0, None
        state = ticked.state
        for i, hv in enumerate(hvs):
            try:
                state = praos.update(
                    _REAL_PARAMS, hv, hv.slot,
                    praos.TickedPraosState(state, lview))
                n = i + 1
            except praos.PraosValidationError as e:
                err = e
                break
        (row,) = [v.row() for v in svc.verdicts(tid)]
        assert row == [0, n, serve._canon_error(err)]
        if err is None:
            assert recovery.encode_state(svc.tenants[tid].state) \
                == recovery.encode_state(state)
    # the reroute is visible on the metrics surface, not just implied
    fam = svc._m_windows
    assert fam.labels(mode="host").value == svc.windows > 0
    assert svc.slo_snapshot()["device_serving"] is False


# ---------------------------------------------------------------------------
# chaos: device-error@serve-dispatch degrades, never drops
# ---------------------------------------------------------------------------


def test_device_error_sheds_to_ladder_byte_identical(stub_crypto,
                                                     monkeypatch):
    """A device fault at the serving dispatch seam: the faulted
    window's segments shed down the recovery ladder, every affected
    tenant still gets byte-identical verdicts, the service keeps
    serving, and the degraded interval opens AND closes on the SLO
    surface."""
    cfg = dict(n_tenants=5, rounds=2, suffix_len=6, bc_every=4,
               bad_lane_every=3, seed=9)
    base_tr = traffic.make_traffic(**cfg)
    base = _service(base_tr)
    _drive(base, base_tr)
    base_rows = _verdict_rows(base, base_tr)

    monkeypatch.setenv("OCT_CHAOS", "device-error@serve-dispatch:1")
    chaos.reset()
    tr = traffic.make_traffic(**cfg)
    svc = _service(tr)
    _drive(svc, tr)
    monkeypatch.delenv("OCT_CHAOS")
    chaos.reset()

    assert chaos.plan() is None  # leave the process disarmed
    assert _verdict_rows(svc, tr) == base_rows
    assert _final_states(svc, tr) == _final_states(base, base_tr)
    snap = svc.slo_snapshot()
    assert snap["degraded"] is False  # recovered: the flag came back
    (iv,) = snap["degraded_intervals"]
    t_open, t_close, klass = iv
    assert t_close is not None and t_close >= t_open
    assert klass == "DeviceChaosError"
    assert svc._m_degraded.value == 0
    assert snap["suffixes_done"] == 10 and snap["queue_depth"] == 0


# ---------------------------------------------------------------------------
# chaos: a REAL SIGKILL mid-traffic, relaunch with per-tenant carry resume
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["OCT_REPO"])
from ouroboros_consensus_tpu.node import serve
from ouroboros_consensus_tpu.obs.registry import MetricsRegistry
from ouroboros_consensus_tpu.testing import stubs, traffic

stubs.install_stub_crypto(None)
tr = traffic.make_traffic(n_tenants=4, rounds=2, suffix_len=6,
                          bad_lane_every=3, seed=7)
svc = serve.ValidationService(
    tr.params, tr.lview, tr.eta0,
    registry=MetricsRegistry(), max_window=8,
)
for sfx in tr.suffixes():
    svc.submit(sfx.tenant_id, sfx.hvs)
svc.run_until_drained()
out = {
    "resumed": svc.resumed,
    "windows": svc.windows,
    "verdicts": {s.tenant_id: [v.row() for v in svc.verdicts(s.tenant_id)]
                 for s in tr.tenants},
}
with open(os.environ["OCT_TEST_OUT"], "w") as f:
    json.dump(out, f)
"""


def test_sigkill_mid_traffic_resumes_per_tenant_carry(tmp_path):
    """sigkill@serve:N kills the service AFTER a window's checkpoint
    landed; the relaunch resumes every tenant's fold state, the seeded
    traffic re-submits byte-identically (already-banked suffixes
    fast-forward) and the combined verdicts equal an uninterrupted
    run's."""

    def run_child(extra_env):
        out = str(tmp_path / f"out_{len(os.listdir(tmp_path))}.json")
        env = dict(os.environ)
        for k in ("OCT_CHAOS", "OCT_SERVE_CHECKPOINT", "OCT_SERVE_DEVICE"):
            env.pop(k, None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "OCT_REPO": REPO,
            "OCT_TEST_OUT": out,
        })
        env.update(extra_env)
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              cwd=REPO, capture_output=True, timeout=300)
        return proc, out

    ck = str(tmp_path / "serve_ck.json")
    # 1. the uninterrupted reference
    proc, ref_out = run_child({})
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    ref = json.load(open(ref_out))
    assert sum(len(v) for v in ref["verdicts"].values()) == 8

    # 2. the killed child: SIGKILL after a mid-run window's checkpoint
    proc, _ = run_child({
        "OCT_SERVE_CHECKPOINT": ck,
        "OCT_CHAOS": "sigkill@serve:2",
    })
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr.decode()[-2000:]
    )
    doc = serve.read_serve_checkpoint(ck)
    assert doc is not None and doc["windows"] == 3
    banked = sum(len(t["verdicts"]) for t in doc["tenants"].values())
    assert banked < 8  # genuinely mid-traffic

    # 3. the relaunch: carry resume + fast-forward == the reference
    proc, res_out = run_child({"OCT_SERVE_CHECKPOINT": ck})
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    res = json.load(open(res_out))
    assert res["resumed"] is True
    assert res["verdicts"] == ref["verdicts"]
    assert res["windows"] >= doc["windows"]


def test_checkpoint_read_is_fail_closed(tmp_path, stub_crypto):
    tr = traffic.make_traffic(n_tenants=2, rounds=1, suffix_len=4, seed=2)
    ck = str(tmp_path / "ck.json")
    svc = _service(tr, checkpoint=ck)
    _drive(svc, tr)
    doc = serve.read_serve_checkpoint(ck)
    assert doc is not None and doc["windows"] == svc.windows
    # a flipped byte anywhere -> the whole record is refused
    tampered = dict(doc)
    tampered["windows"] = doc["windows"] + 1
    with open(ck, "w") as f:
        json.dump(tampered, f)
    assert serve.read_serve_checkpoint(ck) is None
    with open(ck, "w") as f:
        f.write("{not json")
    assert serve.read_serve_checkpoint(ck) is None
    assert serve.read_serve_checkpoint(str(tmp_path / "absent.json")) is None
    # a refused checkpoint means a FRESH start, never a wrong re-seed
    svc2 = _service(tr, checkpoint=ck)
    assert svc2.resumed is False


# ---------------------------------------------------------------------------
# the live SLO surface
# ---------------------------------------------------------------------------


def test_slo_endpoint_serves_live_snapshot(stub_crypto):
    from ouroboros_consensus_tpu.obs import server as obs_server

    tr = traffic.make_traffic(n_tenants=3, rounds=1, suffix_len=5, seed=6)
    reg = MetricsRegistry()
    svc = _service(tr, registry=reg)
    srv = obs_server.MetricsServer(registry=reg,
                                   slo_doc=svc.slo_snapshot)
    try:
        _drive(svc, tr)
        url = f"http://127.0.0.1:{srv.port}"
        doc = json.load(urllib.request.urlopen(f"{url}/slo"))
        assert doc["kind"] == "oct-serve-slo"
        assert doc["headers"] == 15 and doc["queue_depth"] == 0
        assert doc["verdict_latency_p50_s"] is not None
        assert doc["verdict_latency_p99_s"] is not None
        assert doc["headers_per_s"] > 0
        assert doc["degraded"] is False
        # the scrape itself is counted on the shared registry
        txt = urllib.request.urlopen(f"{url}/metrics").read().decode()
        assert 'oct_metrics_scrapes_total{path="/slo"} 1' in txt
        assert "oct_serve_headers_total 15" in txt
        # unmounted twin: /slo without a serving plane is a 404
        bare = obs_server.MetricsServer(registry=MetricsRegistry())
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{bare.port}/slo")
            assert ei.value.code == 404
        finally:
            bare.close()
    finally:
        srv.close()
