"""The durable-store robustness plane (PR 13): crash-consistent open
with truncate-and-repair, the lock/marker/clean-shutdown protocol, and
the torn-write fault matrix.

The headline differential: every seeded corruption — torn write, chunk
bitflip, index truncation, partial marker rename, stale lock, wrong
magic, dirty shutdown — either repairs to a replay verdict- and
nonce-carry-identical to the uninterrupted pristine-prefix run, or
refuses with a classified reason. Never a crash, hang, or silently
wrong verdict; repair actions visible as `oct_repair_total` + warmup
`repairs` rows; and a REAL SIGKILL'd writer child reopens dirty,
deep-validates, repairs, and RESUMES to the byte-identical chain."""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.node import exit as node_exit
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.obs.warmup import WARMUP
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.storage import guard as sg
from ouroboros_consensus_tpu.storage import sidecar as sc_mod
from ouroboros_consensus_tpu.storage.immutable import ImmutableDB
from ouroboros_consensus_tpu.testing import chaos, fixtures
from ouroboros_consensus_tpu.tools import db_analyser as ana
from ouroboros_consensus_tpu.tools import db_synthesizer as synth
from ouroboros_consensus_tpu.tools import db_truncater as trunc
from ouroboros_consensus_tpu.utils.fs import MockFS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    for var in ("OCT_CHAOS", "OCT_CHAOS_SEED", "OCT_CHECKPOINT",
                "OCT_RESUME", "OCT_RECOVERY", "OCT_TRACE"):
        monkeypatch.delenv(var, raising=False)
    chaos.reset()
    yield
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    chaos.reset()


def _params():
    # small epochs, chunk_size == epoch_length: several chunks so the
    # chunk-addressed faults and stranded-chunk drops have targets
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=60,
        kes_depth=3,
    )


PARAMS = _params()
POOL = fixtures.make_pool(11, kes_depth=3)
LVIEW = fixtures.make_ledger_view([POOL])
N_BLOCKS = 40


def _synthesize(path, fault: str | None = None):
    """Forge the deterministic 40-block chain; with `fault`, arm the
    chaos spec for the duration and report how the writer died (None =
    it survived — silent faults like bitflip)."""
    shutil.rmtree(path, ignore_errors=True)
    died = None
    if fault:
        os.environ["OCT_CHAOS"] = fault
        chaos.reset()
    try:
        synth.synthesize(path, PARAMS, [POOL], LVIEW,
                         synth.ForgeLimit(blocks=N_BLOCKS),
                         chunk_size=PARAMS.epoch_length)
    except chaos.ChaosError as e:
        died = e
    finally:
        if fault:
            os.environ.pop("OCT_CHAOS", None)
            chaos.reset()
    return died


@pytest.fixture(scope="module")
def pristine(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("repair") / "pristine")
    assert _synthesize(path) is None
    return path


def _reval(path, **kw):
    kw.setdefault("backend", "host")
    kw.setdefault("validate_all", False)
    return ana.revalidate(path, PARAMS, LVIEW, **kw)


@pytest.fixture(scope="module")
def pristine_states(pristine):
    """final PraosState of the uninterrupted replay at every prefix
    length — the matrix compares each repaired store's replay against
    the pristine prefix of the SAME length."""
    states = {0: praos.PraosState()}
    st = praos.PraosState()
    res = ana.ValidationResult()
    i = 0
    imm = ana.open_immutable(pristine)
    for hv in ana._stream_views(imm, res):
        ticked = praos.tick(PARAMS, LVIEW, hv.slot, st)
        st = praos.update(PARAMS, hv, hv.slot, ticked)
        i += 1
        states[i] = st
    assert i == N_BLOCKS
    return states


# ---------------------------------------------------------------------------
# protocol units: stale lock, live lock, wrong magic, markers
# ---------------------------------------------------------------------------


def test_live_lock_refuses_stale_lock_acquires(tmp_path):
    db = str(tmp_path / "db")
    os.makedirs(db)
    a = sg.DbLockFile(db)
    a.acquire()
    # a LIVE holder (separate open file description, same rules as a
    # second process) refuses loudly
    b = sg.DbLockFile(db)
    with pytest.raises(sg.DbLocked):
        b.acquire()
    a.release()
    # the lock FILE is still on disk — stale. flock semantics: a dead
    # holder's lock is gone, the stale file must NOT wedge the restart
    assert os.path.exists(os.path.join(db, sg.DB_LOCK))
    b.acquire()
    b.release()


def test_mockfs_crash_releases_lock():
    fs = MockFS()
    fs.makedirs("db")
    a = sg.DbLockFile("db", fs=fs)
    a.acquire()
    with pytest.raises(sg.DbLocked):
        sg.DbLockFile("db", fs=fs).acquire()
    fs.crash(0.0)  # every holder died
    sg.DbLockFile("db", fs=fs).acquire()


def test_concurrent_revalidate_refuses_loudly(pristine):
    g = sg.StoreGuard(pristine, writer=False).open()
    try:
        with pytest.raises(sg.DbLocked):
            _reval(pristine)
    finally:
        g.close()
    # and the refusal is classified REFUSE — never laundered through
    # the recovery ladder
    assert node_exit.triage(sg.DbLocked("x")) is node_exit.Disposition.REFUSE
    assert not recovery.recoverable(sg.DbLocked("x"))


def test_wrong_magic_refuses_loudly(pristine):
    assert sg.read_db_marker(pristine) == sg.DEFAULT_MAGIC
    with pytest.raises(sg.DbMarkerMismatch):
        _reval(pristine, network_magic=999)
    assert (node_exit.triage(sg.DbMarkerMismatch("x"))
            is node_exit.Disposition.REFUSE)
    assert not recovery.recoverable(sg.DbMarkerMismatch("x"))
    # the right magic (and the default-accepting None) both open
    assert _reval(pristine, network_magic=sg.DEFAULT_MAGIC).error is None


def test_triage_dispositions():
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDBError

    D = node_exit.Disposition
    assert node_exit.triage(ImmutableDBError("corrupt")) is D.REPAIR
    assert not recovery.recoverable(ImmutableDBError("corrupt"))
    assert node_exit.triage(chaos.ChunkChaosError("io")) is D.RECOVER
    assert node_exit.triage(OSError("io")) is D.RECOVER
    assert node_exit.triage(TypeError("bug")) is D.PROPAGATE
    assert node_exit.to_exit_reason(sg.DbLocked("x")).name == "CONFIG_ERROR"
    assert node_exit.to_exit_reason(
        ImmutableDBError("x")).name == "DB_CORRUPTION"


def test_dirty_shutdown_escalates_and_heals(pristine_states, tmp_path):
    """A missing clean-shutdown marker escalates the validation policy
    to all-chunks + repair; the replay matches, and the orderly close
    writes the marker back — the NEXT open is clean again."""
    db = str(tmp_path / "db")
    _synthesize(db)
    sg.clear_clean_marker(db)
    r = _reval(db)
    assert r.opened_dirty and r.error is None and r.n_valid == N_BLOCKS
    assert r.repairs == {"dirty-open-escalated": 1}
    assert r.final_state == pristine_states[N_BLOCKS]
    assert sg.was_clean_shutdown(db)
    r2 = _reval(db)
    assert not r2.opened_dirty and r2.repairs is None


def test_dirty_escalation_never_stamps_assumed_magic(tmp_path):
    """A magic-agnostic open of an existing marker-less store that
    escalates to writer (dirty open) must NOT create the default
    marker — the store's true chain is unknown, and stamping mainnet
    would refuse its real magic forever."""
    db = str(tmp_path / "db")
    _synthesize(db)
    os.remove(os.path.join(db, sg.DB_MARKER))
    sg.clear_clean_marker(db)
    r = _reval(db)  # network_magic=None, dirty -> promoted to writer
    assert r.opened_dirty and r.error is None and r.n_valid == N_BLOCKS
    assert sg.read_db_marker(db) is None  # never branded
    # an explicit magic on a marker-less store MAY stamp (the caller
    # knows its chain): the writer path with a known magic
    _reval(db, validate_all=True, network_magic=7)
    assert sg.read_db_marker(db) == 7


def test_readonly_scan_of_virgin_path_is_side_effect_free(tmp_path):
    """A read-only analysis of an empty/typo'd db path must not create
    `immutable/` — that side effect would make the NEXT open see a
    marker-less non-first run and misclassify the untouched store as
    dirty (then stamp markers on a store nobody ever wrote)."""
    db = str(tmp_path / "virgin")
    os.makedirs(db)
    r1 = _reval(db)
    assert r1.n_valid == 0 and not r1.opened_dirty
    assert not os.path.exists(os.path.join(db, "immutable"))
    assert sg.read_db_marker(db) is None
    r2 = _reval(db)
    assert not r2.opened_dirty and r2.repairs is None


def test_capped_dirty_replay_stays_dirty(tmp_path):
    """A max_headers-capped stream replay of a DIRTY store validated
    only the chunks behind the cap — it must NOT stamp the clean
    marker (the escalation promised ALL chunks; bench's probe prefix
    proving a store clean would let silent rot past the cap ride every
    later shallow open). The next UNCAPPED open still revalidates,
    repairs, and only THEN heals the marker."""
    db = str(tmp_path / "db")
    _synthesize(db)
    sg.clear_clean_marker(db)
    r = _reval(db, validate_all="stream", max_headers=8)
    assert r.opened_dirty and r.error is None
    assert r.n_valid == 8  # the capped prefix only
    assert not sg.was_clean_shutdown(db)  # still dirty
    r2 = _reval(db, validate_all="stream")
    assert r2.opened_dirty and r2.n_valid == N_BLOCKS
    assert sg.was_clean_shutdown(db)  # the full walk heals


def test_error_aborted_dirty_stream_stays_dirty(tmp_path):
    """An uncapped stream replay of a DIRTY store that ABORTED at a
    validation error proved nothing about the chunks past the error —
    it must NOT stamp the clean marker (regression: any uncapped
    stream stamped it, so a torn tail past a protocol-invalid header
    would ride every later shallow open)."""
    db = str(tmp_path / "db")
    _synthesize(db)
    sg.clear_clean_marker(db)
    # a ledger view with the wrong pool set fails validation at the
    # first header: the stream never consumes the chunks behind it
    wrong = fixtures.make_ledger_view([fixtures.make_pool(99,
                                                          kes_depth=3)])
    r = ana.revalidate(db, PARAMS, wrong, backend="host",
                       validate_all="stream")
    assert r.opened_dirty and r.error is not None
    assert not sg.was_clean_shutdown(db)  # still dirty
    # the right view walks the whole chain and heals honestly
    r2 = _reval(db, validate_all="stream")
    assert r2.opened_dirty and r2.error is None
    assert sg.was_clean_shutdown(db)


# ---------------------------------------------------------------------------
# open-with-repair: quarantine, events, metric, dry-run
# ---------------------------------------------------------------------------


def _corrupt_tail(db, chunk=0, garbage=b"\x81\x18garbage-tail"):
    """Append unparseable garbage past the indexed end of a chunk (the
    classic torn-append shape, applied from outside)."""
    p = os.path.join(db, "immutable", f"{chunk:05d}.chunk")
    with open(p, "ab") as f:
        f.write(garbage)
    return len(garbage)


def test_open_with_repair_quarantines_and_counts(tmp_path):
    db = str(tmp_path / "db")
    _synthesize(db)
    n_garbage = _corrupt_tail(db, chunk=0)
    rec = obs.install()
    try:
        r = _reval(db, validate_all=True)
    finally:
        obs.uninstall()
    # the chunk-0 tail was cut, chunk 1 is now stranded (chain gap) and
    # dropped; everything snipped is QUARANTINED, not deleted
    assert r.error is None
    assert r.repairs["rebuild-index"] == 1  # index lagged the garbage
    assert r.repairs["truncate-chunk"] == 1
    qdir = os.path.join(db, "immutable", "quarantine")
    qfiles = os.listdir(qdir)
    assert any(f.startswith("00000.chunk.tail") for f in qfiles)
    qbytes = sum(
        os.path.getsize(os.path.join(qdir, f)) for f in qfiles
    )
    assert qbytes >= n_garbage
    # visible as oct_repair_total{action=} through the flight recorder
    fam = rec.registry.snapshot()["oct_repair_total"]
    by_action = {s["labels"]["action"]: s["value"]
                 for s in fam["samples"]}
    assert by_action.get("truncate-chunk", 0) >= 1
    assert by_action.get("rebuild-index", 0) >= 1
    # and as warmup `repairs` rows (the round-JSON / ledger story)
    rows = WARMUP.report()["repairs"]
    assert {row["action"] for row in rows} >= {
        "truncate-chunk", "rebuild-index",
    }
    assert all(row["applied"] for row in rows)


def test_unwritable_quarantine_refuses_repair(tmp_path):
    """Quarantine-never-delete is a REFUSAL, not best-effort: when the
    quarantine copy cannot be written (ENOSPC / unwritable dir — disk
    pressure is exactly when stores corrupt), the repair aborts with a
    classified `QuarantineError` BEFORE any destructive mutation, and
    the corrupt bytes stay on disk for the operator."""
    from ouroboros_consensus_tpu.storage.repair import QuarantineError

    db = str(tmp_path / "db")
    _synthesize(db)
    _corrupt_tail(db, chunk=0)
    imm_dir = os.path.join(db, "immutable")
    qdir = os.path.join(imm_dir, "quarantine")
    # a FILE where the quarantine dir must go: makedirs fails -> store
    # raises; cross-platform stand-in for an unwritable filesystem
    with open(qdir, "wb") as f:
        f.write(b"not a directory")
    before = {f: os.path.getsize(os.path.join(imm_dir, f))
              for f in os.listdir(imm_dir)}
    with pytest.raises(QuarantineError):
        _reval(db, validate_all=True)
    after = {f: os.path.getsize(os.path.join(imm_dir, f))
             for f in os.listdir(imm_dir)}
    assert after == before  # nothing destroyed, nothing truncated
    # classified REFUSE — never absorbed by the recovery ladder
    assert (node_exit.triage(QuarantineError("x"))
            is node_exit.Disposition.REFUSE)
    assert not recovery.recoverable(QuarantineError("x"))
    os.remove(qdir)
    r = _reval(db, validate_all=True)  # writable again: repair runs
    assert r.error is None and r.repairs["truncate-chunk"] == 1


def test_stranded_drop_reports_real_block_counts(tmp_path):
    """A chunk dropped before its entries were ever loaded (stranded
    past a truncation) reports the block count from its on-disk index
    — an operator triaging a drop-chunk row sees the real data loss,
    not 0."""
    db = str(tmp_path / "db")
    _synthesize(db)
    imm_dir = os.path.join(db, "immutable")
    # wholly corrupt chunk 0: unparseable bytes, index gone — the
    # reparse truncates it to empty and strands chunk 1
    with open(os.path.join(imm_dir, "00000.chunk"), "wb") as f:
        f.write(b"\xff" * 128)
    os.remove(os.path.join(imm_dir, "00000.index"))
    r = _reval(db, validate_all=True)
    assert r.error is None and r.n_valid == 0
    rows = [row for row in WARMUP.report()["repairs"]
            if row["action"] == "drop-chunk"]
    (row,) = rows
    assert row["chunk"] == 1
    assert row["dropped"] > 0  # from the on-disk index, never silent 0
    assert row["bytes_quarantined"] > 0


def test_dry_run_scan_touches_nothing(tmp_path):
    """ImmutableDB(repair=False): the identical scan computes every
    action in memory (applied=False) and the disk — markers included —
    stays byte-identical."""
    from ouroboros_consensus_tpu.storage.open import (
        default_check_integrity, default_check_integrity_batch,
    )

    db = str(tmp_path / "db")
    _synthesize(db)
    _corrupt_tail(db, chunk=0)
    imm_dir = os.path.join(db, "immutable")

    def snap():
        return {f: open(os.path.join(imm_dir, f), "rb").read()
                for f in sorted(os.listdir(imm_dir))
                if os.path.isfile(os.path.join(imm_dir, f))}

    before = snap()
    imm = ImmutableDB(
        imm_dir, check_integrity=default_check_integrity,
        validate_all=True,
        check_integrity_batch=default_check_integrity_batch,
        repair=False,
    )
    assert snap() == before  # byte-untouched
    assert not os.path.exists(os.path.join(imm_dir, "quarantine"))
    actions = {row["action"] for row in imm.repairs}
    assert "truncate-chunk" in actions
    assert all(not row["applied"] for row in imm.repairs)
    # the in-memory view still reflects the truncation it computed
    assert imm.n_blocks() < N_BLOCKS


# ---------------------------------------------------------------------------
# db_truncater: the repair CLI
# ---------------------------------------------------------------------------


def test_truncater_to_last_valid_dry_run_then_repair(tmp_path, capsys):
    db = str(tmp_path / "db")
    _synthesize(db)
    base = _reval(db, validate_all=True)
    assert base.error is None and base.n_valid == N_BLOCKS
    _corrupt_tail(db, chunk=1)  # tail of the LAST chunk: no stranding
    sizes_corrupted = _corrupted_sizes(db)

    trunc.main(["--db", db, "--to-last-valid", "--dry-run"])
    out1 = capsys.readouterr().out
    rep = json.loads(out1.splitlines()[0])
    assert not rep["applied"] and rep["actions"].get("truncate-chunk")
    assert "would repair" in out1
    # dry-run left the garbage in place
    assert _corrupted_sizes(db) == sizes_corrupted
    assert not os.path.exists(os.path.join(db, "immutable", "quarantine"))

    qdir = str(tmp_path / "jail")
    trunc.main(["--db", db, "--to-last-valid", "--quarantine-dir", qdir])
    out2 = capsys.readouterr().out
    rep = json.loads(out2.splitlines()[0])
    assert rep["applied"] and rep["actions"]["truncate-chunk"] == 1
    assert rep["blocks"] == N_BLOCKS
    assert os.listdir(qdir)  # the --quarantine-dir flag was honored
    # the repaired store replays clean and verdict-identical
    r = _reval(db, validate_all=True)
    assert r.error is None and r.n_valid == N_BLOCKS
    assert r.final_state == base.final_state


def test_truncater_refuses_virgin_path(tmp_path):
    """--to-last-valid / slot truncate of a nonexistent (typo'd) --db
    refuses loudly BEFORE any side effect — a writer-mode open would
    otherwise fabricate a clean default-magic store there and report
    the 'repair' a success."""
    missing = str(tmp_path / "typo")
    with pytest.raises(FileNotFoundError):
        trunc.repair(missing)
    with pytest.raises(FileNotFoundError):
        trunc.truncate(missing, 30)
    with pytest.raises(FileNotFoundError):
        _reval(missing, validate_all=True)  # writer-mode analyser too
    with pytest.raises(FileNotFoundError):
        _reval(missing, repair=True)
    assert not os.path.exists(missing)  # nothing fabricated


def _corrupted_sizes(db):
    d = os.path.join(db, "immutable")
    return {f: os.path.getsize(os.path.join(d, f))
            for f in sorted(os.listdir(d))
            if os.path.isfile(os.path.join(d, f))}


def test_truncate_after_slot_mode_unchanged(tmp_path, capsys):
    db = str(tmp_path / "db")
    _synthesize(db)
    trunc.main(["--db", db, "--truncate-after-slot", "30"])
    out = capsys.readouterr().out
    assert "truncated;" in out
    r = _reval(db, validate_all=True)
    assert r.error is None and 0 < r.n_valid < N_BLOCKS


# ---------------------------------------------------------------------------
# db_analyser --repair: stream-mode write-back
# ---------------------------------------------------------------------------


def test_stream_repair_writeback_differential(pristine_states, tmp_path):
    """Read-only stream mode truncates the VERDICT only; with
    repair=True the same truncation lands on disk (quarantined), and
    both replays are verdict-identical to the pristine prefix."""
    db = str(tmp_path / "db")
    died = _synthesize(db, "bitflip@append:20")
    assert died is None  # silent rot: the writer never knew
    assert sg.was_clean_shutdown(db)

    sizes_before = _corrupted_sizes(db)
    r1 = _reval(db, validate_all="stream")
    assert r1.error is None and r1.n_valid == 20
    assert r1.final_state == pristine_states[20]
    assert r1.repairs is None  # read-only analysis
    assert _corrupted_sizes(db) == sizes_before  # disk untouched

    r2 = _reval(db, validate_all="stream", repair=True)
    assert r2.error is None and r2.n_valid == 20
    assert r2.final_state == pristine_states[20]
    assert r2.repairs and r2.repairs.get("truncate-chunk") == 1
    assert os.listdir(os.path.join(db, "immutable", "quarantine"))

    # the repaired store now passes a FULL deep open clean
    r3 = _reval(db, validate_all=True)
    assert r3.error is None and r3.n_valid == 20
    assert r3.repairs is None
    assert r3.final_state == pristine_states[20]


# ---------------------------------------------------------------------------
# the corruption matrix (tier-1: bounded fault x policy grid)
# ---------------------------------------------------------------------------

# (fault spec, policies) — whether the writer survives and how many
# blocks must survive repair is derived, not hard-coded: the pristine
# replay of the SAME prefix is the oracle. bitflip is placed by append
# order (mid-chain) so every policy that deep-checks catches it; under
# the shallow policy it is placed in the LAST chunk, which even a
# most-recent-chunk open CRC-walks.
_MATRIX = [
    ("torn-write@append:10", [False, True, "stream"]),
    ("index-truncate@epoch:1", [False, "stream"]),
    ("bitflip@append:20", [True, "stream"]),
    ("partial-rename@marker", [False, "stream"]),
    ("sigkill@append:15", [False]),
    # the columnar-sidecar plane (PR 17): a torn sidecar build is
    # SILENT (the chain is intact; only the cache is half-written), a
    # SIGKILL mid-build leaves a dirty store + a stranded .cols.tmp
    ("sidecar-torn@build:1", [False, "stream"]),
    ("sigkill@build:1", [False]),
    # the forge pipeline (PR 18): a SIGKILL between a forged block's
    # retire and the next — the append fully flushed, only the clean
    # marker is missing, and the batched resume must converge
    ("sigkill@forge:10", [False]),
]


def _matrix_cell(tmp_path, pristine_states, fault, policy):
    db = str(tmp_path / "db")
    if fault.startswith("sigkill"):
        # a REAL kill needs a child process (below); in-process matrix
        # cells arm the raise/rot faults only
        _writer_child(db, fault)
    else:
        _synthesize(db, fault)
    r = _reval(db, validate_all=policy)
    assert r.error is None, (fault, policy, r.error)
    # the repaired store's replay IS the pristine prefix: same verdict
    # count, same nonce carry, same counters
    assert r.final_state == pristine_states[r.n_valid], (fault, policy)
    # dirty-open escalation fired for every fault that killed a writer
    if fault.split("@")[0] in ("torn-write", "index-truncate",
                               "partial-rename", "sigkill"):
        assert r.opened_dirty, (fault, policy)
        assert r.repairs.get("dirty-open-escalated") == 1
        # ...and the store healed: the NEXT open is clean and equal
        r2 = _reval(db, validate_all=policy)
        assert not r2.opened_dirty
        assert r2.error is None and r2.n_valid == r.n_valid
        assert r2.final_state == r.final_state
    return r


@pytest.mark.parametrize("fault,policy", [
    (f, p) for f, policies in _MATRIX for p in policies[:1]
])
def test_corruption_matrix_tier1(tmp_path, pristine_states, fault, policy):
    """One policy per fault kind rides tier-1; the full grid is the
    slow-tier sweep below."""
    _matrix_cell(tmp_path, pristine_states, fault, policy)


@pytest.mark.slow
@pytest.mark.parametrize("fault,policy", [
    (f, p) for f, policies in _MATRIX for p in policies[1:]
])
def test_corruption_matrix_deep_sweep(tmp_path, pristine_states, fault,
                                      policy):
    _matrix_cell(tmp_path, pristine_states, fault, policy)


def test_bitflip_last_chunk_caught_even_shallow(tmp_path, pristine_states):
    """The most-recent-chunk policy always CRC-walks the last chunk:
    silent rot there is caught on a plain open even after a clean
    shutdown. The shallow open is a READER: the truncation is computed
    in memory (applied=False forensics, verdict still the pristine
    prefix) and the disk stays byte-untouched until an explicit repair
    lever. (Rot in OLDER chunks under the shallow policy is the
    documented trust trade-off — COVERAGE.md §5.17.)"""
    db = str(tmp_path / "db")
    assert _synthesize(db, "bitflip@append:35") is None
    assert sg.was_clean_shutdown(db)
    sizes = _corrupted_sizes(db)
    r = _reval(db, validate_all=False)
    assert r.error is None and r.n_valid == 35
    assert r.final_state == pristine_states[35]
    assert r.repairs is None  # a reader APPLIES nothing...
    assert _corrupted_sizes(db) == sizes  # ...and writes nothing
    rows = [row for row in WARMUP.report()["repairs"]
            if row["action"] == "truncate-chunk"]
    assert rows and not rows[0]["applied"]  # the would-repair is banked
    # the deep (writer) open DOES land it on disk
    r2 = _reval(db, validate_all=True)
    assert r2.repairs and r2.repairs.get("truncate-chunk") == 1
    assert r2.final_state == pristine_states[35]


# ---------------------------------------------------------------------------
# the columnar sidecar as a repair-plane citizen (PR 17)
# ---------------------------------------------------------------------------


def test_sidecar_torn_at_build_falls_back_then_rebuilds(
        tmp_path, pristine_states):
    """A torn sidecar BUILD (crash shape: a prefix at the final name)
    is silent — the chain is complete and clean. The probe classifies
    it `torn`, the replay parses and stays verdict-identical; the
    first WRITER open rebuilds the seal and the next replay hits."""
    db = str(tmp_path / "db")
    assert _synthesize(db, "sidecar-torn@build:1") is None
    assert sg.was_clean_shutdown(db)
    torn = os.path.join(db, "immutable", "00001.cols")
    assert os.path.exists(torn)
    torn_size = os.path.getsize(torn)

    sc_mod.reset_counters()
    r1 = _reval(db, validate_all="stream")  # read-only analysis
    c = sc_mod.counters()
    assert c["hit"] == 1 and c["torn"] == 1 and c["rebuilt"] == 0
    assert r1.error is None and r1.n_valid == N_BLOCKS
    assert r1.final_state == pristine_states[N_BLOCKS]
    assert os.path.getsize(torn) == torn_size  # reader wrote nothing

    sc_mod.reset_counters()
    r2 = _reval(db, validate_all="stream", repair=True)
    c = sc_mod.counters()
    assert c["torn"] == 1 and c["rebuilt"] == 1
    assert os.path.getsize(torn) > torn_size  # sealed blob landed

    sc_mod.reset_counters()
    r3 = _reval(db, validate_all="stream")
    assert sc_mod.counters()["hit"] == 2
    for r in (r2, r3):
        assert r.error is None and r.n_valid == N_BLOCKS
        assert r.final_state == r1.final_state


def test_sidecar_stale_at_open_forces_fallback(tmp_path, pristine_states):
    """`sidecar-stale@open:0` forces the probe's stale verdict on a
    PERFECTLY fresh sidecar: the fallback parse must never change a
    verdict — that is the whole trust contract."""
    db = str(tmp_path / "db")
    assert _synthesize(db) is None
    os.environ["OCT_CHAOS"] = "sidecar-stale@open:0"
    chaos.reset()
    try:
        sc_mod.reset_counters()
        r = _reval(db, validate_all="stream")
        assert chaos.plan().fired() == ["sidecar-stale@open:0"]
    finally:
        os.environ.pop("OCT_CHAOS", None)
        chaos.reset()
    c = sc_mod.counters()
    assert c["stale"] == 1 and c["hit"] == 1
    assert r.error is None and r.n_valid == N_BLOCKS
    assert r.final_state == pristine_states[N_BLOCKS]
    # unarmed, the same store is all hits and still equal
    sc_mod.reset_counters()
    r2 = _reval(db, validate_all="stream")
    assert sc_mod.counters()["hit"] == 2
    assert r2.final_state == r.final_state


def test_sidecar_bitflip_stale_never_trusted(tmp_path, pristine_states):
    """Silent rot INSIDE a sidecar (one flipped payload byte) breaks
    the payload CRC seal: probe stale, parse fallback, verdict
    untouched — and a writer open re-seals it."""
    db = str(tmp_path / "db")
    assert _synthesize(db) is None
    p = os.path.join(db, "immutable", "00000.cols")
    blob = bytearray(open(p, "rb").read())
    blob[sc_mod.HEADER_SIZE + 9] ^= 0x10
    with open(p, "wb") as f:
        f.write(bytes(blob))

    sc_mod.reset_counters()
    r = _reval(db, validate_all="stream")
    c = sc_mod.counters()
    assert c["stale"] == 1 and c["hit"] == 1
    assert r.error is None and r.n_valid == N_BLOCKS
    assert r.final_state == pristine_states[N_BLOCKS]

    sc_mod.reset_counters()
    _reval(db, validate_all="stream", repair=True)
    assert sc_mod.counters()["rebuilt"] == 1
    sc_mod.reset_counters()
    r2 = _reval(db, validate_all="stream")
    assert sc_mod.counters()["hit"] == 2
    assert r2.final_state == r.final_state


def test_truncater_invalidates_and_regenerates_sidecars(
        tmp_path, pristine_states):
    """`db_truncater --to-last-valid` on a garbage-tailed chunk: the
    rewrite quarantines the now-lying seal BEFORE mutating the chunk,
    and the repair pass regenerates a fresh one — the store comes out
    fully sidecared and verdict-identical."""
    db = str(tmp_path / "db")
    assert _synthesize(db) is None
    _corrupt_tail(db, chunk=1)  # last chunk: tail snip, no strand
    out = trunc.repair(db)
    assert out

    qdir = os.path.join(db, "immutable", "quarantine")
    assert any(f.startswith("00001.cols") for f in os.listdir(qdir))
    sc_mod.reset_counters()
    r = _reval(db, validate_all="stream")
    assert sc_mod.counters()["hit"] == 2  # both seals fresh again
    assert r.error is None and r.n_valid == N_BLOCKS
    assert r.final_state == pristine_states[N_BLOCKS]


def test_orphan_sidecars_swept(tmp_path, pristine_states):
    """A `.cols` without a chunk and a `.cols.tmp` stranded by a crash
    mid-build are derived data with no referent: a reader BANKS the
    would-sweep (`applied=False`), a writer open quarantines both as
    `sweep-orphan-sidecar` — never deletes, never trusts."""
    db = str(tmp_path / "db")
    assert _synthesize(db) is None
    d = os.path.join(db, "immutable")
    for name in ("00007.cols", "00000.cols.tmp"):
        with open(os.path.join(d, name), "wb") as f:
            f.write(b"\x00junk")

    r = _reval(db)  # reader: verdict only
    assert r.error is None and r.repairs is None
    assert os.path.exists(os.path.join(d, "00007.cols"))
    rows = [row for row in WARMUP.report()["repairs"]
            if row["action"] == "sweep-orphan-sidecar"]
    assert len(rows) == 2 and not any(row["applied"] for row in rows)

    r2 = _reval(db, validate_all=True)  # writer: lands on disk
    assert r2.repairs.get("sweep-orphan-sidecar") == 2
    assert not os.path.exists(os.path.join(d, "00007.cols"))
    assert not os.path.exists(os.path.join(d, "00000.cols.tmp"))
    qfiles = os.listdir(os.path.join(d, "quarantine"))
    assert any(f.startswith("00007.cols") for f in qfiles)
    assert any(f.startswith("00000.cols.tmp") for f in qfiles)
    assert r2.error is None and r2.n_valid == N_BLOCKS
    assert r2.final_state == pristine_states[N_BLOCKS]


def test_sigkilled_sidecar_build_sweeps_tmp_on_reopen(
        tmp_path, pristine_states):
    """A REAL SIGKILL mid-sidecar-build (rc=-9, after the chain + index
    flushed, before the clean marker): the store reopens DIRTY with a
    stranded `00001.cols.tmp`, sweeps it as `sweep-orphan-sidecar`,
    back-fills the missing seal on the same (forced-repair) open, and
    replays verdict-identical to the pristine chain."""
    db = str(tmp_path / "db")
    _writer_child(db, "sigkill@build:1")
    assert not sg.was_clean_shutdown(db)
    tmp = os.path.join(db, "immutable", "00001.cols.tmp")
    assert os.path.exists(tmp)

    sc_mod.reset_counters()
    r = _reval(db, validate_all="stream")
    assert r.opened_dirty and r.error is None
    assert r.n_valid == N_BLOCKS  # every block had landed
    assert r.final_state == pristine_states[N_BLOCKS]
    assert r.repairs.get("dirty-open-escalated") == 1
    assert r.repairs.get("sweep-orphan-sidecar") == 1
    assert not os.path.exists(tmp)  # quarantined, not deleted
    qfiles = os.listdir(os.path.join(db, "immutable", "quarantine"))
    assert any(f.startswith("00001.cols.tmp") for f in qfiles)
    assert sc_mod.counters()["rebuilt"] == 1  # forced repair backfills
    assert sg.was_clean_shutdown(db)  # healed

    sc_mod.reset_counters()
    r2 = _reval(db, validate_all="stream")
    assert not r2.opened_dirty and sc_mod.counters()["hit"] == 2
    assert r2.final_state == r.final_state


# ---------------------------------------------------------------------------
# the real thing: a SIGKILL'd WRITER child reopens dirty, repairs, resumes
# ---------------------------------------------------------------------------

_WRITER_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["OCT_REPO"])
from fractions import Fraction
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import db_synthesizer as synth

params = praos.PraosParams(
    slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
    active_slot_coeff=Fraction(1, 2), epoch_length=60, kes_depth=3,
)
pool = fixtures.make_pool(11, kes_depth=3)
lv = fixtures.make_ledger_view([pool])
synth.synthesize(os.environ["OCT_TEST_DB"], params, [pool], lv,
                 synth.ForgeLimit(blocks=40), chunk_size=60,
                 resume=os.environ.get("OCT_TEST_RESUME") == "1")
"""


def _writer_child(db, fault=None, resume=False):
    env = dict(os.environ)
    env.pop("OCT_CHAOS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "OCT_REPO": REPO,
        "OCT_TEST_DB": db,
        "OCT_TEST_RESUME": "1" if resume else "0",
    })
    if fault:
        env["OCT_CHAOS"] = fault
    proc = subprocess.run([sys.executable, "-c", _WRITER_CHILD], env=env,
                          cwd=REPO, capture_output=True, timeout=300)
    if fault and fault.startswith("sigkill"):
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stderr.decode()[-2000:]
        )
    else:
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc


def test_sigkilled_writer_reopens_dirty_repairs_resumes(
        pristine, pristine_states, tmp_path):
    """The acceptance headline: a REAL SIGKILL between a block's chunk
    append and its index append (rc=-9). The store reopens DIRTY,
    deep-validates, repairs the lagging index ON DISK, replays
    verdict-identical to the pristine prefix — and the resumed WRITER
    converges on the byte-identical full chain."""
    db = str(tmp_path / "db")
    _writer_child(db, "sigkill@append:15")
    assert not sg.was_clean_shutdown(db)  # died mid-forge: dirty

    # reopen: dirty -> all-chunks escalation -> index rebuilt from
    # chunk bytes (the 16th block's entry never hit the index)
    r = _reval(db)
    assert r.opened_dirty and r.error is None
    assert r.n_valid == 16  # the killed append's block was recovered
    assert r.repairs.get("dirty-open-escalated") == 1
    assert r.repairs.get("rebuild-index", 0) >= 1
    assert r.final_state == pristine_states[16]
    assert sg.was_clean_shutdown(db)  # healed

    # the writer RESUMES: deterministic forging converges on the
    # uninterrupted chain, byte for byte
    _writer_child(db, resume=True)
    r2 = _reval(db, validate_all=True)
    ref = _reval(pristine, validate_all=True)
    assert r2.error is None and r2.n_valid == N_BLOCKS
    assert r2.final_state == ref.final_state
    t_res = ana.open_immutable(db).tip()
    t_ref = ana.open_immutable(pristine).tip()
    assert (t_res.slot, t_res.hash_) == (t_ref.slot, t_ref.hash_)


def test_sigkilled_forge_child_resume_converges(
        pristine, pristine_states, tmp_path):
    """The batched-forge twin of the headline: a REAL SIGKILL at the
    `forge` seam (right after the 11th forged block's append+reupdate,
    before the clean marker). Unlike sigkill@append the store's last
    append fully flushed — the reopen is dirty but repair-free past the
    escalation — and the RESUMED writer re-enters mid-window through
    the batched pipeline (memoized trusted fold, fresh election sweep)
    and converges on the byte-identical uninterrupted chain."""
    db = str(tmp_path / "db")
    _writer_child(db, "sigkill@forge:10")
    assert not sg.was_clean_shutdown(db)  # died mid-forge: dirty

    r = _reval(db)
    assert r.opened_dirty and r.error is None
    assert r.n_valid == 11  # every append behind the kill had landed
    assert r.repairs.get("dirty-open-escalated") == 1
    assert r.repairs.get("rebuild-index", 0) == 0  # nothing was torn
    assert r.final_state == pristine_states[11]
    assert sg.was_clean_shutdown(db)  # healed

    _writer_child(db, resume=True)
    r2 = _reval(db, validate_all=True)
    ref = _reval(pristine, validate_all=True)
    assert r2.error is None and r2.n_valid == N_BLOCKS
    assert r2.final_state == ref.final_state
    t_res = ana.open_immutable(db).tip()
    t_ref = ana.open_immutable(pristine).tip()
    assert (t_res.slot, t_res.hash_) == (t_ref.slot, t_ref.hash_)


def test_resume_refused_without_flag(tmp_path):
    """The refusal is SIDE-EFFECT-FREE: an operator mistake may not
    dirty (or re-stamp) a healthy store."""
    db = str(tmp_path / "db")
    _synthesize(db)
    with pytest.raises(RuntimeError, match="non-empty DB"):
        synth.synthesize(db, PARAMS, [POOL], LVIEW,
                         synth.ForgeLimit(blocks=N_BLOCKS),
                         chunk_size=PARAMS.epoch_length)
    assert sg.was_clean_shutdown(db)  # still clean
    r = _reval(db)
    assert not r.opened_dirty and r.error is None


def test_refusal_probe_read_only_on_dirty_store(tmp_path):
    """The non-empty refusal on a DIRTY store (crashed writer, torn
    tail still on disk) must not repair under the reader guard: the
    probe open is repair=False, so the disk stays byte-identical and
    the store stays dirty for the next legitimate (resume / analyser)
    open to heal."""
    db = str(tmp_path / "db")
    died = _synthesize(db, fault="torn-write@append:15")
    assert died is not None and not sg.was_clean_shutdown(db)
    sizes = _corrupted_sizes(db)
    with pytest.raises(RuntimeError, match="non-empty DB"):
        synth.synthesize(db, PARAMS, [POOL], LVIEW,
                         synth.ForgeLimit(blocks=N_BLOCKS),
                         chunk_size=PARAMS.epoch_length)
    assert _corrupted_sizes(db) == sizes  # disk byte-untouched
    assert not sg.was_clean_shutdown(db)  # still dirty
    assert not os.path.exists(os.path.join(db, "immutable", "quarantine"))


def test_unparseable_marker_refuses_loudly(tmp_path):
    """A protocolMagicId that EXISTS but does not parse is corruption,
    not 'missing': every open refuses with a classified
    DbMarkerMismatch — a writer may not re-stamp (and a reader may not
    silently accept) a store whose chain identity is unknown."""
    db = str(tmp_path / "db")
    _synthesize(db)
    with open(os.path.join(db, sg.DB_MARKER), "wb") as f:
        f.write(b"not-a-magic\n")
    with pytest.raises(sg.DbMarkerMismatch):
        sg.read_db_marker(db)
    with pytest.raises(sg.DbMarkerMismatch):
        _reval(db)  # reader, no magic requested: still refuses
    with pytest.raises(sg.DbMarkerMismatch):
        _reval(db, network_magic=sg.DEFAULT_MAGIC)
    with pytest.raises(sg.DbMarkerMismatch):
        sg.StoreGuard(db, writer=True).open()  # never a raw ValueError


def test_truncate_after_slot_speaks_lock_protocol(tmp_path):
    """The legacy slot-addressed rewind mutates the store, so it holds
    the writer lock (concurrent open refuses) and leaves the store
    clean-marked on an orderly finish."""
    db = str(tmp_path / "db")
    _synthesize(db)
    g = sg.StoreGuard(db, writer=False).open()
    try:
        with pytest.raises(sg.DbLocked):
            trunc.truncate(db, 30)
    finally:
        g.close()
    assert 0 < trunc.truncate(db, 30) < N_BLOCKS
    assert sg.was_clean_shutdown(db)


def test_dirty_slot_truncate_runs_full_repair_walk(tmp_path,
                                                  pristine_states):
    """Slot-mode truncate of a DIRTY store may not stamp the clean
    marker after a most-recent-chunk open: rot in an OLDER chunk would
    then sit under a clean marker and the next open would bank a
    silently wrong verdict. A dirty open escalates to the full repair
    walk first (regression: the escalation was missing)."""
    db = str(tmp_path / "db")
    assert _synthesize(db, "bitflip@append:5") is None  # rot in chunk 0
    sg.clear_clean_marker(db)  # ...behind a crashed shutdown
    n = trunc.truncate(db, 10**9)  # keep-everything rewind
    assert n == 5  # the walk truncated at the rot, not at the slot
    assert sg.was_clean_shutdown(db)  # clean is honest: full walk ran
    r = _reval(db)
    assert not r.opened_dirty and r.error is None and r.n_valid == 5
    assert r.final_state == pristine_states[5]


def test_reader_open_never_stamps_a_marker(tmp_path):
    """An open of an existing store WITHOUT a marker must not brand it
    with an ASSUMED magic — reader or writer (a testnet DB analysed
    once would otherwise be mainnet forever). Only a caller that KNOWS
    its chain (explicit network_magic) stamps."""
    db = str(tmp_path / "db")
    _synthesize(db)
    os.remove(os.path.join(db, sg.DB_MARKER))
    r = _reval(db)  # shallow reader
    assert r.error is None
    assert sg.read_db_marker(db) is None  # nothing stamped
    r = _reval(db, validate_all=True)  # deep = writer, magic-agnostic
    assert r.error is None
    assert sg.read_db_marker(db) is None  # STILL nothing stamped
    r = _reval(db, validate_all=True, network_magic=42)  # known chain
    assert r.error is None
    assert sg.read_db_marker(db) == 42


# ---------------------------------------------------------------------------
# lint --changed: storage edits map onto the purity selection
# ---------------------------------------------------------------------------


def test_lint_changed_maps_storage_onto_purity_graphs():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_gate_repair", os.path.join(REPO, "scripts", "lint.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    purity = {"packed_unpack", "verdict_reduce", "spmd_sharded_verify"}
    assert purity <= set(lint._select_graphs(
        {"ouroboros_consensus_tpu/storage/immutable.py"}
    ))
    assert purity <= set(lint._select_graphs(
        {"ouroboros_consensus_tpu/storage/guard.py"}
    ))


# ---------------------------------------------------------------------------
# perf_report: repaired@<action> classification
# ---------------------------------------------------------------------------


def test_perf_report_classifies_repaired_rounds(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "perf_report_repair", os.path.join(REPO, "scripts",
                                           "perf_report.py")
    )
    pr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pr)
    doc = {
        "n": 13, "rc": 0,
        "parsed": {
            "value": 4100.0, "metric": "1,000,000-header replay",
            "opened_dirty": True,
            "warmup_report": {
                "repairs": [
                    {"action": "dirty-open-escalated", "applied": True},
                    {"action": "truncate-chunk", "applied": True},
                    {"action": "rebuild-index", "applied": False},
                ],
            },
        },
        "tail": "",
    }
    p = tmp_path / "BENCH_r13.json"
    p.write_text(json.dumps(doc))
    row = pr.analyze_bench_round(str(p))
    # dry-run rows never count; the primary action is the most
    # disk-invasive applied one
    assert row["repair_actions"] == {
        "dirty-open-escalated": 1, "truncate-chunk": 1,
    }
    assert row["repaired_action"] == "truncate-chunk"
    assert row["opened_dirty"] is True
    md = pr.render_markdown({
        "bench_rounds": [row], "multichip_rounds": [],
        "ledger": None, "verdicts": [],
    })
    assert "repaired@truncate-chunk" in md
    assert "## Repaired rounds" in md
