"""BlockFetch fetch-decision logic: FetchMode, in-flight de-dup, limits.

Reference: readFetchModeDefault (MiniProtocol/BlockFetch/
ClientInterface.hs:133-158) and the fetch governor's bulk-sync
de-duplication / in-flight limits.
"""

from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.miniprotocol import blockfetch, chainsync
from ouroboros_consensus_tpu.miniprotocol.blockfetch import (
    BULK_SYNC,
    DEADLINE,
    FetchRegistry,
    read_fetch_mode,
)
from ouroboros_consensus_tpu.miniprotocol.chainsync import Candidate
from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.sim import Channel, Sim

PARAMS = praos.PraosParams(
    slots_per_kes_period=10_000,
    max_kes_evolutions=62,
    security_param=100,
    active_slot_coeff=Fraction(1),
    epoch_length=100_000,
    kes_depth=2,
)
POOLS = [fixtures.make_pool(i, kes_depth=2) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)
ETA0 = b"\x22" * 32


def mk_node(tmp_path, name):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    db = open_chaindb(str(tmp_path / name), ext, st, PARAMS.security_param)
    return NodeKernel(name, db, protocol, ledger,
                      clock=SlotClock(slot_length=1.0))


def forge_chain(n):
    blocks, prev = [], None
    for i in range(n):
        b = forge_block(
            PARAMS, POOLS[i % 2], slot=i + 1, block_no=i,
            prev_hash=prev, epoch_nonce=ETA0,
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


# -- FetchRegistry ------------------------------------------------------------


def test_registry_claims_and_release():
    r = FetchRegistry()
    assert r.claim(b"h1", "a")
    assert not r.claim(b"h1", "b")  # already claimed by a
    assert r.claim(b"h1", "a")  # idempotent for the owner
    assert r.owner(b"h1") == "a"
    r.release(b"h1")
    assert r.claim(b"h1", "b")
    r.claim(b"h2", "b")
    r.release_peer("b")
    assert r.owner(b"h1") is None and r.owner(b"h2") is None


# -- read_fetch_mode ---------------------------------------------------------


def test_fetch_mode_by_slots_behind(tmp_path):
    node = mk_node(tmp_path, "fm")
    sim = Sim()
    node.chain_db.runtime = sim
    # empty chain at slot 0: 1 slot behind -> deadline
    sim.now = 0.0
    assert read_fetch_mode(node) == DEADLINE
    # empty chain at slot 2000: far behind -> bulk sync
    sim.now = 2000.0
    assert read_fetch_mode(node) == BULK_SYNC
    # chain tip close to now -> deadline
    for b in forge_chain(5):
        node.chain_db.add_block(b)
    sim.now = 6.0
    assert read_fetch_mode(node) == DEADLINE
    sim.now = 5 + 1500.0
    assert read_fetch_mode(node) == BULK_SYNC
    # CurrentSlotUnknown (no runtime clock) -> bulk sync
    node.chain_db.runtime = None
    assert read_fetch_mode(node) == BULK_SYNC


# -- bulk-sync de-duplication across two peers --------------------------------


def _count_blocks_served(msgs):
    return sum(1 for m in msgs if m[0] == "block")


def test_two_peers_same_candidate_fetches_one_copy(tmp_path):
    """Two peers offer the SAME candidate; in bulk-sync mode the
    registry de-duplicates: the union of served bodies covers the chain
    exactly once (each block downloaded from exactly one peer)."""
    server_a = mk_node(tmp_path, "sa")
    server_b = mk_node(tmp_path, "sb")
    client_node = mk_node(tmp_path, "cl")
    chain = forge_chain(30)
    for b in chain:
        server_a.chain_db.add_block(b)
        server_b.chain_db.add_block(b)

    sim = Sim()
    for n in (server_a, server_b, client_node):
        n.chain_db.runtime = sim
    sim.now = 0.0

    # candidates as ChainSync would leave them (full header chain)
    def mk_candidate():
        cand = Candidate()
        st = client_node.chain_dep_state_at(None)
        cand.reset(st)
        lview = LVIEW
        for blk in chain:
            ticked = client_node.protocol.tick(lview, blk.slot, cand.states[-1])
            cand.extend(
                blk.header,
                client_node.protocol.update(
                    blk.header.to_view(), blk.slot, ticked
                ),
            )
        return cand

    cand_a, cand_b = mk_candidate(), mk_candidate()

    served = {"a": 0, "b": 0}

    def counting_server(db, rx, tx, key):
        inner = blockfetch.server(db, rx, tx)
        # wrap Sends to count served bodies
        try:
            op = next(inner)
            while True:
                if (
                    hasattr(op, "chan")
                    and getattr(op, "msg", None) is not None
                    and op.msg[0] == "block"
                ):
                    served[key] += 1
                got = yield op
                op = inner.send(got)
        except StopIteration:
            return

    ra, wa = Channel(delay=0.01, name="a-req"), Channel(delay=0.01, name="a-rsp")
    rb, wb = Channel(delay=0.01, name="b-req"), Channel(delay=0.01, name="b-rsp")
    sim.spawn(counting_server(server_a.chain_db, ra, wa, "a"), "srv-a")
    sim.spawn(counting_server(server_b.chain_db, rb, wb, "b"), "srv-b")
    # force bulk-sync: now is far ahead of the (empty) client chain
    sim.now = 5000.0
    sim.spawn(
        blockfetch.client(
            client_node, "a", wa, ra, cand_a, rounds=40, max_fetch_batch=8
        ),
        "bf-a",
    )
    sim.spawn(
        blockfetch.client(
            client_node, "b", wb, rb, cand_b, rounds=40, max_fetch_batch=8
        ),
        "bf-b",
    )
    sim.run(until=5600.0)

    assert len(client_node.chain_db.current_chain) == 30
    total = served["a"] + served["b"]
    assert total == 30, f"served {served} — duplicates fetched"
    # both peers actually contributed (batches interleaved)
    assert served["a"] > 0 and served["b"] > 0, served


def test_deadline_mode_allows_duplicates(tmp_path):
    """In deadline mode (tip near now) the same suffix MAY be fetched
    from both peers — latency beats bandwidth (the reference fetches
    from multiple peers to meet slot deadlines)."""
    server_a = mk_node(tmp_path, "da")
    server_b = mk_node(tmp_path, "db")
    client_node = mk_node(tmp_path, "dc")
    chain = forge_chain(5)
    for b in chain:
        server_a.chain_db.add_block(b)
        server_b.chain_db.add_block(b)
    sim = Sim()
    for n in (server_a, server_b, client_node):
        n.chain_db.runtime = sim

    def mk_candidate():
        cand = Candidate()
        cand.reset(client_node.chain_dep_state_at(None))
        for blk in chain:
            ticked = client_node.protocol.tick(LVIEW, blk.slot, cand.states[-1])
            cand.extend(
                blk.header,
                client_node.protocol.update(
                    blk.header.to_view(), blk.slot, ticked
                ),
            )
        return cand

    served = {"a": 0, "b": 0}

    def counting_server(db, rx, tx, key):
        inner = blockfetch.server(db, rx, tx)
        try:
            op = next(inner)
            while True:
                if (
                    hasattr(op, "chan")
                    and getattr(op, "msg", None) is not None
                    and op.msg[0] == "block"
                ):
                    served[key] += 1
                got = yield op
                op = inner.send(got)
        except StopIteration:
            return

    ra, wa = Channel(delay=0.3, name="a-req"), Channel(delay=0.3, name="a-rsp")
    rb, wb = Channel(delay=0.3, name="b-req"), Channel(delay=0.3, name="b-rsp")
    sim.spawn(counting_server(server_a.chain_db, ra, wa, "a"), "srv-a")
    sim.spawn(counting_server(server_b.chain_db, rb, wb, "b"), "srv-b")
    sim.now = 5.0  # tip (slot 5) is "now": deadline mode
    sim.spawn(
        blockfetch.client(client_node, "a", wa, ra, mk_candidate(), rounds=3),
        "bf-a",
    )
    sim.spawn(
        blockfetch.client(client_node, "b", wb, rb, mk_candidate(), rounds=3),
        "bf-b",
    )
    sim.run(until=100.0)
    assert len(client_node.chain_db.current_chain) == 5
    # the slow symmetric channels force overlap: both served full ranges
    assert served["a"] == 5 and served["b"] == 5, served
