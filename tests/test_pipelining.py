"""ChainSync message pipelining + diffusion pipelining (tentative headers).

Reference: `MkPipelineDecision` (MiniProtocol/ChainSync/Client.hs:422),
tentative-header followers (ChainDB Impl/Follower.hs, trap logic at
Impl/ChainSel.hs:949-984), and the blocking (non-polling) ChainSync
server (Server.hs blocks in STM on the follower's next instruction).
"""

import os
from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.miniprotocol import chainsync
from ouroboros_consensus_tpu.miniprotocol.chainsync import Candidate
from ouroboros_consensus_tpu.node.kernel import NodeKernel
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.sim import Channel, Sim

PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=100,  # no trimming interference in these tests
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOLS = [fixtures.make_pool(i, kes_depth=2) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)
ETA0 = b"\x22" * 32
N_HEADERS = 30


def _mk_node(tmp_path, name):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    db = open_chaindb(str(tmp_path / name), ext, st, PARAMS.security_param)
    return NodeKernel(name, db, protocol, ledger, pool=None)


def _forge_chain(n):
    blocks, prev = [], None
    for i in range(n):
        b = forge_block(
            PARAMS, POOLS[i % 2], slot=i + 1, block_no=i,
            prev_hash=prev, epoch_nonce=ETA0,
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


def _sync_time(tmp_path, label, max_in_flight):
    """Virtual time for a fresh client to pull N_HEADERS headers from a
    server over channels with delay 0.1."""
    server_node = _mk_node(tmp_path, f"server-{label}")
    client_node = _mk_node(tmp_path, f"client-{label}")
    for b in _forge_chain(N_HEADERS):
        server_node.chain_db.add_block(b)

    sim = Sim()
    server_node.chain_db.runtime = sim
    req = Channel(delay=0.1, name="req")
    rsp = Channel(delay=0.1, name="rsp")
    cand = Candidate()
    sim.spawn(chainsync.server(server_node.chain_db, req, rsp), "server")
    client = sim.spawn(
        chainsync.client(
            client_node, "peer", rsp, req, cand,
            max_headers=N_HEADERS, max_in_flight=max_in_flight,
        ),
        "client",
    )
    sim.run()
    assert not client.alive  # finished
    assert len(cand.headers) == N_HEADERS
    return sim.now


def test_pipelined_sync_is_faster(tmp_path):
    """Pipelining amortizes the round trip: with delay d per message,
    strict request/response pays 2d per header; a 10-deep pipeline
    must finish the same sync at least 3x sooner."""
    strict = _sync_time(tmp_path, "strict", max_in_flight=1)
    pipelined = _sync_time(tmp_path, "pipe", max_in_flight=10)
    assert pipelined < strict / 3, (strict, pipelined)


def test_candidate_trimmed_to_k(tmp_path):
    """theirHeaderStateHistory is trimmed to k (HeaderStateHistory.hs):
    memory stays O(k) on long syncs — but only SETTLED (already-adopted)
    headers are trimmed, so BlockFetch's anchor never disappears."""
    from ouroboros_consensus_tpu.miniprotocol import blockfetch

    server_node = _mk_node(tmp_path, "server-t")
    client_node = _mk_node(tmp_path, "client-t")
    k = 5
    client_node.protocol.security_param = k
    for b in _forge_chain(N_HEADERS):
        server_node.chain_db.add_block(b)
    sim = Sim()
    server_node.chain_db.runtime = sim
    client_node.chain_db.runtime = sim
    req, rsp = Channel(name="req"), Channel(name="rsp")
    bf_req, bf_rsp = Channel(name="bf-req"), Channel(name="bf-rsp")
    cand = Candidate()
    sim.spawn(chainsync.server(server_node.chain_db, req, rsp), "server")
    sim.spawn(
        chainsync.client(
            client_node, "peer", rsp, req, cand, max_headers=N_HEADERS
        ),
        "client",
    )
    sim.spawn(blockfetch.server(server_node.chain_db, bf_req, bf_rsp), "bfs")
    sim.spawn(
        blockfetch.client(client_node, "peer", bf_rsp, bf_req, cand), "bfc"
    )
    sim.run(until=120.0)
    # the client fully adopted the server chain; the candidate history
    # was trimmed down to k as blocks settled
    assert client_node.chain_db.tip_point() is not None
    assert (
        client_node.chain_db.tip_point().hash_
        == server_node.chain_db.tip_point().hash_
    )
    assert len(cand.headers) <= k
    assert len(cand.states) == len(cand.headers) + 1
    assert cand.trimmed
    # rollback to the (trimmed-away) intersection must now fail
    assert not cand.truncate_to(None)


def test_tentative_header_announced_before_validation(tmp_path):
    """Decoupled mode: a block extending the tip is announced to
    tentative followers at ENQUEUE time, before the add-block runner
    validates it; the later adoption does not re-announce it."""
    node = _mk_node(tmp_path, "n")
    db = node.chain_db
    sim = Sim()
    runners = db.start_decoupled(sim)
    blocks = _forge_chain(2)

    f_tent = db.new_follower(include_tentative=True)
    f_plain = db.new_follower()

    db.add_block_async(blocks[0])
    # BEFORE any runner step: tentative follower saw the header
    ups = f_tent.take_updates()
    assert [u[0] for u in ups] == ["tentative"]
    assert ups[0][1].hash_ == blocks[0].hash_
    assert f_plain.take_updates() == []

    for i, r in enumerate(runners):
        sim.spawn(r, f"runner{i}")
    sim.run(until=10.0)

    # adoption: plain follower gets the block; tentative follower got it
    # already and must NOT see a duplicate
    plain = f_plain.take_updates()
    assert [u[0] for u in plain] == ["addblock"]
    assert f_tent.take_updates() == []


def test_tentative_header_retracted_when_not_adopted(tmp_path):
    """The trap case (ChainSel.hs:949-984): if validation rejects the
    announced block, tentative followers receive a compensating
    rollback to the pre-announcement tip."""
    node = _mk_node(tmp_path, "n")
    db = node.chain_db
    blocks = _forge_chain(2)
    db.add_block(blocks[0])  # adopted synchronously (still coupled)

    sim = Sim()
    runners = db.start_decoupled(sim)
    f_tent = db.new_follower(include_tentative=True)

    # a block extending the tip but with a corrupted KES signature:
    # announced tentatively, then rejected by chain selection
    good = blocks[1]
    bad_sig = bytes([good.header.kes_sig[0] ^ 0xFF]) + good.header.kes_sig[1:]
    from ouroboros_consensus_tpu.block.praos_block import Block, Header

    bad = Block(Header(good.header.body, bad_sig), good.txs)
    db.add_block_async(bad)
    ups = f_tent.take_updates()
    assert [u[0] for u in ups] == ["tentative"]

    for i, r in enumerate(runners):
        sim.spawn(r, f"runner{i}")
    sim.run(until=10.0)

    ups = f_tent.take_updates()
    assert ("rollback", blocks[0].point) in ups, ups
    assert db.tip_point().hash_ == blocks[0].hash_


def test_invalid_block_punishes_peer(tmp_path):
    """InvalidBlockPunishment (ChainSel.hs:1084-1099): a peer whose
    served BODY fails validation is disconnected (the fetch task ends),
    while the node keeps its valid chain and marks the block invalid."""
    from ouroboros_consensus_tpu.block.praos_block import Block as PB
    from ouroboros_consensus_tpu.block.praos_block import Header as PH
    from ouroboros_consensus_tpu.miniprotocol import blockfetch
    from ouroboros_consensus_tpu.utils.sim import Recv, Send

    node = _mk_node(tmp_path, "victim")
    good = _forge_chain(2)
    node.chain_db.add_block(good[0])

    corrupt = PB(
        PH(good[1].header.body,
           bytes([good[1].header.kes_sig[0] ^ 0xFF]) + good[1].header.kes_sig[1:]),
        good[1].txs,
    )
    cand = Candidate()
    # the candidate claims the (honest-looking) header; the peer serves
    # a corrupted body for it
    base = node.chain_dep_state_at(node.chain_db.tip_point())
    cand.reset(base)
    cand.headers = [good[1].header]
    cand.states = [base, base]

    sim = Sim()
    node.chain_db.runtime = sim
    req, rsp = Channel(), Channel()

    def evil_server():
        while True:
            msg = yield Recv(req)
            if msg[0] != "request_range":
                return
            yield Send(rsp, ("start_batch",))
            yield Send(rsp, ("block", corrupt.bytes_))
            yield Send(rsp, ("batch_done",))

    sim.spawn(evil_server(), "evil")
    disconnects = []

    def guarded():
        try:
            yield from blockfetch.client(node, "evil", rsp, req, cand)
        except blockfetch.InvalidBlockFromPeer as e:
            disconnects.append(e.peer)

    sim.spawn(guarded(), "fetch")
    sim.run(until=10.0)
    assert disconnects == ["evil"]
    assert node.chain_db.get_is_invalid_block(corrupt.hash_) is not None
    assert node.chain_db.tip_point().hash_ == good[0].hash_


def test_server_follower_closed_on_teardown(tmp_path):
    """A killed ChainSync server must not leak its follower (the
    RethrowPolicy disconnect path closes the generator; the server's
    finally unregisters)."""
    node = _mk_node(tmp_path, "n")
    db = node.chain_db
    before = len(db.followers)
    req, rsp = Channel(), Channel()
    gen = chainsync.server(db, req, rsp)
    sim = Sim()
    sim.spawn(gen, "server")
    sim.run(until=0.1)  # server starts, registers its follower, blocks
    assert len(db.followers) == before + 1
    gen.close()
    assert len(db.followers) == before


def test_edge_teardown_on_adversarial_peer(tmp_path):
    """Full-edge adversary: honest ChainSync headers, corrupted
    BlockFetch bodies. The InvalidBlockFromPeer punishment must tear
    down the WHOLE connection — both protocol tasks end, the candidate
    is dropped — while the victim keeps its valid chain."""
    from ouroboros_consensus_tpu.block.praos_block import Block as PB
    from ouroboros_consensus_tpu.block.praos_block import Header as PH
    from ouroboros_consensus_tpu.miniprotocol import blockfetch
    from ouroboros_consensus_tpu.miniprotocol.rethrow import peer_guard
    from ouroboros_consensus_tpu.utils.sim import Recv, Send

    evil = _mk_node(tmp_path, "evil")
    victim = _mk_node(tmp_path, "victim")
    chain = _forge_chain(4)
    for b in chain:
        evil.chain_db.add_block(b)

    def corrupt(raw: bytes) -> bytes:
        b = PB.from_bytes(raw)
        sig = bytes([b.header.kes_sig[0] ^ 0xFF]) + b.header.kes_sig[1:]
        return PB(PH(b.header.body, sig), b.txs).bytes_

    sim = Sim()
    evil.chain_db.runtime = sim
    victim.chain_db.runtime = sim
    cs_req, cs_rsp = Channel(delay=0.01), Channel(delay=0.01)
    bf_req, bf_rsp = Channel(delay=0.01), Channel(delay=0.01)

    def corrupting_bf_server():
        """Wrap the honest server, corrupting every body on the way out."""
        inner = blockfetch.server(evil.chain_db, bf_req, bf_rsp)
        val = None
        while True:
            try:
                eff = inner.send(val)
            except StopIteration:
                return
            if isinstance(eff, Send) and eff.msg[0] == "block":
                eff = Send(eff.chan, ("block", corrupt(eff.msg[1])))
            val = yield eff

    cand = Candidate()
    victim.candidates["evil"] = cand
    tasks = []

    def disconnect():
        for t in tasks:
            t.alive = False
        victim.candidates.pop("evil", None)

    sim.spawn(chainsync.server(evil.chain_db, cs_req, cs_rsp), "cs-srv")
    sim.spawn(corrupting_bf_server(), "bf-srv")
    tasks.append(sim.spawn(
        peer_guard(
            chainsync.client(victim, "evil", cs_rsp, cs_req, cand),
            "cs", victim.trace, disconnect,
        ), "cs-client",
    ))
    tasks.append(sim.spawn(
        peer_guard(
            blockfetch.client(victim, "evil", bf_rsp, bf_req, cand),
            "bf", victim.trace, disconnect,
        ), "bf-client",
    ))
    sim.run(until=30.0)

    assert "evil" not in victim.candidates  # connection torn down
    assert all(not t.alive for t in tasks)
    # nothing corrupt was adopted
    assert victim.chain_db.tip_point() is None or (
        victim.chain_db.get_is_invalid_block(
            victim.chain_db.tip_point().hash_
        ) is None
    )
    assert len(victim.chain_db.invalid) >= 1  # the lie was recorded


def test_server_blocks_without_polling(tmp_path):
    """The caught-up ChainSync server BLOCKS on the follower's event
    (Server.hs blocks in STM on the next instruction) — with a runtime
    attached there is no poll timer, so a quiescent network leaves the
    sim with an EMPTY event queue: sim.run(until=T) returns long before
    T instead of ticking poll wakeups until the horizon."""
    server_node = _mk_node(tmp_path, "server-block")
    client_node = _mk_node(tmp_path, "client-block")
    for b in _forge_chain(5):
        server_node.chain_db.add_block(b)
    sim = Sim()
    server_node.chain_db.runtime = sim
    req, rsp = Channel(delay=0.01, name="req"), Channel(delay=0.01, name="rsp")
    cand = Candidate()
    sim.spawn(chainsync.server(server_node.chain_db, req, rsp), "server")
    # client pulls the 5 available headers, then issues one request_next
    # that can never be answered (no new blocks) -> both endpoints block
    sim.spawn(
        chainsync.client(client_node, "peer", rsp, req, cand, max_headers=6),
        "client",
    )
    end = sim.run(until=1000.0)
    assert len(cand.headers) == 5
    assert end < 10.0, f"sim ran to {end}: the server is polling"


def test_server_wakes_on_new_block_event(tmp_path):
    """A blocked server resumes promptly when chain selection adopts a
    new block and fires the follower event (no poll latency)."""
    server_node = _mk_node(tmp_path, "server-wake")
    client_node = _mk_node(tmp_path, "client-wake")
    chain = _forge_chain(6)
    for b in chain[:5]:
        server_node.chain_db.add_block(b)
    sim = Sim()
    server_node.chain_db.runtime = sim
    req, rsp = Channel(delay=0.01, name="req"), Channel(delay=0.01, name="rsp")
    cand = Candidate()
    sim.spawn(chainsync.server(server_node.chain_db, req, rsp), "server")
    cl = sim.spawn(
        chainsync.client(client_node, "peer", rsp, req, cand, max_headers=6),
        "client",
    )

    def late_block():
        from ouroboros_consensus_tpu.utils.sim import Sleep as S

        yield S(50.0)
        server_node.chain_db.add_block(chain[5])

    sim.spawn(late_block(), "late")
    sim.run(until=1000.0)
    assert not cl.alive
    assert len(cand.headers) == 6
    # 6th header arrives right after t=50 (plus channel delays), far
    # sooner than any poll-interval-quantized schedule would show drift
    assert sim.now < 60.0
