"""Device resource accounting (obs/resources.py): extraction units,
the runtime capture hooks (ops/pk/kernels._stage_call and the
protocol/batch _warm_timed wrapper), the oct_stage_* gauge mirroring,
the OCT_STAGE_RESOURCES lever, and the budgets.json "device_resources"
ratchet — pin coverage of the whole registry, hash-consistency with
costmodel.json, and the check/update dict logic."""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.analysis import costmodel, graphs
from ouroboros_consensus_tpu.obs import resources as R
from ouroboros_consensus_tpu.obs.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    obs.reset_for_tests()
    R.RESOURCES.reset()
    yield
    R.RESOURCES.reset()
    obs.reset_for_tests()


# ---------------------------------------------------------------------------
# extraction units
# ---------------------------------------------------------------------------


def test_from_cost_analysis_handles_dict_list_none():
    assert R.from_cost_analysis(None) == {}
    assert R.from_cost_analysis([]) == {}
    d = {"flops": 12.0, "bytes accessed": 34.0, "utilization0{}": 1.0}
    assert R.from_cost_analysis(d) == {"flops": 12, "bytes_accessed": 34}
    # Compiled returns a per-partition list on this jax
    assert R.from_cost_analysis([d]) == {"flops": 12, "bytes_accessed": 34}


def test_from_memory_analysis_computes_peak():
    class Stats:
        argument_size_in_bytes = 100
        output_size_in_bytes = 20
        temp_size_in_bytes = 300
        generated_code_size_in_bytes = 7

    out = R.from_memory_analysis(Stats())
    assert out["peak_hbm_bytes"] == 427
    assert out["argument_bytes"] == 100
    assert R.from_memory_analysis(None) == {}


def test_from_lowered_and_compiled_real_program():
    lo = jax.jit(lambda x: jnp.dot(x, x) + 1).lower(
        jnp.ones((16, 16), jnp.float32)
    )
    res = R.from_lowered(lo)
    assert res and res["flops"] > 0
    co = lo.compile()
    full = R.from_compiled(co)
    assert full and full["flops"] > 0
    assert "peak_hbm_bytes" in full and full["peak_hbm_bytes"] > 0
    assert full["argument_bytes"] == 16 * 16 * 4


# ---------------------------------------------------------------------------
# recorder + gauges + lever
# ---------------------------------------------------------------------------


def test_note_stage_first_wins_and_mirrors_gauges():
    from ouroboros_consensus_tpu.obs.registry import default_registry

    ok = R.RESOURCES.note_stage(
        "ed@b8", 8, 7,
        {"flops": 100, "bytes_accessed": 200, "peak_hbm_bytes": 50,
         "argument_bytes": 30, "output_bytes": 10, "temp_bytes": 10},
        via="jit", feature_hash="abc",
    )
    assert ok
    # second note for the same (stage, lanes, depth) is dropped
    assert not R.RESOURCES.note_stage("ed@b8", 8, 7, {"flops": 999})
    rep = R.RESOURCES.report()
    (key,) = rep
    assert key == "ed@b8|8|7"
    assert rep[key]["flops"] == 100
    assert rep[key]["feature_hash"] == "abc"
    json.dumps(rep)  # ledger/bench bankable
    snap = default_registry().snapshot()
    assert snap["oct_stage_flops"]["samples"][0]["labels"] == {
        "stage": "ed@b8"
    }
    assert snap["oct_stage_flops"]["samples"][0]["value"] == 100
    kinds = {
        s["labels"]["kind"]: s["value"]
        for s in snap["oct_stage_hbm_bytes"]["samples"]
    }
    assert kinds == {"argument": 30, "output": 10, "temp": 10, "peak": 50}


def test_capture_lever(monkeypatch):
    fn = jax.jit(lambda x: x + 1)
    args = (jnp.zeros((4,), jnp.int32),)
    monkeypatch.setenv("OCT_STAGE_RESOURCES", "0")
    assert not R.capture_stage("lever@b4", fn, args, lanes=4)
    assert R.RESOURCES.report() == {}
    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")
    assert R.capture_stage("lever@b4", fn, args, lanes=4)
    assert "lever@b4|4|None" in R.RESOURCES.report()
    # unset: follows the installed recorder
    monkeypatch.delenv("OCT_STAGE_RESOURCES")
    R.RESOURCES.reset()
    assert not R.enabled()
    obs.install()
    try:
        assert R.enabled()
    finally:
        obs.uninstall()
    assert not R.enabled()


def test_stage_call_captures_on_first_execute(monkeypatch):
    """The ops/pk dispatch hook: one capture per (stage, bucket), on
    the jit path, riding the warmup first-execute gate."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP
    from ouroboros_consensus_tpu.ops.pk import kernels

    monkeypatch.setenv("OCT_PK_AOT", "0")
    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")
    WARMUP.reset()
    kernels._FIRST_EXEC.discard("restest@b4")
    fn = jax.jit(lambda x: x * 2)
    kernels._stage_call("restest", fn, 4, 3, jnp.ones((2, 4), jnp.int32))
    kernels._stage_call("restest", fn, 4, 3, jnp.ones((2, 4), jnp.int32))
    rep = R.RESOURCES.report()
    (key,) = [k for k in rep if k.startswith("restest@b4")]
    assert key == "restest@b4|4|3"
    assert rep[key]["via"] == "jit"
    assert rep[key]["bytes_accessed"] > 0
    kernels._FIRST_EXEC.discard("restest@b4")


def test_warm_timed_captures_xla_twin(monkeypatch):
    """The protocol/batch XLA-twin hook: _warm_timed wraps the jit, the
    first call records both the warmup wall AND the resources, with
    lanes read off the leading batch axis. Since round 10 the
    first-execute label is LANE-QUALIFIED (`<stage>:<lanes>l`) — the
    warm ladder runs the same program family at rung and production
    lane counts, and each shape's compile attributes separately."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP
    from ouroboros_consensus_tpu.protocol import batch as pbatch

    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")
    WARMUP.reset()
    pbatch._WARM_SEEN.discard("restest-twin:6l")
    try:
        wrapped = pbatch._warm_timed("restest-twin",
                                     jax.jit(lambda x: x.sum(axis=1)))
        wrapped(np.ones((6, 3), np.float32))
        wrapped(np.ones((6, 3), np.float32))
        rep = R.RESOURCES.report()
        assert "restest-twin:6l|6|None" in rep
        assert rep["restest-twin:6l|6|None"]["via"] == "xla-jit"
        assert "restest-twin:6l" in WARMUP.report()["stages"]
        # a DIFFERENT lane count is a separate first execute
        wrapped(np.ones((4, 3), np.float32))
        assert "restest-twin:4l" in WARMUP.report()["stages"]
    finally:
        pbatch._WARM_SEEN.discard("restest-twin:6l")
        pbatch._WARM_SEEN.discard("restest-twin:4l")
        WARMUP.reset()


def test_capture_never_raises(monkeypatch):
    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")

    class Broken:
        def lower(self, *a):
            raise RuntimeError("boom")

    assert not R.capture_stage("broken@b1", Broken(), (), lanes=1)


def test_capture_rows_carry_their_own_cost(monkeypatch):
    """Telemetry is accountable: every captured row records what the
    capture itself cost (capture_s), so a warmup wall burned on the
    re-trace is attributed, never mysterious."""
    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")
    fn = jax.jit(lambda x: x + 1)
    assert R.capture_stage("acct@b4", fn, (jnp.zeros((4,), jnp.int32),),
                           lanes=4)
    row = R.RESOURCES.report()["acct@b4|4|None"]
    assert "capture_s" in row and row["capture_s"] >= 0.0


def test_capture_defers_to_a_near_wall_deadline(monkeypatch):
    """The jit-path re-trace is skippable telemetry; a bench attempt's
    OCT_WALL_DEADLINE budget is not — near the deadline the capture
    must stand down (the AOT path stays free and keeps capturing)."""
    import time as _time

    monkeypatch.setenv("OCT_STAGE_RESOURCES", "1")
    monkeypatch.setenv(
        "OCT_WALL_DEADLINE",
        str(_time.time() + R.CAPTURE_DEADLINE_MARGIN_S / 2),
    )
    fn = jax.jit(lambda x: x + 1)
    assert not R.capture_stage("nearwall@b4", fn,
                               (jnp.zeros((4,), jnp.int32),), lanes=4)
    assert R.RESOURCES.report() == {}
    # with wall to spare the same capture goes through
    monkeypatch.setenv("OCT_WALL_DEADLINE", str(_time.time() + 10_000.0))
    assert R.capture_stage("nearwall@b4", fn,
                           (jnp.zeros((4,), jnp.int32),), lanes=4)


# ---------------------------------------------------------------------------
# static measurement + the ratchet
# ---------------------------------------------------------------------------


def test_measure_graph_small_no_compile():
    res = R.measure_graph("verdict_reduce", 8, compile=False)
    assert res["flops"] > 0 and res["bytes_accessed"] > 0
    assert res["source"] == "lowered"
    assert res["at_lanes"] == 8
    assert "peak_hbm_bytes" not in res  # memory stats need the compile


class _Feat:
    def __init__(self, name, h):
        self.name = name
        self._h = h

    def hash(self):
        return self._h


def _budgets_with(pin_hash="h1", flops=100):
    return {
        "device_resources": {
            "graphs": {
                "g": {"feature_hash": pin_hash, "flops": flops,
                      "bytes_accessed": 10, "peak_hbm_bytes": 5,
                      "at_lanes": 2},
            },
            "ceilings": {
                "g": {"flops_max": 120, "bytes_accessed_max": 12,
                      "peak_hbm_bytes_max": 6},
            },
        }
    }


def test_check_device_resources_dict_logic():
    feats = [_Feat("g", "h1")]
    assert R.check_device_resources(feats, _budgets_with()) == []
    # missing pin
    v = R.check_device_resources([_Feat("other", "x")], _budgets_with())
    assert v and "no device_resources pin" in v[0]
    # stale structure fails loudly BEFORE any ceiling compare
    v = R.check_device_resources([_Feat("g", "DRIFTED")], _budgets_with())
    assert v and "drifted" in v[0]
    # pinned value over its ceiling
    v = R.check_device_resources(feats, _budgets_with(flops=121))
    assert v and "exceeds ceiling" in v[0]


def test_update_budgets_section_preserves_existing_ceilings():
    budgets = _budgets_with()
    meas = {"g": {"flops": 110, "bytes_accessed": 11, "peak_hbm_bytes": 6,
                  "at_lanes": 2, "source": "compiled"}}
    R.update_budgets_section(budgets, meas, {"g": "h2"}, measured_at="t")
    sec = budgets["device_resources"]
    assert sec["graphs"]["g"]["feature_hash"] == "h2"
    assert sec["graphs"]["g"]["flops"] == 110
    # the OLD ceiling survives the update — that asymmetry IS the
    # ratchet (a grown program trips it until raised on purpose)
    assert sec["ceilings"]["g"]["flops_max"] == 120
    # a brand-new graph gets a fresh ceiling at the headroom factor
    meas["g2"] = {"flops": 100, "bytes_accessed": 10,
                  "peak_hbm_bytes": 10, "at_lanes": 4,
                  "source": "compiled"}
    R.update_budgets_section(budgets, meas, {"g": "h2", "g2": "h9"})
    assert sec["ceilings"]["g2"]["flops_max"] == int(
        100 * R.CEILING_HEADROOM
    )
    # dropping a graph from the measurements drops its ceiling too
    del meas["g"]
    R.update_budgets_section(budgets, meas, {"g2": "h9"})
    assert "g" not in sec["ceilings"] and "g" not in sec["graphs"]


# ---------------------------------------------------------------------------
# the shipped pins (budgets.json) — coverage + hash consistency
# ---------------------------------------------------------------------------


def test_shipped_pins_cover_every_registry_graph():
    sec = graphs.load_budgets().get("device_resources", {})
    pins = sec.get("graphs", {})
    assert set(pins) == set(graphs.registered_graphs()), (
        "every registry stage must carry a device_resources pin "
        "(run scripts/lint.py --update-resources)"
    )
    for name, pin in pins.items():
        for key in ("flops", "bytes_accessed", "peak_hbm_bytes"):
            assert isinstance(pin.get(key), int) and pin[key] >= 0, (
                f"{name}: pin missing {key}"
            )
        assert pin.get("feature_hash"), f"{name}: pin missing its hash key"
    ceilings = sec.get("ceilings", {})
    for name, pin in pins.items():
        ceil = ceilings.get(name, {})
        for key in R.CEILING_KEYS:
            cmax = ceil.get(f"{key}_max")
            assert cmax is not None, f"{name}: no ceiling for {key}"
            assert pin[key] <= cmax, (
                f"{name}: shipped pin {key}={pin[key]} over its own "
                f"ceiling {cmax}"
            )


def test_shipped_pins_keyed_by_costmodel_hashes():
    """The staleness key IS octwall's pinned feature hash: the two pin
    files must agree, or a costmodel refresh would orphan the resource
    pins silently."""
    sec = graphs.load_budgets().get("device_resources", {})
    for name, pin in sec.get("graphs", {}).items():
        cm = costmodel.pinned(name)
        assert cm is not None, f"{name}: no costmodel.json pin"
        assert pin["feature_hash"] == cm["feature_hash"], (
            f"{name}: device_resources pin hash diverged from "
            "costmodel.json (run scripts/lint.py --update-resources)"
        )


def test_resources_payload_reports_freshness():
    budgets = _budgets_with()
    rows = R.resources_payload(["g", "missing"], budgets,
                               [_Feat("g", "h1")])
    assert rows["g"]["fresh"] and rows["g"]["pin"]["flops"] == 100
    assert rows["missing"]["pin"] is None and not rows["missing"]["fresh"]
    # the CLI --json contract: sorted-keys strict JSON round-trip
    json.loads(json.dumps(rows, sort_keys=True, allow_nan=False))
