"""Ledger-DERIVED epoch views (VERDICT r2 item 4).

The validator's per-epoch pool distribution must come from the ledger's
stake snapshots (Ledger/SupportsProtocol.hs ledgerViewForecastAt; stake
rules reached from shelley/.../Shelley/Ledger/Ledger.hs:584), not from a
fixture. These tests build a chain whose stake SHIFTS across epochs via
a real mock-ledger transaction and require:

  * the mark/set/go-shaped snapshot rule: epoch E elects with the
    distribution sealed at the end of epoch E-2;
  * db-analyser revalidation with the ledger in the loop derives the
    right view per epoch and validates the chain clean;
  * feeding the WRONG (constant genesis) view makes validation fail in
    the shifted epochs — i.e. the derivation is load-bearing.
"""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.ledger.mock import StakeConfig, encode_tx
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.views import (
    IndividualPoolStake,
    LedgerView,
    hash_vrf_vk,
)
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=2,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=30,
    kes_depth=3,
)

ADDR_A, ADDR_B = b"addr-a", b"addr-b"


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]


def mk_ledger(pools):
    stake = StakeConfig(
        delegations={ADDR_A: pools[0].pool_id, ADDR_B: pools[1].pool_id},
        pool_vrf_hashes={
            p.pool_id: hash_vrf_vk(p.vrf_vk) for p in pools
        },
        epoch_length=PARAMS.epoch_length,
    )
    cfg = mock_ledger.MockConfig(
        ledger_view=None, stability_window=PARAMS.stability_window,
        stake=stake,
    )
    ledger = mock_ledger.MockLedger(cfg)
    genesis = ledger.genesis_state([(ADDR_A, 9), (ADDR_B, 1)])
    return ledger, genesis


def lv_of(pools, stakes):
    return LedgerView(
        pool_distr={
            p.pool_id: IndividualPoolStake(s, hash_vrf_vk(p.vrf_vk))
            for p, s in zip(pools, stakes)
        }
    )


# the one stake-moving tx: spend genesis output 0 (addr_a, 9) into
# (addr_a, 1) + (addr_b, 8) -> distribution flips from 9:1 to 1:9
SHIFT_TX = encode_tx([(bytes(32), 0)], [(ADDR_A, 1), (ADDR_B, 8)])


@pytest.fixture(scope="module")
def shifted_chain(tmp_path_factory, pools):
    """4-epoch chain: the shift tx lands in epoch 0's first block, so
    epochs 0-1 elect with 9:1 and epochs >= 2 with 1:9."""
    lv_genesis = lv_of(pools, [Fraction(9, 10), Fraction(1, 10)])
    lv_shifted = lv_of(pools, [Fraction(1, 10), Fraction(9, 10)])
    path = str(tmp_path_factory.mktemp("shifted"))
    first = {"done": False}

    def txs_for_block(slot, block_no):
        if not first["done"]:
            first["done"] = True
            return (SHIFT_TX,)
        return ()

    res = db_synthesizer.synthesize(
        path, PARAMS, pools,
        lv_genesis,
        db_synthesizer.ForgeLimit(slots=4 * PARAMS.epoch_length),
        ledger_view_for_epoch=lambda e: lv_genesis if e < 2 else lv_shifted,
        txs_for_block=txs_for_block,
    )
    assert res.n_blocks > 20
    return path, res, lv_genesis, lv_shifted


def test_snapshot_rule_exact(pools):
    """view_for_epoch implements the end-of-(E-2) snapshot rule."""
    ledger, genesis = mk_ledger(pools)
    # genesis distribution: 9:1
    v0 = ledger.view_for_epoch(genesis, 0)
    assert v0.pool_distr[pools[0].pool_id].stake == Fraction(9, 10)

    # apply the shift tx in a block at slot 3 (epoch 0)
    class Blk:
        slot = 3
        txs = (SHIFT_TX,)

    st = ledger.apply_block(ledger.tick(genesis, 3), Blk)
    # still epoch 0/1: the sealed snapshot predates the tx
    st1 = ledger.tick(st, PARAMS.epoch_length + 1).state  # tick into epoch 1
    assert ledger.view_for_epoch(st1, 1).pool_distr[
        pools[0].pool_id
    ].stake == Fraction(9, 10)
    # epoch 2 uses the end-of-epoch-0 snapshot: shifted
    st2 = ledger.tick(st1, 2 * PARAMS.epoch_length + 1).state
    v2 = ledger.view_for_epoch(st2, 2)
    assert v2.pool_distr[pools[0].pool_id].stake == Fraction(1, 10)
    assert v2.pool_distr[pools[1].pool_id].stake == Fraction(9, 10)


def test_revalidation_with_derived_views(shifted_chain, pools):
    """db-analyser with the ledger in the loop derives every epoch's
    view from the replayed state and validates the chain clean."""
    path, res, lv_genesis, _ = shifted_chain
    ledger, genesis = mk_ledger(pools)
    out = db_analyser.revalidate(
        path, PARAMS, lview=None, backend="native",
        ledger=ledger, genesis_state=genesis,
    )
    assert out.error is None, repr(out.error)
    assert out.n_valid == out.n_blocks == res.n_blocks


def test_wrong_epoch_view_fails(shifted_chain, pools):
    """The derivation is load-bearing: validating the shifted epochs
    against the constant GENESIS distribution rejects the chain (pool
    B's post-shift wins exceed its old 1/10 leader threshold)."""
    path, res, lv_genesis, _ = shifted_chain
    out = db_analyser.revalidate(
        path, PARAMS, lv_genesis, backend="native",
    )
    assert out.error is not None
    assert isinstance(
        out.error,
        (praos.VRFLeaderValueTooBig, praos.VRFKeyBadProof),
    ), repr(out.error)
    # the failure is in the shifted region (epoch >= 2)
    assert out.n_valid >= 1
    assert out.error is not None


def test_forecast_serves_derived_views(pools):
    """ledger_view_forecast_at routes through the same snapshots (the
    ChainSync client's forecast path sees epoch-correct stake)."""
    ledger, genesis = mk_ledger(pools)

    class Blk:
        slot = 3
        txs = (SHIFT_TX,)

    st = ledger.apply_block(ledger.tick(genesis, 3), Blk)
    fc = ledger.ledger_view_forecast_at(st)
    v = fc.view_fn(5)  # same epoch
    assert v.pool_distr[pools[0].pool_id].stake == Fraction(9, 10)


# ---------------------------------------------------------------------------
# The same discipline over the REAL Shelley STS ledger (ledger/shelley.py)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shelley_chain(tmp_path_factory, pools):
    """A Shelley-backed on-disk chain where a pool registered ON CHAIN
    (epoch 0) starts forging in epoch 2: only ledger-derived views can
    revalidate it."""
    from ouroboros_consensus_tpu.ledger import shelley as sh
    from ouroboros_consensus_tpu.protocol.views import hash_key
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB
    from ouroboros_consensus_tpu.block import forge_block

    pool_c = fixtures.make_pool(7, kes_depth=PARAMS.kes_depth)
    pp = sh.PParams(min_fee_a=0, min_fee_b=0, key_deposit=10, pool_deposit=10)
    g = sh.ShelleyGenesis(
        pparams=pp, epoch_length=PARAMS.epoch_length,
        stability_window=PARAMS.stability_window, max_supply=1_000_000,
    )
    ledger = sh.ShelleyLedger(g)

    def cred(i):
        return b"sc%d" % i + b"\x00" * 25

    def pool_params(p, rc):
        return sh.PoolParams(
            pool_id=hash_key(p.vk_cold), vrf_hash=hash_vrf_vk(p.vrf_vk),
            pledge=0, cost=0, margin=Fraction(0), reward_cred=rc, owners=(),
        )

    st0 = ledger.genesis_state(
        [(b"pay-a", cred(0), 900), (b"pay-c", cred(2), 100)],
        initial_pools=(pool_params(pools[0], cred(0)),),
        initial_delegations=((cred(0), hash_key(pools[0].vk_cold)),),
    )
    reg_tx = sh.encode_tx(
        [(bytes(32), 1)], [(b"pay-c", cred(2), 100 - 20)], fee=0,
        certs=[(0, cred(2)),
               (3, hash_key(pool_c.vk_cold), hash_vrf_vk(pool_c.vrf_vk),
                0, 0, 0, 1, cred(2), []),
               (2, cred(2), hash_key(pool_c.vk_cold))],
    )

    path = str(tmp_path_factory.mktemp("shelley_chain"))
    import os

    imm = ImmutableDB(
        os.path.join(path, "immutable"), chunk_size=PARAMS.epoch_length
    )
    forgers = [pools[0], pool_c]
    st, lst, prev, bno = praos.PraosState(), st0, None, 0
    c_forged = 0
    for slot in range(1, 3 * PARAMS.epoch_length):
        tls = ledger.tick(lst, slot)
        view = ledger.view_for_epoch(tls.state, PARAMS.epoch_of(slot))
        ticked = praos.tick(PARAMS, view, slot, st)
        nonce = ticked.state.epoch_nonce
        leader = fixtures.find_leader(PARAMS, forgers, view, slot, nonce)
        if leader is None:
            continue
        if leader is pool_c:
            c_forged += 1
        txs = (reg_tx,) if bno == 0 else ()
        blk = forge_block(
            PARAMS, leader, slot=slot, block_no=bno, prev_hash=prev,
            epoch_nonce=nonce, txs=txs,
        )
        imm.append_block(blk.slot, blk.block_no, blk.hash_, blk.bytes_)
        st = praos.update(PARAMS, blk.header.to_view(), slot, ticked)
        lst = ledger.tick_then_apply(lst, blk)
        prev, bno = blk.hash_, bno + 1
    imm.flush()
    assert c_forged > 0, "pool C must have forged in epoch >= 2"
    return path, bno, ledger, st0


def test_shelley_revalidation_with_derived_views(shelley_chain):
    """db-analyser replays the REAL STS ledger and derives each epoch's
    pool distribution from its stake snapshots; the chain (including the
    on-chain-registered pool's blocks) validates clean."""
    path, n_blocks, ledger, st0 = shelley_chain
    out = db_analyser.revalidate(
        path, PARAMS, lview=None, backend="native",
        ledger=ledger, genesis_state=st0,
    )
    assert out.error is None, repr(out.error)
    assert out.n_valid == out.n_blocks == n_blocks


def test_shelley_wrong_view_fails(shelley_chain):
    """Replaying against the constant GENESIS view rejects the first
    block forged by the on-chain-registered pool (unknown stake pool)."""
    path, n_blocks, ledger, st0 = shelley_chain
    genesis_view = ledger.view_for_epoch(st0, 0)
    out = db_analyser.revalidate(path, PARAMS, genesis_view, backend="native")
    assert out.error is not None
    assert out.n_valid < n_blocks


def test_synthesizer_ledger_mode_shelley(tmp_path, pools):
    """db-synthesizer with the LEDGER IN THE LOOP: forge a Shelley-
    backed chain (views derived from the folding STS state) and
    revalidate it with db-analyser's ledger-derived path — the full
    tool-level round trip on a real-era ledger."""
    from ouroboros_consensus_tpu.ledger import shelley as sh

    cred = b"synth-cred" + b"\x00" * 18
    pp = sh.PParams(min_fee_a=0, min_fee_b=0, key_deposit=10, pool_deposit=10)
    g = sh.ShelleyGenesis(
        pparams=pp, epoch_length=PARAMS.epoch_length,
        stability_window=PARAMS.stability_window, max_supply=1_000_000,
    )
    ledger = sh.ShelleyLedger(g)
    st0 = ledger.genesis_state(
        [(b"pay-s", cred, 1000)],
        initial_pools=(sh.PoolParams(
            pool_id=pools[0].pool_id, vrf_hash=hash_vrf_vk(pools[0].vrf_vk),
            pledge=0, cost=0, margin=Fraction(0), reward_cred=cred,
            owners=(),
        ),),
        initial_delegations=((cred, pools[0].pool_id),),
    )
    path = str(tmp_path / "shelley_synth")
    res = db_synthesizer.synthesize(
        path, PARAMS, [pools[0]], lview=None,
        limit=db_synthesizer.ForgeLimit(slots=3 * PARAMS.epoch_length),
        ledger=ledger, genesis_state=st0,
    )
    assert res.n_blocks > 10
    out = db_analyser.revalidate(
        path, PARAMS, lview=None, backend="native",
        ledger=ledger, genesis_state=st0,
    )
    assert out.error is None, repr(out.error)
    assert out.n_valid == out.n_blocks == res.n_blocks


def test_store_ledger_state_at_shelley(shelley_chain, tmp_path):
    """StoreLedgerStateAt over the REAL STS ledger: the stored snapshot
    (v2 codec) decodes to exactly the (ledger state, tip, protocol
    state) a direct fold reaches — the payload a resumed replay seeds
    from."""
    from ouroboros_consensus_tpu.block.praos_block import Block
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState
    from ouroboros_consensus_tpu.storage.ledgerdb import decode_snapshot

    path, n_blocks, ledger, st0 = shelley_chain
    at = 2 * PARAMS.epoch_length  # into epoch 2
    lview0 = ledger.view_for_epoch(st0, 0)
    name = db_analyser.store_ledger_state_at(
        path, PARAMS, lview0, at, ledger, st0, str(tmp_path / "snaps"),
    )
    assert name is not None
    with open(tmp_path / "snaps" / name, "rb") as f:
        ext = decode_snapshot(f.read())
    assert isinstance(ext.ledger_state, ShelleyState)
    # the snapshot equals the direct fold to the same point — ledger
    # state, the exact tip, AND the protocol (nonce/counter) state
    imm = db_analyser.open_immutable(path)
    lst = st0
    st = praos.PraosState()
    last = None
    for entry, raw in imm.stream_all():
        if entry.slot > at:
            break
        b = Block.from_bytes(raw)
        ticked = praos.tick(PARAMS, lview0, b.header.slot, st)
        st = praos.reupdate(PARAMS, b.header.to_view(), b.header.slot, ticked)
        lst = ledger.tick_then_reapply(lst, b)
        last = b
    assert ext.ledger_state == lst
    tip = ext.header_state.tip
    assert (tip.slot, tip.block_no, tip.hash_) == (
        last.header.slot, last.header.block_no, last.hash_
    )
    assert ext.header_state.chain_dep_state == st
