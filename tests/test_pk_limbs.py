"""Differential tests: ops/pk/limbs (limb-first) vs ops/field + host ints.

Everything runs on CPU under plain jit — the pk functions are pure jnp,
so correctness established here carries to the Pallas kernels that call
them (same trace).
"""

import numpy as np
import pytest

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import field as fe
from ouroboros_consensus_tpu.ops.pk import limbs as pk

B = 64
rng = np.random.default_rng(42)


def rand_fe_cols(b=B):
    """[20, b] nearly-normalized random elements + their int values."""
    arr = rng.integers(0, fe.B_MAX, size=(fe.NLIMBS, b), dtype=np.int32)
    vals = [fe.limbs_to_int_np(arr[:, i]) for i in range(b)]
    return jnp.asarray(arr), vals


def col_ints(x):
    x = np.asarray(x)
    return [fe.limbs_to_int_np(x[:, i]) for i in range(x.shape[1])]


@pytest.fixture(scope="module")
def ab():
    a, av = rand_fe_cols()
    b, bv = rand_fe_cols()
    return a, av, b, bv


def test_mul_sqr_add_sub(ab):
    a, av, b, bv = ab
    got = col_ints(jax.jit(pk.mul)(a, b))
    assert [g % fe.P_INT for g in got] == [
        (x * y) % fe.P_INT for x, y in zip(av, bv)
    ]
    got = col_ints(jax.jit(pk.sqr)(a))
    assert [g % fe.P_INT for g in got] == [x * x % fe.P_INT for x in av]
    got = col_ints(jax.jit(pk.add)(a, b))
    assert [g % fe.P_INT for g in got] == [(x + y) % fe.P_INT for x, y in zip(av, bv)]
    got = col_ints(jax.jit(pk.sub)(a, b))
    assert [g % fe.P_INT for g in got] == [(x - y) % fe.P_INT for x, y in zip(av, bv)]


def test_canonical_parity_eq(ab):
    a, av, b, bv = ab
    got = col_ints(jax.jit(pk.canonical)(a))
    assert got == [x % fe.P_INT for x in av]
    par = np.asarray(jax.jit(pk.parity)(a))
    assert list(par) == [(x % fe.P_INT) & 1 for x in av]
    assert not np.asarray(jax.jit(pk.eq)(a, b)).any()
    assert np.asarray(jax.jit(pk.eq)(a, a)).all()


def test_inv_legendre_sqrt(ab):
    a, av, b, bv = ab
    got = col_ints(jax.jit(pk.inv)(a))
    assert [g % fe.P_INT for g in got] == [
        pow(x % fe.P_INT, fe.P_INT - 2, fe.P_INT) for x in av
    ]
    leg = col_ints(jax.jit(pk.legendre)(a))
    assert [g % fe.P_INT for g in leg] == [
        pow(x % fe.P_INT, (fe.P_INT - 1) // 2, fe.P_INT) for x in av
    ]
    # sqrt of squares round-trips
    sq = jax.jit(pk.sqr)(a)
    ok, r = jax.jit(pk.sqrt)(sq)
    assert np.asarray(ok).all()
    r2 = col_ints(jax.jit(pk.sqr)(r))
    assert [g % fe.P_INT for g in r2] == [x * x % fe.P_INT for x in av]


def test_bytes_roundtrip(ab):
    a, av, _, _ = ab
    by = jax.jit(pk.to_bytes)(a)
    by_np = np.asarray(by)
    for i in range(B):
        want = (av[i] % fe.P_INT).to_bytes(32, "little")
        assert bytes(by_np[:, i].astype(np.uint8)) == want
    back = col_ints(jax.jit(pk.from_bytes32)(by))
    assert back == [x % fe.P_INT for x in av]


def test_scalar_reduce512_and_canonical():
    raw = rng.integers(0, 256, size=(64, B), dtype=np.int32)
    got = col_ints(jax.jit(pk.reduce512)(jnp.asarray(raw)))
    for i in range(B):
        v = int.from_bytes(bytes(raw[:, i].astype(np.uint8)), "little")
        assert got[i] == v % pk.L_INT

    s = rng.integers(0, 256, size=(32, B), dtype=np.int32)
    s[:, 0] = 0
    s[:, 1] = 255  # 2^256-1 > L
    canon = np.asarray(jax.jit(pk.is_canonical_scalar)(jnp.asarray(s)))
    for i in range(B):
        v = int.from_bytes(bytes(s[:, i].astype(np.uint8)), "little")
        assert canon[i] == (v < pk.L_INT)


def test_windows():
    s = rng.integers(0, 256, size=(32, B), dtype=np.int32)
    w4 = np.asarray(jax.jit(lambda x: pk.windows4_from_bytes(x, 256))(jnp.asarray(s)))
    w8 = np.asarray(jax.jit(lambda x: pk.windows8_from_bytes(x, 256))(jnp.asarray(s)))
    for i in range(B):
        v = int.from_bytes(bytes(s[:, i].astype(np.uint8)), "little")
        assert [int(d) for d in w4[:, i]] == [(v >> (4 * k)) & 0xF for k in range(64)]
        assert [int(d) for d in w8[:, i]] == [(v >> (8 * k)) & 0xFF for k in range(32)]

    a, av = rand_fe_cols()
    ac = jax.jit(pk.canonical)(a)
    w4l = np.asarray(jax.jit(lambda x: pk.windows4_from_limbs(x, 256))(ac))
    w8l = np.asarray(jax.jit(lambda x: pk.windows8_from_limbs(x, 256))(ac))
    for i in range(B):
        v = av[i] % fe.P_INT
        assert [int(d) for d in w4l[:, i]] == [(v >> (4 * k)) & 0xF for k in range(64)]
        assert [int(d) for d in w8l[:, i]] == [(v >> (8 * k)) & 0xFF for k in range(32)]
