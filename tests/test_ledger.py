"""The run ledger (obs/ledger.py): record schema, append-only JSONL
semantics, the OCT_LEDGER override/kill-switch, corrupt-line tolerance,
and the bench-shaped acceptance path — bench.append_ledger_record (the
exact function bench.main calls) must append exactly one well-formed
record per run."""

from __future__ import annotations

import json
import os

import pytest

from ouroboros_consensus_tpu.obs import ledger


@pytest.fixture
def tmp_ledger(tmp_path, monkeypatch):
    d = str(tmp_path / "ledger")
    monkeypatch.setenv("OCT_LEDGER", d)
    return d


def _lines(d):
    out = []
    for name in sorted(os.listdir(d)):
        with open(os.path.join(d, name), encoding="utf-8") as f:
            out.extend(ln for ln in f.read().splitlines() if ln.strip())
    return out


def test_record_run_appends_exactly_one_valid_line(tmp_ledger):
    rec = ledger.record_run(
        "unit", config={"n": 7}, result={"ok": True}, wall_s=1.25,
    )
    assert rec is not None
    lines = _lines(tmp_ledger)
    assert len(lines) == 1
    on_disk = json.loads(lines[0])
    assert ledger.validate_record(on_disk) == []
    assert on_disk["kind"] == "unit"
    assert on_disk["config"] == {"n": 7}
    assert on_disk["result"] == {"ok": True}
    assert on_disk["wall_s"] == 1.25
    # provenance is complete at append time, not reconstructed later
    assert "rev" in on_disk["git"] and "dirty" in on_disk["git"]
    assert isinstance(on_disk["env"], dict)
    # this very test runs under OCT_LEDGER -> the kill-switch state is
    # IN the record
    assert on_disk["env"].get("OCT_LEDGER") == tmp_ledger
    assert on_disk["host"]["platform"]
    # day-keyed file name
    (fname,) = os.listdir(tmp_ledger)
    assert fname.startswith("runs-") and fname.endswith(".jsonl")


def test_git_provenance_matches_checkout():
    prov = ledger.git_provenance()
    # this repo IS a git checkout: the rev must resolve
    assert prov["rev"] and len(prov["rev"]) == 40
    assert prov["dirty"] in (True, False)


def test_kill_switch_and_override(tmp_path, monkeypatch):
    monkeypatch.setenv("OCT_LEDGER", "0")
    assert ledger.ledger_dir() is None
    assert ledger.record_run("unit") is None
    d = str(tmp_path / "elsewhere")
    monkeypatch.setenv("OCT_LEDGER", d)
    assert ledger.ledger_dir() == d
    assert ledger.record_run("unit") is not None
    assert len(_lines(d)) == 1


def test_append_only_and_corrupt_line_tolerance(tmp_ledger):
    ledger.record_run("a", result={"i": 1})
    # a torn append (crash mid-write) must be skipped, not fatal
    path = ledger.day_file(tmp_ledger)
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"torn": \n')
    ledger.record_run("b", result={"i": 2})
    runs = ledger.read_runs(tmp_ledger)
    assert [r["kind"] for r in runs] == ["a", "b"]
    assert ledger.read_runs(tmp_ledger, kind="b")[0]["result"] == {"i": 2}


def test_validate_record_rejects_malformed():
    assert ledger.validate_record([]) != []
    assert ledger.validate_record({}) != []
    good = ledger.build_record("unit")
    assert ledger.validate_record(good) == []
    bad = dict(good)
    bad["schema"] = 99
    assert any("schema" in e for e in ledger.validate_record(bad))
    bad = dict(good)
    bad["metrics"] = "not-a-dict"
    assert any("metrics" in e for e in ledger.validate_record(bad))
    bad = dict(good)
    bad["wall_s"] = float("nan")
    assert any("JSON" in e for e in ledger.validate_record(bad))


def test_runtime_build_id_never_initializes_a_backend():
    """The parent bench process never touches the backend (a wedged TPU
    tunnel must not hang the ledger): with no backend initialized the
    probe must answer None, not block."""
    import sys

    if "jax" not in sys.modules:
        assert ledger.runtime_build_id() is None
    else:
        # jax already imported by the test session: the probe may
        # answer a string (backend up — conftest pinned cpu) or None,
        # but must never raise
        v = ledger.runtime_build_id()
        assert v is None or isinstance(v, str)


# ---------------------------------------------------------------------------
# Acceptance: a bench.py-shaped run appends exactly one well-formed
# record through the SAME function bench.main calls
# ---------------------------------------------------------------------------


def test_bench_shaped_run_appends_one_record(tmp_ledger):
    import bench

    out = {
        "metric": "end-to-end db-analyser revalidation of a "
                  "100000-header synthetic Praos chain",
        "value": 3985.7, "unit": "headers/s", "vs_baseline": 2.93,
        "build_id": "test-build-v9",
        "phases_s": {"dispatch": 1.5, "materialize": 2.0},
        "warmup_report": {"stages": {"ed@b8192": {"wall_s": 12.0}},
                          "refusals": []},
        "metrics": {"oct_windows_total": {"type": "counter",
                                          "samples": []}},
        "metrics_summary": {"windows": 13},
        "device_resources": {
            "ed@b8192|8192|7": {"flops": 123, "via": "jit"},
        },
    }
    rec = bench.append_ledger_record(out, baseline=1359.0,
                                     native_wall_s=49.8)
    assert rec is not None
    lines = _lines(tmp_ledger)
    assert len(lines) == 1
    on_disk = json.loads(lines[0])
    assert ledger.validate_record(on_disk) == []
    assert on_disk["kind"] == "bench"
    # the obs blocks land in their dedicated sections, and the result
    # is the SLIM outcome (no double banking of the big blocks)
    assert on_disk["warmup_report"] == out["warmup_report"]
    assert on_disk["metrics_summary"] == {"windows": 13}
    assert on_disk["device_resources"] == out["device_resources"]
    assert "metrics" not in on_disk["result"]
    assert "warmup_report" not in on_disk["result"]
    assert on_disk["result"]["value"] == 3985.7
    assert on_disk["build_id"] == "test-build-v9"
    assert on_disk["config"]["headers"] == bench.BENCH_HEADERS
    assert on_disk["extra"]["native_baseline_per_s"] == 1359.0


def test_bench_ledger_failure_is_soft(tmp_path, monkeypatch):
    """The bench's one JSON line must survive a broken ledger: point
    OCT_LEDGER at a path that cannot be a directory."""
    import bench

    blocker = tmp_path / "not-a-dir"
    blocker.write_text("file, not dir")
    monkeypatch.setenv("OCT_LEDGER", str(blocker / "sub"))
    assert bench.append_ledger_record({"value": 1.0}) is None


def test_bench_suite_emit_appends_record(tmp_ledger, capsys):
    """The suite path: every _emit'd config row lands in the ledger as
    one kind="bench_suite" record."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_suite", os.path.join(repo, "scripts", "bench_suite.py")
    )
    bs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bs)
    bs._emit(2, "standalone Ed25519 verifies", 256, 0.5, 1.0,
             extra={"warmup_report": {"stages": {}}})
    runs = ledger.read_runs(tmp_ledger, kind="bench_suite")
    assert len(runs) == 1
    rec = runs[0]
    assert ledger.validate_record(rec) == []
    assert rec["config"] == {"config": 2, "n": 256}
    assert rec["result"]["vs_baseline"] == 2.0
    # the obs block moved to its dedicated section, out of the result
    assert "warmup_report" not in rec["result"]
    assert rec["warmup_report"] == {"stages": {}}


# ---------------------------------------------------------------------------
# the round-11 CLI: python -m ouroboros_consensus_tpu.obs.ledger tail
# ---------------------------------------------------------------------------


def test_cli_tail_last_and_build_id_filters(tmp_ledger, capsys):
    for i in range(5):
        ledger.record_run(
            "bench" if i % 2 == 0 else "profile_replay",
            config={"i": i},
            result={"value": 1000.0 + i, "unit": "headers/s"},
            wall_s=10.0 + i,
            build_id=f"axon-v{i % 2}",
        )
    # tail --last 2: the two NEWEST records, one line each
    rc = ledger.main(["tail", "--last", "2", "--dir", tmp_ledger])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0 and len(out) == 2
    assert "1003" in out[0] and "1004" in out[1]
    assert "headers/s" in out[1] and "bench" in out[1]
    # --build-id substring filter
    rc = ledger.main(
        ["tail", "--last", "10", "--build-id", "axon-v1",
         "--dir", tmp_ledger]
    )
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0 and len(out) == 2  # i in {1, 3}
    # --kind filter composes
    rc = ledger.main(
        ["tail", "--last", "10", "--kind", "bench", "--dir", tmp_ledger]
    )
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0 and len(out) == 3  # i in {0, 2, 4}
    # --json emits the full records as JSONL
    rc = ledger.main(
        ["tail", "--last", "1", "--json", "--dir", tmp_ledger]
    )
    out = capsys.readouterr().out.strip()
    rec = json.loads(out)
    assert ledger.validate_record(rec) == []
    assert rec["result"]["value"] == 1004.0
    # empty result set: non-zero exit, no traceback
    rc = ledger.main(
        ["tail", "--build-id", "nope", "--dir", tmp_ledger]
    )
    capsys.readouterr()
    assert rc == 1
    # --last 0 means NONE, not "the whole ledger" (runs[-0:] trap)
    rc = ledger.main(["tail", "--last", "0", "--dir", tmp_ledger])
    out = capsys.readouterr().out
    assert rc == 1 and "no matching" in out


def test_cli_blurb_surfaces_no_device_stalls_and_shards(tmp_ledger, capsys):
    """The one-liner answers "what did the last live session do": a
    no-device round shows its reason, stall trips and per-shard
    telemetry are called out."""
    ledger.record_run(
        "bench",
        result={"value": 2100.0, "unit": "headers/s",
                "device_unavailable": True,
                "no_device_reason": "backend-probe-timeout"},
        metrics={
            "oct_stalls_total": {"samples": [
                {"labels": {"phase": "dispatch"}, "value": 1},
            ]},
            "oct_shard_lanes_total": {"samples": [
                {"labels": {"shard": str(i)}, "value": 8} for i in range(8)
            ]},
        },
        wall_s=100.0,
    )
    rc = ledger.main(["tail", "--last", "1", "--dir", tmp_ledger])
    out = capsys.readouterr().out
    assert rc == 0
    assert "NO-DEVICE (backend-probe-timeout)" in out
    assert "1 STALL(s)" in out
    assert "per-shard telemetry x8" in out


def test_cli_module_entrypoint_runs(tmp_ledger):
    """python -m ouroboros_consensus_tpu.obs.ledger actually executes
    (the __main__ guard)."""
    import subprocess
    import sys

    ledger.record_run("unit", result={"value": 1.0, "unit": "x"})
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "ouroboros_consensus_tpu.obs.ledger",
         "tail", "--last", "1", "--dir", tmp_ledger],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "unit" in proc.stdout
