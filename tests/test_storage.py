"""Storage engine tests: ImmutableDB, VolatileDB, LedgerDB, ChainDB.

Mirrors the reference's model-based storage tests (SURVEY.md §4 tier 2) in
spirit: every property is phrased against expected chain/store contents,
including corruption-and-truncate recovery.
"""

import os
from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import Block, Point, forge_block
from ouroboros_consensus_tpu.block.abstract import block_point
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage import (
    ChainDB,
    ImmutableDB,
    LedgerDB,
    VolatileDB,
)
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=3,  # tiny k: exercises copy-to-immutable quickly
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=3,
)
POOLS = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)
ETA0 = b"\x22" * 32


def mk_ext(use_device_batch=False):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=use_device_batch)
    return ExtLedger(ledger, protocol)


def genesis_state(ext):
    st = ext.genesis(ext.ledger.genesis_state([]))
    return replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(st.header_state.chain_dep_state, epoch_nonce=ETA0),
        ),
    )


def forge_chain(n, start_slot=1, start_bno=0, prev=None, pool_ix=0, slot_step=1):
    blocks = []
    for i in range(n):
        b = forge_block(
            PARAMS, POOLS[(pool_ix + i) % len(POOLS)],
            slot=start_slot + i * slot_step, block_no=start_bno + i,
            prev_hash=prev, epoch_nonce=ETA0,
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


# -- ImmutableDB -------------------------------------------------------------


def test_immutable_roundtrip(tmp_path):
    db = ImmutableDB(str(tmp_path / "imm"), chunk_size=4)
    blocks = forge_chain(10)
    for b in blocks:
        db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    assert db.n_blocks() == 10
    assert db.tip().slot == blocks[-1].slot

    # reopen: indices reload, tail chunk revalidated
    db2 = ImmutableDB(str(tmp_path / "imm"), chunk_size=4)
    assert db2.n_blocks() == 10
    streamed = [Block.from_bytes(raw) for _, raw in db2.stream_all()]
    assert streamed == blocks
    assert db2.get_block_bytes(blocks[3].point) == blocks[3].bytes_


def test_immutable_corrupt_tail_truncates(tmp_path):
    db = ImmutableDB(str(tmp_path / "imm"), chunk_size=100)
    blocks = forge_chain(6)
    for b in blocks:
        db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    # corrupt the last block's bytes in the chunk file
    chunk = tmp_path / "imm" / "00000.chunk"
    data = bytearray(chunk.read_bytes())
    data[-3] ^= 0xFF
    chunk.write_bytes(bytes(data))

    db2 = ImmutableDB(str(tmp_path / "imm"), chunk_size=100)
    assert db2.n_blocks() == 5  # corrupted tail dropped
    assert db2.tip().slot == blocks[4].slot


def test_immutable_orphan_index_swept_on_open():
    """Crash recipe from the ImmutableModel: the chunk file's creation was
    never synced (vanishes on crash) but a reparse had atomically written
    the index (durable). Reopening over the orphan index must remove it —
    otherwise a later append extends the stale index and the same block
    appears twice."""
    from ouroboros_consensus_tpu.utils.fs import MockFS

    fs = MockFS()
    b = forge_chain(1)[0]
    db = ImmutableDB("imm", chunk_size=4, fs=fs)
    db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    # index damage + reopen: reparse rebuilds the index (atomic => durable)
    fs.truncate_file("imm/00000.index", 0)
    db = ImmutableDB("imm", chunk_size=4, validate_all=True, fs=fs)
    assert db.n_blocks() == 1
    # crash: unsynced chunk file vanishes, durable index survives alone
    fs.crash(0.0)
    assert not fs.exists("imm/00000.chunk")
    db = ImmutableDB("imm", chunk_size=4, validate_all=True, fs=fs)
    assert db.is_empty
    assert not fs.exists("imm/00000.index")  # orphan swept
    # re-appending the block after recovery must not duplicate it
    db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    db = ImmutableDB("imm", chunk_size=4, validate_all=True, fs=fs)
    assert [(e.slot, raw) for e, raw in db.stream_all()] == [(b.slot, b.bytes_)]


def test_immutable_truncate_after(tmp_path):
    db = ImmutableDB(str(tmp_path / "imm"), chunk_size=4)
    blocks = forge_chain(10)
    for b in blocks:
        db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    db.truncate_after(blocks[6].point)
    assert db.n_blocks() == 7
    db2 = ImmutableDB(str(tmp_path / "imm"), chunk_size=4)
    assert db2.n_blocks() == 7


# -- VolatileDB --------------------------------------------------------------


def test_volatile_roundtrip_and_gc(tmp_path):
    db = VolatileDB(str(tmp_path / "vol"), max_blocks_per_file=3)
    blocks = forge_chain(8)
    for b in blocks:
        db.put_block(b)
        db.put_block(b)  # idempotent
    assert db.get_block_bytes(blocks[2].hash_) == blocks[2].bytes_
    assert db.filter_by_predecessor(None) == {blocks[0].hash_}
    assert db.filter_by_predecessor(blocks[0].hash_) == {blocks[1].hash_}

    # reopen rebuilds the in-memory maps
    db2 = VolatileDB(str(tmp_path / "vol"), max_blocks_per_file=3)
    assert set(db2.all_hashes()) == {b.hash_ for b in blocks}

    # GC removes whole files of old blocks (3 per file)
    db2.garbage_collect(blocks[5].slot + 1)
    remaining = set(db2.all_hashes())
    assert {b.hash_ for b in blocks[6:]} <= remaining
    assert blocks[0].hash_ not in remaining


def test_volatile_torn_write_truncates(tmp_path):
    db = VolatileDB(str(tmp_path / "vol"), max_blocks_per_file=100)
    blocks = forge_chain(3)
    for b in blocks:
        db.put_block(b)
    f = tmp_path / "vol" / "blocks-0000.dat"
    data = f.read_bytes()
    f.write_bytes(data[:-5])  # torn tail
    db2 = VolatileDB(str(tmp_path / "vol"), max_blocks_per_file=100)
    assert set(db2.all_hashes()) == {b.hash_ for b in blocks[:2]}


# -- LedgerDB ----------------------------------------------------------------


def test_ledgerdb_push_rollback_snapshots(tmp_path):
    ext = mk_ext()
    gen = genesis_state(ext)
    db = LedgerDB(ext, k=PARAMS.security_param, anchor=gen)
    blocks = forge_chain(5)
    for b in blocks:
        db.push(b)
    assert db.volatile_length() == 3  # pruned to k
    assert db.tip_point() == blocks[-1].point

    assert db.rollback(2)
    assert db.tip_point() == blocks[2].point
    assert not db.rollback(5)  # beyond k

    # switch to a fork from block 2
    fork = forge_chain(3, start_slot=20, start_bno=3, prev=blocks[2].hash_, pool_ix=1)
    assert db.switch(0, fork)
    assert db.tip_point() == fork[-1].point

    # snapshots
    snap = tmp_path / "snaps"
    name = db.take_snapshot(str(snap))
    assert name is not None
    assert LedgerDB.list_snapshots(str(snap))


def test_ledgerdb_init_replay(tmp_path):
    ext = mk_ext()
    gen = genesis_state(ext)
    imm = ImmutableDB(str(tmp_path / "imm"), chunk_size=100)
    blocks = forge_chain(6)
    for b in blocks:
        imm.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    db = LedgerDB.init_from_snapshots(
        ext, PARAMS.security_param, str(tmp_path / "snaps"), gen, imm
    )
    assert ext.tip_slot(db.current()) == blocks[-1].slot
    # header states replayed without crypto: tip matches
    assert db.current().header_state.tip.block_no == 5


# -- ChainDB + ChainSel ------------------------------------------------------


def open_db(tmp_path, name="db"):
    ext = mk_ext()
    gen = genesis_state(ext)
    return open_chaindb(
        str(tmp_path / name), ext, gen, k=PARAMS.security_param, chunk_size=100
    ), ext


def test_chaindb_linear_growth(tmp_path):
    db, _ = open_db(tmp_path)
    blocks = forge_chain(7)
    for b in blocks:
        r = db.add_block(b)
        assert r.selected
    assert db.tip_point() == blocks[-1].point
    # k=3: 4 blocks copied to immutable
    assert db.immutable.n_blocks() == 4
    assert len(db.current_chain) == 3
    # full chain streams in order
    assert [b.hash_ for b in db.stream_all()] == [b.hash_ for b in blocks]


def test_chaindb_prefers_longer_fork(tmp_path):
    db, _ = open_db(tmp_path)
    main = forge_chain(4)
    for b in main:
        db.add_block(b)
    # fork from block 1 with more blocks (longer chain wins)
    fork = forge_chain(
        5, start_slot=main[1].slot + 1, start_bno=2, prev=main[1].hash_, pool_ix=1,
        slot_step=2,
    )
    for b in fork:
        db.add_block(b)
    assert db.tip_point() == fork[-1].point


def test_chaindb_out_of_order_arrival(tmp_path):
    db, _ = open_db(tmp_path)
    blocks = forge_chain(5)
    # arrive newest-first: nothing selectable until the chain connects
    for b in reversed(blocks[1:]):
        r = db.add_block(b)
        assert not r.selected
    r = db.add_block(blocks[0])
    assert r.selected
    assert db.tip_point() == blocks[-1].point


def test_chaindb_invalid_block_marked(tmp_path):
    db, _ = open_db(tmp_path)
    blocks = forge_chain(4)
    bad_body = Block(blocks[2].header, (b"not-a-valid-tx-cbor",))
    for b in [blocks[0], blocks[1], bad_body]:
        db.add_block(b)
    # invalid block rejected, prefix adopted
    assert db.tip_point() == blocks[1].point
    assert db.get_is_invalid_block(bad_body.hash_) is not None
    # adding the valid block with the same header hash is now impossible
    # (same hash marked invalid) — extension continues on valid prefix
    more = forge_chain(2, start_slot=10, start_bno=2, prev=blocks[1].hash_, pool_ix=1)
    for b in more:
        db.add_block(b)
    assert db.tip_point() == more[-1].point


def test_chaindb_restart_recovers(tmp_path):
    db, _ = open_db(tmp_path)
    blocks = forge_chain(7)
    for b in blocks:
        db.add_block(b)
    tip = db.tip_point()
    # reopen from disk (snapshot + immutable + volatile reparse)
    db2, _ = open_db(tmp_path)
    assert db2.tip_point() == tip
    assert [b.hash_ for b in db2.stream_all()] == [b.hash_ for b in blocks]


def test_chaindb_follower_updates(tmp_path):
    db, _ = open_db(tmp_path)
    f = db.new_follower()
    blocks = forge_chain(3)
    for b in blocks:
        db.add_block(b)
    ups = f.take_updates()
    added = [u[1].hash_ for u in ups if u[0] == "addblock"]
    assert added == [b.hash_ for b in blocks]


class _CountingVerifier:
    """CryptoVerifier wrapper counting verify calls (for Apply-vs-Reapply
    assertions, Impl/LgrDB.hs:330)."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = 0

    def verify_dsign(self, *a):
        self.calls += 1
        return self.inner.verify_dsign(*a)

    def verify_kes(self, *a):
        self.calls += 1
        return self.inner.verify_kes(*a)

    def verify_vrf(self, *a):
        self.calls += 1
        return self.inner.verify_vrf(*a)


def test_chaindb_fork_switch_reapplies_prev_validated(tmp_path):
    """A fork switch crossing blocks validated earlier must NOT re-run
    their header crypto: LgrDB's prev-applied set chooses Reapply
    (LgrDB.hs:86,330)."""
    counting = _CountingVerifier(praos.HOST_VERIFIER)
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False, crypto=counting)
    ext = ExtLedger(ledger, protocol)
    gen = genesis_state(ext)
    db = open_chaindb(str(tmp_path / "db"), ext, gen, k=PARAMS.security_param,
                      chunk_size=100)

    # chain A: 2 blocks (pool 0 at even slots)
    chain_a = forge_chain(2, start_slot=2, slot_step=2)
    for b in chain_a:
        assert db.add_block(b).selected
    # chain B: 3 blocks from genesis (odd slots) — longer, switch to it
    chain_b = forge_chain(3, start_slot=1, pool_ix=1, slot_step=2)
    for b in chain_b:
        db.add_block(b)
    assert db.tip_point().hash_ == chain_b[-1].hash_

    # extend A to 4 blocks: switch back crosses A's 2 OLD blocks
    chain_a_ext = forge_chain(
        2, start_slot=chain_a[-1].slot + 2, start_bno=2,
        prev=chain_a[-1].hash_, slot_step=2,
    )
    calls_before = counting.calls
    for b in chain_a_ext:
        db.add_block(b)
    assert db.tip_point().hash_ == chain_a_ext[-1].hash_
    # only the 2 NEW blocks paid crypto (3 verifies each: dsign+kes+vrf);
    # the 2 previously-validated A blocks were reapplied for free
    assert counting.calls - calls_before == 2 * 3, (
        f"expected 6 verifies for the 2 fresh blocks, "
        f"saw {counting.calls - calls_before}"
    )


def test_chaindb_ranged_stream_gc_safe(tmp_path):
    """ChainDB.stream (API.hs:274, Impl/Iterator.hs): ranged streaming
    across the Immutable/Volatile boundary, robust to blocks MOVING
    between the stores mid-iteration (background copy + GC)."""
    from ouroboros_consensus_tpu.storage.chaindb import MissingBlockError

    db, _ = open_db(tmp_path)
    blocks = forge_chain(8)  # k=3: 5 blocks copied to immutable
    for b in blocks:
        db.add_block(b)
    # full stream == stream_all
    assert [b.hash_ for b in db.stream()] == [b.hash_ for b in blocks]
    # ranged: after blocks[1] up to blocks[5]
    got = list(db.stream(blocks[1].point, blocks[5].point))
    assert [b.hash_ for b in got] == [b.hash_ for b in blocks[2:6]]
    # plan pinned, bodies resolved lazily: blocks copied+GC'd between
    # creation and consumption are found in the ImmutableDB
    it = db.stream(blocks[1].point, blocks[5].point)
    for b in forge_chain(3, start_slot=9, start_bno=8, prev=blocks[-1].hash_):
        db.add_block(b)  # advances immutable tip; GCs volatile files
    assert [b.hash_ for b in it] == [b.hash_ for b in blocks[2:6]]
    # unknown bounds are reported (UnknownRange)
    import pytest as _pytest

    with _pytest.raises(MissingBlockError):
        db.stream(Point(999, b"x" * 32), None)


def test_init_chain_selection_not_shadowed_by_invalid_candidate(tmp_path):
    """Regression (found by TestChainDBModel): when the best-RANKED
    candidate contains an invalid block, selection must fall through to
    the next-best fully-valid candidate instead of settling on the
    truncated prefix — both at reopen (initialChainSelection) and in
    chainSelectionForBlock's loop."""
    from ouroboros_consensus_tpu.block.praos_block import Block as PB
    from ouroboros_consensus_tpu.block.praos_block import Header as PH

    db, ext = open_db(tmp_path)
    main = forge_chain(2)
    db.add_block(main[0])
    db.add_block(main[1])
    # a corrupted-signature SIBLING of main[1] whose tip deterministically
    # OUTRANKS it (same length -> VRF tie-break; grind slots until the
    # tie-break favors the bad block), so selection tries it first and
    # truncates to [main0]
    proto = ext.protocol
    bad = None
    for slot in range(3, 40, 2):
        cand = forge_chain(1, start_slot=slot, start_bno=1,
                           prev=main[0].hash_, pool_ix=1)[0]
        if proto.compare_candidates(
            proto.select_view(main[1].header), proto.select_view(cand.header)
        ) > 0:
            bad = PB(
                PH(cand.header.body,
                   bytes([cand.header.kes_sig[0] ^ 0xFF]) + cand.header.kes_sig[1:]),
                cand.txs,
            )
            break
    assert bad is not None, "no outranking slot found"
    db.add_block(bad)
    assert db.tip_point().hash_ == main[1].hash_, "valid chain shadowed"

    # reopen (in-memory invalid set wiped): initial selection must again
    # end on the fully-valid chain, not the bad candidate's prefix
    db.close()
    db2, _ = open_db(tmp_path)
    assert db2.tip_point().hash_ == main[1].hash_


def test_async_mode_equals_sync_mode(tmp_path):
    """The decoupled add-block queue + background copy/GC must produce
    EXACTLY the chain the synchronous path produces for the same add
    sequence (ChainSel.hs:217-246 decoupling is an execution detail,
    not a semantics change)."""
    from ouroboros_consensus_tpu.utils.sim import Sim

    blocks = forge_chain(8)
    fork = forge_chain(3, start_slot=2, start_bno=3,
                       prev=blocks[2].hash_, pool_ix=1, slot_step=7)
    sequence = blocks[:4] + fork + blocks[4:]

    db_sync, _ = open_db(tmp_path, "sync")
    for b in sequence:
        db_sync.add_block(b)

    db_async, _ = open_db(tmp_path, "async")
    sim = Sim()
    runners = db_async.start_decoupled(sim)
    for i, r in enumerate(runners):
        sim.spawn(r, f"runner{i}")

    def feeder():
        from ouroboros_consensus_tpu.utils.sim import Sleep, Wait

        for b in sequence:
            p = db_async.add_block_async(b)
            if p.result is None:
                yield Wait(p.processed)
            yield Sleep(0.01)

    sim.spawn(feeder(), "feeder")
    sim.run(until=60.0)

    assert [b.hash_ for b in db_sync.stream_all()] == [
        b.hash_ for b in db_async.stream_all()
    ]
    assert db_sync.tip_point() == db_async.tip_point()


# -- DiskPolicy (Storage/LedgerDB/DiskPolicy.hs:87-108) ----------------------


def test_disk_policy_fresh_run_snapshots_at_k():
    from ouroboros_consensus_tpu.storage.chaindb import DiskPolicy

    p = DiskPolicy(k=2160)
    assert p.interval_s == 4320.0  # k*2 seconds = 72 min at k=2160
    # NoSnapshotTakenYet: only the k-block rule applies, time irrelevant
    assert not p.should_take_snapshot(2159, now_s=1e9)
    assert p.should_take_snapshot(2160, now_s=0.0)


def test_disk_policy_time_interval_and_burst():
    from ouroboros_consensus_tpu.storage.chaindb import DiskPolicy

    p = DiskPolicy(k=2160)
    p.snapshot_taken(1000.0)
    # below the interval with few blocks: no
    assert not p.should_take_snapshot(10, now_s=1000.0 + 4319.0)
    # interval reached: yes, regardless of block count
    assert p.should_take_snapshot(0, now_s=1000.0 + 4320.0)
    # burst rule: >= 50k blocks AND >= 6 min
    assert not p.should_take_snapshot(50_000, now_s=1000.0 + 359.0)
    assert p.should_take_snapshot(50_000, now_s=1000.0 + 360.0)
    assert not p.should_take_snapshot(49_999, now_s=1000.0 + 360.0)
    # explicit requested interval overrides the default
    q = DiskPolicy(k=4, requested_interval_s=100.0)
    q.snapshot_taken(0.0)
    assert q.should_take_snapshot(1, now_s=100.0)
    assert not q.should_take_snapshot(1, now_s=99.0)


def test_chaindb_time_based_snapshots_on_sim_clock(tmp_path):
    """The ChainDB honors the time-based DiskPolicy against the node's
    VIRTUAL clock: advancing sim time past the interval triggers exactly
    the expected snapshots as blocks are copied to the immutable tier."""
    from ouroboros_consensus_tpu.storage.chaindb import DiskPolicy
    from ouroboros_consensus_tpu.storage.ledgerdb import LedgerDB

    class FakeRuntime:
        now = 0.0

        def fire(self, ev):
            pass

    ext = mk_ext()
    gen = genesis_state(ext)
    db = open_chaindb(str(tmp_path / "db"), ext, gen, k=PARAMS.security_param)
    db.runtime = FakeRuntime()
    db.disk_policy = DiskPolicy(k=PARAMS.security_param,
                                requested_interval_s=60.0)
    snap_dir = db.snap_dir
    blocks = forge_chain(20)
    # fresh run: first snapshot once k (=3) blocks were copied
    for b in blocks[:8]:
        db.add_block(b)
    first = LedgerDB.list_snapshots(snap_dir)
    assert first, "fresh-run k-block snapshot missing"
    n0 = len(first)

    # time below interval: copying more blocks must NOT snapshot
    db.runtime.now = 30.0
    for b in blocks[8:14]:
        db.add_block(b)
    assert len(LedgerDB.list_snapshots(snap_dir)) == n0 or \
        LedgerDB.list_snapshots(snap_dir) == first

    # past the interval: next copy takes a snapshot
    db.runtime.now = 100.0
    for b in blocks[14:]:
        db.add_block(b)
    after = LedgerDB.list_snapshots(snap_dir)
    assert after != first


def _fix_index_crc(dirpath, chunk_name, index_name, entry_ix):
    """Recompute the stored CRC of entry `entry_ix` from the (corrupted)
    chunk bytes, so the CRC walk passes and only deeper checks can
    catch the corruption."""
    import zlib

    from ouroboros_consensus_tpu.utils import cbor

    idata = (dirpath / index_name).read_bytes()
    rows, off = [], 0
    while off < len(idata):
        obj, off = cbor.decode_prefix(idata, off)
        rows.append(list(obj))
    data = (dirpath / chunk_name).read_bytes()
    e_off, e_size = rows[entry_ix][3], rows[entry_ix][4]
    rows[entry_ix][5] = zlib.crc32(data[e_off : e_off + e_size])
    (dirpath / index_name).write_bytes(
        b"".join(cbor.encode(r) for r in rows)
    )
    return e_off, e_size


def test_integrity_bad_before_crc_bad_truncates_earlier(tmp_path):
    """Deep validation order (round-5 review finding): a written-corrupt
    block (CRC consistent, body hash wrong) EARLIER in the chunk must
    truncate before a bit-rotted (CRC-bad) block later — the fast
    native path must match the per-blob reference loop."""
    from ouroboros_consensus_tpu.storage.open import (
        default_check_integrity, default_check_integrity_batch,
    )

    db = ImmutableDB(str(tmp_path / "imm"), chunk_size=100)
    blocks = forge_chain(8)
    for b in blocks:
        db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    chunk = tmp_path / "imm" / "00000.chunk"
    data = bytearray(chunk.read_bytes())
    # block 2: flip a byte of the DECLARED body hash, keep CRC consistent
    e2 = db._entries[0][2]
    span = bytes(data[e2.offset : e2.offset + e2.size])
    bh = blocks[2].header.body.body_hash
    ix = span.index(bh)
    data[e2.offset + ix] ^= 0xFF
    # block 5: plain bit-rot (CRC now mismatches)
    e5 = db._entries[0][5]
    data[e5.offset + e5.size - 2] ^= 0xFF
    chunk.write_bytes(bytes(data))
    _fix_index_crc(tmp_path / "imm", "00000.chunk", "00000.index", 2)

    db2 = ImmutableDB(
        str(tmp_path / "imm"), chunk_size=100,
        check_integrity=default_check_integrity, validate_all=True,
        check_integrity_batch=default_check_integrity_batch,
    )
    assert db2.n_blocks() == 2  # truncated at the WRITTEN-corrupt block


def test_body_hash_bad_before_malformed_truncates_earlier(tmp_path):
    """Companion ordering case: body-hash corruption at block 1, an
    unparseable block at 4 — truncation lands on block 1."""
    from ouroboros_consensus_tpu.storage.open import (
        default_check_integrity, default_check_integrity_batch,
    )

    db = ImmutableDB(str(tmp_path / "imm"), chunk_size=100)
    blocks = forge_chain(6)
    for b in blocks:
        db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    chunk = tmp_path / "imm" / "00000.chunk"
    data = bytearray(chunk.read_bytes())
    e1 = db._entries[0][1]
    span = bytes(data[e1.offset : e1.offset + e1.size])
    ix = span.index(blocks[1].header.body.body_hash)
    data[e1.offset + ix] ^= 0xFF
    e4 = db._entries[0][4]
    data[e4.offset] = 0xFF  # no longer a CBOR array head: unparseable
    chunk.write_bytes(bytes(data))
    _fix_index_crc(tmp_path / "imm", "00000.chunk", "00000.index", 1)
    _fix_index_crc(tmp_path / "imm", "00000.chunk", "00000.index", 4)

    db2 = ImmutableDB(
        str(tmp_path / "imm"), chunk_size=100,
        check_integrity=default_check_integrity, validate_all=True,
        check_integrity_batch=default_check_integrity_batch,
    )
    assert db2.n_blocks() == 1
