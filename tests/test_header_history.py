"""HeaderStateHistory as a first-class component.

Reference: `Ouroboros.Consensus.HeaderStateHistory` (HeaderStateHistory.hs
current/append/rewind/trim/fromChain) — the k-deep header-state history
shared by the ChainSync client's candidate (Client.hs:291) and the
ChainDB's header-state-at-a-recent-point query.
"""

from dataclasses import replace
from fractions import Fraction

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.ledger.header_history import HeaderStateHistory
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=5,  # tiny k: trimming + immutable-copy kick in fast
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOLS = [fixtures.make_pool(i, kes_depth=2) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)
ETA0 = b"\x33" * 32


def _forge_chain(n, start_slot=1, prev=None, block_no=0):
    blocks = []
    for i in range(n):
        b = forge_block(
            PARAMS, POOLS[i % 2], slot=start_slot + i, block_no=block_no + i,
            prev_hash=prev, epoch_nonce=ETA0,
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


def _mk_ext():
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    return ext, st


# -- the pure component ------------------------------------------------------


def test_from_chain_matches_sequential_fold():
    """fromChain recomputes the same states the protocol fold produces."""
    ext, st = _mk_ext()
    headers = [b.header for b in _forge_chain(8)]
    hh = HeaderStateHistory.from_chain(
        ext.protocol, lambda _s: LVIEW, st.header_state.chain_dep_state, headers
    )
    assert len(hh.headers) == 8
    assert len(hh.states) == 9
    # sequential fold twin
    s = st.header_state.chain_dep_state
    for i, h in enumerate(headers):
        s = ext.protocol.update(
            h.to_view(), h.slot, ext.protocol.tick(LVIEW, h.slot, s)
        )
        assert hh.states[i + 1] == s
    assert hh.current() == s
    assert hh.tip_point() == headers[-1].point


def test_rewind_and_rollback_restore_states():
    ext, st = _mk_ext()
    headers = [b.header for b in _forge_chain(6)]
    hh = HeaderStateHistory.from_chain(
        ext.protocol, lambda _s: LVIEW, st.header_state.chain_dep_state, headers
    )
    mid_state = hh.states[4]
    assert hh.truncate_to(headers[3].point)
    assert hh.current() == mid_state
    assert len(hh.headers) == 4
    # rewind to the anchor
    assert hh.truncate_to(None)
    assert hh.current() == st.header_state.chain_dep_state
    # unknown point fails
    assert not hh.truncate_to(headers[5].point)
    # rollback_n symmetry
    hh2 = HeaderStateHistory.from_chain(
        ext.protocol, lambda _s: LVIEW, st.header_state.chain_dep_state, headers
    )
    assert hh2.rollback_n(2)
    assert hh2.states == hh2.states[: len(hh2.headers) + 1]
    assert len(hh2.headers) == 4
    assert not hh2.rollback_n(99)


def test_trim_to_k_and_settled_gate():
    ext, st = _mk_ext()
    headers = [b.header for b in _forge_chain(10)]
    base = st.header_state.chain_dep_state

    hh = HeaderStateHistory.from_chain(
        ext.protocol, lambda _s: LVIEW, base, headers, k=4
    )
    assert len(hh.headers) == 4  # trimmed while extending
    assert hh.trimmed
    assert [h.point for h in hh.headers] == [h.point for h in headers[-4:]]
    # anchor rollback after trimming is a disconnect-class failure
    assert not hh.truncate_to(None)

    # the settled gate holds trimming back until the owner settles blocks
    settled: set = set()
    hh = HeaderStateHistory(k=4, settled=lambda p: p in settled)
    hh.reset(base)
    for h in headers:
        ticked = ext.protocol.tick(LVIEW, h.slot, hh.current())
        hh.extend(h, ext.protocol.update(h.to_view(), h.slot, ticked))
    assert len(hh.headers) == 10  # nothing settled: nothing trimmed
    for h in headers[:8]:
        settled.add(h.point)
    hh.trim()
    assert len(hh.headers) == 4
    assert hh.trimmed  # the anchor moved past the original base


def test_state_at_lookup():
    ext, st = _mk_ext()
    headers = [b.header for b in _forge_chain(6)]
    hh = HeaderStateHistory.from_chain(
        ext.protocol, lambda _s: LVIEW, st.header_state.chain_dep_state, headers
    )
    for i, h in enumerate(headers):
        assert hh.state_at(h.point) == hh.states[i + 1]
    missing = _forge_chain(1, start_slot=99, block_no=99)[0]
    assert hh.state_at(missing.header.point) is None


# -- ChainDB integration -----------------------------------------------------


def test_chaindb_maintains_header_history(tmp_path):
    """The ChainDB's history tracks adoption, stays k-bounded through
    immutable copy, and header_state_at agrees with the LedgerDB."""
    ext, st = _mk_ext()
    db = open_chaindb(str(tmp_path / "db"), ext, st, PARAMS.security_param)
    blocks = _forge_chain(12)
    for b in blocks:
        db.add_block(b)
    hh = db.header_history
    assert len(hh.headers) <= PARAMS.security_param
    assert hh.states[-1].tip.hash_ == blocks[-1].hash_
    # every current_chain point answers, and matches the LedgerDB's view
    for b in db.current_chain:
        hs = db.header_state_at(b.point)
        assert hs is not None
        ext_state = db.ledgerdb.past_state(b.point)
        if ext_state is not None:
            assert hs == ext_state.header_state
    # a point deeper than k is beyond both the history and the LedgerDB
    assert db.header_state_at(blocks[0].point) is None


def test_chaindb_history_follows_fork_switch(tmp_path):
    ext, st = _mk_ext()
    db = open_chaindb(str(tmp_path / "db"), ext, st, PARAMS.security_param)
    trunk = _forge_chain(4)
    for b in trunk:
        db.add_block(b)
    assert db.header_history.states[-1].tip.hash_ == trunk[-1].hash_
    # longer fork from trunk[1] (offset slots => distinct hashes)
    fork = _forge_chain(
        4, start_slot=trunk[1].slot + 5, prev=trunk[1].hash_, block_no=2
    )
    for b in fork:
        db.add_block(b)
    hh = db.header_history
    assert db.current_chain[-1].hash_ == fork[-1].hash_
    assert hh.states[-1].tip.hash_ == fork[-1].hash_
    # the replaced suffix is gone from the history
    assert hh.state_at(trunk[3].point) is None
    assert hh.state_at(fork[0].point) is not None
    # history/chain alignment: states[i+1].tip == headers[i]
    for i, h in enumerate(hh.headers):
        assert hh.states[i + 1].tip.hash_ == h.hash_
