"""Local ChainSync over WHOLE BLOCKS — the wallet protocol.

Reference: `ouroboros-consensus-diffusion/.../Network/NodeToClient.hs:
92-121` (chainSyncBlocksServer): local clients follow the node's chain
receiving serialised blocks, including roll-backwards when the node
switches forks. Negotiated at node-to-client v4 (handshake.py).
"""

from fractions import Fraction

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.block.praos_block import Block
from ouroboros_consensus_tpu.node.apps import node_to_client_apps
from ouroboros_consensus_tpu.utils.sim import Recv, Send, Sim

import tests.test_pipelining as tp


def _forge_chain(pool, slots, prev=None, block_no=0, body=b"a"):
    from ouroboros_consensus_tpu.ledger.mock import encode_tx

    blocks = []
    for s in slots:
        # a valid mock-ledger tx per block: zero-value output, no inputs
        # (conserves value from an empty genesis), distinct per chain so
        # fork bodies differ
        tx = encode_tx([], [(b"%s-%d" % (body, s), 0)])
        b = forge_block(
            tp.PARAMS, pool, slot=s, block_no=block_no,
            prev_hash=prev, epoch_nonce=tp.ETA0,
            txs=(tx,),
        )
        blocks.append(b)
        prev = b.hash_
        block_no += 1
    return blocks


def test_wallet_follows_chain_with_rollback(tmp_path):
    node = tp._mk_node(tmp_path, "n")
    apps = node_to_client_apps(node, 4)
    assert "localchainsync" in apps.protocols()
    req, rsp = apps.channels["localchainsync"]

    # chain A: 5 blocks by pool 0; fork B: 6 blocks by pool 1 sharing
    # the first 3 — adopting B rolls the wallet back 2 blocks
    chain_a = _forge_chain(tp.POOLS[0], range(1, 6))
    fork_b = _forge_chain(
        tp.POOLS[1], range(6, 9),
        prev=chain_a[2].hash_, block_no=3, body=b"b",
    )
    for b in chain_a:
        node.chain_db.add_block(b)

    wallet_chain: list = []
    events: list = []

    def wallet():
        # a fresh wallet intersects at genesis and pulls the chain
        yield Send(req, ("find_intersect", [None]))
        msg = yield Recv(rsp)
        assert msg[0] == "intersect_found"
        for _ in range(20):
            yield Send(req, ("request_next",))
            kind, payload, _tip = yield Recv(rsp)
            events.append(kind)
            if kind == "roll_forward":
                blk = Block.from_bytes(payload)  # WHOLE block, not header
                assert blk.txs, "wallet must receive block bodies"
                wallet_chain.append(blk)
            elif kind == "roll_backward":
                point = payload
                while wallet_chain and (
                    point is None or wallet_chain[-1].point != point
                ):
                    wallet_chain.pop()
            if len(wallet_chain) == 6 and wallet_chain[-1].slot == 8:
                break
        yield Send(req, ("done",))

    def switcher():
        # let the wallet catch chain A first, then adopt fork B
        from ouroboros_consensus_tpu.utils.sim import Sleep

        yield Sleep(1.0)
        for b in fork_b:
            node.chain_db.add_block(b)

    sim = Sim()
    node.chain_db.runtime = sim
    for _o, name, gen in apps.tasks:
        sim.spawn(gen, name)
    sim.spawn(wallet(), "wallet")
    sim.spawn(switcher(), "switcher")
    sim.run(until=30)

    # the wallet followed the fork switch: rolled back to block 3 and
    # now holds the adopted 6-block chain, bodies included
    assert "roll_backward" in events
    assert [b.hash_ for b in wallet_chain] == [
        b.hash_ for b in (chain_a[:3] + fork_b)
    ]
    assert [b.slot for b in wallet_chain] == [1, 2, 3, 6, 7, 8]


def test_wallet_resumes_from_intersection(tmp_path):
    """A wallet that already holds a prefix resumes from its
    intersection point instead of genesis."""
    node = tp._mk_node(tmp_path, "n2")
    chain = _forge_chain(tp.POOLS[0], range(1, 8))
    for b in chain:
        node.chain_db.add_block(b)
    apps = node_to_client_apps(node, 4)
    req, rsp = apps.channels["localchainsync"]

    got: list = []

    def wallet():
        # the wallet knows up to slot 4 (index 3)
        yield Send(req, ("find_intersect", [chain[3].point]))
        msg = yield Recv(rsp)
        assert msg[0] == "intersect_found" and msg[1] == chain[3].point
        for _ in range(3):
            yield Send(req, ("request_next",))
            kind, payload, _tip = yield Recv(rsp)
            assert kind == "roll_forward"
            got.append(Block.from_bytes(payload))
        yield Send(req, ("done",))

    sim = Sim()
    node.chain_db.runtime = sim
    for _o, name, gen in apps.tasks:
        sim.spawn(gen, name)
    sim.spawn(wallet(), "wallet")
    sim.run(until=10)
    assert [b.hash_ for b in got] == [b.hash_ for b in chain[4:]]
