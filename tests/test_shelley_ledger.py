"""Shelley-class ledger: tx-level STS rules, certificates, deposits,
snapshot rotation, rewards, pool retirement, pparam updates.

Reference behavior: the Shelley ledger rule family reached from
`shelley/.../Shelley/Ledger/Ledger.hs` (LEDGER = UTXOW/UTXO/DELEGS/POOL;
TICK -> NEWEPOCH -> RUPD/SNAP/POOLREAP/PPUP)."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger import shelley as sh


EPOCH = 1000
PP = sh.PParams(
    min_fee_a=1, min_fee_b=10, max_tx_size=4096,
    key_deposit=100, pool_deposit=1000, e_max=5, n_opt=2,
    a0=Fraction(3, 10), rho=Fraction(1, 10), tau=Fraction(1, 5),
)


def genesis(outputs, **kw):
    g = sh.ShelleyGenesis(
        pparams=kw.pop("pparams", PP), epoch_length=EPOCH,
        stability_window=300, max_supply=kw.pop("max_supply", 10_000_000),
        **kw,
    )
    led = sh.ShelleyLedger(g)
    return g, led, led.genesis_state(outputs)


def cred(i):
    return b"C%02d" % i + b"\x00" * 25


def pay(i):
    return b"P%02d" % i + b"\x00" * 25


def pool_id(i):
    return b"p%02d" % i + b"\x00" * 25


class FakeBlock:
    def __init__(self, slot, txs, issuer_vk=None):
        self.slot = slot
        self.txs = list(txs)
        if issuer_vk is not None:
            class H:  # minimal header: the ledger only reads issuer_vk
                pass

            self.header = H()
            self.header.issuer_vk = issuer_vk


def apply_txs(led, st, slot, *txs):
    return led.apply_block(led.tick(st, slot), FakeBlock(slot, txs))


def view(led, st, slot):
    return led.mempool_view(led.tick(st, slot).state, slot)


# ---------------------------------------------------------------------------
# UTXO rules
# ---------------------------------------------------------------------------


def test_simple_spend_and_conservation():
    g, led, st0 = genesis([(pay(0), cred(0), 5000)])
    total0 = sh.total_ada(g, st0)
    fee = PP.min_fee_a * 200 + PP.min_fee_b  # generous
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(1), None, 5000 - fee)], fee=fee, ttl=50,
    )
    st1 = apply_txs(led, st0, 5, tx)
    assert sh.total_ada(g, st1) == total0
    assert st1.fees == fee
    assert ((sh.tx_id(tx), 0) in st1.utxo)


def test_missing_input_and_double_spend():
    g, led, st0 = genesis([(pay(0), None, 5000)])
    fee = 1000
    tx = sh.encode_tx([(b"x" * 32, 0)], [(pay(1), None, 5000 - fee)], fee=fee)
    with pytest.raises(sh.BadInputs):
        apply_txs(led, st0, 1, tx)
    tx2 = sh.encode_tx(
        [(bytes(32), 0), (bytes(32), 0)], [(pay(1), None, 2 * 5000 - fee)],
        fee=fee,
    )
    with pytest.raises(sh.BadInputs):
        apply_txs(led, st0, 1, tx2)


def test_fee_too_small_and_ttl_and_size():
    g, led, st0 = genesis([(pay(0), None, 5000)])
    tx = sh.encode_tx([(bytes(32), 0)], [(pay(1), None, 4999)], fee=1)
    with pytest.raises(sh.FeeTooSmall):
        apply_txs(led, st0, 1, tx)
    fee = 1000
    tx = sh.encode_tx([(bytes(32), 0)], [(pay(1), None, 5000 - fee)],
                      fee=fee, ttl=10)
    with pytest.raises(sh.ExpiredTx):
        apply_txs(led, st0, 11, tx)  # slot past ttl
    g2, led2, st2 = genesis(
        [(pay(0), None, 5000)],
        pparams=sh.PParams(min_fee_a=0, min_fee_b=0, max_tx_size=10),
    )
    with pytest.raises(sh.MaxTxSizeExceeded):
        apply_txs(led2, st2, 1, sh.encode_tx(
            [(bytes(32), 0)], [(pay(1), None, 5000)], fee=0))


def test_value_not_conserved():
    g, led, st0 = genesis([(pay(0), None, 5000)])
    tx = sh.encode_tx([(bytes(32), 0)], [(pay(1), None, 5000)], fee=1000)
    with pytest.raises(sh.ValueNotConserved):
        apply_txs(led, st0, 1, tx)


# ---------------------------------------------------------------------------
# DELEGS / POOL certificates
# ---------------------------------------------------------------------------


def reg_pool_cert(i, pledge=0, cost=0, margin=(0, 1), reward=None, owners=()):
    return (3, pool_id(i), b"V%02d" % i + b"\x00" * 29, pledge, cost,
            margin[0], margin[1], reward if reward is not None else cred(i),
            list(owners))


def test_stake_lifecycle_deposits():
    g, led, st0 = genesis([(pay(0), cred(0), 5000)])
    total0 = sh.total_ada(g, st0)
    fee = 1000
    # register: deposit leaves the utxo
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(0), cred(0), 5000 - fee - PP.key_deposit)],
        fee=fee, certs=[(0, cred(0))],
    )
    st1 = apply_txs(led, st0, 1, tx)
    assert st1.deposits == PP.key_deposit
    assert cred(0) in st1.stake_creds
    assert sh.total_ada(g, st1) == total0
    # duplicate registration rejected
    tx_dup = sh.encode_tx(
        [(sh.tx_id(tx), 0)], [(pay(0), cred(0), 5000 - 2 * fee - 2 * PP.key_deposit)],
        fee=fee, certs=[(0, cred(0))],
    )
    with pytest.raises(sh.DelegError):
        apply_txs(led, st1, 2, tx_dup)
    # deregister: deposit refunded into the tx's value balance
    tx2 = sh.encode_tx(
        [(sh.tx_id(tx), 0)],
        [(pay(0), None, 5000 - 2 * fee)],  # refund covers the extra
        fee=fee, certs=[(1, cred(0))],
    )
    st2 = apply_txs(led, st1, 2, tx2)
    assert st2.deposits == 0
    assert cred(0) not in st2.stake_creds
    assert sh.total_ada(g, st2) == total0


def test_delegation_requires_registration_and_pool():
    g, led, st0 = genesis([(pay(0), cred(0), 50000)])
    fee = 1000
    with pytest.raises(sh.DelegError):  # not registered
        apply_txs(led, st0, 1, sh.encode_tx(
            [(bytes(32), 0)], [(pay(0), cred(0), 50000 - fee)], fee=fee,
            certs=[(2, cred(0), pool_id(1))]))
    # register cred + pool + delegate in one tx (certs in order)
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 50000 - fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee,
        certs=[(0, cred(0)), reg_pool_cert(1), (2, cred(0), pool_id(1))],
    )
    st1 = apply_txs(led, st0, 1, tx)
    assert st1.delegations[cred(0)] == pool_id(1)
    assert st1.deposits == PP.key_deposit + PP.pool_deposit
    # unknown pool
    with pytest.raises(sh.DelegError):
        apply_txs(led, st1, 2, sh.encode_tx(
            [(sh.tx_id(tx), 0)], [(pay(0), cred(0),
             50000 - 2 * fee - PP.key_deposit - PP.pool_deposit)], fee=fee,
            certs=[(2, cred(0), pool_id(9))]))


def test_pool_retirement_epoch_window_and_reap():
    g, led, st0 = genesis([(pay(0), cred(0), 50000)])
    fee = 1000
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 50000 - fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(0, cred(0)), reg_pool_cert(1, reward=cred(0))],
    )
    st1 = apply_txs(led, st0, 1, tx)
    # window: epoch must be in (now, now+e_max]
    for bad in (0, PP.e_max + 1 + 0):
        with pytest.raises(sh.PoolError):
            apply_txs(led, st1, 2, sh.encode_tx(
                [(sh.tx_id(tx), 0)], [(pay(0), cred(0),
                 50000 - 2 * fee - PP.key_deposit - PP.pool_deposit)],
                fee=fee, certs=[(4, pool_id(1), bad + (0 if bad else 0))]))
    tx2 = sh.encode_tx(
        [(sh.tx_id(tx), 0)],
        [(pay(0), cred(0), 50000 - 2 * fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(4, pool_id(1), 2)],
    )
    st2 = apply_txs(led, st1, 2, tx2)
    assert st2.retiring[pool_id(1)] == 2
    total = sh.total_ada(g, st2)
    # crossing into epoch 2 reaps the pool; deposit refunds to cred(0)
    st3 = led.tick(st2, 2 * EPOCH + 1).state
    assert pool_id(1) not in st3.pools
    assert st3.rewards[cred(0)] == PP.pool_deposit
    assert sh.total_ada(g, st3) == total
    # re-registration cancels retirement
    st2b = apply_txs(led, st2, 3, sh.encode_tx(
        [(sh.tx_id(tx2), 0)],
        [(pay(0), cred(0), 50000 - 3 * fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[reg_pool_cert(1, reward=cred(0))]))
    assert pool_id(1) not in st2b.retiring
    assert pool_id(1) in led.tick(st2b, 2 * EPOCH + 1).state.pools


def test_pool_reap_unregistered_reward_account_goes_to_treasury():
    g, led, st0 = genesis([(pay(0), None, 50000)])
    fee = 1000
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(0), None, 50000 - fee - PP.pool_deposit)],
        fee=fee,
        certs=[reg_pool_cert(1, reward=cred(7)), (4, pool_id(1), 1)],
    )
    st1 = apply_txs(led, st0, 1, tx)
    st2 = led.tick(st1, EPOCH + 1).state
    assert st2.treasury >= PP.pool_deposit  # cred(7) never registered
    assert sh.total_ada(g, st2) == sh.total_ada(g, st1)


# ---------------------------------------------------------------------------
# Withdrawals
# ---------------------------------------------------------------------------


def test_withdrawal_full_balance_rule():
    g, led, st0 = genesis([(pay(0), cred(0), 50000)])
    fee = 1000
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 50000 - fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(0, cred(0)), reg_pool_cert(1, reward=cred(0)),
                        (4, pool_id(1), 1)],
    )
    st1 = apply_txs(led, st0, 1, tx)
    st2 = led.tick(st1, EPOCH + 1).state  # reap -> rewards[cred0] = deposit
    bal = st2.rewards[cred(0)]
    assert bal == PP.pool_deposit
    # partial withdrawal rejected
    with pytest.raises(sh.WithdrawalError):
        apply_txs(led, st2, EPOCH + 2, sh.encode_tx(
            [(sh.tx_id(tx), 0)],
            [(pay(1), None, 50000 - 2 * fee - PP.key_deposit - PP.pool_deposit
              + bal - 1)],
            fee=fee, withdrawals=[(cred(0), bal - 1)]))
    # full withdrawal moves the balance into the utxo
    st3 = apply_txs(led, st2, EPOCH + 2, sh.encode_tx(
        [(sh.tx_id(tx), 0)],
        [(pay(1), None, 50000 - 2 * fee - PP.key_deposit - PP.pool_deposit + bal)],
        fee=fee, withdrawals=[(cred(0), bal)]))
    assert st3.rewards[cred(0)] == 0
    assert sh.total_ada(g, st3) == sh.total_ada(g, st2)


def test_withdraw_and_deregister_in_one_tx():
    """DELEGS applies withdrawals before certificates: the standard
    'drain the reward account and deregister the stake key' tx is valid
    in one go (the dereg cert's zero-rewards check sees the drained
    account)."""
    g, led, st0 = genesis([(pay(0), cred(0), 50000)])
    fee = 1000
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 50000 - fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(0, cred(0)), reg_pool_cert(1, reward=cred(0)),
                        (4, pool_id(1), 1)],
    )
    st1 = apply_txs(led, st0, 1, tx)
    st2 = led.tick(st1, EPOCH + 1).state  # reap -> rewards[cred0] = deposit
    bal = st2.rewards[cred(0)]
    assert bal == PP.pool_deposit
    tx2 = sh.encode_tx(
        [(sh.tx_id(tx), 0)],
        [(pay(1), None, 50000 - 2 * fee - PP.pool_deposit + bal)],
        fee=fee, withdrawals=[(cred(0), bal)], certs=[(1, cred(0))],
    )
    blk = FakeBlock(EPOCH + 2, [tx2])
    st3 = led.apply_block(led.tick(st2, EPOCH + 2), blk)
    assert cred(0) not in st3.stake_creds
    assert cred(0) not in st3.rewards
    assert sh.total_ada(g, st3) == sh.total_ada(g, st2)
    # reapply replays the same order
    assert led.reapply_block(led.tick(st2, EPOCH + 2), blk) == st3


def test_pool_reap_refunds_recorded_deposit():
    """POOLREAP refunds the deposit TAKEN at registration, not the
    current pparams.pool_deposit a PPUP update may have changed since."""
    gd = (b"G1" + b"\x00" * 26,)
    g, led, st0 = genesis(
        [(pay(0), cred(0), 50000)], genesis_delegates=gd, update_quorum=1,
    )
    fee = 1000
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 50000 - fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(0, cred(0)), reg_pool_cert(1, reward=cred(0)),
                        (5, gd[0], {"pool_deposit": PP.pool_deposit * 5})],
    )
    st1 = apply_txs(led, st0, 1, tx)
    assert st1.pool_deposits[pool_id(1)] == PP.pool_deposit
    st2 = led.tick(st1, EPOCH + 1).state  # adopts pool_deposit*5
    assert st2.pparams.pool_deposit == PP.pool_deposit * 5
    tx2 = sh.encode_tx(
        [(sh.tx_id(tx), 0)],
        [(pay(0), cred(0), 50000 - 2 * fee - PP.key_deposit - PP.pool_deposit)],
        fee=fee, certs=[(4, pool_id(1), 2)],
    )
    st3 = apply_txs(led, st2, EPOCH + 2, tx2)
    st4 = led.tick(st3, 2 * EPOCH + 1).state  # reap
    assert pool_id(1) not in st4.pools
    assert pool_id(1) not in st4.pool_deposits
    # refund is the RECORDED deposit, and the pot zeroes out exactly
    assert st4.rewards[cred(0)] == PP.pool_deposit
    assert st4.deposits == PP.key_deposit
    assert sh.total_ada(g, st4) == sh.total_ada(g, st0)


# ---------------------------------------------------------------------------
# Snapshots / ledger view / rewards
# ---------------------------------------------------------------------------


def setup_two_pools():
    """cred1 (3000) -> pool1, cred2 (1000) -> pool2, fully set up."""
    g, led, st0 = genesis(
        [(pay(0), None, 100000), (pay(1), cred(1), 3000), (pay(2), cred(2), 1000)],
        max_supply=10_000_000,
    )
    fee = 1000
    certs = [
        (0, cred(1)), (0, cred(2)),
        reg_pool_cert(1, reward=cred(1)), reg_pool_cert(2, reward=cred(2)),
        (2, cred(1), pool_id(1)), (2, cred(2), pool_id(2)),
    ]
    cost = fee + 2 * PP.key_deposit + 2 * PP.pool_deposit
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(0), None, 100000 - cost)], fee=fee,
        certs=certs,
    )
    st1 = apply_txs(led, st0, 1, tx)
    return g, led, st1


def test_mark_set_go_rotation_two_epoch_delay():
    g, led, st1 = setup_two_pools()
    # epoch 0: set snapshot is empty -> no election view yet
    assert led.protocol_ledger_view(led.tick(st1, 10)).pool_distr == {}
    # after ONE boundary the registration epoch's stake is in MARK only
    v1 = led.protocol_ledger_view(led.tick(st1, EPOCH + 1))
    assert v1.pool_distr == {}
    # after TWO boundaries it becomes SET -> elections see it
    v2 = led.protocol_ledger_view(led.tick(st1, 2 * EPOCH + 1))
    assert set(v2.pool_distr) == {pool_id(1), pool_id(2)}
    assert v2.pool_distr[pool_id(1)].stake == Fraction(3, 4)
    assert v2.pool_distr[pool_id(2)].stake == Fraction(1, 4)
    # view_for_epoch agrees with the ticked view
    assert led.view_for_epoch(st1, 2).pool_distr == v2.pool_distr


def test_stake_shift_shows_up_two_epochs_later():
    g, led, st1 = setup_two_pools()
    fee = 1000
    # mid-epoch-1: cred2 receives 3000 more (delegated stake grows)
    st2 = led.tick(st1, EPOCH + 5).state
    key = next(k for k in st2.utxo if st2.utxo[k][0][0] == pay(0))
    amt = st2.utxo[key][1]
    tx = sh.encode_tx(
        [key],
        [(pay(2), cred(2), 3000), (pay(0), None, amt - 3000 - fee)],
        fee=fee,
    )
    st3 = apply_txs(led, st2, EPOCH + 5, tx)
    # election for epoch 2 still uses end-of-epoch-0 stake
    v2 = led.view_for_epoch(st3, 2)
    assert v2.pool_distr[pool_id(2)].stake == Fraction(1, 4)
    # election for epoch 3 sees the shift (1000+3000 vs 3000)
    v3 = led.view_for_epoch(st3, 3)
    assert v3.pool_distr[pool_id(2)].stake == Fraction(4, 7)


def test_rewards_flow_and_conservation():
    g, led, st1 = setup_two_pools()
    total0 = sh.total_ada(g, st1)
    # pool1 forges 3 blocks, pool2 one block, during epoch 2 (so the GO
    # snapshot at the 3->4 boundary covers their stake)
    vk1, vk2 = b"\x01" * 32, b"\x02" * 32
    from ouroboros_consensus_tpu.protocol.views import hash_key

    # rebind pool ids to the issuer key hashes the ledger will count
    st = st1
    fee = 1000
    key = next(k for k in st.utxo if st.utxo[k][0][0] == pay(0))
    amt = st.utxo[key][1]
    tx = sh.encode_tx(
        [key], [(pay(0), None, amt - fee - 2 * PP.pool_deposit)], fee=fee,
        certs=[
            (3, hash_key(vk1), b"W" * 32, 0, 0, 0, 1, cred(1), []),
            (3, hash_key(vk2), b"W" * 32, 0, 0, 0, 1, cred(2), []),
            (2, cred(1), hash_key(vk1)), (2, cred(2), hash_key(vk2)),
        ],
    )
    st = apply_txs(led, st, 2, tx)
    st = led.tick(st, 2 * EPOCH + 1).state  # into epoch 2
    for slot, vk in ((2 * EPOCH + 2, vk1), (2 * EPOCH + 3, vk1),
                     (2 * EPOCH + 4, vk1), (2 * EPOCH + 5, vk2)):
        st = led.apply_block(led.tick(st, slot), FakeBlock(slot, [], vk))
    assert sum(st.blocks_current.values()) == 4
    # cross into epoch 3 (counts move to prev), then epoch 4 (rewarded)
    st = led.tick(st, 4 * EPOCH + 1).state
    r1, r2 = st.rewards.get(cred(1), 0), st.rewards.get(cred(2), 0)
    assert r1 > 0 and r2 > 0
    assert r1 > r2  # 3x stake AND 3x blocks
    assert st.treasury > 0
    assert sh.total_ada(g, st) == total0
    assert st.reserves < g.max_supply - 104000  # expansion paid out


# ---------------------------------------------------------------------------
# PParam updates
# ---------------------------------------------------------------------------


def test_pparam_update_quorum_and_adoption():
    gd = (b"G1" + b"\x00" * 26, b"G2" + b"\x00" * 26)
    g, led, st0 = genesis(
        [(pay(0), None, 100000)], genesis_delegates=gd, update_quorum=2,
    )
    fee = 1000
    upd = {"min_fee_b": 777, "rho": [1, 50]}
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(0), None, 100000 - fee)], fee=fee,
        certs=[(5, gd[0], upd)],
    )
    st1 = apply_txs(led, st0, 1, tx)
    # only one vote -> not adopted at the boundary
    assert led.tick(st1, EPOCH + 1).state.pparams.min_fee_b == PP.min_fee_b
    tx2 = sh.encode_tx(
        [(sh.tx_id(tx), 0)], [(pay(0), None, 100000 - 2 * fee)], fee=fee,
        certs=[(5, gd[1], upd)],
    )
    st2 = apply_txs(led, st1, 2, tx2)
    new = led.tick(st2, EPOCH + 1).state.pparams
    assert new.min_fee_b == 777
    assert new.rho == Fraction(1, 50)
    # non-delegate proposer rejected
    with pytest.raises(sh.ShelleyTxError):
        apply_txs(led, st2, 3, sh.encode_tx(
            [(sh.tx_id(tx2), 0)], [(pay(0), None, 100000 - 3 * fee)],
            fee=fee, certs=[(5, b"EVIL" + b"\x00" * 24, upd)]))
    # unknown pparam key rejected
    with pytest.raises(sh.ShelleyTxError):
        apply_txs(led, st2, 3, sh.encode_tx(
            [(sh.tx_id(tx2), 0)], [(pay(0), None, 100000 - 3 * fee)],
            fee=fee, certs=[(5, gd[0], {"evil": 1})]))


# ---------------------------------------------------------------------------
# apply/reapply agreement + mempool view atomicity
# ---------------------------------------------------------------------------


def test_reapply_matches_apply():
    g, led, st1 = setup_two_pools()
    fee = 1000
    key = next(k for k in st1.utxo if st1.utxo[k][0][0] == pay(0))
    amt = st1.utxo[key][1]
    tx = sh.encode_tx(
        [key], [(pay(3), cred(1), amt - fee)], fee=fee,
        withdrawals=[], certs=[(4, pool_id(2), 2)],
    )
    blk = FakeBlock(EPOCH + 7, [tx], b"\x09" * 32)
    a = led.apply_block(led.tick(st1, EPOCH + 7), blk)
    b = led.reapply_block(led.tick(st1, EPOCH + 7), blk)
    assert a == b


def test_malformed_certs_are_invalid_not_crashes():
    """Gossiped garbage must surface as ShelleyTxError (the Mempool only
    catches LedgerError): zero-denominator margin, wrong arity, bad tag,
    zero-denominator pparam fraction."""
    gd = (b"G1" + b"\x00" * 26,)
    g, led, st0 = genesis([(pay(0), None, 100000)], genesis_delegates=gd)
    bad_certs = [
        (3, pool_id(1), b"V" * 32, 0, 0, 1, 0, cred(1), []),  # margin /0
        (3, pool_id(1)),  # arity
        (99, b"?"),  # unknown tag
        (5, gd[0], {"rho": [1, 0]}),  # pparam fraction /0
        (2,),  # arity
    ]
    for cert in bad_certs:
        tx = sh.encode_tx(
            [(bytes(32), 0)], [(pay(0), None, 100000 - 1000)], fee=1000,
            certs=[cert],
        )
        with pytest.raises(sh.ShelleyTxError):
            apply_txs(led, st0, 1, tx)


def test_mempool_view_atomic_on_failure():
    g, led, st1 = setup_two_pools()
    v = view(led, st1, 10)
    utxo_before = dict(v.utxo)
    regs_before = dict(v.stake_creds)
    key = next(k for k in v.utxo)
    bad = sh.encode_tx(
        [key], [(pay(9), None, 1)], fee=10**9,  # not conserved
        certs=[(0, cred(9))],
    )
    with pytest.raises(sh.ShelleyTxError):
        led.apply_tx(v, bad)
    assert v.utxo == utxo_before
    assert v.stake_creds == regs_before
    assert v.deposit_delta == 0 and v.fee_delta == 0


def test_inspect_events_on_proposals_and_adoption():
    """InspectLedger: a proposal tx emits ShelleyUpdatedProposals; the
    adopting boundary emits ShelleyPParamsAdopted with the changed
    fields (Ledger/Inspect.hs ShelleyLedgerUpdate)."""
    from ouroboros_consensus_tpu.ledger.inspect import (
        ShelleyPParamsAdopted,
        ShelleyUpdatedProposals,
        inspect_ledger,
    )

    gd = (b"G1" + b"\x00" * 26,)
    g, led, st0 = genesis(
        [(pay(0), None, 100000)], genesis_delegates=gd, update_quorum=1,
    )
    tx = sh.encode_tx(
        [(bytes(32), 0)], [(pay(0), None, 100000 - 1000)], fee=1000,
        certs=[(5, gd[0], {"min_fee_b": 9})],
    )
    st1 = apply_txs(led, st0, 1, tx)
    ev = inspect_ledger(led, st0, st1)
    assert any(isinstance(e, ShelleyUpdatedProposals) for e in ev)

    st2 = led.tick(st1, EPOCH + 1).state
    ev2 = inspect_ledger(led, st1, st2)
    adopted = [e for e in ev2 if isinstance(e, ShelleyPParamsAdopted)]
    assert adopted and adopted[0].changed == (
        ("min_fee_b", PP.min_fee_b, 9),
    )


def test_mir_certificates():
    """MIR (move instantaneous rewards): genesis-delegate-proposed
    transfers from reserves/treasury to reward accounts, applied at the
    NEXT epoch boundary; later certs override same-(pot, cred) ones;
    over-allocation and non-delegate proposers are rejected."""
    gd = b"GD0" + b"\x00" * 25
    g, led, st = genesis(
        [(pay(0), cred(0), 10_000)], genesis_delegates=(gd,),
    )
    # register the target credential
    # fee must cover the linear min fee (a=1/byte, b=10)
    tx = sh.encode_tx(
        [(bytes(32), 0)],
        [(pay(0), cred(0), 10_000 - PP.key_deposit - 500)],
        fee=500, certs=[(0, cred(0))],
    )
    st = apply_txs(led, st, 1, tx)
    assert cred(0) in st.stake_creds

    reserves0 = st.reserves
    # two MIR certs: the second overrides the first's allocation
    out = next(k for k in st.utxo)
    coin = st.utxo[out][1]
    tx2 = sh.encode_tx(
        [out], [(pay(0), cred(0), coin - 500)], fee=500,
        certs=[
            (6, 0, gd, [[cred(0), 500]]),
            (6, 0, gd, [[cred(0), 700]]),
        ],
    )
    st = apply_txs(led, st, 2, tx2)
    assert st.pending_mir == {(0, cred(0)): 700}
    assert st.rewards[cred(0)] == 0  # nothing moves until the boundary

    # boundary: funds move reserves -> reward account
    st2 = led.tick(st, EPOCH + 1).state
    assert st2.rewards[cred(0)] == 700
    assert st2.pending_mir == {}
    assert st2.reserves < reserves0
    assert sh.total_ada(g, st2) == g.max_supply

    # rejections: non-delegate proposer, bad pot, over-allocation
    out = next(k for k in st2.utxo)
    coin = st2.utxo[out][1]
    for bad_cert in (
        (6, 0, cred(0), [[cred(0), 5]]),       # not a genesis delegate
        (6, 7, gd, [[cred(0), 5]]),            # bad pot
        (6, 1, gd, [[cred(0), st2.treasury + 1]]),  # over-allocates
        (6, 0, gd, [[cred(0), 0]]),            # non-positive
    ):
        tx_bad = sh.encode_tx(
            [out], [(pay(0), cred(0), coin - 500)], fee=500,
            certs=[bad_cert],
        )
        with pytest.raises(sh.ShelleyTxError):
            apply_txs(led, st2, EPOCH + 2, tx_bad)


def test_mir_to_unregistered_cred_stays_in_pot():
    gd = b"GD0" + b"\x00" * 25
    g, led, st = genesis(
        [(pay(0), None, 10_000)], genesis_delegates=(gd,),
    )
    out = next(k for k in st.utxo)
    tx = sh.encode_tx(
        [out], [(pay(0), None, 10_000 - 500)], fee=500,
        certs=[(6, 0, gd, [[cred(9), 500]])],  # cred(9) never registered
    )
    st = apply_txs(led, st, 1, tx)
    reserves0 = st.reserves
    st2 = led.tick(st, EPOCH + 1).state
    # the allocation lapses: reserves keep the funds (modulo the epoch's
    # ordinary monetary expansion, which moves rho*reserves elsewhere)
    assert cred(9) not in st2.rewards
    assert st2.pending_mir == {}
    assert sh.total_ada(g, st2) == g.max_supply
