"""obs/recovery.py units: the crash-consistent progress record
(PraosState round-trip, digest fail-closed integrity, resume
eligibility), the RecoverySupervisor's ladder semantics (event
trajectory, unrecoverable passthrough, exhaustion), the host-reference
floor's differential equality, and the bench ParentPolicy's
grace-window escalation."""

from __future__ import annotations

import json
import os
from fractions import Fraction

import pytest

import jax  # noqa: F401 — backend pinned by conftest

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import chaos, fixtures
from ouroboros_consensus_tpu.utils import trace as T

from tests.test_obs import _forge_chain, make_params
from tests.test_packed_batch import _stub_verify


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    monkeypatch.delenv("OCT_CHECKPOINT", raising=False)
    monkeypatch.delenv("OCT_RESUME", raising=False)
    monkeypatch.delenv("OCT_RECOVERY", raising=False)
    chaos.reset()
    yield
    obs.reset_for_tests()
    recovery.reset_for_tests()
    chaos.reset()


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(90 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture
def stubbed(monkeypatch):
    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub-recovery", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


# ---------------------------------------------------------------------------
# PraosState <-> record round-trip + integrity
# ---------------------------------------------------------------------------


def _some_state() -> praos.PraosState:
    return praos.PraosState(
        last_slot=1234,
        ocert_counters={b"\x01" * 28: 7, b"\x02" * 28: 0},
        evolving_nonce=b"\xaa" * 32,
        candidate_nonce=b"\xbb" * 32,
        epoch_nonce=b"\xcc" * 32,
        lab_nonce=b"\xdd" * 32,
        last_epoch_block_nonce=None,
    )


def test_state_encode_decode_roundtrip():
    st = _some_state()
    assert recovery.decode_state(recovery.encode_state(st)) == st
    # None nonces and an empty counter map survive too (genesis shape)
    empty = praos.PraosState()
    assert recovery.decode_state(recovery.encode_state(empty)) == empty


def test_progress_writer_and_read_checkpoint(tmp_path):
    path = str(tmp_path / "ckpt.json")
    w = recovery.ProgressWriter(path, "tag1")
    st = _some_state()
    w.note(st, 100)
    w.note(st, 28)
    doc = recovery.read_checkpoint(path)
    assert doc is not None
    assert doc["headers"] == 128 and doc["windows"] == 2
    assert not doc["complete"]
    assert recovery.decode_state(doc["state"]) == st
    # eligible for resume under its own tag, nobody else's
    assert recovery.resume_record("tag1", path) is not None
    assert recovery.resume_record("other", path) is None
    # a COMPLETED record never seeds a resume
    w.finalize(st)
    done = recovery.read_checkpoint(path)
    assert done["complete"]
    assert recovery.resume_record("tag1", path) is None


def test_checkpoint_fails_closed_on_tamper_and_torn(tmp_path):
    path = str(tmp_path / "ckpt.json")
    w = recovery.ProgressWriter(path, "tag1")
    w.note(_some_state(), 64)
    doc = json.load(open(path))
    # hand-edit the position: the digest no longer covers it
    doc["headers"] = 9999
    json.dump(doc, open(path, "w"))
    assert recovery.read_checkpoint(path) is None
    # torn JSON reads as no checkpoint, never an exception
    with open(path, "w") as f:
        f.write('{"kind": "oct-checkpoint", "head')
    assert recovery.read_checkpoint(path) is None
    assert recovery.read_checkpoint(str(tmp_path / "absent.json")) is None


def test_checkpoint_events_flow_to_recorder(tmp_path, monkeypatch):
    monkeypatch.setenv("OCT_CHECKPOINT", str(tmp_path / "c.json"))
    rec = obs.install()
    try:
        w = recovery.arm_writer("tagX")
        pbatch.set_batch_tracer(rec)
        w.note(_some_state(), 8)
        w.finalize(_some_state())
        snap = rec.registry.snapshot()
        rows = {s["labels"]["kind"]: s["value"]
                for s in snap["oct_checkpoint_events_total"]["samples"]}
        assert rows == {"write": 1, "complete": 1}
    finally:
        pbatch.set_batch_tracer(None)
        obs.uninstall()


def test_chain_tag_keys_on_path_and_params():
    params = make_params()
    t1 = recovery.chain_tag("/db/a", params)
    assert t1 == recovery.chain_tag("/db/a", params)
    assert t1 != recovery.chain_tag("/db/b", params)
    assert t1 != recovery.chain_tag("/db/a", make_params(epoch_length=60))


def test_note_window_is_noop_without_writer():
    recovery.disarm_writer()
    recovery.note_window(_some_state(), 8)  # must not raise


# ---------------------------------------------------------------------------
# recoverable() gate
# ---------------------------------------------------------------------------


def test_recoverable_classes():
    assert recovery.recoverable(chaos.DeviceChaosError("x"))
    assert recovery.recoverable(chaos.StagingChaosError("x"))
    assert recovery.recoverable(OSError("io"))
    assert recovery.recoverable(RuntimeError("pjrt says no"))

    class XlaRuntimeError(Exception):
        pass

    assert recovery.recoverable(XlaRuntimeError("fake jaxlib"))
    # programming bugs propagate: recovery never masks a wrong program
    assert not recovery.recoverable(TypeError("bug"))
    assert not recovery.recoverable(AssertionError("bug"))


# ---------------------------------------------------------------------------
# the supervisor ladder
# ---------------------------------------------------------------------------


def _window(params, pools, lview, n=8):
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    _, hvs = _forge_chain(params, pools, lview, n)
    ticked = praos.tick(params, lview, hvs[0].slot, st0)
    return ticked, hvs


def _always_leader_params():
    """f=1 params: every forged header is genuinely leader-valid, so
    the REAL-crypto host-reference floor accepts the whole window (the
    stubbed device paths force ok_leader; the reference fold does not)."""
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 1),
        epoch_length=100_000,
        kes_depth=3,
    )


def test_recover_window_retry_rung_matches_direct(pools, lview, stubbed):
    params = make_params()
    ticked, hvs = _window(params, pools, lview)
    direct = pbatch.validate_batch(params, ticked, hvs)
    sup = recovery.RecoverySupervisor(backoff_s=0.0)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = sup.recover_window(params, ticked, hvs,
                                 chaos.DeviceChaosError("injected"),
                                 backend="device", window=3)
    finally:
        pbatch.set_batch_tracer(None)
    assert res.n_valid == direct.n_valid == len(hvs)
    assert res.error is None and res.state == direct.state
    evs = [e for e in lt.events if isinstance(e, T.RecoveryEvent)]
    assert [(e.action, e.attempt) for e in evs] == [
        ("retry", 1), ("recovered", 1)
    ]
    assert evs[0].window == 3 and evs[0].fault == "DeviceChaosError"
    assert evs[-1].ok is True
    assert sup.episodes == 1 and sup.recovered == 1


def test_recover_window_escalates_to_host_reference(pools, lview,
                                                    stubbed, monkeypatch):
    """Every device-path rung dies -> the exact host fold is the floor
    (it cannot fail for device reasons), and the trajectory is the
    full ladder with the terminal `recovered` event."""
    params = _always_leader_params()
    ticked, hvs = _window(params, pools, lview)
    expected = recovery.host_reference_fold(params, ticked, hvs)

    def boom(*a, **k):
        raise RuntimeError("device still broken")

    monkeypatch.setattr(pbatch, "validate_batch", boom)
    sup = recovery.RecoverySupervisor(backoff_s=0.0)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = sup.recover_window(params, ticked, hvs,
                                 RuntimeError("first failure"),
                                 backend="device")
    finally:
        pbatch.set_batch_tracer(None)
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state == expected.state
    evs = [e for e in lt.events if isinstance(e, T.RecoveryEvent)]
    assert [e.action for e in evs] == [
        "retry", "stage-split", "xla-twin", "host-reference", "recovered",
    ]
    # the banked warmup rows carry the same trajectory for the ledger
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    assert [r["action"] for r in WARMUP.report()["recovery"]] == \
        [e.action for e in evs]


def test_recover_window_unrecoverable_and_disabled_raise(pools, lview,
                                                         stubbed,
                                                         monkeypatch):
    params = make_params()
    ticked, hvs = _window(params, pools, lview)
    sup = recovery.RecoverySupervisor(backoff_s=0.0)
    with pytest.raises(TypeError):  # programming bug: straight through
        sup.recover_window(params, ticked, hvs, TypeError("bug"))
    monkeypatch.setenv("OCT_RECOVERY", "0")
    with pytest.raises(chaos.DeviceChaosError):  # lever: raise-through
        sup.recover_window(params, ticked, hvs,
                           chaos.DeviceChaosError("x"))
    assert sup.episodes == 0


def test_recover_window_exhausted_reraises_with_forensics(
    pools, lview, stubbed, monkeypatch
):
    params = make_params()
    ticked, hvs = _window(params, pools, lview)

    def boom(*a, **k):
        raise RuntimeError("rung died")

    monkeypatch.setattr(pbatch, "validate_batch", boom)
    monkeypatch.setattr(recovery, "host_reference_fold", boom)
    sup = recovery.RecoverySupervisor(backoff_s=0.0)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        with pytest.raises(RuntimeError, match="rung died"):
            sup.recover_window(params, ticked, hvs,
                               RuntimeError("original"))
    finally:
        pbatch.set_batch_tracer(None)
    evs = [e for e in lt.events if isinstance(e, T.RecoveryEvent)]
    assert evs[-1].action == "exhausted" and evs[-1].ok is False
    assert sup.recovered == 0


def test_host_reference_fold_equals_sequential_reference(pools, lview):
    """The floor rung IS the reference: real host crypto, equal to the
    praos.update fold header by header."""
    params = _always_leader_params()
    ticked, hvs = _window(params, pools, lview, n=4)
    res = recovery.host_reference_fold(params, ticked, hvs)
    st, t = ticked.state, ticked
    for i, hv in enumerate(hvs):
        if i:
            t = praos.tick(params, ticked.ledger_view, hv.slot, st)
        st = praos.update(params, hv, hv.slot, t)
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state == st


def test_retry_backoff_is_jittered_and_chaos_seeded(pools, lview, stubbed,
                                                    monkeypatch):
    params = make_params()
    ticked, hvs = _window(params, pools, lview)
    monkeypatch.setenv("OCT_CHAOS", "device-error@dispatch:999")
    monkeypatch.setenv("OCT_CHAOS_SEED", "7")
    chaos.reset()
    waits: list = []
    sup = recovery.RecoverySupervisor(backoff_s=0.5,
                                      sleep=lambda s: waits.append(s))
    sup.recover_window(params, ticked, hvs, chaos.DeviceChaosError("x"))
    chaos.reset()
    waits2: list = []
    sup2 = recovery.RecoverySupervisor(backoff_s=0.5,
                                       sleep=lambda s: waits2.append(s))
    sup2.recover_window(params, ticked, hvs, chaos.DeviceChaosError("x"))
    assert waits == waits2  # seeded chaos RNG -> reproducible timing
    assert all(0.5 <= w <= 0.75 for w in waits)  # base * [1.0, 1.5)


# ---------------------------------------------------------------------------
# ParentPolicy
# ---------------------------------------------------------------------------


def test_parent_policy_grace_windows():
    clk = [0.0]
    p = recovery.ParentPolicy(stall_grace_s=60.0, dead_grace_s=30.0,
                              clock=lambda: clk[0])
    assert p.observe("running") == "keep"
    assert p.observe("stalled") == "keep"  # fuse starts
    clk[0] = 59.0
    assert p.observe("stalled") == "keep"
    clk[0] = 61.0
    assert p.observe("stalled") == "kill"
    # progress of ANY kind resets the fuse
    p2 = recovery.ParentPolicy(stall_grace_s=60.0, clock=lambda: clk[0])
    p2.observe("stalled")
    clk[0] += 30
    assert p2.observe("compiling") == "keep"
    clk[0] += 40
    assert p2.observe("stalled") == "keep"  # a NEW fuse, not the old one
    # dead has its own (shorter) grace, and a state CHANGE re-arms
    clk[0] = 0.0
    p3 = recovery.ParentPolicy(stall_grace_s=60.0, dead_grace_s=30.0,
                               clock=lambda: clk[0])
    p3.observe("stalled")
    clk[0] = 20.0
    assert p3.observe("dead") == "keep"  # stalled->dead restarts the fuse
    clk[0] = 49.0
    assert p3.observe("dead") == "keep"
    clk[0] = 51.0
    assert p3.observe("dead") == "kill"


# ---------------------------------------------------------------------------
# satellite: perf_report chaos-seeded fixture (recovered@<fault>)
# ---------------------------------------------------------------------------


def test_perf_report_recovered_round_classification(tmp_path):
    import importlib.util

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "perf_report", os.path.join(REPO, "scripts", "perf_report.py")
    )
    perf_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_report)

    # the warmup rows a chaos-seeded recovered round banks
    # (OCT_CHAOS=device-error@dispatch:2 walked one window down the
    # ladder, the round still banked its device number)
    recovery_rows = [
        {"action": "retry", "window": 2, "attempt": 1,
         "fault": "DeviceChaosError", "t": 10.0},
        {"action": "recovered", "window": 2, "attempt": 1,
         "fault": "DeviceChaosError", "ok": True, "t": 10.5},
    ]
    p = os.path.join(tmp_path, "BENCH_r06.json")
    with open(p, "w") as f:
        json.dump({"rc": 0, "tail": "", "parsed": {
            "value": 4000.0, "vs_baseline": 2.0,
            "resumed_headers": 81920,
            "metric": "end-to-end db-analyser revalidation of a "
                      "1000000-header synthetic Praos chain",
            "warmup_report": {"recovery": recovery_rows, "stages": {},
                              "ladder": [], "aot": {}, "refusals": []},
        }}, f)
    row = perf_report.analyze_bench_round(p)
    assert row["device_banked"] and row["failures"] == []
    assert row["recovered_fault"] == "DeviceChaosError"
    assert row["recovery_actions"] == {"retry": 1, "recovered": 1}
    assert row["resumed_headers"] == 81920
    md = perf_report.render_markdown(
        {"bench_rounds": [row], "multichip_rounds": [], "ledger": None,
         "verdicts": [], "ok": True})
    assert "recovered@DeviceChaosError" in md
    assert "## Recovered rounds" in md
    assert "retry=1" in md and "resumed past 81920" in md

    # a DEAD round with recovery evidence keeps its failure modes but
    # the attribution notes the ladder engaged (stalled@ wins priority)
    p2 = os.path.join(tmp_path, "BENCH_r07.json")
    with open(p2, "w") as f:
        json.dump({"rc": 124, "tail": "", "parsed": {
            "value": 2100.0, "device_unavailable": True,
            "no_device_reason": "device-run-failed-or-wall",
            "stall_dump": {"phase": "dispatch", "age_s": 600.0,
                           "budget_s": 240.0, "threads": {}},
            "warmup_report": {"recovery": recovery_rows[:1],
                              "stages": {}, "ladder": [], "aot": {},
                              "refusals": []},
        }}, f)
    row2 = perf_report.analyze_bench_round(p2)
    assert [f["mode"] for f in row2["failures"]][0] == "stalled@dispatch"
    md2 = perf_report.render_markdown(
        {"bench_rounds": [row2], "multichip_rounds": [], "ledger": None,
         "verdicts": [], "ok": False})
    assert "recovery ladder HAD engaged" in md2
