"""The obs/ flight recorder: metrics registry units, Prometheus
exposition format, event-SEQUENCE assertions over the pipelined
validate_chain loop (span / gate / fallback order, including the
aggregate anomaly re-dispatch), Perfetto export schema validation of a
replay, warmup-forensics crash safety, and the instrumentation-purity
differential (telemetry must add ZERO equations to the registry
graphs).

Crypto is the hash-only stub throughout (test_packed_batch idiom): the
telemetry plumbing is what's under test, not the ladders."""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

import jax

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.block.metrics import NodeMetrics
from ouroboros_consensus_tpu.obs import perfetto
from ouroboros_consensus_tpu.obs.registry import MetricsRegistry
from ouroboros_consensus_tpu.obs.warmup import WarmupRecorder, read_report
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils import trace as T

from tests.test_packed_batch import _stub_verify


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Every test gets a clean process-wide recorder + registry."""
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------


def test_counter_gauge_labels_and_exposition():
    reg = MetricsRegistry()
    c = reg.counter("oct_widgets_total", "widgets seen", ("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc()
    g = reg.gauge("oct_depth", "queue depth")
    g.set(3)
    text = reg.expose_text()
    assert "# HELP oct_widgets_total widgets seen" in text
    assert "# TYPE oct_widgets_total counter" in text
    assert 'oct_widgets_total{kind="a"} 3' in text
    assert 'oct_widgets_total{kind="b"} 1' in text
    assert "oct_depth 3" in text
    # re-registering the same family returns it; a different shape fails
    assert reg.counter("oct_widgets_total", "x", ("kind",)) is c
    with pytest.raises(ValueError):
        reg.counter("oct_widgets_total", "x", ("other",))
    with pytest.raises(ValueError):
        c.labels(wrong="a")


def test_histogram_buckets_quantiles_and_exposition():
    reg = MetricsRegistry()
    h = reg.histogram("oct_lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    h.observe_many(np.asarray([0.5, 100.0]))  # second lands in +Inf
    assert h.count == 6
    assert h.sum == pytest.approx(0.05 + 0.5 * 3 + 5.0 + 100.0)
    assert np.array_equal(h.counts, [1, 3, 1, 1])
    # cumulative bucket exposition + _sum/_count
    text = reg.expose_text()
    assert 'oct_lat_seconds_bucket{le="0.1"} 1' in text
    assert 'oct_lat_seconds_bucket{le="1"} 4' in text
    assert 'oct_lat_seconds_bucket{le="10"} 5' in text
    assert 'oct_lat_seconds_bucket{le="+Inf"} 6' in text
    assert "oct_lat_seconds_count 6" in text
    # quantiles interpolate within the bucket; +Inf clamps to last bound
    assert 0.1 < h.quantile(0.5) <= 1.0
    assert h.quantile(0.999) == 10.0
    assert reg.histogram("oct_empty", "e").quantile(0.5) is None
    # snapshot is JSON-able and carries p50/p99
    snap = reg.snapshot()
    json.dumps(snap)
    row = snap["oct_lat_seconds"]["samples"][0]
    assert row["count"] == 6 and row["p99"] == 10.0


def test_histogram_observe_many_equals_observe():
    reg = MetricsRegistry()
    a = reg.histogram("a", "", buckets=(0.01, 0.1, 1.0))
    b = reg.histogram("b", "", buckets=(0.01, 0.1, 1.0))
    vals = [0.001, 0.02, 0.5, 2.0, 0.09]
    for v in vals:
        a.observe(v)
    b.observe_many(vals)
    assert np.array_equal(a.counts, b.counts)
    assert a.sum == pytest.approx(b.sum)


# ---------------------------------------------------------------------------
# event dataclasses + NodeTracers
# ---------------------------------------------------------------------------


def test_enclose_event_frozen_like_every_other_event():
    ev = T.EncloseEvent("x", "start", 1.0)
    with pytest.raises(dataclasses.FrozenInstanceError):
        ev.duration = 2.0
    lt = T.ListTracer()
    with T.Enclose(lt, "phase"):
        pass
    assert [e.edge for e in lt.events] == ["start", "end"]
    assert lt.events[1].duration is not None


def test_node_tracers_all_to_derives_field_count():
    tr = T.ListTracer()
    nt = T.NodeTracers.all_to(tr)
    assert all(
        getattr(nt, f.name) is tr for f in dataclasses.fields(T.NodeTracers)
    )

    # REGRESSION: a subclass gaining a tracer field must not silently
    # desync (the old `cls(*([tracer] * 7))` left new fields at null)
    @dataclasses.dataclass
    class MoreTracers(T.NodeTracers):
        extra_subsystem: T.Tracer = T.null_tracer

    mt = MoreTracers.all_to(tr)
    assert mt.extra_subsystem is tr
    assert all(
        getattr(mt, f.name) is tr for f in dataclasses.fields(MoreTracers)
    )


# ---------------------------------------------------------------------------
# NodeMetrics <-> registry wiring
# ---------------------------------------------------------------------------


def test_node_metrics_registry_mirror_and_batch_fold():
    reg = MetricsRegistry()
    m = NodeMetrics().bind(reg)
    m.inc("blocks_forged")
    m.note_batch(T.ValidatedBatch(n_headers=8, n_valid=7, device_s=0.25))
    m.note_batch(T.ValidatedBatch(n_headers=4, n_valid=4, device_s=0.05))
    assert m.batches_validated == 2
    assert m.headers_validated == 11
    assert m.headers_invalid == 1
    assert m.batch_device_s == pytest.approx(0.30)
    snap = reg.snapshot()
    assert snap["oct_node_blocks_forged_total"]["samples"][0]["value"] == 1
    assert snap["oct_node_headers_validated_total"]["samples"][0]["value"] == 11
    assert snap["oct_node_headers_invalid_total"]["samples"][0]["value"] == 1


def test_kernel_wires_ledgerdb_batch_events(tmp_path):
    from tests.test_hotkey import _mk_kernel

    kernel = _mk_kernel(tmp_path)
    reg = MetricsRegistry()
    kernel.metrics.bind(reg)
    lt = T.ListTracer()
    kernel.tracers = T.NodeTracers(batch_validation=lt)
    # the kernel pointed the LedgerDB's typed tracer at its fold
    ldb = kernel.chain_db.ledgerdb
    assert ldb.tracer is not None
    ev = T.ValidatedBatch(n_headers=16, n_valid=15, device_s=0.5)
    ldb.tracer(ev)
    assert kernel.metrics.headers_validated == 15
    assert kernel.metrics.headers_invalid == 1
    assert lt.events == [ev]
    assert (
        reg.snapshot()["oct_node_batches_validated_total"]["samples"][0]["value"]
        == 1
    )


# ---------------------------------------------------------------------------
# pipelined validate_chain: span / gate / fallback event sequences
# ---------------------------------------------------------------------------


def make_params(kes_depth=3, epoch_length=100_000):
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=epoch_length,
        kes_depth=kes_depth,
    )


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(50 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture
def stubbed(monkeypatch):
    """Hash-only fused verifiers, aggregate path off, jit caches fenced
    (the test_packed_batch stubbed_crypto idiom, local so this module
    controls OCT_VRF_AGG per test)."""
    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


def _forge_chain(params, pools, lview, n, first_slot=100, first_blkno=1):
    st = praos.PraosState(epoch_nonce=b"\x07" * 32)
    hvs, prev = [], b"\xaa" * 32
    slot, blkno = first_slot, first_blkno
    while len(hvs) < n:
        ticked = praos.tick(params, lview, slot, st)
        blk = forge_block(
            params, pools[len(hvs) % 2], slot=slot, block_no=blkno,
            prev_hash=prev, epoch_nonce=ticked.state.epoch_nonce,
            txs=(b"t",),
        )
        hv = blk.header.to_view()
        st = praos.reupdate(params, hv, slot, ticked)
        hvs.append(hv)
        prev = blk.header.hash_
        slot += 1
        blkno += 1
    return st, hvs


def _of(events, cls):
    return [e for e in events if isinstance(e, cls)]


def test_clean_chain_span_sequence(pools, lview, stubbed):
    """Every window: WindowStaged at dispatch, WindowSpan at retire, in
    index order, packed outcome, correct lane accounting."""
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )
    finally:
        pbatch.set_batch_tracer(None)
    assert res.error is None and res.n_valid == 24
    staged = _of(lt.events, T.WindowStaged)
    spans = _of(lt.events, T.WindowSpan)
    assert len(spans) == len(staged) >= 3
    assert [s.index for s in spans] == sorted(s.index for s in staged)
    assert sum(s.n_valid for s in spans) == 24
    assert not any(s.failed for s in spans)
    # a window is always staged before it retires
    for sp in spans:
        i_staged = next(
            i for i, e in enumerate(lt.events)
            if isinstance(e, T.WindowStaged) and e.index == sp.index
        )
        i_span = lt.events.index(sp)
        assert i_staged < i_span
    # phase walls are populated and sane
    for sp in spans:
        for v in (sp.stage_s, sp.dispatch_s, sp.materialize_s,
                  sp.epilogue_s):
            assert v >= 0.0
        assert sp.t_done >= sp.t_materialized >= sp.t_dispatch - 1e-9


def test_gate_decline_names_the_gate(pools, lview, stubbed):
    """A window mixing CBOR body widths cannot stage packed: the
    WindowStaged event says generic AND names the qualification gate
    (the PR 5 gates were silent about why)."""
    params = make_params()
    # block_no 18..: crosses the CBOR 1->2-byte boundary at 24, so one
    # window mixes body widths (the test_columnar boundary idiom)
    _, hvs = _forge_chain(params, pools, lview, 16, first_blkno=18)
    widths = {len(hv.signed_bytes) for hv in hvs}
    assert len(widths) == 2, "fixture must cross a CBOR width boundary"
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=16
        )
    finally:
        pbatch.set_batch_tracer(None)
    assert res.error is None and res.n_valid == 16
    staged = _of(lt.events, T.WindowStaged)
    declined = [s for s in staged if s.outcome == "generic"]
    assert declined, "the mixed-width window must fall back"
    assert declined[0].gate == "body-width-mixed"
    # and the retired span carries the same attribution
    sp = next(
        s for s in _of(lt.events, T.WindowSpan)
        if s.index == declined[0].index
    )
    assert sp.outcome == "generic" and sp.gate == "body-width-mixed"


def test_stage_packed_decline_reasons_unit(pools, lview):
    """Each qualification gate reports its own reason."""
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 4)
    nonce = b"\x07" * 32

    assert pbatch.stage_packed(params, lview, nonce, []) is None
    assert pbatch._LAST_DECLINE == "empty-window"

    bad = [replace(hvs[0], signed_bytes=hvs[0].signed_bytes + b"x"), *hvs[1:]]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "body-width-mixed"

    bad = [replace(hv, kes_sig=hv.kes_sig + b"x") for hv in hvs]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "kes-sig-len"

    bad = [replace(hv, vrf_proof=hv.vrf_proof[:64]) for hv in hvs]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "proof-format"

    # lane 0's field not embedded in its body at all: offset discovery
    bad = [replace(hvs[0], vk_cold=bytes(32)), *hvs[1:]]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "field-offsets"

    # a LATER lane whose field differs from its embedded copy: the
    # per-lane byte verification
    bad = [hvs[0], replace(hvs[1], vk_cold=bytes(32)), *hvs[2:]]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "field-mismatch"

    bad = [replace(hv, slot=hv.slot + 2**31) for hv in hvs]
    assert pbatch.stage_packed(params, lview, nonce, bad) is None
    assert pbatch._LAST_DECLINE == "int32-range"


def test_corrupted_chain_failing_window_span(pools, lview, stubbed):
    """First-failure semantics in the telemetry: the failing window's
    span reports failed=True with the valid-prefix lane count, and no
    window after it retires (discarded in-flight successors emit
    WindowStaged but never WindowSpan)."""
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    # lane 13 (window 1 of 3 at max_batch=8): unknown pool -> the exact
    # host precheck error; the signed body still embeds the original
    # key, so the window ALSO exercises the field-mismatch fallback
    hvs = [
        replace(hv, vk_cold=bytes(32)) if i == 13 else hv
        for i, hv in enumerate(hvs)
    ]
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )
    finally:
        pbatch.set_batch_tracer(None)
    assert res.n_valid == 13
    # the exact reference error order: the stateful counter check runs
    # before the VRF pool lookup, and an unknown pool has no counter
    assert isinstance(res.error, praos.NoCounterForKeyHashOCERT)
    spans = _of(lt.events, T.WindowSpan)
    assert spans[-1].failed and spans[-1].n_valid == 5
    assert spans[-1].gate == "field-mismatch"
    assert not any(s.failed for s in spans[:-1])
    staged_idx = {s.index for s in _of(lt.events, T.WindowStaged)}
    retired_idx = {s.index for s in spans}
    assert retired_idx < staged_idx or retired_idx == staged_idx


def test_agg_anomaly_redispatch_event(pools, lview, monkeypatch):
    """The aggregate (RLC/MSM) path re-dispatching a dirty window emits
    AggRedispatch BEFORE that window's span (test_aggregate's stubbed
    dispatch plumbing, now with the event order asserted)."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg_mod

    from tests.test_aggregate import (
        _stub_aggregate, _stub_verdicts, real_chain,
    )

    before = set(pbatch._JIT)
    params = make_params()
    nonce, hvs = real_chain(params, pools, lview, 12)
    assert len(hvs[0].vrf_proof) == 128
    monkeypatch.setattr(agg_mod, "aggregate_window", _stub_aggregate(False))
    monkeypatch.setattr(pbatch, "verify_praos_any",
                        lambda *cols: _stub_verdicts(cols))
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview,
            replace(praos.PraosState(), epoch_nonce=nonce), hvs,
            max_batch=len(hvs),
        )
    finally:
        pbatch.set_batch_tracer(None)
        for k in set(pbatch._JIT) - before:
            del pbatch._JIT[k]
    assert res.error is None and res.n_valid == len(hvs)
    kinds = [type(e).__name__ for e in lt.events]
    assert "AggRedispatch" in kinds
    staged = _of(lt.events, T.WindowStaged)
    assert staged[0].outcome == "packed-agg"
    assert kinds.index("AggRedispatch") < kinds.index("WindowSpan")


# ---------------------------------------------------------------------------
# flight recorder -> registry + Perfetto export of a replay
# ---------------------------------------------------------------------------


def test_recorder_replay_metrics_and_perfetto_schema(pools, lview, stubbed,
                                                     monkeypatch):
    """OCT_TRACE end to end: the recorder rides a (stubbed) pipelined
    replay, the dispatch->materialize latency histogram records p50/p99,
    and the Perfetto export validates against the Chrome trace-event
    schema."""
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    monkeypatch.setenv("OCT_TRACE", "1")
    assert obs.enabled()
    rec = obs.install()
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )
    finally:
        obs.uninstall()
    assert res.error is None
    assert pbatch.BATCH_TRACER is None  # uninstall restored the seam

    summary = rec.latency_summary()
    assert summary["windows"] >= 3
    assert summary["device_latency_p50_s"] is not None
    assert summary["device_latency_p99_s"] is not None
    assert summary["device_latency_p99_s"] >= summary["device_latency_p50_s"]

    snap = rec.registry.snapshot()
    outcomes = {
        s["labels"]["outcome"]: s["value"]
        for s in snap["oct_windows_total"]["samples"]
    }
    assert sum(outcomes.values()) == summary["windows"]
    assert snap["oct_headers_validated_total"]["samples"][0]["value"] == 24
    assert snap["oct_h2d_bytes_total"]["samples"][0]["value"] > 0
    lat = snap["oct_window_device_latency_seconds"]["samples"][0]
    assert lat["count"] == summary["windows"]
    assert lat["p50"] is not None and lat["p99"] is not None

    doc = rec.chrome_trace()
    assert perfetto.validate_chrome_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"]}
    # window indexes are a process-global sequence: assert shape, not 0
    assert any(n.startswith("window ") for n in names)
    assert "stage" in names and "materialize" in names
    # round-trips through real JSON
    doc2 = json.loads(json.dumps(doc))
    assert perfetto.validate_chrome_trace(doc2) == []


def test_perfetto_validator_rejects_malformed():
    assert perfetto.validate_chrome_trace([]) != []
    assert perfetto.validate_chrome_trace({"traceEvents": "no"}) != []
    bad = {"traceEvents": [{"name": 3, "ph": "Q", "ts": -1, "pid": "x"}]}
    errs = perfetto.validate_chrome_trace(bad)
    assert len(errs) >= 4
    good = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0, "dur": 1.5, "pid": 1, "tid": 2},
    ]}
    assert perfetto.validate_chrome_trace(good) == []


# ---------------------------------------------------------------------------
# warmup forensics
# ---------------------------------------------------------------------------


def test_warmup_recorder_report_and_flush(tmp_path, monkeypatch):
    path = str(tmp_path / "warmup.json")
    monkeypatch.setenv("OCT_WARMUP_REPORT", path)
    w = WarmupRecorder()
    assert w.note_stage("ed@b8192", 123.4, via="jit")
    assert not w.note_stage("ed@b8192", 0.001)  # only the first counts
    # the file is flushed ATOMICALLY after every note — a kill at any
    # point leaves the last complete report on disk
    on_disk = read_report(path)
    assert on_disk["stages"]["ed@b8192"]["wall_s"] == pytest.approx(123.4)
    w.note_aot("kes", "rejected", 15.2, "axon format v5, build is v9")
    w.note_cache_probe("stale", 14.9, "cached executable is axon format v5")
    w.note("warmup replay starting")
    rep = read_report(path)
    assert rep["aot"] == {"rejected": 1}
    assert rep["aot_events"][0]["stage"] == "kes"
    assert rep["cache_probe"]["outcome"] == "stale"
    assert rep["compile_total_s"] == pytest.approx(123.4)
    assert rep["n_stages"] == 1
    assert any("warmup replay" in n for n in rep["notes"])
    json.dumps(rep)


def test_warmup_report_survives_a_kill(tmp_path):
    """The r02-r05 failure shape: a bench child dies mid-warmup. The
    per-note atomic flush must leave a readable per-stage diagnosis."""
    path = str(tmp_path / "warmup.json")
    code = (
        "import os\n"
        "from ouroboros_consensus_tpu.obs.warmup import WARMUP\n"
        "WARMUP.note_stage('relayout@b8192', 95.0, via='jit')\n"
        "WARMUP.note_stage('ed@b8192', 180.5, via='jit')\n"
        "WARMUP.note_aot('vrf', 'rejected', 15.0, 'axon format v5')\n"
        "os._exit(137)  # killed at the wall mid-compile\n"
    )
    env = dict(os.environ)
    env["OCT_WARMUP_REPORT"] = path
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, timeout=120,
    )
    assert proc.returncode == 137
    rep = read_report(path)
    assert rep is not None, "a warmup death must still bank a diagnosis"
    assert rep["stages"]["ed@b8192"]["wall_s"] == pytest.approx(180.5)
    assert rep["compile_total_s"] == pytest.approx(275.5)
    assert rep["aot"] == {"rejected": 1}
    # and bench.py banks exactly this block into the round JSON
    import bench

    assert bench._read_warmup_report(path) == rep


def test_stage_call_records_first_execute(monkeypatch):
    from ouroboros_consensus_tpu.obs.warmup import WARMUP
    from ouroboros_consensus_tpu.ops.pk import kernels

    monkeypatch.setenv("OCT_PK_AOT", "0")  # jit path only
    WARMUP.reset()
    kernels._FIRST_EXEC.discard("obstest@b4")
    calls = []

    def fake_stage(x):
        calls.append(x)
        return x

    out = kernels._stage_call("obstest", fake_stage, 4, 2, np.zeros(3))
    kernels._stage_call("obstest", fake_stage, 4, 2, np.zeros(3))
    assert len(calls) == 2 and out is calls[0]
    rep = WARMUP.report()
    assert "obstest@b4" in rep["stages"]
    assert rep["stages"]["obstest@b4"]["via"] == "jit"


# ---------------------------------------------------------------------------
# instrumentation purity (the telemetry-adds-zero-equations ratchet)
# ---------------------------------------------------------------------------


def test_instrumentation_purity_zero_eqn_growth():
    from ouroboros_consensus_tpu.analysis import graphs

    budgets = graphs.load_budgets()
    assert budgets["instrumentation_purity"]["graphs"], (
        "the purity ratchet must pin at least the protocol/batch graphs"
    )
    # the cheap protocol/batch graph: one differential proves the wiring
    assert graphs.check_instrumentation_purity(
        budgets, names=["verdict_reduce"]
    ) == []


# ---------------------------------------------------------------------------
# Prometheus endpoint (tools/immdb_server.serve_metrics)
# ---------------------------------------------------------------------------


def test_metrics_http_endpoint():
    import asyncio

    from ouroboros_consensus_tpu.tools import immdb_server

    reg = MetricsRegistry()
    reg.counter("oct_widgets_total", "w").inc(5)

    async def scenario():
        server = await immdb_server.serve_metrics(port=0, registry=reg)
        port = server.sockets[0].getsockname()[1]

        async def get(path):
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await writer.drain()
            data = await reader.read()
            writer.close()
            return data

        text = await get("/metrics")
        assert text.startswith(b"HTTP/1.0 200 OK")
        assert b"oct_widgets_total 5" in text
        js = await get("/metrics.json")
        body = js.split(b"\r\n\r\n", 1)[1]
        snap = json.loads(body)
        assert snap["oct_widgets_total"]["samples"][0]["value"] == 5
        # scrapes counted themselves
        assert snap["oct_metrics_scrapes_total"]["samples"][0]["value"] >= 1
        missing = await get("/nope")
        assert missing.startswith(b"HTTP/1.0 404")
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# histogram hardening: non-finite observations must never leak NaN into
# JSON snapshots (and through them the bench round file)
# ---------------------------------------------------------------------------


def test_histogram_nonfinite_observations_dropped_and_counted():
    reg = MetricsRegistry()
    h = reg.histogram("oct_nan_seconds", "hardening", buckets=(1.0, 10.0))
    # empty histogram: None, never NaN (regression for the quantile
    # contract the bench round file depends on)
    assert h.quantile(0.5) is None
    assert h.quantile(0.99) is None
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe_many([1.0, float("nan"), 2.0, float("-inf")])
    assert h.count == 2
    assert h.dropped_nonfinite == 4
    assert h.sum == pytest.approx(3.0)  # NaN never poisoned the sum
    snap = reg.snapshot()
    # the whole snapshot stays STRICT json — json.dumps(allow_nan=False)
    # is exactly what obs/ledger.append enforces
    json.dumps(snap, allow_nan=False)
    row = snap["oct_nan_seconds"]["samples"][0]
    assert row["dropped_nonfinite"] == 4
    assert row["p50"] is not None and row["p99"] is not None
    # exposition still renders (finite values only)
    assert "oct_nan_seconds_count 2" in reg.expose_text()


def test_latency_summary_none_not_nan_on_empty_recorder():
    rec = obs.recorder()
    s = rec.latency_summary()
    assert s["windows"] == 0
    assert s["device_latency_p50_s"] is None
    assert s["device_latency_p99_s"] is None
    json.dumps(s, allow_nan=False)


# ---------------------------------------------------------------------------
# metric-name drift gate: obs/README.md vs the registrations, both ways
# ---------------------------------------------------------------------------


def _readme_metric_names():
    import re

    readme = os.path.join(
        os.path.dirname(os.path.abspath(obs.__file__)), "README.md"
    )
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    concrete, wildcards = set(), set()
    # tokens like oct_windows_total, oct_window_{a,b}_seconds{label=},
    # oct_node_*_total; the lookbehind keeps ".oct_ledger" (a path, not
    # a metric) out
    for tok in re.findall(r"(?<![.\w])oct_[a-z0-9_]+(?:\{[^}\s]*\})?"
                          r"[a-z0-9_*]*", text):
        # strip a trailing label annotation: {kind=} / {stage=,kind=}
        tok = re.sub(r"\{[^}]*=[^}]*\}", "", tok)
        m = re.match(r"^([a-z0-9_]*)\{([a-z0-9_,]+)\}([a-z0-9_]*)$", tok)
        if m:  # brace EXPANSION: oct_window_{stage,dispatch}_seconds
            for alt in m.group(2).split(","):
                concrete.add(m.group(1) + alt + m.group(3))
        elif "*" in tok:
            wildcards.add(tok)
        elif re.fullmatch(r"oct_[a-z0-9_]+", tok):
            concrete.add(tok)
    return concrete, wildcards


def _registered_metric_names():
    import re

    from ouroboros_consensus_tpu.node import serve as node_serve
    from ouroboros_consensus_tpu.obs import resources as obs_resources
    from ouroboros_consensus_tpu.obs import server as obs_server
    from ouroboros_consensus_tpu.obs.recorder import FlightRecorder
    from ouroboros_consensus_tpu.tools import immdb_server

    reg = MetricsRegistry()
    FlightRecorder(reg)
    NodeMetrics().bind(reg)
    obs_resources.register_families(reg)
    names = set(reg._families)
    # the immdb server, the (factored-out) HTTP endpoint and the serving
    # plane register their families at serve time: hold them to the same
    # contract via their registration literals
    for mod in (immdb_server, obs_server, node_serve):
        with open(mod.__file__, encoding="utf-8") as f:
            names |= set(re.findall(r'"(oct_[a-z0-9_]+)"', f.read()))
    return names


def test_readme_metric_names_match_registrations():
    """Both directions: the README's metric table cannot rot as families
    are added (this PR adds oct_stage_*), and no documented family may
    silently disappear from the code."""
    import fnmatch

    concrete, wildcards = _readme_metric_names()
    actual = _registered_metric_names()
    assert concrete, "README metric table parsed empty — parser broken?"

    documented_missing = {
        n for n in concrete if n not in actual
    } | {
        w for w in wildcards
        if not any(fnmatch.fnmatch(a, w) for a in actual)
    }
    assert not documented_missing, (
        f"obs/README.md documents families the code never registers: "
        f"{sorted(documented_missing)}"
    )
    undocumented = {
        a for a in actual
        if a not in concrete
        and not any(fnmatch.fnmatch(a, w) for w in wildcards)
    }
    assert not undocumented, (
        f"registered families missing from obs/README.md: "
        f"{sorted(undocumented)}"
    )


# ---------------------------------------------------------------------------
# the PR 8 compile-wall-refused telemetry path, end to end
# ---------------------------------------------------------------------------


def test_compile_wall_refusal_is_visible_telemetry(monkeypatch):
    """A real dispatch_batch window whose aggregate program is refused
    by the octwall pre-flight (stubbed clock via OCT_WALL_DEADLINE):
    the refusal must be VISIBLE — a packed WindowStaged carrying
    gate="compile-wall-refused", an
    oct_gate_declines_total{gate="compile-wall-refused"} increment, and
    an entry in the warmup report's refusals list."""
    import time as _time

    from ouroboros_consensus_tpu.analysis import costmodel
    from ouroboros_consensus_tpu.obs.warmup import WARMUP
    from ouroboros_consensus_tpu.testing import fixtures as _fx

    from tests.test_aggregate import (
        _stub_verdicts, make_params as agg_params, real_chain,
    )

    pools2 = [_fx.make_pool(50 + i, kes_depth=3) for i in range(2)]
    lview2 = fixtures.make_ledger_view(pools2)
    params = agg_params()
    nonce, hvs = real_chain(params, pools2, lview2, 8)
    assert len(hvs[0].vrf_proof) == 128  # batch-compatible window

    WARMUP.reset()
    monkeypatch.delenv("OCT_VRF_AGG", raising=False)
    # stubbed clock: 40 s of wall left vs a 500 s predicted aggregate
    # compile, with the per-lane fallback predicted 10x cheaper
    monkeypatch.setenv("OCT_WALL_DEADLINE", str(_time.time() + 40.0))
    monkeypatch.setattr(
        costmodel, "predicted_wall",
        lambda g: 500.0 if g == "aggregate_core" else 50.0,
    )
    monkeypatch.setattr(pbatch, "verify_praos_any",
                        lambda *cols: _stub_verdicts(cols))
    monkeypatch.setattr(
        pbatch, "_jitted_packed_agg",
        lambda layout, scan, mode="all": pytest.fail(
            "refused aggregate program was still dispatched"),
    )
    before = set(pbatch._JIT)
    rec = obs.install()
    try:
        _pre, disp, b, _carry = pbatch.dispatch_batch(
            params, lview2, nonce, hvs
        )
    finally:
        obs.uninstall()
        for k in set(pbatch._JIT) - before:
            del pbatch._JIT[k]
    assert b == len(hvs) and disp.impl != "agg"

    staged = _of([e for _t, e in rec.timed_events()], T.WindowStaged)
    assert staged, "dispatch_batch must emit WindowStaged"
    assert staged[-1].outcome == "packed"  # still packed — off-agg path
    assert staged[-1].gate == "compile-wall-refused"

    snap = rec.registry.snapshot()
    gates = {
        s["labels"]["gate"]: s["value"]
        for s in snap["oct_gate_declines_total"]["samples"]
    }
    assert gates.get("compile-wall-refused") == 1
    outcomes = {
        s["labels"]["outcome"]: s["value"]
        for s in snap["oct_windows_total"]["samples"]
    }
    assert outcomes.get("packed") == 1

    refs = WARMUP.report()["refusals"]
    assert len(refs) == 1
    assert refs[0]["stage"].startswith("agg-packed:")
    assert refs[0]["predicted_s"] == pytest.approx(500.0)
    WARMUP.reset()


# ---------------------------------------------------------------------------
# Perfetto warmup track (compile walls visible in the wall visualizer)
# ---------------------------------------------------------------------------


def test_perfetto_warmup_track_slices_and_instants():
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    WARMUP.note_stage("agg-packed:410b:scan", 12.5, via="xla-jit",
                      feature_hash="216e9c5e109f6aa6")
    WARMUP.note_aot("ed", "rejected", 1.0, "axon format v5")
    WARMUP.note_refusal("xla-packed:410b:p128:scan", 410.0, 90.0,
                        "stage-split-fallback")
    rec = obs.recorder()
    doc = rec.chrome_trace()
    assert perfetto.validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    names = [e["name"] for e in evs]
    # thread metadata names the warmup row
    threads = {e["args"]["name"] for e in evs if e["ph"] == "M"
               and e["name"] == "thread_name"}
    assert "warmup" in threads
    (slice_ev,) = [e for e in evs if "first-execute" in e["name"]]
    assert slice_ev["ph"] == "X"
    assert slice_ev["dur"] == pytest.approx(12.5e6, rel=1e-6)
    assert slice_ev["tid"] == perfetto._TIDS["warmup"]
    assert slice_ev["args"]["via"] == "xla-jit"
    assert slice_ev["args"]["feature_hash"] == "216e9c5e109f6aa6"
    assert any(n == "aot ed: rejected" for n in names)
    assert any(n.startswith("compile-wall refused:") for n in names)
    # a report WITHOUT its t0 (cross-process file) adds no warmup rows
    doc2 = perfetto.to_chrome_trace([], warmup_report=WARMUP.report(),
                                    warmup_t0=None)
    assert not any("first-execute" in e["name"]
                   for e in doc2["traceEvents"])
    WARMUP.reset()


def test_trace_out_replay_includes_warmup_track(pools, lview, stubbed,
                                                monkeypatch):
    """The --trace-out shape: a (stubbed) replay export carries BOTH
    window spans and the warmup first-execute slices in one document."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    # earlier tests in this process may have consumed the stub jits'
    # first executes — clear the once-only gate so THIS replay notes them
    pbatch._WARM_SEEN.clear()
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 16)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    monkeypatch.setenv("OCT_TRACE", "1")
    rec = obs.install()
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )
    finally:
        obs.uninstall()
    assert res.error is None
    doc = rec.chrome_trace()
    assert perfetto.validate_chrome_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"]}
    assert any(n.startswith("window ") for n in names)
    # the stubbed jits ARE first executes: their compile slices show up
    assert any("first-execute" in n for n in names)
    WARMUP.reset()


# ---------------------------------------------------------------------------
# acceptance: a stubbed-crypto replay appends ONE well-formed ledger
# record carrying the recorder's state
# ---------------------------------------------------------------------------


def test_stubbed_replay_appends_one_ledger_record(pools, lview, stubbed,
                                                  monkeypatch, tmp_path):
    from ouroboros_consensus_tpu.obs import ledger

    led = str(tmp_path / "ledger")
    monkeypatch.setenv("OCT_LEDGER", led)
    monkeypatch.setenv("OCT_TRACE", "1")
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    rec = obs.install()
    try:
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )
    finally:
        obs.uninstall()
    assert res.error is None and res.n_valid == 24
    out = ledger.record_replay(
        "replay", recorder=rec,
        config={"n": 24, "max_batch": 8},
        result={"headers": res.n_valid},
    )
    assert out is not None
    runs = ledger.read_runs(led)
    assert len(runs) == 1, "exactly one record per run"
    rec_d = runs[0]
    assert ledger.validate_record(rec_d) == []
    assert rec_d["kind"] == "replay"
    # the recorder's state rode in: metrics snapshot + latency summary
    assert rec_d["metrics"]["oct_headers_validated_total"][
        "samples"][0]["value"] == 24
    assert rec_d["metrics_summary"]["windows"] >= 3
    assert rec_d["warmup_report"] is not None
    assert rec_d["env"].get("OCT_TRACE") == "1"


# ---------------------------------------------------------------------------
# lint --changed: obs edits re-run the instrumentation-purity re-trace
# ---------------------------------------------------------------------------


def test_lint_changed_maps_obs_sources_to_purity_graphs():
    """An obs/ (or perf_report) edit cannot change a crypto graph, but
    it CAN leak telemetry into a traced program — the --changed fast
    path must select the instrumentation-purity graphs instead of
    skipping every graph pass."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_gate", os.path.join(repo, "scripts", "lint.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    # forge_sweep joined the purity plane in round 18: ForgeSpan
    # telemetry is emitted beside the traced sweep program
    purity = {"packed_unpack", "verdict_reduce", "spmd_sharded_verify",
              "forge_sweep"}
    assert set(lint._select_graphs(
        {"ouroboros_consensus_tpu/obs/recorder.py"}
    )) == purity
    assert set(lint._select_graphs({"scripts/perf_report.py"})) == purity
    # the round-11 live-plane modules ride the obs/ prefix
    assert set(lint._select_graphs(
        {"ouroboros_consensus_tpu/obs/live.py"}
    )) == purity
    assert set(lint._select_graphs(
        {"ouroboros_consensus_tpu/obs/server.py"}
    )) == purity
    # parallel/spmd.py emits ShardSpan telemetry beside the shard_map
    # program: an spmd edit re-runs the purity differential ON TOP of
    # its own graph selection
    assert purity <= set(lint._select_graphs(
        {"ouroboros_consensus_tpu/parallel/spmd.py"}
    ))
    # composes with ordinary graph-source selection
    sel = lint._select_graphs({
        "ouroboros_consensus_tpu/obs/ledger.py",
        "ouroboros_consensus_tpu/ops/pk/msm.py",
    })
    assert set(sel) == purity | {"aggregate_core", "aggregate_vrf_core",
                                 "msm"}
    # and still selects nothing for unrelated files
    assert lint._select_graphs({"README.md"}) == []


# ---------------------------------------------------------------------------
# round 10: warm-ladder events — counter family + Perfetto warmup track
# ---------------------------------------------------------------------------


def test_ladder_events_counter_and_report():
    from ouroboros_consensus_tpu.utils.trace import LadderEvent

    reg = MetricsRegistry()
    from ouroboros_consensus_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(reg)
    for kind in ("engaged", "bg-compile-started", "bg-compile-done",
                 "swap"):
        rec(LadderEvent(kind, 1024, 8192))
    snap = reg.snapshot()
    kinds = {
        s["labels"]["kind"]: s["value"]
        for s in snap["oct_ladder_events_total"]["samples"]
    }
    assert kinds == {"engaged": 1, "bg-compile-started": 1,
                     "bg-compile-done": 1, "swap": 1}


def test_perfetto_ladder_track_renders_bg_compile_slice():
    """The warmup track renders the background production compile as a
    SLICE (started -> done) and every other ladder transition as an
    instant — the compile the ladder hides is finally visible in the
    wall visualizer."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    WARMUP.note_ladder("engaged", rung=1024, target=8192,
                       graph="aggregate_core", predicted_s=757.9,
                       feature_hash="216e9c5e109f6aa6")
    WARMUP.note_ladder("bg-compile-started", rung=1024, target=8192,
                       stage="agg-packed:410b:scan:8192l")
    import time as _time

    _time.sleep(0.02)
    WARMUP.note_ladder("bg-compile-done", rung=1024, target=8192,
                       wall_s=0.02)
    WARMUP.note_ladder("swap", rung=1024, target=8192)
    rec = obs.recorder()
    doc = rec.chrome_trace()
    assert perfetto.validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    names = [e["name"] for e in evs]
    (bg,) = [e for e in evs if e["name"].startswith(
        "ladder background compile")]
    assert bg["ph"] == "X" and bg["dur"] > 0
    assert bg["tid"] == perfetto._TIDS["warmup"]
    assert any(n.startswith("ladder: engaged") for n in names)
    assert any(n.startswith("ladder: swap") for n in names)
    # a FAILED background compile renders as a slice too (kind in args)
    WARMUP.reset()
    WARMUP.note_ladder("bg-compile-started", rung=1024, target=8192)
    WARMUP.note_ladder("bg-compile-failed", rung=1024, target=8192,
                       detail="RuntimeError('boom')")
    doc2 = obs.recorder().chrome_trace()
    assert perfetto.validate_chrome_trace(doc2) == []
    assert any(e["name"] == "ladder background compile [failed]"
               for e in doc2["traceEvents"])
    WARMUP.reset()


def test_warmup_ladder_notes_flush_and_reset(tmp_path, monkeypatch):
    monkeypatch.setenv("OCT_WARMUP_REPORT", str(tmp_path / "wr.json"))
    w = WarmupRecorder()
    w.note_ladder("engaged", rung=1024, target=8192, predicted_s=757.9)
    rep = json.load(open(tmp_path / "wr.json"))
    (row,) = rep["ladder"]
    assert row["kind"] == "engaged" and row["rung"] == 1024
    assert row["predicted_s"] == 757.9 and "t" in row
    w.reset()
    assert w.report()["ladder"] == []
