"""Differential tests: ops/pk verifier cores vs the XLA fused path.

Runs the limb-first cores under plain jit on CPU (same trace the Pallas
kernels execute; the kernels themselves are additionally exercised in
interpret mode by test_kernels_interpret_smoke, and on real TPU hardware
by bench.py / scripts/debug_pk_tpu.py)."""

import dataclasses
import os
from fractions import Fraction

import numpy as np
import pytest

# the composed verify cores take >10 min to compile on single-core
# XLA:CPU (the Pallas kernels themselves compile fast on TPU via Mosaic
# — scripts/debug_pk_tpu.py and bench.py exercise them there); opt in
# with OCT_SLOW_TESTS=1
pytestmark = pytest.mark.skipif(
    not os.environ.get("OCT_SLOW_TESTS"),
    reason="pk composition compile is multi-minute on XLA:CPU; "
    "set OCT_SLOW_TESTS=1 (TPU coverage: bench.py, scripts/debug_pk_tpu.py)",
)

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops.pk import verify as pv
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=100_000,
    kes_depth=3,
)
ETA0 = b"\x07" * 32
B = 16


@pytest.fixture(scope="module")
def staged():
    pools = [fixtures.make_pool(i, kes_depth=3) for i in range(3)]
    lview = fixtures.make_ledger_view(pools)
    hvs, slot, prev = [], 1, None
    while len(hvs) < B:
        pool = fixtures.find_leader(PARAMS, pools, lview, slot, ETA0)
        if pool is not None:
            hvs.append(
                fixtures.forge_header_view(
                    PARAMS, pool, slot=slot, epoch_nonce=ETA0,
                    prev_hash=prev, body_bytes=b"b%d" % len(hvs),
                )
            )
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    # distinct corruption kinds on distinct lanes
    hvs[3] = dataclasses.replace(
        hvs[3],
        ocert=dataclasses.replace(
            hvs[3].ocert,
            sigma=hvs[3].ocert.sigma[:-1] + bytes([hvs[3].ocert.sigma[-1] ^ 1]),
        ),
    )
    hvs[6] = dataclasses.replace(
        hvs[6], kes_sig=hvs[6].kes_sig[:-1] + bytes([hvs[6].kes_sig[-1] ^ 1])
    )
    hvs[9] = dataclasses.replace(
        hvs[9],
        vrf_proof=hvs[9].vrf_proof[:1]
        + bytes([hvs[9].vrf_proof[1] ^ 1])
        + hvs[9].vrf_proof[2:],
    )
    hvs[12] = dataclasses.replace(
        hvs[12],
        vrf_output=hvs[12].vrf_output[:1]
        + bytes([hvs[12].vrf_output[1] ^ 1])
        + hvs[12].vrf_output[2:],
    )
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    batch = pbatch.stage(PARAMS, lview, ETA0, hvs, pre.kes_evolution)
    return batch


def _core_verdicts(batch):
    # the forge default is batch-compatible proofs (22 staged columns,
    # announced u/v): dispatch the matching composed core
    arrays = [jnp.asarray(x) for x in pbatch.pk_arrays(batch)]
    bc = len(arrays) == 22

    def f(*a):
        if bc:
            (ed_pk, ed_r, ed_s, ed_hb, ed_hnb, kes_vk, kes_per, kes_r,
             kes_s, kes_leaf, kes_sib, kes_hb, kes_hnb, vrf_pk, vrf_g,
             vrf_u, vrf_v, vrf_s, vrf_al, beta, tlo, thi) = a
            return pv.verify_praos_core_bc(
                ed_pk, ed_r, ed_s, ed_hb, ed_hnb[0],
                kes_vk, kes_per[0], kes_r, kes_s, kes_leaf, kes_sib,
                kes_hb, kes_hnb[0],
                vrf_pk, vrf_g, vrf_u, vrf_v, vrf_s, vrf_al,
                beta, tlo, thi, kes_depth=3,
            )
        (ed_pk, ed_r, ed_s, ed_hb, ed_hnb, kes_vk, kes_per, kes_r, kes_s,
         kes_leaf, kes_sib, kes_hb, kes_hnb, vrf_pk, vrf_g, vrf_c, vrf_s,
         vrf_al, beta, tlo, thi) = a
        return pv.verify_praos_core(
            ed_pk, ed_r, ed_s, ed_hb, ed_hnb[0],
            kes_vk, kes_per[0], kes_r, kes_s, kes_leaf, kes_sib,
            kes_hb, kes_hnb[0],
            vrf_pk, vrf_g, vrf_c, vrf_s, vrf_al,
            beta, tlo, thi, kes_depth=3,
        )

    return jax.tree.map(np.asarray, jax.jit(f)(*arrays))


def test_core_matches_xla_fused(staged):
    """Lane-for-lane agreement with the original XLA fused verifier on
    every verdict bit plus eta and the leader value."""
    v = _core_verdicts(staged)
    fn = pbatch._jitted_verify(pbatch.batch_is_bc(staged))
    xla = pbatch.Verdicts(
        *(np.asarray(x) for x in fn(
            *(jnp.asarray(x) for x in pbatch.flatten_batch(staged))
        ))
    )
    assert (v.ok_ocert_sig == xla.ok_ocert_sig).all()
    assert (v.ok_kes_sig == xla.ok_kes_sig).all()
    assert (v.ok_vrf == xla.ok_vrf).all()
    assert (v.ok_leader == xla.ok_leader).all()
    assert (v.leader_ambiguous == xla.leader_ambiguous).all()
    assert (v.eta.T == np.asarray(xla.eta)).all()
    assert (v.leader_value.T == np.asarray(xla.leader_value)).all()


def test_core_flags_exact_corrupt_lanes(staged):
    v = _core_verdicts(staged)
    assert not v.ok_ocert_sig[3] and v.ok_kes_sig[3] and v.ok_vrf[3]
    assert v.ok_ocert_sig[6] and not v.ok_kes_sig[6] and v.ok_vrf[6]
    assert not v.ok_vrf[9] and v.ok_ocert_sig[9] and v.ok_kes_sig[9]
    assert not v.ok_vrf[12]
    clean = [i for i in range(B) if i not in (3, 6, 9, 12)]
    for i in clean:
        assert v.ok_ocert_sig[i] and v.ok_kes_sig[i] and v.ok_vrf[i]
