"""Mempool semantics (reference: Test/Consensus/Mempool.hs — validity
consistent with ledger, FIFO ticket order, capacity, sync-on-reorg)."""

import pytest

from ouroboros_consensus_tpu.ledger.mock import (
    InvalidTx,
    MockConfig,
    MockLedger,
    MockState,
    encode_tx,
    tx_id,
)
from ouroboros_consensus_tpu.mempool import Mempool, MempoolFull


from ouroboros_consensus_tpu.protocol.views import LedgerView


@pytest.fixture
def ledger():
    # the mempool never consults the protocol view: empty distr is fine
    return MockLedger(MockConfig(LedgerView(pool_distr={}), 24))


@pytest.fixture
def genesis(ledger):
    return ledger.genesis_state([(b"alice", 100), (b"bob", 50)])


def make_pool(ledger, state, slot=0, **kw):
    return Mempool(ledger, lambda: (state, slot), **kw)


def _genesis_txin(state, addr):
    for txin, (a, amt) in state.utxo.items():
        if a == addr:
            return txin, amt
    raise AssertionError


def test_add_valid_tx_fifo_tickets(ledger, genesis):
    pool = make_pool(ledger, genesis)
    txin, amt = _genesis_txin(genesis, b"alice")
    tx1 = encode_tx([txin], [(b"carol", amt)])
    t1 = pool.add_tx(tx1)
    # chained tx spending tx1's output is valid against the POOL view
    tx2 = encode_tx([(tx_id(tx1), 0)], [(b"dave", amt)])
    t2 = pool.add_tx(tx2)
    assert (t1.number, t2.number) == (1, 2)
    snap = pool.get_snapshot()
    assert snap.tx_bytes() == (tx1, tx2)
    assert snap.after(1) == (snap.txs[1],)


def test_invalid_tx_rejected(ledger, genesis):
    pool = make_pool(ledger, genesis)
    bad = encode_tx([(b"\x00" * 32, 0)], [(b"x", 1)])
    with pytest.raises(InvalidTx):
        pool.add_tx(bad)
    # double spend within the pool
    txin, amt = _genesis_txin(genesis, b"alice")
    pool.add_tx(encode_tx([txin], [(b"c", amt)]))
    with pytest.raises(InvalidTx):
        pool.add_tx(encode_tx([txin], [(b"d", amt)]))


def test_capacity(ledger, genesis):
    txin, amt = _genesis_txin(genesis, b"alice")
    tx = encode_tx([txin], [(b"carol", amt)])
    pool = make_pool(ledger, genesis, capacity_bytes=len(tx) - 1)
    with pytest.raises(MempoolFull):
        pool.add_tx(tx)


def test_sync_with_ledger_drops_spent(ledger, genesis):
    state = {"cur": genesis}
    pool = Mempool(ledger, lambda: (state["cur"], 0))
    txin, amt = _genesis_txin(genesis, b"alice")
    tx = encode_tx([txin], [(b"carol", amt)])
    pool.add_tx(tx)
    # the chain adopts a block spending the same input differently
    other = encode_tx([txin], [(b"eve", amt)])
    new_utxo = ledger.apply_tx(dict(genesis.utxo), other)
    state["cur"] = MockState(new_utxo, 1)
    dropped = pool.sync_with_ledger()
    assert [t.tx for t in dropped] == [tx]
    assert pool.get_snapshot().txs == ()


def test_remove_txs_revalidates_dependents(ledger, genesis):
    pool = make_pool(ledger, genesis)
    txin, amt = _genesis_txin(genesis, b"alice")
    tx1 = encode_tx([txin], [(b"carol", amt)])
    pool.add_tx(tx1)
    tx2 = encode_tx([(tx_id(tx1), 0)], [(b"dave", amt)])
    pool.add_tx(tx2)
    pool.remove_txs([tx_id(tx1)])
    # tx2 depended on tx1's output: dropped by the revalidation pass
    assert pool.get_snapshot().txs == ()


def test_get_snapshot_for_respects_budget(ledger, genesis):
    pool = make_pool(ledger, genesis)
    ta, amta = _genesis_txin(genesis, b"alice")
    tb, amtb = _genesis_txin(genesis, b"bob")
    tx1 = encode_tx([ta], [(b"c", amta)])
    tx2 = encode_tx([tb], [(b"d", amtb)])
    pool.add_tx(tx1)
    pool.add_tx(tx2)
    snap = pool.get_snapshot_for(genesis, 5, max_bytes=len(tx1))
    assert snap.tx_bytes() == (tx1,)
    full = pool.get_snapshot_for(genesis, 5)
    assert full.tx_bytes() == (tx1, tx2)


def test_mempool_rejects_garbage_txs(ledger, genesis):
    """Gossiped garbage — undecodable bytes AND structurally-decodable
    nonsense (unhashable inputs, non-int amounts) — must come back as
    rejections, never crash the mempool."""
    from ouroboros_consensus_tpu.utils import cbor

    pool = make_pool(ledger, genesis)
    garbage = [
        b"\xff\xfe not cbor",
        cbor.encode([[[b"", []]], []]),       # unhashable input index
        cbor.encode([[], [[b"a", b"x"]]]),    # non-int amount
        cbor.encode([1, 2, 3]),               # wrong arity
    ]
    ok, bad = pool.try_add_txs(garbage)
    assert ok == [] and len(bad) == len(garbage)
