"""HotKey KES evolution + operational re-keying.

Reference: `Protocol/Ledger/HotKey.hs` (KESInfo/kesStatus :45,90, HotKey
record :124, mkHotKey :169 — evolution forgets old keys) and the ocert
counter rules checked at `Praos.hs:585-605`; re-keying is the reference's
`ThreadNet/Util/Rekeying.hs` scenario.
"""

import os
from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.node.kernel import NodeKernel
from ouroboros_consensus_tpu.ops.host import kes as hk
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.hotkey import (
    HotKey,
    KESBeforeStart,
    KESKeyExpired,
    KESInfo,
    kes_status,
)
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=2,  # tiny: evolutions happen within a short chain
    max_kes_evolutions=2,
    security_param=3,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOL = fixtures.make_pool(0, kes_depth=2)
LVIEW = fixtures.make_ledger_view([POOL])
ETA0 = b"\x22" * 32


def test_hotkey_signatures_match_static_signer():
    seed, depth = b"\x11" * 32, 3
    hot = HotKey(seed, depth, start_period=0)
    assert hot.vk == hk.derive_vk(seed, depth)
    for t in range(1 << depth):
        msg = b"msg-%d" % t
        assert hot.sign(t, msg) == hk.sign(seed, depth, t, msg)


def test_hotkey_forgets_and_expires():
    hot = HotKey(b"\x11" * 32, 2, start_period=5, max_evolutions=3)
    hot.sign(6, b"a")  # evolution 1
    with pytest.raises(KESBeforeStart):
        hot.sign(5, b"b")  # forgotten
    with pytest.raises(KESKeyExpired):
        hot.sign(8, b"c")  # >= start+max_evolutions
    assert kes_status(hot.kes_info(), 4) == "before"
    assert kes_status(hot.kes_info(), 6) == "in_evolution"
    assert kes_status(hot.kes_info(), 8) == "expired"


def _mk_kernel(tmp_path):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    db = open_chaindb(str(tmp_path / "db"), ext, st, PARAMS.security_param)
    return NodeKernel("n0", db, protocol, ledger, pool=POOL)


def test_kernel_forges_across_kes_evolutions(tmp_path):
    """Forging in later KES periods evolves the hot key in place; the
    chain (ocert period 0, evolutions 0 and 1) validates end to end."""
    kernel = _mk_kernel(tmp_path)
    for slot in (1, 3):  # kes periods 0, 1
        blk = kernel.try_forge(slot)
        assert blk is not None, f"slot {slot}"
        assert kernel.chain_db.tip_point().hash_ == blk.hash_
    assert kernel.hotkey.evolution == 1


def test_kernel_rekey_restores_forging(tmp_path):
    """After max_kes_evolutions the key expires (CannotForge, not a
    crash); rekey() issues counter+1 at the current period and forging —
    and validation by the node's own ChainDB — resumes."""
    kernel = _mk_kernel(tmp_path)
    assert kernel.try_forge(1) is not None
    # kes period 2 >= max_evolutions: expired => CannotForge
    assert kernel.forge_only(5) is None
    kernel.rekey(5)
    assert kernel._ocert_counter == 1
    blk = kernel.try_forge(5)
    assert blk is not None
    assert kernel.chain_db.tip_point().hash_ == blk.hash_
    # the re-issued certificate starts at period 2, evolution 0
    assert kernel._ocert.kes_period == 2
    assert kernel.hotkey.evolution == 0
