"""Differential tests: batched GF(2^255-19) limb arithmetic vs python ints."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ouroboros_consensus_tpu.ops import field as fe

P = fe.P_INT
rng = random.Random(1234)

# jit everything heavy once — eager dispatch of the exponentiation chains
# (hundreds of field muls) is orders of magnitude slower than compiled
_addc = jax.jit(lambda a, b: fe.canonical(fe.add(a, b)))
_subc_ = jax.jit(lambda a, b: fe.canonical(fe.sub(a, b)))
_mulc = jax.jit(lambda a, b: fe.canonical(fe.mul(a, b)))
_invc = jax.jit(lambda x: fe.canonical(fe.inv(x)))
_legc = jax.jit(lambda x: fe.canonical(fe.legendre(x)))
_sqrtc = jax.jit(lambda x: (lambda ok_r: (ok_r[0], fe.canonical(ok_r[1])))(fe.sqrt(x)))
_sqrt_ratio_c = jax.jit(
    lambda n, d: (lambda ok_r: (ok_r[0], fe.canonical(ok_r[1])))(fe.sqrt_ratio(n, d))
)


def _rand_ints(n):
    vals = [0, 1, 2, P - 1, P - 2, P, P + 1, 2**255 - 1, 19, 608]
    vals += [rng.randrange(P) for _ in range(n - len(vals))]
    return vals


def _stage(vals):
    return jnp.asarray(np.stack([fe.int_to_limbs_np(v) for v in vals]))


def _unstage(x):
    return [fe.limbs_to_int_np(row) for row in np.asarray(x)]


def test_add_sub_mul_vs_ints():
    a_int = _rand_ints(32)
    b_int = list(reversed(_rand_ints(32)))
    a, b = _stage(a_int), _stage(b_int)
    for got, want in zip(_unstage(_addc(a, b)),
                         [(x + y) % P for x, y in zip(a_int, b_int)]):
        assert got == want
    for got, want in zip(_unstage(_subc_(a, b)),
                         [(x - y) % P for x, y in zip(a_int, b_int)]):
        assert got == want
    for got, want in zip(_unstage(_mulc(a, b)),
                         [(x * y) % P for x, y in zip(a_int, b_int)]):
        assert got == want


def test_limb_bounds_preserved():
    a_int, b_int = _rand_ints(16), list(reversed(_rand_ints(16)))
    a, b = _stage(a_int), _stage(b_int)
    x = a
    for _ in range(4):  # chain ops without canonicalizing
        x = fe.mul(fe.add(x, b), fe.sub(x, a))
        arr = np.asarray(x)
        assert (arr >= 0).all() and (arr <= fe.B_MAX).all()


def test_inv_sqrt_legendre():
    vals = [v for v in _rand_ints(20) if v % P != 0]
    x = _stage(vals)
    inv_got = _unstage(_invc(x))
    for got, v in zip(inv_got, vals):
        assert got == pow(v, P - 2, P)
    leg = _unstage(_legc(x))
    for got, v in zip(leg, vals):
        assert got == pow(v, (P - 1) // 2, P)
    ok, r = _sqrtc(x)
    ok = np.asarray(ok)
    roots = _unstage(r)
    for o, root, v in zip(ok, roots, vals):
        v %= P
        issq = pow(v, (P - 1) // 2, P) == 1
        assert bool(o) == issq
        if issq:
            assert (root * root) % P == v
            assert root % 2 == 0  # even-parity convention


def test_sqrt_ratio():
    ns = _rand_ints(12)
    ds = [v if v % P else 3 for v in reversed(_rand_ints(12))]
    n, d = _stage(ns), _stage(ds)
    ok, r = _sqrt_ratio_c(n, d)
    for o, root, nv, dv in zip(np.asarray(ok), _unstage(r), ns, ds):
        ratio = nv * pow(dv, P - 2, P) % P
        issq = ratio == 0 or pow(ratio, (P - 1) // 2, P) == 1
        assert bool(o) == issq
        if issq:
            assert (root * root) % P == ratio


def test_bytes_roundtrip():
    vals = _rand_ints(16)
    vals = [v % P for v in vals]
    x = _stage(vals)
    b = fe.to_bytes(x)
    assert np.asarray(b).shape[-1] == 32
    back = fe.from_bytes(b)
    for got, want in zip(_unstage(fe.canonical(back)), vals):
        assert got == want
    for row, v in zip(np.asarray(b), vals):
        assert bytes(row.astype(np.uint8)) == v.to_bytes(32, "little")


def test_eq_iszero_parity_select():
    vals = [5, P - 5, 0, P, 12345]
    x = _stage(vals)
    y = _stage([5, P - 5, P, 0, 54321])
    got = np.asarray(fe.eq(x, y))
    assert got.tolist() == [True, True, True, True, False]
    assert np.asarray(fe.is_zero(_stage([0, P, 1, 2 * P]))).tolist() == [
        True, True, False, True]
    assert np.asarray(fe.parity(_stage([2, 3, P - 1]))).tolist() == [0, 1, 0]
    sel = fe.select(jnp.asarray([True, False]), _stage([1, 1]), _stage([2, 2]))
    assert _unstage(sel) == [1, 2]


def test_mul_large_top_limbs_regression():
    """mul must not drop the carry out of limb 39 (weight 2^520 mod p)."""
    rows = np.full((3, fe.NLIMBS), 0, dtype=np.int32)
    rows[0, :] = 9000  # all limbs near B_MAX
    rows[1, 19] = 8192  # oversized top limb (reachable nearly-normalized)
    rows[1, 0] = 7777
    rows[2, :] = fe.B_MAX
    x = jnp.asarray(rows)
    got = _mulc(x, x)
    for row_in, row_out in zip(rows, np.asarray(got)):
        v = fe.limbs_to_int_np(row_in)
        assert fe.limbs_to_int_np(row_out) == (v * v) % P
