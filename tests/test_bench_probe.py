"""bench.py backend-probe hardening (round-10 satellite): r02-r04 each
died on a single probe timeout. The probe now makes at most TWO
attempts — one under the main probe budget, one backoff'd retry under
its own small budget — and banks a structured verdict distinguishing
probe-timeout (backend init hung) from probe-error (backend answered
wrongly), which perf_report classifies without tail archaeology."""

import subprocess

import pytest

import bench


class _Done:
    returncode = 0
    stdout = "128\n"
    stderr = ""


class _Wrong:
    returncode = 1
    stdout = ""
    stderr = "RuntimeError: device says no\n"


@pytest.fixture
def fast_clock(monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


def test_probe_ok_first_attempt(monkeypatch, fast_clock):
    calls = []
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda cmd, **kw: calls.append(kw) or _Done())
    ok, verdict = bench.probe_device()
    assert ok and len(calls) == 1
    assert verdict["outcome"] == "ok"
    assert verdict["attempts"][0]["outcome"] == "ok"


def test_probe_timeout_retries_exactly_once(monkeypatch, fast_clock):
    calls = []

    def fake_run(cmd, **kw):
        calls.append(kw)
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, verdict = bench.probe_device()
    assert not ok
    assert len(calls) == 2  # one retry, never a loop
    assert verdict["outcome"] == "backend-probe-timeout"
    assert [a["outcome"] for a in verdict["attempts"]] == \
        ["probe-timeout", "probe-timeout"]
    # the retry runs under its own small budget, not the main one
    assert calls[1]["timeout"] <= bench.PROBE_RETRY_BUDGET


def test_probe_recovers_on_retry(monkeypatch, fast_clock):
    seq = [subprocess.TimeoutExpired("x", 1), _Done()]

    def fake_run(cmd, **kw):
        item = seq.pop(0)
        if isinstance(item, BaseException):
            raise item
        return item

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, verdict = bench.probe_device()
    assert ok and verdict["outcome"] == "ok"
    assert [a["outcome"] for a in verdict["attempts"]] == \
        ["probe-timeout", "ok"]


def test_probe_error_classified_distinctly(monkeypatch, fast_clock):
    monkeypatch.setattr(bench.subprocess, "run", lambda cmd, **kw: _Wrong())
    ok, verdict = bench.probe_device()
    assert not ok
    assert verdict["outcome"] == "backend-probe-error"
    assert all(a["outcome"] == "probe-error" for a in verdict["attempts"])
    assert "device says no" in verdict["attempts"][0]["detail"]


def test_probe_no_budget(monkeypatch):
    monkeypatch.setattr(bench, "_remaining", lambda: 100.0)
    ok, verdict = bench.probe_device()
    assert not ok and verdict["outcome"] == "no-budget"
    assert verdict["attempts"] == []
