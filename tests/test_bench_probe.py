"""bench.py backend-probe hardening (round-10 satellite; round-12
backoff): r02-r04 each died on a single probe timeout. The probe makes
one attempt under the main probe budget, then retries with JITTERED
EXPONENTIAL backoff under the shared BENCH_PROBE_RETRY_BUDGET (bounded
by PROBE_MAX_ATTEMPTS), and banks a structured verdict distinguishing
probe-timeout (backend init hung) from probe-error (backend answered
wrongly) — with every attempt's preceding wait recorded, so perf_report
can tell "backed off and recovered" from "retried instantly and
died"."""

import subprocess

import pytest

import bench
from ouroboros_consensus_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _chaos_reset(monkeypatch):
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    chaos.reset()
    yield
    chaos.reset()


class _Done:
    returncode = 0
    stdout = "128\n"
    stderr = ""


class _Wrong:
    returncode = 1
    stdout = ""
    stderr = "RuntimeError: device says no\n"


@pytest.fixture
def fast_clock(monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


def test_probe_ok_first_attempt(monkeypatch, fast_clock):
    calls = []
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda cmd, **kw: calls.append(kw) or _Done())
    ok, verdict = bench.probe_device()
    assert ok and len(calls) == 1
    assert verdict["outcome"] == "ok"
    assert verdict["attempts"][0]["outcome"] == "ok"


def test_probe_timeout_backs_off_exponentially(monkeypatch):
    calls = []
    waits = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: waits.append(s))
    # a roomy retry budget so the FULL ladder runs (the default 75 s
    # budget stops the ladder once a backoff would eat the attempt's
    # own probe window — covered separately below)
    monkeypatch.setattr(bench, "PROBE_RETRY_BUDGET", 10_000.0)

    def fake_run(cmd, **kw):
        calls.append(kw)
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, verdict = bench.probe_device()
    assert not ok
    assert len(calls) == bench.PROBE_MAX_ATTEMPTS  # bounded, never a loop
    assert verdict["outcome"] == "backend-probe-timeout"
    assert all(a["outcome"] == "probe-timeout"
               for a in verdict["attempts"])
    # jittered exponential ladder: each wait in [base*2^k, 1.5*base*2^k]
    assert len(waits) == bench.PROBE_MAX_ATTEMPTS - 1
    for k, w in enumerate(waits):
        base = bench.PROBE_RETRY_BACKOFF_S * (2 ** k)
        assert base - 1e-6 <= w <= 1.5 * base + 1e-6
    assert waits == sorted(waits)  # strictly growing ladder
    # the structured verdict records every attempt's preceding wait:
    # "backed off and died" is distinguishable from "retried instantly"
    assert verdict["attempts"][0]["backoff_s"] == 0.0
    assert all(a["backoff_s"] > 0 for a in verdict["attempts"][1:])


def test_probe_backoff_never_burns_wall_it_cannot_use(monkeypatch):
    """A backoff that would eat the attempt's own probe window stops
    the ladder BEFORE sleeping: the retry budget bounds total wall, and
    no terminal sleep is spent on an attempt that can never run."""
    calls = []
    waits = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: waits.append(s))
    # budget fits attempt 2's ~15-22.5 s backoff but not attempt 3's
    monkeypatch.setattr(bench, "PROBE_RETRY_BUDGET", 30.0)

    def fake_run(cmd, **kw):
        calls.append(kw)
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, verdict = bench.probe_device()
    assert not ok
    assert len(calls) == 2  # attempt 1 + the one retry the budget fits
    assert len(waits) == 1  # and NO sleep for the attempt that never ran
    assert len(verdict["attempts"]) == len(calls)
    # retries run under the retry budget's timeout, not the main one
    assert calls[1]["timeout"] <= 30.0


def test_probe_chaos_timeout_then_recovery(monkeypatch):
    """OCT_CHAOS=probe-timeout: the injected r02 death shape eats one
    attempt; the backoff'd retry recovers — and the banked verdict
    shows exactly that trajectory (wait recorded on the recovery)."""
    monkeypatch.setenv("OCT_CHAOS", "probe-timeout")
    chaos.reset()
    waits = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: waits.append(s))
    monkeypatch.setattr(bench.subprocess, "run", lambda cmd, **kw: _Done())
    ok, verdict = bench.probe_device()
    assert ok and verdict["outcome"] == "ok"
    assert [a["outcome"] for a in verdict["attempts"]] == \
        ["probe-timeout", "ok"]
    assert verdict["attempts"][0]["backoff_s"] == 0.0
    assert verdict["attempts"][1]["backoff_s"] > 0  # backed off, recovered


def test_probe_recovers_on_retry(monkeypatch, fast_clock):
    seq = [subprocess.TimeoutExpired("x", 1), _Done()]

    def fake_run(cmd, **kw):
        item = seq.pop(0)
        if isinstance(item, BaseException):
            raise item
        return item

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, verdict = bench.probe_device()
    assert ok and verdict["outcome"] == "ok"
    assert [a["outcome"] for a in verdict["attempts"]] == \
        ["probe-timeout", "ok"]


def test_probe_error_classified_distinctly(monkeypatch, fast_clock):
    monkeypatch.setattr(bench.subprocess, "run", lambda cmd, **kw: _Wrong())
    ok, verdict = bench.probe_device()
    assert not ok
    assert verdict["outcome"] == "backend-probe-error"
    assert all(a["outcome"] == "probe-error" for a in verdict["attempts"])
    assert "device says no" in verdict["attempts"][0]["detail"]


def test_probe_no_budget(monkeypatch):
    monkeypatch.setattr(bench, "_remaining", lambda: 100.0)
    ok, verdict = bench.probe_device()
    assert not ok and verdict["outcome"] == "no-budget"
    assert verdict["attempts"] == []
