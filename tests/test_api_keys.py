"""Cardano.Api shim: key roles, TextEnvelope round-trips, OpCert cycle.

Reference: `src/tools/Cardano/Api/{KeysShelley,KeysPraos,
OperationalCertificate}.hs`.
"""

import pytest

from ouroboros_consensus_tpu.ops.host import fast
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import api

SEED_A = bytes(range(32))
SEED_B = bytes(range(1, 33))


def test_role_registry_derivations():
    for name in ["payment", "stake", "stake_pool", "genesis_delegate"]:
        sk = api.generate_signing_key(name, SEED_A)
        vk = sk.verification_key()
        assert vk.vk == fast.ed25519_public(SEED_A)
        assert len(vk.key_hash()) == 28  # Blake2b-224 KeyHash
    vrf = api.generate_signing_key("vrf", SEED_A).verification_key()
    assert len(vrf.key_hash()) == 32  # hashVerKeyVRF is Blake2b-256
    kes = api.generate_signing_key("kes", SEED_A, kes_depth=2)
    assert len(kes.verification_key().vk) == 32


def test_signing_key_envelope_roundtrip(tmp_path):
    for name in ["payment", "stake_pool", "vrf"]:
        sk = api.generate_signing_key(name, SEED_A)
        p = api.write_signing_key(str(tmp_path / f"{name}.skey"), sk)
        back = api.read_signing_key(p, name)
        assert back.seed == SEED_A and back.role.name == name
    kes = api.generate_signing_key("kes", SEED_B, kes_depth=3)
    p = api.write_signing_key(str(tmp_path / "kes.skey"), kes)
    back = api.read_signing_key(p, "kes")
    assert back.seed == SEED_B and back.kes_depth == 3
    # verification keys too
    vk = kes.verification_key()
    p = api.write_verification_key(str(tmp_path / "kes.vkey"), vk)
    assert api.read_verification_key(p, "kes").vk == vk.vk


def test_envelope_type_checked(tmp_path):
    sk = api.generate_signing_key("payment", SEED_A)
    p = api.write_signing_key(str(tmp_path / "k.skey"), sk)
    with pytest.raises(ValueError, match="envelope type"):
        api.read_signing_key(p, "stake_pool")


def test_opcert_issue_verify_counter_cycle(tmp_path):
    cold = api.generate_signing_key("stake_pool", SEED_A)
    kes = api.generate_signing_key("kes", SEED_B, kes_depth=2)
    counter = api.OpCertIssueCounter(5, cold.verification_key().vk)
    ocert, counter2 = api.issue_operational_certificate(
        cold, counter, kes.verification_key().vk, kes_period=7
    )
    assert ocert.counter == 5 and ocert.kes_period == 7
    assert counter2.next_counter == 6
    assert api.verify_operational_certificate(
        ocert, cold.verification_key().vk
    )
    # wrong cold key fails verification
    other = api.generate_signing_key("stake_pool", SEED_B)
    assert not api.verify_operational_certificate(
        ocert, other.verification_key().vk
    )
    # counter file for a different cold key is a hard error
    with pytest.raises(api.OperationalCertIssueError):
        api.issue_operational_certificate(
            other, counter, kes.verification_key().vk, kes_period=7
        )
    # envelope round-trips
    p = api.write_ocert(str(tmp_path / "node.opcert"), ocert)
    assert api.read_ocert(p) == ocert
    p = api.write_counter(str(tmp_path / "cold.counter"), counter2)
    assert api.read_counter(p) == counter2


def test_opcert_matches_fixture_issuance():
    """api-issued opcerts are byte-compatible with the ThreadNet
    fixtures' make_ocert (same signable, same cold signature)."""
    pool = fixtures.make_pool(0, kes_depth=2)
    fixture_oc = pool.make_ocert(counter=3, kes_period=11)
    cold = api.generate_signing_key("stake_pool", pool.cold_seed)
    counter = api.OpCertIssueCounter(3, pool.vk_cold)
    oc, _ = api.issue_operational_certificate(
        cold, counter, pool.kes_vk, kes_period=11
    )
    assert oc == fixture_oc


def test_node_key_bundle_cycle(tmp_path):
    seeds = {"cold": SEED_A, "vrf": SEED_B, "kes": bytes(32)}
    paths = api.generate_node_keys(str(tmp_path), seeds, kes_depth=2)
    assert set(paths) >= {"opcert", "counter", "cold.skey", "kes.vkey"}
    cold, vrf, kes, ocert, counter = api.load_node_keys(str(tmp_path))
    assert cold.seed == SEED_A and kes.kes_depth == 2
    assert counter.next_counter == 1  # bumped past the issued cert
    assert ocert.counter == 0
    # a forged node using these credentials signs headers the protocol
    # accepts: the opcert's KES vk is the derived root
    assert ocert.vk_hot == kes.verification_key().vk
