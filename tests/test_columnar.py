"""Columnar-vs-per-object differential suite (the round-8 host
pipeline).

The tentpole invariant: a `ViewColumns` window flowing the columnar
path — vectorized host_prechecks, columnar packed/generic staging, the
columnar all-clean epilogue, the native leader bracket — must be
BYTE-IDENTICAL to the same window flowing as a `Sequence[HeaderView]`:
identical verdicts, identical EXACT reference-error objects, identical
first-failure truncation, identical final PraosState. Corruption,
mixed 80/128-byte proof segments and generic-fallback windows are all
exercised; random chains ride hypothesis when installed, a seeded
sweep otherwise (the repo's test_absint precedent).

Crypto runs through the NATIVE backend (C++, fast on CPU) for the
differential folds and through the hash-only stub for the pipelined
device loop — the real-crypto device end-to-end lives in the slow tier
(test_tools.test_device_revalidation_matches_host).
"""

import os
import random
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.ops import sha512
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.views import ViewColumns
from ouroboros_consensus_tpu.testing import fixtures

pytestmark = pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu"),
    reason="CPU differential suite",
)


def make_params(kes_depth=3, epoch_length=100_000):
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=epoch_length,
        kes_depth=kes_depth,
    )


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


def real_chain(params, pools, n, first_slot=100, first_block=30,
               epoch_nonce=b"\x07" * 32, counter=0):
    hvs, prev = [], b"\xaa" * 32
    for i in range(n):
        blk = forge_block(
            params, pools[i % len(pools)], slot=first_slot + i,
            block_no=first_block + i, prev_hash=prev,
            epoch_nonce=epoch_nonce, txs=(b"tx-%d" % i,),
            ocert_counter=counter,
        )
        hvs.append(blk.header.to_view())
        prev = blk.header.hash_
    return hvs


def leader_chain(params, pools, lview, n, first_slot=100,
                 epoch_nonce=b"\x07" * 32):
    """Real-codec chain where every forged slot PASSES the leader check
    (clean end-to-end validation). Slots stay in one CBOR width class
    so the bodies stay rectangular."""
    hvs, prev = [], b"\xaa" * 32
    slot, blkno = first_slot, 30
    while len(hvs) < n:
        pool = fixtures.find_leader(params, pools, lview, slot, epoch_nonce)
        if pool is None:
            slot += 1
            continue
        blk = forge_block(
            params, pool, slot=slot, block_no=blkno, prev_hash=prev,
            epoch_nonce=epoch_nonce, txs=(b"tx-%03d" % len(hvs),),
            ocert_counter=0,
        )
        hvs.append(blk.header.to_view())
        prev = blk.header.hash_
        slot += 1
        blkno += 1
    return hvs


def columns_of(hvs) -> ViewColumns:
    vc = ViewColumns.from_views(hvs)
    assert vc is not None
    return vc


# ---------------------------------------------------------------------------
# representation round-trips
# ---------------------------------------------------------------------------


def test_viewcolumns_views_roundtrip(pools, lview):
    """from_views -> views() is the identity, per field — including a
    genesis lane (prev_hash None) and both proof formats."""
    params = make_params()
    hvs = real_chain(params, pools, 7)
    blk0 = forge_block(params, pools[0], slot=99, block_no=29,
                       prev_hash=None, epoch_nonce=b"\x07" * 32,
                       txs=(b"tx-x",))
    hvs = [blk0.header.to_view()] + hvs
    vc = ViewColumns.from_views(hvs)
    if vc is None:
        # genesis body width differs: drop it and round-trip the rest
        hvs = hvs[1:]
        vc = columns_of(hvs)
    assert len(vc) == len(hvs)
    assert vc.views() == hvs
    # single-lane lazy view + int indexing agree
    assert vc[3] == hvs[3]
    # slicing composes
    assert vc[2:5].views() == hvs[2:5]


def test_dedup_rows_matches_np_unique():
    rng = np.random.default_rng(11)
    for n, w, k in ((1, 64, 1), (50, 64, 3), (257, 288, 5), (64, 7, 2)):
        base = rng.integers(0, 256, (k, w), np.uint8)
        rows = base[rng.integers(0, k, n)]
        uniq, inv = pbatch._dedup_rows(rows)
        ref_u, ref_inv = np.unique(rows, axis=0, return_inverse=True)
        assert uniq.shape == ref_u.shape
        # same unique SET (ordering may differ) and exact reconstruction
        assert {r.tobytes() for r in uniq} == {r.tobytes() for r in ref_u}
        assert np.array_equal(uniq[inv], rows)


def test_pad_matrix_np_equals_pad_messages():
    rng = np.random.default_rng(3)
    for n, ln in ((1, 1), (5, 111), (9, 112), (4, 240), (3, 300)):
        mat = rng.integers(0, 256, (n, ln), np.uint8)
        msgs = [mat[i].tobytes() for i in range(n)]
        hb_a, nb_a = sha512.pad_matrix_np(mat)
        hb_b, nb_b = sha512.pad_messages_np(msgs)
        assert np.array_equal(hb_a, hb_b) and np.array_equal(nb_a, nb_b)


# ---------------------------------------------------------------------------
# prechecks + staging equivalence
# ---------------------------------------------------------------------------


def test_prechecks_columnar_equals_perview(pools, lview):
    """Same evolution column and the SAME error objects per lane —
    including KES-window violations, an unknown pool and a wrong VRF
    key registration."""
    params = make_params()
    hvs = real_chain(params, pools, 8)
    # KES window violations: c0 > kp (before start), kp >= c0+max (after)
    hvs[2] = replace(hvs[2], ocert=replace(hvs[2].ocert, kes_period=7))
    hvs[5] = replace(hvs[5], slot=hvs[5].slot + 100 * 80)
    # unknown pool: a cold key outside the distribution
    hvs[3] = replace(hvs[3], vk_cold=b"\x99" * 32)
    # wrong VRF key for a registered pool
    hvs[6] = replace(hvs[6], vrf_vk=b"\x77" * 32)
    vc = columns_of(hvs)
    a = pbatch.host_prechecks(params, lview, hvs)
    b = pbatch.host_prechecks(params, lview, vc)
    assert isinstance(b, pbatch.ColumnChecks)
    assert a.kes_window_errors == b.kes_window_errors
    assert a.vrf_lookup_errors == b.vrf_lookup_errors
    assert np.array_equal(a.kes_evolution, b.kes_evolution)
    assert not b.clean and b.any_errors()


@pytest.mark.parametrize("bc", [True, False])
def test_stage_columns_equals_stage(pools, lview, monkeypatch, bc):
    """The generic columnar staging is byte-identical to `stage` over
    the materialized views, for both proof formats."""
    monkeypatch.setenv("OCT_VRF_BATCH", "1" if bc else "0")
    params = make_params()
    hvs = real_chain(params, pools, 9)
    assert len(hvs[0].vrf_proof) == (128 if bc else 80)
    vc = columns_of(hvs)
    nonce = b"\x07" * 32
    pre = pbatch.host_prechecks(params, lview, vc)
    ref = pbatch.stage(params, lview, nonce, hvs, pre.kes_evolution)
    got = pbatch.stage_columns(params, lview, nonce, vc, pre.kes_evolution, pre)
    for name, a, b in zip(
        ["ed", "kes", "vrf"], (ref.ed, ref.kes, ref.vrf),
        (got.ed, got.kes, got.vrf),
    ):
        assert type(a) is type(b), name
        for f, x, y in zip(type(a)._fields, a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y)), (name, f)
    assert np.array_equal(ref.beta, got.beta)
    assert np.array_equal(ref.thr_lo, got.thr_lo)
    assert np.array_equal(ref.thr_hi, got.thr_hi)


def test_stage_packed_columns_equals_stage_packed(pools, lview):
    """Same layout; per-lane equality of every packed column (the dedup
    tables may be PERMUTED — the gather indices compensate, so compare
    the gathered per-lane rows)."""
    params = make_params()
    nonce = b"\x07" * 32
    hvs = real_chain(params, pools, 11)
    vc = columns_of(hvs)
    pre = pbatch.host_prechecks(params, lview, vc)
    ref = pbatch.stage_packed(params, lview, nonce, hvs)
    got = pbatch.stage_packed_columns(params, lview, nonce, vc, pre)
    assert ref is not None and got is not None
    (rl, rp), (gl, gp) = ref, got
    assert rl == gl
    assert np.array_equal(rp.body, gp.body)
    assert np.array_equal(rp.kes_rs, gp.kes_rs)
    assert np.array_equal(
        rp.kes_tail_tab[rp.kes_tail_idx], gp.kes_tail_tab[gp.kes_tail_idx]
    )
    assert np.array_equal(
        rp.thr_tab[rp.thr_idx], gp.thr_tab[gp.thr_idx]
    )
    for f in ("slot", "counter", "c0", "within", "nonce"):
        assert np.array_equal(getattr(rp, f), getattr(gp, f)), f


def test_stage_packed_columns_fallback_gates(pools, lview):
    """Non-qualifying columnar windows fall back exactly like the
    per-view stager: synthetic bodies that do not embed the fields, and
    out-of-int32-range integers."""
    params = make_params()
    nonce = b"\x07" * 32
    fv = [
        fixtures.forge_header_view(params, pools[0], slot=s,
                                   epoch_nonce=nonce, prev_hash=b"x" * 32,
                                   body_bytes=b"body-%03d" % s)
        for s in range(1, 5)
    ]
    vc = columns_of(fv)
    pre = pbatch.host_prechecks(params, lview, vc)
    assert pbatch.stage_packed_columns(params, lview, nonce, vc, pre) is None
    hvs = real_chain(params, pools, 4)
    big = columns_of([replace(hvs[0], slot=2**31)] + hvs[1:])
    pre = pbatch.host_prechecks(params, lview, big)
    assert pbatch.stage_packed_columns(params, lview, nonce, big, pre) is None


# ---------------------------------------------------------------------------
# validate_batch differential (native backend, real C crypto)
# ---------------------------------------------------------------------------


def _corrupt(hvs, i, kind):
    hv = hvs[i]
    if kind == "ocert_sig":
        sig = hv.ocert.sigma
        return replace(hv, ocert=replace(
            hv.ocert, sigma=sig[:1] + bytes([sig[1] ^ 1]) + sig[2:]
        ))
    if kind == "kes_sig":
        ks = hv.kes_sig
        return replace(hv, kes_sig=ks[:1] + bytes([ks[1] ^ 1]) + ks[2:])
    if kind == "vrf_proof":
        pf = hv.vrf_proof
        return replace(hv, vrf_proof=pf[:-1] + bytes([pf[-1] ^ 1]))
    if kind == "counter_jump":
        return replace(hv, ocert=replace(
            hv.ocert, counter=hv.ocert.counter + 5
        ))
    if kind == "kes_window":
        return replace(hv, ocert=replace(hv.ocert, kes_period=900))
    raise AssertionError(kind)


def _assert_same_result(a: pbatch.BatchResult, b: pbatch.BatchResult):
    assert a.n_valid == b.n_valid
    assert type(a.error) is type(b.error)
    assert a.error == b.error
    assert a.state == b.state


def _ticked(params, lview, hvs):
    st = praos.PraosState(epoch_nonce=b"\x07" * 32)
    slot = hvs[0].slot if not isinstance(hvs, ViewColumns) else int(hvs.slot[0])
    return praos.tick(params, lview, slot, st)


def test_validate_batch_native_columnar_clean(pools, lview):
    params = make_params()
    hvs = leader_chain(params, pools, lview, 12)
    t = _ticked(params, lview, hvs)
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    b = pbatch.validate_batch(params, t, columns_of(hvs), backend="native")
    assert a.error is None and a.n_valid == 12
    _assert_same_result(a, b)


@pytest.mark.parametrize(
    "kind,where",
    [
        ("ocert_sig", 0), ("kes_sig", 5), ("vrf_proof", 11),
        ("counter_jump", 3), ("kes_window", 7),
    ],
)
def test_validate_batch_native_columnar_corrupted(pools, lview, kind, where):
    """Corrupted lanes — first lane, interior, last lane; every error
    family — truncate at the SAME position with the SAME exact error
    object through both representations."""
    params = make_params()
    hvs = leader_chain(params, pools, lview, 12)
    hvs[where] = _corrupt(hvs, where, kind)
    t = _ticked(params, lview, hvs)
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    b = pbatch.validate_batch(params, t, columns_of(hvs), backend="native")
    assert a.n_valid == where and a.error is not None
    _assert_same_result(a, b)


def test_validate_batch_mixed_proof_formats(pools, lview, monkeypatch):
    """Mixed 80/128-byte proof chains segment at format boundaries in
    BOTH representations and agree lane-for-lane, clean and tampered."""
    params = make_params()
    eta = b"\x07" * 32
    hvs, prev, slot = [], None, 1
    while len(hvs) < 8:
        pool = fixtures.find_leader(params, pools, lview, slot, eta)
        if pool is not None:
            monkeypatch.setenv("OCT_VRF_BATCH", "0" if len(hvs) % 2 else "1")
            hv = fixtures.forge_header_view(
                params, pool, slot=slot, epoch_nonce=eta,
                prev_hash=prev, body_bytes=b"body-%d" % len(hvs),
            )
            hvs.append(hv)
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    monkeypatch.delenv("OCT_VRF_BATCH", raising=False)
    assert {len(hv.vrf_proof) for hv in hvs} == {80, 128}
    t = _ticked(params, lview, hvs)
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    vc = columns_of(hvs)
    assert not pbatch._proof_len_uniform(vc)
    b = pbatch.validate_batch(params, t, vc, backend="native")
    assert a.error is None and a.n_valid == 8
    _assert_same_result(a, b)
    # tampered mixed-format lane: same truncation, same exact error
    bad = hvs[5]
    hvs[5] = replace(bad, vrf_proof=bad.vrf_proof[:-1]
                     + bytes([bad.vrf_proof[-1] ^ 1]))
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    b = pbatch.validate_batch(params, t, columns_of(hvs), backend="native")
    assert a.n_valid == 5 and isinstance(a.error, praos.VRFKeyBadProof)
    _assert_same_result(a, b)


def test_validate_batch_generic_fallback_window(pools, lview):
    """Synthetic views whose bodies do not embed the fields cannot
    stage packed; the columnar window still flows (columnar generic
    staging) and agrees with the per-view fold."""
    params = make_params()
    eta = b"\x07" * 32
    hvs, prev, slot = [], None, 1
    while len(hvs) < 6:
        pool = fixtures.find_leader(params, pools, lview, slot, eta)
        if pool is not None:
            hv = fixtures.forge_header_view(
                params, pool, slot=slot, epoch_nonce=eta,
                prev_hash=prev, body_bytes=b"body-%d" % len(hvs),
            )
            hvs.append(hv)
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    t = _ticked(params, lview, hvs)
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    b = pbatch.validate_batch(params, t, columns_of(hvs), backend="native")
    assert a.error is None and a.n_valid == 6
    _assert_same_result(a, b)


# ---------------------------------------------------------------------------
# randomized chains: hypothesis when installed, seeded sweep otherwise
# ---------------------------------------------------------------------------

_KINDS = ("ocert_sig", "kes_sig", "vrf_proof", "counter_jump", "kes_window")


def _random_trial(params, pools, lview, seed: int):
    rng = random.Random(seed)
    n = rng.randint(2, 14)
    hvs = real_chain(params, pools, n, first_slot=100 + rng.randint(0, 50))
    n_bad = rng.randint(0, 2)
    for _ in range(n_bad):
        i = rng.randrange(n)
        hvs[i] = _corrupt(hvs, i, rng.choice(_KINDS))
    t = _ticked(params, lview, hvs)
    a = pbatch.validate_batch(params, t, hvs, backend="native")
    b = pbatch.validate_batch(params, t, columns_of(hvs), backend="native")
    _assert_same_result(a, b)


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=12, deadline=None)
    @given(seed=st.integers(min_value=0, max_value=2**32 - 1))
    def test_columnar_differential_property(pools, lview, seed):
        _random_trial(make_params(), pools, lview, seed)

except ImportError:  # seeded fallback: same property, fixed sweep

    @pytest.mark.parametrize("seed", range(12))
    def test_columnar_differential_property(pools, lview, seed):
        _random_trial(make_params(), pools, lview, seed)


# ---------------------------------------------------------------------------
# the pipelined device loop with ViewColumns (crypto stubbed)
# ---------------------------------------------------------------------------


def test_validate_chain_columnar_pipeline_equals_fold(pools, lview,
                                                      monkeypatch):
    """The full pipelined device path fed a ViewColumns chain — packed
    columnar staging, device unpack, bitmask verdicts, the chained
    nonce scan across windows AND epoch boundaries — agrees with the
    sequential reupdate fold and with the same chain fed as a list.
    Crypto is the hash-only stub (test_packed_batch idiom); the columnar
    epilogue fast path is what's under test."""
    import jax

    from tests.test_packed_batch import _stub_verify

    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    try:
        params = make_params(epoch_length=60)
        st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
        st = st0
        hvs, prev = [], b"\xaa" * 32
        slot, blkno = 18, 40  # crosses the CBOR 1->2-byte slot boundary
        while len(hvs) < 60:
            ticked = praos.tick(params, lview, slot, st)
            blk = forge_block(
                params, pools[len(hvs) % 2], slot=slot, block_no=blkno,
                prev_hash=prev, epoch_nonce=ticked.state.epoch_nonce,
                txs=(b"t",),
            )
            hv = blk.header.to_view()
            st = praos.reupdate(params, hv, slot, ticked)
            hvs.append(hv)
            prev = blk.header.hash_
            slot += 1
            blkno += 1
        assert params.epoch_of(hvs[-1].slot) >= 1

        # the forged bodies change width at the CBOR boundary: feed the
        # chain as width-uniform columnar runs, state threading through
        widths = {}
        runs: list = []
        for hv in hvs:
            w = len(hv.signed_bytes)
            if runs and runs[-1][0] == w:
                runs[-1][1].append(hv)
            else:
                runs.append((w, [hv]))
            widths[w] = widths.get(w, 0) + 1
        res_list = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8,
        )
        assert res_list.error is None and res_list.n_valid == 60
        assert res_list.state == st

        state = st0
        total = 0
        for _w, run in runs:
            vc = columns_of(run)
            res = pbatch.validate_chain(
                params, lambda _e: lview, state, vc, max_batch=8,
            )
            assert res.error is None
            total += res.n_valid
            state = res.state
        assert total == 60
        assert state == st
    finally:
        for k in set(pbatch._JIT) - before:
            del pbatch._JIT[k]


def test_revalidate_columnar_equals_perview_on_disk(tmp_path, monkeypatch):
    """End-to-end on-disk differential: synthesize a chain, revalidate
    with the native backend through the columnar window stream and the
    per-object stream (OCT_COLUMNAR=0) — identical verdicts and final
    state; then corrupt a block on disk and check identical truncation."""
    from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

    params = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
        active_slot_coeff=Fraction(1, 2), epoch_length=50, kes_depth=3,
    )
    pools = [fixtures.make_pool(40 + i, kes_depth=3) for i in range(2)]
    lv = fixtures.make_ledger_view(pools)
    path = str(tmp_path / "db")
    res = db_synthesizer.synthesize(
        path, params, pools, lv, db_synthesizer.ForgeLimit(slots=120),
        chunk_size=32,
    )
    assert res.n_blocks > 30

    def run():
        return db_analyser.revalidate(
            path, params, lv, backend="native", validate_all="stream",
        )

    monkeypatch.delenv("OCT_COLUMNAR", raising=False)
    a = run()
    monkeypatch.setenv("OCT_COLUMNAR", "0")
    b = run()
    assert a.error is None and a.n_valid == res.n_blocks
    assert b.n_valid == a.n_valid and b.n_blocks == a.n_blocks
    assert a.final_state == b.final_state

    # corrupt one byte of a mid-chain block body on disk
    import glob

    chunk = sorted(glob.glob(os.path.join(path, "immutable", "*.chunk")))[1]
    with open(chunk, "r+b") as f:
        f.seek(40)
        c = f.read(1)
        f.seek(40)
        f.write(bytes([c[0] ^ 0xFF]))
    monkeypatch.delenv("OCT_COLUMNAR", raising=False)
    a = run()
    monkeypatch.setenv("OCT_COLUMNAR", "0")
    b = run()
    assert a.n_valid == b.n_valid and a.n_blocks == b.n_blocks
    assert repr(a.error) == repr(b.error)
    assert a.final_state == b.final_state
    assert a.n_valid < res.n_blocks  # the corruption truncated the chain
