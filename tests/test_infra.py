"""Tests OF the test infrastructure (the reference's infra-test tier,
test/infra-test/Main.hs): the mock FS's crash semantics and the
deterministic sim are themselves load-bearing — a bug here silently
weakens every model/machine test built on top.
"""

import pytest

from ouroboros_consensus_tpu.utils.fs import MockFS
from ouroboros_consensus_tpu.utils.sim import Channel, Recv, Send, Sim, Sleep


def test_mockfs_crash_respects_fsync_watermark():
    fs = MockFS()
    fs.makedirs("d")
    fs.append("d/f", b"durable")
    fs.fsync("d/f")
    fs.append("d/f", b"-torn-tail")
    fs.crash(0.0)
    assert fs.read_bytes("d/f") == b"durable"  # synced prefix survives
    # partial tearing keeps a prefix of the unsynced suffix
    fs.append("d/f", b"0123456789")
    fs.crash(0.5)
    assert fs.read_bytes("d/f") == b"durable01234"


def test_mockfs_atomic_write_is_durable():
    fs = MockFS()
    fs.makedirs("d")
    fs.write_atomic("d/snap", b"payload")
    fs.crash(0.0)
    assert fs.read_bytes("d/snap") == b"payload"


def test_mockfs_unsynced_creation_vanishes_on_crash():
    fs = MockFS()
    fs.makedirs("d")
    fs.append("d/ephemeral", b"x")
    fs.crash(0.0)
    assert not fs.exists("d/ephemeral")


def test_mockfs_wipe_and_listing():
    fs = MockFS()
    fs.makedirs("a/b")
    fs.append("a/b/f1", b"1")
    fs.append("a/g", b"2")
    assert fs.listdir("a") == ["b", "g"]
    fs.wipe("a/b")
    assert fs.listdir("a") == ["g"]


def test_sim_determinism_bit_identical():
    """Two runs of the same program produce the same trace — the io-sim
    property every ThreadNet result rests on."""

    def run():
        sim = Sim()
        trace = []
        ch = Channel(delay=0.3)

        def producer():
            for i in range(5):
                yield Send(ch, i)
                yield Sleep(0.1)

        def consumer():
            while True:
                v = yield Recv(ch)
                trace.append((sim.now, v))

        sim.spawn(producer(), "p")
        sim.spawn(consumer(), "c")
        sim.run(until=10.0)
        return trace

    assert run() == run()


def test_sim_channel_fifo_with_delay():
    sim = Sim()
    got = []
    ch = Channel(delay=1.0)

    def sender():
        yield Send(ch, "a")
        yield Send(ch, "b")

    def receiver():
        got.append((yield Recv(ch)))
        got.append((yield Recv(ch)))

    sim.spawn(sender(), "s")
    sim.spawn(receiver(), "r")
    sim.run(until=5.0)
    assert got == ["a", "b"]
