"""Differential tests: native C++ crypto (native/hostcrypto.cpp) vs the
Python host references and the hashlib oracles — field/point internals,
the three verifiers, and the batch fold driver behind
db_analyser --backend native (the bench.py baseline)."""

import ctypes
import hashlib

import numpy as np
import pytest

from ouroboros_consensus_tpu import native_loader as nl
from ouroboros_consensus_tpu.ops.host import ecvrf as hv
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.host import kes as hk

lib = nl.load_crypto()
pytestmark = pytest.mark.skipif(lib is None, reason="no native toolchain")

rng = np.random.default_rng(17)


def _rand(n):
    return rng.integers(0, 256, n, dtype=np.uint8).tobytes()


def test_hashes_match_hashlib():
    for n in (0, 1, 63, 64, 111, 112, 127, 128, 129, 1000):
        m = _rand(n)
        out = ctypes.create_string_buffer(64)
        lib.oc_sha512(m, n, out)
        assert out.raw == hashlib.sha512(m).digest()
        for dl in (28, 32, 64):
            o2 = ctypes.create_string_buffer(dl)
            lib.oc_blake2b(m, n, o2, dl)
            assert o2.raw == hashlib.blake2b(m, digest_size=dl).digest()


def test_field_ops_match_host():
    P = he.P
    for _ in range(25):
        a = int.from_bytes(_rand(32), "little") % P
        b = int.from_bytes(_rand(32), "little") % P
        if a == 0:
            continue
        mo, co, io, so = (ctypes.create_string_buffer(32) for _ in range(4))
        ok, sq = ctypes.c_int(0), ctypes.c_int(0)
        lib.oc_fe_test(
            a.to_bytes(32, "little"), b.to_bytes(32, "little"),
            mo, co, io, so, ctypes.byref(ok), ctypes.byref(sq),
        )
        assert int.from_bytes(mo.raw, "little") == a * b % P
        # the lazy add/sub/sq chain inside oc_fe_test
        assert int.from_bytes(co.raw, "little") == (((a + b) * (a - b) + a * a) * 2) ** 2 % P
        assert int.from_bytes(io.raw, "little") == pow(a, P - 2, P)
        hs = he.fe_sqrt(a)
        assert bool(ok.value) == (hs is not None)
        if hs is not None:
            assert int.from_bytes(so.raw, "little") == hs
        assert bool(sq.value) == he.is_square(a)


def test_point_ops_match_host():
    for _ in range(10):
        pk = he.secret_to_public(_rand(32))
        s = _rand(32)
        rt, mo, do = (ctypes.create_string_buffer(32) for _ in range(3))
        assert lib.oc_ge_test(pk, s, rt, mo, do) == 1
        assert rt.raw == pk  # decompress/compress roundtrip
        A = he.point_decompress(pk)
        assert mo.raw == he.point_compress(
            he.point_mul(int.from_bytes(s, "little"), A)
        )
        assert do.raw == he.point_compress(he.point_double(A))


def test_double_scalarmult_matches_host():
    for _ in range(8):
        s1, s2 = _rand(32), _rand(32)
        p = he.secret_to_public(_rand(32))
        q = he.secret_to_public(_rand(32))
        out = ctypes.create_string_buffer(32)
        assert lib.oc_dsmul_test(s1, p, s2, q, out) == 1
        P_, Q_ = he.point_decompress(p), he.point_decompress(q)
        want = he.point_add(
            he.point_mul(int.from_bytes(s1, "little"), P_),
            he.point_mul(int.from_bytes(s2, "little"), Q_),
        )
        assert out.raw == he.point_compress(want)


def test_ed25519_verify_differential():
    for i in range(12):
        seed = _rand(32)
        msg = _rand(int(rng.integers(0, 200)))
        pk = he.secret_to_public(seed)
        sig = he.sign(seed, msg)
        assert nl.native_ed25519_verify(pk, sig, msg)
        assert not nl.native_ed25519_verify(pk, bytes([sig[0] ^ 1]) + sig[1:], msg)
        assert not nl.native_ed25519_verify(pk, sig, msg + b"x")
    # non-canonical encodings rejected exactly like the host
    bad_r = (2**255 - 19 + 1).to_bytes(32, "little") + sig[32:]
    assert not nl.native_ed25519_verify(pk, bad_r, msg)
    assert not he.verify(pk, msg, bad_r)
    bad_s = sig[:32] + he.L.to_bytes(32, "little")
    assert not nl.native_ed25519_verify(pk, bad_s, msg)
    assert not he.verify(pk, msg, bad_s)


def test_ecvrf_verify_differential():
    for i in range(8):
        seed, alpha = _rand(32), _rand(32)
        pk = he.secret_to_public(seed)
        pi = hv.prove(seed, alpha)
        assert nl.native_ecvrf_verify(pk, pi, alpha) == hv.proof_to_hash(pi)
        bad = pi[:40] + bytes([pi[40] ^ 1]) + pi[41:]
        assert nl.native_ecvrf_verify(pk, bad, alpha) is None
        assert nl.native_ecvrf_verify(pk, pi, bytes(32)) is None


def test_kes_verify_differential():
    depth = 4
    for i in range(6):
        seed = _rand(32)
        per = int(rng.integers(0, 1 << depth))
        msg = b"kes-%d" % i
        sig = hk.sign(seed, depth, per, msg)
        vk = hk.derive_vk(seed, depth)
        assert nl.native_kes_verify(vk, depth, per, msg, sig)
        assert not nl.native_kes_verify(vk, depth, (per + 1) % (1 << depth), msg, sig)
        assert not nl.native_kes_verify(vk, depth, per, msg + b"!", sig)


def test_native_backend_vs_host_fold(tmp_path):
    """db_analyser --backend native == --backend host on a synthesized
    chain, both clean and with a tampered block."""
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    params = synth.default_params(kes_depth=3)
    pools, lview = synth.make_credentials(2, kes_depth=3)
    path = str(tmp_path / "db")
    res = synth.synthesize(
        path, params, pools, lview, synth.ForgeLimit(slots=80),
        vrf_backend="host",
    )
    assert res.n_blocks > 0
    rn = ana.revalidate(path, params, lview, backend="native")
    rh = ana.revalidate(path, params, lview, backend="host")
    assert rn.error is None and rh.error is None
    assert rn.n_valid == rh.n_valid == res.n_blocks
    assert rn.final_state == rh.final_state
