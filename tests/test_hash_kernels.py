"""Differential tests: device SHA-512 / Blake2b kernels vs hashlib."""

import hashlib
import random

import jax
import numpy as np
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import blake2b as b2
from ouroboros_consensus_tpu.ops import sha512 as sh


def _rand_msgs(seed, sizes):
    rng = random.Random(seed)
    return [bytes(rng.randrange(256) for _ in range(n)) for n in sizes]


def test_sha512_matches_hashlib_varied_lengths():
    # lengths straddle every padding boundary: 0, <112, 112 (block spill),
    # 127, 128, multi-block
    sizes = [0, 1, 3, 55, 111, 112, 113, 119, 120, 127, 128, 129, 200, 255, 256, 300, 500]
    msgs = _rand_msgs(1, sizes)
    blocks, nblocks = sh.pad_messages_np(msgs)
    out = np.asarray(jax.jit(sh.sha512)(jnp.asarray(blocks), jnp.asarray(nblocks)))
    for i, m in enumerate(msgs):
        want = np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
        assert (out[i] == want).all(), f"lane {i} len {len(m)}"


def test_sha512_batch_shape_2d():
    msgs = _rand_msgs(2, [64] * 6)
    blocks, nblocks = sh.pad_messages_np(msgs)
    blocks = blocks.reshape(2, 3, *blocks.shape[1:])
    nblocks = nblocks.reshape(2, 3)
    out = np.asarray(sh.sha512(jnp.asarray(blocks), jnp.asarray(nblocks)))
    for i, m in enumerate(msgs):
        want = np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
        assert (out[i // 3, i % 3] == want).all()


def test_blake2b_matches_hashlib_varied_lengths():
    sizes = [0, 1, 31, 32, 64, 100, 127, 128, 129, 255, 256, 257, 400]
    msgs = _rand_msgs(3, sizes)
    for digest_size in (32, 28, 64):
        blocks, nblocks, total = b2.pad_messages_np(msgs)
        out = np.asarray(
            jax.jit(b2.blake2b_blocks, static_argnums=3)(
                jnp.asarray(blocks), jnp.asarray(nblocks), jnp.asarray(total), digest_size
            )
        )
        for i, m in enumerate(msgs):
            want = np.frombuffer(
                hashlib.blake2b(m, digest_size=digest_size).digest(), dtype=np.uint8
            )
            assert (out[i] == want).all(), f"lane {i} len {len(m)} ds {digest_size}"


def test_blake2b_fixed_single_block():
    # the KES Merkle-node shape: exactly 64 bytes, digest 32
    msgs = _rand_msgs(4, [64] * 5)
    arr = jnp.asarray(np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(5, 64).astype(np.int32))
    out = np.asarray(b2.blake2b_fixed(arr, 64, 32))
    for i, m in enumerate(msgs):
        want = np.frombuffer(hashlib.blake2b(m, digest_size=32).digest(), dtype=np.uint8)
        assert (out[i] == want).all()
    # 65-byte tagged-seed shape (0x01 || seed64) still single block
    msgs65 = _rand_msgs(5, [65] * 3)
    arr65 = jnp.asarray(
        np.frombuffer(b"".join(msgs65), dtype=np.uint8).reshape(3, 65).astype(np.int32)
    )
    out65 = np.asarray(b2.blake2b_fixed(arr65, 65, 32))
    for i, m in enumerate(msgs65):
        want = np.frombuffer(hashlib.blake2b(m, digest_size=32).digest(), dtype=np.uint8)
        assert (out65[i] == want).all()
