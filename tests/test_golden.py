"""Serialisation golden + roundtrip tests.

Reference: §4 tier 5 — CBOR roundtrip and GOLDEN tests with recorded
fixtures (`consensus-testlib/Test/Util/Serialisation/{Roundtrip,Golden}.hs`,
golden outputs committed under `ouroboros-consensus-cardano/golden/`).
Golden bytes pin the ON-DISK format: an accidental codec change breaks
these tests BEFORE it corrupts somebody's ChainDB.

The goldens are generated from deterministic fixtures (seeded keys,
fixed nonce) and committed under tests/golden/. Regenerate ONLY on an
intentional format change:  python tests/test_golden.py --regen
"""

import os
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.block.praos_block import Block
from ouroboros_consensus_tpu.ledger.header_validation import AnnTip, HeaderState
from ouroboros_consensus_tpu.ledger.mock import MockState
from ouroboros_consensus_tpu.ledger.extended import ExtLedgerState
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.storage import serialize
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils import cbor

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),
    epoch_length=500,
    kes_depth=3,
)
POOL = fixtures.make_pool(7, kes_depth=3)
ETA0 = bytes(range(32))


def golden_block() -> Block:
    return forge_block(
        PARAMS, POOL, slot=42, block_no=7,
        prev_hash=b"\x11" * 32, epoch_nonce=ETA0,
        txs=(b"tx-a", b"tx-b"),
    )


def golden_ext_state() -> ExtLedgerState:
    st = praos.PraosState(
        last_slot=42,
        ocert_counters={POOL.pool_id: 3},
        evolving_nonce=b"\x01" * 32,
        candidate_nonce=b"\x02" * 32,
        epoch_nonce=ETA0,
        lab_nonce=b"\x03" * 32,
        last_epoch_block_nonce=b"\x04" * 32,
    )
    hs = HeaderState(AnnTip(42, 7, b"\x05" * 32), st)
    ls = MockState({(bytes(32), 0): (b"alice", 100)}, 42)
    return ExtLedgerState(ls, hs)


CASES = {
    "praos_block.hex": lambda: golden_block().bytes_,
    "ext_ledger_state.hex": lambda: serialize.encode_ext_state(golden_ext_state()),
    "canonical_cbor.hex": lambda: cbor.encode(
        [0, -1, 23, 24, 255, 65536, b"bytes", "text", [1, [2, [3]]], None, True]
    ),
}


def _path(name):
    return os.path.join(GOLDEN_DIR, name)


@pytest.mark.parametrize("name", sorted(CASES))
def test_golden(name):
    """Recorded bytes match EXACTLY (Golden.hs goldenTestCBOR)."""
    produced = CASES[name]()
    with open(_path(name)) as f:
        expected = bytes.fromhex(f.read().strip())
    assert produced == expected, (
        f"{name}: serialisation changed! If intentional, regenerate with "
        f"`python tests/test_golden.py --regen` and note the format break."
    )


def test_block_roundtrip():
    b = golden_block()
    again = Block.from_bytes(b.bytes_)
    assert again.hash_ == b.hash_ and again.txs == b.txs
    assert again.header.to_view().signed_bytes == b.header.to_view().signed_bytes


def test_ext_state_roundtrip():
    ext = golden_ext_state()
    again = serialize.decode_ext_state(serialize.encode_ext_state(ext))
    assert again == ext


if __name__ == "__main__":
    import sys

    if "--regen" in sys.argv:
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for name, gen in CASES.items():
            with open(_path(name), "w") as f:
                f.write(gen().hex() + "\n")
            print(f"wrote {name}")
