"""Serialisation golden + roundtrip tests.

Reference: §4 tier 5 — CBOR roundtrip and GOLDEN tests with recorded
fixtures (`consensus-testlib/Test/Util/Serialisation/{Roundtrip,Golden}.hs`,
golden outputs committed under `ouroboros-consensus-cardano/golden/`).
Golden bytes pin the ON-DISK format: an accidental codec change breaks
these tests BEFORE it corrupts somebody's ChainDB.

The goldens are generated from deterministic fixtures (seeded keys,
fixed nonce) and committed under tests/golden/. Regenerate ONLY on an
intentional format change:  python tests/test_golden.py --regen
"""

import os
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.block.praos_block import Block
from ouroboros_consensus_tpu.ledger.header_validation import AnnTip, HeaderState
from ouroboros_consensus_tpu.ledger.mock import MockState
from ouroboros_consensus_tpu.ledger.extended import ExtLedgerState
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.storage import serialize
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils import cbor

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),
    epoch_length=500,
    kes_depth=3,
)
POOL = fixtures.make_pool(7, kes_depth=3)
ETA0 = bytes(range(32))


def golden_block() -> Block:
    return forge_block(
        PARAMS, POOL, slot=42, block_no=7,
        prev_hash=b"\x11" * 32, epoch_nonce=ETA0,
        txs=(b"tx-a", b"tx-b"),
    )


def golden_ext_state() -> ExtLedgerState:
    st = praos.PraosState(
        last_slot=42,
        ocert_counters={POOL.pool_id: 3},
        evolving_nonce=b"\x01" * 32,
        candidate_nonce=b"\x02" * 32,
        epoch_nonce=ETA0,
        lab_nonce=b"\x03" * 32,
        last_epoch_block_nonce=b"\x04" * 32,
    )
    hs = HeaderState(AnnTip(42, 7, b"\x05" * 32), st)
    ls = MockState({(bytes(32), 0): (b"alice", 100)}, 42)
    return ExtLedgerState(ls, hs)


def golden_byron_payloads() -> bytes:
    """Deterministic Byron tx + dcert payload bytes (the era-0 wire)."""
    from ouroboros_consensus_tpu.ledger import byron as byron_led

    seed = b"\x2a" * 32
    tx = byron_led.make_tx(
        [(bytes(32), 0)],
        [(byron_led.addr_of(b"\x0b" * 32), 90)],
        [seed],
    )
    cert = byron_led.make_dcert(seed, b"\x0c" * 32, epoch=1)
    return cbor.encode([tx, cert])


def golden_mary_tx() -> bytes:
    """Deterministic Mary tx (multi-asset mint + validity interval)."""
    from ouroboros_consensus_tpu.ledger import mary

    outs = [(b"\x0d" * 28, None,
             mary.MaryValue(70, {(b"\x0e" * 28, b"tok"): 5}))]
    wit = mary.make_mint_witness(
        b"\x2b" * 32, [(bytes(32), 1)], outs, 0, (3, 99), {b"tok": 5}
    )
    return mary.encode_tx([(bytes(32), 1)], outs, validity=(3, 99),
                          mint=[wit])


def golden_dual_byron_snapshot() -> bytes:
    """DualByron ledger-state snapshot payload (tagged codec)."""
    from ouroboros_consensus_tpu.ledger import byron as byron_led
    from ouroboros_consensus_tpu.ledger.byron_spec import DualByronLedger
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed

    gen = byron_led.ByronGenesis(
        pparams=byron_led.ByronPParams(min_fee_a=10, min_fee_b=0),
        genesis_keys=(ed.secret_to_public(b"\x2a" * 32),),
    )
    st = DualByronLedger(gen).genesis_state(
        [(byron_led.addr_of(b"\x0b" * 32), 500)]
    )
    return cbor.encode(serialize.encode_ledger_state_tagged(st))


def golden_mary_shelley_snapshot() -> bytes:
    """Shelley snapshot whose value column carries a Mary value + a
    pending MIR allocation (the round-4 codec extensions)."""
    import dataclasses

    from ouroboros_consensus_tpu.ledger import mary
    from ouroboros_consensus_tpu.ledger import shelley as sh

    led = sh.ShelleyLedger(sh.ShelleyGenesis(
        pparams=sh.PParams(), epoch_length=100, stability_window=30,
    ))
    st = led.genesis_state([(b"\x0d" * 28, b"\x0f" * 28, 100)])
    st = dataclasses.replace(
        st,
        utxo={**st.utxo, (b"\x10" * 32, 0): (
            (b"\x0d" * 28, None),
            mary.MaryValue(7, {(b"\x0e" * 28, b"tok"): 5}),
        )},
        pending_mir={(0, b"\x0f" * 28): 55},
    )
    return cbor.encode(serialize.encode_ledger_state_tagged(st))


CASES = {
    "praos_block.hex": lambda: golden_block().bytes_,
    "ext_ledger_state.hex": lambda: serialize.encode_ext_state(golden_ext_state()),
    "canonical_cbor.hex": lambda: cbor.encode(
        [0, -1, 23, 24, 255, 65536, b"bytes", "text", [1, [2, [3]]], None, True]
    ),
    "byron_payloads.hex": golden_byron_payloads,
    "mary_tx.hex": golden_mary_tx,
    "dual_byron_snapshot.hex": golden_dual_byron_snapshot,
    "mary_shelley_snapshot.hex": golden_mary_shelley_snapshot,
}


def _path(name):
    return os.path.join(GOLDEN_DIR, name)


@pytest.mark.parametrize("name", sorted(CASES))
def test_golden(name):
    """Recorded bytes match EXACTLY (Golden.hs goldenTestCBOR)."""
    produced = CASES[name]()
    with open(_path(name)) as f:
        expected = bytes.fromhex(f.read().strip())
    assert produced == expected, (
        f"{name}: serialisation changed! If intentional, regenerate with "
        f"`python tests/test_golden.py --regen` and note the format break."
    )


def test_block_roundtrip():
    b = golden_block()
    again = Block.from_bytes(b.bytes_)
    assert again.hash_ == b.hash_ and again.txs == b.txs
    assert again.header.to_view().signed_bytes == b.header.to_view().signed_bytes


def test_ext_state_roundtrip():
    ext = golden_ext_state()
    again = serialize.decode_ext_state(serialize.encode_ext_state(ext))
    assert again == ext


if __name__ == "__main__":
    import sys

    if "--regen" in sys.argv:
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for name, gen in CASES.items():
            with open(_path(name), "w") as f:
                f.write(gen().hex() + "\n")
            print(f"wrote {name}")


def test_byron_and_mary_snapshot_roundtrip():
    """Round-4 codecs: ByronState, DualByronState, and Mary multi-asset
    values riding the Shelley snapshot's value column (ada-only entries
    keep the golden-stable bare-int encoding)."""
    from ouroboros_consensus_tpu.ledger.byron import (
        ByronGenesis, ByronLedger, ByronPParams,
    )
    from ouroboros_consensus_tpu.ledger.byron_spec import DualByronLedger
    from ouroboros_consensus_tpu.ledger.mary import MaryValue
    from ouroboros_consensus_tpu.ledger.shelley import (
        PParams, ShelleyGenesis, ShelleyLedger,
    )
    from ouroboros_consensus_tpu.hardfork.combinator import HFState
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed
    from ouroboros_consensus_tpu.utils import cbor

    def rt(st):
        wire = cbor.encode(serialize.encode_ledger_state_tagged(st))
        return serialize.decode_ledger_state_tagged(cbor.decode(wire))

    gen = ByronGenesis(
        pparams=ByronPParams(min_fee_a=10, min_fee_b=0),
        genesis_keys=(ed.secret_to_public(b"\x10" * 32),),
    )
    led = ByronLedger(gen)
    b_st = led.genesis_state([(b"\x0a" * 28, 500)])
    again = rt(b_st)
    assert dict(again.utxo) == dict(b_st.utxo)
    assert dict(again.delegation) == dict(b_st.delegation)
    assert again.fees == b_st.fees and again.tip_slot_ == b_st.tip_slot_
    # HF-wrapped too (the composite's snapshot shape)
    hf = rt(HFState(0, b_st))
    assert hf.era == 0 and dict(hf.inner.utxo) == dict(b_st.utxo)

    dual = DualByronLedger(gen)
    d_st = dual.genesis_state([(b"\x0a" * 28, 500)])
    d_again = rt(d_st)
    assert dict(d_again.impl.utxo) == dict(d_st.impl.utxo)
    assert dict(d_again.spec.utxo) == dict(d_st.spec.utxo)
    assert dict(d_again.spec.delegation) == dict(d_st.spec.delegation)

    sh_led = ShelleyLedger(ShelleyGenesis(
        pparams=PParams(), epoch_length=100, stability_window=30,
    ))
    pid = b"\x77" * 28
    s_st = sh_led.genesis_state([(b"\x0b" * 28, None, 100)])
    s_st = __import__("dataclasses").replace(
        s_st,
        utxo={
            **s_st.utxo,
            (b"\xfe" * 32, 0): (
                (b"\x0c" * 28, None),
                MaryValue(7, {(pid, b"tok"): 9}),
            ),
        },
        pending_mir={(0, b"\x33" * 28): 44, (1, b"\x34" * 28): 9},
    )
    m_again = rt(s_st)
    assert dict(m_again.pending_mir) == dict(s_st.pending_mir)
    vals = sorted(
        (int(v), tuple(getattr(v, "assets", ())))
        for _a, v in m_again.utxo.values()
    )
    assert vals == [(7, (((pid, b"tok"), 9),)), (100, ())]
    mary_val = [v for _a, v in m_again.utxo.values() if int(v) == 7][0]
    assert isinstance(mary_val, MaryValue)
