"""Differential tests: mod-L scalar reduction and batched curve ops vs host."""

import hashlib
import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ouroboros_consensus_tpu.ops import bigint as bi
from ouroboros_consensus_tpu.ops import curve as cv
from ouroboros_consensus_tpu.ops import field as fe
from ouroboros_consensus_tpu.ops import scalar as sc
from ouroboros_consensus_tpu.ops.host import ed25519 as he

rng = random.Random(99)


def _bytes_arr(rows):
    return jnp.asarray(np.stack([np.frombuffer(r, dtype=np.uint8) for r in rows]))


# --- bigint / scalar --------------------------------------------------------


def test_bigint_mul_and_bits():
    a_int = [rng.randrange(2**250) for _ in range(8)]
    b_int = [rng.randrange(2**250) for _ in range(8)]
    a = jnp.asarray(np.stack([bi.int_to_limbs_np(v, 20) for v in a_int]))
    b = jnp.asarray(np.stack([bi.int_to_limbs_np(v, 20) for v in b_int]))
    z = jax.jit(bi.mul)(a, b)
    for row, x, y in zip(np.asarray(z), a_int, b_int):
        assert bi.limbs_to_int_np(row) == x * y
    bits = bi.limbs_to_bits(a, 253)
    for row, x in zip(np.asarray(bits), a_int):
        assert sum(int(v) << i for i, v in enumerate(row)) == x % (1 << 253)


@jax.jit
def _reduce512(b):
    return sc.reduce512(b)


def test_reduce512_vs_int():
    digests = [os.urandom(64) for _ in range(16)]
    digests += [b"\xff" * 64, b"\x00" * 64]
    out = _reduce512(_bytes_arr(digests))
    for row, d in zip(np.asarray(out), digests):
        assert bi.limbs_to_int_np(row) == int.from_bytes(d, "little") % sc.L_INT


def test_is_canonical32():
    vals = [0, 1, sc.L_INT - 1, sc.L_INT, sc.L_INT + 5, 2**256 - 1]
    arr = _bytes_arr([v.to_bytes(32, "little") for v in vals])
    got = np.asarray(jax.jit(sc.is_canonical32)(arr))
    assert got.tolist() == [True, True, True, False, False, False]


# --- curve ------------------------------------------------------------------


def _stage_points(pts):
    """host extended points -> batched Point (canonicalized limbs)."""
    cols = [[], [], [], []]
    for p in pts:
        for i, c in enumerate(p):
            cols[i].append(fe.int_to_limbs_np(c % fe.P_INT))
    return cv.Point(*(jnp.asarray(np.stack(c)) for c in cols))


def _host_point(p: cv.Point, i):
    arr = [fe.limbs_to_int_np(np.asarray(c)[i]) % fe.P_INT for c in p]
    return tuple(arr)


@jax.jit
def _add(p, q):
    return cv.add(p, q)


@jax.jit
def _dbl(p):
    return cv.double(p)


def test_add_double_vs_host():
    hosts = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(8)]
    others = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(8)]
    p, q = _stage_points(hosts), _stage_points(others)
    s = _add(p, q)
    d = _dbl(p)
    for i in range(8):
        assert he.point_equal(_host_point(s, i), he.point_add(hosts[i], others[i]))
        assert he.point_equal(_host_point(d, i), he.point_double(hosts[i]))


@jax.jit
def _smul(bits, p):
    return cv.scalar_mul(bits, p)


@jax.jit
def _bmul(digits):
    return cv.base_mul(digits)


@jax.jit
def _dsmul(ba, pa, bb, pb):
    return cv.double_scalar_mul(ba, pa, bb, pb)


def test_scalar_mul_vs_host():
    scalars = [0, 1, 2, he.L - 1] + [rng.randrange(he.L) for _ in range(4)]
    base_pts = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(8)]
    p = _stage_points(base_pts)
    bits_np = np.zeros((8, 253), dtype=np.int32)
    for i, s in enumerate(scalars):
        for j in range(253):
            bits_np[i, j] = (s >> j) & 1
    got = _smul(jnp.asarray(bits_np), p)
    for i, s in enumerate(scalars):
        assert he.point_equal(_host_point(got, i), he.point_mul(s, base_pts[i]))


def test_base_mul_vs_host():
    scalars = [rng.randrange(2**256) for _ in range(6)] + [0, 1]
    digits_np = np.zeros((8, 64), dtype=np.int32)
    for i, s in enumerate(scalars):
        for w in range(64):
            digits_np[i, w] = (s >> (4 * w)) & 0xF
    got = _bmul(jnp.asarray(digits_np))
    for i, s in enumerate(scalars):
        assert he.point_equal(_host_point(got, i), he.point_mul(s, he.B))


def test_double_scalar_mul():
    pa_h = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(4)]
    pb_h = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(4)]
    a_s = [rng.randrange(2**253) for _ in range(4)]
    b_s = [rng.randrange(2**128) for _ in range(4)]
    ba = np.zeros((4, 253), np.int32)
    bb = np.zeros((4, 128), np.int32)
    for i in range(4):
        for j in range(253):
            ba[i, j] = (a_s[i] >> j) & 1
        for j in range(128):
            bb[i, j] = (b_s[i] >> j) & 1
    got = _dsmul(jnp.asarray(ba), _stage_points(pa_h), jnp.asarray(bb), _stage_points(pb_h))
    for i in range(4):
        want = he.point_add(he.point_mul(a_s[i], pa_h[i]), he.point_mul(b_s[i], pb_h[i]))
        assert he.point_equal(_host_point(got, i), want)


@jax.jit
def _decompress(b):
    return cv.decompress(b)


@jax.jit
def _compress(p):
    return cv.compress(p)


def test_compress_decompress_vs_host():
    pts = [he.point_mul(rng.randrange(he.L), he.B) for _ in range(8)]
    encs = [he.point_compress(p) for p in pts]
    ok, got = _decompress(_bytes_arr(encs))
    assert np.asarray(ok).all()
    for i in range(8):
        assert he.point_equal(_host_point(got, i), pts[i])
    back = _compress(got)
    for row, enc in zip(np.asarray(back), encs):
        assert bytes(row.astype(np.uint8)) == enc


def test_decompress_rejects_bad():
    bad_y = (fe.P_INT + 1).to_bytes(32, "little")  # non-canonical
    nonres = None
    for y in range(2, 100):
        x2 = (y * y - 1) * pow(he.D * y * y + 1, fe.P_INT - 2, fe.P_INT) % fe.P_INT
        if pow(x2, (fe.P_INT - 1) // 2, fe.P_INT) not in (0, 1):
            nonres = y.to_bytes(32, "little")
            break
    ok, _ = _decompress(_bytes_arr([bad_y, nonres]))
    assert not np.asarray(ok).any()


def test_identity_eq_cofactor():
    ident = cv.identity((2,))
    assert np.asarray(jax.jit(cv.is_identity)(ident)).all()
    pts = _stage_points([he.B, he.point_double(he.B)])
    assert not np.asarray(jax.jit(cv.is_identity)(pts)).any()
    e8 = jax.jit(cv.mul_cofactor)(pts)
    for i, hp in enumerate([he.B, he.point_double(he.B)]):
        assert he.point_equal(_host_point(e8, i), he.point_mul(8, hp))


def test_reduce512_borrow_regression():
    """sub_mod_2k needs a normalized subtrahend: crafted digest whose q*L
    has limbs > MASK used to produce a wrong challenge scalar."""
    d = bytes.fromhex(
        "dc6cf55033dd30030807739cfa77160fd9b05d7b851378cf555486a683d8705a"
        "1180000000000000000000000000000000000000000000000000000000000000"
    )
    out = _reduce512(_bytes_arr([d]))
    assert bi.limbs_to_int_np(np.asarray(out)[0]) == int.from_bytes(d, "little") % sc.L_INT
