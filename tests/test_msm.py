"""MSM + RLC aggregation building blocks (ops/pk/msm.py, aggregate.py).

Fast tier: the Pippenger MSM against the host big-int reference at
SMALL widths (64-bit scalars: same code path, 1/4 of the windows — the
full 256-bit differential runs in the slow tier via test_aggregate),
the mod-L scalar product/sum helpers, and the Fiat–Shamir coefficient
properties the aggregation relies on (determinism across re-runs and
window re-ordering). Host/native batch-compatible ECVRF differentials
are pure host work (no device compile).
"""

import random

import numpy as np
import pytest

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import bigint as bi
from ouroboros_consensus_tpu.ops.host import ecvrf as hv
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.pk import curve as pc
from ouroboros_consensus_tpu.ops.pk import limbs as fe
from ouroboros_consensus_tpu.ops.pk import msm


def _limbs_col(ints):
    return jnp.asarray(
        np.stack([bi.int_to_limbs_np(k, 20) for k in ints], axis=-1)
    )


def _points_col(pts):
    enc = np.stack(
        [np.frombuffer(he.point_compress(p), np.uint8) for p in pts]
    ).astype(np.int32).T
    ok, P = pc.decompress(jnp.asarray(enc))
    assert bool(jnp.all(ok))
    return P


def _host_msm(ks, pts):
    acc = he.IDENT
    for k, p in zip(ks, pts):
        acc = he.point_add(acc, he.point_mul(k, p))
    return he.point_compress(acc)


def _run_msm32(scal, P):
    # eager + 32-bit scalars (4 windows): the window count is the only
    # thing nbits changes, and the small graph keeps the COLD-cache
    # compile cost of the fast tier low (the 256-bit differential runs
    # in the slow tier, tests/test_aggregate.py); eager op-by-op
    # compilation shares its pieces between the two tests below
    return msm.msm(scal, P, 32)


@pytest.fixture(scope="module")
def rng_points():
    random.seed(20260803)
    pts = [he.point_mul(random.randrange(1, he.L), he.B) for _ in range(7)]
    return pts


@pytest.mark.slow
def test_msm_matches_host_32bit(rng_points):
    """Σ k_i·P_i for 32-bit scalars — exercises sort, chunked segment
    scan, bucket extraction, weighted sum and the Horner doubling chain
    (window count is the only thing nbits changes). Slow tier: even the
    4-window eager trace costs ~2 min against a cold XLA:CPU cache on
    the 1-core box (the aggregate differentials cover the same code for
    real in tests/test_aggregate.py)."""
    ks = [random.randrange(1 << 32) for _ in rng_points]
    # include collisions + the zero digit bucket: lane 0 scalar 0
    ks[0] = 0
    ks[1] = ks[2]
    got = _run_msm32(_limbs_col(ks), _points_col(rng_points))
    enc = np.asarray(pc.compress(got))[:, 0].astype(np.uint8).tobytes()
    assert enc == _host_msm(ks, rng_points)


@pytest.mark.slow
def test_msm_cancellation_is_identity(rng_points):
    """k·P + k·(−P) = 0 — the exact shape of the aggregate's accept
    condition (identity-equality, not byte compare). Shares the 64-bit
    window count with the differential above (one compiled program)."""
    p = rng_points[0]
    k = random.randrange(1 << 32)
    ks = [k, k, 0, 0, 0, 0, 0]
    P = _points_col([p, he.point_neg(p), *rng_points[2:]])
    total = _run_msm32(_limbs_col(ks), P)
    assert bool(msm.is_identity(total)[0])


def test_recode_signed_roundtrip_and_bounds():
    """Balanced signed-digit recoding (the shared-bucket engine's
    digit form): digits stay in (−2^11, 2^11] and Σ d_i·2^(12i)
    reconstructs the scalar exactly, across every width class the
    unified aggregate folds (64-bit products never appear, but 128-bit
    coefficients and 253-bit mod-L products both do)."""
    random.seed(7)
    for nbits in (64, 128, 253):
        ks = [random.randrange(1 << nbits) for _ in range(50)]
        ks[0] = 0
        ks[1] = (1 << nbits) - 1
        d = np.asarray(msm.recode_signed(_limbs_col(ks), nbits))
        assert d.shape[0] == msm.signed_digit_windows(nbits)
        assert (np.abs(d) <= 1 << 11).all()
        for j, k in enumerate(ks):
            got = sum(int(v) << (12 * i) for i, v in enumerate(d[:, j]))
            assert got == k, (nbits, j)


@pytest.mark.slow
def test_msm_shared_two_group_matches_host(rng_points):
    """ONE shared bucket pass over two width-segmented groups (the
    unified aggregate's exact shape: narrow Fiat–Shamir coefficients +
    wide mod-L products) equals the host fold — including a zero
    scalar, a duplicated scalar and an L−1 wide scalar."""
    pts_a = rng_points[:4]
    pts_b = rng_points[4:]
    ks_a = [random.randrange(1 << 64) for _ in pts_a]
    ks_a[0] = 0
    ks_a[1] = ks_a[2]
    ks_b = [random.randrange(he.L) for _ in pts_b]
    ks_b[0] = he.L - 1
    got = msm.msm_shared([
        (_limbs_col(ks_a), _points_col(pts_a), 64),
        (_limbs_col(ks_b), _points_col(pts_b), 253),
    ])
    enc = np.asarray(pc.compress(got))[:, 0].astype(np.uint8).tobytes()
    acc = he.IDENT
    for k, p in zip(ks_a + ks_b, pts_a + pts_b):
        acc = he.point_add(acc, he.point_mul(k, p))
    assert enc == he.point_compress(acc)


@pytest.mark.slow
def test_msm_shared_cancellation_is_identity(rng_points):
    """k·P + k·(−P) = 0 through the signed-digit shared engine — the
    accept condition of the unified aggregate (identity equality after
    the one folded bucket pass)."""
    p = rng_points[0]
    k = random.randrange(1 << 64)
    total = msm.msm_shared([
        (_limbs_col([k, k]), _points_col([p, he.point_neg(p)]), 64),
    ])
    assert bool(msm.is_identity(total)[0])


def test_mul_sum_mod_l_match_python():
    random.seed(11)
    a = [random.randrange(he.L) for _ in range(5)]
    b = [random.randrange(he.L) for _ in range(5)]
    prod = jax.jit(fe.mul_mod_l)(_limbs_col(a), _limbs_col(b))
    got = np.asarray(prod)
    for i in range(5):
        want = bi.int_to_limbs_np(a[i] * b[i] % he.L, 20)
        assert (got[:, i] == want).all(), i
    terms = [jnp.asarray(_limbs_col(a)), jnp.asarray(_limbs_col(b))]
    s = np.asarray(jax.jit(fe.sum_mod_l)(terms))[:, 0]
    want = bi.int_to_limbs_np((sum(a) + sum(b)) % he.L, 20)
    assert (s == want).all()


def test_sum_mod_l_no_int32_overflow_at_scale():
    """Regression: an un-normalized cross-term accumulator overflows
    int32 once lanes x terms x 2^13 clears 2^31 (~87k lane-terms at 3
    terms). 40 all-(2^252−1) terms of 8192 lanes = 2.7e9 per limb
    column if summed naively; per-term carry normalization keeps it
    exact."""
    t, n_terms = 8192, 40
    col = jnp.broadcast_to(_limbs_col([(1 << 252) - 1]), (20, t))
    s = np.asarray(jax.jit(fe.sum_mod_l)([col] * n_terms))[:, 0]
    want = bi.int_to_limbs_np(n_terms * t * ((1 << 252) - 1) % he.L, 20)
    assert (s == want).all()


# ---------------------------------------------------------------------------
# Fiat–Shamir coefficients
# ---------------------------------------------------------------------------


def _fs_inputs(t, seed=0):
    rng = np.random.default_rng(seed)

    def col(n):
        return jnp.asarray(rng.integers(0, 256, (n, t)).astype(np.int32))

    return (col(32), col(32), col(64), col(32), col(32), col(64),
            col(32), col(32), col(32), col(32), col(32), col(32), col(64))


def test_fs_coefficients_deterministic_and_reorder_invariant():
    """The coefficients are a function of the LANE transcript only:
    identical across re-runs, and permuting the lanes of a window
    permutes the coefficients without changing any lane's value — so
    window segmentation/reordering cannot change the aggregate inputs."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg

    args = _fs_inputs(6)
    fn = jax.jit(agg.fs_coefficients)
    z_a = [np.asarray(z) for z in fn(*args)]
    z_b = [np.asarray(z) for z in fn(*args)]
    for a, b in zip(z_a, z_b):
        assert (a == b).all()
    perm = np.asarray([3, 0, 5, 1, 4, 2])
    args_p = tuple(a[:, perm] for a in args)
    z_p = [np.asarray(z) for z in fn(*args_p)]
    for a, p in zip(z_a, z_p):
        assert (a[:, perm] == p).all()
    # distinct lanes get (overwhelmingly) distinct coefficients
    flat = np.concatenate([z.T for z in z_a], axis=-1)
    assert len({r.tobytes() for r in flat}) == flat.shape[0]


def test_fs_coefficients_odd_on_all_four_lanes():
    """Round-15 extension of the PR-3 cofactor-coprime forcing: ALL
    FOUR coefficient streams (z1 ed, z2 kes — new with the unified
    fold — z3/z4 vrf) carry a forced-odd low bit in every lane, and an
    odd z keeps any nonzero 8-torsion offset alive: z·T ≠ 0 for the
    order-8 generator, host-checked per stream. This is the property
    that makes single-lane torsion grinding on the ed/kes wire points
    detectable by the one aggregated identity check."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg

    args = _fs_inputs(6, seed=42)
    zs = [np.asarray(z) for z in jax.jit(agg.fs_coefficients)(*args)]
    assert len(zs) == 4
    # order-8 torsion generator: [L]Q for a decompressable Q with a
    # full-order torsion component
    t8 = None
    for b0 in range(256):
        q = he.point_decompress(bytes([b0]) + bytes(31))
        if q is None:
            continue
        cand = he.point_mul(he.L, q)
        if (not he.point_equal(cand, he.IDENT)
                and not he.point_equal(he.point_mul(4, cand), he.IDENT)):
            t8 = cand
            break
    assert t8 is not None
    for z in zs:
        assert (z[0] & 1 == 1).all()
        for lane in range(z.shape[-1]):
            zi = int.from_bytes(bytes(z[:, lane].astype(np.uint8)),
                                "little")
            assert zi & 1 == 1
            assert not he.point_equal(he.point_mul(zi % (8 * he.L), t8),
                                      he.IDENT)


# ---------------------------------------------------------------------------
# Host + native batch-compatible ECVRF
# ---------------------------------------------------------------------------


def test_host_prove_bc_verify_roundtrip():
    seed, alpha = b"\x31" * 32, b"\x17" * 32
    pk = he.secret_to_public(seed)
    p80 = hv.prove(seed, alpha)
    p128 = hv.prove_batch_compat(seed, alpha)
    assert len(p128) == hv.PROOF_BYTES_BATCH
    # same transcript, two serializations
    assert p128[:32] == p80[:32] and p128[96:] == p80[48:]
    beta = hv.verify(pk, p80, alpha)
    assert beta is not None
    assert hv.verify(pk, p128, alpha) == beta
    assert hv.verify_batch_compat(pk, p128, alpha) == beta


@pytest.mark.parametrize("where", ["gamma", "u", "v", "s", "alpha"])
def test_host_verify_bc_rejects_tampering(where):
    seed, alpha = b"\x32" * 32, b"\x18" * 32
    pk = he.secret_to_public(seed)
    pi = bytearray(hv.prove_batch_compat(seed, alpha))
    off = {"gamma": 1, "u": 33, "v": 65, "s": 97}.get(where)
    if where == "alpha":
        alpha2 = bytes(31) + b"\x01"
        assert hv.verify(pk, bytes(pi), alpha2) is None
        return
    pi[off] ^= 1
    assert hv.verify(pk, bytes(pi), alpha) is None


def test_native_bc_matches_host():
    from ouroboros_consensus_tpu import native_loader as nl

    if nl.load_crypto() is None:
        pytest.skip("native toolchain unavailable")
    seed, alpha = b"\x33" * 32, b"\x19" * 32
    pk = he.secret_to_public(seed)
    ref = hv.prove_batch_compat(seed, alpha)
    assert nl.native_ecvrf_prove_bc(seed, alpha) == ref
    assert nl.native_ecvrf_verify(pk, ref, alpha) == hv.proof_to_hash(ref)
    bad = bytearray(ref)
    bad[40] ^= 1
    assert nl.native_ecvrf_verify(pk, bytes(bad), alpha) is None


def test_fast_prove_format_follows_env(monkeypatch):
    from ouroboros_consensus_tpu.ops.host import fast

    seed, alpha = b"\x34" * 32, b"\x1a" * 32
    monkeypatch.setenv("OCT_VRF_BATCH", "0")
    assert len(fast.ecvrf_prove(seed, alpha)) == 80
    monkeypatch.setenv("OCT_VRF_BATCH", "1")
    assert len(fast.ecvrf_prove(seed, alpha)) == 128
    monkeypatch.delenv("OCT_VRF_BATCH")
    assert len(fast.ecvrf_prove(seed, alpha)) == 128  # default bc
