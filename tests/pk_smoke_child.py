"""Child process for test_pk_smoke: composed 4-stage pk verification at
a pinned tiny shape (B=8, KES depth 1, unrolled hash cores — the TPU
code path through ops/pk/verify), cross-checked lane-for-lane against
the native verifier. Run in a subprocess so OCT_PK_HASH_IMPL is set
before any ops module is imported.

The composed core runs EAGERLY (jax.disable_jit): XLA:CPU's compile of
the composed graph is pathological on a cold cache (>30 min on a 1-core
box), while eager dispatch is ~4 min deterministically with no cache
dependence. Exits 0 on agreement.
"""

import dataclasses
import os
import sys
from fractions import Fraction

os.environ["OCT_PK_HASH_IMPL"] = "unrolled"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops.pk import verify as pv
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=2,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=100_000,
    kes_depth=1,
)
ETA0 = b"\x07" * 32
B = 8


def main() -> int:
    pools = [fixtures.make_pool(i, kes_depth=1) for i in range(2)]
    lview = fixtures.make_ledger_view(pools)
    hvs, slot, prev = [], 1, None
    while len(hvs) < B:
        pool = fixtures.find_leader(PARAMS, pools, lview, slot, ETA0)
        if pool is not None:
            hvs.append(
                fixtures.forge_header_view(
                    PARAMS, pool, slot=slot, epoch_nonce=ETA0,
                    prev_hash=prev, body_bytes=b"b%d" % len(hvs),
                )
            )
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    # one corruption per verifier leg
    hvs[2] = dataclasses.replace(
        hvs[2],
        ocert=dataclasses.replace(
            hvs[2].ocert,
            sigma=hvs[2].ocert.sigma[:-1] + bytes([hvs[2].ocert.sigma[-1] ^ 1]),
        ),
    )
    hvs[4] = dataclasses.replace(
        hvs[4], kes_sig=hvs[4].kes_sig[:-1] + bytes([hvs[4].kes_sig[-1] ^ 1])
    )
    hvs[6] = dataclasses.replace(
        hvs[6],
        vrf_proof=hvs[6].vrf_proof[:1]
        + bytes([hvs[6].vrf_proof[1] ^ 1])
        + hvs[6].vrf_proof[2:],
    )
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    batch = pbatch.stage(PARAMS, lview, ETA0, hvs, pre.kes_evolution)
    arrays = [jnp.asarray(x) for x in pbatch.pk_arrays(batch)]

    def f(*a):
        (ed_pk, ed_r, ed_s, ed_hb, ed_hnb, kes_vk, kes_per, kes_r, kes_s,
         kes_leaf, kes_sib, kes_hb, kes_hnb, vrf_pk, vrf_g, vrf_c, vrf_s,
         vrf_al, beta, tlo, thi) = a
        return pv.verify_praos_core(
            ed_pk, ed_r, ed_s, ed_hb, ed_hnb[0],
            kes_vk, kes_per[0], kes_r, kes_s, kes_leaf, kes_sib,
            kes_hb, kes_hnb[0],
            vrf_pk, vrf_g, vrf_c, vrf_s, vrf_al,
            beta, tlo, thi, kes_depth=1,
        )

    # EAGER, not jitted: the composed graph's XLA:CPU compile is
    # pathological on a cold cache (>30 min measured on the 1-core CI
    # box — the algebraic-simplifier blowup, PERF.md r4/r5), while
    # eager op dispatch of the same graph is ~4 min deterministically,
    # every run, with no cache dependence. The smoke certifies the
    # composed SEMANTICS lane-for-lane; compiled-path coverage lives in
    # the OCT_SLOW tier and the on-hardware scripts.
    with jax.disable_jit():
        v = jax.tree.map(np.asarray, f(*arrays))
    fields = ("ok_ocert_sig", "ok_kes_sig", "ok_vrf", "ok_leader")
    mism = []
    for i in range(B):
        # native verifier one lane at a time (it short-circuits at the
        # first failing lane, so batch-level lane-for-lane is invalid)
        pre_i = pbatch.HostChecks(
            pre.kes_window_errors[i : i + 1],
            pre.vrf_lookup_errors[i : i + 1],
            pre.kes_evolution[i : i + 1],
        )
        vn = pbatch.run_batch_native(PARAMS, lview, ETA0, hvs[i : i + 1], pre_i)
        sigs_ok = all(
            bool(getattr(vn, f)[0])
            for f in ("ok_ocert_sig", "ok_kes_sig", "ok_vrf")
        )
        for fname in fields:
            if fname == "ok_leader" and not sigs_ok:
                # the native verifier short-circuits: leadership is not
                # evaluated after a failed signature leg (always False
                # there), while the batched core computes legs
                # independently — the composed verdict is identical
                # because _lane_error applies reference order
                continue
            got = bool(np.asarray(getattr(v, fname))[..., i].reshape(-1)[0])
            want = bool(getattr(vn, fname)[0])
            if got != want:
                mism.append((i, fname, got, want))
        if not mism:
            # eta (nonce contribution) must agree bit-for-bit on fully
            # valid lanes — it feeds the evolving-nonce fold. Gate on
            # sigs_ok, not ok_vrf alone: the native verifier
            # short-circuits inside a lane, so ok_vrf/eta are don't-care
            # once an earlier leg failed
            if sigs_ok and bool(vn.ok_vrf[0]):
                dev_eta = np.asarray(v.eta)[..., i].reshape(-1)
                nat_eta = np.asarray(vn.eta[0]).reshape(-1)
                if not np.array_equal(dev_eta, nat_eta):
                    mism.append((i, "eta", None, None))
    # the three corruptions must actually be caught by the composed core
    caught = (
        not bool(np.asarray(v.ok_ocert_sig).reshape(-1)[2])
        and not bool(np.asarray(v.ok_kes_sig).reshape(-1)[4])
        and not bool(np.asarray(v.ok_vrf).reshape(-1)[6])
    )
    if mism or not caught:
        print(f"MISMATCH lanes={mism} corruptions_caught={caught}")
        return 1
    print("composed pk smoke OK (8 lanes, depth-1, unrolled hashes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
