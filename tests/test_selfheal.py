"""The chaos matrix (PR 12 acceptance): for EVERY fault kind in the
OCT_CHAOS grammar, a seeded injection ends in a COMPLETED,
verdict-correct replay — resumed or degraded — differentially equal
(verdicts, exact error taxonomy, final nonce carry) to the
uninterrupted run. Includes a real SIGKILL-mid-window child resumed by
the parent and a sharded (parallel/spmd) shard-fault case.

Crypto is the hash-only stub (test_packed_batch idiom): the recovery
plumbing is what's under test; the rungs' crypto semantics are pinned
by the existing differential suites. probe-timeout is covered in
tests/test_bench_probe.py (it injects into bench's probe, not a
replay); the per-stage `stage-call` seam is unit-covered in
tests/test_chaos.py (the pk dispatch path it sits on is TPU-only)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from fractions import Fraction

import pytest

import jax

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.obs.warmup import WARMUP
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import chaos, fixtures
from ouroboros_consensus_tpu.utils import trace as T

from tests.test_obs import _forge_chain, make_params
from tests.test_packed_batch import _stub_verify

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    for var in ("OCT_CHAOS", "OCT_CHAOS_SEED", "OCT_CHECKPOINT",
                "OCT_RESUME", "OCT_RECOVERY"):
        monkeypatch.delenv(var, raising=False)
    chaos.reset()
    yield
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    chaos.reset()


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(110 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture
def stubbed(monkeypatch):
    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub-selfheal", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


def _arm(monkeypatch, spec: str, **env):
    monkeypatch.setenv("OCT_CHAOS", spec)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    chaos.reset()


def _run_chain(params, lview, hvs, max_batch=8, backend="device"):
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    return pbatch.validate_chain(
        params, lambda _e: lview, st0, hvs, max_batch=max_batch,
        backend=backend,
    )


def _same_result(a, b):
    assert a.n_valid == b.n_valid
    assert repr(a.error) == repr(b.error)  # exact error taxonomy
    assert a.state == b.state  # final nonce carry + counters + slots


def _recovery_events(lt):
    return [e for e in lt.events if isinstance(e, T.RecoveryEvent)]


# ---------------------------------------------------------------------------
# in-process matrix: validate_chain survives every injected pipeline fault
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec", [
    # a fake XlaRuntimeError-class failure at the 2nd window dispatch
    "device-error@dispatch:1",
    # TWO consecutive dispatch faults (x2): retry absorbs each episode
    "device-error@dispatch:1x2",
    # the staging producer thread dies mid-prepare_window
    "staging-thread-death@window:1",
    # faults in BOTH halves of the pipeline in one replay
    "staging-thread-death@window:0,device-error@dispatch:3",
])
def test_chaos_matrix_pipeline_faults(pools, lview, stubbed, monkeypatch,
                                      spec):
    params = make_params(epoch_length=60)
    # slots 100.. with epoch_length=60: the chain crosses an epoch
    # boundary mid-replay, so recovery and the carry re-seed are
    # exercised against the nonce rotation too
    _, hvs = _forge_chain(params, pools, lview, 60)
    base = _run_chain(params, lview, hvs)
    assert base.error is None and base.n_valid == 60

    _arm(monkeypatch, spec)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = _run_chain(params, lview, hvs)
    finally:
        pbatch.set_batch_tracer(None)
    _same_result(res, base)
    assert chaos.plan().fired(), "the injection must actually fire"
    evs = _recovery_events(lt)
    assert evs and evs[-1].action == "recovered" and evs[-1].ok
    # every episode recovered on the retry rung (chaos faults are
    # transient by contract)
    assert {e.action for e in evs} == {"retry", "recovered"}


def test_chaos_compile_stall_is_survived_not_recovered(pools, lview,
                                                       stubbed,
                                                       monkeypatch):
    """compile-stall models a WALL, not an error: the replay simply
    takes longer and completes identically — no recovery episode."""
    params = make_params(epoch_length=60)
    _, hvs = _forge_chain(params, pools, lview, 24)
    base = _run_chain(params, lview, hvs)
    _arm(monkeypatch, "compile-stall@window:1", OCT_CHAOS_STALL_S="0.01")
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = _run_chain(params, lview, hvs)
    finally:
        pbatch.set_batch_tracer(None)
    _same_result(res, base)
    assert chaos.plan().fired() == ["compile-stall@window:1"]
    assert not _recovery_events(lt)


def test_chaos_disabled_supervisor_raises_through(pools, lview, stubbed,
                                                  monkeypatch):
    """OCT_RECOVERY=0 restores the pre-PR-12 behavior: the fault
    propagates raw out of validate_chain."""
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    monkeypatch.setenv("OCT_RECOVERY", "0")
    _arm(monkeypatch, "device-error@dispatch:1")
    with pytest.raises(chaos.DeviceChaosError):
        _run_chain(params, lview, hvs)


def test_shard_fault_recovers_on_sharded_backend(pools, lview, stubbed,
                                                 monkeypatch):
    """The sharded (parallel/spmd) shard-fault case: device-error at
    the 0th sharded dispatch; the supervisor's "sharded" ladder's retry
    re-runs the window through the mesh once the injection is spent."""
    from ouroboros_consensus_tpu.parallel import spmd

    from tests.test_parallel import _fake_sharded_verify

    monkeypatch.setattr(spmd, "_sharded_verify", _fake_sharded_verify)
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    base = _run_chain(params, lview, hvs, backend="sharded")
    assert base.error is None and base.n_valid == 24

    _arm(monkeypatch, "device-error@shard:0")
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        res = _run_chain(params, lview, hvs, backend="sharded")
    finally:
        pbatch.set_batch_tracer(None)
    _same_result(res, base)
    assert chaos.plan().fired() == ["device-error@shard:0"]
    evs = _recovery_events(lt)
    assert [e.action for e in evs] == ["retry", "recovered"]


# ---------------------------------------------------------------------------
# db_analyser-level matrix: chunk corruption, AOT rejection, resume
# ---------------------------------------------------------------------------


def _synth_params():
    # small epochs (stability window 24 < 60) so the chain spans
    # SEVERAL epochs and — chunk_size == epoch_length — several chunks:
    # chunk index stands in for the epoch, exactly the chaos grammar
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=60,
        kes_depth=3,
    )


@pytest.fixture(scope="module")
def synth_db(tmp_path_factory):
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    params = _synth_params()
    pool = fixtures.make_pool(11, kes_depth=3)
    lv = fixtures.make_ledger_view([pool])
    path = str(tmp_path_factory.mktemp("selfheal") / "db")
    res = synth.synthesize(
        path, params, [pool], lv, synth.ForgeLimit(blocks=80),
        chunk_size=params.epoch_length,
    )
    assert res.n_blocks == 80
    return path, params, lv


def _revalidate(synth, **kw):
    from ouroboros_consensus_tpu.tools import db_analyser as ana

    path, params, lv = synth
    return ana.revalidate(path, params, lv, backend="device",
                          validate_all=False, max_batch=8, **kw)


def test_chunk_corrupt_rereads_and_matches(synth_db, stubbed, monkeypatch):
    base = _revalidate(synth_db)
    assert base.error is None and base.n_valid == 80

    _arm(monkeypatch, "chunk-corrupt@epoch:1")
    res = _revalidate(synth_db)
    # (describe() renders the NORMALIZED trigger: epoch -> chunk)
    assert chaos.plan().fired() == ["chunk-corrupt@chunk:1"]
    assert res.error is None and res.n_valid == base.n_valid
    assert res.final_state == base.final_state
    rows = WARMUP.report()["recovery"]
    assert [r["action"] for r in rows] == ["chunk-reread", "recovered"]
    assert rows[0]["fault"] == "ChunkChaosError"


def test_aot_reject_falls_back_and_matches(synth_db, stubbed, monkeypatch):
    """aot-reject@stage: the store reports the r04 'incompatible'
    class; the stage falls back to the jit path and the replay is
    byte-identical — no latch, no marker, nothing condemned."""
    base = _revalidate(synth_db)
    # fence the process-wide first-execute memo so THIS replay consults
    # the AOT store again (other suites may have warmed the label)
    monkeypatch.setattr(pbatch, "_WARM_SEEN", set())
    from ouroboros_consensus_tpu.ops.pk import aot

    monkeypatch.setattr(aot, "_LOADED", {})
    _arm(monkeypatch, "aot-reject@stage:packed")
    res = _revalidate(synth_db)
    assert chaos.plan().fired() == ["aot-reject@stage:packed"]
    assert res.error is None and res.n_valid == base.n_valid
    assert res.final_state == base.final_state
    # the real outcome vocabulary banked the rejection...
    assert WARMUP.report()["aot"].get("rejected", 0) >= 1
    # ...and the transient injection latched NOTHING process-wide
    assert not aot._RUNTIME_REJECTED


def test_checkpoint_resume_differential(synth_db, stubbed, monkeypatch,
                                        tmp_path):
    """The crash-consistent resume contract, differentially: a killed
    attempt (fault with the supervisor disabled) leaves a progress
    record; the resumed replay — including one resuming PAST an epoch
    boundary and one re-tiled onto a different max_batch — is
    verdict-identical to the uninterrupted run."""
    base = _revalidate(synth_db)
    assert base.error is None and base.n_valid == 80

    for fault_at, resume_batch in ((1, 8), (5, 16)):
        ck = str(tmp_path / f"ckpt_{fault_at}.json")
        monkeypatch.setenv("OCT_CHECKPOINT", ck)
        monkeypatch.setenv("OCT_RECOVERY", "0")  # die, don't degrade
        _arm(monkeypatch, f"device-error@dispatch:{fault_at}")
        with pytest.raises(chaos.DeviceChaosError):
            _revalidate(synth_db)
        monkeypatch.delenv("OCT_CHAOS")
        chaos.reset()
        doc = recovery.read_checkpoint(ck)
        assert doc is not None and not doc["complete"]
        assert 0 < doc["headers"] < 80
        # the resumed run: supervisor back on, fresh tiling allowed —
        # resume is window-slicing invariant (the mid-ladder-swap
        # analog: the killed attempt retired 8-lane windows, the
        # resumed one re-tiles at 16)
        monkeypatch.setenv("OCT_RECOVERY", "1")
        monkeypatch.setenv("OCT_RESUME", "1")
        from ouroboros_consensus_tpu.tools import db_analyser as ana

        path, params, lv = synth_db
        res = ana.revalidate(path, params, lv, backend="device",
                             validate_all=False, max_batch=resume_batch)
        monkeypatch.delenv("OCT_RESUME")
        assert res.resumed_headers == doc["headers"]
        assert res.error is None and res.n_valid == base.n_valid
        assert res.final_state == base.final_state
        # the finished record is COMPLETE: a further "resume" starts
        # fresh instead of trusting a finished run's position
        done = recovery.read_checkpoint(ck)
        assert done["complete"] and done["headers"] == 80


def test_resume_ignores_other_chains_record(synth_db, stubbed,
                                            monkeypatch, tmp_path):
    """A record tagged for ANOTHER chain (bench warms on the 100k
    chain, measures the 1M one) must not seed a resume: the replay
    silently starts fresh and still matches."""
    base = _revalidate(synth_db)
    ck = str(tmp_path / "ckpt.json")
    # a record for a different chain tag, valid in every other way
    w = recovery.ProgressWriter(ck, "someone-elses-chain")
    w.note(praos.PraosState(epoch_nonce=b"\x01" * 32), 48)
    monkeypatch.setenv("OCT_CHECKPOINT", ck)
    monkeypatch.setenv("OCT_RESUME", "1")
    res = _revalidate(synth_db)
    assert res.resumed_headers == 0  # fresh start, not a wrong re-seed
    assert res.error is None and res.n_valid == base.n_valid
    assert res.final_state == base.final_state


# ---------------------------------------------------------------------------
# the real thing: SIGKILL mid-window, child resumed by the parent
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["OCT_REPO"])
import jax
from jax import numpy as jnp
from fractions import Fraction
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.ops import blake2b
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import db_analyser as ana


def _stub_verify(*cols):
    beta_decl = cols[-3]
    bd = jnp.asarray(beta_decl).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(jnp.concatenate([tag_l, bd], -1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(jnp.concatenate([tag_n, bd], -1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    ones = jnp.ones((b,), bool)
    return pbatch.Verdicts(ones, ones, ones, ones,
                           jnp.zeros((b,), bool), eta, lv)


pbatch.verify_praos = _stub_verify
pbatch.verify_praos_bc = _stub_verify
pbatch.verify_praos_any = _stub_verify
_stub_jit = {}


def _patched(bc=False):
    if bc not in _stub_jit:
        _stub_jit[bc] = jax.jit(_stub_verify)
    return _stub_jit[bc]


pbatch._jitted_verify = _patched
os.environ["OCT_VRF_AGG"] = "0"

params = praos.PraosParams(
    slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
    active_slot_coeff=Fraction(1, 2), epoch_length=60, kes_depth=3,
)
pool = fixtures.make_pool(11, kes_depth=3)
lv = fixtures.make_ledger_view([pool])
res = ana.revalidate(os.environ["OCT_TEST_DB"], params, lv,
                     backend="device", validate_all=False, max_batch=8)
out = {
    "n_valid": res.n_valid,
    "resumed": res.resumed_headers,
    "error": repr(res.error) if res.error is not None else None,
    "state": recovery.encode_state(res.final_state),
}
with open(os.environ["OCT_TEST_OUT"], "w") as f:
    json.dump(out, f)
"""


def test_sigkill_mid_window_child_resumed_by_parent(synth_db, tmp_path):
    """A REAL SIGKILL between a window's checkpoint and the next: the
    child dies rc=-9 having banked a progress record; the parent
    relaunches it with OCT_RESUME=1 and the resumed child's verdicts,
    error taxonomy and final nonce carry equal an uninterrupted
    child's."""
    path, _params, _lv = synth_db

    def run_child(extra_env):
        out = str(tmp_path / f"out_{len(os.listdir(tmp_path))}.json")
        env = dict(os.environ)
        env.pop("OCT_CHAOS", None)
        env.pop("OCT_CHECKPOINT", None)
        env.pop("OCT_RESUME", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "OCT_REPO": REPO,
            "OCT_TEST_DB": path,
            "OCT_TEST_OUT": out,
        })
        env.update(extra_env)
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              cwd=REPO, capture_output=True, timeout=300)
        return proc, out

    ck = str(tmp_path / "ckpt.json")
    # 1. the uninterrupted reference child
    proc, ref_out = run_child({})
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    ref = json.load(open(ref_out))
    assert ref["error"] is None and ref["n_valid"] == 80

    # 2. the killed child: SIGKILL fires the moment window 2 retires
    # (AFTER its checkpoint landed — the exactly-once boundary)
    proc, _ = run_child({
        "OCT_CHECKPOINT": ck,
        "OCT_CHAOS": "sigkill@window:2",
    })
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr.decode()[-2000:]
    )
    doc = recovery.read_checkpoint(ck)
    assert doc is not None and not doc["complete"]
    assert 0 < doc["headers"] < 80

    # 3. the parent relaunches with resume: verdict-identical
    proc, res_out = run_child({
        "OCT_CHECKPOINT": ck,
        "OCT_RESUME": "1",
    })
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    res = json.load(open(res_out))
    assert res["resumed"] == doc["headers"] > 0
    assert res["n_valid"] == ref["n_valid"]
    assert res["error"] is None
    assert res["state"] == ref["state"]  # the full nonce carry
    assert recovery.read_checkpoint(ck)["complete"]
