"""Byron-class real ledger: UTxO + delegation rules behind PBFT.

Reference: `src/byron/.../Byron/Ledger/Ledger.hs:501` (applyBlock via
the Byron CHAIN rule: UTXOW -> UTXO -> DELEG), `Byron/EBBs.hs`, and the
Byron->Shelley translation (`Cardano/CanHardFork.hs`
translateLedgerStateByronToShelleyWrapper).
"""

import pytest

from ouroboros_consensus_tpu.hardfork import byron_mock
from ouroboros_consensus_tpu.ledger import byron
from ouroboros_consensus_tpu.ledger.byron import (
    ByronBadInputs,
    ByronDelegError,
    ByronFeeTooSmall,
    ByronGenesis,
    ByronInvalidWitness,
    ByronLedger,
    ByronMissingWitness,
    ByronPParams,
    ByronValueNotConserved,
    addr_of,
    make_dcert,
    make_tx,
    tx_id_of,
)
from ouroboros_consensus_tpu.ledger.byron_spec import (
    DualByronLedger,
    DualByronMismatch,
)
from ouroboros_consensus_tpu.ops.host import ed25519 as ed

# cheap fee policy for tests: fees stay small but non-zero
PP = ByronPParams(min_fee_a=10, min_fee_b=0)

ALICE = b"\x01" * 32
BOB = b"\x02" * 32
GK0 = b"\x10" * 32
GK1 = b"\x11" * 32
DELEGATE = b"\x20" * 32


def _genesis(keys=(GK0, GK1), **kw):
    return ByronGenesis(
        pparams=PP,
        genesis_keys=tuple(ed.secret_to_public(k) for k in keys),
        epoch_length=40,
        security_param=5,
        **kw,
    )


def _ledger():
    return ByronLedger(_genesis())


def _fund(ledger, *pairs):
    """pairs: (seed, coin) — one genesis output per seed."""
    return ledger.genesis_state(
        [(addr_of(ed.secret_to_public(s)), c) for s, c in pairs]
    )


class _Blk:
    """Minimal block shim: the ledger only reads .txs/.slot/.header."""

    def __init__(self, slot, txs, is_ebb=False):
        self.slot = slot
        self.txs = tuple(txs)
        self.header = type("H", (), {"is_ebb": is_ebb})()


def test_spend_moves_value_and_collects_fee():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    bob_addr = addr_of(ed.secret_to_public(BOB))
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE])
    st2 = led.apply_block(led.tick(st, 5), _Blk(5, [tx]))
    assert sum(c for _a, c in st2.utxo.values()) == 90
    assert st2.fees == 10
    assert st2.tip_slot_ == 5
    # the new output sits under the witness-free tx id
    tid = tx_id_of([(bytes(32), 0)], [(bob_addr, 90)])
    assert st2.utxo[(tid, 0)] == (bob_addr, 90)


def test_utxow_rejections():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    t = led.tick(st, 1)
    bob_addr = addr_of(ed.secret_to_public(BOB))

    # missing input
    tx = make_tx([(b"\xaa" * 32, 0)], [(bob_addr, 1)], [ALICE])
    with pytest.raises(ByronBadInputs):
        led.apply_block(t, _Blk(1, [tx]))

    # unwitnessed input (witness by the wrong key)
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [BOB])
    with pytest.raises(ByronMissingWitness):
        led.apply_block(t, _Blk(1, [tx]))

    # corrupted witness signature
    good = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE])
    p = byron.decode_payload(good)
    vk, sig = p.witnesses[0]
    bad = byron.encode_tx(
        p.ins, p.outs, [(vk, sig[:-1] + bytes([sig[-1] ^ 1]))]
    )
    with pytest.raises(ByronInvalidWitness):
        led.apply_block(t, _Blk(1, [bad]))

    # produced > consumed
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 150)], [ALICE])
    with pytest.raises(ByronValueNotConserved):
        led.apply_block(t, _Blk(1, [tx]))

    # fee below the linear policy minimum
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 95)], [ALICE])
    with pytest.raises(ByronFeeTooSmall):
        led.apply_block(t, _Blk(1, [tx]))


def test_reapply_skips_witness_crypto():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    bob_addr = addr_of(ed.secret_to_public(BOB))
    good = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE])
    p = byron.decode_payload(good)
    vk, sig = p.witnesses[0]
    corrupted = byron.encode_tx(
        p.ins, p.outs, [(vk, sig[:-1] + bytes([sig[-1] ^ 1]))]
    )
    # apply rejects; reapply (previously-validated fast path) folds the
    # accounting without touching the signature
    with pytest.raises(ByronInvalidWitness):
        led.apply_block(led.tick(st, 1), _Blk(1, [corrupted]))
    st2 = led.reapply_block(led.tick(st, 1), _Blk(1, [corrupted]))
    assert sum(c for _a, c in st2.utxo.values()) == 90


def test_delegation_cert_updates_pbft_view():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    gvk0 = ed.secret_to_public(GK0)
    dvk = ed.secret_to_public(DELEGATE)

    view0 = led.protocol_ledger_view(led.tick(st, 1))
    assert view0.delegates[gvk0] == 0  # identity delegation at genesis

    cert = make_dcert(GK0, dvk, epoch=0)
    st2 = led.apply_block(led.tick(st, 1), _Blk(1, [cert]))
    view = led.protocol_ledger_view(led.tick(st2, 2))
    assert view.delegates[dvk] == 0  # delegate now maps to GK0's index
    assert gvk0 not in view.delegates

    # wrong epoch rejected
    with pytest.raises(ByronDelegError):
        led.apply_block(
            led.tick(st2, 2), _Blk(2, [make_dcert(GK1, dvk, epoch=7)])
        )
    # a delegate serving two genesis keys rejected (Bimap injectivity)
    with pytest.raises(ByronDelegError):
        led.apply_block(
            led.tick(st2, 2), _Blk(2, [make_dcert(GK1, dvk, epoch=0)])
        )
    # non-genesis issuer rejected
    with pytest.raises(ByronDelegError):
        led.apply_block(
            led.tick(st2, 2), _Blk(2, [make_dcert(ALICE, dvk, epoch=0)])
        )


def test_delegated_forging_validates_under_pbft():
    """End-to-end: a dcert moves signing rights; PBFT (with the LEDGER's
    delegation view) accepts the new delegate's block and rejects the
    old identity-delegate — the loop the mock era left open."""
    from ouroboros_consensus_tpu.protocol.instances import (
        PBftNotGenesisDelegate,
        PBftParams,
        PBftProtocol,
    )

    led = _ledger()
    gen = led.genesis
    proto = PBftProtocol(
        PBftParams(
            num_genesis_keys=2,
            threshold=1,  # permissive window for the 2-block test
            window=10,
            security_param=5,
        ),
        list(gen.genesis_keys),
    )
    st = _fund(led, (ALICE, 100))
    dvk = ed.secret_to_public(DELEGATE)
    st = led.apply_block(led.tick(st, 1), _Blk(1, [make_dcert(GK0, dvk, 0)]))

    pbft_st = proto.initial_state()
    view = led.protocol_ledger_view(led.tick(st, 2))

    blk = byron_mock.forge_block(
        DELEGATE, slot=2, block_no=0, prev_hash=None
    )
    pbft_st = proto.update(
        blk.header.to_view(), 2, proto.tick(view, 2, pbft_st)
    )
    assert pbft_st.signers[-1] == (2, 0)  # counted against GK0's window

    # GK0 itself no longer holds signing rights (it delegated away)
    blk_old = byron_mock.forge_block(GK0, slot=3, block_no=1, prev_hash=None)
    with pytest.raises(PBftNotGenesisDelegate):
        proto.update(
            blk_old.header.to_view(), 3, proto.tick(view, 3, pbft_st)
        )


def test_mempool_view_is_atomic_on_failure():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    view = led.mempool_view(st, 1)
    bob_addr = addr_of(ed.secret_to_public(BOB))
    tx1 = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE])
    view = led.apply_tx(view, tx1)
    before = dict(view.utxo)
    with pytest.raises(ByronBadInputs):
        led.apply_tx(view, tx1)  # double spend
    assert view.utxo == before  # unchanged on failure


def test_ebb_has_no_ledger_effect():
    led = _ledger()
    st = _fund(led, (ALICE, 100))
    ebb = byron_mock.forge_ebb(slot=40, block_no=0, prev_hash=None)
    st2 = led.apply_block(led.tick(st, 40), ebb)
    assert dict(st2.utxo) == dict(st.utxo)
    assert st2.tip_slot_ == 40


def test_dual_byron_lockstep_and_divergence():
    dual = DualByronLedger(_genesis())
    st = dual.genesis_state(
        [(addr_of(ed.secret_to_public(ALICE)), 100)]
    )
    bob_addr = addr_of(ed.secret_to_public(BOB))
    dvk = ed.secret_to_public(DELEGATE)
    blk = _Blk(1, [
        make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE]),
        make_dcert(GK0, dvk, 0),
    ])
    st2 = dual.apply_block(dual.tick(st, 1), blk)
    assert st2.spec.balances[bob_addr] == 90
    assert st2.impl.delegation[ed.secret_to_public(GK0)] == dvk

    # both sides agree a bad tx is bad (validity agreement, no mismatch)
    bad = make_tx([(b"\xaa" * 32, 0)], [(bob_addr, 1)], [ALICE])
    with pytest.raises(ByronBadInputs):
        dual.apply_block(dual.tick(st2, 2), _Blk(2, [bad]))

    # injected impl-side corruption surfaces as a mismatch
    import dataclasses

    broken = dataclasses.replace(
        st2,
        impl=dataclasses.replace(
            st2.impl,
            utxo={**st2.impl.utxo,
                  (b"\xfe" * 32, 0): (bob_addr, 7)},
        ),
    )
    tx = make_tx(
        [(tx_id_of([(bytes(32), 0)], [(bob_addr, 90)]), 0)],
        [(bob_addr, 80)], [BOB],
    )
    with pytest.raises(DualByronMismatch):
        dual.apply_block(dual.tick(broken, 3), _Blk(3, [tx]))


def test_byron_to_shelley_translation_carries_real_state():
    """Era-0 value is still spendable in the Shelley era: the carried
    UTxO keeps its outpoints and 28-byte payment credentials, and a
    Shelley tx witnessed-by-construction spends a Byron-created output."""
    from ouroboros_consensus_tpu.ledger.shelley import (
        PParams,
        ShelleyGenesis,
        ShelleyLedger,
        encode_tx as sh_encode_tx,
    )

    led = _ledger()
    st = _fund(led, (ALICE, 1_000))
    bob_addr = addr_of(ed.secret_to_public(BOB))
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 700)], [ALICE])
    st = led.apply_block(led.tick(st, 5), _Blk(5, [tx]))

    sh = ShelleyLedger(ShelleyGenesis(
        pparams=PParams(min_fee_a=0, min_fee_b=0),
        epoch_length=100,
        stability_window=30,
    ))
    stake = b"\x33" * 28
    sh_st = sh.translate_from_utxo_ledger(
        st, at_slot=100, stake_of=lambda _a: stake
    )
    # the Byron-created outpoint survives translation verbatim
    tid = tx_id_of([(bytes(32), 0)], [(bob_addr, 700)])
    assert sh_st.utxo[(tid, 0)] == ((bob_addr, stake), 700)

    # and is spendable under the Shelley rules
    carol = b"\x44" * 28
    sh_tx = sh_encode_tx(
        [(tid, 0)], [(carol, None, 700)], fee=0, ttl=10_000
    )
    t = sh.tick(sh_st, 101)
    sh_st2 = sh.apply_block(
        t, type("B", (), {"slot": 101, "txs": (sh_tx,)})()
    )
    assert ((carol, None), 700) in sh_st2.utxo.values()


def test_byron_inspect_reports_delegation_change():
    """InspectLedger: a dcert produces a ByronDelegationChanged event;
    a value-only block produces none."""
    from ouroboros_consensus_tpu.ledger.inspect import (
        ByronDelegationChanged, inspect_ledger,
    )

    led = _ledger()
    st = _fund(led, (ALICE, 100))
    dvk = ed.secret_to_public(DELEGATE)
    st2 = led.apply_block(led.tick(st, 1), _Blk(1, [make_dcert(GK0, dvk, 0)]))
    events = inspect_ledger(led, st, st2)
    assert len(events) == 1 and isinstance(events[0], ByronDelegationChanged)
    assert len(events[0].changes) == 1

    bob_addr = addr_of(ed.secret_to_public(BOB))
    tx = make_tx([(bytes(32), 0)], [(bob_addr, 90)], [ALICE])
    st3 = led.apply_block(led.tick(st2, 2), _Blk(2, [tx]))
    assert inspect_ledger(led, st2, st3) == []
