"""The live run plane (obs/live.py + obs/server.py): heartbeat
snapshots and crash safety, reader-side classification, the stubbed-
clock stall watchdog (a wedged dispatch_batch must be named in the
dump), the in-replay HTTP endpoint answering mid-replay, and the
bench-parent timeline machinery.

Crypto is the hash-only stub where a replay is needed (the test_obs
idiom): the live plumbing is what's under test."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from fractions import Fraction

import pytest

import jax  # noqa: F401 — backend pinned by conftest

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.obs import live, server
from ouroboros_consensus_tpu.obs.registry import MetricsRegistry
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils import trace as T

from tests.test_obs import _forge_chain, make_params
from tests.test_packed_batch import _stub_verify


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(70 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture
def stubbed(monkeypatch):
    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub-live", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


def _span(index=0, n_valid=8):
    return T.WindowSpan(
        index=index, lanes=8, outcome="packed", gate=None, stage_s=0.01,
        dispatch_s=0.02, materialize_s=0.03, epilogue_s=0.004,
        t_dispatch=1.0, t_materialized=2.0, t_done=3.0,
        n_valid=n_valid, failed=False,
    )


# ---------------------------------------------------------------------------
# snapshot + phase classification
# ---------------------------------------------------------------------------


def test_live_snapshot_phase_from_last_event():
    rec = obs.recorder()
    doc = live.live_snapshot(rec)
    assert doc["phase"] == "idle" and doc["headers"] == 0
    rec(T.WindowStaged(0, 8, 16, "packed", None, 0.01, 0.02))
    assert live.live_snapshot(rec)["phase"] == "dispatch"
    rec(T.EncloseEvent("materialize", "start", 1.0))
    assert live.live_snapshot(rec)["phase"] == "materialize"
    rec(_span(0))
    doc = live.live_snapshot(rec)
    assert doc["phase"] == "retired"
    assert doc["headers"] == 8 and doc["window_index"] == 0
    json.dumps(doc, allow_nan=False)  # strict-JSON like every obs doc


def test_live_snapshot_warmup_side():
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        WARMUP.note("aggregate_core@b8192 first execute starting")
        doc = live.live_snapshot(obs.recorder())
        assert doc["phase"] == "warmup"
        assert "first execute starting" in doc["warmup"]["last_note"]
        assert live.classify(doc) == "compiling"
        WARMUP.note_ladder("bg-compile-started", rung=1024, target=8192)
        doc = live.live_snapshot(obs.recorder())
        assert doc["warmup"]["bg_compile"] == "running"
        assert doc["warmup"]["ladder"] == "bg-compile-started"
    finally:
        WARMUP.reset()


def test_classify_compiling_overrides_frozen_dispatch_phase():
    """An in-flight FOREGROUND first-execute (the ~410 s wall): the
    dispatch loop's last event is stale, but the warmup's last note
    says '<stage> first execute starting' with no completion row — the
    live classification must say compiling, not running/stalled."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        rec = obs.recorder()
        rec(T.WindowStaged(0, 8, 16, "packed", None, 0.01, 0.02))
        WARMUP.note("aggregate_core@b8192 first execute starting")
        doc = live.live_snapshot(rec)
        assert doc["phase"] == "dispatch"  # where the loop froze
        assert doc["warmup"]["compiling_now"]
        assert live.classify(doc) == "compiling"
        # the completion row flips it back to the loop's own phase
        WARMUP.note_stage("aggregate_core@b8192", 410.0)
        doc = live.live_snapshot(rec)
        assert not doc["warmup"]["compiling_now"]
        assert live.classify(doc) == "running"
    finally:
        WARMUP.reset()


def test_classify_vocabulary():
    assert live.classify(None) == "no-heartbeat"
    assert live.classify({"nope": 1}) == "no-heartbeat"
    now = time.time()
    base = {"ts_unix": now, "warmup": {}}
    assert live.classify({**base, "phase": "stage"}, now) == "staging"
    assert live.classify({**base, "phase": "stream"}, now) == "staging"
    for p in ("dispatch", "materialize", "retired", "epilogue"):
        assert live.classify({**base, "phase": p}, now) == "running"
    assert live.classify({**base, "phase": "warmup"}, now) == "compiling"
    assert live.classify({**base, "phase": "idle"}, now) == "idle"
    assert live.classify({**base, "phase": "idle", "stalled_now": True},
                         now) == "stalled"
    # the LIFETIME stall count is informational only: a run that
    # stalled once and recovered classifies by its live phase again
    assert live.classify(
        {**base, "phase": "retired", "stalls": 2, "stalled_now": False},
        now,
    ) == "running"
    # the file stopped being rewritten -> dead, whatever it says
    assert live.classify({**base, "phase": "dispatch"},
                         now + 1000) == "dead"


# ---------------------------------------------------------------------------
# heartbeat: rolling rate, atomic rewrite, SIGKILL crash safety
# ---------------------------------------------------------------------------


def test_heartbeat_beats_and_rolling_rate(tmp_path):
    rec = obs.recorder()
    clk = [100.0]
    path = str(tmp_path / "hb.json")
    hb = live.Heartbeat(path, rec=rec, clock=lambda: clk[0])
    hb.beat()
    doc0 = live.read_heartbeat(path)
    assert doc0["seq"] == 0 and doc0["headers_per_s"] is None
    rec(_span(0, n_valid=100))
    clk[0] = 110.0
    hb.beat()
    doc1 = live.read_heartbeat(path)
    assert doc1["seq"] == 1
    assert doc1["headers"] == 100
    assert doc1["headers_per_s"] == pytest.approx(10.0)
    # samples outside the rolling window age out
    clk[0] = 110.0 + live.RATE_WINDOW_S + 1
    hb.beat()
    assert live.read_heartbeat(path)["headers_per_s"] == pytest.approx(0.0)


def test_heartbeat_thread_start_stop(tmp_path):
    path = str(tmp_path / "hb.json")
    hb = live.Heartbeat(path, rec=obs.recorder(), interval_s=0.05)
    hb.start()
    time.sleep(0.25)
    hb.stop()
    doc = live.read_heartbeat(path)
    assert doc is not None and doc["seq"] >= 2
    assert doc["interval_s"] == 0.05


def test_heartbeat_thread_survives_beat_errors(tmp_path, monkeypatch):
    """A raising beat must not kill the heartbeat thread, must not be
    swallowed silently (the count surfaces as `beat_errors` in the next
    good document + ONE bounded warmup note), and stop() must still
    join and land a final beat."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        path = str(tmp_path / "hb.json")
        hb = live.Heartbeat(path, rec=obs.recorder(), interval_s=0.02)
        boom = [True]
        real_beat = hb.beat

        def flaky_beat():
            if boom[0]:
                raise RuntimeError("snapshot source wedged")
            return real_beat()

        monkeypatch.setattr(hb, "beat", flaky_beat)
        hb.start()  # the immediate armed-plane beat raises too
        time.sleep(0.15)
        assert hb._thread is not None and hb._thread.is_alive()
        assert hb.beat_errors >= 2  # kept beating through the errors
        boom[0] = False
        time.sleep(0.1)
        hb.stop()  # joins cleanly; the final beat succeeds
        doc = live.read_heartbeat(path)
        assert doc is not None
        assert doc["beat_errors"] >= 2  # failures stay visible
        # one bounded forensic note, not one per failed interval
        notes = [n for n in WARMUP.report()["notes"]
                 if "heartbeat beat failed" in n]
        assert len(notes) == 1
        assert "RuntimeError" in notes[0]
    finally:
        WARMUP.reset()


def test_heartbeat_survives_a_kill_mid_rewrite(tmp_path):
    """Mirror of test_warmup_report_survives_a_kill: a child SIGKILLed
    mid-rewrite (a torn .tmp on disk) must leave the last COMPLETE beat
    readable — the parent's classification must never land on a torn
    file."""
    path = str(tmp_path / "hb.json")
    code = (
        "import os\n"
        "from ouroboros_consensus_tpu import obs\n"
        "from ouroboros_consensus_tpu.obs import live\n"
        "from ouroboros_consensus_tpu.utils import trace as T\n"
        "rec = obs.recorder()\n"
        "rec(T.WindowSpan(index=3, lanes=8, outcome='packed', gate=None,\n"
        "    stage_s=.01, dispatch_s=.02, materialize_s=.03,\n"
        "    epilogue_s=.004, t_dispatch=1., t_materialized=2., t_done=3.,\n"
        "    n_valid=8, failed=False))\n"
        f"hb = live.Heartbeat({path!r}, rec=rec)\n"
        "hb.beat()\n"
        "with open(hb.path + '.tmp', 'w') as f:\n"
        "    f.write('{\"torn\": tru')  # killed mid-rewrite\n"
        "os._exit(137)\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, timeout=120,
    )
    assert proc.returncode == 137, proc.stderr.decode()[-2000:]
    doc = live.read_heartbeat(path)
    assert doc is not None, "a kill mid-rewrite must leave the last beat"
    assert doc["phase"] == "retired" and doc["headers"] == 8
    # and once the file goes stale the reader classifies the dead
    # child as dead, not running (fresh reads say running — correct,
    # the beat IS recent)
    assert live.classify(doc, now_unix=doc["ts_unix"] + 60) == "dead"


# ---------------------------------------------------------------------------
# stall watchdog: stubbed clock, wedged dispatch_batch named in the dump
# ---------------------------------------------------------------------------


def test_stall_watchdog_stubbed_clock_names_wedged_dispatch(tmp_path):
    """The forced-wedge harness: a thread wedged inside a frame named
    dispatch_batch, a recorder whose last event is the dispatch, and a
    stubbed clock driven past OCT_STALL_BUDGET_S. The dump must (a)
    name the wedged phase, (b) contain dispatch_batch in a thread
    stack, (c) increment oct_stalls_total{phase=}, and (d) emit a
    first-class StallEvent — and must NOT re-dump while the same stall
    persists."""
    rec = obs.recorder()
    # the last thing the replay did was dispatch a window
    rec(T.WindowStaged(7, 8, 16, "packed", None, 0.01, 0.02))

    wedged = threading.Event()
    release = threading.Event()

    def dispatch_batch(params, lview, eta0, hvs, carry=None, ladder=None):
        wedged.set()
        release.wait(30)

    t = threading.Thread(
        target=dispatch_batch, args=(None,) * 4,
        name="oct-wedged-dispatch", daemon=True,
    )
    t.start()
    assert wedged.wait(10)

    clk = [1000.0]
    dump = str(tmp_path / "stall_dump.json")
    wd = live.StallWatchdog(
        budget_s=60.0, rec=rec, dump_path=dump, clock=lambda: clk[0]
    )
    assert wd.check() is None  # fresh fingerprint: armed, no trip
    clk[0] += 59.0
    assert wd.check() is None  # inside budget
    clk[0] += 2.0
    doc = wd.check()
    release.set()
    assert doc is not None, "61s without progress must trip a 60s budget"
    assert doc["phase"] == "dispatch"
    assert doc["age_s"] == pytest.approx(61.0)
    stacks = "\n".join(
        ln for frames in doc["threads"].values() for ln in frames
    )
    assert "dispatch_batch" in stacks, "the dump must name the wedged stage"
    assert "oct-wedged-dispatch" in "\n".join(doc["threads"])
    # on-disk twin (+ the raw faulthandler dump)
    on_disk = json.load(open(dump))
    assert on_disk["phase"] == "dispatch"
    assert os.path.exists(dump + ".txt")
    # countable + first-class
    snap = rec.registry.snapshot()
    row = snap["oct_stalls_total"]["samples"][0]
    assert row["labels"] == {"phase": "dispatch"} and row["value"] == 1
    stall_evs = [e for _t, e in rec.timed_events()
                 if isinstance(e, T.StallEvent)]
    assert len(stall_evs) == 1 and stall_evs[0].dump_path == dump
    # one dump per stall episode — the watchdog's OWN StallEvent must
    # not read as progress: a persistent multi-budget wedge stays ONE
    # dump and ONE counted trip, never a re-dump per budget window
    for _ in range(10):
        clk[0] += 100.0
        assert wd.check() is None
    assert wd.dumps == 1
    snap2 = rec.registry.snapshot()
    assert sum(s["value"] for s in
               snap2["oct_stalls_total"]["samples"]) == 1
    # progress re-arms
    rec(_span(8))
    assert wd.check() is None and not wd.tripped
    clk[0] += 61.0
    assert wd.check() is not None, "a NEW stall after progress trips again"


def test_heartbeat_stalled_now_recovers_with_progress(tmp_path):
    """The beat carries the watchdog's CURRENT trip state: stalled
    while wedged, back to the live phase once progress resumes — the
    cumulative stalls count alone must not pin classify() to stalled."""
    rec = obs.recorder()
    rec(_span(0))
    clk = [0.0]
    path = str(tmp_path / "hb.json")
    wd = live.StallWatchdog(budget_s=10.0, rec=rec,
                            dump_path=str(tmp_path / "dump.json"),
                            clock=lambda: clk[0])
    hb = live.Heartbeat(path, rec=rec, watchdog=wd, clock=lambda: clk[0])
    hb.beat()
    clk[0] = 20.0
    doc = hb.beat()
    assert doc["stalled_now"] and doc["stalls"] == 1
    assert live.classify(doc, now_unix=doc["ts_unix"]) == "stalled"
    rec(_span(1))  # the wedge clears
    clk[0] = 25.0
    doc = hb.beat()
    assert not doc["stalled_now"] and doc["stalls"] == 1
    assert live.classify(doc, now_unix=doc["ts_unix"]) == "running"


def test_stall_watchdog_warmup_notes_count_as_progress(tmp_path):
    """A 400 s compile is NOT a stall: warmup notes (first executes,
    AOT outcomes, ladder events) advance the progress fingerprint."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        clk = [0.0]
        wd = live.StallWatchdog(budget_s=10.0, rec=obs.recorder(),
                                dump_path=str(tmp_path / "dump.json"),
                                clock=lambda: clk[0])
        clk[0] = 9.0
        WARMUP.note_stage("agg@b8192", 123.0)
        assert wd.check() is None
        clk[0] = 18.0  # 9s since the note: inside budget again
        assert wd.check() is None and not wd.tripped
        clk[0] = 30.0
        assert wd.check() is not None  # silence past the budget trips
    finally:
        WARMUP.reset()


# ---------------------------------------------------------------------------
# acceptance: /metrics.json + /healthz answer MID-REPLAY
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_metrics_port_answers_mid_replay(pools, lview, stubbed,
                                         monkeypatch, tmp_path):
    """A stubbed-crypto replay with OCT_METRICS_PORT (+ heartbeat +
    watchdog) armed answers /metrics.json and /healthz from a second
    thread WHILE a window is materializing — the round-11 acceptance
    criterion, in tier-1."""
    port = _free_port()
    hb_path = str(tmp_path / "hb.json")
    monkeypatch.setenv("OCT_METRICS_PORT", str(port))
    monkeypatch.setenv("OCT_HEARTBEAT", hb_path)
    monkeypatch.setenv("OCT_STALL_BUDGET_S", "300")
    params = make_params()
    _, hvs = _forge_chain(params, pools, lview, 24)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)

    in_materialize = threading.Event()
    scraped = threading.Event()
    orig_mat = pbatch.materialize_verdicts

    def slow_materialize(tagged, b):
        in_materialize.set()
        scraped.wait(15)  # hold the window open until the scrape lands
        return orig_mat(tagged, b)

    monkeypatch.setattr(pbatch, "materialize_verdicts", slow_materialize)

    plane = live.maybe_arm()
    assert plane is not None and plane.server is not None
    assert plane.server.port == port
    results: dict = {}

    def replay():
        results["res"] = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8
        )

    t = threading.Thread(target=replay, daemon=True)
    t.start()
    try:
        assert in_materialize.wait(30), "replay never reached materialize"
        # mid-replay, from this (second) thread:
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["pid"] == os.getpid()
        assert "phase" in hz and "headers" in hz
        mj = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10).read())
        assert "oct_windows_total" in mj
        pg = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/progress", timeout=10).read())
        assert set(pg) <= set(server._PROGRESS_KEYS)
        scraped.set()
        t.join(timeout=60)
        assert not t.is_alive()
        assert results["res"].error is None
        assert results["res"].n_valid == 24
        # the scrapes counted themselves on the shared registry
        snap = obs.recorder().registry.snapshot()
        paths = {s["labels"]["path"]
                 for s in snap["oct_metrics_scrapes_total"]["samples"]}
        assert {"/healthz", "/metrics.json", "/progress"} <= paths
        # and the heartbeat file was written
        assert live.read_heartbeat(hb_path) is not None
    finally:
        scraped.set()
        plane.disarm()
    # disarm stopped the server: the port no longer answers
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=2)


def test_maybe_arm_is_refcounted_and_lever_gated(monkeypatch, tmp_path):
    for var in ("OCT_HEARTBEAT", "OCT_STALL_BUDGET_S", "OCT_METRICS_PORT"):
        monkeypatch.delenv(var, raising=False)
    assert live.maybe_arm() is None  # no levers -> no plane
    monkeypatch.setenv("OCT_HEARTBEAT", str(tmp_path / "hb.json"))
    p1 = live.maybe_arm()
    p2 = live.maybe_arm()  # nested replays share ONE plane
    assert p1 is p2 and p1 is not None
    assert obs.installed()  # the plane installed the recorder
    p2.disarm()
    assert obs.installed(), "inner disarm must not tear the plane down"
    p1.disarm()
    assert not obs.installed()


def test_revalidate_arms_the_live_plane(monkeypatch, tmp_path):
    """db_analyser.revalidate mounts obs/live when a lever is set: the
    heartbeat file exists after a (tiny, host-backend) replay."""
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    hb_path = str(tmp_path / "hb.json")
    monkeypatch.setenv("OCT_HEARTBEAT", hb_path)
    params = make_params()
    pools_ = [fixtures.make_pool(0, kes_depth=3)]
    lview_ = fixtures.make_ledger_view(pools_)
    path = str(tmp_path / "db")
    res = synth.synthesize(
        path, params, pools_, lview_, synth.ForgeLimit(blocks=6),
    )
    assert res.n_blocks == 6
    out = ana.revalidate(path, params, lview_, backend="host")
    assert out.error is None and out.n_valid == 6
    doc = live.read_heartbeat(hb_path)
    assert doc is not None and doc["seq"] >= 0
    # and the plane was disarmed on the way out
    assert not obs.installed()


# ---------------------------------------------------------------------------
# bench parent machinery: heartbeat tail timeline + stall-dump slimming
# ---------------------------------------------------------------------------


def test_bench_heartbeat_tail_and_stall_dump_slim(tmp_path, monkeypatch):
    import bench

    hb_path = str(tmp_path / "hb.json")
    timeline: list = []
    tail = bench._HeartbeatTail(hb_path, timeline, attempt=1)
    try:
        # no file yet -> no-heartbeat
        tail._poll()
        assert timeline and timeline[0]["state"] == "no-heartbeat"
        # a live beat flips the classification ONCE (dedup on state)
        rec = obs.recorder()
        rec(_span(0))
        live.Heartbeat(hb_path, rec=rec).beat()
        tail._poll()
        tail._poll()
        assert [e["state"] for e in timeline] == ["no-heartbeat", "running"]
        assert timeline[1]["phase"] == "retired"
        assert timeline[1]["headers"] == 8
        assert timeline[1]["attempt"] == 1
    finally:
        tail.stop()
    json.dumps(timeline, allow_nan=False)

    # stall-dump slimming keeps the classification + trimmed stacks
    dump_path = str(tmp_path / "stall_dump.json")
    clk = [0.0]
    wd = live.StallWatchdog(budget_s=1.0, rec=obs.recorder(),
                            dump_path=dump_path, clock=lambda: clk[0])
    clk[0] = 5.0
    assert wd.check() is not None
    monkeypatch.setenv("OCT_STALL_DUMP", dump_path)
    slim = bench._read_stall_dump()
    assert slim is not None
    assert slim["phase"] == "retired"  # last event before the wedge
    assert slim["threads"] and all(
        len(frames) <= 6 for frames in slim["threads"].values()
    )
    json.dumps(slim, allow_nan=False)


# ---------------------------------------------------------------------------
# round 12 satellites: classify() edge states feeding the supervisor,
# watchdog episodes across a recovery, failed-replay plane lifecycle
# ---------------------------------------------------------------------------


def test_classify_clock_skewed_future_beat():
    """A beat timestamp IN THE FUTURE (writer/reader clock skew) must
    classify by its live phase — never as dead (staleness is 'too far
    in the past', a skewed-forward clock is not evidence of death)."""
    now = time.time()
    doc = {"ts_unix": now + 3600, "phase": "dispatch", "warmup": {}}
    assert live.classify(doc, now) == "running"
    doc = {"ts_unix": now + 3600, "phase": "stage", "warmup": {}}
    assert live.classify(doc, now) == "staging"
    doc = {"ts_unix": now + 3600, "phase": "idle", "warmup": {},
           "stalled_now": True}
    assert live.classify(doc, now) == "stalled"


def test_classify_zero_window_replay(tmp_path):
    """A replay that never retires a window (empty chain / all work
    ahead of it): armed and fresh it reads idle — not stalled, not
    dead — and the rolling rate stays None, never NaN."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        rec = obs.recorder()
        clk = [100.0]
        hb = live.Heartbeat(str(tmp_path / "hb.json"), rec=rec,
                            clock=lambda: clk[0])
        doc = hb.beat()
        assert doc["headers"] == 0 and doc["phase"] == "idle"
        assert doc["headers_per_s"] is None
        assert live.classify(doc, now_unix=doc["ts_unix"]) == "idle"
        clk[0] = 130.0
        doc = hb.beat()
        assert doc["headers_per_s"] == pytest.approx(0.0)
        assert live.classify(doc, now_unix=doc["ts_unix"]) == "idle"
        json.dumps(doc, allow_nan=False)
    finally:
        WARMUP.reset()


def test_watchdog_one_dump_per_episode_across_recovery(tmp_path):
    """The episode contract across a RECOVERY: a wedge trips once; the
    supervisor's ladder transitions count as progress (re-arming the
    watchdog mid-recovery); a NEW wedge after the recovered episode is
    a new episode with its own dump — one dump per episode, not per
    process."""
    from ouroboros_consensus_tpu.obs.warmup import WARMUP

    WARMUP.reset()
    try:
        rec = obs.recorder()
        rec(_span(0))
        clk = [0.0]
        wd = live.StallWatchdog(budget_s=10.0, rec=rec,
                                dump_path=str(tmp_path / "d.json"),
                                clock=lambda: clk[0])
        clk[0] = 11.0
        assert wd.check() is not None  # episode 1 trips: one dump
        clk[0] = 25.0
        assert wd.check() is None  # SAME episode: no re-dump
        assert wd.dumps == 1
        # the supervisor starts walking the wedged window down the
        # ladder — recovery transitions ARE progress
        WARMUP.note_recovery("retry", window=3, attempt=1,
                             fault="DeviceChaosError")
        clk[0] = 26.0
        assert wd.check() is None and not wd.tripped  # re-armed
        WARMUP.note_recovery("recovered", window=3, attempt=1,
                             fault="DeviceChaosError", ok=True)
        clk[0] = 27.0
        assert wd.check() is None
        clk[0] = 45.0
        assert wd.check() is not None  # a NEW wedge = a new episode
        assert wd.dumps == 2
        snap = rec.registry.snapshot()
        assert sum(s["value"] for s in
                   snap["oct_stalls_total"]["samples"]) == 2
    finally:
        WARMUP.reset()


def test_failed_replay_leaves_no_orphan_listener(monkeypatch, tmp_path):
    """The round-12 lifecycle satellite: an exception escaping the
    replay mid-run must still release maybe_arm()'s ref-count and stop
    the OCT_METRICS_PORT server thread — the port answers mid-replay
    and is CLOSED after the failure, with the recorder uninstalled."""
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    port = _free_port()
    monkeypatch.setenv("OCT_METRICS_PORT", str(port))
    params = make_params()
    pools_ = [fixtures.make_pool(1, kes_depth=3)]
    lview_ = fixtures.make_ledger_view(pools_)
    path = str(tmp_path / "db")
    res = synth.synthesize(
        path, params, pools_, lview_, synth.ForgeLimit(blocks=4),
    )
    assert res.n_blocks == 4
    calls = []
    orig_update = ana.praos.update

    def boom(params_, hv, slot, ticked):
        if calls:
            raise RuntimeError("device fell over mid-replay")
        calls.append(1)
        return orig_update(params_, hv, slot, ticked)

    monkeypatch.setattr(ana.praos, "update", boom)
    with pytest.raises(RuntimeError, match="fell over"):
        ana.revalidate(path, params, lview_, backend="host")
    assert calls, "the replay must have started before failing"
    # the plane unwound: recorder released, no orphan listener
    assert not obs.installed()
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=2)
