"""Packed staging + on-device verdict reduction (the PR-2 "cut the
wire" path).

Three layers:
  1. the packed round-trip property — packed u8 staging -> device unpack
     must be BYTE-IDENTICAL to the host `stage` SoA columns for all
     three column families (ed / kes / vrf), across randomized chains,
     nonces and KES depths; and the limb-first decomposition must equal
     `pk_arrays` of the staged batch;
  2. the D2H reduction — verdict bitmask packing and the sequential
     device nonce scan against the host `nonces.combine` fold,
     including neutral carries and bucket-pad masking;
  3. epilogue equivalence — windows with invalid lanes at the edges
     (first lane, last lane, epoch-tail boundary) produce identical
     `BatchResult` through the packed-verdict fast path and the
     per-lane slow path; and the full pipelined `validate_chain` with
     packed staging agrees with the sequential fold (crypto stubbed so
     the default tier never pays a fused XLA:CPU crypto compile — the
     real-crypto end-to-end runs in the slow tier via
     test_tools.test_device_revalidation_matches_host).
"""

import functools
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.ops import blake2b
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import nonces, praos
from ouroboros_consensus_tpu.testing import fixtures

_COLS_HEAD = [
    "ed.pk", "ed.r", "ed.s", "ed.hblocks", "ed.hnblocks",
    "kes.vk", "kes.period", "kes.r", "kes.s", "kes.vk_leaf",
    "kes.siblings", "kes.hblocks", "kes.hnblocks",
]
_COLS_TAIL = ["beta", "thr_lo", "thr_hi"]


def cols_of(staged):
    """Column names in flatten_batch order — the vrf block depends on
    the staged proof format (draft-03: c; batch-compatible: u, v)."""
    vrf = ["vrf." + f for f in type(staged.vrf)._fields]
    return _COLS_HEAD + vrf + _COLS_TAIL


def make_params(kes_depth=3, epoch_length=100_000):
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=epoch_length,
        kes_depth=kes_depth,
    )


def real_chain(params, pools, n, first_slot=100, first_block=30,
               epoch_nonce=b"\x07" * 32, counter=0):
    """Real-codec headers (block/praos_block CBOR bodies): the packed
    staging extracts fields from these bodies. Slot/block_no ranges are
    chosen inside one CBOR width class so the window stays uniform."""
    hvs, prev = [], b"\xaa" * 32
    for i in range(n):
        blk = forge_block(
            params, pools[i % len(pools)], slot=first_slot + i,
            block_no=first_block + i, prev_hash=prev,
            epoch_nonce=epoch_nonce, txs=(b"tx-%d" % i,),
            ocert_counter=counter,
        )
        hvs.append(blk.header.to_view())
        prev = blk.header.hash_
    return hvs


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


# ---------------------------------------------------------------------------
# 1. the packed round-trip property
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "nonce,depth,first_slot",
    [
        (b"\x07" * 32, 3, 100),
        (None, 3, 300),  # neutral epoch nonce: alpha has no nonce tail
        # different depth + wider (4-byte CBOR) slots; 68200 = KES
        # period 682 = 11*62, so the forged evolution index stays 0
        (b"\x55" * 32, 2, 68_200),
    ],
)
def test_packed_unpack_roundtrips_all_families(nonce, depth, first_slot):
    """Property: for any qualifying window, the device unpack of the
    packed columns equals the host-staged SoA columns byte for byte —
    every ed / kes / vrf column, plus beta and the threshold rows."""
    params = make_params(kes_depth=depth)
    pls = [fixtures.make_pool(10 + i, kes_depth=depth) for i in range(2)]
    lv = fixtures.make_ledger_view(pls)
    hvs = real_chain(params, pls, 9, first_slot=first_slot,
                     epoch_nonce=nonce)
    pre = pbatch.host_prechecks(params, lv, hvs)
    res = pbatch.stage_packed(params, lv, nonce, hvs)
    assert res is not None, "real-codec window must qualify for packing"
    layout, parr = res
    staged = pbatch.stage(params, lv, nonce, hvs, pre.kes_evolution)
    ref = pbatch.flatten_batch(staged)
    got = jax.jit(lambda *a: pbatch.unpack_packed(layout, *a))(*parr[:10])
    # batch-compatible proofs (the forge default) stage 22 columns
    assert len(ref) == len(got) == (22 if layout.vrf_proof_len == 128 else 21)
    for name, a, b in zip(cols_of(staged), ref, got):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape and a.dtype == b.dtype, name
        assert (a == b).all(), name


def test_packed_limb_first_matches_pk_arrays(pools, lview):
    """The packed `unpack` STAGE (unpack + staged_to_limb_first in one
    jit — ops/pk/kernels._mk_packed_unpack) must hand the crypto stages
    exactly what the host-side pk_arrays marshalling builds."""
    from ouroboros_consensus_tpu.ops.pk import kernels as K

    params = make_params()
    nonce = b"\x07" * 32
    hvs = real_chain(params, pools, 8)
    pre = pbatch.host_prechecks(params, lview, hvs)
    layout, parr = pbatch.stage_packed(params, lview, nonce, hvs)
    staged = pbatch.stage(params, lview, nonce, hvs, pre.kes_evolution)
    ref = pbatch.pk_arrays(staged)
    got = jax.jit(K._mk_packed_unpack(layout))(*parr[:10])
    assert len(ref) == len(got) == 22  # bc-staged: u, v replace c
    for i, (a, b) in enumerate(zip(ref, got)):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape and a.dtype == b.dtype == np.int32, i
        assert (a == b).all(), i


def test_packed_h2d_bytes_shrink(pools, lview):
    """The wire contract: the packed columns must ship at most HALF the
    staged bytes per lane of the generic SoA path on a real window."""
    params = make_params(kes_depth=7)
    pls = [fixtures.make_pool(20 + i, kes_depth=7) for i in range(2)]
    lv = fixtures.make_ledger_view(pls)
    hvs = real_chain(params, pls, 16)
    pre = pbatch.host_prechecks(params, lv, hvs)
    _, parr = pbatch.stage_packed(params, lv, b"\x07" * 32, hvs)
    staged = pbatch.stage(params, lv, b"\x07" * 32, hvs, pre.kes_evolution)
    packed_b = sum(np.asarray(c).nbytes for c in parr)
    staged_b = sum(np.asarray(c).nbytes for c in pbatch.flatten_batch(staged))
    assert packed_b * 2 <= staged_b, (packed_b, staged_b)


def test_stage_packed_fallback_gates(pools, lview):
    params = make_params()
    nonce = b"\x07" * 32
    # mixed body lengths (genesis prev=None header) -> generic fallback
    hvs = real_chain(params, pools, 4)
    blk0 = forge_block(params, pools[0], slot=99, block_no=29,
                       prev_hash=None, epoch_nonce=nonce)
    assert pbatch.stage_packed(
        params, lview, nonce, [blk0.header.to_view()] + hvs
    ) is None
    # synthetic views whose signed bytes do not embed the fields
    fv = [
        fixtures.forge_header_view(params, pools[0], slot=s,
                                   epoch_nonce=nonce, prev_hash=b"x" * 32,
                                   body_bytes=b"body-%d" % s)
        for s in range(1, 5)
    ]
    assert pbatch.stage_packed(params, lview, nonce, fv) is None
    # out-of-range integers -> generic fallback
    big = [replace(hvs[0], slot=2**31)] + hvs[1:]
    assert pbatch.stage_packed(params, lview, nonce, big) is None
    # empty window
    assert pbatch.stage_packed(params, lview, nonce, []) is None


def test_kes_tail_table_dedupes(pools, lview):
    """Lanes sharing a (pool, KES period) share one Merkle-tail row —
    the column that used to cost 32 + depth*32 bytes per lane."""
    params = make_params()
    hvs = real_chain(params, pools, 12)
    _, parr = pbatch.stage_packed(params, lview, b"\x07" * 32, hvs)
    n_rows = len({hv.kes_sig[64:] for hv in hvs})
    assert n_rows <= 2  # 2 pools, one period each
    assert parr.kes_tail_idx.max() == n_rows - 1
    # gather reproduces every lane's tail
    for i, hv in enumerate(hvs):
        row = parr.kes_tail_tab[parr.kes_tail_idx[i]]
        assert row.tobytes() == hv.kes_sig[64:]


# ---------------------------------------------------------------------------
# 2. the D2H reduction: bitmasks + nonce scan
# ---------------------------------------------------------------------------


def test_pack_bits_roundtrip():
    rng = np.random.default_rng(7)
    for b in (1, 8, 31, 32, 33, 64, 100):
        bits = rng.integers(0, 2, b).astype(bool)
        words = np.asarray(jax.jit(pbatch._pack_bits_u32)(jnp.asarray(bits)))
        assert (pbatch._mask_bits(words, b) == bits).all(), b


@pytest.mark.parametrize("seed_state", ["set", "neutral"])
def test_verdict_reduce_scan_matches_host_fold(seed_state):
    rng = np.random.default_rng(3)
    b, n_real = 11, 9
    flags = np.ones((5, b), np.int32)
    flags[4] = 0
    flags[2, 9:] = 0  # pad lanes may carry garbage verdicts
    etas = rng.integers(0, 256, (b, 32)).astype(np.int32)
    within = np.ones(b, np.uint8)
    within[6:] = 0
    st = (
        praos.PraosState(evolving_nonce=b"\x01" * 32)
        if seed_state == "set" else praos.PraosState()
    )
    carry = pbatch._state_carry(st)
    red = jax.jit(functools.partial(pbatch.verdict_reduce, scan=True))(
        flags, etas, within, np.int32(n_real), *carry
    )
    masks, ev, evs, cand, cands = (np.asarray(x) for x in red)
    evolving, candidate = st.evolving_nonce, st.candidate_nonce
    for i in range(n_real):
        evolving = nonces.combine(evolving, etas[i].astype(np.uint8).tobytes())
        if within[i]:
            candidate = evolving
    assert bool(evs) == (evolving is not None)
    assert ev.astype(np.uint8).tobytes() == evolving
    assert bool(cands) == (candidate is not None)
    if candidate is not None:
        assert cand.astype(np.uint8).tobytes() == candidate
    # masks reflect the raw flags, pad lanes included
    for r in range(5):
        assert (pbatch._mask_bits(masks[r], b) == (flags[r] != 0)).all(), r
    # scan-off mode ships the packed eta column instead
    m2, eta_u8 = jax.jit(functools.partial(pbatch.verdict_reduce, scan=False))(
        flags, etas, within, np.int32(n_real), *carry
    )
    assert (np.asarray(eta_u8) == etas.astype(np.uint8)).all()
    assert (np.asarray(m2) == masks).all()


# ---------------------------------------------------------------------------
# 3. epilogue equivalence: packed fast path vs per-lane slow path
# ---------------------------------------------------------------------------


def _fab_verdicts(hvs, bad=(), ambiguous=()):
    """Fabricated device outputs: all lanes valid except `bad` (KES bit
    cleared) / `ambiguous` (leader undecided). Etas are arbitrary —
    equivalence is about identical FOLDS, not crypto."""
    b = len(hvs)
    rng = np.random.default_rng(b)
    ok = np.ones(b, bool)
    kes_ok = ok.copy()
    for i in bad:
        kes_ok[i] = False
    amb = np.zeros(b, bool)
    for i in ambiguous:
        amb[i] = True
    eta = rng.integers(0, 256, (b, 32)).astype(np.uint8)
    lv = np.zeros((b, 32), np.uint8)  # certainly-below any threshold
    return pbatch.Verdicts(ok, kes_ok.copy(), ok.copy(), ok.copy(), amb,
                           eta, lv)


def _as_packed(v, params, hvs, st, carried):
    """Wrap fabricated Verdicts as the PackedVerdicts materialize would
    produce (numpy mask packing + host-side reference scan)."""
    b = len(hvs)
    rows = [v.ok_ocert_sig, v.ok_kes_sig, v.ok_vrf, v.ok_leader,
            v.leader_ambiguous]
    w = -(-b // 32)
    masks = np.zeros((5, w), np.uint32)
    for r, bits in enumerate(rows):
        for i, x in enumerate(np.asarray(bits)):
            if x:
                masks[r, i // 32] |= np.uint32(1 << (i % 32))
    nonces_out = None
    if carried:
        evolving, candidate = st.evolving_nonce, st.candidate_nonce
        for i, hv in enumerate(hvs):
            evolving = nonces.combine(
                evolving, np.asarray(v.eta)[i].astype(np.uint8).tobytes()
            )
            first_next = params.first_slot_of(params.epoch_of(hv.slot) + 1)
            if hv.slot + params.stability_window < first_next:
                candidate = evolving
        nonces_out = (
            np.frombuffer(evolving or bytes(32), np.uint8),
            evolving is not None,
            np.frombuffer(candidate or bytes(32), np.uint8),
            candidate is not None,
        )
    flags = np.stack([np.asarray(r).astype(np.int32) for r in rows])
    return pbatch.PackedVerdicts(
        masks, b, "xla", carried, nonces_out,
        np.asarray(v.eta).astype(np.uint8),
        (flags, np.asarray(v.eta).astype(np.int32),
         np.asarray(v.leader_value).astype(np.int32)),
    )


def _results_equal(a, b):
    assert a.n_valid == b.n_valid
    assert (a.error is None) == (b.error is None)
    if a.error is not None:
        assert type(a.error) is type(b.error)
        assert vars(a.error) == vars(b.error)
    assert a.state == b.state


@pytest.mark.parametrize("carried", [True, False])
@pytest.mark.parametrize("bad_at", ["none", "first", "last", "tail-edge"])
def test_epilogue_packed_fast_equals_slow(pools, lview, bad_at, carried):
    """Satellite: invalid lanes at window edges (first lane, last lane,
    epoch-tail boundary) give identical BatchResult.error and nonce
    state through the packed fast path and the per-lane slow path."""
    params = make_params(epoch_length=160)
    nonce = b"\x07" * 32
    if bad_at == "tail-edge":
        # last lane sits at the epoch tail: slots run up to the final
        # slot of epoch 0 (epoch_length 160, first_slot 140 + 19 = 159)
        hvs = real_chain(params, pools, 20, first_slot=140)
    else:
        hvs = real_chain(params, pools, 20)
    bad = {"none": (), "first": (0,), "last": (len(hvs) - 1,),
           "tail-edge": (len(hvs) - 1,)}[bad_at]
    v = _fab_verdicts(hvs, bad=bad)
    st = praos.PraosState(epoch_nonce=nonce, evolving_nonce=b"\x02" * 32)
    ticked = praos.TickedPraosState(st, lview)
    pre = pbatch.host_prechecks(params, lview, hvs)
    pv = _as_packed(v, params, hvs, st, carried)
    res_packed = pbatch._epilogue(params, ticked, hvs, pre, pv)
    res_slow = pbatch._epilogue(params, ticked, hvs, pre, v)
    _results_equal(res_packed, res_slow)
    if bad_at == "none":
        # the all-clean window must have taken the fast path (the slow
        # Verdicts were never materialized from the handles)
        assert pv._full is None
    else:
        assert isinstance(res_packed.error, praos.InvalidKesSignatureOCERT)


def test_epilogue_counter_gate_routes_to_slow_path(pools, lview):
    """A counter regression is only detectable by the stateful host
    gate: the packed mask is all-clean, yet the fast path must decline
    and the slow path must produce the exact reference error."""
    params = make_params()
    nonce = b"\x07" * 32
    hvs = real_chain(params, pools, 6)
    # pool 1 appears at lanes 1 and 3: counter 1 then a REGRESSION to 0
    # (the view's ocert is edited without re-signing — fine here, the
    # fabricated verdicts stand in for the crypto)
    hvs[1] = replace(hvs[1], ocert=replace(hvs[1].ocert, counter=1))
    hvs[3] = replace(hvs[3], ocert=replace(hvs[3].ocert, counter=0))
    v = _fab_verdicts(hvs)
    st = praos.PraosState(epoch_nonce=nonce)
    ticked = praos.TickedPraosState(st, lview)
    pre = pbatch.host_prechecks(params, lview, hvs)
    pv = _as_packed(v, params, hvs, st, carried=True)
    res_packed = pbatch._epilogue(params, ticked, hvs, pre, pv)
    res_slow = pbatch._epilogue(params, ticked, hvs, pre, v)
    _results_equal(res_packed, res_slow)
    assert isinstance(res_packed.error, praos.CounterTooSmallOCERT)
    assert res_packed.n_valid == 3


# ---------------------------------------------------------------------------
# 3b. the pipelined loop end-to-end (crypto stubbed, everything else real)
# ---------------------------------------------------------------------------


def _stub_verify(*cols):
    """All-valid crypto stub with the REAL eta / leader-value range
    extensions (hash-only: compiles in seconds on XLA:CPU where the
    full curve graphs take minutes). Keeps every non-crypto part of the
    packed pipeline — staging, unpack, masks, nonce scan, carries,
    epilogue — byte-exact against the reupdate fold. Arity-generic
    (21 draft-03 / 22 batch-compatible columns): beta_decl is always
    the third-from-last column."""
    beta_decl = cols[-3]
    bd = jnp.asarray(beta_decl).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(jnp.concatenate([tag_l, bd], axis=-1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(jnp.concatenate([tag_n, bd], axis=-1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    ones = jnp.ones((b,), bool)
    return pbatch.Verdicts(ones, ones, ones, ones, jnp.zeros((b,), bool),
                           eta, lv)


@pytest.fixture
def stubbed_crypto(monkeypatch):
    """Patch the fused verifiers (both proof formats) with the hash-only
    stub, disable the aggregated fast path (its RLC/MSM program is real
    crypto — covered stubbed by test_aggregate.py and for real in the
    slow tier), and fence the jit caches so stub-compiled programs never
    leak into other tests."""
    before = set(pbatch._JIT)
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    monkeypatch.setattr(pbatch, "verify_praos", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_bc", _stub_verify)
    monkeypatch.setattr(pbatch, "verify_praos_any", _stub_verify)

    def patched_jv(bc=False):
        key = ("fn-stub", bc)
        if key not in pbatch._JIT:
            pbatch._JIT[key] = jax.jit(_stub_verify)
        return pbatch._JIT[key]

    monkeypatch.setattr(pbatch, "_jitted_verify", patched_jv)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


def test_validate_chain_packed_pipeline_equals_fold(
    pools, lview, stubbed_crypto, monkeypatch
):
    """The full pipelined device path — packed staging, device unpack,
    bitmask verdicts, chained on-device nonce scan across windows AND
    epoch boundaries, fallback windows (CBOR width changes) breaking
    and re-seeding the carry — against the sequential reupdate fold.
    Covers packed-on, packed-off and scan-off configurations."""
    params = make_params(epoch_length=60)
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    st = st0
    hvs, prev = [], b"\xaa" * 32
    slot, blkno = 18, 40  # slots cross the CBOR 1->2-byte boundary at 24
    while len(hvs) < 60:
        ticked = praos.tick(params, lview, slot, st)
        blk = forge_block(
            params, pools[len(hvs) % 2], slot=slot, block_no=blkno,
            prev_hash=prev, epoch_nonce=ticked.state.epoch_nonce,
            txs=(b"t",),
        )
        hv = blk.header.to_view()
        st = praos.reupdate(params, hv, slot, ticked)
        hvs.append(hv)
        prev = blk.header.hash_
        slot += 1
        blkno += 1
    assert params.epoch_of(hvs[-1].slot) >= 1  # crossed an epoch boundary

    for packed, scan in ((True, True), (True, False), (False, True)):
        monkeypatch.setattr(pbatch, "PACKED_STAGE", packed)
        monkeypatch.setattr(pbatch, "NONCE_SCAN", scan)
        res = pbatch.validate_chain(
            params, lambda _e: lview, st0, hvs, max_batch=8,
            pipeline_depth=3,
        )
        assert res.error is None, (packed, scan, repr(res.error))
        assert res.n_valid == len(hvs)
        assert res.state == st, (packed, scan)


def test_transfer_events_report_packed_bytes(
    pools, lview, stubbed_crypto, monkeypatch
):
    """The tracer byte accounting: packed windows must report ≥2x fewer
    H2D bytes than the generic path and ≥8x fewer D2H bytes."""
    from ouroboros_consensus_tpu.utils.trace import TransferEvent

    params = make_params()
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    hvs = real_chain(params, pools, 16)

    def run(packed):
        monkeypatch.setattr(pbatch, "PACKED_STAGE", packed)
        events = []
        pbatch.set_batch_tracer(events.append)
        try:
            res = pbatch.validate_chain(
                params, lambda _e: lview, st0, hvs, max_batch=16
            )
        finally:
            pbatch.set_batch_tracer(None)
        assert res.error is None and res.n_valid == len(hvs)
        h2d = sum(e.h2d_bytes for e in events
                  if isinstance(e, TransferEvent))
        d2h = sum(e.d2h_bytes for e in events
                  if isinstance(e, TransferEvent))
        return h2d, d2h

    h2d_packed, d2h_packed = run(True)
    h2d_generic, d2h_generic = run(False)
    assert h2d_packed * 2 <= h2d_generic, (h2d_packed, h2d_generic)
    assert d2h_packed * 8 <= d2h_generic, (d2h_packed, d2h_generic)
