"""Praos protocol state machine: happy path, error taxonomy, epoch nonces."""

from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.protocol import nonces, praos
from ouroboros_consensus_tpu.protocol.praos import (
    CounterOverIncrementedOCERT,
    CounterTooSmallOCERT,
    InvalidKesSignatureOCERT,
    InvalidSignatureOCERT,
    KESAfterEndOCERT,
    KESBeforeStartOCERT,
    NoCounterForKeyHashOCERT,
    PraosParams,
    PraosState,
    VRFKeyBadProof,
    VRFKeyUnknown,
    VRFKeyWrongVRFKey,
    VRFLeaderValueTooBig,
    tick,
    update,
)
from ouroboros_consensus_tpu.protocol.views import hash_key
from ouroboros_consensus_tpu.testing import fixtures as fx

# small test params: short epochs, generous f so leadership is common
PARAMS = PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=50,
    kes_depth=6,
)

POOLS = [fx.make_pool(i) for i in range(3)]
LV = fx.make_ledger_view(POOLS)


def _update_at(hv, state=PraosState(), params=PARAMS, lv=LV):
    ticked = tick(params, lv, hv.slot, state)
    return update(params, hv, hv.slot, ticked)


def _leader_in(slots, epoch_nonce):
    """First (pool, slot) actually winning the VRF lottery — leadership is
    probabilistic, so tests must search rather than assume."""
    for slot in slots:
        pool = fx.find_leader(PARAMS, POOLS, LV, slot, epoch_nonce)
        if pool is not None:
            return pool, slot
    raise AssertionError("no leader found in slot range")


def test_update_happy_path_and_bookkeeping():
    st = PraosState(epoch_nonce=b"\x07" * 32)
    # need slot + stability(24) < epoch_end(50) so candidate still follows
    pool, slot = _leader_in(range(1, 26), st.epoch_nonce)
    hv = fx.forge_header_view(PARAMS, pool, slot, st.epoch_nonce, None, b"body-0")
    st2 = _update_at(hv, st)
    assert st2.last_slot == slot
    assert st2.ocert_counters[pool.pool_id] == 0
    # evolving nonce combined with this header's nonce value
    eta = nonces.vrf_nonce_value(hv.vrf_output)
    assert st2.evolving_nonce == eta  # neutral ⭒ eta = eta
    # slot + stability(24) < 50: within window -> candidate follows
    assert st2.candidate_nonce == st2.evolving_nonce
    assert st2.lab_nonce is None  # genesis prev-hash -> neutral


def test_candidate_nonce_freezes_near_epoch_end():
    st = PraosState(epoch_nonce=b"\x07" * 32, last_slot=30)
    # stability window = ceil(3*4 / (1/2)) = 24; slot >= 31: slot+24 >= 50 -> frozen
    pool, slot = _leader_in(range(31, 50), st.epoch_nonce)
    hv = fx.forge_header_view(PARAMS, pool, slot, st.epoch_nonce, b"\xaa" * 32)
    st2 = _update_at(hv, st)
    assert st2.candidate_nonce is None  # unchanged (was neutral)
    assert st2.evolving_nonce is not None
    assert st2.lab_nonce == b"\xaa" * 32


def test_tick_rotates_nonces_on_epoch_boundary():
    st = PraosState(
        last_slot=49,
        candidate_nonce=b"\x01" * 32,
        last_epoch_block_nonce=b"\x02" * 32,
        lab_nonce=b"\x03" * 32,
        epoch_nonce=b"\x09" * 32,
    )
    ticked = tick(PARAMS, LV, 55, st)  # slot 55 is epoch 1
    assert ticked.state.epoch_nonce == nonces.combine(b"\x01" * 32, b"\x02" * 32)
    assert ticked.state.last_epoch_block_nonce == b"\x03" * 32
    # same epoch: no rotation
    ticked2 = tick(PARAMS, LV, 49, replace(st, last_slot=48))
    assert ticked2.state.epoch_nonce == b"\x09" * 32


def test_error_taxonomy():
    pool = POOLS[0]
    nonce = b"\x07" * 32
    st = PraosState(epoch_nonce=nonce)
    hv = fx.forge_header_view(PARAMS, pool, 3, nonce, None, b"body")

    # KES period before ocert start
    bad = replace(hv, ocert=pool.make_ocert(0, 5))  # slot 3 -> period 0 < 5
    with pytest.raises(KESBeforeStartOCERT):
        _update_at(bad, st)

    # KES period beyond max evolutions
    far = fx.forge_header_view(PARAMS, pool, 100 * 63, nonce, None, b"body")
    bad = replace(far, ocert=pool.make_ocert(0, 0))
    with pytest.raises(KESAfterEndOCERT):
        _update_at(bad, st)

    # corrupt ocert cold-key signature
    oc = hv.ocert
    bad = replace(hv, ocert=replace(oc, sigma=bytes(64)))
    with pytest.raises(InvalidSignatureOCERT):
        _update_at(bad, st)

    # corrupt KES signature
    ks = bytearray(hv.kes_sig)
    ks[0] ^= 1
    with pytest.raises(InvalidKesSignatureOCERT):
        _update_at(replace(hv, kes_sig=bytes(ks)), st)

    # issuer not in pool distribution
    rogue = fx.make_pool(99)
    bad = fx.forge_header_view(PARAMS, rogue, 3, nonce, None, b"body")
    with pytest.raises(NoCounterForKeyHashOCERT):
        _update_at(bad, st)
    # ...unless it has a counter already (then it fails later, at the VRF)
    st_known = replace(st, ocert_counters={rogue.pool_id: 0})
    with pytest.raises(VRFKeyUnknown):
        _update_at(bad, st_known)

    # registered VRF key hash mismatch (header carries another pool's VRF vk)
    bad = replace(hv, vrf_vk=POOLS[1].vrf_vk)
    with pytest.raises(VRFKeyWrongVRFKey):
        _update_at(bad, st)

    # bad VRF proof
    pf = bytearray(hv.vrf_proof)
    pf[3] ^= 4
    with pytest.raises(VRFKeyBadProof):
        _update_at(replace(hv, vrf_proof=bytes(pf)), st)

    # wrong epoch nonce in state => proof doesn't match
    with pytest.raises(VRFKeyBadProof):
        _update_at(hv, replace(st, epoch_nonce=b"\x08" * 32))

    # counter rules
    st_high = replace(st, ocert_counters={pool.pool_id: 5})
    with pytest.raises(CounterTooSmallOCERT):
        _update_at(hv, st_high)  # header counter 0 < last 5
    bad = fx.forge_header_view(PARAMS, pool, 3, nonce, None, b"body", ocert_counter=7)
    with pytest.raises(CounterOverIncrementedOCERT):
        _update_at(bad, st_high)  # 7 > 5+1

    # leader value too big: tiny stake + tiny f
    lv_tiny = fx.make_ledger_view(POOLS, [Fraction(1, 10**12)] * 3)
    params_tiny = replace(PARAMS, active_slot_coeff=Fraction(1, 10**6))
    with pytest.raises(VRFLeaderValueTooBig):
        ticked = tick(params_tiny, lv_tiny, hv.slot, st)
        update(params_tiny, hv, hv.slot, ticked)


def test_check_is_leader_agrees_with_validation():
    pool = POOLS[0]
    nonce = b"\x05" * 32
    st = PraosState(epoch_nonce=nonce)
    cbl = fx.can_be_leader(pool)
    hits = 0
    for slot in range(40):
        ticked = tick(PARAMS, LV, slot, st)
        res = praos.check_is_leader(PARAMS, cbl, slot, ticked)
        if res is None:
            continue
        hits += 1
        hv = fx.forge_header_view(PARAMS, pool, slot, nonce, None, b"b")
        assert hv.vrf_output == res.vrf_output
        _update_at(hv, st)  # must validate
    # f = 1/2, sigma = 1/3: expect ~1-(1/2)^(1/3) ≈ 20% of 40 slots
    assert hits >= 2


def test_sequential_chain_multi_epoch():
    """Batch-of-1 spec run: a 3-epoch chain with per-epoch nonce evolution."""
    st = PraosState()
    prev_hash = None
    counters = {}
    forged = 0
    for slot in range(0, 140):  # crosses epochs at 50 and 100
        ticked = tick(PARAMS, LV, slot, st)
        pool = fx.find_leader(
            PARAMS, POOLS, LV, slot, ticked.state.epoch_nonce
        )
        if pool is None:
            continue
        n = counters.get(pool.pool_id, 0)
        hv = fx.forge_header_view(
            PARAMS, pool, slot, ticked.state.epoch_nonce, prev_hash,
            b"body-%d" % slot, ocert_counter=n,
        )
        st = update(PARAMS, hv, slot, ticked)
        counters[pool.pool_id] = n
        prev_hash = bytes(32)  # placeholder header hash
        forged += 1
    assert forged > 100 * PARAMS.active_slot_coeff  # sanity: chain is dense
    assert st.last_slot > 100  # reached the third epoch
    assert st.epoch_nonce is not None
    assert all(c == 0 for c in st.ocert_counters.values())
