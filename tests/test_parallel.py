"""Multi-chip SPMD validation on the virtual 8-device CPU mesh.

Checks that sharding the staged batch over a Mesh produces the same
verdicts and first-failure index as the single-device fused kernel.
"""

from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

import jax

from ouroboros_consensus_tpu.parallel import spmd
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=10_000,  # one epoch: batch spans a single nonce
    kes_depth=3,
)

NONCE = b"\x07" * 32


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture(scope="module")
def chain(pools, lview):
    hvs = []
    prev = None
    slot = 1
    while len(hvs) < 11:  # deliberately NOT divisible by 8: exercises padding
        pool = fixtures.find_leader(PARAMS, pools, lview, slot, NONCE)
        if pool is not None:
            hvs.append(
                fixtures.forge_header_view(
                    PARAMS, pool, slot=slot, epoch_nonce=NONCE,
                    prev_hash=prev, body_bytes=b"body-%d" % len(hvs),
                )
            )
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    return hvs


def _stage(lview, hvs):
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    return pbatch.stage(PARAMS, lview, NONCE, hvs, pre.kes_evolution)


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.slow
def test_sharded_matches_single_device(lview, chain):
    batch = _stage(lview, chain)
    ref = pbatch.run_batch(batch)
    mesh = spmd.make_mesh()
    v, first_bad, n_ok = spmd.sharded_run_batch(batch, mesh)
    for a, b in zip(v, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert first_bad is None
    assert n_ok >= len(chain)  # pad lanes replicate a valid lane


@pytest.mark.slow
def test_sharded_detects_first_failure(lview, chain):
    bad = list(chain)
    # corrupt the KES signature of the header at position 5
    ks = bytearray(bad[5].kes_sig)
    ks[0] ^= 0xFF
    bad[5] = replace(bad[5], kes_sig=bytes(ks))
    batch = _stage(lview, bad)
    mesh = spmd.make_mesh()
    v, first_bad, _ = spmd.sharded_run_batch(batch, mesh)
    assert first_bad == 5
    assert not v.ok_kes_sig[5]
    assert v.ok_kes_sig[4] and v.ok_kes_sig[6]


def _fake_sharded_verify(mesh, n_real, *cols):
    """Host-side stand-in for the jit-of-shard_map program: all lanes
    valid. Lets the per-shard TELEMETRY contract run in tier-1 without
    the fused compile (the verdict parity tests above cover the real
    program)."""
    b = cols[0].shape[0]
    ones = np.ones(b, bool)
    v = pbatch.Verdicts(
        ones, ones, ones, ones, np.zeros(b, bool),
        np.zeros((b, 32), np.uint8), np.zeros((b, 32), np.uint8),
    )
    return v, np.int32(np.iinfo(np.int32).max), np.int32(int(n_real))


def test_shard_span_event_sequence(lview, chain, monkeypatch):
    """Round-11 per-shard telemetry: one ShardSpan per mesh position
    per sharded dispatch, shard-ordered, with exact lane/pad/popcount
    accounting over the bucket-padded batch (8-device virtual mesh)."""
    from ouroboros_consensus_tpu.utils import trace as T

    monkeypatch.setattr(spmd, "_sharded_verify", _fake_sharded_verify)
    batch = _stage(lview, chain)
    lt = T.ListTracer()
    pbatch.set_batch_tracer(lt)
    try:
        v, first_bad, n_ok = spmd.sharded_run_batch(batch, spmd.make_mesh())
        # a second dispatch advances the sequence number
        spmd.sharded_run_batch(batch, spmd.make_mesh())
    finally:
        pbatch.set_batch_tracer(None)
    assert first_bad is None and n_ok == len(chain)
    spans = [e for e in lt.events if isinstance(e, T.ShardSpan)]
    assert len(spans) == 16  # 8 shards x 2 dispatches
    first, second = spans[:8], spans[8:]
    assert [s.shard for s in first] == list(range(8))
    assert len({s.index for s in first}) == 1
    assert {s.index for s in second} != {s.index for s in first}
    # exact accounting: real lanes sum to the true batch size, pads
    # fill the bucket, every real lane of this all-valid chain is ok
    assert sum(s.lanes_real for s in first) == len(chain)
    assert sum(s.lanes for s in first) == sum(
        s.lanes_real + s.pad_lanes for s in first
    )
    assert all(s.n_ok == s.lanes_real for s in first)
    assert all(s.wall_s >= 0.0 for s in first)
    # shard-local lane counts are uniform (pad_batch divisibility)
    assert len({s.lanes for s in first}) == 1


def test_shard_spans_silent_without_tracer(lview, chain, monkeypatch):
    """BATCH_TRACER=None: the sharded hot path emits nothing and the
    sequence number does not advance (zero overhead untraced)."""
    monkeypatch.setattr(spmd, "_sharded_verify", _fake_sharded_verify)
    batch = _stage(lview, chain)
    seq_before = spmd._SHARD_SEQ
    assert pbatch.BATCH_TRACER is None
    spmd.sharded_run_batch(batch, spmd.make_mesh())
    assert spmd._SHARD_SEQ == seq_before


def test_multichip_shaped_ledger_record(lview, chain, monkeypatch, tmp_path):
    """The round-11 acceptance shape: a MULTICHIP-style run (sharded
    dispatch with the recorder installed, dryrun_multichip's banking
    path) appends ONE ledger record whose metrics snapshot carries the
    per-shard span telemetry."""
    from ouroboros_consensus_tpu import obs
    from ouroboros_consensus_tpu.obs import ledger

    monkeypatch.setattr(spmd, "_sharded_verify", _fake_sharded_verify)
    monkeypatch.setenv("OCT_LEDGER", str(tmp_path / "ledger"))
    obs.reset_for_tests()
    rec = obs.install()
    try:
        batch = _stage(lview, chain)
        v, first_bad, n_ok = spmd.sharded_run_batch(batch, spmd.make_mesh())
        assert first_bad is None
        out = ledger.record_replay(
            "multichip", recorder=rec,
            config={"n_devices": 8},
            result={"headers": len(chain), "n_devices": 8},
        )
    finally:
        obs.uninstall()
        obs.reset_for_tests()
    assert out is not None
    runs = ledger.read_runs(str(tmp_path / "ledger"), kind="multichip")
    assert len(runs) == 1
    rec_d = runs[0]
    assert ledger.validate_record(rec_d) == []
    metrics = rec_d["metrics"]
    for fam in ("oct_shard_windows_total", "oct_shard_lanes_total",
                "oct_shard_ok_lanes_total", "oct_shard_pad_lanes_total"):
        samples = metrics[fam]["samples"]
        assert {s["labels"]["shard"] for s in samples} == {
            str(i) for i in range(8)
        }
    lanes_total = sum(
        s["value"] for s in metrics["oct_shard_lanes_total"]["samples"]
    )
    assert lanes_total == len(chain)


def test_pad_batch_roundtrip(lview, chain):
    batch = _stage(lview, chain)
    padded, b = spmd.pad_batch(batch, 8)
    assert b == len(chain)
    assert padded.beta.shape[0] % 8 == 0
    np.testing.assert_array_equal(padded.beta[:b], batch.beta)
    # pad lanes replicate lane 0
    np.testing.assert_array_equal(padded.beta[b:], np.repeat(batch.beta[:1], padded.beta.shape[0] - b, axis=0))


@pytest.mark.slow
def test_sharded_backend_through_db_analyser(tmp_path, lview, pools):
    """The PRODUCTION sharded path (VERDICT r2 item 3): synthesize an
    on-disk chain crossing epoch boundaries, then run the real
    db-analyser revalidation with backend="sharded" — epoch-segmented
    staging, batch axis sharded over the 8-device mesh, psum/pmin
    verdict collectives — and require the exact host-fold result."""
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    # epoch_length 24 with f=1/2 gives ~12-block segments -> the SAME
    # 16-lane bucket the other tests compile, so this e2e adds no extra
    # jit-of-shard_map compile (one mega-compile per bucket shape)
    params = replace(PARAMS, epoch_length=24, security_param=2)
    path = str(tmp_path / "chain")
    res = synth.synthesize(
        path, params, pools, lview, synth.ForgeLimit(slots=72),
    )
    assert res.n_blocks > 25  # ~36 expected at f=1/2

    host = ana.revalidate(path, params, lview, backend="host")
    assert host.error is None and host.n_valid == res.n_blocks

    sharded = ana.revalidate(path, params, lview, backend="sharded")
    assert sharded.error is None
    assert sharded.n_valid == res.n_blocks
    assert sharded.final_state.evolving_nonce == host.final_state.evolving_nonce
    assert sharded.final_state.epoch_nonce == host.final_state.epoch_nonce
    assert (
        sharded.final_state.ocert_counters == host.final_state.ocert_counters
    )


@pytest.mark.skipif(
    not __import__("os").environ.get("OCT_SLOW_TESTS"),
    reason="10k-header sharded replay + two fused compiles on XLA:CPU; "
    "set OCT_SLOW_TESTS=1 (default-run scale coverage: "
    "__graft_entry__.dryrun_multichip stage 3 at 2048 headers)",
)
def test_sharded_replay_at_scale(tmp_path):
    """VERDICT r3 item 8: a >=10k-block on-disk chain through the
    8-device sharded backend, with 1-device-vs-8-device throughput
    recorded (the scaling shape; absolute numbers are virtual-CPU)."""
    import time

    from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

    params = praos.PraosParams(
        slots_per_kes_period=2000,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1),
        epoch_length=100_000,
        kes_depth=3,
    )
    pools_ = [fixtures.make_pool(0, kes_depth=3)]
    lview_ = fixtures.make_ledger_view(pools_)
    n = 10_000
    fr = db_synthesizer.synthesize(
        str(tmp_path / "db"), params, pools_, lview_,
        db_synthesizer.ForgeLimit(blocks=n), chunk_size=4096,
    )
    assert fr.n_blocks == n

    rates = {}
    for n_dev in (1, 8):
        mesh = spmd.make_mesh(jax.devices()[:n_dev])
        # go through validate_chain's sharded path with an explicit mesh
        imm = db_analyser.open_immutable(str(tmp_path / "db"))
        res_acc = db_analyser.ValidationResult()
        hvs = list(db_analyser._stream_views(imm, res_acc))
        t0 = time.time()
        result = pbatch.validate_chain(
            params, lambda _e: lview_, praos.PraosState(), hvs,
            backend="sharded", mesh=mesh, max_batch=2048,
        )
        dt = time.time() - t0
        assert result.error is None, repr(result.error)
        assert result.n_valid == n
        rates[n_dev] = n / dt
    # record the scaling shape for PERF.md (stdout shows under -s)
    print(f"sharded replay scaling: {rates}")


@pytest.mark.skipif(
    not __import__("os").environ.get("OCT_SLOW_TESTS"),
    reason="≥64k-header sharded replay on XLA:CPU (VERDICT r5 item 5); "
    "set OCT_SLOW_TESTS=1 (OCT_MULTICHIP_HEADERS scales the size)",
)
def test_cross_shard_first_failure_at_scale(tmp_path):
    """VERDICT r5 item 5: at ≥64k headers, the cross-shard first-failure
    index (pmin over global lane positions) must equal the sequential
    first failure — same valid-prefix length, same error class — with
    the corrupted lane landing mid-chain on a non-zero shard."""
    import os

    from dataclasses import replace as dreplace

    from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

    n = int(os.environ.get("OCT_MULTICHIP_HEADERS", "65536")) or 65536
    params = praos.PraosParams(
        slots_per_kes_period=2000,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1),
        epoch_length=1_000_000,
        kes_depth=3,
    )
    pools_ = [fixtures.make_pool(0, kes_depth=3)]
    lview_ = fixtures.make_ledger_view(pools_)
    fr = db_synthesizer.synthesize(
        str(tmp_path / "db"), params, pools_, lview_,
        db_synthesizer.ForgeLimit(blocks=n), chunk_size=8192,
    )
    assert fr.n_blocks == n
    imm = db_analyser.open_immutable(str(tmp_path / "db"))
    res_acc = db_analyser.ValidationResult()
    hvs = list(db_analyser._stream_views(imm, res_acc))
    bad = (3 * n) // 4 + 1  # mid-shard, non-zero shard at every batch size
    sig = bytearray(hvs[bad].kes_sig)
    sig[1] ^= 1
    hvs[bad] = dreplace(hvs[bad], kes_sig=bytes(sig))

    seq = pbatch.validate_chain(
        params, lambda _e: lview_, praos.PraosState(), hvs,
        backend="native", max_batch=8192,
    )
    assert seq.n_valid == bad
    assert isinstance(seq.error, praos.InvalidKesSignatureOCERT)

    sharded = pbatch.validate_chain(
        params, lambda _e: lview_, praos.PraosState(), hvs,
        backend="sharded", mesh=spmd.make_mesh(), max_batch=8192,
    )
    assert sharded.n_valid == seq.n_valid
    assert type(sharded.error) is type(seq.error)
    assert vars(sharded.error) == vars(seq.error)
