"""BlockSupportsMetrics + NodeMetrics.

Reference: Block/SupportsMetrics.hs (isSelfIssued) and the NodeKernel's
metric reporting (NodeKernel.hs:88-114).
"""

from fractions import Fraction

from ouroboros_consensus_tpu.block.metrics import NodeMetrics, is_self_issued

from tests.test_hotkey import _mk_kernel  # same tiny-node fixture


def test_is_self_issued(tmp_path):
    kernel = _mk_kernel(tmp_path)
    blk = kernel.forge_only(1)
    assert is_self_issued(blk.header, kernel.pool.vk_cold)
    assert not is_self_issued(blk.header, b"\x00" * 32)
    assert not is_self_issued(blk.header, None)


def test_kernel_metrics_counts(tmp_path):
    kernel = _mk_kernel(tmp_path)
    assert kernel.try_forge(1) is not None
    assert kernel.try_forge(3) is not None
    m = kernel.metrics
    assert m.slots_led == 2
    assert m.blocks_forged == 2
    assert m.blocks_adopted_self == 2
    assert m.blocks_adopted_peer == 0
    # KES expiry at period 2 (max_evolutions=2) is CannotForge
    assert kernel.forge_only(5) is None
    assert m.blocks_could_not_forge == 1
