"""Differential test: batched device ECVRF verify vs host reference."""

import random

import numpy as np
import pytest

from ouroboros_consensus_tpu.ops import ecvrf_batch as vb
from ouroboros_consensus_tpu.ops.host import ecvrf as hv
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.host import hashes


# ~60 s on the 1-core box EVERY run (the limb-wise XLA:CPU graph's
# EXECUTION, not its compile — the persistent cache cannot help), so
# this XLA-twin differential rides the slow tier since round 8, like
# the PR-1 device-twin family. The same curve/hash math stays
# differentially covered inline by the pk-kernel suites
# (test_pk_verify / test_sign_kernels) and the native-backend folds.
@pytest.mark.slow
def test_ecvrf_batch_mixed():
    rng = random.Random(11)
    pks, proofs, alphas, want = [], [], [], []

    # valid proofs over Praos-shaped alphas (InputVRF)
    for slot in (1, 77, 4096):
        seed = bytes(rng.randrange(256) for _ in range(32))
        pk = he.secret_to_public(seed)
        alpha = hashes.input_vrf(slot, b"\x42" * 32)
        pi = hv.prove(seed, alpha)
        assert hv.verify(pk, pi, alpha) is not None
        pks.append(pk); proofs.append(pi); alphas.append(alpha); want.append(True)

    # corrupted gamma
    seed = bytes(rng.randrange(256) for _ in range(32))
    pk = he.secret_to_public(seed)
    alpha = hashes.input_vrf(5, b"\x01" * 32)
    pi = bytearray(hv.prove(seed, alpha))
    pi[2] ^= 0x10
    pks.append(pk); proofs.append(bytes(pi)); alphas.append(alpha); want.append(False)

    # corrupted c
    pi = bytearray(hv.prove(seed, alpha))
    pi[33] ^= 0x01
    pks.append(pk); proofs.append(bytes(pi)); alphas.append(alpha); want.append(False)

    # corrupted s
    pi = bytearray(hv.prove(seed, alpha))
    pi[50] ^= 0x80
    pks.append(pk); proofs.append(bytes(pi)); alphas.append(alpha); want.append(False)

    # wrong alpha
    pi = hv.prove(seed, alpha)
    wrong = hashes.input_vrf(6, b"\x01" * 32)
    pks.append(pk); proofs.append(pi); alphas.append(wrong); want.append(False)

    # non-canonical s (s + L)
    pi = hv.prove(seed, alpha)
    s = int.from_bytes(pi[48:], "little")
    pi_nc = pi[:48] + int.to_bytes(s + he.L, 32, "little")
    pks.append(pk); proofs.append(pi_nc); alphas.append(alpha); want.append(False)

    # host agrees with expectations
    for pk_, pi_, al_, w_ in zip(pks, proofs, alphas, want):
        assert (hv.verify(pk_, pi_, al_) is not None) == w_

    ok, beta = vb.verify_batch(pks, proofs, alphas)
    assert list(ok) == want
    # beta matches host proof_to_hash on the valid lanes
    for i, w_ in enumerate(want):
        if w_:
            assert bytes(beta[i]) == hv.proof_to_hash(proofs[i])
