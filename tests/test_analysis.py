"""octlint tier-1 gate: AST jit-safety rules + jaxpr pathology budgets.

Three layers:
  1. fixture coverage — every rule fires on its purpose-built positive
     and honors its suppression (tests/lint_fixtures/case_rules.py);
  2. the package gate — zero unsuppressed findings on the package
     itself (the CI enforcement of Pass 1);
  3. the graph gate — synthetic-jaxpr metric sanity, the GOLDEN
     chain-depth pin of the composed `verify_praos_core` at its
     post-remediation value, and every registered graph under its
     `analysis/budgets.json` ceiling (full sweep in the slow tier).
"""

import json
import os

import pytest

from ouroboros_consensus_tpu.analysis import astlint, graphs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ouroboros_consensus_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


# ---------------------------------------------------------------------------
# Pass 1 — fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fixture_findings():
    return astlint.lint_paths([os.path.join(FIXTURES, "case_rules.py")],
                              rel_to=FIXTURES)


@pytest.mark.parametrize("rule", sorted(astlint.RULES))
def test_each_rule_fires_and_suppresses(fixture_findings, rule):
    fired = [f for f in fixture_findings if f.rule == rule]
    assert any(not f.suppressed for f in fired), \
        f"{rule} positive fixture did not fire"
    assert any(f.suppressed for f in fired), \
        f"{rule} suppressed fixture was not recorded as suppressed"


def test_every_rule_represented_in_fixtures(fixture_findings):
    # all six rules, incl. the OCT106 stale-suppression audit
    assert {f.rule for f in fixture_findings} == set(astlint.RULES)


def test_clean_fixture_lines_not_flagged(fixture_findings):
    flagged = {(f.rule, f.line) for f in fixture_findings}
    src = open(os.path.join(FIXTURES, "case_rules.py")).read().splitlines()
    # the dtype-wrapped literal and the released-lock await are clean
    for marker in ("jnp.uint32(0xFFFFFFFF)", "lock released: NOT a finding"):
        line = next(i for i, l in enumerate(src, 1) if marker in l)
        assert not any(ln == line for _, ln in flagged), marker


def test_suppression_scopes():
    src = (
        "import jax, jax.numpy as jnp\n"
        "# octlint: disable-file=OCT104\n"
        "@jax.jit\n"
        "def f(x):  # octlint: disable=OCT102\n"
        "    y = jnp.sum(x)\n"
        "    if y:\n"
        "        return x & 0xFFFFFFFF\n"
        "    return float(y)\n"
    )
    found = astlint.lint_source(src, "scopes")
    by_rule = {f.rule: f for f in found}
    assert by_rule["OCT104"].suppressed  # file-level
    assert by_rule["OCT102"].suppressed  # def-line level
    assert not by_rule["OCT101"].suppressed  # untouched


def test_finding_key_is_line_stable():
    src = ("import jax, jax.numpy as jnp\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return float(jnp.sum(x))\n")
    shifted = "# a new comment line\n" + src
    k1 = [f.key() for f in astlint.lint_source(src, "mod")]
    k2 = [f.key() for f in astlint.lint_source(shifted, "mod")]
    assert k1 and k1 == k2


# ---------------------------------------------------------------------------
# Pass 1 — the package gate
# ---------------------------------------------------------------------------


def test_package_has_no_unsuppressed_findings():
    findings = astlint.lint_paths([PKG], rel_to=REPO)
    active = [f.format() for f in findings if not f.suppressed]
    assert active == [], "\n".join(active)
    # the reviewed exceptions stay visible, not silently absent
    assert any(f.suppressed for f in findings)


def test_baseline_entries_match_current_findings():
    """Every grandfathered key must still fire (else the ratchet file
    is stale) and the file must parse."""
    path = os.path.join(PKG, "analysis", "baseline.json")
    with open(path, encoding="utf-8") as f:
        baseline = set(json.load(f).get("findings", []))
    findings = astlint.lint_paths([PKG], rel_to=REPO)
    current = {f.key() for f in findings if not f.suppressed}
    assert baseline <= current, f"stale baseline entries: {baseline - current}"


# ---------------------------------------------------------------------------
# Pass 2 — synthetic metric sanity
# ---------------------------------------------------------------------------


def _trace(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def test_chain_depth_counts_sequential_muls():
    import jax
    from jax import numpy as jnp

    def chain(x):
        for _ in range(5):
            x = x * x
        return x

    r = graphs.analyze_jaxpr(
        _trace(chain, jax.ShapeDtypeStruct((4,), jnp.float32)), "chain"
    )
    assert r.mul_chain_depth == 5
    assert r.mul_count == 5


def test_fori_loop_fences_the_chain():
    import jax
    from jax import lax, numpy as jnp

    def fenced(x):
        x = x * x  # depth 1 outside the loop
        x = lax.fori_loop(0, 100, lambda _, v: v * v, x)
        return x * x  # depth 1 after the fence

    r = graphs.analyze_jaxpr(
        _trace(fenced, jax.ShapeDtypeStruct((4,), jnp.float32)), "fenced"
    )
    # the loop body is a separate computation: the unrolled chain never
    # exceeds the body's own depth + the unfenced prologue/epilogue
    assert r.mul_chain_depth <= 3
    assert r.computations >= 2


def test_fanout_and_width_metrics():
    import jax
    from jax import numpy as jnp

    def wide(x):
        parts = [x + i for i in range(7)]  # x consumed 7 times
        return sum(parts)

    r = graphs.analyze_jaxpr(
        _trace(wide, jax.ShapeDtypeStruct((4,), jnp.float32)), "wide"
    )
    assert r.op_fanout >= 7
    assert r.remat_width >= 7


def test_budget_check_flags_over_and_missing():
    rep = graphs.GraphReport("g", eqns=10, mul_count=5, mul_chain_depth=50,
                             op_fanout=3, remat_width=4, computations=1)
    budgets = {"graphs": {"g": {"mul_chain_depth": 40}}}
    assert graphs.check_budgets([rep], budgets) == [
        "g: mul_chain_depth = 50 exceeds budget 40"
    ]
    assert graphs.check_budgets([rep], {"graphs": {}})  # missing entry fails


# ---------------------------------------------------------------------------
# Pass 2 — the real kernels
# ---------------------------------------------------------------------------

# Golden post-remediation value of the composed graph's longest
# unrolled multiply chain (pre-remediation: >900; ed_core alone was 451
# before the ops/pk/curve.py fencing). A change in either direction is
# a deliberate act: update this AND analysis/budgets.json together.
# Round 7: the BATCH-COMPATIBLE composed core (derived challenge +
# unchanged ladders/finish) lands on the SAME depth — the extra prep
# work (compress H + challenge SHA) is all fenced or non-multiplicative.
GOLDEN_COMPOSED_CHAIN_DEPTH = 114
GOLDEN_COMPOSED_BC_CHAIN_DEPTH = 114


@pytest.fixture(scope="module")
def composed_report():
    return graphs.analyze_jaxpr(
        graphs.trace_graph("verify_praos_core"), "verify_praos_core"
    )


def test_golden_composed_chain_depth(composed_report):
    assert composed_report.mul_chain_depth == GOLDEN_COMPOSED_CHAIN_DEPTH


@pytest.mark.slow
def test_golden_composed_bc_chain_depth():
    r = graphs.analyze_jaxpr(
        graphs.trace_graph("verify_praos_core_bc"), "verify_praos_core_bc"
    )
    assert r.mul_chain_depth == GOLDEN_COMPOSED_BC_CHAIN_DEPTH


def test_composed_graph_under_budget(composed_report):
    violations = graphs.check_budgets([composed_report])
    assert violations == [], violations
    # the fences actually exist: the composed graph must be many
    # computations, not one flat 355k-eqn program
    assert composed_report.computations > 100


def test_every_registered_graph_has_a_budget():
    budgets = graphs.load_budgets()
    missing = set(graphs.registered_graphs()) - set(budgets["graphs"])
    assert missing == set()


@pytest.mark.slow
def test_all_registered_graphs_under_budget():
    reports = graphs.analyze_registered()
    violations = graphs.check_budgets(reports)
    assert violations == [], violations
