"""DualByron ThreadNet: a PBFT network over the REAL Byron-class ledger
run in lock-step with its executable spec, under the deterministic Sim.

Reference: `byron-test/Test/ThreadNet/Byron.hs` (1,370 LoC) +
`Test/ThreadNet/DualByron.hs` — N nodes with real PBFT crypto and the
real ledger diffuse blocks over mini-protocol edges; a mid-run
delegation certificate moves a genesis key's signing rights, and the
network only stays live because forging AND validation both follow the
LEDGER-derived delegation map (PBftLedgerView from ByronLedger).
"""

from fractions import Fraction

from ouroboros_consensus_tpu.hardfork import byron_mock
from ouroboros_consensus_tpu.hardfork.byron_mock import ByronMockBlock, ByronMockHeader
from ouroboros_consensus_tpu.ledger import byron as byron_led
from ouroboros_consensus_tpu.ledger.byron import addr_of, make_dcert, make_tx
from ouroboros_consensus_tpu.ledger.byron_spec import DualByronLedger
from ouroboros_consensus_tpu.ledger.extended import ExtLedger
from ouroboros_consensus_tpu.miniprotocol import blockfetch, chainsync
from ouroboros_consensus_tpu.miniprotocol.chainsync import Candidate
from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock
from ouroboros_consensus_tpu.testing import refmodel
from ouroboros_consensus_tpu.ops.host import ed25519 as ed
from ouroboros_consensus_tpu.protocol.instances import PBftParams, PBftProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.sim import Channel, Sim, Sleep

N_NODES = 3
N_SLOTS = 30
K = 5
GK_SEEDS = [bytes([0x30 + i]) * 32 for i in range(N_NODES)]
GK_VKS = [ed.secret_to_public(s) for s in GK_SEEDS]
NEW_DELEGATE_SEED = b"\x4d" * 32
NEW_DELEGATE_VK = ed.secret_to_public(NEW_DELEGATE_SEED)
SPENDER = b"\x51" * 32
SPEND_ADDR = addr_of(ed.secret_to_public(SPENDER))
PP = byron_led.ByronPParams(min_fee_a=10, min_fee_b=0)
GENESIS = byron_led.ByronGenesis(
    pparams=PP, genesis_keys=tuple(GK_VKS), epoch_length=40,
    security_param=K, stability_window=10_000,
)
DCERT_SLOT = 9  # node-0 slot: the cert lands before node 0's NEXT turn


def _forge_fn(i):
    """Byron forging seam: sign with the key the LEDGER currently says
    holds genesis key i's rights (after the dcert lands, node 0 must
    switch to the new delegate key or every peer rejects its blocks)."""

    def fn(node, slot, block_no, prev_hash, _ticked, _is_leader, txs):
        dlg = node.chain_db.current_ledger().ledger_state.impl.delegation
        current = dlg[GK_VKS[i]]
        seed = NEW_DELEGATE_SEED if current == NEW_DELEGATE_VK else GK_SEEDS[i]
        return byron_mock.forge_block(
            seed, slot=slot, block_no=block_no, prev_hash=prev_hash,
            txs=txs,
        )

    return fn


def _mk_node(base, i):
    ledger = DualByronLedger(GENESIS)
    proto = PBftProtocol(
        PBftParams(
            num_genesis_keys=N_NODES,
            threshold=Fraction(1, 2),
            window=10,
            security_param=K,
        ),
        GK_VKS,
    )
    ext = ExtLedger(ledger, proto)
    genesis_st = ext.genesis(
        ledger.genesis_state([(SPEND_ADDR, 10_000)])
    )
    db = open_chaindb(
        f"{base}/node{i}", ext, genesis_st, K,
        decode_block=ByronMockBlock.from_bytes,
        check_integrity=lambda raw: ByronMockBlock.from_bytes(
            raw
        ).check_integrity(),
    )
    node = NodeKernel(
        f"node{i}", db, proto, ledger,
        pool=fixtures.make_pool(i, kes_depth=2),
        clock=SlotClock(1.0),
        forge_fn=_forge_fn(i),
        can_be_leader=i,  # PBFT: leadership = genesis key index
    )
    node.decode_header = ByronMockHeader.from_bytes
    return node


def _edge(sim, nodes, i, j, delay=0.05):
    server, client = nodes[i], nodes[j]
    cand = Candidate()
    client.candidates[f"node{i}"] = cand
    cs_req = Channel(delay=delay, name=f"cs-req-{i}{j}")
    cs_rsp = Channel(delay=delay, name=f"cs-rsp-{i}{j}")
    bf_req = Channel(delay=delay, name=f"bf-req-{i}{j}")
    bf_rsp = Channel(delay=delay, name=f"bf-rsp-{i}{j}")
    sim.spawn(chainsync.server(server.chain_db, cs_req, cs_rsp),
              f"cs-s-{i}{j}")
    sim.spawn(chainsync.client(client, f"node{i}", cs_rsp, cs_req, cand),
              f"cs-c-{i}{j}")
    sim.spawn(blockfetch.server(server.chain_db, bf_req, bf_rsp),
              f"bf-s-{i}{j}")
    sim.spawn(blockfetch.client(client, f"node{i}", bf_rsp, bf_req, cand),
              f"bf-c-{i}{j}")


def test_dual_byron_network_with_redelegation(tmp_path):
    sim = Sim()
    nodes = [_mk_node(str(tmp_path), i) for i in range(N_NODES)]
    for n in nodes:
        n.chain_db.runtime = sim
    for i in range(N_NODES):
        for j in range(N_NODES):
            if i != j:
                _edge(sim, nodes, i, j)
    for i, n in enumerate(nodes):
        sim.spawn(n.forging_loop(N_SLOTS), f"forge{i}")

    def injector():
        # a value-moving tx enters via node 1's mempool at slot 4
        yield Sleep(4.2)
        tx = make_tx(
            [(bytes(32), 0)],
            [(addr_of(b"\x99" * 32), 10_000 - PP.min_fee_a)],
            [SPENDER],
        )
        nodes[1].mempool.add_tx(tx)
        # genesis key 0 delegates to a fresh key at slot 9 (via node 2)
        yield Sleep(DCERT_SLOT - 4.2 + 0.2)
        cert = make_dcert(GK_SEEDS[0], NEW_DELEGATE_VK, epoch=0)
        nodes[2].mempool.add_tx(cert)

    sim.spawn(injector(), "tx-injector")
    sim.run(until=N_SLOTS + 5)

    chains = [list(n.chain_db.stream_all()) for n in nodes]
    hashes = [[b.hash_ for b in c] for c in chains]
    assert hashes[0] == hashes[1] == hashes[2], (
        f"no convergence: lens {[len(h) for h in hashes]}"
    )
    # PBFT round-robin cross-checked against the PURE reference model
    # (Ref/PBFT.hs role): all nodes up, threshold 1/2 of window 10 is
    # never hit by a 3-way rotation -> exactly one block per slot
    exp_len, _ = refmodel.pbft_ref_simulate(
        N_SLOTS, N_NODES, 10, Fraction(1, 2)
    )
    assert exp_len == N_SLOTS
    assert len(chains[0]) == exp_len, (len(chains[0]), exp_len)

    st = nodes[0].chain_db.current_ledger().ledger_state
    # the spend moved value through the REAL rules (fee collected)
    assert st.impl.fees == PP.min_fee_a
    assert st.spec.balances[addr_of(b"\x99" * 32)] == 10_000 - PP.min_fee_a
    # the delegation cert is live in the ledger-derived PBFT view
    assert st.impl.delegation[GK_VKS[0]] == NEW_DELEGATE_VK
    assert dict(st.spec.delegation)[GK_VKS[0]] == NEW_DELEGATE_VK

    # node 0's post-cert blocks are SIGNED BY THE DELEGATE key — and
    # were accepted by every peer (they are in the common chain)
    post = [
        b for b in chains[0]
        if b.slot > DCERT_SLOT + 1 and b.slot % N_NODES == 0
    ]
    assert post, "node 0 forged nothing after the cert"
    assert all(b.header.issuer_vk == NEW_DELEGATE_VK for b in post)
    # and its pre-cert blocks used the genesis key itself
    pre = [b for b in chains[0] if b.slot <= DCERT_SLOT and b.slot % N_NODES == 0]
    assert all(b.header.issuer_vk == GK_VKS[0] for b in pre)


def test_dual_byron_network_rejects_invalid_tx_gossip(tmp_path):
    """An invalid tx (bad witness) offered to a node's mempool is
    rejected by the REAL rules and never reaches a block."""
    import pytest

    sim = Sim()
    nodes = [_mk_node(str(tmp_path), i) for i in range(N_NODES)]
    for n in nodes:
        n.chain_db.runtime = sim
    for i in range(N_NODES):
        for j in range(N_NODES):
            if i != j:
                _edge(sim, nodes, i, j)
    for i, n in enumerate(nodes):
        sim.spawn(n.forging_loop(12), f"forge{i}")

    good = make_tx(
        [(bytes(32), 0)], [(addr_of(b"\x88" * 32), 10_000 - 10)], [SPENDER]
    )
    p = byron_led.decode_payload(good)
    vk, sig = p.witnesses[0]
    bad = byron_led.encode_tx(
        p.ins, p.outs, [(vk, sig[:-1] + bytes([sig[-1] ^ 1]))]
    )

    def injector():
        yield Sleep(3.2)
        with pytest.raises(byron_led.ByronInvalidWitness):
            nodes[0].mempool.add_tx(bad)

    sim.spawn(injector(), "bad-tx")
    sim.run(until=16)
    chains = [list(n.chain_db.stream_all()) for n in nodes]
    assert all(not b.txs for c in chains for b in c)
    assert chains[0] and [b.hash_ for b in chains[0]] == [
        b.hash_ for b in chains[1]
    ]


def test_dual_byron_network_across_schedules(tmp_path):
    """Seeded schedule exploration (SURVEY §5.2): the same Byron network
    converges to the same chain content under permuted task wakeups."""
    finals = []
    for seed in (None, 7, 131):
        sim = Sim(seed=seed)
        nodes = [_mk_node(str(tmp_path / f"s{seed}"), i)
                 for i in range(N_NODES)]
        for n in nodes:
            n.chain_db.runtime = sim
        for i in range(N_NODES):
            for j in range(N_NODES):
                if i != j:
                    _edge(sim, nodes, i, j)
        for i, n in enumerate(nodes):
            sim.spawn(n.forging_loop(12), f"forge{i}")
        sim.run(until=16)
        chains = [[b.hash_ for b in n.chain_db.stream_all()] for n in nodes]
        assert chains[0] == chains[1] == chains[2], f"seed {seed} diverged"
        assert len(chains[0]) >= 10, (seed, len(chains[0]))
        finals.append(len(chains[0]))
    # deterministic round-robin layout: every schedule yields the same
    # chain LENGTH (content differs only in signature bytes timing-free)
    assert len(set(finals)) == 1, finals


def test_dual_byron_node_restart_with_snapshot_recovery(tmp_path):
    """A Byron-net node is killed mid-run and reopened with FULL
    revalidation (the crash-marker policy): the LedgerDB writes and
    restores DUAL-BYRON snapshots (impl + spec states through the
    tagged codec), the reopened node revalidates the real txs, and the
    network reconverges."""
    sim = Sim()
    nodes = [_mk_node(str(tmp_path), i) for i in range(N_NODES)]
    for n in nodes:
        n.chain_db.runtime = sim
    for i in range(N_NODES):
        for j in range(N_NODES):
            if i != j:
                _edge(sim, nodes, i, j)
    # node 2 only forges in round one; 0 and 1 carry the chain so the
    # network keeps growing while 2 is down
    for i, n in enumerate(nodes):
        sim.spawn(n.forging_loop(10), f"forge{i}")

    def spend():
        yield Sleep(2.2)
        nodes[0].mempool.add_tx(make_tx(
            [(bytes(32), 0)],
            [(addr_of(b"\x77" * 32), 10_000 - PP.min_fee_a)],
            [SPENDER],
        ))

    sim.spawn(spend(), "spend")
    sim.run(until=10)
    len_before = len(list(nodes[2].chain_db.stream_all()))
    assert len_before >= 8

    # kill node 2 (all its edge tasks share the Sim; closing the db is
    # the crash — no clean marker is written)
    nodes[2].chain_db.close()

    # reopen with full revalidation: the init path reads the newest
    # DUAL-BYRON snapshot and replays the chain through the real rules
    n2 = _mk_node(str(tmp_path), 2)
    n2.chain_db.runtime = sim
    assert len(list(n2.chain_db.stream_all())) == len_before
    st = n2.chain_db.current_ledger().ledger_state
    assert st.spec.balances[addr_of(b"\x77" * 32)] == 10_000 - PP.min_fee_a

    # rejoin the network: fresh edges, second forging round
    nodes[2] = n2
    for i in range(N_NODES):
        for j in range(N_NODES):
            if i != j and 2 in (i, j):
                _edge(sim, nodes, i, j)
    for i, n in enumerate(nodes):
        sim.spawn(n.forging_loop(20, start_slot=10), f"forge2-{i}")
    sim.run(until=24)
    chains = [[b.hash_ for b in n.chain_db.stream_all()] for n in nodes]
    assert chains[0] == chains[1] == chains[2]
    assert len(chains[2]) > len_before


def test_pbft_window_violation_matches_ref_model(tmp_path):
    """Degenerate net where the PBFT signing window BINDS: only node 0
    forges (designated every 2nd slot with 2 genesis keys), so its
    share of the sliding window exceeds threshold*window after exactly
    tcount adopted blocks — the pure model predicts the capped chain
    length and the live net must match it (Ref/PBFT.hs:General.hs:479
    shape: expected fork/skip structure from the model, not a loose
    bound)."""
    window, threshold, n_keys, n_slots = 4, Fraction(1, 2), 2, 20
    exp_len, outcome = refmodel.pbft_ref_simulate(
        n_slots, n_keys, window, threshold,
        join_plan={1: n_slots + 1},  # node 1 never forges
    )
    # model sanity: cap = floor(threshold*window) = 2 blocks, then stall
    assert exp_len == 2 and outcome[0] == 0 and outcome[2] == 0

    proto_params = PBftParams(
        num_genesis_keys=n_keys, threshold=threshold, window=window,
        security_param=K,
    )

    def mk(base, i):
        ledger = DualByronLedger(GENESIS)
        proto = PBftProtocol(proto_params, GK_VKS[:n_keys])
        ext = ExtLedger(ledger, proto)
        genesis_st = ext.genesis(ledger.genesis_state([(SPEND_ADDR, 10_000)]))
        db = open_chaindb(
            f"{base}/wnode{i}", ext, genesis_st, K,
            decode_block=ByronMockBlock.from_bytes,
            check_integrity=lambda raw: ByronMockBlock.from_bytes(
                raw
            ).check_integrity(),
        )
        node = NodeKernel(
            f"wnode{i}", db, proto, ledger,
            pool=fixtures.make_pool(i, kes_depth=2),
            clock=SlotClock(1.0),
            forge_fn=_forge_fn(i),
            can_be_leader=i,
        )
        node.decode_header = ByronMockHeader.from_bytes
        return node

    sim = Sim()
    nodes = [mk(str(tmp_path), i) for i in range(2)]
    for n in nodes:
        n.chain_db.runtime = sim
    for i in range(2):
        for j in range(2):
            if i != j:
                _edge(sim, nodes, i, j)
    sim.spawn(nodes[0].forging_loop(n_slots), "forge0")  # node 1 silent
    sim.run(until=n_slots + 5)

    chains = [list(n.chain_db.stream_all()) for n in nodes]
    assert len(chains[0]) == exp_len, (len(chains[0]), exp_len)
    assert [b.hash_ for b in chains[0]] == [b.hash_ for b in chains[1]]
    # the adopted slots match the model's outcome list exactly
    model_slots = [s for s, o in enumerate(outcome) if o is not None]
    assert [b.slot for b in chains[0]] == model_slots
