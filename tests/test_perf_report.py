"""scripts/perf_report.py over the five CHECKED-IN bench rounds: the
trajectory report must identify r01 as the only device-banking round,
attribute r02–r05 to their recorded failure modes, render valid
markdown + JSON, fold a run ledger when one exists, and exit non-zero
under a configurable regression threshold (the future CI perf gate)."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "perf_report", os.path.join(REPO, "scripts", "perf_report.py")
)
perf_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_report)


@pytest.fixture(scope="module")
def report():
    return perf_report.build_report(REPO, threshold=None,
                                    require_device=False, ledger_dir="0")


def test_r01_is_the_only_device_banking_round(report):
    rounds = report["bench_rounds"]
    assert [r["round"] for r in rounds] == [1, 2, 3, 4, 5]
    banked = [r["round"] for r in rounds if r["device_banked"]]
    assert banked == [1]
    r01 = rounds[0]
    assert r01["value_per_s"] == pytest.approx(3985.7)
    assert r01["vs_baseline"] == pytest.approx(2.93)
    assert r01["failures"] == []


def test_dead_rounds_attributed_to_recorded_failure_modes(report):
    by_round = {r["round"]: r for r in report["bench_rounds"]}
    modes = {
        n: {f["mode"] for f in by_round[n]["failures"]} for n in (2, 3, 4, 5)
    }
    # r02 died at the driver wall while the backend probe hung
    assert "backend-probe-timeout" in modes[2]
    assert any(m.startswith("driver-timeout") for m in modes[2])
    # r03/r04: probe timeouts, clean fallback to the native number
    assert modes[3] == {"backend-probe-timeout"}
    assert modes[4] == {"backend-probe-timeout"}
    assert by_round[3]["native_baseline_per_s"] == pytest.approx(2007.0)
    # r05: axon-format AOT rejections + the attempt exceeding its wall
    assert "aot-cache-rejected" in modes[5]
    assert "warmup-exceeded-wall" in modes[5]
    assert by_round[5]["headers"] == 1_000_000


def test_markdown_and_json_render(report, tmp_path):
    md = perf_report.render_markdown(report)
    assert "r01" in md and "YES" in md
    assert "backend-probe-timeout" in md
    assert "aot-cache-rejected" in md
    # JSON round-trips strictly
    json.loads(json.dumps(report, allow_nan=False))
    assert report["multichip_rounds"], "MULTICHIP files must fold in"


def test_threshold_regression_verdict(report):
    verdicts = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=0.8, require_device=False
    )
    (v,) = verdicts
    assert not v["ok"]  # r05's 2484 native vs r01's 3985.7 device
    assert "r05" in v["detail"]
    ok = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=0.5, require_device=False
    )
    assert ok[0]["ok"]
    dv = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=None, require_device=True
    )
    assert not dv[0]["ok"]
    assert "banked NO device result" in dv[0]["detail"]


def test_threshold_fails_a_round_with_no_value_at_all(report):
    """The worst regression: the newest round produced NO measurable
    number (the r02 shape — driver kill before the JSON line). The
    threshold gate must fail it, not silently pass for lack of a
    number to compare."""
    rounds = [dict(r) for r in report["bench_rounds"]]
    rounds.append({
        "round": 6, "device_banked": False, "value_per_s": None,
        "failures": [{"mode": "driver-timeout (rc=137)",
                      "detail": "killed"}],
    })
    (v,) = perf_report.regression_verdicts(rounds, threshold=0.5,
                                           require_device=False)
    assert not v["ok"]
    assert "no measurable" in v["detail"]
    assert "driver-timeout" in v["detail"]


def test_threshold_with_no_prior_value_is_explicit_not_silent():
    """A configured threshold must always produce a verdict: with no
    previous round banking a value (or a single round), the rule says
    so explicitly instead of letting `all([])` go green unevaluated."""
    dead = {"round": 1, "device_banked": False, "value_per_s": None,
            "failures": []}
    live = {"round": 2, "device_banked": True, "value_per_s": 100.0,
            "failures": []}
    for rounds in ([live], [dead, dict(live, round=2)],
                   [dead, dict(dead, round=2)]):
        verdicts = perf_report.regression_verdicts(
            rounds, threshold=0.8, require_device=False
        )
        assert len(verdicts) == 1, rounds
        assert verdicts[0]["ok"]
        assert "nothing to compare" in verdicts[0]["detail"]


def test_cli_exit_codes_and_outputs(tmp_path):
    """The CI-gate contract: report-only exits 0; a tripped threshold
    exits 1; --json writes a parseable document."""
    jout = str(tmp_path / "report.json")
    mout = str(tmp_path / "report.md")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         "--dir", REPO, "--ledger", "0", "--json", jout, "--out", mout],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert p.returncode == 0, p.stderr
    with open(jout, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["ok"] and len(doc["bench_rounds"]) == 5
    assert os.path.getsize(mout) > 200
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         "--dir", REPO, "--ledger", "0", "--threshold", "0.8"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert p.returncode == 1, "a tripped threshold must exit non-zero"
    assert "REGRESSION" in p.stdout


def test_ledger_fold_reports_env_and_build_transitions(tmp_path,
                                                      monkeypatch):
    """The r01→r02 question answered by the ledger: consecutive bench
    records with different env/build facts surface as transitions."""
    from ouroboros_consensus_tpu.obs import ledger

    led = str(tmp_path / "led")
    monkeypatch.setenv("OCT_LEDGER", led)
    monkeypatch.setenv("OCT_VRF_AGG", "1")
    ledger.record_run("bench", result={"value": 3985.7},
                      build_id="pjrt-v8")
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    ledger.record_run("bench", result={"value": 2007.0,
                                       "device_unavailable": True},
                      build_id="pjrt-v9")
    sec = perf_report.ledger_section(led)
    assert sec["runs"] == 2 and sec["by_kind"] == {"bench": 2}
    (tr,) = sec["bench_transitions"]
    assert tr["changed"]["build_id"] == ["pjrt-v8", "pjrt-v9"]
    assert tr["changed"]["env"]["OCT_VRF_AGG"] == ["1", "0"]
    # and the full report folds it
    rep = perf_report.build_report(REPO, None, False, led)
    assert rep["ledger"]["runs"] == 2
    md = perf_report.render_markdown(rep)
    assert "OCT_VRF_AGG" in md
