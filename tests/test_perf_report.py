"""scripts/perf_report.py over the five CHECKED-IN bench rounds: the
trajectory report must identify r01 as the only device-banking round,
attribute r02–r05 to their recorded failure modes, render valid
markdown + JSON, fold a run ledger when one exists, and exit non-zero
under a configurable regression threshold (the future CI perf gate)."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "perf_report", os.path.join(REPO, "scripts", "perf_report.py")
)
perf_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_report)


@pytest.fixture(scope="module")
def report():
    return perf_report.build_report(REPO, threshold=None,
                                    require_device=False, ledger_dir="0")


def test_r01_is_the_only_device_banking_round(report):
    rounds = report["bench_rounds"]
    assert [r["round"] for r in rounds] == [1, 2, 3, 4, 5]
    banked = [r["round"] for r in rounds if r["device_banked"]]
    assert banked == [1]
    r01 = rounds[0]
    assert r01["value_per_s"] == pytest.approx(3985.7)
    assert r01["vs_baseline"] == pytest.approx(2.93)
    assert r01["failures"] == []


def test_dead_rounds_attributed_to_recorded_failure_modes(report):
    by_round = {r["round"]: r for r in report["bench_rounds"]}
    modes = {
        n: {f["mode"] for f in by_round[n]["failures"]} for n in (2, 3, 4, 5)
    }
    # r02 died at the driver wall while the backend probe hung
    assert "backend-probe-timeout" in modes[2]
    assert any(m.startswith("driver-timeout") for m in modes[2])
    # r03/r04: probe timeouts, clean fallback to the native number
    assert modes[3] == {"backend-probe-timeout"}
    assert modes[4] == {"backend-probe-timeout"}
    assert by_round[3]["native_baseline_per_s"] == pytest.approx(2007.0)
    # r05: axon-format AOT rejections + the attempt exceeding its wall
    assert "aot-cache-rejected" in modes[5]
    assert "warmup-exceeded-wall" in modes[5]
    assert by_round[5]["headers"] == 1_000_000


def test_markdown_and_json_render(report, tmp_path):
    md = perf_report.render_markdown(report)
    assert "r01" in md and "YES" in md
    assert "backend-probe-timeout" in md
    assert "aot-cache-rejected" in md
    # JSON round-trips strictly
    json.loads(json.dumps(report, allow_nan=False))
    assert report["multichip_rounds"], "MULTICHIP files must fold in"


def test_threshold_regression_verdict(report):
    verdicts = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=0.8, require_device=False
    )
    (v,) = verdicts
    assert not v["ok"]  # r05's 2484 native vs r01's 3985.7 device
    assert "r05" in v["detail"]
    ok = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=0.5, require_device=False
    )
    assert ok[0]["ok"]
    dv = perf_report.regression_verdicts(
        report["bench_rounds"], threshold=None, require_device=True
    )
    assert not dv[0]["ok"]
    assert "banked NO device result" in dv[0]["detail"]


def test_threshold_fails_a_round_with_no_value_at_all(report):
    """The worst regression: the newest round produced NO measurable
    number (the r02 shape — driver kill before the JSON line). The
    threshold gate must fail it, not silently pass for lack of a
    number to compare."""
    rounds = [dict(r) for r in report["bench_rounds"]]
    rounds.append({
        "round": 6, "device_banked": False, "value_per_s": None,
        "failures": [{"mode": "driver-timeout (rc=137)",
                      "detail": "killed"}],
    })
    (v,) = perf_report.regression_verdicts(rounds, threshold=0.5,
                                           require_device=False)
    assert not v["ok"]
    assert "no measurable" in v["detail"]
    assert "driver-timeout" in v["detail"]


def test_threshold_with_no_prior_value_is_explicit_not_silent():
    """A configured threshold must always produce a verdict: with no
    previous round banking a value (or a single round), the rule says
    so explicitly instead of letting `all([])` go green unevaluated."""
    dead = {"round": 1, "device_banked": False, "value_per_s": None,
            "failures": []}
    live = {"round": 2, "device_banked": True, "value_per_s": 100.0,
            "failures": []}
    for rounds in ([live], [dead, dict(live, round=2)],
                   [dead, dict(dead, round=2)]):
        verdicts = perf_report.regression_verdicts(
            rounds, threshold=0.8, require_device=False
        )
        assert len(verdicts) == 1, rounds
        assert verdicts[0]["ok"]
        assert "nothing to compare" in verdicts[0]["detail"]


def test_cli_exit_codes_and_outputs(tmp_path):
    """The CI-gate contract: report-only exits 0; a tripped threshold
    exits 1; --json writes a parseable document."""
    jout = str(tmp_path / "report.json")
    mout = str(tmp_path / "report.md")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         "--dir", REPO, "--ledger", "0", "--json", jout, "--out", mout],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert p.returncode == 0, p.stderr
    with open(jout, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["ok"] and len(doc["bench_rounds"]) == 5
    assert os.path.getsize(mout) > 200
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         "--dir", REPO, "--ledger", "0", "--threshold", "0.8"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert p.returncode == 1, "a tripped threshold must exit non-zero"
    assert "REGRESSION" in p.stdout


def test_ledger_fold_reports_env_and_build_transitions(tmp_path,
                                                      monkeypatch):
    """The r01→r02 question answered by the ledger: consecutive bench
    records with different env/build facts surface as transitions."""
    from ouroboros_consensus_tpu.obs import ledger

    led = str(tmp_path / "led")
    monkeypatch.setenv("OCT_LEDGER", led)
    monkeypatch.setenv("OCT_VRF_AGG", "1")
    ledger.record_run("bench", result={"value": 3985.7},
                      build_id="pjrt-v8")
    monkeypatch.setenv("OCT_VRF_AGG", "0")
    ledger.record_run("bench", result={"value": 2007.0,
                                       "device_unavailable": True},
                      build_id="pjrt-v9")
    sec = perf_report.ledger_section(led)
    assert sec["runs"] == 2 and sec["by_kind"] == {"bench": 2}
    (tr,) = sec["bench_transitions"]
    assert tr["changed"]["build_id"] == ["pjrt-v8", "pjrt-v9"]
    assert tr["changed"]["env"]["OCT_VRF_AGG"] == ["1", "0"]
    # and the full report folds it
    rep = perf_report.build_report(REPO, None, False, led)
    assert rep["ledger"]["runs"] == 2
    md = perf_report.render_markdown(rep)
    assert "OCT_VRF_AGG" in md


# ---------------------------------------------------------------------------
# round 10: structured probe classification + laddered rounds
# ---------------------------------------------------------------------------


def _write_round(tmp_path, n, parsed, tail="", rc=0):
    doc = {"rc": rc, "tail": tail, "parsed": parsed}
    p = os.path.join(tmp_path, f"BENCH_r{n:02d}.json")
    with open(p, "w") as f:
        json.dump(doc, f)
    return p


def test_structured_probe_verdict_classifies_distinctly(tmp_path):
    """bench.py now BANKS the probe verdict: probe-timeout vs
    driver-timeout vs run-death are separated structurally, no regex
    archaeology on the tail."""
    p = _write_round(
        tmp_path, 6,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "backend-probe-timeout",
         "probe": {"ok": False, "outcome": "backend-probe-timeout",
                   "attempts": [
                       {"outcome": "probe-timeout", "wall_s": 90.0},
                       {"outcome": "probe-timeout", "wall_s": 60.0},
                   ]}},
        tail="# device probe failed (attempt 2): probe timed out",
    )
    row = perf_report.analyze_bench_round(p)
    assert not row["device_banked"]
    modes = [f["mode"] for f in row["failures"]]
    assert modes[0] == "backend-probe-timeout"
    assert modes.count("backend-probe-timeout") == 1  # deduped vs regex
    # a probe that ANSWERED WRONGLY is a different failure class
    p2 = _write_round(
        tmp_path, 7,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "backend-probe-error",
         "probe": {"ok": False, "outcome": "backend-probe-error",
                   "attempts": [{"outcome": "probe-error",
                                 "wall_s": 3.0, "detail": "boom"}]}},
    )
    row2 = perf_report.analyze_bench_round(p2)
    assert [f["mode"] for f in row2["failures"]][0] == "backend-probe-error"
    # run-death after a GOOD probe classifies as the banked reason
    p3 = _write_round(
        tmp_path, 8,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "device-run-failed-or-wall",
         "probe": {"ok": True, "outcome": "ok", "attempts": []}},
    )
    row3 = perf_report.analyze_bench_round(p3)
    modes3 = [f["mode"] for f in row3["failures"]]
    assert "device-run-failed-or-wall" in modes3
    assert not any(m.startswith("backend-probe") for m in modes3)


def test_laddered_round_is_its_own_class(tmp_path):
    """A round that banked THROUGH the warm ladder renders as
    'laddered', not lumped with warmup deaths; a dead round with ladder
    events keeps its failure modes but notes the engagement."""
    ladder = [
        {"kind": "engaged", "rung": 1024, "target": 8192, "t": 1.0},
        {"kind": "bg-compile-started", "rung": 1024, "target": 8192,
         "t": 1.1},
        {"kind": "bg-compile-done", "rung": 1024, "target": 8192,
         "wall_s": 410.0, "t": 411.1},
        {"kind": "swap", "rung": 1024, "target": 8192, "t": 411.2},
    ]
    p = _write_round(
        tmp_path, 6,
        {"value": 4100.0, "vs_baseline": 2.1, "laddered": True,
         "metric": "end-to-end db-analyser revalidation of a "
                   "1000000-header synthetic Praos chain",
         "warmup_report": {"ladder": ladder, "stages": {},
                           "aot": {}, "refusals": []}},
    )
    row = perf_report.analyze_bench_round(p)
    assert row["device_banked"] and row["laddered"] and row["ladder_swapped"]
    assert row["failures"] == []
    assert row["warmup"]["ladder"] == 4
    report = {"bench_rounds": [row], "multichip_rounds": [],
              "ledger": None, "verdicts": [], "ok": True}
    md = perf_report.render_markdown(report)
    assert "laddered (swapped)" in md
    assert "## Laddered rounds" in md
    # dead-but-laddered: failure modes survive, engagement noted
    p2 = _write_round(
        tmp_path, 7,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "device-run-failed-or-wall",
         "warmup_report": {"ladder": ladder[:2], "stages": {},
                           "aot": {}, "refusals": []}},
        rc=124,
    )
    row2 = perf_report.analyze_bench_round(p2)
    assert not row2["device_banked"] and row2["laddered"]
    md2 = perf_report.render_markdown(
        {"bench_rounds": [row2], "multichip_rounds": [], "ledger": None,
         "verdicts": [], "ok": False})
    assert "warm ladder HAD engaged" in md2


def test_stalled_round_classifies_by_live_plane(tmp_path):
    """Round 11: a dead round with a banked stall dump (or whose
    heartbeat timeline's last word is stalled/dead) classifies as
    stalled@<phase> — distinct from probe-timeout and compile-wall."""
    p = _write_round(
        tmp_path, 6,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "device-run-failed-or-wall",
         "probe": {"ok": True, "outcome": "ok", "attempts": []},
         "stall_dump": {
             "phase": "dispatch", "age_s": 600.0, "budget_s": 240.0,
             "threads": {"MainThread-1": ["  File ...dispatch_batch"]},
         },
         "live_timeline": [
             {"t": 0.0, "attempt": 1, "state": "compiling"},
             {"t": 120.0, "attempt": 1, "state": "running",
              "phase": "dispatch", "headers": 81920, "age_s": 1.0},
             {"t": 700.0, "attempt": 1, "state": "stalled",
              "phase": "dispatch", "headers": 81920, "age_s": 600.0},
         ]},
        rc=124,
    )
    row = perf_report.analyze_bench_round(p)
    assert not row["device_banked"]
    modes = [f["mode"] for f in row["failures"]]
    assert modes[0] == "stalled@dispatch"
    assert row["stalled_phase"] == "dispatch"
    assert row["live_states"] == ["compiling", "running", "stalled"]
    assert not any(m.startswith("backend-probe") for m in modes)
    md = perf_report.render_markdown(
        {"bench_rounds": [row], "multichip_rounds": [], "ledger": None,
         "verdicts": [], "ok": False})
    assert "stalled@dispatch" in md

    # no dump, but the tailed timeline's last heartbeat says DEAD at
    # phase=materialize: still stalled@materialize, from the timeline
    p2 = _write_round(
        tmp_path, 7,
        {"value": 2100.0, "device_unavailable": True,
         "no_device_reason": "device-run-failed-or-wall",
         "live_timeline": [
             {"t": 0.0, "attempt": 1, "state": "running",
              "phase": "dispatch", "headers": 1000},
             {"t": 650.0, "attempt": 1, "state": "dead",
              "phase": "materialize", "headers": 81920, "age_s": 610.0},
         ]},
        rc=124,
    )
    row2 = perf_report.analyze_bench_round(p2)
    modes2 = [f["mode"] for f in row2["failures"]]
    assert modes2[0] == "stalled@materialize"
    # a HEALTHY banked round with a timeline gains no failure modes
    p3 = _write_round(
        tmp_path, 8,
        {"value": 4100.0, "vs_baseline": 2.1,
         "metric": "end-to-end db-analyser revalidation of a "
                   "1000000-header synthetic Praos chain",
         "live_timeline": [
             {"t": 0.0, "attempt": 1, "state": "compiling"},
             {"t": 400.0, "attempt": 1, "state": "running",
              "phase": "retired", "headers": 1000000},
         ]},
    )
    row3 = perf_report.analyze_bench_round(p3)
    assert row3["device_banked"] and row3["failures"] == []
    assert row3["live_states"] == ["compiling", "running"]
