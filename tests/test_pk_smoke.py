"""Default-tier composed pk smoke (VERDICT r4 item 6).

The production TPU composition (ops/pk/verify.verify_praos_core — ed +
kes + vrf + finish in one graph, unrolled hash cores) runs in the
DEFAULT suite at a pinned tiny shape and is checked lane-for-lane
against the native C++ verifier, including one corrupted lane per
verifier leg. Everything bigger (full depth, tile 128, the Pallas
kernel wrappers) stays in the OCT_SLOW tier / on-hardware scripts.

Subprocess: OCT_PK_HASH_IMPL=unrolled must be set before the ops
modules are imported (the TPU code path — the XLA hash modules'
constant arrays cannot be captured by Pallas, see PERF.md), and this
process has long since imported them.

Budget: the child runs the composed graph EAGERLY — ~4 min of op
dispatch on the 1-core CI box, deterministic, no compile and no cache
dependence (a cold-cache XLA:CPU compile of the same graph exceeded
30 min there).
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow  # ~4 min of eager op dispatch — the single biggest
# default-tier cost (VERDICT r5 next #8 wants the tier <480 s). The
# composed graph keeps default-tier coverage through the octlint golden
# gate (tests/test_analysis.py pins its chain-depth/structure) and the
# per-core differentials (test_pk_limbs/test_pk_hashes/test_pk_curve);
# this lane-for-lane numeric check runs in the slow tier and on TPU
# sessions.
def test_composed_pk_smoke_vs_native():
    child = os.path.join(os.path.dirname(__file__), "pk_smoke_child.py")
    proc = subprocess.run(
        [sys.executable, child],
        capture_output=True, text=True, timeout=1500,
    )
    assert proc.returncode == 0, (
        f"composed pk smoke failed:\n{proc.stdout}\n{proc.stderr[-2000:]}"
    )
    assert "composed pk smoke OK" in proc.stdout
