"""Node start-up assembly: DB lock, network marker, crash recovery
(reference: Node.hs stdWithCheckedDB + Node/{DbLock,DbMarker,Recovery})."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger.extended import ExtLedger
from ouroboros_consensus_tpu.ledger.mock import MockConfig, MockLedger
from ouroboros_consensus_tpu.node import run as node_run
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=3,
    active_slot_coeff=Fraction(1),
    epoch_length=1000,
    kes_depth=3,
)


@pytest.fixture
def setup():
    pool = fixtures.make_pool(0, kes_depth=3)
    lview = fixtures.make_ledger_view([pool])
    ledger = MockLedger(MockConfig(lview, PARAMS.stability_window))
    proto = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, proto)
    genesis = ext.genesis(ledger.genesis_state([(b"a", 10)]))
    return pool, ext, genesis


def test_lock_excludes_second_node(tmp_path, setup):
    pool, ext, genesis = setup
    n1 = node_run.start_node("n1", str(tmp_path), ext, genesis, k=3)
    with pytest.raises(node_run.DbLocked):
        node_run.start_node("n2", str(tmp_path), ext, genesis, k=3)
    n1.shutdown()
    # released: can start again
    n2 = node_run.start_node("n2", str(tmp_path), ext, genesis, k=3)
    n2.shutdown()


def test_double_shutdown_is_noop(tmp_path, setup):
    """shutdown() rides StoreGuard.close — idempotent: a second call
    must not re-run the marker write (or error), and the clean marker
    survives."""
    pool, ext, genesis = setup
    n = node_run.start_node("n", str(tmp_path), ext, genesis, k=3)
    n.shutdown()
    assert node_run.was_clean_shutdown(str(tmp_path))
    n.shutdown()
    assert node_run.was_clean_shutdown(str(tmp_path))
    # and the lock is free for the next node
    n2 = node_run.start_node("n2", str(tmp_path), ext, genesis, k=3)
    assert not n2.crashed_last_run
    n2.shutdown()


def test_marker_mismatch(tmp_path, setup):
    pool, ext, genesis = setup
    n = node_run.start_node("n", str(tmp_path), ext, genesis, k=3, network_magic=1)
    n.shutdown()
    with pytest.raises(node_run.DbMarkerMismatch):
        node_run.start_node("n", str(tmp_path), ext, genesis, k=3, network_magic=2)


def test_crash_recovery_flag(tmp_path, setup):
    pool, ext, genesis = setup
    # first run: forge a couple blocks, shut down cleanly
    n = node_run.start_node("n", str(tmp_path), ext, genesis, k=3, pool=pool)
    n.kernel.try_forge(0)
    n.kernel.try_forge(1)
    n.shutdown()
    # clean restart: no revalidation flag
    n = node_run.start_node("n", str(tmp_path), ext, genesis, k=3, pool=pool)
    assert not n.crashed_last_run
    assert n.kernel.chain_db.tip_point().slot == 1
    # simulate crash: do NOT call shutdown (marker stays absent)
    n.lock.release()
    n2 = node_run.start_node("n", str(tmp_path), ext, genesis, k=3, pool=pool)
    assert n2.crashed_last_run  # full revalidation path taken
    assert n2.kernel.chain_db.tip_point().slot == 1
    n2.shutdown()


def test_exit_reason_triage():
    from ouroboros_consensus_tpu.storage.immutable import MissingBlock

    assert (
        node_run.to_exit_reason(node_run.DbLocked())
        is node_run.ExitReason.CONFIG_ERROR
    )
    assert (
        node_run.to_exit_reason(MissingBlock(None))
        is node_run.ExitReason.DB_CORRUPTION
    )
    assert node_run.to_exit_reason(ConnectionError()) is node_run.ExitReason.NETWORK_ERROR
    assert node_run.to_exit_reason(ValueError()) is node_run.ExitReason.GENERIC


def test_whole_node_on_mock_fs(setup):
    """The FULL node lifecycle — lock, marker, forge, clean shutdown,
    reopen, CRASH (torn writes), recovery with full revalidation — runs
    entirely on the in-memory MockFS: the fs-sim property the reference
    gets from running nodes on mock filesystems in ThreadNet."""
    from ouroboros_consensus_tpu.node import run as node_run
    from ouroboros_consensus_tpu.utils.fs import MockFS

    fs = MockFS()
    pool, ext, genesis = setup

    def boot():
        return node_run.start_node(
            "m0", "node-db", ext, genesis, k=3,
            pool=pool, fs=fs, chunk_size=20,
        )

    # first run: forge a few blocks, clean shutdown
    rn = boot()
    assert not rn.crashed_last_run
    for slot in (1, 2, 3, 4, 5):
        rn.kernel.try_forge(slot)
    tip = rn.kernel.chain_db.tip_point()
    rn.shutdown()

    # second process: lock is free, clean shutdown detected, state back
    rn2 = boot()
    assert not rn2.crashed_last_run
    assert rn2.kernel.chain_db.tip_point() == tip
    # a CONCURRENT process is refused while rn2 holds the lock
    import pytest as _pytest

    with _pytest.raises(node_run.DbLocked):
        boot()
    for slot in (6, 7):
        rn2.kernel.try_forge(slot)
    tip2 = rn2.kernel.chain_db.tip_point()

    # CRASH: unsynced bytes vanish (incl. the lock file) — no shutdown
    fs.crash(0.0)
    rn3 = boot()
    assert rn3.crashed_last_run  # missing clean marker => revalidation
    got = rn3.kernel.chain_db.tip_point()
    # recovered to a consistent prefix of the pre-crash chain
    assert got is None or got.slot <= tip2.slot
    rn3.shutdown()
