"""Typed ChainDB trace-event algebra (ChainDB/Impl.hs:10-28 analog):
tests assert event SEQUENCES — add-block lifecycle, fork switch,
invalid-block marking, tentative pipelining, background copy/GC — and
the Enclose latency brackets around the batch hot path."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.utils import trace as T
from ouroboros_consensus_tpu.utils.sim import Sim

import tests.test_pipelining as tp
from tests.test_local_chainsync import _forge_chain


def _node_with_tracer(tmp_path, name):
    node = tp._mk_node(tmp_path, name)
    tracer = T.ListTracer()
    node.chain_db.tracer = tracer
    return node, tracer


def _types(events):
    return [type(e).__name__ for e in events]



def test_add_block_lifecycle_sequence(tmp_path):
    node, tracer = _node_with_tracer(tmp_path, "n")
    chain = _forge_chain(tp.POOLS[0], range(1, 4))
    node.chain_db.add_block(chain[0])
    assert _types(tracer.events) == [
        "AddedBlockToVolatileDB", "ValidCandidate", "AddedToCurrentChain",
    ]
    ev = tracer.events[-1]
    assert ev.n_blocks == 1 and ev.new_tip_slot == 1
    # re-adding is ignored as already-selected (store-but-dont-change)
    tracer.events.clear()
    node.chain_db.add_block(chain[0])
    assert _types(tracer.events)[-1] == "StoreButDontChange"


def test_fork_switch_and_invalid_events(tmp_path):
    node, tracer = _node_with_tracer(tmp_path, "n")
    chain_a = _forge_chain(tp.POOLS[0], range(1, 5))
    fork_b = _forge_chain(
        tp.POOLS[1], range(5, 8), prev=chain_a[1].hash_, block_no=2,
        body=b"b",
    )
    for b in chain_a:
        node.chain_db.add_block(b)
    tracer.events.clear()
    for b in fork_b:
        node.chain_db.add_block(b)
    kinds = _types(tracer.events)
    assert "SwitchedToAFork" in kinds
    sw = next(e for e in tracer.events if isinstance(e, T.SwitchedToAFork))
    assert sw.n_rollback == 2 and sw.new_tip_slot == 7

    # an invalid block (garbage body hash) emits InvalidBlockEvent
    from dataclasses import replace as dreplace

    good = _forge_chain(
        tp.POOLS[0], [9], prev=fork_b[-1].hash_, block_no=5
    )[0]
    bad = dreplace(good, txs=(b"\xff\xfe",))  # body no longer matches
    tracer.events.clear()
    node.chain_db.add_block(bad)
    kinds = _types(tracer.events)
    assert "InvalidBlockEvent" in kinds or "StoreButDontChange" in kinds


def test_tentative_pipelining_events(tmp_path):
    """Decoupled mode: a tip-extending block is announced tentatively
    before validation; an invalid one is TRAPPED (retracted)."""
    from dataclasses import replace as dreplace

    node, tracer = _node_with_tracer(tmp_path, "n")
    sim = Sim()
    runners = node.chain_db.start_decoupled(sim)
    for r in runners:
        sim.spawn(r, "runner")
    follower = node.chain_db.new_follower(include_tentative=True)

    chain = _forge_chain(tp.POOLS[0], range(1, 3))
    good, nxt = chain[0], chain[1]
    node.chain_db.add_block_async(good)
    sim.run(until=1)
    bad = dreplace(nxt, txs=(b"\xff\xfe",))
    node.chain_db.add_block_async(bad)
    sim.run(until=2)
    kinds = _types(tracer.events)
    assert "SetTentativeHeader" in kinds
    assert "TrapTentativeHeader" in kinds
    assert "AddedBlockToQueue" in kinds and "PoppedBlockFromQueue" in kinds
    # the tentative announcement precedes the queue pop that traps it
    assert kinds.index("SetTentativeHeader") < kinds.index(
        "TrapTentativeHeader"
    )


def test_background_copy_and_gc_events(tmp_path):
    node, tracer = _node_with_tracer(tmp_path, "n")
    k = tp.PARAMS.security_param  # 100
    chain = _forge_chain(tp.POOLS[0], range(1, k + 5))
    for b in chain:
        node.chain_db.add_block(b)
    kinds = _types(tracer.events)
    assert "CopiedToImmutableDB" in kinds
    assert "PerformedGC" in kinds
    copied = [e for e in tracer.events if isinstance(e, T.CopiedToImmutableDB)]
    assert sum(e.n_blocks for e in copied) == 4  # k+4 blocks, k stay


@pytest.mark.slow
def test_enclose_brackets_on_batch_path():
    """The stage/dispatch/materialize/epilogue Enclose brackets fire in
    order with durations on the end edges."""
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos
    from ouroboros_consensus_tpu.testing import fixtures

    params = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
        active_slot_coeff=Fraction(1), epoch_length=1000, kes_depth=2,
    )
    pool = fixtures.make_pool(0, kes_depth=2)
    lview = fixtures.make_ledger_view([pool])
    eta = b"\x07" * 32
    hvs, prev = [], None
    for s in range(1, 5):
        hvs.append(fixtures.forge_header_view(
            params, pool, slot=s, epoch_nonce=eta, prev_hash=prev,
            body_bytes=b"b%d" % s,
        ))
        prev = (b"%032d" % s)[:32]
    tracer = T.ListTracer()
    pbatch.set_batch_tracer(tracer)
    try:
        import dataclasses

        st = dataclasses.replace(praos.PraosState(), epoch_nonce=eta)
        res = pbatch.validate_chain(
            params, lambda _e: lview, st, hvs, backend="device",
        )
        assert res.error is None and res.n_valid == 4
    finally:
        pbatch.set_batch_tracer(None)
    # TransferEvents (byte accounting) interleave with the brackets now;
    # the bracket ORDER is what this test pins
    labels = [
        (e.label, e.edge) for e in tracer.events
        if isinstance(e, T.EncloseEvent)
    ]
    assert any(isinstance(e, T.TransferEvent) for e in tracer.events)
    assert labels == [
        ("stage", "start"), ("stage", "end"),
        ("dispatch", "start"), ("dispatch", "end"),
        ("materialize", "start"), ("materialize", "end"),
        ("epilogue", "start"), ("epilogue", "end"),
    ]
    ends = [
        e for e in tracer.events
        if isinstance(e, T.EncloseEvent) and e.edge == "end"
    ]
    assert all(e.duration is not None and e.duration >= 0 for e in ends)
