"""TxSubmission2 / KeepAlive / PeerSharing unit tests (sim-driven).

Reference: the n2n `Apps` bundle (Network/NodeToNode.hs:434-466); the
ThreadNet-level diffusion test lives in test_threadnet.py.
"""

from fractions import Fraction

from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.ledger.mock import encode_tx, tx_id
from ouroboros_consensus_tpu.mempool import Mempool
from ouroboros_consensus_tpu.miniprotocol import txsubmission
from ouroboros_consensus_tpu.utils.sim import Channel, Sim


class _FakeNode:
    def __init__(self, mempool, peers=()):
        self.mempool = mempool
        self.known_peers = list(peers)


def _mk_mempool(n_outputs=4):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(None, 100)
    )
    state = ledger.genesis_state([(b"a%d" % i, 10) for i in range(n_outputs)])
    return Mempool(ledger, lambda: (state, 0))


def test_txsubmission_transfers_txs():
    src, dst = _mk_mempool(), _mk_mempool()
    txs = [encode_tx([(bytes(32), i)], [(b"out", 10)]) for i in range(3)]
    for t in txs:
        src.add_tx(t)
    sim = Sim()
    req, rsp = Channel(delay=0.01), Channel(delay=0.01)
    sim.spawn(txsubmission.outbound(_FakeNode(src), req, rsp), "out")
    sim.spawn(
        txsubmission.inbound(_FakeNode(dst), "peer", rsp, req, max_rounds=2),
        "in",
    )
    sim.run(until=5.0)
    got = {tx_id(t.tx) for t in dst.get_snapshot().txs}
    assert got == {tx_id(t) for t in txs}


def test_txsubmission_does_not_refetch_known():
    """Already-known txids are acked but their bodies never re-requested
    (the inbound side requests only missing ids)."""
    src, dst = _mk_mempool(), _mk_mempool()
    t0 = encode_tx([(bytes(32), 0)], [(b"out", 10)])
    src.add_tx(t0)
    dst.add_tx(t0)  # already known at the destination
    sim = Sim()
    req, rsp = Channel(), Channel()
    sent = []

    def spy(gen):
        """Record request_txs messages the inbound side emits."""
        from ouroboros_consensus_tpu.utils.sim import Send

        val = None
        while True:
            try:
                eff = gen.send(val)
            except StopIteration:
                return
            if isinstance(eff, Send) and eff.msg[0] == "request_txs":
                sent.append(eff.msg)
            val = yield eff

    sim.spawn(txsubmission.outbound(_FakeNode(src), req, rsp), "out")
    sim.spawn(
        spy(txsubmission.inbound(_FakeNode(dst), "peer", rsp, req, max_rounds=1)),
        "in",
    )
    sim.run(until=5.0)
    assert sent == []  # no body request was needed
    assert len(dst.get_snapshot().txs) == 1


def test_keepalive_roundtrip():
    sim = Sim()
    req, rsp = Channel(delay=0.05), Channel(delay=0.05)
    sim.spawn(txsubmission.keepalive_server(req, rsp), "server")
    client = sim.spawn(
        txsubmission.keepalive_client(rsp, req, interval=0.1, rounds=5),
        "client",
    )
    sim.run(until=10.0)
    assert not client.alive and len(client.result) == 5


def test_peersharing():
    sim = Sim()
    node = _FakeNode(_mk_mempool(), peers=["n1:3001", "n2:3001", "n3:3001"])
    req, rsp = Channel(), Channel()
    sim.spawn(txsubmission.peersharing_server(node, req, rsp), "server")
    client = sim.spawn(txsubmission.peersharing_client(rsp, req, 2), "client")
    sim.run(until=1.0)
    assert client.result == ["n1:3001", "n2:3001"]
