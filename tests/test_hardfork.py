"""Hard-fork combinator: time-conversion roundtrips (reference:
Test/Consensus/HardFork/History.hs) and a two-era chain crossing a real
transition with state translation (the A→B model test,
diffusion test/consensus-test HardFork/Combinator.hs)."""

from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.hardfork import (
    Era,
    EraParams,
    HardForkBlock,
    HardForkProtocol,
    PastHorizon,
    decode_block,
    summarize,
)
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.testing import fixtures

# -- history -----------------------------------------------------------------


def two_era_summary():
    return summarize(
        Fraction(0),
        [
            EraParams(epoch_size=20, slot_length=Fraction(1)),
            EraParams(epoch_size=50, slot_length=Fraction(2)),
        ],
        [2, None],  # era 0 ends at epoch 2 (slot 40); era 1 open
    )


def test_summary_bounds():
    s = two_era_summary()
    assert s.eras[0].end.slot == 40
    assert s.eras[0].end.epoch == 2
    assert s.eras[0].end.time == Fraction(40)
    assert s.eras[1].end is None


def test_slot_epoch_roundtrip():
    s = two_era_summary()
    for slot in list(range(0, 41)) + [41, 89, 90, 139, 500]:
        epoch, in_epoch = s.slot_to_epoch(slot)
        first = s.epoch_to_first_slot(epoch)
        assert first + in_epoch == slot
        assert in_epoch < s.epoch_size(epoch)


def test_wallclock_roundtrip():
    s = two_era_summary()
    for slot in [0, 5, 39, 40, 41, 100]:
        t, ln = s.slot_to_wallclock(slot)
        back, spent = s.wallclock_to_slot(t)
        assert back == slot and spent == 0
        back2, spent2 = s.wallclock_to_slot(t + ln / 2)
        assert back2 == slot and spent2 == ln / 2


def test_era_boundary_conversions():
    s = two_era_summary()
    # era 0: slots are 1s; era 1 starts at slot 40, time 40, slots are 2s
    assert s.slot_to_wallclock(40) == (Fraction(40), Fraction(2))
    assert s.slot_to_wallclock(41) == (Fraction(42), Fraction(2))
    assert s.slot_to_epoch(40) == (2, 0)
    assert s.epoch_to_first_slot(3) == 90


def test_past_horizon_on_negative():
    s = two_era_summary()
    with pytest.raises(PastHorizon):
        s.wallclock_to_slot(Fraction(-1))


# -- combinator: two Praos eras with a parameter change ----------------------

EPOCHS_IN_A = 2


def make_hf(pools):
    lview = fixtures.make_ledger_view(pools)
    pa = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
        active_slot_coeff=Fraction(1, 2), epoch_length=20, kes_depth=3,
    )
    pb = replace(pa, epoch_length=50)
    summary = summarize(
        Fraction(0),
        [EraParams(20, Fraction(1)), EraParams(50, Fraction(1))],
        [EPOCHS_IN_A, None],
    )
    era_a = Era("eraA", PraosProtocol(pa, use_device_batch=False), ledger=None)
    era_b = Era("eraB", PraosProtocol(pb, use_device_batch=False), ledger=None)
    return HardForkProtocol([era_a, era_b], summary), (pa, pb), lview


def test_two_era_chain_crosses_transition():
    pools = [fixtures.make_pool(i, kes_depth=3) for i in range(2)]
    hf, (pa, pb), lview = make_hf(pools)
    st = hf.initial_state()
    prev = None
    n_a = n_b = 0
    slot = 0
    while slot < 120 and (n_a < 3 or n_b < 3):
        ticked = hf.tick(lview, slot, st)
        era = ticked.era
        params = pa if era == 0 else pb
        eta0 = ticked.inner.state.epoch_nonce
        pool = fixtures.find_leader(params, pools, lview, slot, eta0)
        if pool is not None:
            hv = fixtures.forge_header_view(
                params, pool, slot=slot, epoch_nonce=eta0, prev_hash=prev,
                body_bytes=b"b%d" % slot,
            )
            st = hf.update(hv, slot, ticked)
            assert st.era == era
            prev = (b"%032d" % slot)[:32]
            if era == 0:
                n_a += 1
            else:
                n_b += 1
        slot += 1
    assert n_a >= 3 and n_b >= 3
    assert st.era == 1  # crossed into era B
    # nonce state carried across the transition (translated, not reset)
    assert st.inner.evolving_nonce is not None


def test_tick_refuses_past_era():
    pools = [fixtures.make_pool(0, kes_depth=3)]
    hf, _, lview = make_hf(pools)
    st = hf.initial_state()
    st2 = hf._cross_eras(st, 1)
    with pytest.raises(ValueError):
        hf.tick(lview, 5, st2)  # slot 5 is era 0, state already in era 1


def test_cross_era_candidate_comparison():
    pools = [fixtures.make_pool(0, kes_depth=3)]
    hf, (pa, pb), lview = make_hf(pools)
    nonce = b"\x07" * 32
    ha = fixtures.forge_header_view  # convenience: need Header-like objs

    # forge one header in each era; wrap minimal select-view comparison
    from ouroboros_consensus_tpu.block.forge import forge_block

    blk_a = forge_block(pa, pools[0], slot=5, block_no=7, prev_hash=None, epoch_nonce=nonce)
    blk_b = forge_block(pb, pools[0], slot=45, block_no=9, prev_hash=None, epoch_nonce=nonce)
    va = hf.select_view(blk_a.header)
    vb = hf.select_view(blk_b.header)
    assert va[0] == 0 and vb[0] == 1
    assert hf.compare_candidates(va, vb) > 0  # higher block_no wins across eras
    assert hf.compare_candidates(vb, va) < 0


def test_hardfork_block_roundtrip():
    from ouroboros_consensus_tpu.block.forge import forge_block
    from ouroboros_consensus_tpu.block.praos_block import Block

    pools = [fixtures.make_pool(0, kes_depth=3)]
    pa = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=4,
        active_slot_coeff=Fraction(1), epoch_length=20, kes_depth=3,
    )
    blk = forge_block(pa, pools[0], slot=3, block_no=0, prev_hash=None,
                      epoch_nonce=b"\x07" * 32, txs=(b"tx1",))
    hfb = HardForkBlock(1, blk)
    data = hfb.bytes_
    back = decode_block(data, [Block.from_bytes, Block.from_bytes])
    assert back.era == 1
    assert back.hash_ == blk.hash_
    assert back.txs == (b"tx1",)


# -- cross-era txs + queries (InjectTxs.hs, Combinator/Ledger/Query.hs) ------


def test_inject_tx_translates_across_eras():
    from ouroboros_consensus_tpu.hardfork.combinator import (
        CannotInjectTx,
        HardForkTx,
        TxFromFutureEra,
        inject_tx,
    )

    # era B's tx format wraps era A's with a version marker
    era_a = Era("A", None, ledger=None)
    era_b = Era("B", None, ledger=None,
                translate_tx=lambda raw: b"v2:" + raw)
    era_c = Era("C", None, ledger=None)  # no translation INTO C

    eras = [era_a, era_b, era_c]
    # same-era: unchanged
    assert inject_tx(eras, 0, HardForkTx(0, b"tx")) == b"tx"
    # A-era tx offered in era B: translated
    assert inject_tx(eras, 1, HardForkTx(0, b"tx")) == b"v2:tx"
    # B-era tx in era C: boundary has no translation
    with pytest.raises(CannotInjectTx):
        inject_tx(eras, 2, HardForkTx(1, b"tx"))
    # future-era tx rejected
    with pytest.raises(TxFromFutureEra):
        inject_tx(eras, 0, HardForkTx(1, b"tx"))


def test_hard_fork_queries():
    from ouroboros_consensus_tpu.hardfork.combinator import (
        HardForkLedger,
        HFState,
        hard_fork_query,
    )

    s = two_era_summary()
    era_a = Era("eraA", None, ledger=None)
    era_b = Era("eraB", None, ledger=None)
    ledger = HardForkLedger([era_a, era_b], s)
    st = HFState(1, None)
    assert hard_fork_query(ledger, s, st, "get_current_era") == (1, "eraB")
    assert hard_fork_query(ledger, s, st, "get_era_start") == 40
    interp = hard_fork_query(ledger, s, st, "get_interpreter")
    assert interp.slot_to_epoch(45)[0] >= 2  # clients run conversions locally
