"""immdb-server: ChainSync+BlockFetch off a bare ImmutableDB.

Reference: `Cardano.Tools.ImmDBServer` ({Diffusion,MiniProtocols}.hs) —
a stripped node feeding syncing peers straight from disk, over the real
wire handshake.
"""

import asyncio
import dataclasses
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.miniprotocol import blockfetch, chainsync
from ouroboros_consensus_tpu.miniprotocol.chainsync import Candidate
from ouroboros_consensus_tpu.node.kernel import NodeKernel
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.immutable import ImmutableDB
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.tools import immdb_server
from ouroboros_consensus_tpu.utils.sim import Channel, Sim

PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=100,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOL = fixtures.make_pool(0, kes_depth=2)
LVIEW = fixtures.make_ledger_view([POOL])
ETA0 = b"\x22" * 32


def _write_chain(tmp_path, n=12):
    imm = ImmutableDB(str(tmp_path / "srv" / "immutable"), chunk_size=100)
    blocks, prev = [], None
    for i in range(n):
        b = forge_block(PARAMS, POOL, slot=i + 1, block_no=i,
                        prev_hash=prev, epoch_nonce=ETA0)
        imm.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
        blocks.append(b)
        prev = b.hash_
    imm.flush()
    return str(tmp_path / "srv"), blocks


def _mk_client(tmp_path):
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    st = ext.genesis(ledger.genesis_state([]))
    st = dataclasses.replace(
        st,
        header_state=dataclasses.replace(
            st.header_state,
            chain_dep_state=dataclasses.replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    db = open_chaindb(str(tmp_path / "client"), ext, st, PARAMS.security_param)
    return NodeKernel("client", db, protocol, ledger, pool=None)


def test_serve_sim_full_sync(tmp_path):
    """A fresh node syncs the WHOLE served chain through the standard
    chainsync+blockfetch clients against the static view."""
    path, blocks = _write_chain(tmp_path)
    view = immdb_server.ImmutableChainView(path)
    client = _mk_client(tmp_path)
    sim = Sim()
    client.chain_db.runtime = sim
    cs_req, cs_rsp = Channel(delay=0.01), Channel(delay=0.01)
    bf_req, bf_rsp = Channel(delay=0.01), Channel(delay=0.01)
    cs_srv, bf_srv = immdb_server.serve_sim(view, cs_req, cs_rsp, bf_req, bf_rsp)
    sim.spawn(cs_srv, "cs-srv")
    sim.spawn(bf_srv, "bf-srv")
    cand = Candidate()
    sim.spawn(
        chainsync.client(client, "immdb", cs_rsp, cs_req, cand,
                         max_headers=len(blocks)),
        "cs-client",
    )
    sim.spawn(blockfetch.client(client, "immdb", bf_rsp, bf_req, cand), "bf")
    sim.run(until=60.0)
    assert client.chain_db.tip_point() is not None
    assert client.chain_db.tip_point().hash_ == blocks[-1].hash_


def test_tcp_handshake_and_fetch(tmp_path):
    """TCP transport: wire handshake first (magic checked), then
    intersect + range fetch over length-prefixed CBOR frames."""
    path, blocks = _write_chain(tmp_path, n=6)

    async def scenario():
        server = await immdb_server.serve_tcp(path, port=0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)

        async def rpc(msg):
            writer.write(immdb_server._frame(msg))
            await writer.drain()
            return await immdb_server._read_frame(reader)

        # handshake: good magic -> accept at the highest common version
        r = await rpc(("propose_versions", [(2, immdb_server._NETWORK_MAGIC),
                                            (3, immdb_server._NETWORK_MAGIC)]))
        assert r[0] == "accept_version" and r[1] == 3

        r = await rpc(("find_intersect", [None]))
        assert r[0] == "intersect_found"

        writer.write(immdb_server._frame(
            ("request_range", None, blocks[2].point)
        ))
        await writer.drain()
        assert (await immdb_server._read_frame(reader))[0] == "start_batch"
        got = []
        while True:
            m = await immdb_server._read_frame(reader)
            if m[0] == "batch_done":
                break
            got.append(m[1])
        assert len(got) == 3  # genesis..blocks[2]
        writer.write(immdb_server._frame(("done",)))
        await writer.drain()
        writer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_tcp_handshake_refused_on_magic_mismatch(tmp_path):
    path, _ = _write_chain(tmp_path, n=3)

    async def scenario():
        server = await immdb_server.serve_tcp(path, port=0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(immdb_server._frame(("propose_versions", [(3, 42)])))
        await writer.drain()
        r = await immdb_server._read_frame(reader)
        assert r[0] == "refuse"
        writer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_tcp_requires_handshake_first(tmp_path):
    """Serving before version negotiation is refused (the reference
    handshakes before serving, ImmDBServer/Diffusion.hs)."""
    path, _ = _write_chain(tmp_path, n=3)

    async def scenario():
        server = await immdb_server.serve_tcp(path, port=0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(immdb_server._frame(("find_intersect", [None])))
        await writer.drain()
        r = await immdb_server._read_frame(reader)
        assert r[0] == "refuse" and "handshake" in r[1]
        writer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())
