"""ThreadNet integration: multi-node convergence under simulation.

Reference analog: Test/ThreadNet/Praos.hs + prop_general
(General.hs:403) — common prefix and chain growth over a simulated
network of real nodes."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.testing import threadnet


@pytest.mark.slow
def test_three_nodes_converge(tmp_path):
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=30, k=10, msg_delay=0.05
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    # stronger: with prompt delivery all nodes should agree on tip
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1, "nodes did not converge to one tip"


@pytest.mark.slow
def test_two_nodes_ring_topology(tmp_path):
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2,
        n_slots=20,
        k=8,
        topology=[(0, 1), (1, 0)],
        msg_delay=0.1,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)


@pytest.mark.slow
def test_deterministic_replay(tmp_path):
    """The io-sim property: identical runs, identical chains."""
    cfg = threadnet.ThreadNetConfig(n_nodes=2, n_slots=15, k=8)
    r1 = threadnet.run_thread_network(str(tmp_path / "a"), cfg)
    r2 = threadnet.run_thread_network(str(tmp_path / "b"), cfg)
    assert [r1.chain_hashes(i) for i in range(2)] == [
        r2.chain_hashes(i) for i in range(2)
    ]


@pytest.mark.slow
def test_async_chaindb_converges(tmp_path):
    """Decoupled add-block queue + background copy/GC (ChainSel.hs:217,
    Background.hs): same convergence properties, deterministically."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=30, k=10, msg_delay=0.05, async_chaindb=True
    )
    res = threadnet.run_thread_network(str(tmp_path / "a"), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1, "nodes did not converge to one tip"
    # determinism holds with the extra runner tasks in the schedule
    res2 = threadnet.run_thread_network(str(tmp_path / "b"), cfg)
    assert [res.chain_hashes(i) for i in range(3)] == [
        res2.chain_hashes(i) for i in range(3)
    ]


@pytest.mark.slow
def test_device_batch_threadnet(tmp_path):
    """Multi-node sim with candidate validation through the fused batch
    kernel (use_device_batch=True) — co-testing networking + device
    crypto (VERDICT r1: ThreadNet never exercised the device path)."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=12, k=6, msg_delay=0.05, use_device_batch=True,
        async_chaindb=True,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1
