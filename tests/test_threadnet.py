"""ThreadNet integration: multi-node convergence under simulation.

Reference analog: Test/ThreadNet/Praos.hs + prop_general
(General.hs:403) — common prefix and chain growth over a simulated
network of real nodes."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.testing import threadnet


@pytest.mark.slow
def test_three_nodes_converge(tmp_path):
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=30, k=10, msg_delay=0.05
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    # stronger: with prompt delivery all nodes should agree on tip
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1, "nodes did not converge to one tip"


@pytest.mark.slow
def test_two_nodes_ring_topology(tmp_path):
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2,
        n_slots=20,
        k=8,
        topology=[(0, 1), (1, 0)],
        msg_delay=0.1,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)


@pytest.mark.slow
def test_deterministic_replay(tmp_path):
    """The io-sim property: identical runs, identical chains."""
    cfg = threadnet.ThreadNetConfig(n_nodes=2, n_slots=15, k=8)
    r1 = threadnet.run_thread_network(str(tmp_path / "a"), cfg)
    r2 = threadnet.run_thread_network(str(tmp_path / "b"), cfg)
    assert [r1.chain_hashes(i) for i in range(2)] == [
        r2.chain_hashes(i) for i in range(2)
    ]


@pytest.mark.slow
def test_async_chaindb_converges(tmp_path):
    """Decoupled add-block queue + background copy/GC (ChainSel.hs:217,
    Background.hs): same convergence properties, deterministically."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=30, k=10, msg_delay=0.05, async_chaindb=True
    )
    res = threadnet.run_thread_network(str(tmp_path / "a"), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1, "nodes did not converge to one tip"
    # determinism holds with the extra runner tasks in the schedule
    res2 = threadnet.run_thread_network(str(tmp_path / "b"), cfg)
    assert [res.chain_hashes(i) for i in range(3)] == [
        res2.chain_hashes(i) for i in range(3)
    ]


@pytest.mark.slow
def test_device_batch_threadnet(tmp_path):
    """Multi-node sim with candidate validation through the fused batch
    kernel (use_device_batch=True) — co-testing networking + device
    crypto (VERDICT r1: ThreadNet never exercised the device path)."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=12, k=6, msg_delay=0.05, use_device_batch=True,
        async_chaindb=True,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1


@pytest.mark.slow
def test_join_plan_late_node_syncs(tmp_path):
    """NodeJoinPlan (ThreadNet/Util/NodeJoinPlan.hs analog): a node
    joining at slot 10 must still converge with the others, and the
    single-forger reference simulator predicts the exact chain length."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=20, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        join_plan={2: 10},
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    expect = threadnet.expected_chain_length(cfg)
    assert len(res.chains[0]) == expect
    # the late joiner caught up fully
    assert res.chain_hashes(2) == res.chain_hashes(0)


@pytest.mark.slow
def test_node_restart_mid_run(tmp_path):
    """NodeRestarts (ThreadNet/Util/NodeRestarts.hs analog): the forger
    restarts mid-run — ChainDB closed, reopened WITH full revalidation —
    and the network still reaches the reference-predicted length."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=20, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        restarts=[(8, 0), (14, 1)],
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    assert res.n_restarts == 2
    threadnet.check_common_prefix(res, cfg.k)
    expect = threadnet.expected_chain_length(cfg)
    assert len(res.chains[0]) == expect
    assert res.chain_hashes(1) == res.chain_hashes(0)


@pytest.mark.slow
def test_restart_with_rekey(tmp_path):
    """Rekeying (Util/Rekeying.hs analog): the restarted forger comes
    back with a FRESH KES key and an ocert at counter+1; its later
    blocks must still validate on every peer."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=20, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        restarts=[(10, 0)],
        rekey_on_restart=True,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    assert res.nodes[0]._ocert_counter == 1
    expect = threadnet.expected_chain_length(cfg)
    assert len(res.chains[0]) == expect
    assert res.chain_hashes(1) == res.chain_hashes(0)


@pytest.mark.slow
def test_threadnet_device_batch_path(tmp_path):
    """Multi-node + device batching co-tested (the fused-kernel
    candidate validation path that production uses), per VERDICT: the
    sim network must behave identically when candidate suffixes are
    validated through protocol/batch.py instead of the host fold."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=10, k=8, msg_delay=0.05,
        kes_depth=2, use_device_batch=True,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    tips = {res.chain_hashes(i)[-1] for i in range(cfg.n_nodes)}
    assert len(tips) == 1


@pytest.mark.slow
def test_tx_submission_diffuses_to_block(tmp_path):
    """TxSubmission2 (Network/NodeToNode.hs:434-466): a tx injected at a
    NON-forging node's mempool must gossip to the forger and appear in a
    block adopted by everyone."""
    from ouroboros_consensus_tpu.ledger.mock import encode_tx

    # spends node-genesis output (zero-txid, 0): valid on every node
    tx = encode_tx([(bytes(32), 0)], [(b"dest", 100)])
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=12, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        tx_submission=True,
        tx_injections=[(2, 1, tx)],  # node 1 (non-forger) gets the tx
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    included = [
        b for b in res.chains[0] if any(t == tx for t in b.txs)
    ]
    assert included, "injected tx never reached a forged block"
    # and the non-forger adopted that block too
    assert any(tx in b.txs for b in res.chains[1])


@pytest.mark.slow
def test_properties_hold_across_schedules(tmp_path):
    """Schedule exploration (io-sim seed variation, SURVEY §5.2): the
    consensus properties must hold under PERTURBED task interleavings,
    not just the FIFO schedule."""
    for seed in (None, 7, 1234):
        cfg = threadnet.ThreadNetConfig(
            n_nodes=3, n_slots=15, k=10, msg_delay=0.05, seed=seed,
        )
        res = threadnet.run_thread_network(
            str(tmp_path / f"s{seed}"), cfg
        )
        threadnet.check_common_prefix(res, cfg.k)
        threadnet.check_chain_growth(res, cfg)


@pytest.mark.slow
def test_restart_before_peer_joins(tmp_path):
    """Regression: a restart of node A before peer B's join slot used to
    kill the delayed A<->B edge tasks without respawning them — B then
    never synced at all. The restart must re-establish edges to
    not-yet-joined peers with their remaining join delay."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=2, n_slots=16, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        join_plan={1: 12},
        restarts=[(5, 0)],
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    assert len(res.chains[0]) == threadnet.expected_chain_length(cfg)
    assert res.chain_hashes(1) == res.chain_hashes(0), (
        f"late joiner stuck at {len(res.chains[1])} blocks"
    )


@pytest.mark.slow
def test_txgen_diffusion(tmp_path):
    """TxGen (ThreadNet/TxGen.hs analog): generated txs entering at
    rotating nodes diffuse via TxSubmission2 and land in blocks on every
    node's chain."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=16, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        forgers=[0],
        tx_submission=True,
        tx_gen_every=2,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    included = [tx for b in res.chains[0] for tx in b.txs]
    assert len(included) >= 3, f"only {len(included)} generated txs adopted"
    # all nodes converged on the same blocks (txs included)
    assert res.chain_hashes(1) == res.chain_hashes(0)
    assert res.chain_hashes(2) == res.chain_hashes(0)


@pytest.mark.slow
def test_two_era_hard_fork_network(tmp_path):
    """The flagship HFC model test (diffusion test/consensus-test
    HardFork/Combinator.hs, A→B net): a LIVE multi-node network forges
    and syncs ACROSS a hard fork — era A (epoch length 10) hands over to
    era B (epoch length 20) at epoch 2, slot 20 — and still satisfies
    common-prefix/convergence. Every node runs the composite
    protocol/ledger with era-tagged blocks on disk."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=40, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        epoch_length=10,
        forgers=[0],
        hard_fork_at_epoch=2,  # era boundary at slot 20
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    assert len(res.chains[0]) == cfg.n_slots  # f=1, single forger
    # everyone crossed the era boundary and converged
    assert res.chain_hashes(1) == res.chain_hashes(0)
    assert res.chain_hashes(2) == res.chain_hashes(0)
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock

    eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
    assert set(eras) == {0, 1}, "chain never crossed the boundary"
    assert eras == sorted(eras)
    # the adopted protocol state sits in era B
    st = res.nodes[0].chain_db.current_ledger().header_state.chain_dep_state
    assert st.era == 1


@pytest.mark.slow
def test_async_chaindb_across_schedules(tmp_path):
    """Decoupled add-block queue + background GC under PERTURBED
    schedules: the async architecture must keep the consensus
    properties for every explored interleaving (io-sim seed variation
    over the mode with the most concurrency)."""
    for seed in (None, 11, 97):
        cfg = threadnet.ThreadNetConfig(
            n_nodes=3, n_slots=14, k=10, msg_delay=0.05,
            async_chaindb=True, seed=seed,
        )
        res = threadnet.run_thread_network(str(tmp_path / f"s{seed}"), cfg)
        threadnet.check_common_prefix(res, cfg.k)
        threadnet.check_chain_growth(res, cfg)


def test_two_era_network_with_live_shelley_ledger(tmp_path):
    """The A→B HFC net where era B is the REAL Shelley STS ledger: the
    boundary translation carries the mock UTxO across, genesis staking
    delegates it to the forger pools, and post-fork blocks are forged,
    diffused, validated and adopted by every node against LEDGER-DERIVED
    Shelley stake."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=40, k=30, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        epoch_length=10,
        forgers=[0, 1],
        hard_fork_at_epoch=2,  # era boundary at slot 20
        hf_shelley_era=True,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    assert res.chain_hashes(1) == res.chain_hashes(0)
    assert res.chain_hashes(2) == res.chain_hashes(0)
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState

    eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
    assert set(eras) == {0, 1}, "chain never crossed the boundary"
    # the adopted LEDGER state is a real Shelley state with the carried
    # UTxO and per-pool block counts from the post-fork forging
    st = res.nodes[2].chain_db.current_ledger().ledger_state
    assert st.era == 1 and isinstance(st.inner, ShelleyState)
    assert sum(c for _a, c in st.inner.utxo.values()) > 0
    assert sum(st.inner.blocks_current.values()) + sum(
        st.inner.blocks_prev.values()
    ) == sum(1 for e in eras if e == 1)


def test_shelley_era_network_under_lottery_and_txgen(tmp_path):
    """The mock->Shelley net with a REAL leader lottery (f = 1/2), every
    node forging, and TxGen spending mock-era outputs across the run:
    pre-fork nodes must forecast era-B leadership with the SHELLEY view
    (the cross-era forecast path), re-addressed outputs keep their stake
    through the boundary translation, and post-fork mock-era txs are
    rejected by era dispatch without killing the generator."""
    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=60, k=40, msg_delay=0.05,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=10,
        hard_fork_at_epoch=2,
        hf_shelley_era=True,
        tx_gen_every=3,
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    threadnet.check_chain_growth(res, cfg)
    assert res.chain_hashes(1) == res.chain_hashes(0) == res.chain_hashes(2)
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState

    eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
    assert 1 in eras, "no era-B blocks under the lottery"
    st = res.nodes[0].chain_db.current_ledger().ledger_state
    assert st.era == 1 and isinstance(st.inner, ShelleyState)
    # at least one pre-fork TxGen spend moved a genesis output, and the
    # re-addressed outputs still carry stake in the translated state
    spent = [a for (a, _c) in st.inner.utxo.values()
             if a[0].startswith(b"paid-")]
    assert spent, "TxGen never landed a pre-fork spend"
    assert all(s is not None for (_p, s) in spent)


def test_three_era_network_mock_shelley_mary(tmp_path):
    """A 3-era net crossing TWO genuine rule changes: mock -> Shelley
    STS at epoch 2, Shelley -> Mary-class at epoch 4. A multi-asset
    MINT tx injected after the second boundary validates under the Mary
    rules, is forged, diffused and adopted by every node — and the SAME
    wire bytes would be malformed under Shelley (the era really
    changed)."""
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock
    from ouroboros_consensus_tpu.ledger import mary as mary_mod
    from ouroboros_consensus_tpu.ledger.mary import MaryValue, policy_id
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed

    policy_seed = b"\x5a" * 32
    pid = policy_id(ed.secret_to_public(policy_seed))
    # spend genesis output #7 (untouched by TxGen: tx_gen off), minting
    # 42 "NET" into the new output — a MARY-format tx
    genesis_in = (bytes(32), 7)
    outs = [(b"mary-paid", None, MaryValue(100, {(pid, b"NET"): 42}))]
    wit = mary_mod.make_mint_witness(
        policy_seed, [genesis_in], outs, 0, (None, None), {b"NET": 42}
    )
    mint_tx = mary_mod.encode_tx([genesis_in], outs, mint=[wit])

    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=60, k=40, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        epoch_length=10,
        # ONE forger: two forgers racing the same slot can strand the
        # mint tx (the loser's mempool drops it when it momentarily
        # adopts its own tx-block — reference-faithful: abandoned-block
        # txs are not resurrected)
        forgers=[0],
        hard_fork_at_epoch=2,   # mock -> Shelley at slot 20
        hf_shelley_era=True,
        hf_mary_at_epoch=4,     # Shelley -> Mary at slot 40
        tx_submission=True,
        tx_injections=[(45, 0, mint_tx)],
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    assert res.chain_hashes(1) == res.chain_hashes(0) == res.chain_hashes(2)

    eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
    assert set(eras) == {0, 1, 2}, f"eras seen: {set(eras)}"

    st = res.nodes[0].chain_db.current_ledger().ledger_state
    assert st.era == 2 and isinstance(st.inner, ShelleyState)
    # the minted asset landed and survived adoption on every node
    minted = [
        v for _a, v in st.inner.utxo.values()
        if isinstance(v, MaryValue) and v.assets
    ]
    assert minted and minted[0].asset_map() == {(pid, b"NET"): 42}
    for n in res.nodes[1:]:
        st_i = n.chain_db.current_ledger().ledger_state
        assert any(
            getattr(v, "assets", ()) for _a, v in st_i.inner.utxo.values()
        )
    # era differentiation: the same bytes are REJECTED by the Shelley
    # rules (malformed 7-element wire)
    from ouroboros_consensus_tpu.ledger.shelley import (
        ShelleyLedger, ShelleyTxError,
    )
    import pytest as _pytest

    sh_led = ShelleyLedger(res.nodes[0].ledger.eras[1].ledger.genesis)
    with _pytest.raises(ShelleyTxError):
        sh_led.apply_tx(
            sh_led.mempool_view(
                sh_led.genesis_state([(b"x", None, 100)]), 1
            ),
            mint_tx,
        )


def test_three_era_network_across_schedules(tmp_path):
    """The 3-era net under permuted task schedules (io-sim seed
    exploration): both boundaries cross and all nodes converge under
    every seed."""
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock

    for seed in (23, 171):
        cfg = threadnet.ThreadNetConfig(
            n_nodes=3, n_slots=55, k=40, msg_delay=0.05,
            active_slot_coeff=Fraction(1),
            epoch_length=10,
            forgers=[0],
            hard_fork_at_epoch=2,
            hf_shelley_era=True,
            hf_mary_at_epoch=4,
            seed=seed,
        )
        res = threadnet.run_thread_network(str(tmp_path / f"s{seed}"), cfg)
        threadnet.check_common_prefix(res, cfg.k)
        assert res.chain_hashes(1) == res.chain_hashes(0) == res.chain_hashes(2)
        eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
        assert set(eras) == {0, 1, 2}, f"seed {seed}: eras {set(eras)}"


def test_four_era_network_crosses_into_script_era(tmp_path):
    """A 4-era net: mock -> Shelley -> Mary -> ALONZO (epoch 6, slot
    60). After the third boundary a LIVE phase-2 script flow runs on
    the network: a lock tx pays into a script output (datum by hash),
    then a spend tx provides the script + datum + redeemer + collateral
    and passes phase-2 evaluation — diffused and adopted by every node
    (VERDICT r4 item 4: a ThreadNet crossing a new capability boundary
    live)."""
    from ouroboros_consensus_tpu.hardfork.combinator import HardForkBlock
    from ouroboros_consensus_tpu.ledger import allegra as al
    from ouroboros_consensus_tpu.ledger import alonzo as az
    from ouroboros_consensus_tpu.ledger.alonzo import AlonzoPParams
    from ouroboros_consensus_tpu.utils import cbor

    script = az.plutus_script([4, [1], [2]])  # redeemer == datum
    datum = cbor.encode(b"tn-secret")
    saddr = al.script_addr(script)
    genesis_in = (bytes(32), 8)  # untouched by TxGen (tx_gen off)
    lock_tx = az.encode_tx(
        [genesis_in],
        [(saddr, None, 60, az.datum_hash(datum)), (b"ada-coll", None, 40)],
    )
    lock_tid = az.tx_id(lock_tx)
    spend_tx = az.encode_tx(
        [(lock_tid, 0)], [(b"alonzo-paid", None, 59)],
        collateral=[(lock_tid, 1)],
        scripts=[script], datums=[datum],
        redeemers=[(0, 0, cbor.decode(datum))], budget=100, fee=1,
    )

    cfg = threadnet.ThreadNetConfig(
        n_nodes=3, n_slots=80, k=60, msg_delay=0.05,
        active_slot_coeff=Fraction(1),
        epoch_length=10,
        forgers=[0],
        hard_fork_at_epoch=2,   # mock -> Shelley at slot 20
        hf_shelley_era=True,
        hf_mary_at_epoch=4,     # Shelley -> Mary at slot 40
        hf_alonzo_at_epoch=6,   # Mary -> Alonzo at slot 60
        tx_submission=True,
        tx_injections=[(65, 0, lock_tx), (70, 0, spend_tx)],
    )
    res = threadnet.run_thread_network(str(tmp_path), cfg)
    threadnet.check_common_prefix(res, cfg.k)
    assert res.chain_hashes(1) == res.chain_hashes(0) == res.chain_hashes(2)
    eras = [b.era for b in res.chains[0] if isinstance(b, HardForkBlock)]
    assert set(eras) == {0, 1, 2, 3}, f"eras seen: {set(eras)}"

    st = res.nodes[0].chain_db.current_ledger().ledger_state
    assert st.era == 3
    assert isinstance(st.inner.pparams, AlonzoPParams)
    # the phase-2 spend executed: locked output consumed, payment landed,
    # collateral untouched — on EVERY node
    for n in res.nodes:
        utxo = n.chain_db.current_ledger().ledger_state.inner.utxo
        assert (lock_tid, 0) not in utxo
        assert (lock_tid, 1) in utxo
        assert any(a[0] == b"alonzo-paid" for a, _v in utxo.values())
