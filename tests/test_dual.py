"""DualLedger conformance pairing.

Reference: Ledger/Dual.hs (DualBlock), byronspec pairing, exercised by
Test/ThreadNet/DualByron.hs — the impl and an independently-written
executable spec consume identical blocks; divergence throws.
"""

import dataclasses
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.ledger.dual import (
    DualLedger,
    DualLedgerMismatch,
    DualState,
    SpecState,
)
from ouroboros_consensus_tpu.ledger.mock import encode_tx
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=3,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=2,
)
POOL = fixtures.make_pool(0, kes_depth=2)
LVIEW = fixtures.make_ledger_view([POOL])
ETA0 = b"\x22" * 32
GENESIS_OUTS = [(b"alice", 70), (b"bob", 30)]


def _mk_db(tmp_path):
    ledger = DualLedger(mock_ledger.MockConfig(LVIEW, PARAMS.stability_window))
    proto = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, proto)
    st = ext.genesis(ledger.genesis_state(GENESIS_OUTS))
    st = dataclasses.replace(
        st,
        header_state=dataclasses.replace(
            st.header_state,
            chain_dep_state=dataclasses.replace(
                st.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    return open_chaindb(str(tmp_path / "dual"), ext, st, PARAMS.security_param), ledger


def test_dual_ledger_lockstep(tmp_path):
    """A chain of value-moving txs applies through BOTH ledgers; the
    spec's balance table always matches the impl's UTxO projection."""
    db, ledger = _mk_db(tmp_path)
    # alice pays carol 70 (spends genesis output 0)
    tx1 = encode_tx([(bytes(32), 0)], [(b"carol", 70)])
    b1 = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                     epoch_nonce=ETA0, txs=(tx1,))
    assert db.add_block(b1).selected
    st = db.current_ledger().ledger_state
    assert dict(st.spec.balances) == {b"carol": 70, b"bob": 30}

    # carol splits to dave+erin
    from ouroboros_consensus_tpu.ledger.mock import tx_id

    tx2 = encode_tx([(tx_id(tx1), 0)], [(b"dave", 50), (b"erin", 20)])
    b2 = forge_block(PARAMS, POOL, slot=2, block_no=1, prev_hash=b1.hash_,
                     epoch_nonce=ETA0, txs=(tx2,))
    assert db.add_block(b2).selected
    st = db.current_ledger().ledger_state
    assert dict(st.spec.balances) == {b"dave": 50, b"erin": 20, b"bob": 30}


def test_dual_state_snapshot_roundtrip():
    """DualState survives the v2 snapshot codec (the ChainDB writes a
    final snapshot on close, so the dual net must be serializable)."""
    from ouroboros_consensus_tpu.ledger.header_validation import HeaderState
    from ouroboros_consensus_tpu.ledger.extended import ExtLedgerState
    from ouroboros_consensus_tpu.protocol.praos import PraosState
    from ouroboros_consensus_tpu.storage import serialize

    ledger = DualLedger(mock_ledger.MockConfig(LVIEW, PARAMS.stability_window))
    st = ledger.genesis_state(GENESIS_OUTS)
    pair = ExtLedgerState(st, HeaderState(None, PraosState(epoch_nonce=ETA0)))
    assert serialize.decode_ext_state(serialize.encode_ext_state(pair)) == pair


def test_dual_ledger_catches_divergence():
    """Tampering with one side's state makes the next block application
    throw DualLedgerMismatch — the conformance alarm."""
    ledger = DualLedger(mock_ledger.MockConfig(LVIEW, PARAMS.stability_window))
    st = ledger.genesis_state(GENESIS_OUTS)
    # corrupt the SPEC side's own abstract UTxO: bob's output off by one
    bad_utxo = dict(st.spec.utxo)
    bad_utxo[(bytes(32), 1)] = (b"bob", 29)
    bad = DualState(st.impl, SpecState(bad_utxo))
    tx = encode_tx([(bytes(32), 0)], [(b"carol", 70)])
    b = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                    epoch_nonce=ETA0, txs=(tx,))
    with pytest.raises(DualLedgerMismatch):
        ledger.tick_then_apply(bad, b)


def test_dual_ledger_catches_validity_disagreement():
    """If one side accepts a tx the other rejects, the pairing throws:
    here the spec is missing the spent outpoint entirely, so the spec
    rejects (missing input) while the impl accepts."""
    ledger = DualLedger(mock_ledger.MockConfig(LVIEW, PARAMS.stability_window))
    st = ledger.genesis_state(GENESIS_OUTS)
    spec_utxo = dict(st.spec.utxo)
    del spec_utxo[(bytes(32), 0)]  # alice's output unknown to the spec
    bad = DualState(st.impl, SpecState(spec_utxo))
    tx = encode_tx([(bytes(32), 0)], [(b"carol", 70)])
    b = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                    epoch_nonce=ETA0, txs=(tx,))
    with pytest.raises(DualLedgerMismatch, match="validity disagreement"):
        ledger.tick_then_apply(bad, b)


def test_dual_both_sides_reject_invalid_tx():
    """An invalid tx (value not conserved) is rejected by BOTH sides in
    agreement: the impl's error propagates, no mismatch is raised."""
    from ouroboros_consensus_tpu.ledger.mock import ValueNotConserved

    ledger = DualLedger(mock_ledger.MockConfig(LVIEW, PARAMS.stability_window))
    st = ledger.genesis_state(GENESIS_OUTS)
    tx = encode_tx([(bytes(32), 0)], [(b"carol", 71)])  # creates value
    b = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                    epoch_nonce=ETA0, txs=(tx,))
    with pytest.raises(ValueNotConserved):
        ledger.tick_then_apply(st, b)

    # a float amount (decodable, non-int) must be an AGREED rejection,
    # not a validity disagreement: the spec rejects non-int amounts
    # rather than coercing 70.0 -> 70
    from ouroboros_consensus_tpu.ledger.mock import InvalidTx
    from ouroboros_consensus_tpu.utils import cbor

    float_tx = cbor.encode([[[bytes(32), 0]], [[b"carol", 70.0]]])
    b2 = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                     epoch_nonce=ETA0, txs=(float_tx,))
    with pytest.raises(InvalidTx):
        ledger.tick_then_apply(st, b2)

    # likewise a float input INDEX (0.0 finds the int-keyed outpoint
    # under dict lookup) and a tx with trailing garbage elements: both
    # must be agreed rejections, not mismatches
    for bad in (
        cbor.encode([[[bytes(32), 0.0]], [[b"carol", 70]]]),
        cbor.encode([[[bytes(32), 0]], [[b"carol", 70]], 99]),
    ):
        bb = forge_block(PARAMS, POOL, slot=1, block_no=0, prev_hash=None,
                         epoch_nonce=ETA0, txs=(bad,))
        with pytest.raises(InvalidTx):
            ledger.tick_then_apply(st, bb)
