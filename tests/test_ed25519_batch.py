"""Differential test: batched device Ed25519 verify vs host reference."""

import random

import numpy as np

from ouroboros_consensus_tpu.ops import ed25519_batch as eb
from ouroboros_consensus_tpu.ops.host import ed25519 as he


def _keypair(rng):
    seed = bytes(rng.randrange(256) for _ in range(32))
    return seed, he.secret_to_public(seed)


def test_ed25519_batch_mixed_valid_invalid():
    rng = random.Random(7)
    pks, sigs, msgs, want = [], [], [], []

    # 6 valid signatures, varied message lengths
    for n in (0, 1, 31, 64, 100, 200):
        seed, pk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(n))
        sig = he.sign(seed, msg)
        assert he.verify(pk, msg, sig)
        pks.append(pk)
        sigs.append(sig)
        msgs.append(msg)
        want.append(True)

    # corrupted signature R
    seed, pk = _keypair(rng)
    msg = b"corrupt-R"
    sig = bytearray(he.sign(seed, msg))
    sig[1] ^= 0x40
    pks.append(pk); sigs.append(bytes(sig)); msgs.append(msg); want.append(False)

    # corrupted s
    seed, pk = _keypair(rng)
    msg = b"corrupt-s"
    sig = bytearray(he.sign(seed, msg))
    sig[40] ^= 0x01
    pks.append(pk); sigs.append(bytes(sig)); msgs.append(msg); want.append(False)

    # corrupted message
    seed, pk = _keypair(rng)
    msg = b"the real message"
    sig = he.sign(seed, msg)
    pks.append(pk); sigs.append(sig); msgs.append(b"a fake message!!"); want.append(False)

    # wrong public key
    seed, pk = _keypair(rng)
    _, pk2 = _keypair(rng)
    msg = b"wrong pk"
    sig = he.sign(seed, msg)
    pks.append(pk2); sigs.append(sig); msgs.append(msg); want.append(False)

    # non-canonical s (s + L)
    seed, pk = _keypair(rng)
    msg = b"non-canonical s"
    sig = he.sign(seed, msg)
    s = int.from_bytes(sig[32:], "little")
    sig_nc = sig[:32] + int.to_bytes(s + he.L, 32, "little")
    pks.append(pk); sigs.append(sig_nc); msgs.append(msg); want.append(False)

    # undecodable public key (y >= p, canonicality)
    seed, pk = _keypair(rng)
    msg = b"bad point"
    sig = he.sign(seed, msg)
    bad_pk = int.to_bytes(he.P + 1, 32, "little")
    pks.append(bad_pk); sigs.append(sig); msgs.append(msg); want.append(False)

    # cross-check host reference agrees with expectations
    for pk, sig, msg, w in zip(pks, sigs, msgs, want):
        assert he.verify(pk, msg, sig) == w

    got = eb.verify_batch(pks, sigs, msgs)
    assert got.dtype == np.bool_
    assert list(got) == want
