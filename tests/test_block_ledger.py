"""Block model, CBOR codecs, mock ledger, extended validation (host-only)."""

from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.block import Block, Header, forge_block
from ouroboros_consensus_tpu.ledger import (
    ExtLedger,
    HeaderEnvelopeError,
    validate_envelope,
)
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.testing import fixtures

# f = 1: every slot is active for every pool (reference short-circuit,
# activeSlotVal == maxBound), so chains can be forged deterministically
PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),
    epoch_length=500,
    kes_depth=3,
)

POOLS = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)


def mk_ext():
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS)
    return ExtLedger(ledger, protocol), ledger


def forge_chain(n, eta0=b"\x11" * 32, txs_for=lambda i: ()):
    blocks = []
    prev = None
    for i in range(n):
        b = forge_block(
            PARAMS, POOLS[i % len(POOLS)], slot=i + 1, block_no=i,
            prev_hash=prev, epoch_nonce=eta0, txs=tuple(txs_for(i)),
        )
        blocks.append(b)
        prev = b.hash_
    return blocks


def test_header_roundtrip():
    blk = forge_chain(1)[0]
    h2 = Header.from_bytes(blk.header.bytes_)
    assert h2 == blk.header
    assert h2.hash_ == blk.header.hash_
    b2 = Block.from_bytes(blk.bytes_)
    assert b2 == blk
    assert b2.check_integrity()


def test_signed_bytes_cover_body():
    blk = forge_chain(1)[0]
    view = blk.header.to_view()
    assert view.signed_bytes == blk.header.body.signed_bytes
    # KES sig verifies over the signed bytes
    from ouroboros_consensus_tpu.ops.host import kes as hk

    t = PARAMS.kes_period_of(blk.slot) - blk.header.body.ocert.kes_period
    assert hk.verify(
        blk.header.body.ocert.vk_hot, PARAMS.kes_depth, t, view.signed_bytes, view.kes_sig
    )


def test_envelope_checks():
    blocks = forge_chain(3)
    ext, _ = mk_ext()
    st = ext.genesis(ext.ledger.genesis_state([]))
    # genesis expects block_no 0
    validate_envelope(None, blocks[0].header)
    with pytest.raises(HeaderEnvelopeError):
        validate_envelope(None, blocks[1].header)


def test_ext_ledger_chain_apply():
    eta0 = b"\x11" * 32
    ext, ledger = mk_ext()
    st = ext.genesis(ledger.genesis_state([]))
    # chain must be forged against the evolving protocol state's epoch
    # nonce; with one epoch (epoch_length=500) eta0 stays the initial one
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(st.header_state.chain_dep_state, epoch_nonce=eta0),
        ),
    )
    for blk in forge_chain(5):
        st = ext.tick_then_apply(st, blk)
    assert st.header_state.tip.block_no == 4
    assert ext.tip_slot(st) == 5

    # reapply reproduces the same state without crypto
    st2 = ext.genesis(ledger.genesis_state([]))
    st2 = replace(
        st2,
        header_state=replace(
            st2.header_state,
            chain_dep_state=replace(st2.header_state.chain_dep_state, epoch_nonce=eta0),
        ),
    )
    for blk in forge_chain(5):
        st2 = ext.tick_then_reapply(st2, blk)
    assert st2.header_state.tip == st.header_state.tip
    assert st2.header_state.chain_dep_state == st.header_state.chain_dep_state


def test_mock_ledger_utxo():
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    st = ledger.genesis_state([(b"alice", 100)])
    gtx = (bytes(32), 0)
    tx1 = mock_ledger.encode_tx([gtx], [(b"bob", 60), (b"alice", 40)])
    blocks = forge_chain(1, txs_for=lambda i: [tx1])
    st2 = ledger.tick_then_apply(st, blocks[0])
    tid = mock_ledger.tx_id(tx1)
    assert st2.utxo[(tid, 0)] == (b"bob", 60)
    assert gtx not in st2.utxo

    # double spend rejected
    tx_bad = mock_ledger.encode_tx([gtx], [(b"eve", 100)])
    blocks_bad = forge_chain(1, txs_for=lambda i: [tx1, tx_bad])
    with pytest.raises(mock_ledger.MissingInput):
        ledger.tick_then_apply(st, blocks_bad[0])

    # value conservation
    tx_inflate = mock_ledger.encode_tx([gtx], [(b"eve", 101)])
    blocks_inf = forge_chain(1, txs_for=lambda i: [tx_inflate])
    with pytest.raises(mock_ledger.ValueNotConserved):
        ledger.tick_then_apply(st, blocks_inf[0])


def test_forecast_horizon():
    ext, ledger = mk_ext()
    st = ledger.genesis_state([])
    fc = ledger.ledger_view_forecast_at(st)
    assert fc.forecast_for(0) is LVIEW
    from ouroboros_consensus_tpu.ledger.abstract import OutsideForecastRange

    with pytest.raises(OutsideForecastRange):
        fc.forecast_for(fc.max_for)
