"""octsync tier-1 gate (Pass 5): concurrency & durability checkers.

Three layers, mirroring test_analysis.py:
  1. fixture coverage — every SYNC rule fires on its purpose-built
     positive at the EXACT pinned (file, line) and honors its
     suppressed twin (tests/lint_fixtures/sync_*.py);
  2. the tree gate — zero unsuppressed findings over the shipped
     default roots, and the concurrency.json ratchet round-trips
     clean;
  3. the wiring — scripts/lint.py exits 7 on a seeded violation and
     maps --changed diffs onto the sweep; the `sync` subcommand's
     sorted-keys --json is byte-stable and exits 7 on its own.

The env-lever drift gate (analysis/envlevers.py) rides along: the
obs/README.md "## Levers" table must match the tree's actual
`os.environ` reads in both directions.
"""

import importlib.util
import json
import os

import pytest

from ouroboros_consensus_tpu.analysis import concurrency, envlevers, flow
from ouroboros_consensus_tpu.analysis.__main__ import main as analysis_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "lint_gate_sync", os.path.join(REPO, "scripts", "lint.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def _sweep_fixture(name):
    rep = concurrency.sweep_paths(
        [os.path.join(FIXTURES, f"{name}.py")], rel_to=FIXTURES
    )
    return rep.findings


# ---------------------------------------------------------------------------
# 1 — fixtures: exact (rule, line) pins per seeded violation
# ---------------------------------------------------------------------------

# (fixture module, unsuppressed (rule, line) pins, suppressed pins)
_FIXTURE_PINS = [
    ("sync_lock_order", [("SYNC201", 21)], [("SYNC201", 33)]),
    ("sync_acquire", [("SYNC202", 15)], [("SYNC202", 28)]),
    ("sync_guarded", [("SYNC203", 23)], [("SYNC203", 26)]),
    ("sync_threads",
     [("SYNC204", 40), ("SYNC205", 15), ("SYNC205", 22)],
     [("SYNC204", 48), ("SYNC205", 55)]),
    ("sync_install", [("SYNC206", 13)], [("SYNC206", 27)]),
    ("sync_durability", [("SYNC207", 17)], [("SYNC207", 33)]),
    ("sync_stale", [("SYNC208", 10)], []),
]


@pytest.mark.parametrize(
    "name,fired,suppressed", _FIXTURE_PINS,
    ids=[p[0] for p in _FIXTURE_PINS],
)
def test_fixture_exact_findings(name, fired, suppressed):
    """Set equality, not subset: a fixture firing anything beyond its
    pins means a checker regressed into noise."""
    found = _sweep_fixture(name)
    assert {(f.rule, f.line) for f in found if not f.suppressed} \
        == set(fired)
    assert {(f.rule, f.line) for f in found if f.suppressed} \
        == set(suppressed)
    assert all(f.path == f"{name}.py" for f in found)


def test_every_sync_rule_represented():
    all_rules = {r for _, fired, _ in _FIXTURE_PINS for r, _ in fired}
    assert all_rules == set(concurrency.RULES)


def test_suppressed_twin_for_every_suppressible_rule():
    # SYNC208 is the suppression audit itself — the one rule without a
    # suppressed twin in the fixture set
    twinned = {r for _, _, sup in _FIXTURE_PINS for r, _ in sup}
    assert twinned == set(concurrency.RULES) - {"SYNC208"}


def test_lock_order_reports_one_finding_per_cycle():
    found = [f for f in _sweep_fixture("sync_lock_order")
             if f.rule == "SYNC201"]
    # two cycles ({A,B} and {C,D}), each reported exactly once even
    # though each has two inverted edges
    assert len(found) == 2
    msgs = "\n".join(f.message for f in found)
    assert "sync_lock_order._A -> sync_lock_order._B" in msgs
    assert "sync_lock_order._C -> sync_lock_order._D" in msgs


def test_durability_blesses_tmp_rename_idiom():
    found = _sweep_fixture("sync_durability")
    # write_atomic's tmp write (line 25) must NOT fire: `.tmp` taint +
    # an os.replace in the same function is the blessed protocol
    assert not any(f.line == 25 for f in found)


def test_standalone_comment_does_not_suppress():
    src = (
        "import threading\n"
        "_L = threading.Lock()\n"
        "def grab():\n"
        "    # octsync: disable=SYNC202\n"
        "    _L.acquire()\n"
        "    return 1\n"
    )
    found = concurrency.sweep_source(src, "scopes")
    by_rule = {f.rule: f for f in found}
    # the comment line above the acquire suppresses nothing — the
    # grammar is line-exact (finding line or def line only) — so the
    # finding fires AND the comment is audited as stale
    assert not by_rule["SYNC202"].suppressed
    assert by_rule["SYNC208"].line == 4


def test_def_line_suppression_scopes_whole_function():
    src = (
        "import threading\n"
        "_L = threading.Lock()\n"
        "def grab():  # octsync: disable=SYNC202\n"
        "    _L.acquire()\n"
        "    return 1\n"
    )
    found = concurrency.sweep_source(src, "scopes")
    assert [f.rule for f in found] == ["SYNC202"]
    assert found[0].suppressed


# ---------------------------------------------------------------------------
# 2 — the tree gate + ratchet round-trip
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tree_report():
    return concurrency.sweep_paths(
        concurrency.default_roots(REPO), REPO, concurrency.load_roots()
    )


def test_tree_has_no_unsuppressed_findings(tree_report):
    bad = [f.format() for f in tree_report.findings if not f.suppressed]
    assert not bad, "\n".join(bad)


def test_ratchet_round_trips_clean(tree_report):
    violations, stale = concurrency.check_sync(
        tree_report, concurrency.load_baseline()
    )
    assert violations == []
    assert stale == []


def test_shipped_baseline_matches_payload(tree_report):
    payload = concurrency.baseline_payload(tree_report)
    shipped = concurrency.load_baseline()
    assert payload["findings"] == shipped["findings"] == []
    assert payload["inventory"] == shipped["inventory"]


def test_inventory_drift_is_a_violation(tree_report):
    base = json.loads(json.dumps(concurrency.load_baseline()))
    base["inventory"]["locks"] = base["inventory"]["locks"][:-1]
    violations, _ = concurrency.check_sync(tree_report, base)
    assert any("inventory drift in `locks`" in v for v in violations)


def test_new_finding_is_a_violation_and_keys_are_line_free():
    found = _sweep_fixture("sync_acquire")
    rep = concurrency.SyncReport(found, concurrency.load_baseline()
                                 .get("inventory", {}))
    violations, _ = concurrency.check_sync(
        rep, concurrency.load_baseline()
    )
    assert any("SYNC202" in v and "grab" in v for v in violations)
    # ratchet keys carry rule::path::message, never line numbers — a
    # pure-whitespace shift above a grandfathered finding cannot
    # resurrect it
    for f in found:
        assert f"::{f.line}" not in f.key()


# ---------------------------------------------------------------------------
# 3 — wiring: lint.py exit 7, --changed mapping, sync subcommand
# ---------------------------------------------------------------------------


def test_lint_changed_maps_concurrency_plane_to_sweep():
    lint = _load_lint()
    assert lint._sync_selected({"ouroboros_consensus_tpu/obs/live.py"})
    assert lint._sync_selected({"ouroboros_consensus_tpu/storage/guard.py"})
    assert lint._sync_selected({"ouroboros_consensus_tpu/analysis/sync_roots.json"})
    assert lint._sync_selected({"ouroboros_consensus_tpu/testing/chaos.py"})
    assert lint._sync_selected({"ouroboros_consensus_tpu/protocol/batch.py"})
    assert lint._sync_selected({"ouroboros_consensus_tpu/ops/pk/aot.py"})
    assert lint._sync_selected({"bench.py"})
    assert not lint._sync_selected({"README.md"})
    assert not lint._sync_selected({"ouroboros_consensus_tpu/ops/pk/msm.py"})
    # empty diff / no git -> conservative full sweep
    assert lint._sync_selected(set())


def test_lint_exits_7_on_seeded_violation(monkeypatch):
    """End to end through scripts/lint.py main(): poison the octsync
    roots with a fixture that fires, assert the NEW exit code, then
    assert --changed on an unrelated diff skips the sweep entirely."""
    lint = _load_lint()
    seeded = [os.path.join(FIXTURES, "sync_stale.py")]
    monkeypatch.setattr(concurrency, "default_roots", lambda repo: seeded)
    # scope the Pass-6 sweep to the same tiny file — exit 7 wins the
    # cascade regardless, and the whole-tree flow sweep is pinned by
    # test_flow.py's tree gate
    monkeypatch.setattr(flow, "default_roots", lambda repo=None: seeded)
    assert lint.main(["--no-graphs"]) == 7
    # an unrelated --changed diff skips the sweep: exit 0 even with
    # the poisoned roots
    monkeypatch.setattr(lint, "_changed_files", lambda: {"README.md"})
    assert lint.main(["--no-graphs", "--changed"]) == 0
    # a concurrency-plane diff selects it again
    monkeypatch.setattr(
        lint, "_changed_files",
        lambda: {"ouroboros_consensus_tpu/obs/live.py"},
    )
    assert lint.main(["--no-graphs", "--changed"]) == 7


def test_sync_subcommand_exit_and_json_byte_stable(capsys):
    fixture = os.path.join(FIXTURES, "sync_stale.py")
    # findings not in the shipped ratchet -> the distinct exit code
    assert analysis_cli(["sync", "--paths", fixture]) == 7
    capsys.readouterr()
    # --no-ratchet reports without enforcing
    assert analysis_cli(["sync", "--paths", fixture, "--no-ratchet"]) == 0
    capsys.readouterr()
    assert analysis_cli(
        ["sync", "--paths", fixture, "--no-ratchet", "--json"]
    ) == 0
    first = capsys.readouterr().out
    assert analysis_cli(
        ["sync", "--paths", fixture, "--no-ratchet", "--json"]
    ) == 0
    second = capsys.readouterr().out
    assert first == second  # byte-stable for CI diffing
    doc = json.loads(first)
    assert doc["ok"] is True
    assert [(f["rule"], f["line"]) for f in doc["findings"]] \
        == [("SYNC208", 10)]


def test_sync_subcommand_clean_tree_exits_0(capsys):
    assert analysis_cli(["sync", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["inventory"] == concurrency.load_baseline()["inventory"]


# ---------------------------------------------------------------------------
# env-lever drift gate (analysis/envlevers.py)
# ---------------------------------------------------------------------------


def test_env_lever_table_matches_tree():
    violations = envlevers.check_env_levers()
    assert not violations, "\n".join(violations)


def test_env_lever_gate_catches_both_directions(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "import os\n"
        "A = os.environ.get('OCT_FAKE_READ_LEVER')\n"
        "os.environ['OCT_FAKE_WRITE_LEVER'] = '1'\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "## Levers\n\n"
        "| Env | Effect |\n|---|---|\n"
        "| `OCT_FAKE_DOC_LEVER=1` | documented but never read |\n"
    )
    out = envlevers.check_env_levers([str(src)], str(readme))
    assert any("OCT_FAKE_READ_LEVER" in v and "no row" in v for v in out)
    assert any("OCT_FAKE_DOC_LEVER" in v and "nothing" in v for v in out)
    # a WRITE is not a read: bench.py sets levers for its child
    assert not any("OCT_FAKE_WRITE_LEVER" in v for v in out)


def test_env_lever_scanner_seams(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "import os\n"
        "_E = 'OCT_CONST_LEVER'\n"
        "A = os.environ.get(_E)\n"
        "B = os.getenv('OCT_GETENV_LEVER', '0')\n"
        "C = os.environ['OCT_SUBSCRIPT_LEVER']\n"
        "D = 'OCT_MEMBER_LEVER' in os.environ\n"
        "E = os.environ.get('NOT_A_LEVER')\n"
    )
    reads = envlevers.scan_reads([str(src)])
    assert reads == {"OCT_CONST_LEVER", "OCT_GETENV_LEVER",
                     "OCT_SUBSCRIPT_LEVER", "OCT_MEMBER_LEVER"}


def test_env_lever_variant_row_spellings_collapse(tmp_path):
    readme = tmp_path / "README.md"
    readme.write_text(
        "## Levers\n\n"
        "| Env | Effect |\n|---|---|\n"
        "| `OCT_V=<dir>` / `OCT_V=0` | one lever, two spellings |\n\n"
        "## Next section\n\n"
        "| `OCT_NOT_A_LEVER_ROW` | tables after Levers don't count |\n"
    )
    assert envlevers.documented_levers(str(readme)) == {"OCT_V"}
