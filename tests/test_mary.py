"""Mary-class era: multi-asset values, minting, validity intervals.

Reference: ShelleyMA eras (`Shelley/Eras.hs:82-97`) and their
translations (`Cardano/CanHardFork.hs:273`+).
"""

import pytest

from ouroboros_consensus_tpu.ledger import shelley as sh
from ouroboros_consensus_tpu.ledger.mary import (
    MaryLedger,
    MaryValue,
    MintError,
    OutsideValidityInterval,
    decode_tx,
    encode_tx,
    make_mint_witness,
    policy_id,
    translate_tx_from_shelley,
)
from ouroboros_consensus_tpu.ledger.shelley import (
    ExpiredTx,
    PParams,
    ShelleyGenesis,
    ShelleyLedger,
    ShelleyTxError,
    ValueNotConserved,
)
from ouroboros_consensus_tpu.ops.host import ed25519 as ed

ALICE = b"\x0a" * 28
BOB = b"\x0b" * 28
POLICY_SEED = b"\x5f" * 32
GENESIS_IN = (bytes(32), 0)

PP = PParams(min_fee_a=0, min_fee_b=0)


def _ledger():
    return MaryLedger(ShelleyGenesis(
        pparams=PP, epoch_length=100, stability_window=30,
    ))


def _state(led, coin=1_000):
    return led.genesis_state([(ALICE, None, coin)])


class _Blk:
    def __init__(self, slot, txs):
        self.slot = slot
        self.txs = tuple(txs)


def test_mary_value_is_int_compatible():
    v = MaryValue(100, {(b"p" * 28, b"tok"): 5})
    assert v == 100 and v + 1 == 101 and sum([v, v]) == 200
    assert v.asset_map() == {(b"p" * 28, b"tok"): 5}
    # zero quantities are normalized away
    assert MaryValue(7, {(b"p" * 28, b"t"): 0}).assets == ()


def test_mint_and_transfer_asset():
    led = _ledger()
    st = _state(led)
    pid = policy_id(ed.secret_to_public(POLICY_SEED))

    # mint 50 "tok" into bob's output
    outs = [(BOB, None, MaryValue(1_000, {(pid, b"tok"): 50}))]
    wit = make_mint_witness(
        POLICY_SEED, [GENESIS_IN], outs, 0, (None, None), {b"tok": 50}
    )
    tx = encode_tx([GENESIS_IN], outs, mint=[wit])
    st2 = led.apply_block(led.tick(st, 5), _Blk(5, [tx]))
    (val,) = [v for _a, v in st2.utxo.values()]
    assert int(val) == 1_000 and val.asset_map() == {(pid, b"tok"): 50}

    # transfer: split the asset across two outputs, conservation holds
    tid = sh.tx_id(tx)
    outs2 = [
        (ALICE, None, MaryValue(400, {(pid, b"tok"): 20})),
        (BOB, None, MaryValue(600, {(pid, b"tok"): 30})),
    ]
    tx2 = encode_tx([(tid, 0)], outs2)
    st3 = led.apply_block(led.tick(st2, 6), _Blk(6, [tx2]))
    assert sorted(
        (int(v), dict(v.assets)) for _a, v in st3.utxo.values()
    ) == [(400, {(pid, b"tok"): 20}), (600, {(pid, b"tok"): 30})]


def test_asset_conservation_enforced():
    led = _ledger()
    st = _state(led)
    pid = policy_id(ed.secret_to_public(POLICY_SEED))

    # produce an asset with NO mint: rejected
    outs = [(BOB, None, MaryValue(1_000, {(pid, b"tok"): 1}))]
    tx = encode_tx([GENESIS_IN], outs)
    with pytest.raises(ValueNotConserved):
        led.apply_block(led.tick(st, 1), _Blk(1, [tx]))

    # mint witnessed by the WRONG key for the claimed policy: the id of
    # the signing key differs, so the group mints a different policy id
    wrong = b"\x66" * 32
    wit = make_mint_witness(
        wrong, [GENESIS_IN], outs, 0, (None, None), {b"tok": 1}
    )
    tx = encode_tx([GENESIS_IN], outs, mint=[wit])
    with pytest.raises(ValueNotConserved):
        led.apply_block(led.tick(st, 1), _Blk(1, [tx]))

    # corrupted mint signature: MintError
    vk, sig, am = make_mint_witness(
        POLICY_SEED, [GENESIS_IN], outs, 0, (None, None), {b"tok": 1}
    )
    bad = (vk, sig[:-1] + bytes([sig[-1] ^ 1]), am)
    tx = encode_tx([GENESIS_IN], outs, mint=[bad])
    with pytest.raises(MintError):
        led.apply_block(led.tick(st, 1), _Blk(1, [tx]))


def test_burn_assets():
    led = _ledger()
    st = _state(led)
    pid = policy_id(ed.secret_to_public(POLICY_SEED))
    outs = [(BOB, None, MaryValue(1_000, {(pid, b"tok"): 50}))]
    wit = make_mint_witness(
        POLICY_SEED, [GENESIS_IN], outs, 0, (None, None), {b"tok": 50}
    )
    tx = encode_tx([GENESIS_IN], outs, mint=[wit])
    st = led.apply_block(led.tick(st, 1), _Blk(1, [tx]))
    tid = sh.tx_id(tx)

    # burn 30 of the 50 (negative mint), keep 20
    outs2 = [(BOB, None, MaryValue(1_000, {(pid, b"tok"): 20}))]
    wit2 = make_mint_witness(
        POLICY_SEED, [(tid, 0)], outs2, 0, (None, None), {b"tok": -30}
    )
    tx2 = encode_tx([(tid, 0)], outs2, mint=[wit2])
    st2 = led.apply_block(led.tick(st, 2), _Blk(2, [tx2]))
    (val,) = [v for _a, v in st2.utxo.values()]
    assert val.asset_map() == {(pid, b"tok"): 20}


def test_validity_interval():
    led = _ledger()
    st = _state(led)
    outs = [(BOB, None, 1_000)]

    # not yet valid
    tx = encode_tx([GENESIS_IN], outs, validity=(10, 20))
    with pytest.raises(OutsideValidityInterval):
        led.apply_block(led.tick(st, 5), _Blk(5, [tx]))
    # expired
    with pytest.raises(ExpiredTx):
        led.apply_block(led.tick(st, 25), _Blk(25, [tx]))
    # in range
    st2 = led.apply_block(led.tick(st, 15), _Blk(15, [tx]))
    assert ((BOB, None), 1_000) in [
        (a, int(v)) for a, v in st2.utxo.values()
    ]
    # open-ended interval always valid
    tx2 = decode_tx(encode_tx([GENESIS_IN], outs, validity=(None, None)))
    assert tx2.start is None and tx2.end is None


def test_era_differentiation_same_tx_rejected_in_shelley():
    """The SAME bytes are a valid Mary tx and an invalid Shelley tx —
    the rule sets genuinely differ (VERDICT r3 item 6's 'tx rejected in
    one era and valid in the next')."""
    mary = _ledger()
    shelley_led = ShelleyLedger(mary.genesis)
    st_mary = _state(mary)
    st_sh = shelley_led.genesis_state([(ALICE, None, 1_000)])

    tx = encode_tx([GENESIS_IN], [(BOB, None, 1_000)], validity=(None, None))
    # valid under Mary
    mary.apply_block(mary.tick(st_mary, 1), _Blk(1, [tx]))
    # malformed under Shelley (6-element wire, not 7)
    with pytest.raises(ShelleyTxError):
        shelley_led.apply_block(shelley_led.tick(st_sh, 1), _Blk(1, [tx]))


def test_shelley_to_mary_translation_and_tx_injection():
    led_sh = ShelleyLedger(ShelleyGenesis(
        pparams=PP, epoch_length=100, stability_window=30,
    ))
    st = led_sh.genesis_state([(ALICE, None, 1_000)])
    mary = MaryLedger(led_sh.genesis)

    st_m = mary.translate_from_shelley(st)
    # values widened to MaryValue, ada preserved
    (val,) = [v for _a, v in st_m.utxo.values()]
    assert isinstance(val, MaryValue) and int(val) == 1_000

    # a Shelley-era mempool tx crosses the boundary via tx injection
    sh_tx = sh.encode_tx([GENESIS_IN], [(BOB, None, 1_000)], fee=0, ttl=50)
    m_tx = translate_tx_from_shelley(sh_tx)
    st_m2 = mary.apply_block(mary.tick(st_m, 5), _Blk(5, [m_tx]))
    assert ((BOB, None), 1_000) in [
        (a, int(v)) for a, v in st_m2.utxo.values()
    ]
    # and the translated ttl still expires
    with pytest.raises(ExpiredTx):
        mary.apply_block(mary.tick(st_m, 60), _Blk(60, [m_tx]))


def test_mary_inherits_shelley_certs_and_epochs():
    """Certificates + epoch machinery run unchanged under Mary (shared
    rule family): register a stake cred, delegate, cross an epoch; the
    multi-asset utxo feeds the stake snapshot by its ADA component."""
    led = _ledger()
    pid = policy_id(ed.secret_to_public(POLICY_SEED))
    stake_cred = b"\x77" * 28
    st = led.genesis_state([(ALICE, stake_cred, 5_000_000)])

    tx = encode_tx(
        [GENESIS_IN],
        [(ALICE, stake_cred,
          MaryValue(5_000_000 - led.genesis.pparams.key_deposit))],
        certs=[(0, stake_cred)],
    )
    # key_deposit defaults to 0 in our PP? No: PParams defaults. Use the
    # real equation: consumed = produced + deposit
    st2 = led.apply_block(led.tick(st, 1), _Blk(1, [tx]))
    assert stake_cred in st2.stake_creds
    # epoch boundary rotates snapshots with the Mary-valued utxo
    st3 = led.tick(st2, 100).state
    assert st3.epoch == 1
    assert st3.mark.stake.get(stake_cred, 0) > 0


def test_mary_reapply_parses_mary_wire():
    """REAPPLY (the LedgerDB fast path for previously-validated blocks:
    fork-switch replay, crash recovery) must parse the MARY wire format
    — the inherited Shelley reapply decoding Mary txs was a crash
    (round-4 review finding)."""
    led = _ledger()
    st = _state(led)
    pid = policy_id(ed.secret_to_public(POLICY_SEED))
    outs = [(BOB, None, MaryValue(1_000, {(pid, b"tok"): 5}))]
    wit = make_mint_witness(
        POLICY_SEED, [GENESIS_IN], outs, 0, (None, None), {b"tok": 5}
    )
    tx = encode_tx([GENESIS_IN], outs, mint=[wit])
    blk = _Blk(3, [tx])
    applied = led.apply_block(led.tick(st, 3), blk)
    reapplied = led.reapply_block(led.tick(st, 3), blk)
    assert dict(reapplied.utxo) == dict(applied.utxo)
    (val,) = [v for _a, v in reapplied.utxo.values()]
    assert isinstance(val, MaryValue) and val.asset_map() == {(pid, b"tok"): 5}
