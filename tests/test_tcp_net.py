"""Node-to-node over real TCP sockets: the full versioned bundle
(handshake + ChainSync + BlockFetch + TxSubmission2 + KeepAlive) between
two complete nodes on localhost.

Reference: the diffusion layer handing the mini-protocol Apps to
socket-based `ouroboros-network` (`Node.hs:103-120`,
`Network/NodeToNode.hs:434-466`); SURVEY §7.2 step 8 ("in-memory channel
transport first, TCP second"). The SAME protocol generators ThreadNet
drives under the deterministic Sim run here under utils/aio.AsyncRuntime
— the IOLike seam, crossed for real.
"""

import asyncio
import os
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger.extended import ExtLedger
from ouroboros_consensus_tpu.ledger.mock import MockConfig, MockLedger
from ouroboros_consensus_tpu.node import transport
from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock
from ouroboros_consensus_tpu.miniprotocol import handshake
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.aio import AsyncRuntime

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=60,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=3,
)
POOLS = [fixtures.make_pool(0, kes_depth=3)]
LVIEW = fixtures.make_ledger_view(POOLS)
N_SLOTS = 120
SLOT_LEN = 0.02


def _mk_node(base: str, i: int, *, forger: bool) -> NodeKernel:
    ledger = MockLedger(MockConfig(LVIEW, PARAMS.stability_window))
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    genesis = ext.genesis(
        ledger.genesis_state([(b"g-%d" % k, 100) for k in range(4)])
    )
    db = open_chaindb(
        os.path.join(base, f"node{i}"), ext, genesis, PARAMS.security_param
    )
    return NodeKernel(
        f"node{i}", db, protocol, ledger,
        pool=POOLS[0] if forger else None,
        clock=SlotClock(SLOT_LEN),
    )


def _chain_len(node) -> int:
    return len(list(node.chain_db.stream_all()))


async def _converged(node, want: int, timeout: float = 30.0) -> int:
    t0 = asyncio.get_event_loop().time()
    while True:
        n = _chain_len(node)
        if n >= want:
            return n
        if asyncio.get_event_loop().time() - t0 > timeout:
            return n
        await asyncio.sleep(0.05)


def test_sync_over_tcp(tmp_path):
    """A fresh node syncs 100+ blocks from a forger over a localhost
    socket and converges to the identical chain (VERDICT r3 item 7)."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        forger.chain_db.runtime = runtime
        syncer.chain_db.runtime = runtime
        server = await transport.serve_node(forger, runtime)
        port = server.sockets[0].getsockname()[1]
        runtime.spawn(forger.forging_loop(N_SLOTS), "forge")
        mux = await transport.connect_node(
            syncer, runtime, "127.0.0.1", port
        )
        assert mux is not None
        n = await _converged(syncer, N_SLOTS)
        forged = _chain_len(forger)
        assert forged >= 100, f"forger only made {forged} blocks"
        assert n == forged, f"syncer at {n}/{forged}"
        a = [b.hash_ for b in forger.chain_db.stream_all()]
        b = [b.hash_ for b in syncer.chain_db.stream_all()]
        assert a == b
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_tx_diffusion_over_tcp(tmp_path):
    """TxSubmission2 over the socket: a tx submitted to the FORGER
    reaches the downstream peer's mempool through the outbound/inbound
    pair (the reference's tx flow is server→client pull)."""
    from ouroboros_consensus_tpu.ledger.mock import encode_tx

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        forger.chain_db.runtime = runtime
        syncer.chain_db.runtime = runtime
        server = await transport.serve_node(forger, runtime)
        port = server.sockets[0].getsockname()[1]
        await transport.connect_node(syncer, runtime, "127.0.0.1", port)
        tx = encode_tx([(bytes(32), 0)], [(b"tcp-paid", 100)])
        forger.mempool.add_tx(tx)
        for _ in range(100):
            if syncer.mempool.get_snapshot().txs:
                break
            await asyncio.sleep(0.05)
        got = [t.tx for t in syncer.mempool.get_snapshot().txs]
        assert tx in got, "tx never diffused over TCP"
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_handshake_magic_mismatch_refused(tmp_path):
    """Cross-network dial: mismatched network magic is refused at the
    wire handshake, no protocols start (stdVersionDataNTN guard)."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        server = await transport.serve_node(
            forger, runtime,
            versions={
                v: handshake.VersionData(network_magic=1)
                for v in handshake.NODE_TO_NODE_VERSIONS
            },
        )
        port = server.sockets[0].getsockname()[1]
        with pytest.raises(handshake.HandshakeRefused):
            await transport.connect_node(
                syncer, runtime, "127.0.0.1", port,
                versions={
                    v: handshake.VersionData(network_magic=2)
                    for v in handshake.NODE_TO_NODE_VERSIONS
                },
            )
        server.close()
        await runtime.shutdown()

    asyncio.run(run())
