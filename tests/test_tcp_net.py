"""Node-to-node over real TCP sockets: the full versioned bundle
(handshake + ChainSync + BlockFetch + TxSubmission2 + KeepAlive) between
two complete nodes on localhost.

Reference: the diffusion layer handing the mini-protocol Apps to
socket-based `ouroboros-network` (`Node.hs:103-120`,
`Network/NodeToNode.hs:434-466`); SURVEY §7.2 step 8 ("in-memory channel
transport first, TCP second"). The SAME protocol generators ThreadNet
drives under the deterministic Sim run here under utils/aio.AsyncRuntime
— the IOLike seam, crossed for real.
"""

import asyncio
import os
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger.extended import ExtLedger
from ouroboros_consensus_tpu.ledger.mock import MockConfig, MockLedger
from ouroboros_consensus_tpu.node import transport
from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock
from ouroboros_consensus_tpu.miniprotocol import handshake
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.aio import AsyncRuntime

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=60,
    active_slot_coeff=Fraction(1),
    epoch_length=10_000,
    kes_depth=3,
)
POOLS = [fixtures.make_pool(0, kes_depth=3)]
LVIEW = fixtures.make_ledger_view(POOLS)
N_SLOTS = 120
SLOT_LEN = 0.02


def _mk_node(base: str, i: int, *, forger: bool, lview=None, pool=None,
             slot_len: float = SLOT_LEN) -> NodeKernel:
    ledger = MockLedger(MockConfig(
        lview if lview is not None else LVIEW, PARAMS.stability_window
    ))
    protocol = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, protocol)
    genesis = ext.genesis(
        ledger.genesis_state([(b"g-%d" % k, 100) for k in range(4)])
    )
    db = open_chaindb(
        os.path.join(base, f"node{i}"), ext, genesis, PARAMS.security_param
    )
    return NodeKernel(
        f"node{i}", db, protocol, ledger,
        pool=(pool if pool is not None else POOLS[0]) if forger else None,
        clock=SlotClock(slot_len),
    )


def _chain_len(node) -> int:
    return len(list(node.chain_db.stream_all()))


async def _converged(node, want: int, timeout: float = 30.0) -> int:
    t0 = asyncio.get_event_loop().time()
    while True:
        n = _chain_len(node)
        if n >= want:
            return n
        if asyncio.get_event_loop().time() - t0 > timeout:
            return n
        await asyncio.sleep(0.05)


def test_sync_over_tcp(tmp_path):
    """A fresh node syncs 100+ blocks from a forger over a localhost
    socket and converges to the identical chain (VERDICT r3 item 7)."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        forger.chain_db.runtime = runtime
        syncer.chain_db.runtime = runtime
        server = await transport.serve_node(forger, runtime)
        port = server.sockets[0].getsockname()[1]
        runtime.spawn(forger.forging_loop(N_SLOTS), "forge")
        mux = await transport.connect_node(
            syncer, runtime, "127.0.0.1", port
        )
        assert mux is not None
        n = await _converged(syncer, N_SLOTS)
        forged = _chain_len(forger)
        assert forged >= 100, f"forger only made {forged} blocks"
        assert n == forged, f"syncer at {n}/{forged}"
        a = [b.hash_ for b in forger.chain_db.stream_all()]
        b = [b.hash_ for b in syncer.chain_db.stream_all()]
        assert a == b
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_tx_diffusion_over_tcp(tmp_path):
    """TxSubmission2 over the socket: a tx submitted to the FORGER
    reaches the downstream peer's mempool through the outbound/inbound
    pair (the reference's tx flow is server→client pull)."""
    from ouroboros_consensus_tpu.ledger.mock import encode_tx

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        forger.chain_db.runtime = runtime
        syncer.chain_db.runtime = runtime
        server = await transport.serve_node(forger, runtime)
        port = server.sockets[0].getsockname()[1]
        await transport.connect_node(syncer, runtime, "127.0.0.1", port)
        tx = encode_tx([(bytes(32), 0)], [(b"tcp-paid", 100)])
        forger.mempool.add_tx(tx)
        for _ in range(100):
            if syncer.mempool.get_snapshot().txs:
                break
            await asyncio.sleep(0.05)
        got = [t.tx for t in syncer.mempool.get_snapshot().txs]
        assert tx in got, "tx never diffused over TCP"
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_handshake_magic_mismatch_refused(tmp_path):
    """Cross-network dial: mismatched network magic is refused at the
    wire handshake, no protocols start (stdVersionDataNTN guard)."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        server = await transport.serve_node(
            forger, runtime,
            versions={
                v: handshake.VersionData(network_magic=1)
                for v in handshake.NODE_TO_NODE_VERSIONS
            },
        )
        port = server.sockets[0].getsockname()[1]
        with pytest.raises(handshake.HandshakeRefused):
            await transport.connect_node(
                syncer, runtime, "127.0.0.1", port,
                versions={
                    v: handshake.VersionData(network_magic=2)
                    for v in handshake.NODE_TO_NODE_VERSIONS
                },
            )
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_node_to_client_over_tcp(tmp_path):
    """The node-to-client bundle over a local socket (the reference's
    wallet/CLI surface, Network/NodeToClient.hs): handshake, then
    LocalStateQuery acquire/query/release, LocalTxSubmission, and
    LocalTxMonitor against a live forging node."""
    from ouroboros_consensus_tpu.ledger.mock import encode_tx

    async def run():
        runtime = AsyncRuntime()
        node = _mk_node(str(tmp_path), 0, forger=True)
        node.chain_db.runtime = runtime
        server = await transport.serve_node_to_client(node, runtime)
        port = server.sockets[0].getsockname()[1]
        forge_task = runtime.spawn(node.forging_loop(20), "forge")
        await asyncio.sleep(0.5)  # a few blocks first

        cli = await transport.LocalClient.connect(
            runtime, "127.0.0.1", port
        )
        assert cli.version == max(handshake.NODE_TO_CLIENT_VERSIONS)

        # LocalStateQuery session
        r = await cli.request("localstatequery", ("acquire", None))
        assert r == ("acquired",)
        r = await cli.request(
            "localstatequery", ("query", "get_tip_slot", ())
        )
        assert r[0] == "result" and r[1] >= 1
        r = await cli.request(
            "localstatequery", ("query", "get_balance", (b"g-0",))
        )
        assert r == ("result", 100)
        # era-mismatch failure travels the wire as a failure, not a hang
        r = await cli.request(
            "localstatequery", ("query", "get_epoch_no", ())
        )
        assert r[0] == "failed"

        # stop the forger before the mempool protocols: a forge landing
        # between submit and the monitor's snapshot flushes the tx into
        # a block, and the monitor honestly answers no_more — a timing
        # race on a loaded box, not a protocol property
        forge_task.cancel()
        await asyncio.gather(forge_task, return_exceptions=True)

        # LocalTxSubmission: a valid tx accepted, a garbage one rejected
        tx = encode_tx([(bytes(32), 1)], [(b"n2c-paid", 100)])
        r = await cli.request("localtxsubmission", ("submit", tx))
        assert r == ("accepted",)
        r = await cli.request("localtxsubmission", ("submit", b"junk"))
        assert r[0] == "rejected"

        # LocalTxMonitor sees the submitted tx
        r = await cli.request("localtxmonitor", ("acquire",))
        assert r[0] == "acquired"
        r = await cli.request("localtxmonitor", ("next_tx",))
        assert r[0] == "tx" and r[1] == tx

        cli.close()
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_peer_discovery_over_tcp(tmp_path):
    """PeerSharing mechanics over sockets: C dials relay R, learns the
    forger F's address from R's sharing registry, dials F directly and
    syncs — the discovery handoff the reference's P2P governor drives
    (the governor itself lives in ouroboros-network, out of consensus
    scope; consensus contributes the registry + the mini-protocol)."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        relay = _mk_node(str(tmp_path), 1, forger=False)
        edge = _mk_node(str(tmp_path), 2, forger=False)
        for n in (forger, relay, edge):
            n.chain_db.runtime = runtime

        f_srv = await transport.serve_node(forger, runtime)
        f_port = f_srv.sockets[0].getsockname()[1]
        r_srv = await transport.serve_node(relay, runtime)
        r_port = r_srv.sockets[0].getsockname()[1]

        runtime.spawn(forger.forging_loop(60), "forge")
        await transport.connect_node(relay, runtime, "127.0.0.1", f_port)
        assert [("127.0.0.1"), f_port] in [
            list(p) for p in relay.known_peers
        ]

        mux = await transport.connect_node(
            edge, runtime, "127.0.0.1", r_port
        )
        ps_task = next(
            t for t in mux.tasks if "peersharing" in t.get_name()
        )
        peers = await ps_task
        assert ["127.0.0.1", f_port] in [list(p) for p in peers]

        # act on the discovery: dial the forger directly and converge
        host, port = peers[0]
        await transport.connect_node(edge, runtime, host, port)
        n = await _converged(edge, 55, timeout=20)
        assert n >= 55, n

        f_srv.close()
        r_srv.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_n2c_wire_totality_and_disconnect(tmp_path):
    """The wire codec is TOTAL: dataclass query results travel as
    tagged field maps (never killing the server task), Mary values keep
    their assets, and a dropped connection surfaces as ConnectionError
    on the client instead of a hang."""
    from ouroboros_consensus_tpu.ledger.mary import MaryValue
    from ouroboros_consensus_tpu.node.transport import from_wire, to_wire

    # round-trip the rich types the query surface produces
    mv = MaryValue(70, {(b"p" * 28, b"tok"): 9})
    back = from_wire(to_wire(mv))
    assert isinstance(back, MaryValue) and int(back) == 70
    assert back.asset_map() == {(b"p" * 28, b"tok"): 9}
    from ouroboros_consensus_tpu.ledger.shelley import PParams

    dumped = from_wire(to_wire(PParams()))
    assert dumped["__type__"] == "PParams"
    assert dumped["min_fee_a"] == PParams().min_fee_a
    # the desperate fallback is lossy but non-fatal
    assert from_wire(to_wire(object()))[0] == "opaque"

    async def run():
        runtime = AsyncRuntime()
        node = _mk_node(str(tmp_path), 0, forger=False)
        node.chain_db.runtime = runtime
        server = await transport.serve_node_to_client(node, runtime)
        port = server.sockets[0].getsockname()[1]
        cli = await transport.LocalClient.connect(
            runtime, "127.0.0.1", port
        )
        r = await cli.request("localstatequery", ("acquire", None))
        assert r == ("acquired",)
        # a dataclass-rich result crosses the wire as a tagged map
        r = await cli.request(
            "localstatequery", ("query", "get_utxo", ())
        )
        assert r[0] == "result" and len(r[1]) == 4
        # drop the connection; an in-flight request must raise, not
        # hang (the client's pump sees EOF and sets mux.closed)
        server.close()
        cli.mux.writer.close()
        try:
            await asyncio.wait_for(
                cli.request("localstatequery", ("query", "get_utxo", ())),
                timeout=5,
            )
            raise AssertionError("request should have failed")
        except (ConnectionError, OSError):
            pass  # ConnectionError from mux.closed, or the closed writer
        except asyncio.TimeoutError:
            raise AssertionError("request hung on a dead connection")
        await runtime.shutdown()

    asyncio.run(run())


def test_reconnect_resumes_from_intersection(tmp_path):
    """A syncer that loses its connection mid-sync reconnects and
    RESUMES from the intersection of its existing chain (find_intersect
    with non-genesis points over the wire), not from scratch."""

    async def run():
        runtime = AsyncRuntime()
        forger = _mk_node(str(tmp_path), 0, forger=True)
        syncer = _mk_node(str(tmp_path), 1, forger=False)
        forger.chain_db.runtime = runtime
        syncer.chain_db.runtime = runtime
        server = await transport.serve_node(forger, runtime)
        port = server.sockets[0].getsockname()[1]
        runtime.spawn(forger.forging_loop(N_SLOTS), "forge")

        mux = await transport.connect_node(
            syncer, runtime, "127.0.0.1", port
        )
        # let it sync part of the chain, then cut the connection
        await _converged(syncer, 40, timeout=15)
        mid = _chain_len(syncer)
        assert mid >= 40
        for t in mux.tasks:
            t.cancel()
        mux.pump_task.cancel()
        mux.writer.close()

        # reconnect: the client offers its tip among the intersect
        # points; the server streams only the suffix
        await transport.connect_node(syncer, runtime, "127.0.0.1", port)
        n = await _converged(syncer, N_SLOTS, timeout=20)
        forged = _chain_len(forger)
        assert n == forged >= 100, (n, forged)
        a = [b.hash_ for b in forger.chain_db.stream_all()]
        b = [b.hash_ for b in syncer.chain_db.stream_all()]
        assert a == b
        server.close()
        await runtime.shutdown()

    asyncio.run(run())


def test_full_mesh_all_forging_over_tcp(tmp_path):
    """Three complete nodes, full mesh over real sockets, ALL forging
    every slot (f=1): same-slot ties resolve by the VRF tie-break and
    every node converges on the identical chain — the closest shape to
    a real deployment this suite runs (asyncio timing, concurrent
    forging, chain selection under contention). Slot length must beat
    the 1-core box's gossip latency or every node outruns its peers'
    candidates forever (measured: 0.02 s slots never converge)."""

    slot_len = 0.15
    n_slots = 40
    pools3 = [fixtures.make_pool(i, kes_depth=3) for i in range(3)]
    lview3 = fixtures.make_ledger_view(pools3)

    async def run():
        runtime = AsyncRuntime()
        nodes = []
        for i in range(3):
            n = _mk_node(str(tmp_path / f"mesh{i}"), i, forger=True,
                         lview=lview3, pool=pools3[i], slot_len=slot_len)
            n.chain_db.runtime = runtime
            nodes.append(n)
        servers = []
        for n in nodes:
            servers.append(await transport.serve_node(n, runtime))
        ports = [s.sockets[0].getsockname()[1] for s in servers]
        for i, n in enumerate(nodes):
            for j, p in enumerate(ports):
                if i != j:
                    await transport.connect_node(n, runtime, "127.0.0.1", p)
        for i, n in enumerate(nodes):
            runtime.spawn(n.forging_loop(n_slots), f"forge{i}")

        # convergence: identical chains across all three, >= 30 blocks
        deadline = asyncio.get_event_loop().time() + 40
        while True:
            chains = [
                [b.hash_ for b in n.chain_db.stream_all()] for n in nodes
            ]
            if (len(chains[0]) >= 30
                    and chains[0] == chains[1] == chains[2]):
                break
            assert asyncio.get_event_loop().time() < deadline, (
                f"no convergence: lens {[len(c) for c in chains]}"
            )
            await asyncio.sleep(0.1)
        for s in servers:
            s.close()
        await runtime.shutdown()
        return len(chains[0])

    n = asyncio.run(run())
    assert n >= 30
