"""Differential tests: ops/pk/curve vs the host reference point arithmetic.

The ladder tests (scalar_mul_w4 / double_scalar_mul_w4 / base_mul_w8 /
compress chains) compile for minutes on single-core XLA:CPU, so they are
gated behind OCT_SLOW_TESTS=1; add/double/decompress stay in the default
suite. TPU coverage: scripts/debug_pk_tpu.py + bench.py run the same
code through Mosaic on hardware.
"""

import os

import numpy as np
import pytest

_slow = pytest.mark.skipif(
    not os.environ.get("OCT_SLOW_TESTS"),
    reason="multi-minute XLA:CPU compile; set OCT_SLOW_TESTS=1",
)

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops import field as fe_b
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.pk import curve as pc
from ouroboros_consensus_tpu.ops.pk import limbs as fe

B = 32
rng = np.random.default_rng(7)


def host_points(b=B):
    """Random curve points (multiples of B) as host affine ints."""
    pts = []
    for i in range(b):
        k = int(rng.integers(1, 2**60))
        p = he.point_mul(k, he.B)
        zi = pow(p[2], fe.P_INT - 2, fe.P_INT)
        pts.append((p[0] * zi % fe.P_INT, p[1] * zi % fe.P_INT))
    return pts


def stage_points(pts):
    """Affine host points -> device Point [20, B]."""
    x = np.stack([fe_b.int_to_limbs_np(p[0]) for p in pts], axis=1)
    y = np.stack([fe_b.int_to_limbs_np(p[1]) for p in pts], axis=1)
    t = np.stack(
        [fe_b.int_to_limbs_np(p[0] * p[1] % fe.P_INT) for p in pts], axis=1
    )
    one = np.tile(fe_b.int_to_limbs_np(1)[:, None], (1, len(pts)))
    return pc.Point(jnp.asarray(x), jnp.asarray(y), jnp.asarray(one), jnp.asarray(t))


def affine_of(point) -> list[tuple[int, int]]:
    x, y, z = (np.asarray(c) for c in (point.x, point.y, point.z))
    out = []
    for i in range(x.shape[1]):
        zi = pow(fe_b.limbs_to_int_np(z[:, i]) % fe.P_INT, fe.P_INT - 2, fe.P_INT)
        out.append(
            (
                fe_b.limbs_to_int_np(x[:, i]) * zi % fe.P_INT,
                fe_b.limbs_to_int_np(y[:, i]) * zi % fe.P_INT,
            )
        )
    return out


def host_affine(p):
    zi = pow(p[2], fe.P_INT - 2, fe.P_INT)
    return (p[0] * zi % fe.P_INT, p[1] * zi % fe.P_INT)


@pytest.fixture(scope="module")
def pts():
    hp = host_points()
    return hp, stage_points(hp)


def test_add_double(pts):
    hp, dp = pts
    got = affine_of(jax.jit(pc.double)(dp))
    want = [host_affine(he.point_double((x, y, 1, x * y % fe.P_INT))) for x, y in hp]
    assert got == want
    hp2 = list(reversed(hp))
    dp2 = stage_points(hp2)
    got = affine_of(jax.jit(pc.add)(dp, dp2))
    want = [
        host_affine(
            he.point_add((x1, y1, 1, x1 * y1 % fe.P_INT), (x2, y2, 1, x2 * y2 % fe.P_INT))
        )
        for (x1, y1), (x2, y2) in zip(hp, hp2)
    ]
    assert got == want


@_slow
def test_scalar_mul_w4(pts):
    hp, dp = pts
    ks = [int.from_bytes(rng.bytes(32), 'little') >> 3 for _ in range(B)]
    digits = np.zeros((64, B), np.int32)
    for i, k in enumerate(ks):
        for w in range(64):
            digits[w, i] = (k >> (4 * w)) & 0xF
    digits_msb = jnp.asarray(digits[::-1].copy())
    got = affine_of(jax.jit(pc.scalar_mul_w4)(digits_msb, dp))
    want = [
        host_affine(he.point_mul(k, (x, y, 1, x * y % fe.P_INT)))
        for k, (x, y) in zip(ks, hp)
    ]
    assert got == want


@_slow
def test_double_scalar_mul_w4(pts):
    hp, dp = pts
    hp2 = list(reversed(hp))
    dp2 = stage_points(hp2)
    kas = [int.from_bytes(rng.bytes(32), 'little') >> 3 for _ in range(B)]
    kbs = [int.from_bytes(rng.bytes(16), 'little') for _ in range(B)]
    da = np.zeros((64, B), np.int32)
    db = np.zeros((32, B), np.int32)
    for i in range(B):
        for w in range(64):
            da[w, i] = (kas[i] >> (4 * w)) & 0xF
        for w in range(32):
            db[w, i] = (kbs[i] >> (4 * w)) & 0xF
    got = affine_of(
        jax.jit(pc.double_scalar_mul_w4)(
            jnp.asarray(da[::-1].copy()), dp, jnp.asarray(db[::-1].copy()), dp2
        )
    )
    want = []
    for i in range(B):
        x1, y1 = hp[i]
        x2, y2 = hp2[i]
        pa = he.point_mul(kas[i], (x1, y1, 1, x1 * y1 % fe.P_INT))
        pb = he.point_mul(kbs[i], (x2, y2, 1, x2 * y2 % fe.P_INT))
        want.append(host_affine(he.point_add(pa, pb)))
    assert got == want


@_slow
def test_base_mul_w8():
    ks = [int.from_bytes(rng.bytes(32), 'little') for _ in range(B)]
    digits = np.zeros((32, B), np.int32)
    for i, k in enumerate(ks):
        for w in range(32):
            digits[w, i] = (k >> (8 * w)) & 0xFF
    got = affine_of(jax.jit(pc.base_mul_w8)(jnp.asarray(digits)))
    want = [host_affine(he.point_mul(k, he.B)) for k in ks]
    assert got == want


@_slow
def test_compress_decompress(pts):
    hp, dp = pts
    enc = jax.jit(pc.compress)(dp)
    enc_np = np.asarray(enc)
    for i, (x, y) in enumerate(hp):
        want = he.point_compress((x, y, 1, x * y % fe.P_INT))
        assert bytes(enc_np[:, i].astype(np.uint8)) == want
    ok, back = jax.jit(pc.decompress)(enc)
    assert np.asarray(ok).all()
    assert affine_of(back) == hp

    # invalid encodings are mask lanes, not crashes
    bad = np.asarray(enc).copy()
    bad[:, 0] = 255  # y >= p
    ok2, _ = jax.jit(pc.decompress)(jnp.asarray(bad))
    assert not np.asarray(ok2)[0]


@_slow
def test_compress_many_shared_inversion(pts):
    hp, dp = pts
    d2 = jax.jit(pc.double)(dp)
    encs = jax.jit(lambda a, b: pc.compress_many([a, b]))(dp, d2)
    e1 = np.asarray(encs[0])
    e2 = np.asarray(encs[1])
    for i, (x, y) in enumerate(hp):
        p = (x, y, 1, x * y % fe.P_INT)
        assert bytes(e1[:, i].astype(np.uint8)) == he.point_compress(p)
        assert bytes(e2[:, i].astype(np.uint8)) == he.point_compress(he.point_double(p))
