"""Differential test: batched device KES verify vs host reference."""

import random

import pytest

from ouroboros_consensus_tpu.ops import kes_batch as kb
from ouroboros_consensus_tpu.ops.host import kes as hk

DEPTH = 6


# slow tier since round 8 (XLA-twin execution wall; see the note in
# test_ecvrf_batch.py — the pk twin keeps inline coverage)
@pytest.mark.slow
def test_kes_batch_mixed():
    rng = random.Random(13)
    seeds = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(4)]
    vks_all = [hk.derive_vk(sd, DEPTH) for sd in seeds]

    vks, periods, msgs, sigs, want = [], [], [], [], []

    # valid signatures across the period range (different tree paths)
    for sd, vk, p in zip(seeds, vks_all, (0, 1, 31, 63)):
        msg = bytes(rng.randrange(256) for _ in range(120))
        sig = hk.sign(sd, DEPTH, p, msg)
        assert hk.verify(vk, DEPTH, p, msg, sig)
        vks.append(vk); periods.append(p); msgs.append(msg); sigs.append(sig)
        want.append(True)

    sd, vk = seeds[0], vks_all[0]
    msg = b"kes message under test"
    sig = hk.sign(sd, DEPTH, 17, msg)

    # wrong period (tree path mismatch)
    vks.append(vk); periods.append(18); msgs.append(msg); sigs.append(sig)
    want.append(False)

    # corrupted sibling vk
    bad = bytearray(sig); bad[100] ^= 0x01
    vks.append(vk); periods.append(17); msgs.append(msg); sigs.append(bytes(bad))
    want.append(False)

    # corrupted leaf signature
    bad = bytearray(sig); bad[3] ^= 0x80
    vks.append(vk); periods.append(17); msgs.append(msg); sigs.append(bytes(bad))
    want.append(False)

    # wrong message
    vks.append(vk); periods.append(17); msgs.append(b"a different message!!!"); sigs.append(sig)
    want.append(False)

    # wrong root vk
    vks.append(vks_all[1]); periods.append(17); msgs.append(msg); sigs.append(sig)
    want.append(False)

    for v, p, m, s, w in zip(vks, periods, msgs, sigs, want):
        assert hk.verify(v, DEPTH, p, m, s) == w

    got = kb.verify_batch(vks, periods, msgs, sigs, DEPTH)
    assert list(got) == want
