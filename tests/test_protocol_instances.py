"""BFT / PBFT / LeaderSchedule protocol instances (Protocol/{BFT,PBFT,
LeaderSchedule}.hs semantics: round-robin, signature window, schedule)."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.protocol.instances import (
    BftInvalidSignature,
    BftProtocol,
    BftView,
    BftWrongLeader,
    LeaderScheduleProtocol,
    NotScheduledLeader,
    PBftExceededSignThreshold,
    PBftInvalidSignature,
    PBftNotGenesisDelegate,
    PBftParams,
    PBftProtocol,
    PBftView,
)

SEEDS = [bytes([i]) * 32 for i in range(3)]
VKS = [he.secret_to_public(s) for s in SEEDS]


def bft_view(node, msg=b"hdr"):
    return BftView(node, msg, he.sign(SEEDS[node], msg))


def test_bft_round_robin():
    p = BftProtocol(3, VKS)
    st = p.initial_state()
    t = p.tick(None, 4, st)
    st2 = p.update(bft_view(1), 4, t)  # 4 % 3 == 1
    assert st2.last_slot == 4
    with pytest.raises(BftWrongLeader):
        p.update(bft_view(2), 4, t)
    bad = BftView(1, b"hdr", b"\x00" * 64)
    with pytest.raises(BftInvalidSignature):
        p.update(bad, 4, t)
    assert p.check_is_leader(1, 4, t) == 1
    assert p.check_is_leader(0, 4, t) is None


def pbft_view(node, msg=b"hdr"):
    return PBftView(VKS[node], msg, he.sign(SEEDS[node], msg))


def test_pbft_window_threshold():
    # window 4, threshold 1/2: max 2 of the last 4 signed by one delegate
    p = PBftProtocol(PBftParams(3, Fraction(1, 2), 4), VKS)
    st = p.initial_state()
    st = p.update(pbft_view(0), 0, p.tick(None, 0, st))
    st = p.update(pbft_view(0), 1, p.tick(None, 1, st))
    # a third signature by delegate 0 within the window exceeds 2/4
    with pytest.raises(PBftExceededSignThreshold):
        p.update(pbft_view(0), 2, p.tick(None, 2, st))
    # another delegate is fine; window then slides
    st = p.update(pbft_view(1), 2, p.tick(None, 2, st))
    st = p.update(pbft_view(2), 3, p.tick(None, 3, st))
    st = p.update(pbft_view(1), 4, p.tick(None, 4, st))
    # window is now [0,1,2,1] -> delegate 0 appears once: allowed again
    st = p.update(pbft_view(0), 5, p.tick(None, 5, st))
    assert st.signers[-1] == (5, 0)


def test_pbft_rejections():
    p = PBftProtocol(PBftParams(2, Fraction(1, 2), 4), VKS[:2])
    t = p.tick(None, 0, p.initial_state())
    rogue = PBftView(VKS[2], b"hdr", he.sign(SEEDS[2], b"hdr"))
    with pytest.raises(PBftNotGenesisDelegate):
        p.update(rogue, 0, t)
    forged = PBftView(VKS[0], b"hdr", he.sign(SEEDS[1], b"hdr"))
    with pytest.raises(PBftInvalidSignature):
        p.update(forged, 0, t)


def test_pbft_slot_monotonicity_and_delegation():
    """PBFT.hs:320-352: slots must be non-decreasing; the delegation map
    from the TICKED ledger view decides genesis-key membership — a
    delegation cert redirects a genesis key's signing rights."""
    from ouroboros_consensus_tpu.protocol.instances import (
        PBftInvalidSlot,
        PBftLedgerView,
    )

    p = PBftProtocol(PBftParams(3, Fraction(1, 2), 4), VKS)
    st = p.update(pbft_view(0), 5, p.tick(None, 5, p.initial_state()))
    # same slot is allowed (EBBs share their epoch's first slot)...
    st2 = p.update(pbft_view(1), 5, p.tick(None, 5, st))
    # ...an EARLIER slot is not
    with pytest.raises(PBftInvalidSlot):
        p.update(pbft_view(1), 4, p.tick(None, 4, st2))

    # delegation: genesis key 0 delegates to VKS[2]'s holder — the NEW
    # delegate signs as genesis key 0; the old key is rejected
    dlg = PBftLedgerView({VKS[2]: 0, VKS[1]: 1})
    t = p.tick(dlg, 6, st2)
    st3 = p.update(PBftView(VKS[2], b"hdr", he.sign(SEEDS[2], b"hdr")), 6, t)
    assert st3.signers[-1] == (6, 0)
    with pytest.raises(PBftNotGenesisDelegate):
        p.update(pbft_view(0), 6, p.tick(dlg, 6, st2))


def test_pbft_reupdate_skips_crypto():
    p = PBftProtocol(PBftParams(2, Fraction(1, 2), 4), VKS[:2])
    t = p.tick(None, 0, p.initial_state())
    v = PBftView(VKS[0], b"hdr", b"garbage")  # bad sig: reupdate ignores
    st = p.reupdate(v, 0, t)
    assert st.signers == ((0, 0),)


def test_leader_schedule():
    p = LeaderScheduleProtocol({0: [1], 1: [0, 2], 2: []})
    t = p.tick(None, 1, p.initial_state())
    assert p.check_is_leader(0, 1, t) == 0
    assert p.check_is_leader(1, 1, t) is None
    st = p.update(2, 1, t)
    assert st.last_slot == 1
    with pytest.raises(NotScheduledLeader):
        p.update(1, 1, t)
    assert p.check_is_leader(0, 2, p.tick(None, 2, st)) is None


def test_pbft_boundary_blocks():
    """EBBs (Block/EBB.hs, PBFT.hs PBftValidateBoundary): unsigned epoch
    boundary blocks validate with NO state change and NO window effect."""
    from ouroboros_consensus_tpu.hardfork import byron_mock
    from ouroboros_consensus_tpu.protocol.instances import PBFT_BOUNDARY_VIEW

    p = PBftProtocol(PBftParams(2, Fraction(1, 2), 4), VKS[:2])
    st = p.update(pbft_view(0), 0, p.tick(None, 0, p.initial_state()))
    ebb = byron_mock.forge_ebb(slot=40, block_no=0, prev_hash=b"\x00" * 32)
    assert ebb.header.to_view() is PBFT_BOUNDARY_VIEW
    assert ebb.check_integrity()
    # roundtrips through the codec with the EBB marker intact
    again = byron_mock.ByronMockBlock.from_bytes(ebb.bytes_)
    assert again.header.is_ebb and again.hash_ == ebb.hash_
    st2 = p.update(ebb.header.to_view(), 40, p.tick(None, 40, st))
    assert st2 == st  # no signer-window change
    assert p.reupdate(ebb.header.to_view(), 40, p.tick(None, 40, st)) == st
