"""Shelley ledger wired into the CONSENSUS stack: ExtLedger + ChainDB
with the Praos protocol electing from LEDGER-DERIVED views.

This is the real-era integration the reference gets from
`ouroboros-consensus-cardano` Shelley: `protocol_ledger_view` serves the
SET snapshot of the real STS state (Shelley/Ledger/Ledger.hs:584 area),
so who may forge is decided by on-chain stake — registered via genesis
staking (sgStaking analog) or via certificates in blocks, becoming
electable only two epoch boundaries later (mark -> set rotation).

With f = 1 the Praos leader check is deterministic in the view: a pool
with positive SET-snapshot stake certainly wins, a pool with zero stake
certainly loses — so chain-level adoption/rejection of forged blocks IS
an assertion about the derived ledger view.
"""

from __future__ import annotations

from dataclasses import replace
from fractions import Fraction

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import shelley as sh
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.fs import MockFS

EPOCH = 30
PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=3,
    active_slot_coeff=Fraction(1),
    epoch_length=EPOCH,
    kes_depth=3,
)
PP = sh.PParams(
    min_fee_a=0, min_fee_b=0, key_deposit=100, pool_deposit=1000,
    e_max=5, n_opt=2,
)
ETA0 = b"\x2d" * 32

POOL_A = fixtures.make_pool(0, kes_depth=PARAMS.kes_depth)
POOL_B = fixtures.make_pool(1, kes_depth=PARAMS.kes_depth)
POOL_C = fixtures.make_pool(2, kes_depth=PARAMS.kes_depth)


def cred(i):
    return b"c%02d" % i + b"\x00" * 25


def pay(i):
    return b"y%02d" % i + b"\x00" * 25


def pool_params(pool, reward_cred):
    return sh.PoolParams(
        pool_id=hash_key(pool.vk_cold), vrf_hash=hash_vrf_vk(pool.vrf_vk),
        pledge=0, cost=0, margin=Fraction(0), reward_cred=reward_cred,
        owners=(),
    )


def build():
    g = sh.ShelleyGenesis(
        pparams=PP, epoch_length=EPOCH,
        stability_window=PARAMS.stability_window, max_supply=10_000_000,
    )
    ledger = sh.ShelleyLedger(g)
    st0 = ledger.genesis_state(
        [(pay(0), cred(0), 60000), (pay(1), cred(1), 30000),
         (pay(2), cred(2), 90000)],
        initial_pools=(
            pool_params(POOL_A, cred(0)), pool_params(POOL_B, cred(1)),
        ),
        initial_delegations=((cred(0), hash_key(POOL_A.vk_cold)),
                             (cred(1), hash_key(POOL_B.vk_cold))),
    )
    ext = ExtLedger(ledger, PraosProtocol(PARAMS, use_device_batch=False))
    genesis = ext.genesis(st0)
    genesis = replace(
        genesis,
        header_state=replace(
            genesis.header_state,
            chain_dep_state=replace(
                genesis.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    return ext, genesis


def current_nonce(ticked):
    return ticked.ticked_header_state.ticked_chain_dep_state.state.epoch_nonce


def test_two_era_hfc_with_live_shelley_ledger():
    """A 2-era HFC composite whose SECOND era runs the real Shelley STS
    ledger: era A (MockLedger, Praos) hands its UTxO across the boundary
    via translate_from_utxo_ledger (the Byron->Shelley translation
    shape), stake seals from the carried distribution, and Shelley rules
    are LIVE after the fork — an invalid Shelley tx makes its block
    rejected at chain level, a valid one moves real value."""
    import dataclasses
    import functools

    from ouroboros_consensus_tpu.block.praos_block import Block as PraosBlock
    from ouroboros_consensus_tpu.hardfork.combinator import (
        Era,
        HardForkBlock,
        HardForkLedger,
        HardForkProtocol,
        HFState,
        decode_block,
    )
    from ouroboros_consensus_tpu.hardfork.history import (
        EraParams as HEraParams,
    )
    from ouroboros_consensus_tpu.hardfork.history import summarize
    from ouroboros_consensus_tpu.ledger import mock as mock_ledger

    EP_A = 10
    HF_EPOCH = 2  # boundary at slot 20
    params_a = dataclasses.replace(PARAMS, epoch_length=EP_A)
    params_b = dataclasses.replace(PARAMS, epoch_length=EP_A)

    g = sh.ShelleyGenesis(
        pparams=PP, epoch_length=EP_A,
        stability_window=PARAMS.stability_window, max_supply=10_000_000,
    )
    shelley = sh.ShelleyLedger(g)
    mock_view = fixtures.make_ledger_view([POOL_A])
    mock = mock_ledger.MockLedger(
        mock_ledger.MockConfig(mock_view, params_a.stability_window)
    )

    addr = b"rich-addr"
    staking = dict(
        stake_of=lambda a: cred(0) if a == addr else None,
        initial_pools=(pool_params(POOL_A, cred(0)),),
        initial_delegations=((cred(0), hash_key(POOL_A.vk_cold)),),
    )
    eras = [
        Era("mockA", PraosProtocol(params_a, use_device_batch=False),
            ledger=mock),
        Era(
            "shelleyB", PraosProtocol(params_b, use_device_batch=False),
            ledger=shelley,
            translate_ledger_state=lambda st: shelley.translate_from_utxo_ledger(
                st, at_slot=HF_EPOCH * EP_A, **staking
            ),
        ),
    ]
    summary = summarize(
        Fraction(0),
        [HEraParams(EP_A, Fraction(1)), HEraParams(EP_A, Fraction(1))],
        [HF_EPOCH, None],
    )
    protocol = HardForkProtocol(eras, summary)
    hf_ledger = HardForkLedger(eras, summary)
    codec = functools.partial(
        decode_block,
        era_decoders=[PraosBlock.from_bytes, PraosBlock.from_bytes],
    )

    ext = ExtLedger(hf_ledger, protocol)
    genesis = ext.genesis(
        hf_ledger.genesis_state(mock.genesis_state([(addr, 50000)]))
    )
    hs = genesis.header_state
    genesis = replace(
        genesis,
        header_state=replace(
            hs,
            chain_dep_state=HFState(
                0, replace(hs.chain_dep_state.inner, epoch_nonce=ETA0)
            ),
        ),
    )
    db = open_chaindb(
        "db", ext, genesis, k=PARAMS.security_param, chunk_size=50,
        fs=MockFS(), decode_block=codec,
    )

    cur, prev, bno = genesis, None, 0
    shelley_rules_hit = False
    for slot in range(1, 3 * EP_A):
        era = protocol.era_of_slot(slot)
        ticked = ext.tick(cur, slot)
        nonce = ticked.ticked_header_state.ticked_chain_dep_state.inner.state.epoch_nonce
        view = ticked.ledger_view
        leader = fixtures.find_leader(PARAMS, [POOL_A], view, slot, nonce)
        assert leader is POOL_A, f"slot {slot}: no leader in era {era}"

        txs = ()
        if era == 1 and not shelley_rules_hit:
            # Shelley rules are live: a tx spending a missing input is
            # rejected WITH ITS BLOCK at chain level...
            bad_tx = sh.encode_tx(
                [(b"\x77" * 32, 0)], [(pay(5), None, 5)], fee=0
            )
            bad = HardForkBlock(1, forge_block(
                params_b, POOL_A, slot=slot, block_no=bno, prev_hash=prev,
                epoch_nonce=nonce, txs=(bad_tx,),
            ))
            db.add_block(bad)
            assert bad.hash_ in db.invalid
            # ...and a valid one spending the CARRIED-OVER mock-era
            # outpoint moves real value under the STS rules
            txs = (sh.encode_tx(
                [(bytes(32), 0)], [(pay(6), cred(0), 50000)], fee=0,
            ),)
            shelley_rules_hit = True

        blk = HardForkBlock(era, forge_block(
            params_a if era == 0 else params_b, POOL_A, slot=slot,
            block_no=bno, prev_hash=prev, epoch_nonce=nonce, txs=txs,
        ))
        db.add_block(blk)
        assert db.tip_point().hash_ == blk.hash_, f"slot {slot} (era {era})"
        cur = ext.apply_block(ticked, blk)
        prev, bno = blk.hash_, bno + 1

    assert shelley_rules_hit
    final = cur.ledger_state
    assert final.era == 1
    assert isinstance(final.inner, sh.ShelleyState)
    # the spend really moved through the Shelley UTxO
    assert any(a[0] == pay(6) for (a, _c) in final.inner.utxo.values())
    # and stake still elects POOL_A from the carried-over distribution
    assert hash_key(POOL_A.vk_cold) in ext.tick(
        cur, 3 * EP_A
    ).ledger_view.pool_distr
    db.close()


def test_mempool_over_hfc_shelley_era():
    """The Mempool anchored past the fork validates under the SHELLEY
    era's rules through HardForkLedger.mempool_view: a double spend of
    the carried-over outpoint is rejected by the STS rules."""
    import dataclasses

    from ouroboros_consensus_tpu.hardfork.combinator import (
        Era, HardForkLedger, HFState,
    )
    from ouroboros_consensus_tpu.hardfork.history import (
        EraParams as HEraParams,
    )
    from ouroboros_consensus_tpu.hardfork.history import summarize
    from ouroboros_consensus_tpu.ledger import mock as mock_ledger
    from ouroboros_consensus_tpu.mempool import Mempool

    EP_A = 10
    params_a = dataclasses.replace(PARAMS, epoch_length=EP_A)
    g = sh.ShelleyGenesis(
        pparams=PP, epoch_length=EP_A,
        stability_window=PARAMS.stability_window, max_supply=10_000_000,
    )
    shelley = sh.ShelleyLedger(g)
    mock = mock_ledger.MockLedger(
        mock_ledger.MockConfig(
            fixtures.make_ledger_view([POOL_A]), params_a.stability_window
        )
    )
    eras = [
        Era("mockA", None, ledger=mock),
        Era("shelleyB", None, ledger=shelley,
            translate_ledger_state=lambda st:
                shelley.translate_from_utxo_ledger(st, at_slot=2 * EP_A)),
    ]
    summary = summarize(
        Fraction(0),
        [HEraParams(EP_A, Fraction(1)), HEraParams(EP_A, Fraction(1))],
        [2, None],
    )
    hf = HardForkLedger(eras, summary)
    anchor = HFState(0, mock.genesis_state([(b"a0", 7000)]))
    pool = Mempool(hf, lambda: (anchor, 2 * EP_A + 1))  # past the fork
    pool.add_tx(sh.encode_tx(
        [(bytes(32), 0)], [(pay(3), None, 7000)], fee=0,
    ))
    import pytest

    with pytest.raises(sh.ShelleyTxError):
        pool.add_tx(sh.encode_tx(
            [(bytes(32), 0)], [(pay(4), None, 7000)], fee=0,
        ))
    assert len(pool.get_snapshot().txs) == 1


def test_node_kernel_forges_over_shelley_ledger():
    """A full NodeKernel over the Shelley ledger: the forging loop's
    leadership comes from the ledger-derived view, the mempool snapshot
    (full STS validation) feeds the block body, and adoption syncs the
    pool — the NodeKernel.hs forge path on a real-era ledger."""
    from ouroboros_consensus_tpu.node.kernel import NodeKernel, SlotClock

    ext, genesis = build()
    db = open_chaindb("db", ext, genesis, k=PARAMS.security_param,
                      chunk_size=50, fs=MockFS())
    node = NodeKernel(
        "n0", db, ext.protocol, ext.ledger, pool=POOL_A,
        clock=SlotClock(1.0),
    )
    spend = sh.encode_tx(
        [(bytes(32), 0)], [(pay(9), cred(0), 60000)], fee=0,
    )
    node.mempool.add_tx(spend)
    forged = []
    for slot in range(1, 8):
        blk = node.try_forge(slot)
        if blk is not None:
            forged.append(blk)
    assert forged, "POOL_A has genesis stake and f=1: it must forge"
    assert any(spend in b.txs for b in forged), "mempool tx not included"
    st = db.current_ledger().ledger_state
    assert any(a[0] == pay(9) for (a, _c) in st.utxo.values())
    # adoption synced the mempool: the included tx is gone
    assert not node.mempool.get_snapshot().txs
    db.close()


def test_shelley_and_hf_snapshot_roundtrip():
    """The v2 tagged snapshot codec: a Shelley state (with pools,
    rewards, retiring, proposals, snapshots) inside an HFState, paired
    with a Praos header state, survives encode -> decode exactly; the
    legacy mock format is untouched (golden-pinned separately)."""
    from ouroboros_consensus_tpu.hardfork.combinator import HFState
    from ouroboros_consensus_tpu.ledger.extended import ExtLedgerState
    from ouroboros_consensus_tpu.ledger.header_validation import HeaderState
    from ouroboros_consensus_tpu.storage import serialize

    ext, genesis = build()
    led = ext.ledger
    st = genesis.ledger_state
    # make the state non-trivial: a real tx + an epoch boundary
    tx = sh.encode_tx(
        [(bytes(32), 2)],
        [(pay(2), cred(2), 90000 - PP.key_deposit - PP.pool_deposit)],
        fee=0,
        certs=[(0, cred(2)),
               (3, hash_key(POOL_C.vk_cold), hash_vrf_vk(POOL_C.vrf_vk),
                0, 0, 1, 4, cred(2), [cred(2)]),
               (2, cred(2), hash_key(POOL_C.vk_cold)),
               (4, hash_key(POOL_C.vk_cold), 3)],
    )

    class Blk:
        slot = 5
        txs = [tx]

    st = led.apply_block(led.tick(st, 5), Blk())
    st = led.tick(st, EPOCH + 1).state  # rotate snapshots

    hs = genesis.header_state
    pair = ExtLedgerState(
        HFState(1, st),
        HeaderState(hs.tip, HFState(1, hs.chain_dep_state)),
    )
    back = serialize.decode_ext_state(serialize.encode_ext_state(pair))
    assert back == pair


def test_mempool_over_shelley_ledger():
    """The generic Mempool runs over the Shelley TxView seam: the full
    STS rules validate adds (Mempool/API.hs addTx), and advancing the
    anchor past a tx's TTL drops it on sync."""
    from ouroboros_consensus_tpu.mempool import Mempool

    ext, genesis = build()
    ledger = ext.ledger
    anchor = {"state": genesis.ledger_state, "slot": 1}
    pool = Mempool(
        ledger, lambda: (anchor["state"], anchor["slot"]),
    )
    spend = sh.encode_tx(
        [(bytes(32), 0)], [(pay(9), None, 60000)], fee=0, ttl=10,
    )
    pool.add_tx(spend)
    # double-spend of the same genesis input: rejected against the
    # pool-extended view
    import pytest

    with pytest.raises(sh.ShelleyTxError):
        pool.add_tx(sh.encode_tx(
            [(bytes(32), 0)], [(pay(8), None, 60000)], fee=0,
        ))
    assert len(pool.get_snapshot().txs) == 1
    # TTL expiry: advancing the anchor past slot 10 drops the tx
    anchor["slot"] = 11
    dropped = pool.sync_with_ledger()
    assert [t.tx for t in dropped] == [spend]
    assert not pool.get_snapshot().txs


def test_genesis_staking_seeds_all_snapshots():
    ext, genesis = build()
    view = ext.tick(genesis, 1).ledger_view
    distr = view.pool_distr
    assert set(distr) == {hash_key(POOL_A.vk_cold), hash_key(POOL_B.vk_cold)}
    # stake = utxo held by the delegating creds: 60000 vs 30000
    assert distr[hash_key(POOL_A.vk_cold)].stake == Fraction(2, 3)
    assert distr[hash_key(POOL_B.vk_cold)].stake == Fraction(1, 3)
    assert distr[hash_key(POOL_A.vk_cold)].vrf_key_hash == hash_vrf_vk(POOL_A.vrf_vk)


def test_chaindb_elects_from_ledger_derived_views():
    """Drive a ChainDB whose election views come from the Shelley STS
    state: genesis pools forge from slot 1; a pool registered ON CHAIN in
    epoch 0 is rejected through epoch 1 (not yet in SET) and accepted in
    epoch 2 (mark -> set rotation) — at chain-adoption level."""
    ext, genesis = build()
    db = open_chaindb("db", ext, genesis, k=PARAMS.security_param,
                      chunk_size=50, fs=MockFS())

    # the registration tx for pool C, delegating the rich cred(2) to it
    reg_tx = sh.encode_tx(
        [(bytes(32), 2)],
        [(pay(2), cred(2), 90000 - PP.key_deposit - PP.pool_deposit)],
        fee=0,
        certs=[(0, cred(2)),
               (3, hash_key(POOL_C.vk_cold), hash_vrf_vk(POOL_C.vrf_vk),
                0, 0, 0, 1, cred(2), []),
               (2, cred(2), hash_key(POOL_C.vk_cold))],
    )

    cur = genesis
    prev = None
    block_no = 0
    c_rejected_epoch1 = False
    c_adopted_epoch2 = False
    slot = 1
    while slot < 2 * EPOCH + EPOCH // 2:
        ticked = ext.tick(cur, slot)
        nonce = current_nonce(ticked)
        view = ticked.ledger_view
        epoch = slot // EPOCH

        if epoch == 1 and not c_rejected_epoch1:
            # C has been registered on chain since epoch 0 but is NOT in
            # the SET snapshot yet: its block must be rejected
            bad = forge_block(
                PARAMS, POOL_C, slot=slot, block_no=block_no,
                prev_hash=prev, epoch_nonce=nonce,
            )
            db.add_block(bad)
            assert db.tip_point() is None or db.tip_point().hash_ != bad.hash_
            assert bad.hash_ in db.invalid
            c_rejected_epoch1 = True

        leader = fixtures.find_leader(
            PARAMS, [POOL_A, POOL_B, POOL_C], view, slot, nonce
        )
        if epoch < 2:
            assert leader in (POOL_A, POOL_B), f"slot {slot}"
        txs = (reg_tx,) if slot == 2 else ()
        blk = forge_block(
            PARAMS, leader, slot=slot, block_no=block_no, prev_hash=prev,
            epoch_nonce=nonce, txs=txs,
        )
        db.add_block(blk)
        assert db.tip_point() is not None
        assert db.tip_point().hash_ == blk.hash_, f"slot {slot} not adopted"
        cur = ext.apply_block(ticked, blk)
        prev = blk.hash_
        block_no += 1

        if epoch == 2 and not c_adopted_epoch2:
            # C's stake (90000 - deposits delegated at slot 2) is in SET
            # from the epoch-2 boundary: now C forges and is ADOPTED
            assert hash_key(POOL_C.vk_cold) in view.pool_distr
            slot += 1
            ticked = ext.tick(cur, slot)
            nonce = current_nonce(ticked)
            cblk = forge_block(
                PARAMS, POOL_C, slot=slot, block_no=block_no,
                prev_hash=prev, epoch_nonce=nonce,
            )
            db.add_block(cblk)
            assert db.tip_point().hash_ == cblk.hash_
            cur = ext.apply_block(ticked, cblk)
            prev = cblk.hash_
            block_no += 1
            c_adopted_epoch2 = True
        slot += 1

    assert c_rejected_epoch1 and c_adopted_epoch2
    # and the ledger really processed the registration: pool C is a
    # real pool with a recorded deposit in the final state
    final = cur.ledger_state
    assert hash_key(POOL_C.vk_cold) in final.pools
    assert final.pool_deposits[hash_key(POOL_C.vk_cold)] == PP.pool_deposit
    db.close()


def test_hf_forecast_crosses_era_boundary():
    """A node whose tip is still pre-fork must FORGE with the same view
    validators will enforce post-fork: ledger_view_forecast_at on the
    HFC translates the state across the boundary and serves the target
    era's (Shelley-derived) view, not the anchor era's mock view."""
    import dataclasses

    from ouroboros_consensus_tpu.hardfork.combinator import (
        Era, HardForkLedger, HFState,
    )
    from ouroboros_consensus_tpu.hardfork.history import (
        EraParams as HEraParams,
    )
    from ouroboros_consensus_tpu.hardfork.history import summarize
    from ouroboros_consensus_tpu.ledger import mock as mock_ledger

    EP = 10
    g = sh.ShelleyGenesis(
        pparams=PP, epoch_length=EP,
        stability_window=10_000,  # horizon reaches past the boundary
        max_supply=10_000_000,
    )
    shelley = sh.ShelleyLedger(g)
    mock_view = fixtures.make_ledger_view([POOL_A, POOL_B])
    mock = mock_ledger.MockLedger(mock_ledger.MockConfig(mock_view, 10_000))
    addr = b"rich"
    staking = dict(
        stake_of=lambda a: cred(0),
        initial_pools=(pool_params(POOL_A, cred(0)),),
        initial_delegations=((cred(0), hash_key(POOL_A.vk_cold)),),
    )
    eras = [
        Era("mockA", None, ledger=mock),
        Era("shelleyB", None, ledger=shelley,
            translate_ledger_state=lambda st:
                shelley.translate_from_utxo_ledger(
                    st, at_slot=2 * EP, **staking)),
    ]
    summary = summarize(
        Fraction(0),
        [HEraParams(EP, Fraction(1)), HEraParams(EP, Fraction(1))],
        [2, None],
    )
    hf = HardForkLedger(eras, summary)
    pre = HFState(0, mock.genesis_state([(addr, 1000)]))

    fc = hf.ledger_view_forecast_at(pre)
    # same era: the mock fixture view (both pools)
    assert set(fc.forecast_for(5).pool_distr) == {
        hash_key(POOL_A.vk_cold), hash_key(POOL_B.vk_cold)
    }
    # past the boundary: the SHELLEY-derived view (only the staked pool)
    post = fc.forecast_for(2 * EP + 1)
    assert set(post.pool_distr) == {hash_key(POOL_A.vk_cold)}
    assert post.pool_distr[hash_key(POOL_A.vk_cold)].stake == Fraction(1)
