"""Shelley ledger wired into the CONSENSUS stack: ExtLedger + ChainDB
with the Praos protocol electing from LEDGER-DERIVED views.

This is the real-era integration the reference gets from
`ouroboros-consensus-cardano` Shelley: `protocol_ledger_view` serves the
SET snapshot of the real STS state (Shelley/Ledger/Ledger.hs:584 area),
so who may forge is decided by on-chain stake — registered via genesis
staking (sgStaking analog) or via certificates in blocks, becoming
electable only two epoch boundaries later (mark -> set rotation).

With f = 1 the Praos leader check is deterministic in the view: a pool
with positive SET-snapshot stake certainly wins, a pool with zero stake
certainly loses — so chain-level adoption/rejection of forged blocks IS
an assertion about the derived ledger view.
"""

from __future__ import annotations

from dataclasses import replace
from fractions import Fraction

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import shelley as sh
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.fs import MockFS

EPOCH = 30
PARAMS = praos.PraosParams(
    slots_per_kes_period=1000,
    max_kes_evolutions=62,
    security_param=3,
    active_slot_coeff=Fraction(1),
    epoch_length=EPOCH,
    kes_depth=3,
)
PP = sh.PParams(
    min_fee_a=0, min_fee_b=0, key_deposit=100, pool_deposit=1000,
    e_max=5, n_opt=2,
)
ETA0 = b"\x2d" * 32

POOL_A = fixtures.make_pool(0, kes_depth=PARAMS.kes_depth)
POOL_B = fixtures.make_pool(1, kes_depth=PARAMS.kes_depth)
POOL_C = fixtures.make_pool(2, kes_depth=PARAMS.kes_depth)


def cred(i):
    return b"c%02d" % i + b"\x00" * 25


def pay(i):
    return b"y%02d" % i + b"\x00" * 25


def pool_params(pool, reward_cred):
    return sh.PoolParams(
        pool_id=hash_key(pool.vk_cold), vrf_hash=hash_vrf_vk(pool.vrf_vk),
        pledge=0, cost=0, margin=Fraction(0), reward_cred=reward_cred,
        owners=(),
    )


def build():
    g = sh.ShelleyGenesis(
        pparams=PP, epoch_length=EPOCH,
        stability_window=PARAMS.stability_window, max_supply=10_000_000,
    )
    ledger = sh.ShelleyLedger(g)
    st0 = ledger.genesis_state(
        [(pay(0), cred(0), 60000), (pay(1), cred(1), 30000),
         (pay(2), cred(2), 90000)],
        initial_pools=(
            pool_params(POOL_A, cred(0)), pool_params(POOL_B, cred(1)),
        ),
        initial_delegations=((cred(0), hash_key(POOL_A.vk_cold)),
                             (cred(1), hash_key(POOL_B.vk_cold))),
    )
    ext = ExtLedger(ledger, PraosProtocol(PARAMS, use_device_batch=False))
    genesis = ext.genesis(st0)
    genesis = replace(
        genesis,
        header_state=replace(
            genesis.header_state,
            chain_dep_state=replace(
                genesis.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )
    return ext, genesis


def current_nonce(ticked):
    return ticked.ticked_header_state.ticked_chain_dep_state.state.epoch_nonce


def test_mempool_over_shelley_ledger():
    """The generic Mempool runs over the Shelley TxView seam: the full
    STS rules validate adds (Mempool/API.hs addTx), and advancing the
    anchor past a tx's TTL drops it on sync."""
    from ouroboros_consensus_tpu.mempool import Mempool

    ext, genesis = build()
    ledger = ext.ledger
    anchor = {"state": genesis.ledger_state, "slot": 1}
    pool = Mempool(
        ledger, lambda: (anchor["state"], anchor["slot"]),
    )
    spend = sh.encode_tx(
        [(bytes(32), 0)], [(pay(9), None, 60000)], fee=0, ttl=10,
    )
    pool.add_tx(spend)
    # double-spend of the same genesis input: rejected against the
    # pool-extended view
    import pytest

    with pytest.raises(sh.ShelleyTxError):
        pool.add_tx(sh.encode_tx(
            [(bytes(32), 0)], [(pay(8), None, 60000)], fee=0,
        ))
    assert len(pool.get_snapshot().txs) == 1
    # TTL expiry: advancing the anchor past slot 10 drops the tx
    anchor["slot"] = 11
    dropped = pool.sync_with_ledger()
    assert [t.tx for t in dropped] == [spend]
    assert not pool.get_snapshot().txs


def test_genesis_staking_seeds_all_snapshots():
    ext, genesis = build()
    view = ext.tick(genesis, 1).ledger_view
    distr = view.pool_distr
    assert set(distr) == {hash_key(POOL_A.vk_cold), hash_key(POOL_B.vk_cold)}
    # stake = utxo held by the delegating creds: 60000 vs 30000
    assert distr[hash_key(POOL_A.vk_cold)].stake == Fraction(2, 3)
    assert distr[hash_key(POOL_B.vk_cold)].stake == Fraction(1, 3)
    assert distr[hash_key(POOL_A.vk_cold)].vrf_key_hash == hash_vrf_vk(POOL_A.vrf_vk)


def test_chaindb_elects_from_ledger_derived_views():
    """Drive a ChainDB whose election views come from the Shelley STS
    state: genesis pools forge from slot 1; a pool registered ON CHAIN in
    epoch 0 is rejected through epoch 1 (not yet in SET) and accepted in
    epoch 2 (mark -> set rotation) — at chain-adoption level."""
    ext, genesis = build()
    db = open_chaindb("db", ext, genesis, k=PARAMS.security_param,
                      chunk_size=50, fs=MockFS())

    # the registration tx for pool C, delegating the rich cred(2) to it
    reg_tx = sh.encode_tx(
        [(bytes(32), 2)],
        [(pay(2), cred(2), 90000 - PP.key_deposit - PP.pool_deposit)],
        fee=0,
        certs=[(0, cred(2)),
               (3, hash_key(POOL_C.vk_cold), hash_vrf_vk(POOL_C.vrf_vk),
                0, 0, 0, 1, cred(2), []),
               (2, cred(2), hash_key(POOL_C.vk_cold))],
    )

    cur = genesis
    prev = None
    block_no = 0
    c_rejected_epoch1 = False
    c_adopted_epoch2 = False
    slot = 1
    while slot < 2 * EPOCH + EPOCH // 2:
        ticked = ext.tick(cur, slot)
        nonce = current_nonce(ticked)
        view = ticked.ledger_view
        epoch = slot // EPOCH

        if epoch == 1 and not c_rejected_epoch1:
            # C has been registered on chain since epoch 0 but is NOT in
            # the SET snapshot yet: its block must be rejected
            bad = forge_block(
                PARAMS, POOL_C, slot=slot, block_no=block_no,
                prev_hash=prev, epoch_nonce=nonce,
            )
            db.add_block(bad)
            assert db.tip_point() is None or db.tip_point().hash_ != bad.hash_
            assert bad.hash_ in db.invalid
            c_rejected_epoch1 = True

        leader = fixtures.find_leader(
            PARAMS, [POOL_A, POOL_B, POOL_C], view, slot, nonce
        )
        if epoch < 2:
            assert leader in (POOL_A, POOL_B), f"slot {slot}"
        txs = (reg_tx,) if slot == 2 else ()
        blk = forge_block(
            PARAMS, leader, slot=slot, block_no=block_no, prev_hash=prev,
            epoch_nonce=nonce, txs=txs,
        )
        db.add_block(blk)
        assert db.tip_point() is not None
        assert db.tip_point().hash_ == blk.hash_, f"slot {slot} not adopted"
        cur = ext.apply_block(ticked, blk)
        prev = blk.hash_
        block_no += 1

        if epoch == 2 and not c_adopted_epoch2:
            # C's stake (90000 - deposits delegated at slot 2) is in SET
            # from the epoch-2 boundary: now C forges and is ADOPTED
            assert hash_key(POOL_C.vk_cold) in view.pool_distr
            slot += 1
            ticked = ext.tick(cur, slot)
            nonce = current_nonce(ticked)
            cblk = forge_block(
                PARAMS, POOL_C, slot=slot, block_no=block_no,
                prev_hash=prev, epoch_nonce=nonce,
            )
            db.add_block(cblk)
            assert db.tip_point().hash_ == cblk.hash_
            cur = ext.apply_block(ticked, cblk)
            prev = cblk.hash_
            block_no += 1
            c_adopted_epoch2 = True
        slot += 1

    assert c_rejected_epoch1 and c_adopted_epoch2
    # and the ledger really processed the registration: pool C is a
    # real pool with a recorded deposit in the final state
    final = cur.ledger_state
    assert hash_key(POOL_C.vk_cold) in final.pools
    assert final.pool_deposits[hash_key(POOL_C.vk_cold)] == PP.pool_deposit
    db.close()
